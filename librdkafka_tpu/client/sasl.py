"""SASL authentication providers: PLAIN, SCRAM-SHA-256/512, OAUTHBEARER,
and GSSAPI/Kerberos (via python-gssapi when installed).

The provider-vtable design mirrors struct rd_kafka_sasl_provider
(src/rdkafka_sasl_int.h:32); the handshake bytes flow over the broker's
normal request path via SaslHandshake + SaslAuthenticate requests
(Kafka >= 1.0 framing). GSSAPI (reference: rdkafka_sasl_cyrus.c:1-645,
which uses libsasl2) is implemented directly over RFC 4752: the GSS
context loop plus the final security-layer negotiation. The GSS context
itself comes from the python-gssapi package (MIT Kerberos); when that is
not installed, selecting GSSAPI fails fast with _UNSUPPORTED_FEATURE at
client creation — exactly like a reference build without WITH_SASL_CYRUS.
The context factory is injectable so the SASL token framing is testable
against recorded vectors without a KDC.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import os
import struct
import time
from typing import TYPE_CHECKING, Optional

from ..protocol.apis import APIS
from ..protocol.proto import ApiKey
from .errors import Err, KafkaError, KafkaException

if TYPE_CHECKING:
    from .broker import Broker
    from .kafka import Kafka


SUPPORTED_MECHANISMS = ("PLAIN", "SCRAM-SHA-256", "SCRAM-SHA-512",
                        "OAUTHBEARER", "GSSAPI")


def gssapi_available() -> bool:
    try:
        import gssapi  # noqa: F401
        return True
    except Exception:
        return False


def validate_mechanism(conf) -> None:
    """Fail fast at client creation for unsupported mechanisms
    (reference: rd_kafka_sasl_select_provider, rdkafka_sasl.c:~350)."""
    mech = conf.get("sasl.mechanisms").upper()
    if mech in ("GSSAPI", "KERBEROS") and not gssapi_available():
        raise KafkaException(
            Err._UNSUPPORTED_FEATURE,
            "SASL mechanism GSSAPI (Kerberos) requires the python-gssapi "
            "package (not installed); supported here: "
            + ", ".join(m for m in SUPPORTED_MECHANISMS if m != "GSSAPI"))
    if mech not in SUPPORTED_MECHANISMS:
        raise KafkaException(
            Err._UNSUPPORTED_FEATURE,
            f"Unsupported sasl.mechanisms {mech!r}; supported: "
            + ", ".join(SUPPORTED_MECHANISMS))


def _auth_error(e: Exception) -> KafkaError:
    """Normalize provider exceptions (KafkaException, ValueError from
    SCRAM verification, gssapi.GSSError, ...) into the _AUTHENTICATION
    error sasl_done() reports to the app."""
    if isinstance(e, KafkaException):
        return e.error
    return KafkaError(Err._AUTHENTICATION, f"SASL auth failed: {e}")


def sasl_client_start(rk: "Kafka", broker: "Broker") -> None:
    mech = rk.conf.get("sasl.mechanisms").upper()
    if mech == "PLAIN":
        client = PlainClient(rk)
    elif mech in ("SCRAM-SHA-256", "SCRAM-SHA-512"):
        client = ScramClient(rk, mech)
    elif mech == "OAUTHBEARER":
        try:
            client = OauthBearerClient(rk)
        except KafkaException as e:
            broker.sasl_done(e.error)   # clean auth failure + backoff
            return
    elif mech == "GSSAPI":
        try:
            client = GssapiClient(rk, broker.host)
        except Exception as e:
            # python-gssapi raises gssapi.GSSError from Credentials/
            # Name/SecurityContext construction (e.g. no ticket in the
            # ccache); normalize it to a clean _AUTHENTICATION failure
            # instead of letting it escape as a generic _FAIL
            # disconnect/reconnect loop.
            broker.sasl_done(_auth_error(e))
            return
    else:
        broker.sasl_done(KafkaError(
            Err._UNSUPPORTED_FEATURE,
            f"SASL mechanism {mech} not supported in this build"))
        return
    _handshake(rk, broker, mech, client)


def _handshake(rk, broker, mech, client):
    from .broker import Request

    def on_handshake(err, resp):
        if err is not None:
            broker.sasl_done(err)
            return
        if resp["error_code"] != 0:
            broker.sasl_done(KafkaError(
                Err.from_wire(resp["error_code"]),
                f"SASL {mech} rejected; broker supports "
                f"{resp['mechanisms']}"))
            return
        try:
            first = client.first_message()
        except Exception as e:      # e.g. GSSError: no Kerberos ticket
            broker.sasl_done(_auth_error(e))
            return
        _auth_step(rk, broker, client, first)

    broker._xmit(Request(ApiKey.SaslHandshake, {"mechanism": mech},
                         cb=on_handshake))


def _auth_step(rk, broker, client, out_bytes: bytes):
    from .broker import Request

    def on_auth(err, resp):
        if err is not None:
            broker.sasl_done(err)
            return
        if resp["error_code"] != 0:
            broker.sasl_done(KafkaError(
                Err.from_wire(resp["error_code"]),
                resp.get("error_message") or "SASL authentication failed"))
            return
        try:
            nxt = client.step(resp["auth_bytes"] or b"")
        except Exception as e:      # provider-level failure (bad server
            broker.sasl_done(_auth_error(e))    # sig, GSS error, ...)
            return
        if nxt is None:
            broker.sasl_done(None)       # authenticated
        else:
            _auth_step(rk, broker, client, nxt)

    broker._xmit(Request(ApiKey.SaslAuthenticate, {"auth_bytes": out_bytes},
                         cb=on_auth))


class PlainClient:
    """RFC 4616: [authzid] NUL authcid NUL passwd (rdkafka_sasl_plain.c)."""

    def __init__(self, rk):
        self.user = rk.conf.get("sasl.username")
        self.passwd = rk.conf.get("sasl.password")

    def first_message(self) -> bytes:
        return b"\x00" + self.user.encode() + b"\x00" + self.passwd.encode()

    def step(self, data: bytes) -> Optional[bytes]:
        return None


class ScramClient:
    """RFC 5802 SCRAM (reference: rdkafka_sasl_scram.c, 912 LoC)."""

    def __init__(self, rk, mech: str):
        self.user = rk.conf.get("sasl.username")
        self.passwd = rk.conf.get("sasl.password").encode()
        self.hashname = "sha256" if mech.endswith("256") else "sha512"
        self.nonce = base64.b64encode(os.urandom(24)).decode()
        self.client_first_bare = f"n={self._saslname(self.user)},r={self.nonce}"
        self.server_first = ""
        self.state = 0

    @staticmethod
    def _saslname(s: str) -> str:
        return s.replace("=", "=3D").replace(",", "=2C")

    def first_message(self) -> bytes:
        return ("n,," + self.client_first_bare).encode()

    def step(self, data: bytes) -> Optional[bytes]:
        if self.state == 0:
            self.state = 1
            self.server_first = data.decode()
            fields = dict(kv.split("=", 1) for kv in self.server_first.split(","))
            r, s, i = fields["r"], fields["s"], int(fields["i"])
            if not r.startswith(self.nonce):
                raise ValueError("SCRAM server nonce mismatch")
            salted = hashlib.pbkdf2_hmac(self.hashname, self.passwd,
                                         base64.b64decode(s), i)
            client_key = hmac.new(salted, b"Client Key", self.hashname).digest()
            stored_key = hashlib.new(self.hashname, client_key).digest()
            cfinal_bare = f"c={base64.b64encode(b'n,,').decode()},r={r}"
            auth_msg = ",".join([self.client_first_bare, self.server_first,
                                 cfinal_bare]).encode()
            sig = hmac.new(stored_key, auth_msg, self.hashname).digest()
            proof = bytes(a ^ b for a, b in zip(client_key, sig))
            server_key = hmac.new(salted, b"Server Key", self.hashname).digest()
            self.server_sig = base64.b64encode(
                hmac.new(server_key, auth_msg, self.hashname).digest()).decode()
            return (cfinal_bare + ",p=" +
                    base64.b64encode(proof).decode()).encode()
        if self.state == 1:
            self.state = 2
            fields = dict(kv.split("=", 1) for kv in data.decode().split(","))
            if fields.get("v") != self.server_sig:
                raise ValueError("SCRAM server signature mismatch")
            return None
        return None


class OauthBearerClient:
    """OAUTHBEARER with the builtin unsecured-JWS token handler
    (reference: rdkafka_sasl_oauthbearer.c unsecured JWS builder)."""

    def __init__(self, rk):
        self.rk = rk
        cfg = dict(kv.split("=", 1) for kv in
                   rk.conf.get("sasl.oauthbearer.config").split(",") if "=" in kv)
        self.principal = cfg.get("principal", rk.conf.get("sasl.username")
                                 or "user")
        # app-supplied token via set_oauthbearer_token / the refresh
        # callback takes precedence; with a refresh cb configured, a
        # missing/failed/expired token FAILS auth — never a silent
        # unsecured-JWS fallback against a real broker
        got = rk.get_oauthbearer_token()
        if got is not None:
            self.token, principal, _exp = got
            if principal:
                self.principal = principal
        elif (rk.conf.get("oauthbearer_token_refresh_cb") is not None
                or rk._oauth_token is not None):
            # a configured refresh cb OR a previously app-set (now
            # expired/failed) token means the app owns credentials —
            # failing auth beats fabricating an unsecured JWS
            raise KafkaException(
                Err._AUTHENTICATION,
                "OAUTHBEARER token unavailable: "
                + (rk._oauth_failure or "token expired or not set"))
        elif not rk.conf.get("enable.sasl.oauthbearer.unsecure.jwt"):
            # reference default: the builtin unsecured-JWS handler must
            # be explicitly enabled (rdkafka_conf.c
            # "enable.sasl.oauthbearer.unsecure.jwt"); without it and
            # without an app token source, auth fails
            raise KafkaException(
                Err._AUTHENTICATION,
                "OAUTHBEARER: no token set and the builtin unsecured JWS "
                "handler is disabled "
                "(enable.sasl.oauthbearer.unsecure.jwt=false)")
        else:
            self.token = self._unsecured_jws(
                self.principal, int(cfg.get("lifeSeconds", "3600")))

    @staticmethod
    def _b64url(b: bytes) -> str:
        return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

    def _unsecured_jws(self, principal: str, life: int) -> str:
        import json
        now = int(time.time())
        header = self._b64url(json.dumps({"alg": "none"}).encode())
        claims = self._b64url(json.dumps(
            {"sub": principal, "iat": now, "exp": now + life}).encode())
        return f"{header}.{claims}."

    def first_message(self) -> bytes:
        return (f"n,,\x01auth=Bearer {self.token}\x01\x01").encode()

    def step(self, data: bytes) -> Optional[bytes]:
        return None


class GssapiClient:
    """SASL GSSAPI / Kerberos v5 (RFC 4752; reference:
    rdkafka_sasl_cyrus.c:1-645).

    Two phases, both carried in SaslAuthenticate auth_bytes:

    1. GSS-API context establishment: opaque tokens from the mechanism
       (AP-REQ / AP-REP for krb5) are relayed verbatim until the
       initiator context is complete.
    2. Security-layer negotiation: the server sends ONE wrapped 4-byte
       message (supported-layers bitmask + max message size); the client
       answers with a wrapped [chosen layer | max size | authzid].
       Kafka brokers use no security layer (TLS handles privacy), so we
       select LAYER_NONE.

    ``ctx_factory(service, host)`` builds the GSS security context; the
    default uses python-gssapi with the hostbased service name
    ``<sasl.kerberos.service.name>@<broker host>`` and the default
    credential cache (the reference's cyrus provider resolves the same
    via libsasl2). Tests inject a scripted context — the SASL framing
    above it is exactly what is under test.
    """

    SEC_LAYER_NONE = 0x01        # RFC 4752 security-layer bitmask

    def __init__(self, rk, broker_host: str, ctx_factory=None):
        service = rk.conf.get("sasl.kerberos.service.name")
        # RFC 4752 authzid stays EMPTY (authorize as the authenticated
        # principal) — the reference's cyrus provider does the same; a
        # non-empty authzid that differs from the Kerberos principal is
        # rejected by the broker's authorize check.
        self.authzid = ""
        # sasl.kerberos.principal selects which cached credential to
        # initiate with (the reference uses it for kinit); when the app
        # leaves the row untouched we use the ccache default — keyed on
        # explicit-set, not the value, so configuring the literal
        # default string still looks up that credential
        principal = rk.conf.get("sasl.kerberos.principal")
        explicit = rk.conf.is_set("sasl.kerberos.principal")
        if ctx_factory is None:
            if not gssapi_available():
                raise KafkaException(
                    Err._UNSUPPORTED_FEATURE,
                    "GSSAPI requires the python-gssapi package")
            import gssapi
            creds = None
            if explicit and principal:
                creds = gssapi.Credentials(
                    name=gssapi.Name(principal), usage="initiate")
            name = gssapi.Name(
                f"{service}@{broker_host}",
                name_type=gssapi.NameType.hostbased_service)
            self.ctx = gssapi.SecurityContext(name=name, creds=creds,
                                              usage="initiate")
        else:
            self.ctx = ctx_factory(service, broker_host)
        self._ssf_done = False

    def first_message(self) -> bytes:
        return self.ctx.step(None) or b""

    def step(self, data: bytes) -> Optional[bytes]:
        if not self.ctx.complete:
            # phase 1: relay mechanism tokens. A completing step may
            # produce no output (AP-REP consumed) — send empty bytes,
            # the server's next message starts phase 2.
            return self.ctx.step(data or None) or b""
        if not self._ssf_done:
            # phase 2: RFC 4752 §3.1 — unwrap [bitmask u8 | max u24]
            plain = self.ctx.unwrap(data).message
            if len(plain) != 4:
                raise KafkaException(
                    Err._AUTHENTICATION,
                    f"GSSAPI: malformed security-layer token "
                    f"({len(plain)} bytes, want 4)")
            offered = plain[0]
            if not offered & self.SEC_LAYER_NONE:
                raise KafkaException(
                    Err._AUTHENTICATION,
                    "GSSAPI: server does not offer security layer NONE "
                    f"(bitmask 0x{offered:02x}); TLS provides privacy "
                    "in this client")
            resp = (struct.pack(">I", self.SEC_LAYER_NONE << 24)
                    + self.authzid.encode())
            self._ssf_done = True
            return self.ctx.wrap(resp, False).message
        return None                  # outcome arrives as error_code


def render_conf_template(conf, template: str) -> str:
    """Replace ``%{config.prop.name}`` with the property's value
    (reference: rd_string_render used by the kinit cmd,
    rdkafka_sasl_cyrus.c:206)."""
    import re

    def sub(m):
        try:
            v = conf.get(m.group(1))
        except Exception:
            return ""
        return "" if v is None else str(v)

    return re.sub(r"%\{([^}]+)\}", sub, template)


def kinit_setup(rk: "Kafka") -> None:
    """Execute sasl.kerberos.kinit.cmd at client creation and then every
    sasl.kerberos.min.time.before.relogin ms (0 disables the timer) —
    the ticket-refresh loop of the reference's cyrus provider
    (rdkafka_sasl_cyrus.c:193-260, kinit_refresh_tmr). Only active for
    the GSSAPI mechanism; failures log ERROR and auth proceeds (the
    ccache may still hold a valid ticket)."""
    mech = rk.conf.get("sasl.mechanisms").upper()
    if mech not in ("GSSAPI", "KERBEROS"):
        return
    cmd_tmpl = rk.conf.get("sasl.kerberos.kinit.cmd")
    if not cmd_tmpl:
        return

    def refresh():
        import subprocess
        cmd = render_conf_template(rk.conf, cmd_tmpl)
        try:
            r = subprocess.run(["/bin/sh", "-c", cmd],
                               capture_output=True, text=True, timeout=60)
        except Exception as e:
            rk.log("ERROR", f"kinit execution failed: {e}")
            return
        if r.returncode != 0:
            rk.log("ERROR",
                   f"kinit returned {r.returncode}: "
                   f"{(r.stderr or r.stdout).strip()[:256]}")
        else:
            rk.dbg("security", f"kinit refreshed: {cmd}")

    refresh()
    interval_ms = rk.conf.get("sasl.kerberos.min.time.before.relogin")
    if interval_ms > 0:
        rk.timers.add(interval_ms / 1000.0, refresh)
