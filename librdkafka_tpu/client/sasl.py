"""SASL authentication providers: PLAIN, SCRAM-SHA-256/512, OAUTHBEARER.

The provider-vtable design mirrors struct rd_kafka_sasl_provider
(src/rdkafka_sasl_int.h:32); the handshake bytes flow over the broker's
normal request path via SaslHandshake + SaslAuthenticate requests
(Kafka >= 1.0 framing). GSSAPI/Kerberos is not provided in this build
(no libsasl2 dependency); selecting it raises _UNSUPPORTED_FEATURE.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import os
import time
from typing import TYPE_CHECKING, Optional

from ..protocol.apis import APIS
from ..protocol.proto import ApiKey
from .errors import Err, KafkaError, KafkaException

if TYPE_CHECKING:
    from .broker import Broker
    from .kafka import Kafka


SUPPORTED_MECHANISMS = ("PLAIN", "SCRAM-SHA-256", "SCRAM-SHA-512",
                        "OAUTHBEARER")


def validate_mechanism(conf) -> None:
    """Fail fast at client creation for unsupported mechanisms
    (reference: rd_kafka_sasl_select_provider, rdkafka_sasl.c:~350 —
    GSSAPI requires libsasl2/cyrus which this build does not link)."""
    mech = conf.get("sasl.mechanisms").upper()
    if mech in ("GSSAPI", "KERBEROS"):
        raise KafkaException(
            Err._UNSUPPORTED_FEATURE,
            "SASL mechanism GSSAPI (Kerberos) is not supported in this "
            "build; supported: " + ", ".join(SUPPORTED_MECHANISMS))
    if mech not in SUPPORTED_MECHANISMS:
        raise KafkaException(
            Err._UNSUPPORTED_FEATURE,
            f"Unsupported sasl.mechanisms {mech!r}; supported: "
            + ", ".join(SUPPORTED_MECHANISMS))


def sasl_client_start(rk: "Kafka", broker: "Broker") -> None:
    mech = rk.conf.get("sasl.mechanisms").upper()
    if mech == "PLAIN":
        client = PlainClient(rk)
    elif mech in ("SCRAM-SHA-256", "SCRAM-SHA-512"):
        client = ScramClient(rk, mech)
    elif mech == "OAUTHBEARER":
        try:
            client = OauthBearerClient(rk)
        except KafkaException as e:
            broker.sasl_done(e.error)   # clean auth failure + backoff
            return
    else:
        broker.sasl_done(KafkaError(
            Err._UNSUPPORTED_FEATURE,
            f"SASL mechanism {mech} not supported in this build"))
        return
    _handshake(rk, broker, mech, client)


def _handshake(rk, broker, mech, client):
    from .broker import Request

    def on_handshake(err, resp):
        if err is not None:
            broker.sasl_done(err)
            return
        if resp["error_code"] != 0:
            broker.sasl_done(KafkaError(
                Err.from_wire(resp["error_code"]),
                f"SASL {mech} rejected; broker supports "
                f"{resp['mechanisms']}"))
            return
        _auth_step(rk, broker, client, client.first_message())

    broker._xmit(Request(ApiKey.SaslHandshake, {"mechanism": mech},
                         cb=on_handshake))


def _auth_step(rk, broker, client, out_bytes: bytes):
    from .broker import Request

    def on_auth(err, resp):
        if err is not None:
            broker.sasl_done(err)
            return
        if resp["error_code"] != 0:
            broker.sasl_done(KafkaError(
                Err.from_wire(resp["error_code"]),
                resp.get("error_message") or "SASL authentication failed"))
            return
        nxt = client.step(resp["auth_bytes"] or b"")
        if nxt is None:
            broker.sasl_done(None)       # authenticated
        else:
            _auth_step(rk, broker, client, nxt)

    broker._xmit(Request(ApiKey.SaslAuthenticate, {"auth_bytes": out_bytes},
                         cb=on_auth))


class PlainClient:
    """RFC 4616: [authzid] NUL authcid NUL passwd (rdkafka_sasl_plain.c)."""

    def __init__(self, rk):
        self.user = rk.conf.get("sasl.username")
        self.passwd = rk.conf.get("sasl.password")

    def first_message(self) -> bytes:
        return b"\x00" + self.user.encode() + b"\x00" + self.passwd.encode()

    def step(self, data: bytes) -> Optional[bytes]:
        return None


class ScramClient:
    """RFC 5802 SCRAM (reference: rdkafka_sasl_scram.c, 912 LoC)."""

    def __init__(self, rk, mech: str):
        self.user = rk.conf.get("sasl.username")
        self.passwd = rk.conf.get("sasl.password").encode()
        self.hashname = "sha256" if mech.endswith("256") else "sha512"
        self.nonce = base64.b64encode(os.urandom(24)).decode()
        self.client_first_bare = f"n={self._saslname(self.user)},r={self.nonce}"
        self.server_first = ""
        self.state = 0

    @staticmethod
    def _saslname(s: str) -> str:
        return s.replace("=", "=3D").replace(",", "=2C")

    def first_message(self) -> bytes:
        return ("n,," + self.client_first_bare).encode()

    def step(self, data: bytes) -> Optional[bytes]:
        if self.state == 0:
            self.state = 1
            self.server_first = data.decode()
            fields = dict(kv.split("=", 1) for kv in self.server_first.split(","))
            r, s, i = fields["r"], fields["s"], int(fields["i"])
            if not r.startswith(self.nonce):
                raise ValueError("SCRAM server nonce mismatch")
            salted = hashlib.pbkdf2_hmac(self.hashname, self.passwd,
                                         base64.b64decode(s), i)
            client_key = hmac.new(salted, b"Client Key", self.hashname).digest()
            stored_key = hashlib.new(self.hashname, client_key).digest()
            cfinal_bare = f"c={base64.b64encode(b'n,,').decode()},r={r}"
            auth_msg = ",".join([self.client_first_bare, self.server_first,
                                 cfinal_bare]).encode()
            sig = hmac.new(stored_key, auth_msg, self.hashname).digest()
            proof = bytes(a ^ b for a, b in zip(client_key, sig))
            server_key = hmac.new(salted, b"Server Key", self.hashname).digest()
            self.server_sig = base64.b64encode(
                hmac.new(server_key, auth_msg, self.hashname).digest()).decode()
            return (cfinal_bare + ",p=" +
                    base64.b64encode(proof).decode()).encode()
        if self.state == 1:
            self.state = 2
            fields = dict(kv.split("=", 1) for kv in data.decode().split(","))
            if fields.get("v") != self.server_sig:
                raise ValueError("SCRAM server signature mismatch")
            return None
        return None


class OauthBearerClient:
    """OAUTHBEARER with the builtin unsecured-JWS token handler
    (reference: rdkafka_sasl_oauthbearer.c unsecured JWS builder)."""

    def __init__(self, rk):
        self.rk = rk
        cfg = dict(kv.split("=", 1) for kv in
                   rk.conf.get("sasl.oauthbearer.config").split(",") if "=" in kv)
        self.principal = cfg.get("principal", rk.conf.get("sasl.username")
                                 or "user")
        # app-supplied token via set_oauthbearer_token / the refresh
        # callback takes precedence; with a refresh cb configured, a
        # missing/failed/expired token FAILS auth — never a silent
        # unsecured-JWS fallback against a real broker
        got = rk.get_oauthbearer_token()
        if got is not None:
            self.token, principal, _exp = got
            if principal:
                self.principal = principal
        elif (rk.conf.get("oauthbearer_token_refresh_cb") is not None
                or rk._oauth_token is not None):
            # a configured refresh cb OR a previously app-set (now
            # expired/failed) token means the app owns credentials —
            # failing auth beats fabricating an unsecured JWS
            raise KafkaException(
                Err._AUTHENTICATION,
                "OAUTHBEARER token unavailable: "
                + (rk._oauth_failure or "token expired or not set"))
        else:
            self.token = self._unsecured_jws(
                self.principal, int(cfg.get("lifeSeconds", "3600")))

    @staticmethod
    def _b64url(b: bytes) -> str:
        return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

    def _unsecured_jws(self, principal: str, life: int) -> str:
        import json
        now = int(time.time())
        header = self._b64url(json.dumps({"alg": "none"}).encode())
        claims = self._b64url(json.dumps(
            {"sub": principal, "iat": now, "exp": now + life}).encode())
        return f"{header}.{claims}."

    def first_message(self) -> bytes:
        return (f"n,,\x01auth=Bearer {self.token}\x01\x01").encode()

    def step(self, data: bytes) -> Optional[bytes]:
        return None
