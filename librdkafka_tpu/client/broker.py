"""Broker engine: one thread per broker (reference: src/rdkafka_broker.c).

Each ``Broker`` runs a connection state machine
(INIT→TRY_CONNECT→CONNECT→AUTH→APIVERSION_QUERY→UP, rdkafka_broker.h:88-100)
inside its own thread (rd_kafka_broker_thread_main, rdkafka_broker.c:4653),
multiplexing socket IO with an op-queue wakeup pipe
(rd_kafka_broker_ops_io_serve, :3009). Requests flow through three queues:
outq (to send), waitresp (corrid-matched in-flight, :1449), retryq
(backoff retry, :2352).

The producer hot loop (rd_kafka_toppar_producer_serve, :3242) is rebuilt
here with the TPU seam widened: each serve pass collects *all* ready
partition batches, frames them (phase 1), compresses+CRCs them in ONE
batched codec-provider call (phase 2 — a single vmapped TPU launch when
compression.backend=tpu), then finalizes and sends (phase 3).
"""
from __future__ import annotations

import enum
import errno
import random
import select
import socket
import ssl as _ssl
import struct
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional, TYPE_CHECKING

from ..obs import trace as _trace
from ..analysis import lockdep as _lockdep
from ..analysis.races import shared
from ..ops.packing import FrameBlob
from ..protocol import apis, proto
from ..protocol.apis import APIS
from ..utils import sockbuf
from ..protocol.msgset import MsgsetWriterV2
from ..protocol.proto import ApiKey, ATTR_TRANSACTIONAL
from .errors import Err, KafkaError, KafkaException
from .feature import (MSGVER1, MSGVER2, fallback_api_versions,
                      features_from_api_versions, pick_version)
from .arena import ArenaBatch, batch_head_msgid
from .msg import Message, MsgStatus
from .queue import Op, OpQueue, OpType

if TYPE_CHECKING:
    from .kafka import Kafka


class BrokerState(enum.Enum):
    INIT = "INIT"
    DOWN = "DOWN"
    TRY_CONNECT = "TRY_CONNECT"
    CONNECT = "CONNECT"
    AUTH_HANDSHAKE = "AUTH_HANDSHAKE"
    AUTH_REQ = "AUTH_REQ"
    APIVERSION_QUERY = "APIVERSION_QUERY"
    UP = "UP"


@dataclass
class Request:
    api: ApiKey
    body: dict
    cb: Optional[Callable] = None      # cb(err: KafkaError|None, resp: dict)
    expect_response: bool = True
    retries_left: int = 0
    abs_timeout: float = 0.0
    corrid: int = 0
    version: Optional[int] = None      # api version override
    opaque: object = None
    ts_enq: float = 0.0                # enqueue_request() time (outbuf lat.)
    ts_sent: float = 0.0               # wire write time (rtt)


# max in-flight ProduceRequests per partition with idempotence
# (reference: RD_KAFKA_IDEMP_MAX_INFLIGHT, rdkafka_idempotence.h:38)
IDEMP_MAX_INFLIGHT = 5


class _FusedJob:
    """Phase-2 marker replacing MsgsetWriterV2 for ArenaBatches the
    fused native builder (tk_enqlane.build_batch) can finish in one
    GIL-released call: frame + compress + v2 header + CRC, no
    intermediate Python bytes.  Idempotence fields are captured at
    batch-formation time exactly like _make_writer does."""

    __slots__ = ("codec_id", "pid", "epoch", "base_seq", "now_ms",
                 "attrs")

    def __init__(self, codec_id: int, pid: int, epoch: int,
                 base_seq: int, now_ms: int, attrs: int = 0):
        self.codec_id = codec_id
        self.pid = pid
        self.epoch = epoch
        self.base_seq = base_seq
        self.now_ms = now_ms
        # extra v2 attribute bits (ATTR_TRANSACTIONAL for EOS batches)
        self.attrs = attrs


def _fused_builder():
    from .arena import _mod
    m = _mod()
    return getattr(m, "build_batch", None) if m else None


class _PendingCodec:
    """A codec phase in flight on the async offload engine
    (ops/engine.py), as a two-stage state machine:

      stage "compress" — the per-(codec,level) compress groups ride the
        engine as host-job tickets (``comp_tickets``), so compression
        of batch k+1 runs on the dispatch thread while batch k's CRC
        launch executes on the device.  When they resolve, the writers
        assemble and the CRC batch is submitted.
      stage "crc" — the writers in ``assembled`` await their ticket's
        checksums; finish() patches CRCs and returns the results in
        ``ready`` order.

    done() advances the state machine opportunistically so the codec
    worker's poll loop pipelines both stages without blocking."""

    __slots__ = ("rk", "by_idx", "n", "writer_items", "assembled",
                 "ticket", "comp_tickets", "t_compress_ns", "t_crc_ns")

    def __init__(self, rk, by_idx: dict, n: int, writer_items: list):
        self.rk = rk
        self.by_idx = by_idx
        self.n = n
        self.writer_items = writer_items    # [(idx, (tp, msgs, writer))]
        self.comp_tickets = None            # [(idxs, ticket)] stage 1
        self.assembled = []                 # [(idx, (tp, msgs, writer))]
        self.ticket = None                  # CRC ticket, stage 2
        self.t_compress_ns = 0              # compress submit (trace)
        self.t_crc_ns = 0                   # CRC submit (trace)

    def done(self) -> bool:
        if self.comp_tickets is not None:
            if not all(t.done() for _i, t in self.comp_tickets):
                return False
            self._assemble()
        return self.ticket is None or self.ticket.done()

    def _assemble(self) -> None:
        """Compress tickets resolved: incompressible check + writer
        assembly + CRC submit — exactly the synchronous phase tail."""
        tickets, self.comp_tickets = self.comp_tickets, None
        blobs: dict[int, bytes] = {}
        try:
            for idxs, t in tickets:
                for i, blob in zip(idxs, t.result(120)):
                    blobs[i] = blob
        except Exception as e:      # a failed group fails the batch set
            for i, (tp, msgs, _w) in self.writer_items:
                self.by_idx[i] = (tp, msgs, None, e)
            return
        if self.t_compress_ns:
            # compress-ticket span: submit -> all groups resolved
            _trace.complete("produce", "compress", self.t_compress_ns,
                            {"groups": len(tickets),
                             "batches": len(self.writer_items)})
        if _trace.enabled:
            self.t_crc_ns = _trace.now()
        self.assembled, self.ticket = _assemble_and_submit_crc(
            self.rk, self.writer_items, self.by_idx, blobs)

    def finish(self) -> list:
        if self.comp_tickets is not None:
            self._assemble()        # blocks on the compress tickets
        if self.ticket is not None:
            try:
                crcs = self.ticket.result()
            except Exception as e:
                for i, (tp, msgs, _w) in self.assembled:
                    self.by_idx[i] = (tp, msgs, None, e)
            else:
                for (i, (tp, msgs, w)), crc in zip(self.assembled, crcs):
                    self.by_idx[i] = (tp, msgs, w.patch_crc(int(crc)),
                                      None)
            if self.t_crc_ns:
                # CRC-ticket span: submit -> checksums patched (covers
                # the engine's fan-in wait + launch + readback)
                _trace.complete("produce", "crc_ticket", self.t_crc_ns,
                                {"batches": len(self.assembled)})
        return [self.by_idx[i] for i in range(self.n)]


def _run_codec_phase(rk, ready: list) -> list:
    """Compress + assemble + CRC a batch set, synchronously. Pure
    compute — safe on any thread. Returns
    [(tp, msgs, wire|None, exc|None)] in ``ready`` order (same-tp
    batches must stay FIFO)."""
    results, pending = _begin_codec_phase(rk, ready)
    return results if pending is None else pending.finish()


def _begin_codec_phase(rk, ready: list):
    """Phase 2 with an async seam: returns ``(results, None)`` when the
    whole phase resolved synchronously, or ``(None, _PendingCodec)``
    when the provider accepted the CRC batch as an async ticket — the
    caller overlaps other work and calls pending.finish() later.

    ArenaBatches carrying a _FusedJob take the fused native path; the
    rest (Message batches, non-native codecs, device-routed providers)
    run the 3-phase writer pipeline."""
    build = _fused_builder()
    by_idx: dict[int, tuple] = {}
    writer_items: list[tuple[int, tuple]] = []
    for i, item in enumerate(ready):
        tp, msgs, w = item
        if isinstance(w, _FusedJob):
            try:
                if build is None:       # extension vanished mid-flight
                    raise RuntimeError("fused builder unavailable")
                t0 = _trace.now() if _trace.enabled else 0
                wire = build(msgs.base, msgs.klens, msgs.vlens,
                             msgs.count, w.now_ms, w.pid, w.epoch,
                             w.base_seq, w.codec_id, w.attrs,
                             msgs.tss, msgs.hbuf, msgs.hlens)
                if t0:
                    # the one-call frame+compress+CRC fast lane
                    _trace.complete("produce", "fused_build", t0,
                                    {"topic": tp.topic,
                                     "partition": tp.partition,
                                     "msgs": msgs.count})
                by_idx[i] = (tp, msgs, wire, None)
            except Exception as e:
                by_idx[i] = (tp, msgs, None, e)
        else:
            writer_items.append((i, item))
    pending = None
    if writer_items:
        pending = _begin_writer_phase(rk, writer_items, by_idx, len(ready))
    if pending is not None:
        return None, pending
    return [by_idx[i] for i in range(len(ready))], None


def _begin_writer_phase(rk, writer_items: list, by_idx: dict,
                        n: int):
    """Compress + assemble the non-fused batches, filling ``by_idx`` for
    failures.  With an engine-backed provider BOTH codec stages go
    async: compression rides ``compress_submit`` (an engine host job,
    overlapping the previous batch's in-flight CRC launch) and the CRC
    batch rides ``crc32c_submit``; otherwise each stage runs
    synchronously here.  Returns a _PendingCodec or None (phase fully
    resolved into ``by_idx``)."""
    provider = rk.codec_provider
    # compression.codec and compression.level are topic-scoped:
    # group the fan-in by (codec, level) so one serve pass honors
    # every topic's settings (each writer carries its own codec,
    # resolved at batch formation via Broker._codec_for)
    by_key: dict = {}
    for i, (tp, _msgs, w) in writer_items:
        if w.codec is None:
            continue
        lvl = rk.topic_conf_for(tp.topic).get("compression.level")
        by_key.setdefault((w.codec, lvl), []).append(i)
    items = {i: item for i, item in writer_items}

    csub = getattr(provider, "compress_submit", None)
    if csub is not None and by_key:
        t_comp = _trace.now() if _trace.enabled else 0
        # topic.qos.weight: per-buffer (topic, weight) pairs feed the
        # engine's weighted fan-in + shed model.  Only offered to
        # providers that declare accepts_qos — test doubles keep the
        # 3-arg compress_submit signature.
        accepts_qos = getattr(provider, "accepts_qos", False)
        wcache: dict = {}
        comp_tickets = []
        for (cdc, lvl), idxs in by_key.items():
            try:
                if accepts_qos:
                    qos = []
                    for i in idxs:
                        topic = items[i][0].topic
                        w = wcache.get(topic)
                        if w is None:
                            w = float(rk.topic_conf_for(topic).get(
                                "topic.qos.weight") or 1.0)
                            wcache[topic] = w
                        qos.append((topic, w))
                    t = csub(cdc,
                             [items[i][2].records_bytes for i in idxs],
                             lvl, qos=qos)
                else:
                    t = csub(cdc,
                             [items[i][2].records_bytes for i in idxs],
                             lvl)
            except Exception:
                t = None
            if t is None:           # pipeline disabled: sync route below
                comp_tickets = None
                break
            comp_tickets.append((idxs, t))
        if comp_tickets is not None:
            pend = _PendingCodec(rk, by_idx, n, writer_items)
            pend.comp_tickets = comp_tickets
            pend.t_compress_ns = t_comp
            return pend

    try:
        t_comp = _trace.now() if _trace.enabled else 0
        blobs = {}
        for (cdc, lvl), idxs in by_key.items():
            out = provider.compress_many(
                cdc, [items[i][2].records_bytes for i in idxs], lvl)
            for i, blob in zip(idxs, out):
                blobs[i] = blob
        if t_comp and by_key:
            _trace.complete("produce", "compress", t_comp,
                            {"groups": len(by_key),
                             "batches": len(writer_items)})
    except Exception as e:
        for i, (tp, msgs, _w) in writer_items:
            by_idx[i] = (tp, msgs, None, e)
        return None

    t_crc = _trace.now() if _trace.enabled else 0
    assembled, ticket = _assemble_and_submit_crc(rk, writer_items,
                                                 by_idx, blobs)
    if ticket is None:
        return None
    pend = _PendingCodec(rk, by_idx, n, writer_items)
    pend.assembled = assembled
    pend.ticket = ticket
    pend.t_crc_ns = t_crc
    return pend


def _assemble_and_submit_crc(rk, writer_items: list, by_idx: dict,
                             blobs: dict):
    """Incompressible check + writer assembly; the CRC batch goes to
    the provider's async submit seam when it has one
    (``crc32c_submit`` -> Ticket), else it is computed synchronously
    into ``by_idx``.  Returns ``(assembled, ticket)`` — ticket None
    means the CRC stage fully resolved here."""
    provider = rk.codec_provider
    assembled = []                # (idx, (tp, msgs, writer))
    regions = []                  # CRC region per batch
    for i, (tp, msgs, writer) in writer_items:
        blob = blobs.get(i)
        try:
            if blob is not None and len(blob) >= len(writer.records_bytes):
                blob = None       # incompressible: send plain
                writer.codec = None
            region = writer.assemble(blob)
            if isinstance(blob, FrameBlob):
                # fused compress→CRC route (ISSUE 17): the frame came
                # back from the device with per-part CRCs — fold the
                # batch CRC over the 21-byte header prefix with
                # crc32c_combine instead of re-scanning the frame.
                crc = blob.region_crc(
                    bytes(region[:len(region) - len(blob)]))
                by_idx[i] = (tp, msgs, writer.patch_crc(crc), None)
                continue
            regions.append(region)
            assembled.append((i, (tp, msgs, writer)))
        except Exception as e:
            by_idx[i] = (tp, msgs, None, e)
    if not assembled:
        return [], None
    submit = getattr(provider, "crc32c_submit", None)
    if submit is not None:
        try:
            ticket = submit(regions)
        except Exception:
            ticket = None
        if ticket is not None:
            return assembled, ticket
    try:
        crcs = provider.crc32c_many(regions)
        for (i, (tp, msgs, writer)), crc in zip(assembled, crcs):
            by_idx[i] = (tp, msgs, writer.patch_crc(int(crc)), None)
    except Exception as e:
        for i, (tp, msgs, _w) in assembled:
            by_idx[i] = (tp, msgs, None, e)
    return [], None


class _PendingFetch:
    """A fetch partition whose phase-B CRC verify and phase-C decompress
    are in flight as offload tickets (the consumer mirror of
    _PendingCodec): phase-A framing/splitting is done, the partition's
    ``fetch_in_flight`` claim is still held, and phase D (parse +
    delivery) runs at resolve time — strictly FIFO per broker, so
    per-partition delivery order is preserved exactly."""

    __slots__ = ("entry", "crc_ticket", "crc_infos",
                 "legacy_ticket", "legacy_owners", "dec_tickets",
                 "t_submit_ns")

    def __init__(self, entry):
        self.entry = entry          # (tp, pres, batches, fo, ver)
        self.crc_ticket = None      # v2 batch-CRC (crc32c) ticket
        self.crc_infos = ()         # batch infos in crc_ticket order
        self.legacy_ticket = None   # MsgVer0/1 zlib-poly CRC ticket
        self.legacy_owners = ()     # (offset, wanted_crc) per region
        self.dec_tickets = ()       # [(codec, items, ticket)]
        self.t_submit_ns = 0        # ticket submit (fetch_latency/trace)

    def done(self) -> bool:
        for t in (self.crc_ticket, self.legacy_ticket):
            if t is not None and not t.done():
                return False
        return all(t.done() for _c, _i, t in self.dec_tickets)


class CodecWorker(threading.Thread):
    """The codec pipeline thread (one per producer instance): runs the
    batched compress+CRC phase off the broker threads so socket IO and
    batch formation overlap with device/native launches (the
    double-buffered offload of SURVEY.md §5 axis 2, absent in the
    reference — its compression runs inline on each broker thread,
    rdkafka_msgset_writer.c:1129)."""

    # relaxed: written only by the codec worker thread; tests read the
    # high-water mark after flush/close joins
    inflight_hwm = shared("codec_worker.inflight_hwm", relaxed=True)

    def __init__(self, rk):
        super().__init__(daemon=True, name="rdk:codec")
        import queue as _q
        self.rk = rk
        self.jobs = _q.Queue()
        # max codec jobs whose CRC tickets may be outstanding before
        # the worker blocks on the oldest — mirrors the broker-side
        # codec.pipeline.depth gate so results can't pile up unbounded
        self.max_inflight = max(
            2, int(getattr(rk, "codec_pipeline_depth", 2) or 2))
        # test/bench observability: high-water mark of concurrently
        # in-flight async CRC tickets (>=2 proves pipeline overlap)
        self.inflight_hwm = 0
        self.start()

    def submit(self, broker: "Broker", ready: list,
               ts_codec: float, purge_epoch: int) -> None:
        self.jobs.put((broker, ready, ts_codec, purge_epoch))

    def stop(self) -> None:
        self.jobs.put(None)

    def run(self):
        if self.rk.interceptors:
            self.rk.interceptors.on_thread_start("codec", self.name)
        try:
            self._run()
        finally:
            if self.rk.interceptors:
                self.rk.interceptors.on_thread_exit("codec", self.name)

    def _post(self, broker, results, ts_codec, pepoch) -> None:
        broker.ops.push(Op(OpType.BROKER_WAKEUP,
                           payload=("codec_done", results, ts_codec,
                                    pepoch)))

    def _finish(self, entry) -> None:
        broker, pending, ts_codec, pepoch = entry
        self._post(broker, pending.finish(), ts_codec, pepoch)

    def _run(self):
        """Pipelined consume loop: phase-2 work whose CRC went to the
        async offload engine parks in ``pending`` as a ticket; the
        worker frames + compresses the NEXT job while the device
        executes, and patches checksums when tickets resolve — the
        double-buffered overlap of ISSUE 1 (the r5 loop blocked inside
        _run_codec_phase for every device round-trip).  ``pending``
        drains strictly FIFO so per-partition send order — and with it
        idempotent sequence order — is preserved."""
        import queue as _q
        pending: deque = deque()
        while True:
            # reap resolved tickets (FIFO — stop at the first unresolved)
            while pending and pending[0][1].done():
                self._finish(pending.popleft())
            # cap the in-flight window: block on the oldest ticket
            while len(pending) >= self.max_inflight:
                self._finish(pending.popleft())
            try:
                # with tickets in flight, poll briefly so the next job
                # overlaps the device; idle otherwise blocks for real
                job = self.jobs.get(timeout=0.002 if pending else None)
            except _q.Empty:
                if pending:
                    self._finish(pending.popleft())
                continue
            if job is None:
                while pending:
                    self._finish(pending.popleft())
                return
            broker, ready, ts_codec, pepoch = job
            try:
                results, pend = _begin_codec_phase(self.rk, ready)
            except Exception as e:      # belt & braces: fail every batch
                results, pend = ([(tp, msgs, None, e)
                                  for tp, msgs, _w in ready], None)
            if pend is None:
                self._post(broker, results, ts_codec, pepoch)
            else:
                pending.append((broker, pend, ts_codec, pepoch))
                self.inflight_hwm = max(self.inflight_hwm, len(pending))


class Broker:
    """One broker connection + its serve thread."""

    # lockset declarations (analysis/races.py), all RELAXED with one
    # justification: the broker is single-writer by design — every
    # field below is mutated ONLY on this broker's serve thread (ops
    # from other threads arrive through the locked OpQueue and are
    # applied here), while the stats emitter and kafka accessors take
    # lock-free len()/enum/int snapshots.  Those are atomic under the
    # GIL and a one-emit-stale gauge is acceptable; adding a broker
    # state lock would put an acquisition on every serve-loop step.
    # The sweep still tracks these through the state machine, so a
    # future SECOND writer thread shows up in the relaxed report.
    state = shared("broker.state", relaxed=True)
    ts_state = shared("broker.ts_state", relaxed=True)
    waitresp = shared("broker.waitresp", relaxed=True)
    toppars = shared("broker.toppars", relaxed=True)
    _unsent_req_ends = shared("broker.unsent_req_ends", relaxed=True)
    _fetch_pending = shared("broker.fetch_pending", relaxed=True)
    _fetch_deferred = shared("broker.fetch_deferred", relaxed=True)
    reconnect_backoff = shared("broker.reconnect_backoff", relaxed=True)
    c_tx = shared("broker.c_tx", relaxed=True)
    c_rx = shared("broker.c_rx", relaxed=True)
    c_tx_bytes = shared("broker.c_tx_bytes", relaxed=True)
    c_rx_bytes = shared("broker.c_rx_bytes", relaxed=True)
    c_connects = shared("broker.c_connects", relaxed=True)
    c_req_timeouts = shared("broker.c_req_timeouts", relaxed=True)
    # KIP-227 fetch session + per-API fetch wire counters (ISSUE 14):
    # mutated on the serve thread (request build / response handling),
    # snapshot-read by the stats emitter like the counters above
    _fetch_session = shared("broker.fetch_session", relaxed=True)
    c_fetch_tx_bytes = shared("broker.c_fetch_tx_bytes", relaxed=True)
    c_fetch_rx_bytes = shared("broker.c_fetch_rx_bytes", relaxed=True)

    def __init__(self, rk: "Kafka", nodeid: int, host: str, port: int,
                 name: str = ""):
        self.rk = rk
        self.nodeid = nodeid
        self.host = host
        self.port = port
        self.name = name or f"{host}:{port}/{nodeid}"
        self.state = BrokerState.INIT
        self.ops = OpQueue(f"broker-{self.name}-ops")
        self.sock: Optional[socket.socket] = None
        self.outq: deque[Request] = deque()
        self.waitresp: dict[int, Request] = {}
        self.retryq: list[tuple[float, Request]] = []
        self._corrid = 0
        self._rbuf = bytearray()
        # segment-queue write buffer: request segments drain via
        # sendmsg iovecs without being flattened (sockbuf.SegWriter)
        self._wbuf = sockbuf.SegWriter()
        # built-but-untransmitted request accounting for
        # queue.buffering.backpressure.threshold (reference: rkb_outbufs
        # count, rdkafka_broker.c:3262). The deque holds each queued
        # request's end position in the writer's monotonic queued-bytes
        # space.
        self._unsent_req_ends: deque[int] = deque()
        self._wakeup_r, self._wakeup_w = socket.socketpair()
        self._wakeup_r.setblocking(False)
        # non-blocking: a full pipe must drop the wakeup byte (reader is
        # already pending), never block the op-pushing thread
        self._wakeup_w.setblocking(False)
        self.ops.set_wakeup_cb(self._wakeup)
        self.api_versions: dict[int, int] = {}
        # None = not yet negotiated (vs set() = negotiated, no
        # features — a 0.8.x broker); the writer must not assume v2
        # before negotiation resolves (reference: rkb_features set by
        # rd_kafka_broker_features_set after ApiVersions/fallback)
        self.features: set[str] | None = None
        self._apiversion_failed = False   # broker closed on ApiVersions
        self._fallback_until = 0.0        # api.version.fallback.ms window
        self.reconnect_backoff = rk.conf.get("reconnect.backoff.ms") / 1000.0
        self._next_connect = 0.0
        # (monotonic, applied_delay_s) per backoff decision, newest
        # last — observability for the chaos retry-shape tests
        self.reconnect_history: deque = deque(maxlen=64)
        self._connect_wanted = False    # sparse-connections override
        self.terminate = False
        self.fetch_inflight_cnt = 0     # outstanding FetchRequests
        # fetch responses' partitions awaiting decompress+parse under
        # the decompressed-ahead budget (see _serve_deferred_fetch)
        self._fetch_deferred: deque = deque()
        # partitions whose codec phases (CRC verify / decompress) are in
        # flight as offload tickets (_PendingFetch FIFO; claims held
        # until phase D resolves — see _reap_fetch_pending)
        self._fetch_pending: deque = deque()
        self._tls_handshaking = False
        self._codec_outstanding = 0     # async codec jobs in flight
        self._last_throttle = 0         # throttle_cb change detection
        self.toppars: set = set()           # toppars led by this broker
        self.ts_connected = 0.0
        self.ts_state = time.monotonic()    # last state CHANGE (stats)
        # stats
        self.c_tx = self.c_rx = self.c_tx_bytes = self.c_rx_bytes = 0
        self.c_connects = 0             # connection attempts (stats)
        self.c_req_timeouts = 0
        # Fetch-API wire bytes (both directions), split out from the
        # totals so the bench can prove the incremental-session savings
        # (stats: brokers[].fetch_session + top-level wire_fetch_bytes)
        self.c_fetch_tx_bytes = 0
        self.c_fetch_rx_bytes = 0
        # KIP-227 incremental fetch session with this broker
        # (client/fetch_session.py); torn down on disconnect
        from .fetch_session import FetchSession
        self._fetch_session = FetchSession()
        # consecutive request timeouts since the last good response;
        # socket.max.fails of these mark the connection broken
        # (reference: rkb_req_timeouts, rdkafka_broker.c timeout scan)
        self._req_timeouts_pending = 0
        # latency decomposition (reference: rkb_avg_rtt/outbuf_latency/
        # throttle, rdkafka_broker.h; emitted rdkafka.c:1582-1630)
        from .stats import Avg
        self.rtt_avg = Avg()            # request sent -> response (µs)
        self.outbuf_avg = Avg()         # enqueue -> wire write (µs)
        self.throttle_avg = Avg(1, 5 * 60 * 1000, 3)  # broker throttle (ms)
        # consumer fetch-pipeline window (ISSUE 5): codec-ticket submit
        # (_begin_fetch_partition) -> reap (_reap_fetch_pending), the
        # per-broker mirror of the producer's codec_latency
        self.fetch_latency_avg = Avg()
        self.thread = threading.Thread(target=self._thread_main,
                                       name=f"rdk:broker/{self.name}",
                                       daemon=True)

    def start(self):
        self.thread.start()

    # ------------------------------------------------------------ wakeup --
    def _wakeup(self):
        try:
            self._wakeup_w.send(b"x")
        except (BlockingIOError, OSError):
            pass

    # -------------------------------------------------------- public API --
    def enqueue_request(self, req: Request) -> None:
        """Thread-safe: queue a request for transmission (any thread)."""
        req.ts_enq = time.monotonic()
        self.ops.push(Op(OpType.BROKER_WAKEUP, payload=("xmit", req)))

    def add_toppar(self, toppar) -> None:
        self.ops.push(Op(OpType.PARTITION_JOIN, payload=toppar))

    def remove_toppar(self, toppar) -> None:
        self.ops.push(Op(OpType.PARTITION_LEAVE, payload=toppar))

    def stop(self):
        self.ops.push(Op(OpType.TERMINATE))

    def is_up(self) -> bool:
        return self.state == BrokerState.UP

    def _has_work(self) -> bool:
        """Anything that needs a live connection (sparse-connections
        gate): led/fetched toppars, queued or in-flight requests, or an
        explicit connection request from a component that needs this
        specific broker up (admin controller/coordinator targeting)."""
        return bool(self.toppars or self.outq or self.waitresp
                    or self.retryq or self._connect_wanted)

    def schedule_connect(self) -> None:
        """On-demand connection under sparse connections (reference:
        rd_kafka_broker_schedule_connection, rdkafka_broker.c:880):
        called by waiters that need THIS broker UP before they can
        enqueue a request (admin worker, cgrp coordinator)."""
        if not self._connect_wanted:
            self._connect_wanted = True
            self._wakeup()

    # --------------------------------------------------------- the thread --
    def _thread_main(self):
        if self.rk.interceptors:
            self.rk.interceptors.on_thread_start("broker", self.name)
        while not self.terminate:
            try:
                self._serve()
            except Exception as e:  # keep the broker thread alive
                self.rk.log("ERROR", f"broker {self.name} serve error: {e!r}")
                self._disconnect(KafkaError(Err._FAIL, repr(e)))
                # error backoff, not a wait-for-state: nothing signals
                # "the fault cleared", so there is no condvar to wait on
                time.sleep(0.05)  # lint: ok sleep-poll
        self._disconnect(KafkaError(Err._DESTROY, "terminating"))
        # release deferred partitions' in-flight claims so another
        # broker (or a later instance) can fetch them.  Guarded: close()
        # tears these structures down concurrently once the join times
        # out, and a release raced that way must not kill the exit path
        # ("deque mutated during iteration")
        try:
            for entry in list(self._fetch_deferred):
                entry[0].fetch_in_flight = False
            self._fetch_deferred.clear()
            for pend in list(self._fetch_pending):
                pend.entry[0].fetch_in_flight = False
            self._fetch_pending.clear()
        except Exception:
            pass
        if self.rk.interceptors:
            self.rk.interceptors.on_thread_exit("broker", self.name)

    def _serve(self):
        now = time.monotonic()
        # deferred fetch partitions need no socket — drain them FIRST
        # so a DOWN/backing-off/sparse-idle broker still delivers what
        # it already received (their toppars hold fetch_in_flight until
        # processed, so leaving them parked would starve the partitions
        # on every broker)
        if self._fetch_deferred or self._fetch_pending:
            self._serve_deferred_fetch()
        if self.state in (BrokerState.INIT, BrokerState.DOWN):
            # sparse connections (reference enable.sparse.connections,
            # hidden, default true; rdkafka_broker.c:880): a metadata-
            # discovered broker with nothing to do stays unconnected.
            # Bootstrap brokers (nodeid < 0) always connect — they are
            # the metadata path.
            if (self.nodeid >= 0 and not self._has_work()
                    and self.rk.conf.get("enable.sparse.connections")):
                self._serve_ops(0.05)
                if not self._has_work():
                    return
            if now >= self._next_connect:
                self._try_connect()
            else:
                self._serve_ops(min(0.05, self._next_connect - now))
                return
        self._serve_ops(0)
        if self._tls_handshaking:
            self._tls_handshake_serve()
            return
        self._serve_retries(now)
        if self.state == BrokerState.UP:
            if self.rk.is_producer:
                self._producer_serve(now)
            if self.rk.is_consumer:
                self._consumer_serve(now)
        self._io_serve()
        self._scan_timeouts(now)

    def _serve_ops(self, timeout: float):
        deadline = time.monotonic() + timeout
        while True:
            op = self.ops.pop(0)
            if op is None:
                if timeout > 0 and time.monotonic() < deadline:
                    op = self.ops.pop(deadline - time.monotonic())
                    if op is None:
                        return
                else:
                    return
            self._op_serve(op)
            timeout = 0

    def _op_serve(self, op: Op):
        """(reference: rd_kafka_broker_op_serve, rdkafka_broker.c:2597)"""
        if op.type == OpType.TERMINATE:
            self.terminate = True
        elif op.type == OpType.PURGE:
            # abandon in-flight ProduceRequests (rd_kafka_purge
            # RD_KAFKA_PURGE_F_INFLIGHT): fail them locally; the late
            # response hits an unknown corrid and is dropped
            for corrid, req in list(self.waitresp.items()):
                if req.api == ApiKey.Produce:
                    del self.waitresp[corrid]
                    if req.cb:
                        req.cb(KafkaError(Err._PURGE_INFLIGHT,
                                          "purged in flight",
                                          retriable=False), None)
        elif op.type == OpType.PARTITION_JOIN:
            self.toppars.add(op.payload)
        elif op.type == OpType.PARTITION_LEAVE:
            self.toppars.discard(op.payload)
        elif (op.type == OpType.BROKER_WAKEUP and op.payload
                and op.payload[0] == "codec_done"):
            _, results, ts_codec, pepoch = op.payload
            self._codec_outstanding -= 1
            self._codec_results(results, ts_codec, pepoch)
        elif op.type == OpType.BROKER_WAKEUP and op.payload:
            kind, req = op.payload
            if kind == "xmit":
                if self.state == BrokerState.UP:
                    self._xmit(req)
                else:
                    # park until UP; fail fast if down too long
                    self.outq.append(req)

    # ------------------------------------------------------ connect logic --
    def _try_connect(self):
        # one-shot demand satisfied by this attempt; a still-waiting
        # component re-schedules on its next resolve pass
        self._connect_wanted = False
        self._set_state(BrokerState.TRY_CONNECT)
        self.c_connects += 1
        if _lockdep.enabled:
            _lockdep.note_blocking("broker.connect")
        try:
            self.sock = self.rk.connect_cb(self.host, self.port,
                                           self.rk.conf.get(
                                               "socket.timeout.ms") / 1000.0)
            self.sock.setblocking(False)
            if self.rk.conf.get("socket.nagle.disable"):
                try:
                    self.sock.setsockopt(socket.IPPROTO_TCP,
                                         socket.TCP_NODELAY, 1)
                except OSError:
                    pass    # not TCP (e.g. a sockem AF_UNIX pair)
        except OSError as e:
            self.sock = None
            self._connect_failed(f"connect failed: {e}")
            return
        except KafkaException as e:
            self.sock = None
            self._connect_failed(e.error.reason)
            return
        self.ts_connected = time.monotonic()
        # TLS: wrap the socket and drive the non-blocking handshake from
        # the serve loop (reference: rdkafka_transport.c:612-719 drives
        # rd_kafka_transport_ssl_handshake from CONNECT state)
        ctx = self.rk.ssl_ctx()
        if ctx is not None:
            try:
                self.sock = ctx.wrap_socket(self.sock, server_hostname=self.host,
                                            do_handshake_on_connect=False)
            except (OSError, ValueError) as e:
                self._disconnect(KafkaError(Err._SSL, f"TLS wrap: {e}"))
                return
            self._tls_handshaking = True
            self._set_state(BrokerState.CONNECT)
            return
        self._connected()

    def _tls_handshake_serve(self):
        """Advance the TLS handshake; non-blocking with a short select
        so the broker thread keeps serving ops during slow handshakes.
        Bounded by socket.timeout.ms like every other setup stage."""
        if (time.monotonic() - self.ts_connected >
                self.rk.conf.get("socket.timeout.ms") / 1000.0):
            self._disconnect(KafkaError(Err._SSL, "TLS handshake timed out"))
            return
        try:
            self.sock.do_handshake()
        except _ssl.SSLWantReadError:
            select.select([self.sock], [], [], 0.05)
            return
        except _ssl.SSLWantWriteError:
            select.select([], [self.sock], [], 0.05)
            return
        except (OSError, _ssl.SSLError) as e:
            self._disconnect(KafkaError(Err._SSL, f"TLS handshake: {e}"))
            return
        self._tls_handshaking = False
        cert = None
        try:
            cert = self.sock.getpeercert()
        except (ValueError, OSError):
            pass
        # ssl.certificate.verify_cb: app veto over the peer certificate
        # (reference rd_kafka_conf_set_ssl_cert_verify_cb; called after
        # OpenSSL's own verification with its result — returning False
        # rejects the connection as an SSL failure)
        vcb = self.rk.conf.get("ssl.certificate.verify_cb")
        if vcb is not None:
            try:
                der = self.sock.getpeercert(binary_form=True)
            except (ValueError, OSError):
                der = None
            try:
                # openssl_ok: whether OpenSSL actually VERIFIED the
                # chain — getpeercert() returns {} (truthy-empty) for a
                # presented-but-unverified cert under CERT_NONE
                ok = vcb(self.name, self.nodeid, 0, der, bool(cert))
            except Exception as e:
                ok = False
                self.rk.log("ERROR",
                            f"{self.name}: verify_cb raised: {e!r}")
            if not ok:
                self._disconnect(KafkaError(
                    Err._SSL,
                    "broker certificate rejected by "
                    "ssl.certificate.verify_cb"))
                return
        self.rk.dbg("security",
                    f"{self.name}: TLS established "
                    f"({self.sock.version()}, peer={'verified' if cert else 'unverified'})")
        self._connected()

    def _connected(self):
        self._set_state(BrokerState.APIVERSION_QUERY)
        # ApiVersions negotiation (reference: rdkafka_request.c:1809).
        # Pre-0.10 brokers close the connection on unknown requests; the
        # reference retries the connect WITHOUT ApiVersions and applies
        # broker.version.fallback (rdkafka_feature.c legacy versions)
        if (self.rk.conf.get("api.version.request")
                and not self._apiversion_failed
                and time.monotonic() >= self._fallback_until):
            self._xmit(Request(
                ApiKey.ApiVersions, {},
                abs_timeout=time.monotonic() + self.rk.conf.get(
                    "api.version.request.timeout.ms") / 1000.0,
                cb=self._handle_apiversions))
        else:
            self._apply_version_fallback()
            self._broker_up()

    def _apply_version_fallback(self):
        fb = self.rk.conf.get("broker.version.fallback")
        self.api_versions = fallback_api_versions(fb)
        self.features = features_from_api_versions(self.api_versions)
        # one-shot: the NEXT reconnect (after api.version.fallback.ms)
        # probes ApiVersions again, so a transient blip can't pin a
        # modern broker to legacy mode forever
        if self._apiversion_failed:
            self._fallback_until = time.monotonic() + \
                self.rk.conf.get("api.version.fallback.ms") / 1000.0
        self._apiversion_failed = False
        self.rk.dbg("feature",
                    f"{self.name}: assuming broker {fb}: "
                    f"features {sorted(self.features)}")

    def _handle_apiversions(self, err, resp):
        if err is not None and err.code in (Err._TRANSPORT, Err._TIMED_OUT):
            # broker closed/ignored the request — a pre-0.10 broker.
            # Reconnect once without ApiVersions (reference behavior)
            self._apiversion_failed = True
            if err.code == Err._TIMED_OUT:
                # a timeout does not tear the connection down by itself
                self._disconnect(KafkaError(
                    Err._TRANSPORT, "ApiVersions timed out"))
            return      # the disconnect path triggers the reconnect
        if err or resp["error_code"] != 0:
            self._apply_version_fallback()
        else:
            self.api_versions = {v["api_key"]: v["max_version"]
                                 for v in resp["api_versions"]}
            self.features = features_from_api_versions(self.api_versions)
            self.rk.dbg("feature",
                        f"{self.name}: features {sorted(self.features)}")
        if self.rk.sasl_required():
            self._set_state(BrokerState.AUTH_HANDSHAKE)
            self.rk.sasl_start(self)
        else:
            self._broker_up()

    def sasl_done(self, err: Optional[KafkaError]):
        if err:
            self.rk.op_err(err)
            self._disconnect(err)
        else:
            self._broker_up()

    def _broker_up(self):
        self._set_state(BrokerState.UP)
        self.reconnect_backoff = self.rk.conf.get("reconnect.backoff.ms") / 1000.0
        # flush parked requests
        parked, self.outq = self.outq, deque()
        for req in parked:
            self._xmit(req)
        self.rk.broker_state_change(self)

    def _update_reconnect_backoff(self) -> float:
        """Schedule the next connect attempt: -25%..+50% jitter on the
        current backoff, capped at reconnect.backoff.max.ms, base
        doubled for the next round — the reference's exact scheme
        (rd_kafka_broker_update_reconnect_backoff, rdkafka_broker.c:
        1708; reconnect.backoff.jitter.ms is a deprecated no-op there
        too).  Returns the applied delay; every (when, delay) lands in
        ``reconnect_history`` so the chaos kill9 retry-shape test can
        assert the schedule was honored against a real dead process."""
        backoff_max = self.rk.conf.get("reconnect.backoff.max.ms") / 1000.0
        backoff = min(self.reconnect_backoff * random.uniform(0.75, 1.5),
                      backoff_max)
        self._next_connect = time.monotonic() + backoff
        self.reconnect_backoff = min(self.reconnect_backoff * 2,
                                     backoff_max)
        self.reconnect_history.append((time.monotonic(), backoff))
        return backoff

    def _connect_failed(self, reason: str):
        self._set_state(BrokerState.DOWN)
        self._update_reconnect_backoff()
        self.rk.broker_down(self, KafkaError(Err._TRANSPORT, reason))

    def _disconnect(self, err: KafkaError, quiet: bool = False):
        # consecutive-timeout accounting is per-connection (reference
        # resets rkb_req_timeouts in rd_kafka_broker_fail)
        self._req_timeouts_pending = 0
        if quiet:
            # log.connection.close=false: idle disconnects are expected
            # (broker idle reaper); reconnect with a debug line only
            self.rk.dbg("broker", f"{self.name}: {err.reason} (quiet)")
        elif self.sock is not None and not self.terminate:
            self.rk.log("INFO", f"{self.name}: disconnected: {err.reason}")
        if self.sock:
            # closesocket_cb: app-supplied close hook, paired with
            # connect_cb/socket_cb (reference closesocket_cb,
            # rdkafka_conf.c:520)
            ccb = self.rk.conf.get("closesocket_cb")
            try:
                if ccb:
                    ccb(self.sock)
                self.sock.close()
            except Exception as e:
                # an app close-hook that raises must not abort teardown
                # midway (socket leak + in-flight requests never failed)
                if not isinstance(e, OSError):
                    self.rk.log("ERROR",
                                f"{self.name}: closesocket_cb raised: {e!r}")
            self.sock = None
        self._rbuf.clear()
        self._wbuf.clear()
        self._unsent_req_ends.clear()
        self.fetch_inflight_cnt = 0
        # the broker's session cache entry died with the connection (or
        # will be evicted); renegotiate from epoch 0 after reconnect
        self._fetch_session.reset("disconnect")
        self._tls_handshaking = False
        # fail all in-flight + queued requests (callers decide on retry)
        for req in list(self.waitresp.values()):
            self._req_fail(req, err)
        self.waitresp.clear()
        outq, self.outq = self.outq, deque()
        for req in outq:
            self._req_fail(req, err)
        if self.state != BrokerState.DOWN and not self.terminate:
            self._connect_failed(err.reason)

    def _set_state(self, st: BrokerState):
        if self.state != st:
            self.rk.dbg("broker", f"{self.name}: {self.state.value} -> {st.value}")
            self.state = st
            self.ts_state = time.monotonic()   # stats: time in state

    # ------------------------------------------------------------ xmit/IO --
    def _next_corrid(self) -> int:
        self._corrid += 1
        return self._corrid

    def _xmit(self, req: Request):
        if self.state != BrokerState.UP and req.api not in (
                ApiKey.ApiVersions, ApiKey.SaslHandshake,
                ApiKey.SaslAuthenticate):
            self.outq.append(req)
            return
        req.corrid = self._next_corrid()
        ver = req.version
        if ver is None:
            our = APIS[req.api][0]
            ver = min(our, self.api_versions.get(int(req.api), our))
        req.version = ver          # response parses with the same schema
        wire = apis.build_request_buf(req.api, req.corrid,
                                      self.rk.conf.get("client.id"),
                                      req.body, version=ver)
        wire_len = len(wire)
        self._wbuf.append(wire.iovecs())
        self._unsent_req_ends.append(self._wbuf.queued_total)
        self.c_tx += 1
        self.c_tx_bytes += wire_len
        if req.api == ApiKey.Fetch:
            self.c_fetch_tx_bytes += wire_len
        req.ts_sent = time.monotonic()
        if req.ts_enq:
            self.outbuf_avg.add((req.ts_sent - req.ts_enq) * 1e6)
        if self.rk.interceptors:
            self.rk.interceptors.on_request_sent(
                self.nodeid, int(req.api), req.corrid, wire_len)
        if req.expect_response:
            self.waitresp[req.corrid] = req
            if not req.abs_timeout:
                req.abs_timeout = time.monotonic() + \
                    self.rk.conf.get("socket.timeout.ms") / 1000.0
        self._flush_wbuf()

    def _flush_wbuf(self):
        # scatter-gather drain: request segments (incl. spliced
        # RecordBatch bytes) go to sendmsg in place — no flat-buffer
        # copy, no consumed-prefix memmove
        if not self.sock or not self._wbuf.pending():
            return
        _n, _blocked, err = self._wbuf.send(self.sock)
        if err is not None:
            self._disconnect(KafkaError(Err._TRANSPORT,
                                        f"send failed: {err}"))
            return
        while (self._unsent_req_ends
               and self._unsent_req_ends[0] <= self._wbuf.sent_total):
            self._unsent_req_ends.popleft()

    def _io_serve(self, timeout: float = 0.005):
        """select() over socket + wakeup pipe
        (reference: rd_kafka_transport_io_serve, rdkafka_transport.c:795)."""
        rlist = [self._wakeup_r]
        wlist = []
        if self.sock:
            # decrypted TLS bytes may already be buffered in the SSL
            # layer where select() cannot see them
            if isinstance(self.sock, _ssl.SSLSocket) and self.sock.pending():
                self._recv()
                timeout = 0
            if self.sock is None:    # _recv may have disconnected
                return
            rlist.append(self.sock)
            if self._wbuf.pending():
                wlist.append(self.sock)
        if _lockdep.enabled:
            _lockdep.note_blocking("broker.select")
        try:
            r, w, _ = select.select(rlist, wlist, [], timeout)
        except (OSError, ValueError):
            return
        if self._wakeup_r in r:
            try:
                while self._wakeup_r.recv(4096):
                    pass
            except (BlockingIOError, OSError):
                pass
        if self.sock in w:
            self._flush_wbuf()
        if self.sock and self.sock in r:
            self._recv()

    def _recv(self):
        # Loop until the socket would block: a TLS record may decrypt to
        # more bytes than one recv() surfaces, and SSLSocket buffers
        # decrypted data invisible to select() (hence the pending() check
        # in _io_serve).
        got = 0
        while True:
            try:
                data = self.sock.recv(1 << 20)
            except (_ssl.SSLWantReadError, _ssl.SSLWantWriteError,
                    BlockingIOError, InterruptedError):
                break
            except OSError as e:
                self._disconnect(KafkaError(Err._TRANSPORT,
                                            f"recv failed: {e}"))
                return
            if not data:
                quiet = not self.rk.conf.get("log.connection.close")
                self._disconnect(KafkaError(
                    Err._TRANSPORT, "connection closed by peer",
                    retriable=True), quiet=quiet)
                return
            self._rbuf += data
            got += len(data)
            # SSLSocket.recv returns one decrypted record (~16KB) per
            # call, so only a would-block exception ends the loop; cap
            # the drain so a firehose peer can't starve the serve loop
            if got >= (8 << 20):
                break
        if not got:
            return
        self.c_rx_bytes += got
        # offset-based frame walk: ONE buffer compaction per recv burst
        # instead of a memmove per response
        frames, bad = sockbuf.extract_frames(
            self._rbuf, self.rk.conf.get("receive.message.max.bytes"))
        for payload in frames:
            self._handle_response(payload)
            if self.sock is None:           # handler disconnected us
                return
        if bad is not None:
            self._disconnect(KafkaError(Err._BAD_MSG,
                                        f"invalid frame size {bad}"))

    def _handle_response(self, payload: bytes):
        (corrid,) = struct.unpack(">i", payload[:4])
        req = self.waitresp.pop(corrid, None)
        if req is None:
            self.rk.dbg("broker", f"{self.name}: unknown corrid {corrid}")
            return
        self.c_rx += 1
        if req.api == ApiKey.Fetch:
            # + frame length prefix: count what crossed the wire
            self.c_fetch_rx_bytes += len(payload) + 4
        self._req_timeouts_pending = 0  # connection is alive
        if req.ts_sent:
            self.rtt_avg.add((time.monotonic() - req.ts_sent) * 1e6)
        try:
            _, body = apis.parse_response(req.api, payload,
                                          version=req.version)
        except Exception as e:
            self._req_fail(req, KafkaError(Err._BAD_MSG,
                                           f"response parse: {e!r}"))
            return
        tt = body.get("throttle_time_ms") if isinstance(body, dict) else None
        if tt:
            self.throttle_avg.add(tt)
        # throttle event on changes (reference rd_kafka_op_throttle —
        # fires when the broker starts/changes/stops throttling). Only
        # responses that CARRY a throttle field count: tt is None for
        # schemas without one (Metadata v2, ApiVersions, ...) and must
        # not read as "throttling stopped"
        if tt is not None and tt != self._last_throttle:
            self._last_throttle = tt
            # unconditional like ERR/STATS: the event-API path consumes
            # THROTTLE ops without a throttle_cb configured
            self.rk.rep.push(Op(OpType.THROTTLE,
                                payload=(self.name, self.nodeid, tt)))
        if req.cb:
            req.cb(None, body)

    def _req_fail(self, req: Request, err: KafkaError):
        # the absolute timeout budget spans retries (reference keeps one
        # deadline per request); an exhausted budget means no retry
        budget_left = (not req.abs_timeout
                       or time.monotonic() < req.abs_timeout)
        if err.retriable and req.retries_left > 0 and budget_left:
            req.retries_left -= 1
            backoff = self.rk.conf.get("retry.backoff.ms") / 1000.0
            self.retryq.append((time.monotonic() + backoff, req))
            return
        if req.cb:
            req.cb(err, None)

    def _serve_retries(self, now: float):
        if not self.retryq:
            return
        due = [r for t, r in self.retryq if t <= now]
        self.retryq = [(t, r) for t, r in self.retryq if t > now]
        for req in due:
            self._xmit(req)

    def _scan_timeouts(self, now: float):
        timed_out = [c for c, r in self.waitresp.items()
                     if r.abs_timeout and now > r.abs_timeout]
        for c in timed_out:
            req = self.waitresp.pop(c)
            self.c_req_timeouts += 1
            self._req_timeouts_pending += 1
            if _trace.enabled:
                # flight-recorder trigger: the trace explaining WHY the
                # request stalled is exactly what times out with it
                _trace.instant("broker", "request_timeout",
                               {"broker": self.name, "api": req.api.name,
                                "corrid": req.corrid})
                _trace.flight_record(f"request_timeout_{req.api.name}")
            self._req_fail(req, KafkaError(Err._TIMED_OUT,
                                           f"{req.api.name} timed out"))
        # socket.max.fails consecutive timeouts with no response in
        # between: the connection is dead — force a reconnect cycle
        # (reference: rd_kafka_broker_timeout_scan's rkb_req_timeouts
        # accounting; 0 disables)
        max_fails = self.rk.conf.get("socket.max.fails")
        if max_fails and self._req_timeouts_pending >= max_fails:
            consec = self._req_timeouts_pending
            self._disconnect(KafkaError(
                Err._TIMED_OUT,
                f"{consec} consecutive request(s) timed out: "
                f"disconnect (socket.max.fails={max_fails})"))

    # =================================================== PRODUCER SERVE ===
    def _producer_serve(self, now: float):
        """The hot loop (reference rdkafka_broker.c:3242), restructured for
        batched codec offload: gather all ready batches across toppars,
        compress them in one provider call, then send."""
        rk = self.rk
        linger = rk.conf.get("queue.buffering.max.ms") / 1000.0
        batch_max = rk.conf.get("batch.num.messages")
        codec = rk.conf.get("compression.codec")
        # pre-0.11 broker: magic 0/1 path — skip V2 writer construction
        legacy = self.features is not None and MSGVER2 not in self.features
        # codec pipeline backpressure: at most `depth` launches in
        # flight; messages keep accumulating in xmit_msgq meanwhile
        if (rk.codec_worker is not None
                and self._codec_outstanding >= rk.codec_pipeline_depth):
            return
        # queue.buffering.backpressure.threshold: with this many built-
        # but-untransmitted requests still sitting in the socket write
        # buffer, hold off forming new MessageSets — messages keep
        # accumulating into bigger batches instead (reference:
        # rd_kafka_toppar_producer_serve's outbuf backpressure,
        # rdkafka_broker.c:3262)
        if len(self._unsent_req_ends) >= rk.conf.get(
                "queue.buffering.backpressure.threshold"):
            return
        t_assembly = _trace.now() if _trace.enabled else 0
        ready: list[tuple] = []   # (toppar, msgs, writer|None-when-legacy)

        # one locked flush-flag snapshot per serve pass (the --races
        # sweep flagged the per-toppar lock-free reads against flush()'s
        # kafka.msg_cnt-guarded writes); a pass-stale value only delays
        # the linger override by one loop turn
        with rk._msg_cnt_lock:
            flush_forced = rk.flushing

        for tp in list(self.toppars):
            if tp.leader_id != self.nodeid:
                continue
            tp.xmit_move()
            # idempotence / backpressure gates
            max_inflight = (IDEMP_MAX_INFLIGHT if rk.idemp else
                            rk.conf.get("max.in.flight.requests.per.connection"))
            if rk.idemp and not rk.idemp.can_produce():
                continue
            # transactional gate: a partition's batches may only leave
            # once it is registered with the txn coordinator
            # (AddPartitionsToTxn; partition_ready queues unregistered
            # ones for the main-thread serve pass — this loop never
            # blocks on a coordinator round trip). Only toppars with
            # actual work register: an idle partition must never draw a
            # txn marker just for being led here.
            if (rk.txnmgr is not None
                    and (tp.retry_batches or tp.xmit_msgq
                         or (tp.arena is not None and len(tp.arena)))
                    and not rk.txnmgr.partition_ready(tp)):
                continue
            # frozen retry batches resend first, membership intact, and
            # block new batch formation until drained (ordering); popped
            # batches are accounted in-flight IMMEDIATELY so the DRAIN
            # rebase on the main thread never runs past messages held in
            # this serve pass's `ready` list
            if now >= tp.retry_backoff_until:
                while tp.inflight < max_inflight:
                    with tp.lock:
                        # emptiness re-checked under the lock: purge()
                        # clears retry_batches from the app thread
                        if not tp.retry_batches:
                            break
                        msgs = tp.retry_batches.popleft()
                        if not isinstance(msgs, ArenaBatch):
                            msgs = list(msgs)
                        tp.inflight_msgids.add(batch_head_msgid(msgs))
                        tp.inflight += 1
                    ready.append((tp, msgs,
                                  None if legacy else
                                  self._make_writer(tp, msgs, self._codec_for(tp, codec))))
            if tp.retry_batches or tp.inflight >= max_inflight:
                continue
            # ---- native enqueue fast lane: form an ArenaBatch ----------
            if tp.arena is not None and len(tp.arena):
                if not tp.arena_ok:
                    # records appended concurrently with a demotion:
                    # convert them so the Message path below carries them
                    rk._demote(tp, "race")
                    tp.xmit_move()
                elif not tp.xmit_msgq:
                    if now < tp.retry_backoff_until:
                        continue
                    first_us = tp.arena.first_enq_us()
                    # full by count OR by bytes: one message.max.bytes
                    # worth is a complete wire batch — lingering past it
                    # buys nothing (reference size gate in
                    # rd_kafka_toppar_producer_serve, rdkafka_broker.c:3453)
                    full = (len(tp.arena) >= batch_max
                            or tp.arena.nbytes()
                            >= rk.conf.get("message.max.bytes"))
                    lingered = (first_us >= 0
                                and now - first_us / 1e6 >= linger)
                    if not (full or lingered or flush_forced):
                        continue
                    t0 = _trace.now() if _trace.enabled else 0
                    with tp.lock:
                        run = tp.arena.take(
                            batch_max, rk.conf.get("message.max.bytes"))
                        if run is None:
                            continue
                        b = ArenaBatch(*run)
                        # batch msgid assignment: takes are FIFO and
                        # exclusive under tp.lock, so sequence numbering
                        # is identical to per-enqueue assignment
                        b.msgid_base = tp.next_msgid
                        tp.next_msgid += b.count
                        tp.inflight_msgids.add(b.msgid_base)
                        tp.inflight += 1
                    if t0:
                        # per-stage attribution: broker-thread run take
                        # (arena → ArenaBatch descriptor, under tp.lock)
                        _trace.complete("produce", "run_take", t0,
                                        {"topic": tp.topic,
                                         "partition": tp.partition,
                                         "msgs": b.count})
                    ready.append((tp, b,
                                  None if legacy else
                                  self._make_writer(tp, b, self._codec_for(tp, codec))))
                    continue
            if not tp.xmit_msgq or now < tp.retry_backoff_until:
                continue
            # linger gate (rdkafka_broker.c:3453-3470)
            try:
                oldest = tp.xmit_msgq[0]
            except IndexError:      # raced with the msg-timeout scan
                continue
            full = len(tp.xmit_msgq) >= batch_max
            lingered = (now - oldest.enq_time) >= linger
            if not (full or lingered or flush_forced):
                continue
            size_max = rk.conf.get("message.max.bytes")
            q = tp.xmit_msgq
            msgs = []
            sz = 0
            # under tp.lock: the main thread's msg-timeout scan pops
            # expired messages from this same deque
            with tp.lock:
                n_take = min(len(q), batch_max)
                for _ in range(n_take):
                    m = q[0]
                    if msgs and sz + m.size > size_max:
                        break
                    q.popleft()
                    msgs.append(m)
                    sz += m.size
                # pop + in-flight claim are ONE critical section: the
                # DRAIN rebase observes inflight and the queues under
                # this same lock, so a popped batch is never invisible
                # to both
                if msgs:
                    tp.inflight_msgids.add(msgs[0].msgid)
                    tp.inflight += 1
            if not msgs:
                continue
            ready.append((tp, msgs,
                          None if legacy else
                          self._make_writer(tp, msgs, self._codec_for(tp, codec))))

        if not ready:
            return
        if t_assembly:
            # spans only when batches actually formed: the idle serve
            # pass must not flood the ring
            _trace.complete("produce", "batch_assembly", t_assembly,
                            {"batches": len(ready)})

        # int_latency: produce() -> MessageSet write (reference rkb_avg
        # int_latency fed per message at rdkafka_msgset_writer.c; here the
        # batch's oldest+newest bound the window at 2 adds/batch instead
        # of N)
        for tp, msgs, _w in ready:
            if isinstance(msgs, ArenaBatch):
                self.rk.stats.int_latency.add((now - msgs.enq_first) * 1e6)
                if msgs.count > 1:
                    self.rk.stats.int_latency.add(
                        (now - msgs.enq_last) * 1e6)
            else:
                self.rk.stats.int_latency.add(
                    (now - msgs[0].enq_time) * 1e6)
                if len(msgs) > 1:
                    self.rk.stats.int_latency.add(
                        (now - msgs[-1].enq_time) * 1e6)
        ts_codec = time.monotonic()

        # legacy broker (no MSGVER2): magic 0/1 messagesets via the v01
        # writer, Produce <= v2 (reference MsgVersion selection,
        # rdkafka_msgset_writer.c:100 by feature set)
        if legacy:
            self._produce_legacy(ready, codec, now)
            return

        # ---- phase 2: ONE batched compress + ONE batched CRC call across
        # partitions (both ride the same provider/offload axis; reference
        # does each per batch on the broker thread,
        # rdkafka_msgset_writer.c:1129 + :1230).  Batches in `ready` are
        # already accounted in-flight; any failure from here on must
        # release the accounting and error-DR the batch or tp.inflight
        # leaks (flush() would hang, DRAIN never resolves)
        # With codec.pipeline.depth > 0 this phase runs on the client's
        # codec worker thread (SURVEY.md §5 parallelism axis 2: pipeline
        # overlap): the broker thread keeps serving socket IO and forms
        # the NEXT batch while this launch compresses; results come back
        # through the broker ops queue (FIFO — per-partition send order,
        # and with it idempotent sequence order, is preserved)
        worker = rk.codec_worker
        if worker is not None:
            self._codec_outstanding += 1
            worker.submit(self, ready, ts_codec, rk._purge_epoch)
            return
        self._codec_results(_run_codec_phase(rk, ready), ts_codec,
                            rk._purge_epoch)

    def _codec_results(self, results: list, ts_codec: float,
                       purge_epoch: int):
        """Phase 3: finalize+send (or fail) each batch from the codec
        phase. Runs on the broker thread.

        Two invalidation gates: a purge(in_flight=True) issued while the
        batch was inside the pipeline discards it with _PURGE_INFLIGHT;
        a broker no longer UP (disconnected mid-launch) requeues the
        batch as a frozen retry batch so the message-timeout scan and
        reconnect logic own it — it must NOT be parked in outq where no
        timeout scan can reach it."""
        rk = self.rk
        now = time.monotonic()
        rk.stats.codec_latency.add((now - ts_codec) * 1e6)
        purged = purge_epoch != rk._purge_epoch
        for tp, msgs, wire, exc in results:
            if purged:
                tp.release_inflight(msgs)
                rk.dr_msgq(msgs, KafkaError(Err._PURGE_INFLIGHT,
                                            "purged in flight",
                                            retriable=False), tp=tp)
            elif exc is not None:
                self._release_unsent(tp, msgs, exc)
            elif self.state != BrokerState.UP or self.terminate:
                # requeue FIRST: the DRAIN rebase scans retry_batches the
                # instant inflight drops to 0 (release_inflight docstring)
                tp.enqueue_retry_batch(msgs)
                tp.release_inflight(msgs)
            else:
                self._send_produce(tp, msgs, wire, now)

    def _release_unsent(self, tp, msgs: list[Message], exc: Exception):
        tp.release_inflight(msgs)
        self.rk.log("ERROR", f"{self.name}: batch codec failed: {exc!r}")
        self.rk.dr_msgq(msgs, KafkaError(Err._FAIL,
                                         f"batch codec failed: {exc!r}"),
                        tp=tp)

    def _codec_for(self, tp, global_codec: str) -> str:
        """Topic-scope compression.codec override; 'inherit' falls
        through to the global row (reference rdkafka_conf.c:1360)."""
        t = self.rk.topics.get(tp.topic)
        if t is not None:
            tc = t.conf.get("compression.codec")
            if tc != "inherit":
                return tc
        return global_codec

    def _make_writer(self, tp, msgs, codec: str):
        rk = self.rk
        pid, epoch = (-1, -1)
        base_seq = -1
        if rk.idemp:
            pid, epoch = rk.idemp.pid, rk.idemp.epoch
            base_seq = (batch_head_msgid(msgs) - 1
                        - tp.epoch_base_msgid) & 0x7FFFFFFF
        # transactional attr bit: every batch of a transactional
        # producer carries it (produce() is gated to IN_TXN), flowing
        # through the same writer on both CPU and TPU codec providers
        transactional = rk.txnmgr is not None
        now_ms = int(time.time() * 1000)
        if isinstance(msgs, ArenaBatch):
            # fused fast lane: defer frame+compress+CRC to ONE native
            # call in the codec phase (no intermediate records_bytes)
            # when the provider routes this codec to the CPU path.
            # Transactional batches ride it too — build_batch ORs the
            # transactional bit into the attribute word
            cid = getattr(rk.codec_provider, "fused_codec_id",
                          lambda c: None)(codec)
            if cid is not None and _fused_builder() is not None:
                return _FusedJob(cid, pid, epoch, base_seq, now_ms,
                                 ATTR_TRANSACTIONAL if transactional
                                 else 0)
        w = MsgsetWriterV2(producer_id=pid, producer_epoch=epoch,
                           base_sequence=base_seq,
                           transactional=transactional,
                           codec=None if codec == "none" else codec)
        if isinstance(msgs, ArenaBatch):
            # fast lane: ONE native call straight off the arena buffers
            t0 = _trace.now() if _trace.enabled else 0
            w.build_arena(msgs, now_ms)
            if t0:
                # per-stage attribution: arena run → framed records
                _trace.complete("produce", "native_frame", t0,
                                {"topic": tp.topic,
                                 "partition": tp.partition,
                                 "msgs": msgs.count})
        else:
            # Message duck-types Record (key/value/headers/timestamp) —
            # no per-message conversion on the hot path
            w.build(msgs, now_ms)
        return w

    def _produce_legacy(self, ready: list, codec: str, now: float):
        """Magic 0/1 path for pre-0.11 brokers: per-batch msgset build +
        compression wrapper (no batched CRC seam — MsgVer0/1 CRC is the
        per-message zlib crc32 the v01 writer computes inline)."""
        from ..protocol.msgset import write_msgset_v01
        rk = self.rk
        magic = 1 if MSGVER1 in self.features else 0
        ver = pick_version(self.api_versions, ApiKey.Produce, 2)
        provider = rk.codec_provider
        now_ms = int(time.time() * 1000)
        for tp, msgs, _writer in ready:
            if isinstance(msgs, ArenaBatch):
                # legacy brokers are off the fast path: materialize
                # Messages (rare — pre-0.11 cluster)
                msgs = msgs.to_messages(tp.topic)
            try:
                compress_fn = None
                codec_tp = self._codec_for(tp, codec)
                use_codec = None if codec_tp == "none" else codec_tp
                if use_codec:
                    lvl = rk.topic_conf_for(tp.topic).get("compression.level")
                    compress_fn = (lambda raw, c=use_codec, l=lvl:
                                   provider.compress_many(c, [raw], l)[0])
                wire = write_msgset_v01(msgs, magic=magic, codec=use_codec,
                                        now_ms=now_ms,
                                        compress_fn=compress_fn)
            except Exception as e:
                self._release_unsent(tp, msgs, e)
                continue
            self._send_produce(tp, msgs, wire, now, version=ver)

    def _send_produce(self, tp, msgs, wire: bytes, now: float,
                      version: Optional[int] = None):
        rk = self.rk
        tconf = rk.topic_conf_for(tp.topic)
        acks = tconf.get("request.required.acks")
        # NOTE: tp.inflight / inflight_msgids were accounted at batch
        # formation time in _producer_serve (DRAIN-rebase atomicity)
        if isinstance(msgs, ArenaBatch):
            msgs.possibly_persisted = True
        else:
            for m in msgs:
                m.status = MsgStatus.POSSIBLY_PERSISTED
                m.latency_us = int((now - m.enq_time) * 1e6)
        t_tx = _trace.now() if _trace.enabled else 0
        req = Request(
            ApiKey.Produce,
            {"transactional_id": (rk.conf.get("transactional.id") or None
                                  if rk.txnmgr is not None else None),
             "acks": acks,
             "timeout": tconf.get("request.timeout.ms"),
             "topics": [{"topic": tp.topic, "partitions": [
                 {"partition": tp.partition, "records": wire}]}]},
            expect_response=(acks != 0),
            version=version,
            cb=lambda err, resp, tp=tp, msgs=msgs, t_tx=t_tx:
            self._handle_produce(tp, msgs, err, resp, t_tx))
        self._xmit(req)
        if t_tx:
            # framing + write-queue submit of the ProduceRequest
            _trace.complete("produce", "produce_tx", t_tx,
                            {"topic": tp.topic,
                             "partition": tp.partition,
                             "bytes": len(wire)})
        if acks == 0:
            tp.release_inflight(msgs)
            if not isinstance(msgs, ArenaBatch):
                for m in msgs:
                    m.offset = -1
            rk.dr_msgq(msgs, None, tp=tp)

    def _handle_produce(self, tp, msgs: list[Message], err, resp,
                        t_tx_ns: int = 0):
        """Produce response → DR / retry / idempotence reconciliation
        (reference: rd_kafka_handle_Produce, rdkafka_request.c:2887,
        error path :2415).  The in-flight accounting is released only
        AFTER the requeue-or-DR decision so the main thread's DRAIN
        rebase can never observe inflight==0 while this batch is still
        unresolved."""
        if t_tx_ns and _trace.enabled:
            # tx -> ack/DR span (the wire round trip of this batch)
            _trace.complete("produce", "ack", t_tx_ns,
                            {"topic": tp.topic, "partition": tp.partition,
                             "err": (err.code.name if err is not None
                                     else None)})
        try:
            self._handle_produce0(tp, msgs, err, resp, t_tx_ns)
        finally:
            tp.release_inflight(msgs)

    def _gapless_fatal(self, tp, kerr: KafkaError) -> Optional[KafkaError]:
        """enable.gapless.guarantee: any permanently failed message in an
        idempotent stream leaves a sequence gap — escalate to a fatal
        error (reference: RD_KAFKA_RESP_ERR__GAPLESS_GUARANTEE)."""
        rk = self.rk
        if rk.idemp is None or not rk.conf.get("enable.gapless.guarantee"):
            return None
        if kerr.code in (Err._PURGE_QUEUE, Err._PURGE_INFLIGHT):
            return None          # app-initiated purge is not a gap
        fatal = KafkaError(
            Err._GAPLESS_GUARANTEE,
            f"{tp}: message failed ({kerr.code.name}) and "
            "enable.gapless.guarantee is set")
        rk.set_fatal_error(fatal)
        return fatal

    def _handle_produce0(self, tp, msgs: list[Message], err, resp,
                         t_tx_ns: int = 0):
        rk = self.rk
        ut = rk.conf.get("ut_handle_ProduceResponse")
        if ut is not None:
            # hidden unit-test hook (reference ut_handle_ProduceResponse,
            # rdkafka_conf.c:849): may override the response outcome
            override = ut(self.nodeid, batch_head_msgid(msgs), err)
            if override is not None:
                err = override
        fast = isinstance(msgs, ArenaBatch)
        if err is None:
            pres = resp["topics"][0]["partitions"][0]
            ec = Err.from_wire(pres["error_code"])
            if ec == Err.NO_ERROR:
                base = pres["base_offset"]
                if _trace.enabled and _trace.flow_sample_every and base >= 0:
                    # cross-process flow points (ISSUE 20): offsets are
                    # only known HERE, at ack time — emit the sampled
                    # produce point back-dated to the request tx stamp
                    # and the ack point at now; obs/collect.py stitches
                    # them to the consumer's fetch/deliver points by
                    # (topic, partition, offset)
                    n = msgs.count if fast else len(msgs)
                    step = _trace.flow_sample_every
                    for off in range(base + (-base) % step, base + n,
                                     step):
                        a = {"topic": tp.topic, "partition": tp.partition,
                             "offset": off}
                        _trace.evt("flow", "flow_produce", "i",
                                   t_tx_ns or None, 0, a)
                        _trace.instant("flow", "flow_ack", a)
                if not fast and (rk.interceptors or rk.conf.get("dr_msg_cb")
                                 or rk.conf.get("dr_cb")
                                 or any(m.on_delivery is not None
                                        for m in msgs)):
                    for i, m in enumerate(msgs):
                        m.offset = base + i if base >= 0 else -1
                        m.status = MsgStatus.PERSISTED
                rk.dr_msgq(msgs, None, tp=tp, base_offset=base)
                return
            kerr = KafkaError(ec)
        else:
            kerr = err

        # error path
        if rk.txnmgr is not None and kerr.code in (
                Err.PRODUCER_FENCED, Err.INVALID_PRODUCER_EPOCH,
                Err.TRANSACTION_COORDINATOR_FENCED):
            # zombie fencing: a newer instance of this transactional.id
            # bumped the epoch — fatal, never retried (resending under
            # a stale epoch is exactly what fencing exists to stop)
            fatal = rk.txnmgr.fenced(f"{tp}: produce")
            rk.dr_msgq(msgs, fatal, tp=tp)
            return
        if kerr.code in (Err.DUPLICATE_SEQUENCE_NUMBER,):
            # benign: broker already has these (idempotent dedup)
            if not fast:
                for m in msgs:
                    m.status = MsgStatus.PERSISTED
            rk.dr_msgq(msgs, None, tp=tp)
            return
        if rk.idemp and kerr.code == Err.OUT_OF_ORDER_SEQUENCE_NUMBER:
            # If an EARLIER batch of this partition failed retriably, the
            # broker rejects every in-flight successor with OUT_OF_ORDER —
            # a consequent error: requeue in msgid order and let the head
            # batch retry first.  A gap at the head of the line, however,
            # is a true sequence desynchronization: the batch is
            # POSSIBLY_PERSISTED and resending under a fresh PID would
            # bypass broker dedup, so it is FATAL (reference:
            # rd_kafka_handle_Produce_error, rdkafka_request.c:2173 r==0).
            head = batch_head_msgid(msgs)
            with tp.lock:
                pending_earlier = (
                    any(m.msgid < head for m in tp.xmit_msgq)
                    or any(batch_head_msgid(b) < head
                           for b in tp.retry_batches)
                    or any(mid < head for mid in tp.inflight_msgids))
            if pending_earlier:
                tp.enqueue_retry_batch(msgs)
                tp.retry_backoff_until = time.monotonic() + \
                    rk.conf.get("retry.backoff.ms") / 1000.0
                return
            fatal = KafkaError(
                Err.OUT_OF_ORDER_SEQUENCE_NUMBER,
                f"{tp}: sequence desynchronization: head-of-line batch "
                f"rejected with OUT_OF_ORDER_SEQUENCE_NUMBER "
                f"(possibly persisted; resend would bypass broker dedup)")
            rk.set_fatal_error(fatal)
            rk.dr_msgq(msgs, fatal, tp=tp)
            return
        retriable = kerr.retriable
        max_retries = rk.conf.get("message.send.max.retries")
        if retriable:
            if kerr.code in (Err.NOT_LEADER_FOR_PARTITION,
                             Err.LEADER_NOT_AVAILABLE,
                             Err.UNKNOWN_TOPIC_OR_PART):
                rk.metadata_refresh(reason=f"produce error {kerr.code.name}",
                                    topics=[tp.topic])
            if rk.idemp or fast:
                # keep the batch frozen: membership must survive the retry
                # for (BaseSequence, count) dup detection; budget is judged
                # on the batch head (fast-lane batches always travel
                # whole — their records share one retry budget)
                batch_retries = (msgs.retries if fast
                                 else msgs[0].retries)
                if batch_retries < max_retries:
                    if fast:
                        msgs.retries += 1
                    else:
                        for m in msgs:
                            m.retries += 1
                    tp.enqueue_retry_batch(msgs)
                    tp.retry_backoff_until = time.monotonic() + \
                        rk.conf.get("retry.backoff.ms") / 1000.0
                else:
                    rk.dr_msgq(msgs, self._gapless_fatal(tp, kerr) or kerr,
                               tp=tp)
                return
            retry = [m for m in msgs if m.retries < max_retries]
            fail = [m for m in msgs if m.retries >= max_retries]
            # (non-idempotent path continues below)
            for m in retry:
                m.retries += 1
            if retry:
                tp.insert_retry(retry)
                tp.retry_backoff_until = time.monotonic() + \
                    rk.conf.get("retry.backoff.ms") / 1000.0
            if fail:
                rk.dr_msgq(fail, self._gapless_fatal(tp, kerr) or kerr,
                           tp=tp)
        else:
            rk.dr_msgq(msgs, self._gapless_fatal(tp, kerr) or kerr, tp=tp)

    # =================================================== CONSUMER SERVE ===
    def _consumer_serve(self, now: float):
        """(reference: rd_kafka_broker_consumer_serve, rdkafka_broker.c:4489
        → rd_kafka_broker_fetch_toppars :4279)

        Fetch pipelining: up to ``fetch.num.inflight`` FetchRequests may
        be outstanding per broker, over DISJOINT partition sets (each
        toppar is in at most one outstanding Fetch) — the reference
        keeps the fetch pipe full the same way instead of serializing
        one Fetch per broker round trip."""
        rk = self.rk
        if self.fetch_inflight_cnt >= rk.conf.get("fetch.num.inflight"):
            return
        from .partition import FetchState
        fetch_parts = []
        # O(active): scan the client's active-toppar index (consumer-
        # started or produced-to), not this broker's full toppar set —
        # metadata registration alone puts every partition of every
        # known topic in self.toppars, and a 100k-toppar client must
        # not walk them per serve pass (ISSUE 14)
        for tp in rk.active_toppars():
            if tp not in self.toppars:
                continue
            # KIP-392: a delegated partition fetches from its follower;
            # everyone else fetches from the leader
            fetch_node = (tp.fetch_broker_id
                          if tp.fetch_broker_id is not None
                          else tp.leader_id)
            if fetch_node != self.nodeid or tp.paused:
                continue
            if tp.fetch_in_flight:
                continue
            if tp.fetch_state == FetchState.OFFSET_QUERY:
                self._offset_query(tp)
                continue
            if tp.fetch_state != FetchState.ACTIVE:
                continue
            if now < tp.fetch_backoff_until:
                continue
            # budget reads under the toppar lock: the app thread's
            # drain decrements them concurrently (same --races finding
            # as the kafka/consumer RMW sites)
            with tp.lock:
                fq_cnt, fq_bytes = tp.fetchq_cnt, tp.fetchq_bytes
            if fq_cnt >= rk.conf.get("queued.min.messages"):
                continue
            if fq_bytes >= rk.conf.get(
                    "queued.max.messages.kbytes") * 1024:
                continue
            if tp.fetch_offset < 0:
                continue
            fetch_parts.append(tp)
        if not fetch_parts:
            return
        fetch_ver = pick_version(self.api_versions, ApiKey.Fetch, 11)
        fs = self._fetch_session
        use_session = (fetch_ver >= 7
                       and rk.conf.get("fetch.session.enable"))
        part_max = rk.conf.get("fetch.message.max.bytes")
        body = {
            "replica_id": -1,
            "max_wait_time": rk.conf.get("fetch.wait.max.ms"),
            "min_bytes": rk.conf.get("fetch.min.bytes"),
            "max_bytes": rk.conf.get("fetch.max.bytes"),
            "isolation_level": 1 if rk.conf.get("isolation.level") ==
                               "read_committed" else 0,
            # v11+ (KIP-392): our rack lets the broker nominate a
            # same-rack follower via preferred_read_replica
            "rack_id": rk.conf.get("client.rack")}
        session_req = False
        if use_session and not fs.inflight:
            # KIP-227 session fetch: the request lists only partitions
            # whose (offset, max_bytes) CHANGED vs the session book —
            # added/seeked — plus forgotten_topics for removals; an
            # all-unchanged steady state sends an EMPTY topic list and
            # the broker long-polls the whole book.  The effective
            # partition set is all of `wanted`, so every eligible
            # partition is claimed and version-stamped, listed or not.
            wanted = {(tp.topic, tp.partition): (tp.fetch_offset, part_max)
                      for tp in fetch_parts}
            epoch, to_send, forgotten = fs.build(wanted)
            by_tp = {(tp.topic, tp.partition): tp for tp in fetch_parts}
            by_topic: dict[str, list] = {}
            for key in to_send:
                by_topic.setdefault(key[0], []).append(by_tp[key])
            fby: dict[str, list] = {}
            for t, p in forgotten:
                fby.setdefault(t, []).append(p)
            body["session_id"] = fs.session_id
            body["session_epoch"] = epoch
            body["topics"] = [
                {"topic": t, "partitions": [
                    {"partition": tp.partition,
                     "fetch_offset": tp.fetch_offset,
                     "max_bytes": part_max}
                    for tp in tps]} for t, tps in by_topic.items()]
            body["forgotten_topics"] = [
                {"topic": t, "partitions": ps} for t, ps in fby.items()]
            session_req = True
        else:
            # sessionless full fetch (schema defaults: session_id=0,
            # epoch=-1): sessions disabled, a pre-v7 broker, or a
            # session request already outstanding — newly eligible
            # partitions go out as one-shot full fetches and fold into
            # the session on a later pass (KIP-227 epochs are strictly
            # sequential; only ONE session request may be in flight)
            if use_session:
                # overflow next to an in-flight session: ONE immediate-
                # return fetch per partition per session epoch.  A
                # long-polling (or repeated) overflow turns over on the
                # same cadence as the session itself, so its partitions
                # are forever in flight at session-build time and never
                # fold into the book (observed: a 1000-partition assign
                # stuck at a 1-partition session, then a half-absorbed
                # book with the spin costing more wire than the session
                # saved).  One max_wait=0 round serves fresh data NOW;
                # after it the partition sits free until the in-flight
                # session turns over (<= fetch.wait.max.ms) and the
                # next epoch's build absorbs it deterministically.
                fetch_parts = [tp for tp in fetch_parts
                               if (tp.topic, tp.partition)
                               not in fs.overflowed]
                if not fetch_parts:
                    return
                fs.overflowed.update(
                    (tp.topic, tp.partition) for tp in fetch_parts)
                body["max_wait_time"] = 0
            by_topic = {}
            for tp in fetch_parts:
                by_topic.setdefault(tp.topic, []).append(tp)
            body["topics"] = [{"topic": t, "partitions": [
                {"partition": tp.partition,
                 "fetch_offset": tp.fetch_offset,
                 "max_bytes": part_max}
                for tp in tps]} for t, tps in by_topic.items()]
        self.fetch_inflight_cnt += 1
        for tp in fetch_parts:
            tp.fetch_in_flight = True
        versions = {(tp.topic, tp.partition): tp.version for tp in fetch_parts}
        self._xmit(Request(ApiKey.Fetch, body, version=fetch_ver,
                           cb=lambda err, resp, parts=fetch_parts,
                           sess=session_req:
                           self._handle_fetch(err, resp, versions, parts,
                                              session=sess)))

    def _offset_query(self, tp):
        """Logical offset (BEGINNING/END) → ListOffsets
        (reference: rd_kafka_toppar_offset_request)."""
        from .partition import FetchState
        ts = (proto.OFFSET_BEGINNING
              if tp.fetch_offset == proto.OFFSET_BEGINNING
              else proto.OFFSET_END)
        tp.fetch_state = FetchState.OFFSET_WAIT
        body = {"replica_id": -1,
                "topics": [{"topic": tp.topic, "partitions": [
                    {"partition": tp.partition, "timestamp": ts,
                     "max_num_offsets": 1}]}]}    # v0 field; v1 ignores
        self._xmit(Request(ApiKey.ListOffsets, body, retries_left=3,
                           version=pick_version(self.api_versions,
                                                ApiKey.ListOffsets, 1),
                           cb=lambda err, resp, tp=tp:
                           self._handle_offset(tp, err, resp)))

    def _handle_offset(self, tp, err, resp):
        from .partition import FetchState
        if err is not None:
            tp.fetch_state = FetchState.OFFSET_QUERY
            tp.fetch_backoff_until = time.monotonic() + \
                self.rk.conf.get("fetch.error.backoff.ms") / 1000.0
            return
        pres = resp["topics"][0]["partitions"][0]
        ec = Err.from_wire(pres["error_code"])
        if ec != Err.NO_ERROR:
            tp.fetch_state = FetchState.OFFSET_QUERY
            tp.fetch_backoff_until = time.monotonic() + \
                self.rk.conf.get("fetch.error.backoff.ms") / 1000.0
            return
        if "offset" in pres:
            resolved = pres["offset"]
        else:                       # ListOffsets v0: plural offsets
            offs = pres.get("offsets") or [-1]
            resolved = offs[0]
        if resolved < 0:
            # no resolvable offset: back off and re-query rather than
            # fetching at -1 (OFFSET_OUT_OF_RANGE loop)
            tp.fetch_state = FetchState.OFFSET_QUERY
            tp.fetch_backoff_until = time.monotonic() + \
                self.rk.conf.get("fetch.error.backoff.ms") / 1000.0
            return
        tp.fetch_offset = resolved
        tp.fetch_state = FetchState.ACTIVE
        self.rk.dbg("fetch", f"{tp}: offset query -> {tp.fetch_offset}")

    def _handle_fetch(self, err, resp, versions, parts, session=False):
        self.fetch_inflight_cnt = max(0, self.fetch_inflight_cnt - 1)
        # in-flight claim discipline: OK partitions stay claimed
        # continuously from request to deferred-entry processing (a
        # clear-then-reclaim window would let another broker double-
        # fetch the same offsets mid-migration); everything else —
        # errored partitions, stale versions, and ANY exception before
        # the ok-list is final — releases in _handle_fetch0's finally.
        ok_final = None
        try:
            ok_final = self._handle_fetch0(err, resp, versions, parts,
                                           session=session)
        finally:
            keep = ({id(e[0]) for e in ok_final}
                    if ok_final is not None else set())
            for tp in parts:
                if id(tp) not in keep:
                    tp.fetch_in_flight = False

    def _handle_fetch0(self, err, resp, versions, parts, session=False):
        if session:
            fs = self._fetch_session
            fs.inflight = False
            if err is not None:
                # transport error: the broker-side cache entry is gone
                # (or unreachable) — renegotiate from epoch 0
                fs.reset("transport error")
            else:
                top_ec = Err.from_wire(resp.get("error_code", 0))
                if top_ec in (Err.FETCH_SESSION_ID_NOT_FOUND,
                              Err.INVALID_FETCH_SESSION_EPOCH):
                    # the broker evicted/lost the session (cache
                    # pressure, restart) or we desynced: fall back to a
                    # full fetch — the reset makes the next request an
                    # epoch-0 full renegotiation.  The response carries
                    # no partitions; claims release via the finally.
                    self.rk.dbg("fetch",
                                f"{self.name}: fetch session "
                                f"{top_ec.name}; renegotiating")
                    fs.reset(top_ec.name)
                    return None
                fs.on_success(resp.get("session_id", 0))
        if err is not None:
            # a failed fetch to a FOLLOWER falls back to the leader
            # (reference reverts the preferred replica on errors) —
            # WITH backoff, or transport errors would ping-pong the
            # partition between brokers at error rate
            backoff = time.monotonic() + \
                self.rk.conf.get("fetch.error.backoff.ms") / 1000.0
            for tp in parts:
                if tp.fetch_broker_id is not None:
                    tp.fetch_backoff_until = backoff
                    self.rk.revoke_fetch_delegation(tp, f"fetch: {err}")
            return
        rk = self.rk
        from .partition import FetchState
        from ..protocol.msgset import iter_batches

        from ..protocol.msgset import split_msgset_segments
        # phase A: collect OK partitions; split v2 blobs into batches so
        # CRC verify and decompress each run as ONE batched provider
        # call across the whole Fetch response — the consumer-side
        # mirror of the producer's batched codec seam (reference does
        # both per batch on the broker thread,
        # rdkafka_msgset_reader.c:950-1016 CRC, :258-530 decompress)
        # every phase works from the (fetch_offset, version) snapshot
        # taken here, so a concurrent seek() cannot desync the
        # decompress decision (phase C) from the parse decision (D) —
        # the op version stamp makes post-seek deliveries discardable
        ok: list[tuple] = []      # (tp, pres, batches|None, fo, ver)
        for t in resp["topics"]:
            for p in t["partitions"]:
                tp = rk.get_toppar(t["topic"], p["partition"], create=False)
                if tp is None or tp not in self.toppars:
                    continue
                if versions.get((tp.topic, tp.partition), -1) != tp.version:
                    continue  # stale (seek/rebalance since request)
                ec = Err.from_wire(p["error_code"])
                if ec == Err.NO_ERROR:
                    # v11 KIP-392: the leader may nominate a follower;
                    # move this partition's fetching there (the
                    # redirect response itself carries no records)
                    pref = p.get("preferred_read_replica", -1)
                    if pref != -1 and pref != self.nodeid:
                        rk.delegate_fetch(tp, pref)
                    tp.hi_offset = p["high_watermark"]
                    tp.ls_offset = p.get("last_stable_offset",
                                         p["high_watermark"])
                    blob = p["records"] or b""
                    batches = None
                    if blob:
                        # ONE frame walk per partition response: its
                        # result feeds the mixed/legacy decisions here,
                        # the legacy CRC verify (phase B), and the reply
                        # handler (via pres["_segments"])
                        segs = split_msgset_segments(blob)
                        p["_segments"] = segs
                        if len(segs) == 1 and segs[0][0] == "v2":
                            batches = [
                                [info, payload,
                                 info.base_offset + info.last_offset_delta,
                                 full]
                                for info, payload, full in
                                iter_batches(blob)]
                        # mixed or legacy blobs: the reply handler
                        # splits/processes inline — precomputed batches
                        # would silently drop the legacy run
                    ok.append((tp, p, batches, tp.fetch_offset, tp.version))
                elif ec == Err.OFFSET_OUT_OF_RANGE \
                        and tp.fetch_broker_id is not None:
                    # a lagging follower, not a truncated log: retry
                    # from the leader before any offset reset
                    # (reference: rd_kafka_fetch_reply OUT_OF_RANGE on
                    # preferred replica → revert, no reset) — with
                    # backoff so a still-lagging follower can't
                    # ping-pong the partition at RTT rate
                    tp.fetch_backoff_until = time.monotonic() + \
                        rk.conf.get("fetch.error.backoff.ms") / 1000.0
                    rk.revoke_fetch_delegation(tp, "follower out of range")
                elif ec == Err.OFFSET_OUT_OF_RANGE:
                    rk.offset_reset(tp, f"fetch offset {tp.fetch_offset} out of range")
                elif ec in (Err.NOT_LEADER_FOR_PARTITION,
                            Err.UNKNOWN_TOPIC_OR_PART,
                            Err.LEADER_NOT_AVAILABLE,
                            Err.FENCED_LEADER_EPOCH):
                    if tp.fetch_broker_id is not None:
                        rk.revoke_fetch_delegation(tp, ec.name)
                    rk.metadata_refresh(reason=f"fetch error {ec.name}",
                                        topics=[tp.topic])
                    tp.fetch_backoff_until = time.monotonic() + \
                        rk.conf.get("fetch.error.backoff.ms") / 1000.0
                else:
                    if tp.fetch_broker_id is not None:
                        rk.revoke_fetch_delegation(tp, ec.name)
                    tp.fetch_backoff_until = time.monotonic() + \
                        rk.conf.get("fetch.error.backoff.ms") / 1000.0
        if not ok:
            return None
        if _trace.enabled:
            _trace.instant("fetch", "fetch_rx",
                           {"broker": self.name, "partitions": len(ok)})
        # phases B-D run PER PARTITION with decompressed-ahead flow
        # control (r5). Two measured pathologies of whole-response
        # batching: (a) a 1MB-wire partition can decompress to tens of
        # MB at high compression ratios, so the app thread saw seconds
        # of zero delivery while the broker ground through the whole
        # response; (b) materializing hundreds of MB ahead of the app
        # walks the heap through fresh pages — fault+zero+cold-write
        # measured 275 MB/s effective decode vs 5-7 GB/s when the
        # working set recycles. So a partition is processed only while
        # the total queued-undelivered volume is under the
        # queued.max.messages.kbytes budget; the rest defer to the
        # serve loop and resume as the app drains (the reference's
        # fetchq bound, applied at the decompress stage). Within a
        # partition, CRC and decompress still run as BATCHED provider
        # calls over its ~10 batches — the offload seam's launch axis.
        # entries park still-claimed (no other broker may re-fetch the
        # same offsets); _serve_deferred_fetch releases at process time
        self._fetch_deferred.extend(ok)
        self._serve_deferred_fetch()
        return ok

    def _queued_fetch_bytes(self) -> int:
        # O(active): only started/produced-to toppars can hold fetchq
        # bytes — never walk the full (metadata-registered) toppar set
        total = 0
        for tp in self.rk.active_toppars():
            if tp not in self.toppars:
                continue
            with tp.lock:
                total += tp.fetchq_bytes
        return total

    def _serve_deferred_fetch(self) -> None:
        """Process deferred fetch partitions while the app-side queue
        has room (called from _handle_fetch and each serve pass). The
        queued-bytes sum is computed once per drain and advanced by
        each resolved entry's own contribution — per-entry re-sums
        were O(partitions^2) on wide brokers; app-side drains between
        iterations only make the estimate conservative.

        Codec phases are pipelined: each admitted partition's CRC
        regions and decompress jobs are SUBMITTED as offload tickets
        (_begin_fetch_partition) and parked in the _PendingFetch FIFO
        up to tpu.fetch.pipeline.depth deep, so this thread frames and
        splits the NEXT partition (or fetch response) while the engine
        dispatch thread and the device execute; tickets resolve in
        order (_reap_fetch_pending), preserving delivery order, the
        seek-stamp discard and the CRC-mismatch semantics exactly."""
        # migrated partitions release their claims FIRST, regardless of
        # the queued-bytes budget: the new leader's fetch is blocked on
        # fetch_in_flight, and an undrained old-broker backlog must not
        # starve it (their parked data is stale — the new broker
        # re-fetches the same offsets)
        if any(e[0] not in self.toppars for e in self._fetch_deferred):
            kept: deque = deque()
            for entry in self._fetch_deferred:
                if entry[0] in self.toppars:
                    kept.append(entry)
                else:
                    entry[0].fetch_in_flight = False
            self._fetch_deferred = kept
        self._reap_fetch_pending(block=False)
        budget = self.rk.conf.get("queued.max.messages.kbytes") * 1024
        depth = max(1, int(getattr(self.rk, "fetch_pipeline_depth", 2)
                           or 1))
        queued = self._queued_fetch_bytes()
        while self._fetch_deferred:
            if queued >= budget:
                return
            if len(self._fetch_pending) >= depth:
                # pipeline full: block on the oldest entry's tickets —
                # the newer launches keep executing meanwhile (the
                # CodecWorker in-flight gate, consumer side)
                queued += self._reap_fetch_pending(block=True)
                continue
            entry = self._fetch_deferred.popleft()
            tp = entry[0]
            if tp not in self.toppars:
                tp.fetch_in_flight = False   # migrated while deferred
                continue
            try:
                self._fetch_pending.append(
                    self._begin_fetch_partition(entry))
            except Exception as e:
                tp.fetch_in_flight = False
                self.rk.log("ERROR",
                            f"{self.name}: fetch partition process: {e!r}")
                continue
            # opportunistic reap: keeps the budget accounting current,
            # and with pre-resolved tickets (CPU provider) preserves the
            # sync path's strict process-then-admit ordering
            queued += self._reap_fetch_pending(block=False)
        self._reap_fetch_pending(block=False)

    def _reap_fetch_pending(self, block: bool) -> int:
        """Resolve pending fetch partitions strictly FIFO; returns the
        delivered fetchq-bytes delta for the budget accounting.
        ``block=True`` waits for the OLDEST entry's tickets (pipeline
        full), then keeps draining whatever else already resolved."""
        delta = 0
        while self._fetch_pending and (block
                                       or self._fetch_pending[0].done()):
            block = False
            pend = self._fetch_pending.popleft()
            tp = pend.entry[0]
            with tp.lock:
                before = tp.fetchq_bytes
            # release-then-process, the sync path's ordering; migrated
            # partitions only release (their parked data is stale — the
            # new broker re-fetches the same offsets)
            tp.fetch_in_flight = False
            try:
                if tp in self.toppars:
                    self._finish_fetch_partition(pend)
            except Exception as e:
                self.rk.log("ERROR",
                            f"{self.name}: fetch partition process: {e!r}")
            if pend.t_submit_ns:
                # fetch pipeline window: ticket submit -> reap (stats
                # brokers.fetch_latency, STATISTICS.md)
                self.fetch_latency_avg.add(
                    (time.monotonic_ns() - pend.t_submit_ns) / 1e3)
            with tp.lock:
                after = tp.fetchq_bytes
            delta += max(0, after - before)
        return delta

    @staticmethod
    def _codec_submit(provider, submit_name: str, sync_fn, regions):
        """Submit a CRC batch through the provider's async seam
        (``crc32c_submit`` / ``crc32_submit``), falling back to a
        pre-resolved ticket computed synchronously right here — an
        exception is carried in the ticket and re-raises at resolve
        time, exactly where the synchronous path raised it."""
        from ..ops.engine import SyncTicket
        submit = getattr(provider, submit_name, None)
        if submit is not None:
            try:
                t = submit(regions)
            except Exception:
                t = None
            if t is not None:
                return t
        try:
            return SyncTicket(sync_fn(regions))
        except Exception as e:
            return SyncTicket(exc=e)

    @staticmethod
    def _decompress_submit(provider, codec: str, bufs: list):
        from ..ops.engine import SyncTicket
        sub = getattr(provider, "decompress_submit", None)
        if sub is not None:
            try:
                t = sub(codec, bufs)
            except Exception:
                t = None
            if t is not None:
                return t
        try:
            return SyncTicket(provider.decompress_many(codec, bufs))
        except Exception as e:
            return SyncTicket(exc=e)

    def _begin_fetch_partition(self, entry) -> _PendingFetch:
        """Phases B+C with the async seam: submit this partition's CRC
        verify regions (both polynomials) and decompress jobs as
        offload tickets and return a _PendingFetch.  Submission order —
        CRC first, then the host decompress job — matches the engine's
        dispatch order, so the device executes the CRC launch while the
        dispatch thread inflates the payloads.  Providers without an
        async seam resolve through pre-resolved SyncTickets: same code
        path, synchronous schedule, identical bytes."""
        rk = self.rk
        provider = rk.codec_provider
        from ..protocol.msgset import iter_legacy_crc_regions
        tp, pres, batches, fo, ver = entry
        pend = _PendingFetch(entry)
        pend.t_submit_ns = time.monotonic_ns()
        # phase B: batched CRC verify for this partition
        if rk.conf.get("check.crcs"):
            if batches:
                regions = [b[3][proto.V2_OF_Attributes:]
                           for b in batches if b[2] >= fo]
                if regions:
                    pend.crc_infos = [b[0] for b in batches
                                      if b[2] >= fo]
                    pend.crc_ticket = self._codec_submit(
                        provider, "crc32c_submit", provider.crc32c_many,
                        regions)
            else:
                # legacy MsgVer0/1 blobs: per-message zlib CRC,
                # same batched provider seam (MXU GF(2) kernel on
                # the tpu backend; reference verifies inline,
                # rdkafka_msgset_reader.c v0/v1). The phase-A
                # segment split keeps v2 batches out of this walk.
                lregions, lowners = [], []
                for kind, seg in pres.get("_segments") or []:
                    if kind != "legacy":
                        continue
                    for off, crc, region in iter_legacy_crc_regions(seg):
                        lregions.append(region)
                        lowners.append((off, crc))
                if lregions:
                    pend.legacy_owners = lowners
                    pend.legacy_ticket = self._codec_submit(
                        provider, "crc32_submit", provider.crc32_many,
                        lregions)
        # phase C: batched decompress, submitted eagerly (not gated on
        # the CRC results): a mismatch is the rare path and its
        # decompressed bytes are simply discarded at resolve time —
        # wire-visible behavior is identical to verify-then-decompress
        if batches:
            by_codec: dict[str, list] = {}
            for b in batches:
                info, _payload, last, _full = b
                if last >= fo and info.codec:
                    by_codec.setdefault(info.codec, []).append(b)
            pend.dec_tickets = [
                (codec, items, self._decompress_submit(
                    provider, codec, [b[1] for b in items]))
                for codec, items in by_codec.items()]
        return pend

    def _finish_fetch_partition(self, pend: _PendingFetch) -> None:
        """Resolve a partition's codec tickets and run phase D, with
        the synchronous path's exact observable semantics: a CRC
        mismatch emits Err._BAD_MSG + 0.5s fetch backoff and drops the
        partition's batches; a failing decompress isolates per batch
        (payload=None) so a corrupt batch inside an aborted transaction
        does not suppress the partition's valid committed data; the
        delivery is stamped with the (fetch_offset, version) snapshot
        so post-seek resolutions get discarded."""
        rk = self.rk
        tp, pres, batches, fo, ver = pend.entry
        if pend.crc_ticket is not None:
            crcs = pend.crc_ticket.result(60.0)
            if _trace.enabled:
                # submit -> resolve: the verify's share of the pipeline
                _trace.complete("fetch", "crc_verify", pend.t_submit_ns,
                                {"topic": tp.topic,
                                 "partition": tp.partition,
                                 "batches": len(pend.crc_infos)})
            for info, crc in zip(pend.crc_infos, crcs):
                if int(crc) != info.crc:
                    if _trace.enabled:
                        _trace.instant("fetch", "crc_mismatch",
                                       {"topic": tp.topic,
                                        "partition": tp.partition,
                                        "offset": info.base_offset})
                        _trace.flight_record("crc_mismatch")
                    rk.op_err(KafkaError(
                        Err._BAD_MSG,
                        f"{tp}: CRC mismatch at offset "
                        f"{info.base_offset}"))
                    tp.fetch_backoff_until = time.monotonic() + 0.5
                    return
        if pend.legacy_ticket is not None:
            crcs = pend.legacy_ticket.result(60.0)
            if _trace.enabled:
                _trace.complete("fetch", "crc_verify", pend.t_submit_ns,
                                {"topic": tp.topic,
                                 "partition": tp.partition,
                                 "legacy": True,
                                 "batches": len(pend.legacy_owners)})
            for (off, want), got in zip(pend.legacy_owners, crcs):
                if int(got) != want:
                    if _trace.enabled:
                        _trace.instant("fetch", "crc_mismatch",
                                       {"topic": tp.topic,
                                        "partition": tp.partition,
                                        "offset": off, "legacy": True})
                        _trace.flight_record("crc_mismatch")
                    rk.op_err(KafkaError(
                        Err._BAD_MSG,
                        f"{tp}: legacy message CRC mismatch "
                        f"at offset {off}"))
                    tp.fetch_backoff_until = time.monotonic() + 0.5
                    return
        t_dec = _trace.now() if _trace.enabled else 0
        for codec, items, ticket in pend.dec_tickets:
            blobs = None
            try:
                blobs = ticket.result(60.0)
            except Exception:
                pass   # isolate the failing batch below
            for i, b in enumerate(items):
                if blobs is not None:
                    b[1] = blobs[i]
                    continue
                try:
                    b[1] = rk.codec_provider.decompress_many(
                        codec, [b[1]])[0]
                except Exception:
                    b[1] = None
        if t_dec and pend.dec_tickets:
            _trace.complete("fetch", "decompress", t_dec,
                            {"topic": tp.topic, "partition": tp.partition,
                             "codecs": [c for c, _i, _t in
                                        pend.dec_tickets]})
        # phase D: record parsing + delivery op for this partition
        t_del = _trace.now() if _trace.enabled else 0
        rk.fetch_reply_handle(
            tp, pres, self,
            batches=None if batches is None else
            [(info, payload, last)
             for info, payload, last, _full in batches],
            fo=fo, ver=ver)
        if t_del:
            _trace.complete("fetch", "deliver", t_del,
                            {"topic": tp.topic,
                             "partition": tp.partition})
