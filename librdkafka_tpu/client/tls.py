"""TLS transport support (reference: src/rdkafka_ssl.c, src/rdkafka_cert.c).

The reference builds one OpenSSL ``SSL_CTX`` per client instance at
``rd_kafka_ssl_ctx_init`` (rdkafka_ssl.c:~1100) from the ``ssl.*``
configuration properties, loading CA bundles, client cert/key pairs and
PKCS#12 keystores (rdkafka_cert.c:~200), then drives the per-connection
handshake from the transport poll loop (rdkafka_transport.c:612-719).

This module is the TPU-rebuild equivalent: ``make_client_ctx(conf)``
constructs a single :class:`ssl.SSLContext` per client from the same
property names; the broker thread drives the non-blocking handshake in
its connection FSM (client/broker.py, state CONNECT).
"""
from __future__ import annotations

import os
import ssl
import tempfile
from typing import Optional

from .errors import Err, KafkaError, KafkaException


def uses_ssl(conf) -> bool:
    return conf.get("security.protocol") in ("ssl", "sasl_ssl")


def make_client_ctx(conf) -> Optional[ssl.SSLContext]:
    """Build the client SSLContext from ``ssl.*`` conf properties.

    Maps the reference's property semantics (rdkafka_conf.c ssl section):
      - ssl.ca.location: CA bundle file or directory; default = system CAs
      - ssl.certificate.location / ssl.key.location / ssl.key.password:
        client cert+key PEM pair
      - ssl.keystore.location / ssl.keystore.password: PKCS#12 keystore
        holding the client key+cert (rdkafka_cert.c PKCS12 path)
      - ssl.cipher.suites: OpenSSL cipher list
      - enable.ssl.certificate.verification: peer verification on/off
      - ssl.endpoint.identification.algorithm: "https" = hostname check
    """
    if not uses_ssl(conf):
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)

    verify = conf.get("enable.ssl.certificate.verification")
    algo = conf.get("ssl.endpoint.identification.algorithm")
    # check_hostname must be disabled before verify_mode can be relaxed
    ctx.check_hostname = bool(verify) and algo == "https"
    ctx.verify_mode = ssl.CERT_REQUIRED if verify else ssl.CERT_NONE

    ca = conf.get("ssl.ca.location")
    ca_mem = conf.get("ssl_ca")               # in-memory PEM/DER bytes
    if ca:
        try:
            if os.path.isdir(ca):
                ctx.load_verify_locations(capath=ca)
            else:
                ctx.load_verify_locations(cafile=ca)
        except (ssl.SSLError, OSError) as e:
            raise KafkaException(Err._SSL, f"ssl.ca.location {ca!r}: {e}")
    elif ca_mem:
        try:
            # load_verify_locations(cadata=...) takes PEM str or DER bytes
            if isinstance(ca_mem, bytes) and b"-----BEGIN" in ca_mem:
                ca_mem = ca_mem.decode()
            ctx.load_verify_locations(cadata=ca_mem)
        except (ssl.SSLError, ValueError) as e:
            raise KafkaException(Err._SSL, f"ssl_ca: {e}")
    elif verify:
        ctx.load_default_certs(ssl.Purpose.SERVER_AUTH)

    crl = conf.get("ssl.crl.location")
    if crl:
        if not verify:
            # OpenSSL ignores verify_flags entirely under CERT_NONE —
            # a CRL that can never be consulted must not pass silently
            raise KafkaException(
                Err._INVALID_ARG,
                "ssl.crl.location requires "
                "enable.ssl.certificate.verification=true (revocation "
                "checking is part of verification)")
        try:
            ctx.verify_flags |= ssl.VERIFY_CRL_CHECK_LEAF
            ctx.load_verify_locations(cafile=crl)
        except (ssl.SSLError, OSError) as e:
            raise KafkaException(Err._SSL, f"ssl.crl.location {crl!r}: {e}")

    _load_client_cert(ctx, conf)

    ks = conf.get("ssl.keystore.location")
    if ks:
        _load_pkcs12(ctx, ks, conf.get("ssl.keystore.password"))

    ciphers = conf.get("ssl.cipher.suites")
    if ciphers:
        try:
            ctx.set_ciphers(ciphers)
        except ssl.SSLError as e:
            raise KafkaException(Err._SSL, f"ssl.cipher.suites: {e}")
    curves = conf.get("ssl.curves.list")
    if curves:
        _ctx_ctrl_str(ctx, _SSL_CTRL_SET_GROUPS_LIST, curves,
                      "ssl.curves.list")
    sigalgs = conf.get("ssl.sigalgs.list")
    if sigalgs:
        _ctx_ctrl_str(ctx, _SSL_CTRL_SET_SIGALGS_LIST, sigalgs,
                      "ssl.sigalgs.list")
    return ctx


def _load_client_cert(ctx: ssl.SSLContext, conf) -> None:
    """Client cert+key from file paths, in-memory PEM strings
    (ssl.certificate.pem / ssl.key.pem), or in-memory bytes
    (ssl_certificate / ssl_key — the rd_kafka_conf_set_ssl_cert analog,
    reference rdkafka_cert.c:1-556). Python's ssl module only ingests
    cert chains from files, so in-memory material goes through a
    transient file deleted right after the load (same pattern as the
    PKCS#12 path)."""
    cert = conf.get("ssl.certificate.location")
    key = conf.get("ssl.key.location")
    pw = conf.get("ssl.key.password") or None
    cert_mem = conf.get("ssl.certificate.pem") or conf.get("ssl_certificate")
    key_mem = conf.get("ssl.key.pem") or conf.get("ssl_key")
    if cert and not key_mem:
        try:
            ctx.load_cert_chain(cert, keyfile=key or None, password=pw)
        except (ssl.SSLError, OSError) as e:
            raise KafkaException(Err._SSL, f"client certificate: {e}")
        return
    if cert and key_mem and not cert_mem:
        # cert from file + key in memory (the reference allows any
        # mix of rd_kafka_conf_set_ssl_cert and file rows): read the
        # file so both halves go through the transient-PEM load below
        try:
            with open(cert, "rb") as f:
                cert_mem = f.read()
        except OSError as e:
            raise KafkaException(Err._SSL, f"client certificate: {e}")
    if not cert_mem:
        if key_mem:
            # key without a certificate is as much a config error as the
            # mirror case below — failing here beats an opaque
            # handshake rejection at connect time
            raise KafkaException(
                Err._INVALID_ARG,
                "ssl.key.pem / ssl_key requires ssl.certificate.pem / "
                "ssl_certificate (or ssl.certificate.location)")
        return
    if not key_mem and not key:
        raise KafkaException(
            Err._INVALID_ARG,
            "in-memory client certificate requires ssl.key.pem / "
            "ssl_key (or ssl.key.location)")
    blob = b""
    for part in (cert_mem, key_mem):
        if part is None:
            continue
        if isinstance(part, str):
            part = part.encode()
        if b"-----BEGIN" not in part:
            raise KafkaException(
                Err._INVALID_ARG,
                "in-memory certificate/key must be PEM (DER client "
                "material: use ssl.keystore.location)")
        blob += part if part.endswith(b"\n") else part + b"\n"
    fd, tmp = tempfile.mkstemp(suffix=".pem")
    try:
        os.write(fd, blob)
        os.close(fd)
        try:
            ctx.load_cert_chain(tmp, keyfile=key or None, password=pw)
        except (ssl.SSLError, OSError) as e:
            raise KafkaException(Err._SSL,
                                 f"in-memory client certificate: {e}")
    finally:
        os.unlink(tmp)


# OpenSSL SSL_CTX_ctrl sub-commands (public ABI constants; the Python
# ssl module has no API for groups/sigalgs, so these reach the already-
# loaded libssl through the process symbol table)
_SSL_CTRL_SET_GROUPS_LIST = 92
_SSL_CTRL_SET_SIGALGS_LIST = 98

_libssl_handle = None


def _libssl(ctypes):
    """Handle to the libssl the interpreter's _ssl module already
    mapped (CDLL(None) can't see it: _ssl loads it RTLD_LOCAL)."""
    global _libssl_handle
    if _libssl_handle is None:
        path = None
        try:
            with open("/proc/self/maps") as f:
                for line in f:
                    if "libssl" in line:
                        path = line.split()[-1]
                        break
        except OSError:
            pass
        _libssl_handle = ctypes.CDLL(path)   # None falls back to process
    return _libssl_handle


def _ctx_ctrl_str(ctx: ssl.SSLContext, cmd: int, value: str,
                  propname: str) -> None:
    """Apply an SSL_CTX_ctrl string option (curves/sigalgs lists) to the
    context's underlying SSL_CTX. CPython's _ssl.PySSLContext stores the
    SSL_CTX* directly after PyObject_HEAD; a bad list makes
    SSL_CTX_ctrl return 0 and raises, so misconfiguration cannot pass
    silently. If the runtime layout/symbols are unavailable the
    property fails loudly rather than being ignored."""
    import ctypes

    class _PySSLContext(ctypes.Structure):
        _fields_ = [("ob_refcnt", ctypes.c_ssize_t),
                    ("ob_type", ctypes.c_void_p),
                    ("ctx", ctypes.c_void_p)]

    import sys
    import sysconfig
    if (sys.implementation.name != "cpython"
            or sysconfig.get_config_var("Py_GIL_DISABLED")
            or sysconfig.get_config_var("Py_TRACE_REFS")):
        # the struct layout below is standard-CPython-specific; on other
        # builds the pointer extraction would be garbage — refuse
        # loudly instead of dereferencing it
        raise KafkaException(
            Err._NOT_IMPLEMENTED,
            f"{propname}: unsupported on this Python build "
            f"({sys.implementation.name}, free-threaded/debug)")
    try:
        libssl = _libssl(ctypes)
        fn = libssl.SSL_CTX_ctrl
        fn.restype = ctypes.c_long
        fn.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_long,
                       ctypes.c_char_p]
        raw = _PySSLContext.from_address(id(ctx)).ctx
        # layout sanity probe before the real call: SSL_CTX_get_timeout
        # on a correctly-extracted context returns the default session
        # timeout (7200s) — garbage pointers fail this cheaply instead
        # of crashing inside SSL_CTX_ctrl
        get_timeout = libssl.SSL_CTX_get_timeout
        get_timeout.restype = ctypes.c_long
        get_timeout.argtypes = [ctypes.c_void_p]
        if not raw or not (0 < get_timeout(raw) < (1 << 31)):
            raise KafkaException(
                Err._NOT_IMPLEMENTED,
                f"{propname}: SSL_CTX layout probe failed on this "
                f"runtime")
        ok = fn(raw, cmd, 0, value.encode())
    except (OSError, AttributeError) as e:
        raise KafkaException(
            Err._NOT_IMPLEMENTED,
            f"{propname}: cannot reach SSL_CTX_ctrl in this runtime "
            f"({e})")
    if ok != 1:
        raise KafkaException(Err._INVALID_ARG,
                             f"{propname}: OpenSSL rejected {value!r}")


def _load_pkcs12(ctx: ssl.SSLContext, path: str, password: str) -> None:
    """PKCS#12 keystore → client cert chain (rdkafka_cert.c PKCS12 load).

    Python's ssl module cannot ingest PKCS#12 directly; decode with
    `cryptography` and hand the PEM material to the context through a
    transient file (deleted immediately after load).
    """
    try:
        from cryptography.hazmat.primitives.serialization import (
            Encoding, NoEncryption, PrivateFormat, pkcs12)
    except ImportError:
        raise KafkaException(Err._SSL,
                         "ssl.keystore.location requires the 'cryptography' "
                         "package for PKCS#12 decoding")
    try:
        blob = open(path, "rb").read()
        pw = password.encode() if password else None
        pkey, pcert, extra = pkcs12.load_key_and_certificates(blob, pw)
    except Exception as e:
        raise KafkaException(Err._SSL, f"ssl.keystore.location {path!r}: {e}")
    pem = b""
    if pkey is not None:
        pem += pkey.private_bytes(Encoding.PEM, PrivateFormat.PKCS8,
                                  NoEncryption())
    if pcert is not None:
        pem += pcert.public_bytes(Encoding.PEM)
    for c in extra or []:
        pem += c.public_bytes(Encoding.PEM)
    fd, tmp = tempfile.mkstemp(suffix=".pem")
    try:
        os.write(fd, pem)
        os.close(fd)
        ctx.load_cert_chain(tmp)
    finally:
        os.unlink(tmp)


def make_server_ctx(certfile: str, keyfile: str, cafile: str = None,
                    require_client_cert: bool = False) -> ssl.SSLContext:
    """Server-side context for the mock cluster's TLS listener mode."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile, keyfile)
    if cafile:
        ctx.load_verify_locations(cafile)
    if require_client_cert:
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx
