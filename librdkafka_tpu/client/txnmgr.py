"""Transactional producer (EOS) state machine.

The subsystem librdkafka v1.3.0 stops just short of (its txn manager
lands in 1.4, src/rdkafka_txnmgr.c): a coordinator-backed transaction
FSM layered over the idempotent producer —

    UNINIT ──init_transactions()──> READY
    READY ──begin_transaction()──> IN_TXN
    IN_TXN ──commit_transaction()──> COMMITTING ──> READY
    IN_TXN ──abort_transaction()──> ABORTING ──> READY
    (any) ──abortable error──> ABORTABLE_ERROR ──abort_transaction()──> READY
    (any) ──fenced / fatal──> FATAL

init_transactions() finds the transaction coordinator
(FindCoordinator key_type=1) and acquires a (pid, epoch) bound to
``transactional.id`` via InitProducerId — re-initialization of the same
id bumps the epoch, fencing zombie instances (their next request fails
fatally with PRODUCER_FENCED).  During a transaction every partition
touched by a produced batch is registered with the coordinator
(AddPartitionsToTxn) before its ProduceRequests may leave — the broker
serve loop gates on partition_ready(), and the main-thread serve() pass
flushes the pending-partition set, mirroring the reference's
rd_kafka_txn_register_toppar flow.  commit/abort resolve through
EndTxn, which makes the coordinator write COMMIT/ABORT control records
into every registered partition log.

Error taxonomy (the reference's three txn error classes):
retriable (coordinator moved/loading, timeouts) are retried internally
until the API timeout; abortable (a failed produce inside the txn)
park the FSM in ABORTABLE_ERROR until abort_transaction(); fatal
(fencing, authorization) poison the producer permanently.
"""
from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Optional, TYPE_CHECKING

from ..protocol.proto import ApiKey
from ..analysis.locks import new_cond, new_rlock
from ..analysis.races import shared
from .broker import Request
from .errors import Err, KafkaError, KafkaException
from .queue import Op, OpType

if TYPE_CHECKING:
    from .kafka import Kafka

#: Errors a transactional request may be retried on (coordinator
#: election/loading, an ongoing txn still completing, plain transport).
RETRIABLE = frozenset({
    Err._TRANSPORT, Err._TIMED_OUT, Err.REQUEST_TIMED_OUT,
    Err.COORDINATOR_NOT_AVAILABLE, Err.NOT_COORDINATOR,
    Err.COORDINATOR_LOAD_IN_PROGRESS, Err.CONCURRENT_TRANSACTIONS,
    Err.UNKNOWN_TOPIC_OR_PART,
})

#: Errors that permanently poison this producer instance (reference:
#: rd_kafka_txn_set_fatal_error callers).
FATAL = frozenset({
    Err.PRODUCER_FENCED, Err.INVALID_PRODUCER_EPOCH,
    Err.TRANSACTION_COORDINATOR_FENCED,
    Err.TRANSACTIONAL_ID_AUTHORIZATION_FAILED,
    Err.INVALID_TRANSACTION_TIMEOUT, Err.INVALID_PRODUCER_ID_MAPPING,
    Err.UNSUPPORTED_VERSION,
})


class TransactionManager:
    """Owns the txn FSM for one transactional producer instance."""

    # relaxed lockset declarations (analysis/races.py): every FSM
    # transition and registration mutation happens under the txn.mgr
    # RLock, but the produce gate (kafka.produce: ``state != IN_TXN``)
    # and the stats emitter read lock-free — str/int/len snapshots,
    # atomic under the GIL, and the gate is re-validated by the broker
    # protocol (PRODUCER_FENCED / INVALID_TXN_STATE) if it races a
    # transition.  Tracked so a second writer thread would surface.
    state = shared("txn.state", relaxed=True)
    pid = shared("txn.pid", relaxed=True)
    epoch = shared("txn.epoch", relaxed=True)
    coord_id = shared("txn.coord_id", relaxed=True)
    _registered = shared("txn.registered", relaxed=True)
    _pending = shared("txn.pending", relaxed=True)

    def __init__(self, rk: "Kafka"):
        self.rk = rk
        self.transactional_id: str = rk.conf.get("transactional.id")
        self.state = "UNINIT"
        self.pid = -1
        self.epoch = -1
        self.coord_id: Optional[int] = None
        self._lock = new_rlock("txn.mgr")
        # notified on AddPartitionsToTxn completion and fatal errors;
        # retriable backoffs ride timed waits on it (no sleep-polling
        # in client/ — test_0120 — and close()/fatal can wake them)
        self._cv = new_cond("txn.mgr", self._lock)
        # partitions of the CURRENT transaction
        self._registered: set[tuple[str, int]] = set()
        self._pending: set[tuple[str, int]] = set()
        self._register_inflight = False
        self._abortable_reason: Optional[KafkaError] = None
        # offsets staged via send_offsets_to_transaction (group ids,
        # for the empty-txn EndTxn skip decision)
        self._sent_offsets = False

    # ------------------------------------------------------- state helpers --
    def _set_state(self, state: str) -> None:
        """FSM transition (callers hold self._lock). Keeps the native
        produce fast lane's enable flag in sync: it is only open while
        produce() is legal (IN_TXN) because the C entry point cannot
        check the state gate per call."""
        self.state = state
        self.rk._txn_lane_sync()

    def _require(self, *states: str):
        if self.rk.fatal_error is not None:
            raise KafkaException(self.rk.fatal_error)
        if self.state not in states:
            raise KafkaException(
                Err._STATE,
                f"operation not valid in transaction state {self.state} "
                f"(expected {'/'.join(states)})")

    def _fatal(self, code: Err, reason: str) -> KafkaError:
        err = KafkaError(code, reason, retriable=False)
        with self._lock:
            self._set_state("FATAL")
            self._cv.notify_all()
        self.rk.set_fatal_error(err)
        # fail everything still queued NOW (reference: a fatal error
        # purges the producer queues) so flush()/commit callers blocked
        # on outstanding messages unwedge immediately
        try:
            self.rk.purge(in_queue=True, in_flight=False)
        except Exception:
            pass
        return err

    def fenced(self, where: str) -> KafkaError:
        """A broker told us a newer instance of this transactional.id
        exists: this producer is a zombie (reference: PRODUCER_FENCED
        is always fatal)."""
        return self._fatal(
            Err.PRODUCER_FENCED,
            f"{where}: producer fenced by a newer instance of "
            f"transactional.id {self.transactional_id!r} "
            f"(pid {self.pid} epoch {self.epoch})")

    def msg_failed(self, err: KafkaError) -> None:
        """A message in the current transaction failed delivery: the
        transaction may no longer be committed — only aborted
        (reference: rd_kafka_txn_set_abortable_error)."""
        with self._lock:
            if self.state in ("IN_TXN", "COMMITTING") and err.code not in (
                    Err._PURGE_QUEUE, Err._PURGE_INFLIGHT):
                self._abortable_reason = err
                if self.state == "IN_TXN":
                    self._set_state("ABORTABLE_ERROR")

    # ---------------------------------------------------------- transport --
    def _backoff(self, deadline: float, max_wait: float = 0.05) -> None:
        """Timed retry backoff on the manager condvar (wakeable by a
        fatal error / AddPartitionsToTxn completion, never a bare
        sleep-poll)."""
        remain = min(max_wait, deadline - time.monotonic())
        if remain <= 0:
            return
        with self._cv:
            self._cv.wait(remain)

    def _wait_any_broker(self, deadline: float):
        b = self.rk.any_up_broker()
        if b is not None:
            return b
        # wakes on every metadata cache update — which broker-up
        # transitions trigger (kafka.broker_state_change)
        self.rk.metadata_wait(
            lambda: self.rk.any_up_broker() is not None,
            max(0.0, deadline - time.monotonic()))
        b = self.rk.any_up_broker()
        if b is None:
            raise KafkaException(Err._TIMED_OUT,
                                 "no broker became available")
        return b

    def _coord_broker(self, deadline: float, *, key: str, key_type: int):
        """Resolve + return the coordinator broker, demanding a
        connection under sparse connections. Blocks (app thread)."""
        while True:
            remain = deadline - time.monotonic()
            if remain <= 0:
                raise KafkaException(Err._TIMED_OUT,
                                     "coordinator lookup timed out")
            b = self._wait_any_broker(deadline)
            err, resp = self._sync_request(
                b, ApiKey.FindCoordinator,
                {"key": key, "key_type": key_type}, deadline)
            if err is None and resp["error_code"] == 0:
                coord_id = resp["node_id"]
                with self.rk._brokers_lock:
                    cb = self.rk.brokers.get(coord_id)
                if cb is None:
                    self.rk.metadata_refresh("txn coordinator unknown")
                    self._backoff(deadline)
                    continue
                if key_type == 1:
                    self.coord_id = coord_id
                cb.schedule_connect()
                return cb
            code = (err.code if err is not None
                    else Err.from_wire(resp["error_code"]))
            if code in FATAL:
                raise KafkaException(self._fatal(
                    code, f"FindCoordinator({key!r}): {code.name}"))
            self._backoff(deadline)

    @staticmethod
    def _sync_request(broker, api: ApiKey, body: dict, deadline: float):
        """enqueue_request + block for the response (app thread).
        Returns (err, resp) like a Request callback receives them."""
        q: _queue.Queue = _queue.Queue(1)
        broker.enqueue_request(Request(
            api, body, retries_left=3, abs_timeout=deadline,
            cb=lambda e, r: q.put((e, r))))
        try:
            return q.get(timeout=max(0.0, deadline - time.monotonic()) + 1.0)
        except _queue.Empty:
            return KafkaError(Err._TIMED_OUT,
                              f"{api.name} timed out"), None

    def _txn_request(self, api: ApiKey, body: dict, deadline: float,
                     what: str) -> dict:
        """Issue a coordinator request, retrying retriable errors and
        re-resolving the coordinator, until the deadline. Raises on
        fatal/abortable errors; returns the response body."""
        while True:
            if time.monotonic() >= deadline:
                raise KafkaException(
                    KafkaError(Err._TIMED_OUT, f"{what} timed out",
                               retriable=True))
            b = self._coord_broker(deadline, key=self.transactional_id,
                                   key_type=1)
            err, resp = self._sync_request(b, api, body, deadline)
            if err is None:
                code = Err.from_wire(resp.get("error_code", 0))
                if code == Err.NO_ERROR:
                    return resp
            else:
                code = err.code
            if code in (Err.PRODUCER_FENCED, Err.INVALID_PRODUCER_EPOCH,
                        Err.TRANSACTION_COORDINATOR_FENCED):
                raise KafkaException(self.fenced(what))
            if code in FATAL:
                raise KafkaException(self._fatal(
                    code, f"{what}: {code.name}"))
            if code in RETRIABLE:
                self.coord_id = None      # NOT_COORDINATOR: re-resolve
                self._backoff(deadline)
                continue
            # anything else: the transaction can only be aborted
            kerr = KafkaError(code, f"{what}: {code.name}",
                              retriable=False)
            with self._lock:
                self._abortable_reason = kerr
                if self.state in ("IN_TXN", "COMMITTING"):
                    self._set_state("ABORTABLE_ERROR")
            raise KafkaException(kerr)

    # ----------------------------------------------------------- public API --
    def _deadline(self, timeout: float) -> float:
        if timeout is None or timeout < 0:
            timeout = self.rk.conf.get("transaction.timeout.ms") / 1000.0
        return time.monotonic() + timeout

    def init_transactions(self, timeout: float = -1) -> None:
        """FindCoordinator(txn) + InitProducerId(transactional.id):
        acquire the fencing (pid, epoch) (reference:
        rd_kafka_init_transactions)."""
        self._require("UNINIT", "READY")
        deadline = self._deadline(timeout)
        resp = self._txn_request(
            ApiKey.InitProducerId,
            {"transactional_id": self.transactional_id,
             "transaction_timeout_ms":
                 self.rk.conf.get("transaction.timeout.ms")},
            deadline, "init_transactions")
        with self._lock:
            self.pid = resp["producer_id"]
            self.epoch = resp["producer_epoch"]
            self._set_state("READY")
        # hand the identity to the idempotence layer: the writer stamps
        # every batch from rk.idemp (one source of truth for pid/epoch)
        idemp = self.rk.idemp
        with idemp._lock:
            idemp.pid = self.pid
            idemp.epoch = self.epoch
            idemp.state = "ASSIGNED"
        self.rk.dbg("eos", f"transactional pid {self.pid} "
                           f"epoch {self.epoch} "
                           f"({self.transactional_id!r})")

    def begin_transaction(self) -> None:
        self._require("READY")
        with self._lock:
            self._registered.clear()
            self._pending.clear()
            self._abortable_reason = None
            self._sent_offsets = False
            self._set_state("IN_TXN")
        self.rk.dbg("eos", "transaction begun")

    def send_offsets_to_transaction(self, offsets, group_metadata,
                                    timeout: float = -1) -> None:
        """Commit consumed offsets as part of this transaction
        (reference: rd_kafka_send_offsets_to_transaction —
        AddOffsetsToTxn to the txn coordinator, then TxnOffsetCommit to
        the group coordinator)."""
        self._require("IN_TXN")
        group_id = getattr(group_metadata, "group_id", group_metadata)
        if not isinstance(group_id, str) or not group_id:
            raise KafkaException(Err._INVALID_ARG,
                                 "group metadata must carry a group id")
        deadline = self._deadline(timeout)
        self._txn_request(
            ApiKey.AddOffsetsToTxn,
            {"transactional_id": self.transactional_id,
             "producer_id": self.pid, "producer_epoch": self.epoch,
             "group_id": group_id},
            deadline, "send_offsets_to_transaction(AddOffsetsToTxn)")
        by_topic: dict[str, list] = {}
        for tp in offsets:
            by_topic.setdefault(tp.topic, []).append(
                {"partition": tp.partition, "offset": tp.offset,
                 "metadata": getattr(tp, "metadata", None)})
        body = {"transactional_id": self.transactional_id,
                "group_id": group_id,
                "producer_id": self.pid, "producer_epoch": self.epoch,
                "topics": [{"topic": t, "partitions": ps}
                           for t, ps in by_topic.items()]}
        while True:
            gb = self._coord_broker(deadline, key=group_id, key_type=0)
            err, resp = self._sync_request(gb, ApiKey.TxnOffsetCommit,
                                           body, deadline)
            codes = []
            if err is None:
                codes = [Err.from_wire(p["error_code"])
                         for t in resp["topics"] for p in t["partitions"]]
                if all(c == Err.NO_ERROR for c in codes):
                    with self._lock:
                        self._sent_offsets = True
                    return
            bad = (err.code if err is not None
                   else next(c for c in codes if c != Err.NO_ERROR))
            if bad in (Err.PRODUCER_FENCED, Err.INVALID_PRODUCER_EPOCH):
                raise KafkaException(self.fenced("TxnOffsetCommit"))
            if bad in FATAL:
                raise KafkaException(self._fatal(
                    bad, f"TxnOffsetCommit: {bad.name}"))
            if bad not in RETRIABLE or time.monotonic() >= deadline:
                kerr = KafkaError(bad, f"TxnOffsetCommit: {bad.name}",
                                  retriable=bad in RETRIABLE)
                with self._lock:
                    if bad not in RETRIABLE:
                        self._abortable_reason = kerr
                        self._set_state("ABORTABLE_ERROR")
                raise KafkaException(kerr)
            self._backoff(deadline)

    def commit_transaction(self, timeout: float = -1) -> None:
        """Flush every in-flight message, then EndTxn(committed=True)
        (reference: rd_kafka_commit_transaction)."""
        self._require("IN_TXN")
        deadline = self._deadline(timeout)
        # all outstanding messages must be delivered before the commit
        # marker is written — including batches still inside the codec
        # offload pipeline (their tickets resolve through the normal
        # flush path)
        remain = max(0.1, deadline - time.monotonic())
        if self.rk.flush(remain) != 0:
            raise KafkaException(KafkaError(
                Err._TIMED_OUT,
                "commit_transaction: outstanding messages did not "
                "drain within the timeout", retriable=True))
        with self._lock:
            if self.state == "ABORTABLE_ERROR" or \
                    self._abortable_reason is not None:
                reason = self._abortable_reason
                raise KafkaException(KafkaError(
                    Err._STATE,
                    "commit_transaction: transaction must be aborted "
                    f"(a message failed: {reason!r})", retriable=False))
            self._require("IN_TXN")
            empty = (not self._registered and not self._pending
                     and not self._sent_offsets)
            self._set_state("COMMITTING")
        try:
            if not empty:
                self._txn_request(
                    ApiKey.EndTxn,
                    {"transactional_id": self.transactional_id,
                     "producer_id": self.pid,
                     "producer_epoch": self.epoch, "committed": True},
                    deadline, "commit_transaction")
        except KafkaException as e:
            with self._lock:
                if self.state == "COMMITTING":
                    self._set_state("ABORTABLE_ERROR"
                                    if not e.error.retriable
                                    and e.error.code not in FATAL
                                    else "IN_TXN" if e.error.retriable
                                    else self.state)
            raise
        with self._lock:
            self._set_state("READY")
            self._registered.clear()
            self._pending.clear()
        self.rk.dbg("eos", "transaction committed")

    def abort_transaction(self, timeout: float = -1) -> None:
        """Purge queued messages, drain in-flight ones (codec tickets
        included — fail-or-drain, never wedge the dispatch thread),
        then EndTxn(committed=False) (reference:
        rd_kafka_abort_transaction)."""
        self._require("IN_TXN", "ABORTABLE_ERROR", "COMMITTING")
        deadline = self._deadline(timeout)
        with self._lock:
            self._set_state("ABORTING")
        # queued-but-unsent messages will never be wanted: purge them
        # (their DRs carry _PURGE_QUEUE). In-flight requests AND batches
        # inside the codec pipeline are left to complete — their records
        # land before the ABORT marker and are hidden by it — so the
        # flush below drains every outstanding ticket deterministically.
        self.rk.purge(in_queue=True, in_flight=False)
        remain = max(0.1, deadline - time.monotonic())
        if self.rk.flush(remain) != 0:
            with self._lock:
                self._set_state("ABORTABLE_ERROR")
            raise KafkaException(KafkaError(
                Err._TIMED_OUT,
                "abort_transaction: in-flight messages did not drain "
                "within the timeout", retriable=True))
        # registration quiescence: an in-flight AddPartitionsToTxn must
        # resolve before EndTxn (its response decides the final
        # registered set). Partitions still merely *pending* after the
        # purge+flush carry no broker-side data — produce is gated on
        # registration — so with the queue purged they never will:
        # drop them instead of registering partitions the coordinator
        # would mark with an empty transaction.
        with self._cv:
            while self._register_inflight:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise KafkaException(KafkaError(
                        Err._TIMED_OUT,
                        "abort_transaction: partition registration did "
                        "not quiesce within the timeout", retriable=True))
                self._cv.wait(remain)
            self._pending.clear()
            had_work = bool(self._registered or self._sent_offsets)
        try:
            if had_work:
                self._txn_request(
                    ApiKey.EndTxn,
                    {"transactional_id": self.transactional_id,
                     "producer_id": self.pid,
                     "producer_epoch": self.epoch, "committed": False},
                    deadline, "abort_transaction")
        except KafkaException as e:
            with self._lock:
                if self.state == "ABORTING":
                    self._set_state("IN_TXN" if e.error.retriable
                                    else self.state)
            raise
        # bump the epoch (KIP-360 shape): purged messages consumed
        # msgids, so per-partition sequences have gaps the broker would
        # reject — a fresh epoch restarts sequencing at 0, and the
        # DRAIN-style rebase realigns every toppar's msgid origin
        resp = self._txn_request(
            ApiKey.InitProducerId,
            {"transactional_id": self.transactional_id,
             "transaction_timeout_ms":
                 self.rk.conf.get("transaction.timeout.ms")},
            deadline, "abort_transaction(epoch bump)")
        with self.rk._toppars_lock:
            tps = list(self.rk._toppars.values())
        for tp in tps:
            with tp.lock:
                tp.epoch_base_msgid = tp.next_msgid - 1
        with self._lock:
            self.pid = resp["producer_id"]
            self.epoch = resp["producer_epoch"]
            self._registered.clear()
            self._pending.clear()
            self._abortable_reason = None
            self._set_state("READY")
        idemp = self.rk.idemp
        with idemp._lock:
            idemp.pid = self.pid
            idemp.epoch = self.epoch
            idemp.state = "ASSIGNED"
        self.rk.dbg("eos", f"transaction aborted (epoch -> {self.epoch})")

    # --------------------------------------------- broker-thread interface --
    def can_produce(self) -> bool:
        return self.state in ("IN_TXN", "COMMITTING", "ABORTING")

    def partition_ready(self, tp) -> bool:
        """May this toppar's batches be sent? True once the partition
        is registered with the coordinator; otherwise queues it for the
        main-thread serve() pass to register (the broker serve loop
        must never block on a coordinator round trip)."""
        key = (tp.topic, tp.partition)
        with self._lock:
            if not self.can_produce():
                return False
            if key in self._registered:
                return True
            first = key not in self._pending
            self._pending.add(key)
        if first:
            # wake the main thread NOW: its serve() pass sends the
            # AddPartitionsToTxn — without the nudge the partition's
            # first batches stall up to a full main-loop tick (100ms)
            self.rk.ops.push(Op(OpType.BROKER_WAKEUP))
        return False

    def serve(self) -> None:
        """Main-thread pass: flush the pending-partition set with ONE
        AddPartitionsToTxn (reference: rd_kafka_txn_register_toppars)."""
        with self._lock:
            # IN_TXN only: commit flushes (and so registers) before it
            # leaves IN_TXN, and an abort's purged messages must not
            # re-register partitions the coordinator would then hold
            # an empty transaction open for
            if (not self._pending or self._register_inflight
                    or self.state != "IN_TXN"):
                return
            batch = sorted(self._pending)
            self._register_inflight = True
        with self.rk._brokers_lock:
            b = self.rk.brokers.get(self.coord_id)
        if b is None:
            with self._lock:
                self._register_inflight = False
                self._cv.notify_all()
            return
        if not b.is_up():
            b.schedule_connect()
        by_topic: dict[str, list[int]] = {}
        for t, p in batch:
            by_topic.setdefault(t, []).append(p)
        b.enqueue_request(Request(
            ApiKey.AddPartitionsToTxn,
            {"transactional_id": self.transactional_id,
             "producer_id": self.pid, "producer_epoch": self.epoch,
             "topics": [{"topic": t, "partitions": ps}
                        for t, ps in by_topic.items()]},
            retries_left=3,
            cb=self._handle_add_partitions))

    def _handle_add_partitions(self, err, resp):
        with self._lock:
            self._register_inflight = False
            self._cv.notify_all()           # wakes abort's quiescence wait
            if err is not None:
                return                      # retried by the next serve()
            woke = []
            for t in resp["results"]:
                for p in t["partitions"]:
                    key = (t["topic"], p["partition"])
                    code = Err.from_wire(p["error_code"])
                    if code == Err.NO_ERROR:
                        self._pending.discard(key)
                        self._registered.add(key)
                        woke.append(key)
                    elif code in (Err.PRODUCER_FENCED,
                                  Err.INVALID_PRODUCER_EPOCH):
                        self._pending.discard(key)
                        self.fenced("AddPartitionsToTxn")
                    elif code not in RETRIABLE:
                        self._pending.discard(key)
                        kerr = KafkaError(
                            code, f"AddPartitionsToTxn {key}: {code.name}",
                            retriable=False)
                        self._abortable_reason = kerr
                        if self.state == "IN_TXN":
                            self._set_state("ABORTABLE_ERROR")
                    # retriable: stays pending for the next serve()
        for t, p in woke:
            tp = self.rk.get_toppar(t, p, create=False)
            if tp is not None:
                self.rk._wake_leader(tp)
