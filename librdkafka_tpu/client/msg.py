"""Message objects and partitioners (reference: src/rdkafka_msg.c).

``Message`` is the app-visible object (rd_kafka_message_t analog) carrying
payload/key/headers/offset/timestamp/error plus the internal delivery
state used by the idempotent producer (persistence status, msgid,
retries). Partitioners mirror the reference set (rdkafka_msg.c:797-869):
random, consistent, consistent_random, murmur2, murmur2_random.
"""
from __future__ import annotations

import enum
import random
import time
from typing import Optional, Sequence

from ..protocol import proto
from ..utils.hash import consistent_partition, murmur2_partition
from .errors import Err, KafkaError

PARTITION_UA = -1  # unassigned: partitioner decides


class MsgStatus(enum.Enum):
    """Delivery status (rd_kafka_msg_status_t): drives idempotent retry
    safety — POSSIBLY_PERSISTED messages may not be retried blindly."""
    NOT_PERSISTED = 0
    POSSIBLY_PERSISTED = 1
    PERSISTED = 2


class Message:
    __slots__ = ("topic", "partition", "key", "value", "headers", "offset",
                 "timestamp", "timestamp_type", "error", "opaque", "msgid",
                 "retries", "status", "enq_time", "ts_backoff", "latency_us",
                 "on_delivery",
                 "size")

    def __init__(self, topic: str, value: Optional[bytes] = None,
                 key: Optional[bytes] = None,
                 headers: Sequence[tuple[str, Optional[bytes]]] = (),
                 partition: int = PARTITION_UA, timestamp: int = 0,
                 opaque=None):
        self.topic = topic
        self.partition = partition
        self.key = key
        self.value = value
        self.headers = list(headers) if headers else []
        self.offset = proto.OFFSET_INVALID
        self.timestamp = timestamp or int(time.time() * 1000)
        self.timestamp_type = proto.TSTYPE_CREATE_TIME
        self.error: Optional[KafkaError] = None
        self.opaque = opaque
        self.msgid = 0            # producer-assigned FIFO id (idempotence)
        self.retries = 0
        self.status = MsgStatus.NOT_PERSISTED
        self.enq_time = time.monotonic()
        self.ts_backoff = 0.0
        self.latency_us = 0
        self.on_delivery = None       # per-message DR callback
        self.size = (len(value) if value else 0) + (len(key) if key else 0)

    def __len__(self) -> int:
        return self.size

    def __bool__(self) -> bool:
        # an empty message (size 0) must not be falsy: the idiomatic
        # `m = c.poll(...); if m and not m.error:` loop would silently
        # drop empty-value records via __len__ otherwise
        return True

    def __repr__(self):
        return (f"Message({self.topic}[{self.partition}]@{self.offset}"
                f"{' err=' + self.error.code.name if self.error else ''})")


class FetchMessage:
    """Consumer-side message with LAZY key/value/headers: the native
    bulk materializer stores the shared records buffer plus packed
    (offset << 32 | length) ints per record; the bytes objects are
    created only when the app reads ``.value``/``.key`` and are cached
    on first access. Offset-commit-only consumers and key filters
    never pay the per-record payload copy (the reference's rko_msg
    points into the fetch buffer the same way,
    rdkafka_msgset_reader.c:715).

    Also the delivery-report message for fast-lane batches
    (materialize_arena_lazy): ``status`` and ``error`` are per-instance
    slots stamped per batch at materialization. The remaining
    producer-internal fields (msgid, retries, on_delivery, ...) are
    class-level constants — readable, never set on these messages."""

    __slots__ = ("topic", "partition", "offset", "timestamp",
                 "timestamp_type", "error", "status",
                 "_buf", "_v", "_k", "_h")

    msgid = 0
    retries = 0
    opaque = None
    on_delivery = None
    enq_time = 0.0
    ts_backoff = 0.0
    latency_us = 0

    @property
    def value(self) -> Optional[bytes]:
        v = self._v
        if type(v) is int:
            o = v >> 32
            v = self._buf[o:o + (v & 0xFFFFFFFF)]
            if type(v) is not bytes:
                v = bytes(v)          # memoryview slice (zero-copy path)
            self._v = v               # cache: second read is free
        return v

    @property
    def key(self) -> Optional[bytes]:
        k = self._k
        if type(k) is int:
            o = k >> 32
            k = self._buf[o:o + (k & 0xFFFFFFFF)]
            if type(k) is not bytes:
                k = bytes(k)
            self._k = k
        return k

    @property
    def headers(self) -> list:
        h = self._h
        return h if h is not None else []

    @property
    def size(self) -> int:
        v, k = self._v, self._k
        n = (v & 0xFFFFFFFF) if type(v) is int else (len(v) if v else 0)
        n += (k & 0xFFFFFFFF) if type(k) is int else (len(k) if k else 0)
        return n

    def __len__(self) -> int:
        return self.size

    def __bool__(self) -> bool:
        return True

    def __repr__(self):
        return (f"Message({self.topic}[{self.partition}]@{self.offset}"
                f"{' err=' + self.error.code.name if self.error else ''})")


def partition_random(key, cnt, rnd=random.random):
    return int(rnd() * cnt) % cnt


def partitioner_fn(name: str):
    """Resolve a partitioner by config name; returns f(key, cnt) -> int."""
    if name == "random":
        return lambda key, cnt: partition_random(key, cnt)
    if name == "consistent":
        return lambda key, cnt: consistent_partition(key or b"", cnt)
    if name == "consistent_random":
        return lambda key, cnt: (consistent_partition(key, cnt) if key
                                 else partition_random(key, cnt))
    if name == "murmur2":
        return lambda key, cnt: murmur2_partition(key or b"", cnt)
    if name == "murmur2_random":
        return lambda key, cnt: (murmur2_partition(key, cnt) if key
                                 else partition_random(key, cnt))
    raise ValueError(f"unknown partitioner {name!r}")
