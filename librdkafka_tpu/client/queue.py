"""L2 event/op runtime: ops, MPSC queues with forwarding, timers.

The rebuild of the reference's op/queue/timer trio (src/rdkafka_op.c,
rdkafka_queue.c, rdkafka_timer.c): every cross-thread interaction flows
through ``OpQueue`` (mutex+condvar MPSC, reference rdkafka_queue.h:47),
including delivery reports, fetched messages, rebalance events, and admin
results. Queue *forwarding* (rd_kafka_q_fwd_set0, rdkafka_queue.c:127)
re-plumbs per-partition fetch queues into the single consumer queue so one
poll serves all partitions.
"""
from __future__ import annotations

import enum
import heapq
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..analysis import interleave as _itl
from ..analysis.locks import new_cond, new_lock
from ..analysis.races import shared


class OpType(enum.Enum):
    """Op types (subset of the reference's ~40, rdkafka_op.h:73-124)."""
    FETCH = "fetch"                  # consumed message
    ERR = "err"
    CONSUMER_ERR = "consumer_err"
    DR = "dr"                        # delivery report
    STATS = "stats"
    LOG = "log"
    REBALANCE = "rebalance"
    OFFSET_COMMIT = "offset_commit"
    THROTTLE = "throttle"
    PARTITION_JOIN = "partition_join"
    PARTITION_LEAVE = "partition_leave"
    BROKER_WAKEUP = "wakeup"
    TERMINATE = "terminate"
    ADMIN_RESULT = "admin_result"
    OAUTHBEARER_REFRESH = "oauthbearer_refresh"
    PURGE = "purge"
    MOCK = "mock"


@dataclass
class Op:
    type: OpType
    payload: Any = None
    version: int = 0      # epoch barrier for stale-op filtering (op versioning)
    cb: Optional[Callable] = None


class OpQueue:
    """MPSC op queue with forwarding and optional wakeup callback."""

    # lockset-checked shared state (analysis/races.py): every field
    # producers/consumers race over is guarded by ``queue.opq`` —
    # including the wakeup callback, which is PUBLISHED under the lock
    # (the --races sweep caught the old unlocked set against push()'s
    # locked read)
    _items = shared("queue.opq.items")
    _fwd = shared("queue.opq.fwd")
    _wakeup_cb = shared("queue.opq.wakeup_cb")
    disabled = shared("queue.opq.disabled")

    def __init__(self, name: str = "q"):
        self.name = name
        self._lock = new_lock("queue.opq")
        self._cond = new_cond("queue.opq", self._lock)
        self._items: list[Op] = []
        self._fwd: Optional["OpQueue"] = None
        self._wakeup_cb: Optional[Callable[[], None]] = None
        self.disabled = False

    # -- forwarding (rd_kafka_q_fwd_set) ---------------------------------
    def forward_to(self, dst: Optional["OpQueue"]) -> None:
        with self._lock:
            self._fwd = dst
            if dst is not None and self._items:
                items, self._items = self._items, []
            else:
                items = []
        for op in items:
            dst.push(op)

    def set_wakeup_cb(self, cb: Optional[Callable[[], None]]):
        with self._lock:
            self._wakeup_cb = cb

    def io_event_enable(self, fd: int, payload: bytes = b"1") -> None:
        """App event-loop integration (reference:
        rd_kafka_queue_io_event_enable, rdkafka_queue.h:294): every
        enqueue writes ``payload`` to ``fd`` so the app can select()/
        epoll() on it alongside its other fds. Pass fd < 0 to disable.
        The write is non-blocking and best-effort — a full pipe means a
        wakeup is already pending."""
        if fd < 0:
            with self._lock:
                self._wakeup_cb = None
            return
        import os

        def _wake(_fd=fd, _payload=bytes(payload)):
            try:
                os.write(_fd, _payload)
            except (BlockingIOError, OSError):
                pass
        with self._lock:
            self._wakeup_cb = _wake

    def push(self, op: Op) -> None:
        if _itl.active:
            _itl.maybe_yield("opq.push")
        with self._lock:
            fwd = self._fwd
            if fwd is None:
                if self.disabled:
                    return
                self._items.append(op)
                self._cond.notify()
                wcb = self._wakeup_cb
            else:
                wcb = None
        if fwd is not None:
            fwd.push(op)
            return
        if wcb:
            wcb()

    def pop(self, timeout: Optional[float] = None) -> Optional[Op]:
        if _itl.active:
            _itl.maybe_yield("opq.pop")
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while not self._items:
                remain = None if deadline is None else deadline - time.monotonic()
                if self._fwd is not None:
                    # forwarded queue: new pushes go to the target, so
                    # nothing will ever arrive here — but honor the
                    # caller's timeout instead of busy-returning (the
                    # reference's rd_kafka_q_pop on a fwd queue waits).
                    # A None timeout returns immediately rather than
                    # blocking forever on a dead queue.
                    if remain is not None and remain > 0:
                        self._cond.wait(timeout=remain)
                    return None
                if remain is not None and remain <= 0:
                    return None
                if not self._cond.wait(timeout=remain):
                    return None
            return self._items.pop(0)

    def pop_all(self) -> list[Op]:
        with self._lock:
            items, self._items = self._items, []
            return items

    def pop_upto(self, n: int, timeout: Optional[float] = None) -> list[Op]:
        """Batch pop for consumer_poll-style serving
        (rd_kafka_q_serve_rkmessages, rdkafka_queue.c:519)."""
        first = self.pop(timeout)
        if first is None:
            return []
        out = [first]
        with self._lock:
            take = min(n - 1, len(self._items))
            out.extend(self._items[:take])
            del self._items[:take]
        return out

    def serve(self, handler: Callable[[Op], None], timeout: float = 0.0,
              max_ops: int = 0) -> int:
        """Pop and dispatch ops; returns count served (rd_kafka_q_serve)."""
        served = 0
        t = timeout
        while True:
            op = self.pop(t)
            if op is None:
                return served
            t = 0.0
            (op.cb or handler)(op)
            served += 1
            if max_ops and served >= max_ops:
                return served

    def __len__(self) -> int:
        # follow forwarding like rd_kafka_q_len (rkq_fwdq chain): a
        # forwarded queue's ops live in its destination.  The
        # destination's len is taken AFTER our lock drops — the
        # pytest --lockdep sweep flagged the old nested hold as a
        # queue.opq self-order (len(A) inside A.lock takes B.lock;
        # a forwarding cycle would deadlock), and a length read is
        # inherently a snapshot anyway.
        with self._lock:
            fwd = self._fwd
            if fwd is None:
                return len(self._items)
        return len(fwd)


class SyncReply:  # lint: ok shared-state
    """Condvar-blocking reply slot for synchronous request/response
    calls (shared-state pragma: the condvar IS the whole state —
    callers own the predicate's storage and declare it at their layer)
    — the reference's pattern of enqueuing an op with a replyq
    and blocking in rd_kafka_q_serve on it (rdkafka_queue.c:431),
    without the op-object overhead: response callbacks call
    :meth:`post` after recording their result; the caller blocks in
    :meth:`wait` until its predicate holds or the deadline passes.
    Replaces the sleep-polled waits flagged in rounds 2-3."""

    def __init__(self):
        self._cond = new_cond("queue.sync_reply")

    def post(self) -> None:
        with self._cond:
            self._cond.notify_all()

    def wait(self, predicate: Callable[[], bool],
             timeout: float) -> bool:
        """Block until ``predicate()`` is true; returns False on
        timeout. The predicate is evaluated under the condvar lock, so
        a post() between check and wait cannot be lost."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while not predicate():
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return False
                self._cond.wait(remain)
            return True


@dataclass(order=True)
class _Timer:
    next_fire: float
    seq: int
    interval: float = field(compare=False)   # 0 = one-shot
    callback: Callable = field(compare=False)
    active: bool = field(default=True, compare=False)


class Timers:
    """Monotonic timer wheel served by an owning thread
    (reference: rd_kafka_timers_run, rdkafka_timer.c:226)."""

    # add() runs on app/broker threads, run()/next_timeout on the
    # owner; both sides hold ``queue.timers``
    _heap = shared("queue.timers.heap")
    _seq = shared("queue.timers.seq")

    def __init__(self):
        self._heap: list[_Timer] = []
        self._lock = new_lock("queue.timers")
        self._seq = 0

    def add(self, interval_s: float, callback: Callable,
            *, once: bool = False, initial_delay: Optional[float] = None) -> _Timer:
        with self._lock:
            self._seq += 1
            t = _Timer(time.monotonic() + (initial_delay if initial_delay
                                           is not None else interval_s),
                       self._seq, 0.0 if once else interval_s, callback)
            heapq.heappush(self._heap, t)
            return t

    def stop(self, timer: _Timer) -> None:
        timer.active = False

    def next_timeout(self, default: float = 1.0) -> float:
        with self._lock:
            while self._heap and not self._heap[0].active:
                heapq.heappop(self._heap)
            if not self._heap:
                return default
            return max(0.0, min(default, self._heap[0].next_fire - time.monotonic()))

    def run(self) -> int:
        """Fire all due timers; returns count fired."""
        fired = 0
        now = time.monotonic()
        while True:
            with self._lock:
                while self._heap and not self._heap[0].active:
                    heapq.heappop(self._heap)
                if not self._heap or self._heap[0].next_fire > now:
                    return fired
                t = heapq.heappop(self._heap)
                if t.interval > 0:
                    t.next_fire = now + t.interval
                    heapq.heappush(self._heap, t)
            t.callback()
            fired += 1
