"""Partition assignors + consumer-protocol metadata marshalling.

Reference: src/rdkafka_assignor.c (pluggable partition.assignment.strategy,
protocol metadata wire format) with the builtin range
(rdkafka_range_assignor.c), roundrobin (rdkafka_roundrobin_assignor.c)
and KIP-429 cooperative-sticky (rdkafka_sticky_assignor.c) strategies;
rd_kafka_assignor_run (:283) executes on the elected leader.

Wire formats are the public Kafka "consumer" embedded protocol:
  Subscription v0: Version i16, Topics [String], UserData Bytes
  Subscription v1: + OwnedPartitions [Topic String, Partitions [Int32]]
                   (KIP-429: the member's current claims ride the
                   JoinGroup so the leader can compute sticky,
                   incremental assignments)
  Assignment:      Version i16, [Topic String, Partitions [Int32]],
                   UserData

Each assignor also names its **rebalance protocol** (EAGER revokes the
world before every rejoin; COOPERATIVE keeps unrevoked partitions
flowing through the rebalance) — ``ASSIGNOR_PROTOCOLS``, the
rd_kafka_rebalance_protocol() analog.
"""
from __future__ import annotations

from typing import Callable, Optional

from ..protocol.types import Array, Bytes, Int16, Int32, Schema, String
from ..utils.buf import SegBuf, Slice

SUBSCRIPTION_SCHEMA = Schema(
    ("version", Int16), ("topics", Array(String)), ("user_data", Bytes))
_OWNED_SCHEMA = Array(Schema(("topic", String),
                             ("partitions", Array(Int32))))
ASSIGNMENT_SCHEMA = Schema(
    ("version", Int16),
    ("topics", Array(Schema(("topic", String),
                            ("partitions", Array(Int32))))),
    ("user_data", Bytes))


def subscription_encode(topics: list[str], user_data: bytes = b"",
                        owned: Optional[dict[str, list[int]]] = None
                        ) -> bytes:
    """``owned`` (topic -> partitions, the member's CURRENT claims)
    selects Subscription v1 — the cooperative assignor's input; eager
    assignors keep emitting v0 exactly as before."""
    buf = SegBuf()
    SUBSCRIPTION_SCHEMA.write(buf, {
        "version": 0 if owned is None else 1,
        "topics": sorted(topics), "user_data": user_data})
    if owned is not None:
        _OWNED_SCHEMA.write(buf, [
            {"topic": t, "partitions": sorted(ps)}
            for t, ps in sorted(owned.items()) if ps])
    return buf.as_bytes()


def subscription_decode(data: bytes) -> dict:
    sl = Slice(data)
    out = SUBSCRIPTION_SCHEMA.read(sl)
    out["owned_partitions"] = {}
    if out["version"] >= 1 and sl.remains() >= 4:
        out["owned_partitions"] = {
            row["topic"]: row["partitions"]
            for row in _OWNED_SCHEMA.read(sl)}
    return out


def assignment_encode(assignment: dict[str, list[int]],
                      user_data: bytes = b"") -> bytes:
    buf = SegBuf()
    ASSIGNMENT_SCHEMA.write(buf, {
        "version": 0,
        "topics": [{"topic": t, "partitions": sorted(ps)}
                   for t, ps in sorted(assignment.items())],
        "user_data": user_data})
    return buf.as_bytes()


def assignment_decode(data: bytes) -> dict[str, list[int]]:
    if not data:
        return {}
    parsed = ASSIGNMENT_SCHEMA.read(Slice(data))
    return {t["topic"]: t["partitions"] for t in parsed["topics"]}


def range_assignor(members: dict[str, list[str]],
                   partitions: dict[str, int]) -> dict[str, dict[str, list[int]]]:
    """Per-topic contiguous ranges (Java RangeAssignor semantics):
    for each topic, sort consumers; first (n_parts % n_consumers) consumers
    get one extra partition."""
    out: dict[str, dict[str, list[int]]] = {m: {} for m in members}
    topics: dict[str, list[str]] = {}
    for member, subscribed in members.items():
        for t in subscribed:
            topics.setdefault(t, []).append(member)
    for topic, consumers in topics.items():
        nparts = partitions.get(topic, 0)
        if nparts <= 0:
            continue
        consumers = sorted(consumers)
        n = len(consumers)
        per, extra = divmod(nparts, n)
        start = 0
        for i, c in enumerate(consumers):
            cnt = per + (1 if i < extra else 0)
            if cnt:
                out[c][topic] = list(range(start, start + cnt))
            start += cnt
    return out


def roundrobin_assignor(members: dict[str, list[str]],
                        partitions: dict[str, int]) -> dict[str, dict[str, list[int]]]:
    """All (topic, partition) pairs sorted, dealt round-robin to the sorted
    eligible consumers (Java RoundRobinAssignor semantics)."""
    out: dict[str, dict[str, list[int]]] = {m: {} for m in members}
    pairs = []
    for t in sorted(partitions):
        for p in range(partitions[t]):
            pairs.append((t, p))
    consumers = sorted(members)
    i = 0
    for t, p in pairs:
        # find next consumer subscribed to t
        for _ in range(len(consumers)):
            c = consumers[i % len(consumers)]
            i += 1
            if t in members[c]:
                out[c].setdefault(t, []).append(p)
                break
    return out


def cooperative_sticky_assignor(
        members: dict[str, list[str]], partitions: dict[str, int],
        owned: Optional[dict[str, dict[str, list[int]]]] = None
        ) -> dict[str, dict[str, list[int]]]:
    """KIP-429 cooperative-sticky (reference: rdkafka_sticky_assignor.c
    + the CooperativeStickyAssignor adjustment): every member keeps the
    partitions it already owns (stickiness maximized), free partitions
    go to the least-loaded eligible member, and **no partition is ever
    assigned to a new owner in the generation it is revoked from the
    old one** — a moving partition is simply left out of this
    generation's assignment (the old owner's incremental revoke +
    rejoin triggers the next generation, which hands it over).

    ``owned``: member -> {topic: [partitions]} claims from the
    Subscription v1 ``owned_partitions`` field.  A partition claimed by
    two members (zombie generation overlap) is kept by NEITHER — both
    revoke, and the next generation reassigns it cleanly.
    """
    owned = owned or {}
    out: dict[str, dict[str, list[int]]] = {m: {} for m in members}
    topic_members: dict[str, list[str]] = {}
    for m, subscribed in members.items():
        for t in subscribed:
            if partitions.get(t, 0) > 0:
                topic_members.setdefault(t, []).append(m)
    all_parts = [(t, p) for t in sorted(topic_members)
                 for p in range(partitions[t])]
    # validate claims: drop unsubscribed topics / out-of-range ids
    claims: dict[tuple[str, int], list[str]] = {}
    for m in sorted(members):
        for t, ps in (owned.get(m) or {}).items():
            if t not in members[m] or partitions.get(t, 0) <= 0:
                continue
            for p in ps:
                if 0 <= p < partitions[t]:
                    claims.setdefault((t, p), []).append(m)
    sticky = {tp: cs[0] for tp, cs in claims.items() if len(cs) == 1}
    conflicted = {tp for tp, cs in claims.items() if len(cs) > 1}
    load = {m: 0 for m in members}
    for (t, p), m in sorted(sticky.items()):
        out[m].setdefault(t, []).append(p)
        load[m] += 1
    # free partitions (unclaimed) placed least-loaded-first; conflicted
    # ones sit out this generation entirely (see docstring)
    for t, p in all_parts:
        if (t, p) in sticky or (t, p) in conflicted:
            continue
        elig = topic_members.get(t)
        if not elig:
            continue
        m = min(elig, key=lambda c: (load[c], c))
        out[m].setdefault(t, []).append(p)
        load[m] += 1
    # rebalance overloaded members: strip sticky partitions down toward
    # the mean, WITHOUT assigning them to anyone this generation — the
    # virtual load bump models where the next generation will put them,
    # so one pass never strips more than the imbalance
    moved = True
    while moved:
        moved = False
        for (t, p), m in sorted(sticky.items()):
            if p not in out[m].get(t, ()):
                continue                       # already stripped
            cands = [c for c in topic_members[t] if c != m]
            if not cands:
                continue
            c = min(cands, key=lambda x: (load[x], x))
            if load[m] - load[c] >= 2:
                out[m][t].remove(p)
                if not out[m][t]:
                    del out[m][t]
                load[m] -= 1
                load[c] += 1                   # virtual: lands next gen
                moved = True
    for m in out:
        out[m] = {t: sorted(ps) for t, ps in out[m].items()}
    return out


ASSIGNORS: dict[str, Callable] = {
    "range": range_assignor,
    "roundrobin": roundrobin_assignor,
    "cooperative-sticky": cooperative_sticky_assignor,
}

#: rebalance protocol per assignor (rd_kafka_rebalance_protocol): the
#: member's effective protocol is the one of the broker-elected
#: assignor, so a group mixing cooperative and eager-only members
#: downgrades to EAGER via the broker's common-protocol selection
ASSIGNOR_PROTOCOLS: dict[str, str] = {
    "range": "EAGER",
    "roundrobin": "EAGER",
    "cooperative-sticky": "COOPERATIVE",
}
