"""Admin client (reference: src/rdkafka_admin.c, 2734 LoC).

Each admin operation runs through the reference's generic async worker
state machine (states documented rdkafka_admin.c:91-177, worker at
:645):

    INIT → WAIT_BROKER / WAIT_CONTROLLER → CONSTRUCT_REQUEST
         → WAIT_RESPONSE → (retry on retriable/NOT_CONTROLLER) → DONE

Results are delivered through per-item ``concurrent.futures.Future``
objects (the Pythonic analog of the reference's result events on the
app queue): ``create_topics`` returns ``{topic: Future}``, each future
resolving to ``None`` on success or raising :class:`KafkaException`.

Targets (reference rd_kafka_admin_targets): topic mutation ops go to
the cluster controller (discovered via Metadata), config ops for BROKER
resources to that specific broker, group ops to the group coordinator
(FindCoordinator), everything else to any up broker.
"""
from __future__ import annotations

import time
from concurrent.futures import Future
from typing import Callable, Optional

from ..protocol.proto import ApiKey
from .broker import Request
from .conf import Conf
from .errors import Err, KafkaError, KafkaException

# Kafka AdminResourceType values
RESOURCE_UNKNOWN = 0
RESOURCE_ANY = 1
RESOURCE_TOPIC = 2
RESOURCE_GROUP = 3
RESOURCE_BROKER = 4

# Per-item response errors the worker retries rather than surfaces
# (reference: rd_kafka_admin_worker retriable response handling). NOTE:
# narrower than errors.RETRIABLE_ERRS — e.g. UNKNOWN_TOPIC_OR_PART is a
# final answer for admin ops.
ADMIN_RETRIABLE = frozenset({
    Err.NOT_CONTROLLER, Err.COORDINATOR_NOT_AVAILABLE,
    Err.COORDINATOR_LOAD_IN_PROGRESS, Err.NOT_COORDINATOR,
    Err.REQUEST_TIMED_OUT, Err.NETWORK_EXCEPTION,
})

CONFIG_SOURCE_NAMES = {
    0: "UNKNOWN_CONFIG", 1: "DYNAMIC_TOPIC_CONFIG",
    2: "DYNAMIC_BROKER_CONFIG", 3: "DYNAMIC_DEFAULT_BROKER_CONFIG",
    4: "STATIC_BROKER_CONFIG", 5: "DEFAULT_CONFIG",
}


class NewTopic:
    """Topic specification for create_topics (rd_kafka_NewTopic_t)."""

    def __init__(self, topic: str, num_partitions: int = 1,
                 replication_factor: int = -1,
                 replica_assignment: Optional[list] = None,
                 config: Optional[dict] = None):
        self.topic = topic
        self.num_partitions = num_partitions
        self.replication_factor = replication_factor
        self.replica_assignment = replica_assignment or []
        self.config = dict(config or {})

    def __repr__(self):
        return f"NewTopic({self.topic}, np={self.num_partitions})"


class NewPartitions:
    """Partition-count increase for create_partitions
    (rd_kafka_NewPartitions_t)."""

    def __init__(self, topic: str, new_total_count: int,
                 replica_assignment: Optional[list] = None):
        self.topic = topic
        self.new_total_count = new_total_count
        self.replica_assignment = replica_assignment or []


class ConfigEntry:
    """One config row from describe_configs (rd_kafka_ConfigEntry_t)."""

    __slots__ = ("name", "value", "source", "is_read_only", "is_sensitive",
                 "is_synonym", "synonyms")

    def __init__(self, name, value, source=0, is_read_only=False,
                 is_sensitive=False, is_synonym=False, synonyms=None):
        self.name = name
        self.value = value
        self.source = source
        self.is_read_only = is_read_only
        self.is_sensitive = is_sensitive
        self.is_synonym = is_synonym
        self.synonyms = synonyms or []

    def __repr__(self):
        return f"ConfigEntry({self.name}={self.value})"


class ConfigResource:
    """Target of describe/alter_configs (rd_kafka_ConfigResource_t)."""

    TOPIC = RESOURCE_TOPIC
    BROKER = RESOURCE_BROKER
    GROUP = RESOURCE_GROUP

    def __init__(self, restype: int, name: str,
                 set_config: Optional[dict] = None):
        self.restype = restype
        self.name = name
        self.set_config_dict = dict(set_config or {})

    def set_config(self, name: str, value: str):
        self.set_config_dict[name] = value
        return self

    def __hash__(self):
        return hash((self.restype, self.name))

    def __eq__(self, other):
        return (isinstance(other, ConfigResource)
                and (self.restype, self.name) == (other.restype, other.name))

    def __repr__(self):
        return f"ConfigResource({self.restype}, {self.name!r})"


class _AdminWorker:
    """One in-flight admin operation (reference rd_kafka_admin_worker,
    rdkafka_admin.c:645). Drives target lookup + request + retry with
    timers on the rk main thread; resolves futures from the broker
    thread that receives the response."""

    def __init__(self, rk, *, api: ApiKey, body: dict, target: str,
                 resolve: Callable, fail_all: Callable,
                 timeout_s: float, group: Optional[str] = None):
        self.rk = rk
        self.api = api
        self.body = body
        self.target = target          # "controller" | "any" | "coordinator"
        self.group = group
        self.resolve = resolve        # resolve(resp) -> None (sets futures)
        self.fail_all = fail_all      # fail_all(KafkaError)
        self.deadline = time.monotonic() + timeout_s
        self.state = "INIT"
        self._timer = None
        self._step()                  # enter the FSM

    # ------------------------------------------------------------- states --
    def _retry_soon(self, delay: float = 0.1):
        if time.monotonic() >= self.deadline:
            self.fail_all(KafkaError(Err._TIMED_OUT,
                                     f"{self.api.name} admin op timed out "
                                     f"in state {self.state}"))
            return
        self._timer = self.rk.timers.add(delay, self._step, once=True)

    def _step(self):
        if self.rk.terminating:
            self.fail_all(KafkaError(Err._DESTROY, "client terminating"))
            return
        broker = self._pick_broker()
        if broker is None:
            # WAIT_BROKER / WAIT_CONTROLLER: need metadata or a connection
            self.state = ("WAIT_CONTROLLER" if self.target == "controller"
                          else "WAIT_BROKER")
            self.rk.metadata_refresh(f"admin {self.api.name}")
            self._retry_soon()
            return
        self.state = "WAIT_RESPONSE"
        broker.enqueue_request(Request(self.api, self.body,
                                       cb=self._on_response))

    def _pick_broker(self):
        if self.target == "any":
            return self.rk.any_up_broker()
        if self.target == "controller":
            cid = self.rk.metadata.get("controller_id", -1)
            if cid < 0:
                return None
            b = self.rk.brokers.get(cid)
        elif self.target == "coordinator":
            b = self._coord_broker
        elif self.target.startswith("broker:"):
            b = self.rk.brokers.get(int(self.target[7:]))
        else:
            return None
        if b is None:
            return None
        if not b.is_up():
            # sparse connections: this broker may be idle-unconnected;
            # demand a connection and keep polling
            b.schedule_connect()
            return None
        return b

    _coord_broker = None

    def _on_response(self, err, resp):
        if err is not None:
            if err.retriable and time.monotonic() < self.deadline:
                self._retry_soon(self.rk.conf.get("retry.backoff.ms") / 1e3)
            else:
                self.fail_all(err)
            return
        try:
            needs_retry = self.resolve(resp)
        except Exception as e:            # never leave futures pending
            self.fail_all(KafkaError(Err._FAIL, f"result parse: {e!r}"))
            return
        if needs_retry:
            # some items returned retriable errors (NOT_CONTROLLER etc);
            # re-run the FSM — done futures are skipped on re-resolve
            if self.target == "controller":
                self.rk.metadata_refresh("admin NOT_CONTROLLER")
            self._retry_soon(self.rk.conf.get("retry.backoff.ms") / 1e3)


def _start_coordinator_worker(rk, group: str, worker_kwargs: dict):
    """FindCoordinator first, then run the worker against it
    (reference WAIT_BROKER with coordinator lookup)."""
    w = _AdminWorker.__new__(_AdminWorker)

    def do_find():
        b = rk.any_up_broker()
        if b is None:
            if time.monotonic() >= w.deadline:
                w.fail_all(KafkaError(Err._TIMED_OUT,
                                      "no broker for FindCoordinator"))
            else:
                rk.metadata_refresh("admin coordinator lookup")
                rk.timers.add(0.1, do_find, once=True)
            return
        b.enqueue_request(Request(
            ApiKey.FindCoordinator,
            {"key": group, "key_type": 0},
            cb=on_coord))

    def on_coord(err, resp):
        if err is None and resp["error_code"] == 0:
            nid = resp["node_id"]
            coord = rk.brokers.get(nid)
            w._coord_broker = coord
            w.__init__(rk, **worker_kwargs)
        elif time.monotonic() < w.deadline:
            rk.timers.add(0.25, do_find, once=True)
        else:
            w.fail_all(err or KafkaError(Err.from_wire(resp["error_code"]),
                                         "FindCoordinator failed"))

    # pre-init the fields fail paths need before __init__ runs
    w.rk = rk
    w.deadline = time.monotonic() + worker_kwargs["timeout_s"]
    w.fail_all = worker_kwargs["fail_all"]
    w.state = "WAIT_COORDINATOR"
    do_find()
    return w


class AdminClient:
    """App-facing admin API (reference: the rd_kafka_CreateTopics family,
    rdkafka.h admin section). Owns its own client instance like any
    producer/consumer handle; all methods are async and return dicts of
    futures keyed the way confluent-kafka does."""

    def __init__(self, conf):
        from .kafka import Kafka, PRODUCER
        if isinstance(conf, dict):
            c = Conf()
            c.update(conf)
            conf = c
        # admin handles never produce: force idempotence off
        conf.set("enable.idempotence", False)
        self._rk = Kafka(conf, PRODUCER)

    # --------------------------------------------------------- lifecycle --
    def poll(self, timeout: float = 0.0) -> int:
        return self._rk.poll(timeout)

    def close(self, timeout: float = 5.0):
        self._rk.close(timeout)

    @property
    def rk(self):
        return self._rk

    # -------------------------------------------------------- operations --
    @staticmethod
    def _futures(keys) -> dict:
        return {k: Future() for k in keys}

    @staticmethod
    def _fail_all(futs):
        def fail(err: KafkaError):
            for f in futs.values():
                if not f.done():
                    f.set_exception(KafkaException(err))
        return fail

    @staticmethod
    def _set(fut: Future, err_code: int, err_msg: Optional[str],
             value=None) -> bool:
        """Resolve one per-item result. Returns True when the item hit an
        admin-retriable error and was left pending for the worker to
        retry (the worker's deadline eventually fails it)."""
        if fut.done():
            return False
        err = Err.from_wire(err_code)
        if err in ADMIN_RETRIABLE:
            return True
        if err != Err.NO_ERROR:
            fut.set_exception(KafkaException(
                KafkaError(err, err_msg or err.name)))
        else:
            fut.set_result(value)
        return False

    def create_topics(self, new_topics: list[NewTopic], *,
                      operation_timeout: float = 30.0,
                      validate_only: bool = False) -> dict[str, Future]:
        """CreateTopics via the controller (rdkafka_admin.c
        rd_kafka_CreateTopics, :1296)."""
        futs = self._futures(t.topic for t in new_topics)
        body = {
            "topics": [{
                "topic": t.topic,
                "num_partitions": t.num_partitions,
                "replication_factor": t.replication_factor,
                "replica_assignment": [
                    {"partition": i, "replicas": reps}
                    for i, reps in enumerate(t.replica_assignment)],
                "configs": [{"name": k, "value": v}
                            for k, v in t.config.items()],
            } for t in new_topics],
            "timeout": int(operation_timeout * 1000),
            "validate_only": validate_only,
        }

        def resolve(resp):
            retry = False
            for r in resp["topics"]:
                retry |= self._set(futs[r["topic"]], r["error_code"],
                                   r.get("error_message"))
            return retry

        _AdminWorker(self._rk, api=ApiKey.CreateTopics, body=body,
                     target="controller", resolve=resolve,
                     fail_all=self._fail_all(futs),
                     timeout_s=operation_timeout)
        return futs

    def delete_topics(self, topics: list[str], *,
                      operation_timeout: float = 30.0) -> dict[str, Future]:
        futs = self._futures(topics)
        body = {"topics": list(topics),
                "timeout": int(operation_timeout * 1000)}

        def resolve(resp):
            retry = False
            for r in resp["topics"]:
                retry |= self._set(futs[r["topic"]], r["error_code"], None)
            return retry

        _AdminWorker(self._rk, api=ApiKey.DeleteTopics, body=body,
                     target="controller", resolve=resolve,
                     fail_all=self._fail_all(futs),
                     timeout_s=operation_timeout)
        return futs

    def create_partitions(self, new_parts: list[NewPartitions], *,
                          operation_timeout: float = 30.0,
                          validate_only: bool = False) -> dict[str, Future]:
        futs = self._futures(p.topic for p in new_parts)
        body = {
            "topics": [{
                "topic": p.topic,
                "count": p.new_total_count,
                "assignment": [{"broker_ids": bids}
                               for bids in p.replica_assignment],
            } for p in new_parts],
            "timeout": int(operation_timeout * 1000),
            "validate_only": validate_only,
        }

        def resolve(resp):
            retry = False
            for r in resp["topics"]:
                retry |= self._set(futs[r["topic"]], r["error_code"],
                                   r.get("error_message"))
            return retry

        _AdminWorker(self._rk, api=ApiKey.CreatePartitions, body=body,
                     target="controller", resolve=resolve,
                     fail_all=self._fail_all(futs),
                     timeout_s=operation_timeout)
        return futs

    def describe_configs(self, resources: list[ConfigResource], *,
                         operation_timeout: float = 30.0,
                         include_synonyms: bool = False
                         ) -> dict[ConfigResource, Future]:
        futs = self._futures(resources)
        by_key = {(r.restype, r.name): f for r, f in futs.items()}
        body = {
            "resources": [{"resource_type": r.restype,
                           "resource_name": r.name,
                           "config_names": None}
                          for r in resources],
            "include_synonyms": include_synonyms,
        }
        # BROKER resources must be asked of that broker itself
        target = "any"
        if (len(resources) == 1
                and resources[0].restype == RESOURCE_BROKER
                and resources[0].name.lstrip("-").isdigit()):
            target = f"broker:{resources[0].name}"

        def resolve(resp):
            retry = False
            for r in resp["resources"]:
                fut = by_key.get((r["resource_type"], r["resource_name"]))
                if fut is None:
                    continue
                entries = {
                    e["name"]: ConfigEntry(
                        e["name"], e["value"], e.get("source", 0),
                        e.get("read_only", False), e.get("sensitive", False),
                        synonyms=[ConfigEntry(s["name"], s["value"],
                                              s.get("source", 0),
                                              is_synonym=True)
                                  for s in e.get("synonyms", [])])
                    for e in r["entries"]}
                retry |= self._set(fut, r["error_code"],
                                   r.get("error_message"), entries)
            return retry

        _AdminWorker(self._rk, api=ApiKey.DescribeConfigs, body=body,
                     target=target, resolve=resolve,
                     fail_all=self._fail_all(futs),
                     timeout_s=operation_timeout)
        return futs

    def alter_configs(self, resources: list[ConfigResource], *,
                      operation_timeout: float = 30.0,
                      validate_only: bool = False
                      ) -> dict[ConfigResource, Future]:
        futs = self._futures(resources)
        by_key = {(r.restype, r.name): f for r, f in futs.items()}
        body = {
            "resources": [{
                "resource_type": r.restype,
                "resource_name": r.name,
                "entries": [{"name": k, "value": v}
                            for k, v in r.set_config_dict.items()],
            } for r in resources],
            "validate_only": validate_only,
        }

        def resolve(resp):
            retry = False
            for r in resp["resources"]:
                fut = by_key.get((r["resource_type"], r["resource_name"]))
                if fut is not None:
                    retry |= self._set(fut, r["error_code"],
                                       r.get("error_message"))
            return retry

        _AdminWorker(self._rk, api=ApiKey.AlterConfigs, body=body,
                     target="controller", resolve=resolve,
                     fail_all=self._fail_all(futs),
                     timeout_s=operation_timeout)
        return futs

    # ---------------------------------------------------------- group ops --
    def list_groups(self, *, operation_timeout: float = 30.0) -> Future:
        """ListGroups against any up broker; resolves to
        [(group_id, protocol_type)]."""
        fut = Future()
        futs = {"_": fut}

        def resolve(resp):
            err = Err.from_wire(resp["error_code"])
            if err != Err.NO_ERROR:
                fut.set_exception(KafkaException(KafkaError(err)))
            else:
                fut.set_result([(g["group_id"], g["protocol_type"])
                                for g in resp["groups"]])

        _AdminWorker(self._rk, api=ApiKey.ListGroups, body={},
                     target="any", resolve=resolve,
                     fail_all=self._fail_all(futs),
                     timeout_s=operation_timeout)
        return fut

    def describe_groups(self, groups: list[str], *,
                        operation_timeout: float = 30.0
                        ) -> dict[str, Future]:
        futs = self._futures(groups)

        def resolve(resp):
            retry = False
            for g in resp["groups"]:
                retry |= self._set(futs[g["group_id"]], g["error_code"],
                                   None, {
                    "state": g["state"],
                    "protocol_type": g["protocol_type"],
                    "protocol": g["protocol"],
                    "members": g["members"],
                })
            return retry

        for group in groups:
            _start_coordinator_worker(self._rk, group, dict(
                api=ApiKey.DescribeGroups, body={"groups": [group]},
                target="coordinator", group=group, resolve=resolve,
                fail_all=self._fail_all(
                    {group: futs[group]}),
                timeout_s=operation_timeout))
        return futs

    def delete_groups(self, groups: list[str], *,
                      operation_timeout: float = 30.0) -> dict[str, Future]:
        futs = self._futures(groups)

        def resolve(resp):
            retry = False
            for g in resp["results"]:
                retry |= self._set(futs[g["group_id"]], g["error_code"], None)
            return retry

        for group in groups:
            _start_coordinator_worker(self._rk, group, dict(
                api=ApiKey.DeleteGroups, body={"groups": [group]},
                target="coordinator", group=group, resolve=resolve,
                fail_all=self._fail_all({group: futs[group]}),
                timeout_s=operation_timeout))
        return futs

    # ------------------------------------------------------------ metadata --
    def list_topics(self, timeout: float = 10.0) -> dict:
        """Synchronous metadata snapshot: {topic: {partition: leader}}
        (reference rd_kafka_metadata). Delegates to the shared client
        implementation (Kafka.list_topics)."""
        return self._rk.list_topics(timeout)
