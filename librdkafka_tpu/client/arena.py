"""Fast-lane produce batches backed by the native enqueue arena.

The reference enqueues produce()d records with zero per-record
allocations (rd_kafka_toppar_enq_msg, rdkafka_msg.c:241); the Python
client's per-record ``Message`` object was the GIL ceiling on the app
thread (~7 µs/record).  The fast lane appends key/value straight into a
per-toppar native arena (ops/native/enqlane.cpp) and the broker thread
take()s contiguous runs that the native framer consumes directly —
``ArenaBatch`` is that run flowing through the same produce pipeline as
a ``list[Message]`` batch (codec phase → send → response → retry/DR).

Eligibility (checked in Kafka.produce / the native Lane): no
interceptors (on_send must fire per message at produce() time),
bytes/None key+value, no on_delivery/opaque.  Widened in PR 16:
explicit partition OR murmur2 auto-partition (native hash, bit-exact
vs utils/hash.murmur2), explicit timestamps (per-record int64 side
array, 0 = batch build time), and record headers (pre-encoded wire
blobs in a side arena — the framer memcpys them).  DR consumers
(dr_msg_cb/dr_cb/"dr" events/background) do NOT demote: delivery
reports materialize Message objects from the arena run at DR time
(dr_msgq → to_messages → materialize_arena), off the produce() path.
Anything else falls back to the Message path; a toppar that sees a
fallback message is permanently demoted (arena drained into Messages
first — FIFO order is preserved exactly).
"""
from __future__ import annotations

import time
from typing import Optional

from ..analysis.locks import new_lock

_enqlane = None
_enqlane_err = False


def _mod():
    global _enqlane, _enqlane_err
    if _enqlane is None and not _enqlane_err:
        try:
            from ..ops.native.build import load_enqlane
            _enqlane = load_enqlane()
        except Exception:
            _enqlane_err = True
    return _enqlane


def arena_new():
    """A new native Arena, or None when the extension can't build."""
    m = _mod()
    return m.Arena() if m else None


class _PyLane:  # lint: ok shared-state
    """Pure-Python Lane stand-in when the C extension is unavailable:
    same interface, always routes produce() to the fallback.

    shared-state pragma: mirrors the C lane's contract — counter RMWs
    ride arena.pylane, the enable flags are single-writer rdk:main
    ints read atomically under the GIL (same contract the native lane
    documents for its struct fields)."""

    def __init__(self):
        self.map: dict = {}
        self.enabled = 0
        self.fatal = 0
        self.msg_cnt = 0
        self.msg_bytes = 0
        self.max_msgs = 100000
        self.max_bytes = 1 << 30
        self._fallback = None
        self._lock = new_lock("arena.pylane")

    def configure(self, fallback, wake, max_msgs, max_bytes,
                  copy_max=None):
        # copy_max (message.copy.max.bytes) is irrelevant here: this
        # stand-in never copies into an arena — everything already takes
        # the reference-holding Message path
        self._fallback = fallback
        self.max_msgs = max_msgs
        self.max_bytes = max_bytes

    def acct(self, dn: int, dbytes: int):
        with self._lock:
            self.msg_cnt += dn
            self.msg_bytes += dbytes
            return (self.msg_cnt, self.msg_bytes)

    def full(self, sz: int = 0) -> bool:
        return (self.msg_cnt >= self.max_msgs
                or self.msg_bytes + sz > self.max_bytes)

    def map_set(self, topic, partition, entry):
        self.map[(topic, partition)] = entry

    def map_del(self, topic, partition):
        return self.map.pop((topic, partition), None)

    def part_set(self, topic, partition_cnt, mode):
        """No-op: the stand-in never auto-partitions natively."""

    def part_del(self, topic):
        """No-op counterpart of part_set."""

    def counters(self):
        """Same shape as the native Lane.counters() — all zero (every
        produce() routed to the fallback)."""
        return {"engaged": 0,
                "fallback": {"disabled": 0, "shape": 0, "oversize": 0,
                             "queue_full": 0, "no_entry": 0,
                             "auto_partition": 0}}

    def produce(self, *args, **kwargs):
        return self._fallback(*args, **kwargs)


def lane_new():
    """A native Lane (C produce entry point + shared counters), or the
    Python stand-in."""
    m = _mod()
    return m.Lane() if m else _PyLane()


def encode_headers(hdrs) -> Optional[bytes]:
    """Pre-encode a headers sequence into the arena side-blob framing —
    varint(nh) + per-header varint(len(key))+key + varint(len(val)|-1)
    [+val] — exactly the record-tail bytes the native framer memcpys.
    Returns None when the shape is fast-lane ineligible (non-str/bytes
    keys, non-bytes values, not a sequence of 2-tuples)."""
    from ..utils import varint
    enc = varint.enc_i64
    try:
        out = bytearray(enc(len(hdrs)))
        for hk, hv in hdrs:
            hkb = hk.encode() if isinstance(hk, str) else hk
            if not isinstance(hkb, bytes):
                return None
            out += enc(len(hkb))
            out += hkb
            if hv is None:
                out.append(1)                   # varint(-1)
            elif isinstance(hv, bytes):
                out += enc(len(hv))
                out += hv
            else:
                return None
        return bytes(out)
    except (TypeError, ValueError):
        return None


def decode_hblob(blob) -> list:
    """Inverse of encode_headers: [(str key, bytes|None value)] —
    demotion drains and DR materialization rebuild Message.headers
    from the side-arena blob."""
    from ..utils.buf import Slice
    sl = Slice(bytes(blob))
    out = []
    for _ in range(sl.read_varint()):
        hk = sl.read(sl.read_varint()).decode("utf-8", "replace")
        vl = sl.read_varint()
        out.append((hk, None if vl < 0 else sl.read(vl)))
    return out


class ArenaBatch:
    """One taken arena run: the fast-lane analog of list[Message].

    ``base`` is the concatenated key||value payload bytes; ``klens`` /
    ``vlens`` are raw little-endian int32 arrays (-1 = null) that
    tk_frame_v2 reads in place.  Widened runs additionally carry
    ``tss`` (raw int64 per-record create timestamps, 0 = batch build
    time), and ``hbuf``/``hlens`` (concatenated pre-encoded header
    blobs + raw int32 per-record blob lengths); all three are None for
    the all-default hot shape.  msgid_base is assigned at take() time
    under the toppar lock — idempotent sequence numbering is identical
    to the Message path's per-enqueue assignment because takes are
    FIFO and exclusive."""

    __slots__ = ("base", "klens", "vlens", "count", "nbytes",
                 "msgid_base", "enq_first", "enq_last", "retries",
                 "possibly_persisted", "tss", "hbuf", "hlens")

    def __init__(self, base: bytes, klens: bytes, vlens: bytes,
                 count: int, nbytes: int, enq_first_us: int,
                 enq_last_us: int, tss: Optional[bytes] = None,
                 hbuf: Optional[bytes] = None,
                 hlens: Optional[bytes] = None):
        self.base = base
        self.klens = klens
        self.vlens = vlens
        self.count = count
        self.nbytes = nbytes
        self.enq_first = enq_first_us / 1e6     # time.monotonic() seconds
        self.enq_last = enq_last_us / 1e6
        self.tss = tss
        self.hbuf = hbuf
        self.hlens = hlens
        self.msgid_base = 0
        self.retries = 0
        self.possibly_persisted = False

    def __len__(self) -> int:
        return self.count

    def to_messages_lazy(self, topic: str, partition: int,
                         base_offset: int, status, error) -> list:
        """DR-path materialization: FetchMessage objects holding the
        arena base buffer + packed offsets — key/value bytes exist only
        if the DR callback reads them (most read error/offset/topic).
        Falls back to the eager path when the extension is absent."""
        from ..protocol import proto
        from .msg import FetchMessage

        m_ = _mod()
        mat = getattr(m_, "materialize_arena_lazy", None) if m_ else None
        # widened runs (explicit ts / headers) take the eager path so
        # every Message carries its real timestamp + decoded headers
        if mat is not None and self.tss is None and self.hbuf is None:
            out = mat(FetchMessage, self.base, self.klens, self.vlens,
                      self.count, topic, partition, base_offset,
                      int(time.time() * 1000), proto.TSTYPE_CREATE_TIME,
                      status, error)
            if out is not None:
                return out
        return self.to_messages(topic, partition, base_offset,
                                status=status, error=error)

    def to_messages(self, topic: str = "", partition: int = -1,
                    base_offset: int = -1, status=None, error=None) -> list:
        """Materialize per-record Message objects (legacy MsgVer0/1
        brokers, delivery reports).  Bulk native creation when the
        extension is loaded (materialize_arena: tp_alloc + direct slot
        stores — the DR path for fast-lane batches); ``status``/
        ``error``/``base_offset`` stamp every record."""
        from .msg import Message, MsgStatus

        m_ = _mod()
        mat = getattr(m_, "materialize_arena", None) if m_ else None
        if (mat is not None and self.tss is None and self.hbuf is None):
            out = mat(Message, self.base, self.klens, self.vlens,
                      self.count, topic, partition, base_offset,
                      self.msgid_base, self.enq_first, self.retries,
                      status if status is not None
                      else MsgStatus.NOT_PERSISTED,
                      error)
            if out is not None:
                return out
        import numpy as np

        kl = np.frombuffer(self.klens, np.int32)
        vl = np.frombuffer(self.vlens, np.int32)
        tsv = (np.frombuffer(self.tss, np.int64)
               if self.tss is not None else None)
        hl = (np.frombuffer(self.hlens, np.int32)
              if self.hbuf is not None else None)
        out = []
        off = 0
        hoff = 0
        for i in range(self.count):
            k = v = None
            if kl[i] >= 0:
                k = self.base[off:off + kl[i]]
                off += int(kl[i])
            if vl[i] >= 0:
                v = self.base[off:off + vl[i]]
                off += int(vl[i])
            hdrs = ()
            if hl is not None and hl[i] > 0:
                hdrs = decode_hblob(
                    self.hbuf[hoff:hoff + int(hl[i])])
                hoff += int(hl[i])
            ts = int(tsv[i]) if tsv is not None else 0
            m = Message(topic, value=v, key=k, partition=partition,
                        headers=hdrs, timestamp=ts)
            m.msgid = self.msgid_base + i
            m.enq_time = self.enq_first
            m.retries = self.retries
            if base_offset >= 0:
                m.offset = base_offset + i
            if status is not None:
                m.status = status
            if error is not None:
                m.error = error
            out.append(m)
        return out

    def __repr__(self):
        return (f"ArenaBatch(n={self.count}, bytes={self.nbytes}, "
                f"msgid_base={self.msgid_base})")


def batch_head_msgid(batch) -> int:
    """First msgid of a produce batch (list[Message] | ArenaBatch)."""
    if isinstance(batch, ArenaBatch):
        return batch.msgid_base
    return batch[0].msgid


def batch_msgids(batch) -> list:
    """All msgids of a batch — the DRAIN rebase's pending scan."""
    if isinstance(batch, ArenaBatch):
        return [batch.msgid_base + i for i in range(batch.count)]
    return [m.msgid for m in batch]
