"""KIP-227 incremental fetch sessions (client side).

The v1.3.0 reference issues sessionless full fetches — every Fetch
request re-lists every fetchable partition, so the steady-state request
cost is O(partitions) per RTT even when nothing changed.  This module
goes beyond the reference: a per-broker ``FetchSession`` negotiates a
session with the broker (Fetch v7+) and from then on sends only the
partitions whose fetch state CHANGED since the last request — an offset
that moved (data consumed, or a seek), a newly added partition, or a
removal (which rides the request's ``forgotten_topics`` array).  A
request with an empty topic list is the steady-state win: it tells the
broker "long-poll my whole session book", costing O(1) bytes for any
number of idle partitions.

Epoch protocol (KIP-227, FetchSessionHandler.java):

- epoch ``-1``  sessionless full fetch (what the reference always sends;
  what this client sends with ``fetch.session.enable=false`` or against
  pre-v7 brokers),
- epoch ``0`` + session_id ``0``  "create a session": the request carries
  the full partition list, the response carries the broker-assigned
  ``session_id``,
- epoch ``1, 2, ...``  incremental requests carrying only changes; the
  broker omits partitions with no data and no error from the response.

Top-level response errors ``FETCH_SESSION_ID_NOT_FOUND`` (the broker
evicted the session — cache pressure, or the broker died and restarted)
and ``INVALID_FETCH_SESSION_EPOCH`` (request/response desync) reset the
session: the next fetch is a full epoch-0 negotiation.  Transport errors
and broker disconnects reset the same way — the session cache lives in
broker memory and dies with it.

Threading: a FetchSession belongs to one Broker and is mutated ONLY on
that broker's serve thread (build at request time, commit/reset at
response time).  The stats emitter reads id/epoch/counter snapshots
lock-free, same single-writer discipline as the Broker fields — the
slots are declared relaxed with that justification.
"""
from __future__ import annotations

from typing import Optional

from ..analysis.races import register_slots

#: session_epoch of a sessionless (full) fetch request
SESSIONLESS_EPOCH = -1
#: session_epoch that asks the broker to create a new session
INITIAL_EPOCH = 0


class FetchSession:
    """Per-broker incremental fetch session state (the client-side
    mirror of the broker's session cache entry)."""

    __slots__ = ("session_id", "epoch", "book", "inflight",
                 "c_partitions_sent", "c_full_fetches", "c_resets",
                 "_pending", "overflowed")

    def __init__(self):
        self.session_id = 0
        self.epoch = INITIAL_EPOCH      # next epoch to SEND
        # committed book: (topic, partition) -> (fetch_offset, max_bytes)
        # as last acknowledged by the broker
        self.book: dict[tuple, tuple] = {}
        self.inflight = False           # one session request at a time
        self.c_partitions_sent = 0      # cumulative, for stats/bench
        self.c_full_fetches = 0         # epoch-0 negotiations issued
        self.c_resets = 0               # session teardowns (errors)
        # book snapshot sent with the in-flight request, committed on
        # success (the broker applies it when it ACCEPTS the request)
        self._pending: Optional[dict] = None
        # partitions already granted their one immediate-return
        # overflow fetch this epoch (see Broker._consumer_serve) —
        # cleared at each session build so the next epoch absorbs them
        self.overflowed: set[tuple] = set()

    # ------------------------------------------------------------ build --
    def build(self, wanted: dict[tuple, tuple]):
        """Compute the request for the next fetch given ``wanted`` —
        the complete current set of fetchable partitions, as
        {(topic, partition): (fetch_offset, max_bytes)}.

        Returns ``(epoch, to_send, forgotten)`` where ``to_send`` is the
        list of keys to serialize into the request's topic list and
        ``forgotten`` the keys for ``forgotten_topics``.  The caller
        must treat the request's EFFECTIVE partition set as all of
        ``wanted`` — the broker may return data for any partition in
        the session book, not just the listed ones."""
        if self.epoch == INITIAL_EPOCH:
            to_send = list(wanted)
            forgotten: list = []
            self.c_full_fetches += 1
        else:
            to_send = [k for k, v in wanted.items()
                       if self.book.get(k) != v]
            forgotten = [k for k in self.book if k not in wanted]
        self._pending = dict(wanted)
        self.inflight = True
        self.overflowed.clear()
        self.c_partitions_sent += len(to_send)
        return self.epoch, to_send, forgotten

    # --------------------------------------------------------- response --
    def on_success(self, session_id: int) -> None:
        """The broker accepted the request: commit the pending book and
        advance the epoch (epoch 0 adopts the broker-assigned id)."""
        if self._pending is not None:
            self.book = self._pending
            self._pending = None
        if self.epoch == INITIAL_EPOCH:
            self.session_id = session_id
        # KIP-227 wraps to 1 (0 and -1 are reserved)
        self.epoch = self.epoch + 1 if self.epoch < 0x7fffffff else 1
        self.inflight = False

    def reset(self, reason: str = "") -> None:
        """Tear the session down: the next fetch renegotiates from a
        full epoch-0 request (session errors, transport errors, broker
        disconnect, migration)."""
        if (self.session_id == 0 and self.epoch == INITIAL_EPOCH
                and not self.book and not self.inflight):
            return                      # nothing negotiated yet: no-op
        self.session_id = 0
        self.epoch = INITIAL_EPOCH
        self.book.clear()
        self._pending = None
        self.inflight = False
        self.overflowed.clear()
        self.c_resets += 1

    def stats(self) -> dict:
        """Lock-free snapshot for the stats emitter (single-writer
        fields; a one-emit-stale gauge is acceptable)."""
        return {"session_id": self.session_id,
                "epoch": self.epoch,
                "partitions_sent": self.c_partitions_sent,
                "partitions_total": len(self.book),
                "full_fetches": self.c_full_fetches,
                "resets": self.c_resets}

    def __repr__(self):
        return (f"FetchSession(id={self.session_id}, epoch={self.epoch}, "
                f"book={len(self.book)})")


# lockset declarations (analysis/races.py; slot form — FetchSession is
# __slots__).  RELAXED with the Broker justification: every mutation
# happens on the owning broker's serve thread (request build + response
# commit/reset both run there); the stats emitter takes lock-free
# int/len snapshots, atomic under the GIL.
register_slots(FetchSession, "session_id", "epoch", "book", "inflight",
               "c_partitions_sent", "c_full_fetches", "c_resets",
               "_pending", "overflowed", prefix="fetch_session",
               relaxed=True)
