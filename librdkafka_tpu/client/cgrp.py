"""Consumer group coordinator ("cgrp") state machine.

Reference: src/rdkafka_cgrp.c (3547 LoC) — two nested FSMs driven from the
main thread via serve() (rd_kafka_cgrp_serve, :3231): the coordinator
query/connect FSM (states rdkafka_cgrp.h:61-79) and the join FSM
(WAIT_JOIN → WAIT_SYNC → WAIT_ASSIGN_REBALANCE_CB → STARTED,
rdkafka_cgrp.h:86-111). The elected leader runs the assignor
(handle_JoinGroup :894 → assignor_run). Heartbeats (:1469) detect
generation changes; max.poll.interval.ms is enforced here (:2742).
"""
from __future__ import annotations

import re
import time
from typing import Optional, TYPE_CHECKING

from ..analysis.locks import new_lock
from ..analysis.races import shared
from ..protocol.proto import ApiKey
from .assignor import (ASSIGNOR_PROTOCOLS, ASSIGNORS, assignment_decode,
                       assignment_encode, subscription_decode,
                       subscription_encode)
from .broker import Request
from .errors import Err, KafkaError
from .queue import Op, OpType, SyncReply

if TYPE_CHECKING:
    from .kafka import Kafka


def _tps_dict(tps) -> dict:
    """(topic, partition) set -> {topic: sorted [partitions]}."""
    out: dict = {}
    for t, p in sorted(tps):
        out.setdefault(t, []).append(p)
    return out


class ConsumerGroup:
    # lockset declarations (analysis/races.py).  Relaxed: the join/
    # sync/heartbeat response handlers run on broker threads while
    # serve() drives the FSM from the rk main thread — serialized by
    # the single-flight ``_pending`` gate (at most one group request
    # outstanding) and read lock-free by the stats emitter (str/int
    # snapshots, GIL-atomic); tracked so a genuinely concurrent second
    # writer path would surface in the --races sweeps.  Strict (all
    # sites under the ``cgrp`` factory lock): ``assignment`` — replaced
    # by the apply paths on app AND broker-callback threads while
    # _join snapshots it for owned_partitions and stats reads it — and
    # the incremental-revoke counter, an RMW between those threads.
    join_state = shared("cgrp.join_state", relaxed=True)
    member_id = shared("cgrp.member_id", relaxed=True)
    generation = shared("cgrp.generation", relaxed=True)
    rebalance_protocol = shared("cgrp.rebalance_proto", relaxed=True)
    assignment = shared("cgrp.assignment")
    incremental_revoke_cnt = shared("cgrp.incremental_revokes")

    def __init__(self, rk: "Kafka", group_id: str):
        self.rk = rk
        self.group_id = group_id
        self.state = "init"            # coordinator FSM
        self.join_state = "init"       # join FSM
        self.coord_id = -1
        self.member_id = ""
        self.generation = -1
        self.protocol = ""
        #: rebalance protocol of the broker-elected assignor
        #: (rd_kafka_rebalance_protocol): NONE until the first
        #: JoinGroup completes, then EAGER or COOPERATIVE
        self.rebalance_protocol = "NONE"
        #: guards ``assignment`` + ``incremental_revoke_cnt`` (leaf
        #: lock: nothing else is ever acquired while held)
        self._lock = new_lock("cgrp")
        self.incremental_revoke_cnt = 0
        # two-phase cooperative rebalance chain (KIP-429): the sync
        # response's incremental revoke is delivered first; its ack
        # chains the incremental assign; a non-empty revoke re-joins
        # afterwards so the freed partitions land next generation
        self._coop_active = False
        self._coop_added: Optional[dict] = None
        self._coop_rejoin = False
        self.subscription: list[str] = []
        self.patterns: list = []            # compiled ^regex subscriptions
        self._matched: set[str] = set()     # topics currently matching
        # literal subscription topics whose metadata is known: a topic
        # whose metadata arrives AFTER the JoinGroup must trigger a
        # rejoin too (reference: rd_kafka_cgrp_metadata_update_check,
        # rdkafka_cgrp.c:3412, rejoins for literal and regex alike)
        self._lit_known: set[str] = set()
        # bumped by rejoin(); a JoinGroup begun under an older version is
        # abandoned on response instead of syncing a stale subscription
        self.sub_version = 0
        self._join_version = 0
        self.assignment: dict[str, list[int]] = {}
        self.rebalance_cnt = 0
        self.last_heartbeat = 0.0
        self.last_coord_query = 0.0
        self.last_poll = time.monotonic()
        self.max_poll_exceeded = False
        self._pending = False          # a request is in flight
        self._unknown_topic_scan = 0.0  # last unknown-literal re-query
        self._wait_rebalance_cb = False
        self._auto_commit_next = 0.0
        self.terminated = False
        # posted when the coordinator FSM reaches "up": sync callers
        # (commit/committed on a consumer that hasn't subscribed yet)
        # block here instead of failing with _WAIT_COORD
        self.coord_ready = SyncReply()

    # ------------------------------------------------------------ public --
    def subscribe(self, topics: list[str]):
        """Topics starting with ``^`` are regex patterns matched against
        the full cluster topic list (reference: rdkafka_pattern.c topic
        pattern lists; the ``^`` prefix is part of the regex, matched
        with search semantics like the reference's regexec).

        All patterns are validated before any state changes (like the
        reference, a bad pattern fails the whole subscribe atomically)."""
        pats = []
        for t in topics:
            if t.startswith("^"):
                try:
                    pats.append(re.compile(t))
                except re.error as e:
                    from .errors import KafkaException
                    raise KafkaException(Err._INVALID_ARG,
                                         f"bad subscription regex {t!r}: {e}")
        self.subscription = list(topics)
        self.patterns = pats
        self._matched = set()
        # literal topics already in the metadata cache won't fire a
        # metadata_update rejoin; unknown ones rejoin when their
        # metadata lands (the assignor needs the partition counts)
        with self.rk._metadata_lock:
            known = set(self.rk.metadata["topics"])
        self._lit_known = {t for t in topics
                           if not t.startswith("^") and t in known}
        # literals after patterns are installed: their metadata_refresh
        # must request the FULL topic list for pattern discovery
        for t in topics:
            if not t.startswith("^"):
                self.rk.get_topic(t)
        if self.patterns:
            self.rk.metadata_refresh("regex subscription")
        self.rejoin("subscribe")

    def effective_subscription(self) -> list[str]:
        """Literal topics + current regex matches."""
        lits = [t for t in self.subscription if not t.startswith("^")]
        return sorted(set(lits) | self._matched)

    def metadata_update(self, topic_names, full: bool = True) -> None:
        """Re-evaluate the subscription against fresh metadata
        (reference: rd_kafka_cgrp_metadata_update_check,
        rdkafka_cgrp.c:3412 — rejoins for literal AND regex
        subscriptions): a literal topic whose metadata arrives after the
        JoinGroup rejoins so the leader's assignor finally sees its
        partitions; a regex match-set change rebalances onto the new
        topics.  ``full=False`` is a sparse (per-topic) update: literal
        arrival still counts, but patterns are only re-evaluated against
        full enumerations (a sparse list would shrink the match set)."""
        topic_names = set(topic_names)
        reasons = []
        lits = {t for t in self.subscription if not t.startswith("^")}
        newly = (lits & topic_names) - self._lit_known
        self._lit_known |= newly
        if full:
            # full enumeration: a deleted topic re-arms its trigger so
            # a later re-create rejoins again
            self._lit_known &= topic_names
        if newly:
            reasons.append(f"literal topic metadata arrived "
                           f"({sorted(newly)})")
        if self.patterns and full:
            matched = {t for t in topic_names
                       if not self.rk.blacklisted(t)
                       and any(p.search(t) for p in self.patterns)}
            if matched != self._matched:
                added = matched - self._matched
                self._matched = matched
                for t in added:
                    self.rk.get_topic(t)
                reasons.append(f"regex match changed (+{sorted(added)})")
        if reasons:
            self.rejoin("; ".join(reasons))

    def unsubscribe(self):
        self.subscription = []
        self.patterns = []
        self._matched = set()
        self._lit_known = set()
        self.sub_version += 1    # abandon any JoinGroup in flight
        self._leave()

    def poll_tick(self):
        self.last_poll = time.monotonic()
        self.max_poll_exceeded = False

    def rejoin(self, reason: str):
        self.rk.dbg("cgrp", f"rejoin: {reason}")
        self.sub_version += 1
        if self.join_state in ("started", "steady"):
            # COOPERATIVE (KIP-429): rejoin WITHOUT revoking — the
            # current assignment rides the JoinGroup as
            # owned_partitions and every unrevoked partition keeps
            # fetching through the whole rebalance; only the sync
            # response's incremental revoke set ever stops a fetcher
            if self.rebalance_protocol != "COOPERATIVE":
                self._trigger_rebalance_revoke()
        self.join_state = "init"

    # ------------------------------------------------------------- serve --
    def serve(self):
        """Called from the main thread loop (rd_kafka_cgrp_serve)."""
        if self.terminated:
            return
        now = time.monotonic()
        if self.subscription:
            # max.poll.interval.ms enforcement (reference :2742) — runs
            # regardless of coordinator state: a stalled app thread must
            # be detected even while the coordinator is being re-queried
            mpi = self.rk.conf.get("max.poll.interval.ms") / 1000.0
            if (self.join_state == "steady" and not self.max_poll_exceeded
                    and now - self.last_poll > mpi):
                self.max_poll_exceeded = True
                self.rk.op_err(KafkaError(
                    Err._MAX_POLL_EXCEEDED,
                    f"application maximum poll interval "
                    f"({int(mpi * 1000)}ms) exceeded"))
                self._leave()
                return
            # a subscribed literal topic with no metadata yet (created
            # after subscribe(), or still propagating) is re-queried on
            # a 1s scan — the reference's rd_kafka_1s_tmr topic scan —
            # so its arrival can fire the metadata_update rejoin; the
            # periodic refresh timer alone is minutes away
            if now - self._unknown_topic_scan >= 1.0 and any(
                    not t.startswith("^") and t not in self._lit_known
                    for t in self.subscription):
                self._unknown_topic_scan = now
                self.rk.metadata_refresh(
                    "unknown subscribed topic(s)",
                    topics=[t for t in self.subscription
                            if not t.startswith("^")
                            and t not in self._lit_known])
        if self.state != "up":
            # the coordinator lookup runs even without a subscription:
            # commit()/committed() on an assign()-based or fresh consumer
            # still needs the group coordinator (reference:
            # rd_kafka_cgrp_serve drives the coord FSM unconditionally)
            self._coord_query(now)
            return
        if not self.subscription:
            return
        if self._pending:
            return
        if self.join_state == "init":
            self._join()
        elif self.join_state == "steady":
            hb = self.rk.conf.get("heartbeat.interval.ms") / 1000.0
            if now - self.last_heartbeat >= hb:
                self._heartbeat()
            self._serve_auto_commit(now)

    # ------------------------------------------------- coordinator query --
    def _coord_query(self, now: float):
        # fast 1s retry while the coordinator is unknown, capped by
        # coordinator.query.interval.ms (reference coord_query_intvl)
        ivl = min(1.0,
                  self.rk.conf.get("coordinator.query.interval.ms") / 1e3)
        if self._pending or now - self.last_coord_query < ivl:
            return
        b = self.rk.any_up_broker()
        if b is None:
            return
        self.last_coord_query = now
        self._pending = True
        self.state = "query-coord"
        b.enqueue_request(Request(
            ApiKey.FindCoordinator, {"key": self.group_id, "key_type": 0},
            cb=self._handle_coord))

    def _handle_coord(self, err, resp):
        self._pending = False
        if err is not None or resp["error_code"] != 0:
            self.state = "init"
            return
        self.coord_id = resp["node_id"]
        with self.rk._brokers_lock:
            known = self.coord_id in self.rk.brokers
        if not known:
            self.rk.metadata_refresh("coordinator unknown")
            self.state = "init"
            return
        self.state = "up"
        self.coord_ready.post()
        self.rk.dbg("cgrp", f"coordinator is broker {self.coord_id}")

    def _coord_broker(self):
        with self.rk._brokers_lock:
            b = self.rk.brokers.get(self.coord_id)
        if b is None or not b.is_up():
            if b is not None:
                # sparse connections: demand the coordinator connect
                b.schedule_connect()
            self.state = "init"
            return None
        return b

    # --------------------------------------------------------------- join --
    def _join(self):
        b = self._coord_broker()
        if b is None:
            return
        self._pending = True
        self.join_state = "wait-join"
        self._join_version = self.sub_version
        names = [n.strip() for n in
                 self.rk.conf.get("partition.assignment.strategy").split(",")
                 if n.strip()]
        topics = self.effective_subscription()
        meta = subscription_encode(topics)
        with self._lock:
            owned = {t: list(ps) for t, ps in self.assignment.items()}
        # cooperative assignors get Subscription v1 with the member's
        # current claims (KIP-429); eager ones keep the v0 encoding
        coop_meta = subscription_encode(topics, owned=owned)
        self.rk.dbg("cgrp", f"joining group {self.group_id!r} "
                            f"member={self.member_id!r}")
        b.enqueue_request(Request(
            ApiKey.JoinGroup,
            {"group_id": self.group_id,
             "session_timeout": self.rk.conf.get("session.timeout.ms"),
             "rebalance_timeout": self.rk.conf.get("max.poll.interval.ms"),
             "member_id": self.member_id,
             # KIP-345 static membership (JoinGroup v5+)
             "group_instance_id":
                 self.rk.conf.get("group.instance.id") or None,
             "protocol_type": self.rk.conf.get("group.protocol.type"),
             "protocols": [{"name": n,
                            "metadata":
                            (coop_meta if ASSIGNOR_PROTOCOLS.get(n)
                             == "COOPERATIVE" else meta)}
                           for n in names]},
            cb=self._handle_join,
            abs_timeout=time.monotonic() +
            self.rk.conf.get("max.poll.interval.ms") / 1000.0 + 5))

    def _handle_join(self, err, resp):
        self._pending = False
        if self.sub_version != self._join_version:
            # subscription changed while the JoinGroup was in flight
            # (e.g. a regex matched new topics): abandon and rejoin with
            # the fresh effective subscription. Keep the broker-assigned
            # member_id — rejoining with it replaces our slot instead of
            # leaving a ghost member that stalls the group's rebalance
            if err is None and resp.get("member_id"):
                self.member_id = resp["member_id"]
            self.join_state = "init"
            return
        if err is not None:
            self.join_state = "init"
            return
        ec = Err.from_wire(resp["error_code"])
        if ec == Err.MEMBER_ID_REQUIRED:
            self.member_id = resp["member_id"]
            self.join_state = "init"
            return
        if ec in (Err.UNKNOWN_MEMBER_ID, Err.ILLEGAL_GENERATION):
            self.member_id = ""
            self.join_state = "init"
            self._lost_assignment(ec.name)
            return
        if ec == Err.NOT_COORDINATOR or ec == Err.COORDINATOR_NOT_AVAILABLE:
            self.state = "init"
            self.join_state = "init"
            return
        if ec != Err.NO_ERROR:
            self.join_state = "init"
            return
        self.member_id = resp["member_id"]
        self.generation = resp["generation_id"]
        self.protocol = resp["protocol"]
        self.rebalance_protocol = ASSIGNOR_PROTOCOLS.get(self.protocol,
                                                         "EAGER")
        is_leader = resp["leader_id"] == self.member_id
        self.rk.dbg("cgrp", f"joined gen {self.generation} "
                            f"{'as leader' if is_leader else ''}")
        assignments = []
        if is_leader:
            assignments = self._run_assignor(resp["members"])
        self._sync(assignments)

    def _run_assignor(self, members: list[dict]) -> list[dict]:
        """Leader-side assignment (reference: rd_kafka_assignor_run)."""
        subs = {}
        owned = {}
        for m in members:
            d = subscription_decode(m["metadata"])
            subs[m["member_id"]] = d["topics"]
            owned[m["member_id"]] = d.get("owned_partitions") or {}
        all_topics = sorted({t for ts in subs.values() for t in ts})
        # partition counts from metadata (refresh if missing)
        with self.rk._metadata_lock:
            parts = {t: len(self.rk.metadata["topics"].get(t, {}))
                     for t in all_topics}
        missing = [t for t, n in parts.items() if n == 0]
        if missing:
            self.rk.metadata_refresh(f"assignor needs {missing}",
                                     topics=missing)
        fn = ASSIGNORS.get(self.protocol, ASSIGNORS["range"])
        if ASSIGNOR_PROTOCOLS.get(self.protocol) == "COOPERATIVE":
            per_member = fn(subs, parts, owned)
        else:
            per_member = fn(subs, parts)
        return [{"member_id": m,
                 "assignment": assignment_encode(a)}
                for m, a in per_member.items()]

    def _sync(self, assignments: list[dict]):
        b = self._coord_broker()
        if b is None:
            self.join_state = "init"
            return
        self._pending = True
        self.join_state = "wait-sync"
        b.enqueue_request(Request(
            ApiKey.SyncGroup,
            {"group_id": self.group_id, "generation_id": self.generation,
             "member_id": self.member_id, "assignments": assignments},
            cb=self._handle_sync))

    def _handle_sync(self, err, resp):
        self._pending = False
        if err is not None:
            self.join_state = "init"
            return
        ec = Err.from_wire(resp["error_code"])
        if ec != Err.NO_ERROR:
            if ec in (Err.UNKNOWN_MEMBER_ID,):
                self.member_id = ""
                self._lost_assignment(ec.name)
            self.join_state = "init"
            return
        new_assignment = assignment_decode(resp["assignment"] or b"")
        self.rebalance_cnt += 1
        self.last_heartbeat = time.monotonic()
        self.rk.dbg("cgrp", f"assignment: {new_assignment}")
        if self.rebalance_protocol == "COOPERATIVE":
            self._apply_cooperative(new_assignment)
        else:
            self._deliver_rebalance(Err._ASSIGN_PARTITIONS, new_assignment)

    # ------------------------------------- cooperative two-phase flow --
    def _apply_cooperative(self, target: dict):
        """KIP-429 incremental application of a sync response: deliver
        only the revoked/added DELTAS — partitions in both the old and
        new assignment are never touched and keep fetching through the
        entire rebalance.  A non-empty revoke chains revoke → assign →
        rejoin (the freed partitions land with their new owner next
        generation — the assignor never moves a partition in the
        generation it is revoked)."""
        with self._lock:
            owned = {t: list(ps) for t, ps in self.assignment.items()}
        own = {(t, p) for t, ps in owned.items() for p in ps}
        tgt = {(t, p) for t, ps in target.items() for p in ps}
        revoked = _tps_dict(own - tgt)
        added = _tps_dict(tgt - own)
        self._coop_active = True
        self._coop_added = added
        self._coop_rejoin = bool(revoked)
        self.rk.dbg("cgrp", f"cooperative delta: revoke={revoked} "
                            f"add={added}")
        if revoked:
            with self._lock:
                self.incremental_revoke_cnt += 1
            self._deliver_rebalance(Err._REVOKE_PARTITIONS, revoked,
                                    incremental=True)
        else:
            self._deliver_assign_phase()

    def _deliver_assign_phase(self):
        added = self._coop_added if self._coop_added is not None else {}
        self._coop_added = None
        self._deliver_rebalance(Err._ASSIGN_PARTITIONS, added,
                                incremental=True)

    def _coop_ack(self, assigned: bool):
        """Advance the cooperative chain after an incremental assign/
        unassign (the app's callback, or the auto-apply path)."""
        self._wait_rebalance_cb = False
        if not self._coop_active:
            return          # manual incremental call outside a rebalance
        if not assigned and self._coop_added is not None:
            self._deliver_assign_phase()
            return
        rejoin = self._coop_rejoin
        self._coop_active = False
        self._coop_rejoin = False
        self._coop_added = None
        self.join_state = "init" if rejoin else "steady"

    def _deliver_rebalance(self, code: Err, assignment: dict,
                           incremental: bool = False):
        """Rebalance op to the app (or auto-apply)
        (reference: rd_kafka_cgrp_rebalance → op to app queue)."""
        consumer = self.rk.consumer
        if self.rk.conf.get("rebalance_cb"):
            self.join_state = "wait-assign-rebalance-cb"
            self._wait_rebalance_cb = True
            consumer.queue.push(Op(OpType.REBALANCE,
                                   payload=(code, assignment, incremental)))
            return
        if incremental:
            if code == Err._ASSIGN_PARTITIONS:
                consumer.apply_incremental_assign(assignment)
                self._coop_ack(True)
            else:
                consumer.apply_incremental_unassign(assignment)
                self._coop_ack(False)
            return
        if code == Err._ASSIGN_PARTITIONS:
            consumer.apply_assignment(assignment)
        else:
            consumer.apply_assignment({})
        self.join_state = "steady"

    def rebalance_done(self, assigned: bool):
        """Called after the app's assign()/unassign() in the rebalance cb."""
        if self._coop_active:
            # the app answered a cooperative op (with either the
            # incremental API or a full assign): drive the chain
            self._coop_ack(assigned)
            return
        self._wait_rebalance_cb = False
        self.join_state = "steady" if assigned else "init"

    def _trigger_rebalance_revoke(self):
        with self._lock:
            assignment = {t: list(ps) for t, ps in self.assignment.items()}
        self._deliver_rebalance(Err._REVOKE_PARTITIONS, assignment)

    def _lost_assignment(self, why: str):
        """This member's ownership is void (fenced / unknown member /
        illegal generation): in cooperative mode every owned partition
        must be revoked — incrementally, so the flow machinery stays on
        the incremental path — before the fresh join claims nothing
        (reference: rd_kafka_cgrp_assignment_lost)."""
        if self.rebalance_protocol != "COOPERATIVE":
            return
        with self._lock:
            owned = {t: list(ps) for t, ps in self.assignment.items()}
        if not any(owned.values()):
            return
        self.rk.dbg("cgrp", f"assignment lost ({why}): revoking {owned}")
        self._coop_active = True
        self._coop_added = {}
        self._coop_rejoin = True    # chain must end back at init
        with self._lock:
            self.incremental_revoke_cnt += 1
        self._deliver_rebalance(Err._REVOKE_PARTITIONS, owned,
                                incremental=True)

    # ---------------------------------------------------------- heartbeat --
    def _heartbeat(self):
        b = self._coord_broker()
        if b is None:
            return
        self.last_heartbeat = time.monotonic()
        b.enqueue_request(Request(
            ApiKey.Heartbeat,
            {"group_id": self.group_id, "generation_id": self.generation,
             "member_id": self.member_id},
            cb=self._handle_heartbeat))

    def _handle_heartbeat(self, err, resp):
        if err is not None:
            return
        ec = Err.from_wire(resp["error_code"])
        if ec == Err.NO_ERROR:
            return
        if ec == Err.REBALANCE_IN_PROGRESS:
            self.rk.dbg("cgrp", "group is rebalancing")
            if self.rebalance_protocol == "COOPERATIVE":
                # KIP-429: rejoin WITHOUT revoking — every owned
                # partition keeps fetching; the sync response's
                # incremental revoke is the only thing that stops one
                if not self._wait_rebalance_cb:
                    self.join_state = "init"
            else:
                self._trigger_rebalance_revoke()
                if not self._wait_rebalance_cb:
                    self.join_state = "init"
        elif ec in (Err.UNKNOWN_MEMBER_ID, Err.ILLEGAL_GENERATION,
                    Err.FENCED_INSTANCE_ID):
            self.member_id = "" if ec == Err.UNKNOWN_MEMBER_ID else self.member_id
            self.join_state = "init"
            # ownership is void: cooperative members must drop their
            # claims (and stop those fetchers) before rejoining
            self._lost_assignment(ec.name)
        elif ec in (Err.NOT_COORDINATOR, Err.COORDINATOR_NOT_AVAILABLE):
            self.state = "init"

    # -------------------------------------------------------- auto commit --
    def _serve_auto_commit(self, now: float):
        if not self.rk.conf.get("enable.auto.commit"):
            return
        ival = self.rk.conf.get("auto.commit.interval.ms") / 1000.0
        if now < self._auto_commit_next:
            return
        self._auto_commit_next = now + ival
        offsets = self.rk.consumer.stored_offsets()
        if offsets:
            self.commit_offsets(offsets, None, from_store=True)

    @staticmethod
    def _synth_offset_resp(items: dict, with_offsets: bool) -> dict:
        """Build an OffsetCommit/OffsetFetch-shaped response for locally
        (file-)stored offsets so every caller sees one response shape."""
        by_topic: dict[str, list] = {}
        for (t, p), off in items.items():
            row = {"partition": p, "error_code": 0, "metadata": None}
            if with_offsets:
                row["offset"] = off if off is not None else -1
            by_topic.setdefault(t, []).append(row)
        return {"topics": [{"topic": t, "partitions": ps}
                           for t, ps in by_topic.items()]}

    def commit_offsets(self, offsets: dict[tuple[str, int], int],
                       cb, from_store: bool = False) -> bool:
        # values may be plain offsets or (offset, metadata) — the
        # commit-metadata string of rd_kafka_topic_partition_t
        # (reference test 0099-commit_metadata); normalize here
        offsets = {k: (v if isinstance(v, tuple) else (v, None))
                   for k, v in offsets.items()}
        # legacy file store split (offset.store.method=file,
        # rdkafka_offset.c:98-330): file-backed topics commit locally
        rk = self.rk
        all_offsets = {k: v[0] for k, v in offsets.items()}
        store = rk.offset_store
        # NOTE: file-backed items commit locally BEFORE the coordinator
        # check — async/terminate callers get the partial file commit
        # even during a coordinator outage (the reference's file store
        # is purely local).  The sync commit() retry loop strips
        # file-backed keys after the first attempt so they are not
        # re-committed per retry.
        if store is not None:
            # offset.store.method=none: offsets for these topics are not
            # stored anywhere (reference RD_KAFKA_OFFSET_METHOD_NONE).
            # Only STORE-DERIVED auto-commit offsets are filtered — an
            # explicitly requested commit (commit(message=...) /
            # commit(offsets=...)) must reach the broker, not vanish
            # behind a synthetic success callback
            none_keys = ([k for k in offsets
                          if store.method(k[0]) == "none"]
                         if from_store else [])
            if none_keys:
                offsets = {k: v for k, v in offsets.items()
                           if k not in none_keys}
                if not offsets:
                    if cb:
                        cb(None, {"topics": []})
                    return True
            file_items = {k: v for k, v in offsets.items()
                          if store.uses_file(k[0])}
            if file_items:
                # plain-int offset dict: callbacks/interceptors keep the
                # pre-metadata contract on every path
                file_plain = {k: v[0] for k, v in file_items.items()}
                store.commit_all(file_plain)
                for (t, p), off in file_plain.items():
                    tp = rk.get_toppar(t, p, create=False)
                    if tp is not None:
                        tp.committed_offset = off
                if rk.interceptors:
                    rk.interceptors.on_commit(file_plain)
                offsets = {k: v for k, v in offsets.items()
                           if k not in file_items}
                if not offsets:
                    if cb:
                        cb(None, self._synth_offset_resp(file_plain, False))
                    occb = rk.conf.get("offset_commit_cb")
                    if occb:
                        occb(None, file_plain)
                    return True
                # mixed commit: report file-backed partitions alongside
                # the broker result in both cb's response and occb
                orig_cb = cb

                def cb(err, resp, _orig=orig_cb, _file=file_plain):
                    if err is None and resp is not None:
                        resp = dict(resp)
                        resp["topics"] = (
                            list(resp["topics"])
                            + self._synth_offset_resp(_file, False)["topics"])
                    if _orig:
                        _orig(err, resp)
        b = self._coord_broker()
        if b is None:
            if cb:
                cb(KafkaError(Err._WAIT_COORD, "no coordinator"), None)
            return False
        by_topic: dict[str, list] = {}
        for (t, p), (off, meta) in offsets.items():
            by_topic.setdefault(t, []).append(
                {"partition": p, "offset": off, "metadata": meta,
                 "timestamp": -1})    # OffsetCommit v1 field; v2 ignores

        def on_commit(err, resp):
            if err is None and self.rk.interceptors:
                self.rk.interceptors.on_commit(
                    {k: v[0] for k, v in offsets.items()})
            if err is None:
                for tpc in resp["topics"]:
                    for pres in tpc["partitions"]:
                        tp = self.rk.get_toppar(tpc["topic"],
                                                pres["partition"],
                                                create=False)
                        if tp is not None and pres["error_code"] == 0:
                            tp.committed_offset = offsets.get(
                                (tpc["topic"], pres["partition"]),
                                (tp.committed_offset, None))[0]
            if cb:
                cb(err, resp)
            occb = self.rk.conf.get("offset_commit_cb")
            if occb:
                occb(err, all_offsets)

        b.enqueue_request(Request(
            ApiKey.OffsetCommit,
            {"group_id": self.group_id, "generation_id": self.generation,
             "member_id": self.member_id, "retention_time": -1,
             "topics": [{"topic": t, "partitions": ps}
                        for t, ps in by_topic.items()]},
            cb=on_commit, retries_left=2))
        return True

    def fetch_committed(self, tps: list[tuple[str, int]], cb) -> bool:
        rk = self.rk
        store = rk.offset_store
        file_reads: dict[tuple[str, int], Optional[int]] = {}
        if store is not None:
            file_tps = [k for k in tps if store.uses_file(k[0])]
            if file_tps:
                file_reads = {(t, p): store.read(t, p) for t, p in file_tps}
                tps = [k for k in tps if k not in file_reads]
                if not tps:
                    if cb:
                        cb(None, self._synth_offset_resp(file_reads, True))
                    return True
        b = self._coord_broker()
        if b is None:
            if file_reads and cb:
                # deliver the file offsets we DID read; the broker-backed
                # partitions fall back to the caller's no-result path
                cb(None, self._synth_offset_resp(file_reads, True))
                return True
            return False
        by_topic: dict[str, list] = {}
        for t, p in tps:
            by_topic.setdefault(t, []).append(p)

        def on_fetch(err, resp):
            if file_reads:
                # merge locally-read file offsets into the result; on
                # broker error still deliver the file offsets rather
                # than discarding successfully-read local state
                if err is None:
                    resp = dict(resp)
                    resp["topics"] = (list(resp["topics"])
                                      + self._synth_offset_resp(
                                          file_reads, True)["topics"])
                else:
                    err, resp = None, self._synth_offset_resp(
                        file_reads, True)
            cb(err, resp)

        b.enqueue_request(Request(
            ApiKey.OffsetFetch,
            {"group_id": self.group_id,
             "topics": [{"topic": t, "partitions": ps}
                        for t, ps in by_topic.items()]},
            cb=on_fetch if cb else None, retries_left=2))
        return True

    # --------------------------------------------------------------- leave --
    def _leave(self):
        b = self._coord_broker()
        # KIP-345: static members do NOT send LeaveGroup — the member
        # slot survives restarts until session.timeout.ms (reference:
        # rd_kafka_cgrp_leave skips for group.instance.id)
        static = bool(self.rk.conf.get("group.instance.id"))
        if b is not None and self.member_id and not static:
            b.enqueue_request(Request(
                ApiKey.LeaveGroup,
                {"group_id": self.group_id, "member_id": self.member_id},
                cb=lambda e, r: None))
        self.join_state = "init"
        self.generation = -1
        self.rk.consumer.apply_assignment({})

    def terminate(self):
        self.terminated = True
        offsets = self.rk.consumer.stored_offsets()
        if offsets and self.rk.conf.get("enable.auto.commit"):
            # final auto-commit must reach the wire before LeaveGroup
            # (reference: rd_kafka_cgrp_terminate waits for the commit
            # reply) — block on the reply instead of sleeping
            done = []
            reply = SyncReply()

            def _cb(err, resp):
                done.append(err)
                reply.post()

            self.commit_offsets(offsets, _cb, from_store=True)
            reply.wait(lambda: bool(done), 1.0)
        self._leave()
