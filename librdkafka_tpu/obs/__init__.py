"""librdkafka_tpu.obs — observability: event tracing (trace.py).

The statistics half of observability lives in client/stats.py (the
rd_avg_t windowed-histogram JSON of STATISTICS.md); this package holds
the EVENT half — the flight-recorder trace rings and the Chrome
trace-event exporter (TRACING.md).
"""
