"""librdkafka_tpu.obs — observability: tracing, metrics, collection.

The per-client statistics half of observability lives in
client/stats.py (the rd_avg_t windowed-histogram JSON of
STATISTICS.md); this package holds the rest of the plane:

  * trace.py   — flight-recorder trace rings + Chrome trace-event
                 export (TRACING.md)
  * metrics.py — the process-wide metrics registry (counters / gauges
                 / HdrHistogram windows) every subsystem registers
                 into (OBSERVABILITY.md)
  * collect.py — cross-process trace merging: clock alignment, one
                 Perfetto-loadable timeline, produce->deliver flow
                 stitching (OBSERVABILITY.md)
"""
