"""Unified metrics registry: named counters, gauges and HdrHistogram
windows shared by every subsystem (ISSUE 20).

The stats JSON (client/stats.py) is per-CLIENT — one blob per Kafka
handle, rendered from that handle's internal counters.  This registry
is per-PROCESS: the offload engine, the broker IO threads, the fleet
driver and the chaos scheduler all register into ONE flat namespace,
so a bench artifact or a fleet verdict can carry a single versioned
snapshot of everything the process observed, regardless of how many
clients (or zero clients — the fleet driver) it ran.

Contract (same as obs/trace.py, gated by the same bench.py --smoke
overhead gate):

  * a module-level ``enabled`` flag; every hot site guards itself with
    ``if metrics.enabled:`` so the disabled cost is ONE attribute load;
  * ``enable()``/``disable()`` are refcounted; the LAST disable clears
    the registry (the conftest leak fixture asserts both);
  * instruments are get-or-create by name (``counter(n)``, ``gauge(n)``,
    ``window(n)``) — sites never hold references across enable cycles,
    so a cleared registry can never swallow later increments;
  * ``snapshot()`` renders the whole registry under a versioned schema
    (``SCHEMA``); window dicts carry exactly the STATISTICS.md window
    keys so the stats-schema test covers them bidirectionally.

Instrument costs are enabled-only: Counter.inc is one locked int add,
Window.record one locked HdrHistogram record (O(1), constant memory).
obs/ is outside the analysis lock-factory scope (like trace.py): plain
``threading.Lock`` keeps this module importable from anywhere without
dragging the analysis layer into stdlib-light processes.
"""
from __future__ import annotations

import threading
from typing import Optional

#: snapshot schema version — bump when the rendered shape changes
SCHEMA = 1

#: master switch — hot sites check THIS attribute inline
enabled = False

_lock = threading.Lock()
_enable_count = 0
_counters: dict[str, "Counter"] = {}
_gauges: dict[str, "Gauge"] = {}
_windows: dict[str, "Window"] = {}


class Counter:
    """Monotonic event count (e.g. ``engine.launches``)."""

    __slots__ = ("name", "_v", "_lk")

    def __init__(self, name: str):
        self.name = name
        self._v = 0
        self._lk = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lk:
            self._v += n

    @property
    def value(self) -> int:
        with self._lk:
            return self._v


class Gauge:
    """Last-write-wins level (e.g. ``fleet.workers``)."""

    __slots__ = ("name", "_v", "_lk")

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0
        self._lk = threading.Lock()

    def set(self, v: float) -> None:
        with self._lk:
            self._v = v

    @property
    def value(self) -> float:
        with self._lk:
            return self._v


class Window:
    """HdrHistogram value distribution (microsecond convention, like
    the stats Avg windows).  Non-destructive snapshot: the registry is
    process-lifetime state, not an interval roller."""

    __slots__ = ("name", "_hist", "_lk")

    #: STATISTICS.md percentile fields (client/stats.py Avg.PCTS)
    PCTS = ((50, "p50"), (75, "p75"), (90, "p90"), (95, "p95"),
            (99, "p99"), (99.99, "p99_99"))

    def __init__(self, name: str, lowest: int = 1,
                 highest: int = 60_000_000, sigfigs: int = 2):
        from ..utils.hdrhistogram import HdrHistogram
        self.name = name
        self._hist = HdrHistogram(lowest, highest, sigfigs)
        self._lk = threading.Lock()

    def record(self, v: float) -> None:
        with self._lk:
            self._hist.record(max(1, int(v)))

    def render(self) -> dict:
        with self._lk:
            h = self._hist
            vals, stddev = h.snapshot([p for p, _ in self.PCTS])
            out = {"min": h.min_v, "max": h.max_v,
                   "avg": int(h.mean()), "sum": h.sum_v, "cnt": h.total,
                   "stddev": int(stddev), "hdrsize": h.memsize,
                   "outofrange": h.out_of_range}
            for (_pct, name), v in zip(self.PCTS, vals):
                out[name] = v
        return out


# ------------------------------------------------------ registration --
def counter(name: str) -> Counter:
    c = _counters.get(name)
    if c is None:
        with _lock:
            c = _counters.setdefault(name, Counter(name))
    return c


def gauge(name: str) -> Gauge:
    g = _gauges.get(name)
    if g is None:
        with _lock:
            g = _gauges.setdefault(name, Gauge(name))
    return g


def window(name: str) -> Window:
    w = _windows.get(name)
    if w is None:
        with _lock:
            w = _windows.setdefault(name, Window(name))
    return w


def registered_count() -> int:
    with _lock:
        return len(_counters) + len(_gauges) + len(_windows)


# ---------------------------------------------------- enable/disable --
def enable() -> None:
    """Turn the registry on (refcounted, like trace.enable)."""
    global enabled, _enable_count
    with _lock:
        _enable_count += 1
        enabled = True


def disable() -> None:
    """Drop one reference; the last one turns recording off and clears
    the registry (asserted by the conftest leak fixture)."""
    global enabled, _enable_count
    with _lock:
        if _enable_count > 0:
            _enable_count -= 1
        if _enable_count == 0:
            enabled = False
            _counters.clear()
            _gauges.clear()
            _windows.clear()


# -------------------------------------------------------- rendering --
def snapshot() -> dict:
    """The whole registry under the versioned schema — embedded in the
    per-client stats blob (STATISTICS.md ``obs``) and in every
    ``bench.py --json`` artifact."""
    with _lock:
        counters = list(_counters.values())
        gauges = list(_gauges.values())
        windows = list(_windows.values())
    return {
        "schema": SCHEMA,
        "enabled": enabled,
        "counters": {c.name: c.value for c in counters},
        "gauges": {g.name: g.value for g in gauges},
        "windows": {w.name: w.render() for w in windows},
    }
