"""Flight-recorder tracing: per-thread event rings + Chrome trace export.

The stats JSON (client/stats.py, STATISTICS.md) answers "how fast is the
pipeline on average"; this module answers "where did THIS ticket spend
its 800 microseconds".  The reference treats stats as a first-class
subsystem (rd_kafka_stats_emit_all, rdkafka.c:1473) but has no event
tracer — its nearest analog is the debug-context log stream (rdlog.c),
which serializes through one mutex and costs a format call per line.
This tracer is built for the deeply pipelined offload machine of
PRs 1-3, where the interesting latency lives BETWEEN threads (codec
worker -> engine dispatch -> device -> broker IO):

  * One fixed-size ring of events PER THREAD, written lock-free (each
    ring has a single writer; the GIL makes the index/slot stores safe
    to read from the dumper).  Recording never allocates beyond the
    event tuple and never blocks on another thread.
  * A module-level ``enabled`` flag: every hook site guards itself with
    ``if trace.enabled:`` so the disabled cost is ONE attribute load —
    measured against the hook count per message by the bench.py --smoke
    overhead gate (must stay < 2% of the produce budget).
  * Spans are Chrome "complete" events (ph="X"): the instrumentation
    site captures ``t0 = trace.now()`` and emits ONE event at resolve
    time with the computed duration — no begin/end pairing across the
    pipeline's thread hops.
  * Engine spans carry the ROUTING DECISION as args: ``device_launch``
    and ``readback`` stamp ``device=<id>`` (the dispatch lane's mesh
    device, or -1 for a whole-mesh sharded launch) plus
    ``sharded=bool``, so scripts/traceview.py and Perfetto can
    attribute launch latency per chip (ISSUE 6).
  * Flight recorder: on fatal error, CRC mismatch, or request timeout
    the last N events are auto-dumped to ``flight_dir`` (bounded per
    process) so the trace that EXPLAINS the failure survives it.

Export is the Chrome trace-event JSON array format — load with Perfetto
(https://ui.perfetto.dev), chrome://tracing, or scripts/traceview.py
offline.  See TRACING.md for the workflow.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Optional

#: master switch — hook sites check THIS attribute inline
#: (``if trace.enabled: trace.complete(...)``), so a disabled build
#: pays one module-attribute load per hook site and nothing else
enabled = False

#: auto-dump the rings on fatal error / CRC mismatch / request timeout
dump_on_fatal = True

#: per-thread ring capacity (events); power of two (conf-validated)
ring_events = 8192

#: where flight dumps land (default: the system temp dir)
flight_dir: Optional[str] = None

#: path of the most recent flight dump (test/diagnostic hook)
last_flight_path: Optional[str] = None

#: flight dumps are bounded per process: a CRC-mismatch storm must not
#: turn the tracer into a disk-filling loop
FLIGHT_MAX_DUMPS = 8

#: cross-process flow sampling (ISSUE 20): hot paths emit ``flow_*``
#: instants keyed by (topic, partition, offset) for offsets where
#: ``offset % flow_sample_every == 0`` (0 disables); obs/collect.py
#: stitches the produce->ack->fetch->deliver chain across processes
flow_sample_every = 64

_lock = threading.Lock()
_enable_count = 0            # enable()/disable() refcount (N clients)
_generation = 0              # bumped per enable cycle; stale rings die
_rings: list["_Ring"] = []   # registry (dump/flight iterate a snapshot)
_local = threading.local()
_flight_count = 0


class _Ring:
    """Fixed-capacity event ring with a single writer (its thread).

    Events are tuples ``(ts_ns, cat, name, ph, dur_ns, args)`` stored
    into a preallocated slot list; the write index wraps with a power-
    of-two mask.  Readers (dump/flight) take a GIL-consistent snapshot
    — a concurrently-written slot shows either the old or the new
    tuple, never a torn one."""

    __slots__ = ("tid", "thread_name", "gen", "cap", "_mask", "_buf",
                 "_pos")

    def __init__(self, cap: int, gen: int):
        self.tid = threading.get_ident()
        self.thread_name = threading.current_thread().name
        self.gen = gen
        self.cap = cap
        self._mask = cap - 1
        self._buf: list = [None] * cap
        self._pos = 0

    def append(self, ev: tuple) -> None:
        i = self._pos
        self._buf[i & self._mask] = ev
        self._pos = i + 1

    def snapshot(self) -> list[tuple]:
        """Events in write order, oldest first."""
        pos = self._pos
        buf = list(self._buf)          # GIL-atomic slot copies
        if pos <= self.cap:
            out = buf[:pos]
        else:
            i = pos & self._mask
            out = buf[i:] + buf[:i]
        return [e for e in out if e is not None]


def now() -> int:
    """Monotonic nanoseconds — the trace timebase."""
    return time.monotonic_ns()


def _get_ring() -> _Ring:
    ring = getattr(_local, "ring", None)
    if ring is None or ring.gen != _generation:
        ring = _Ring(ring_events, _generation)
        _local.ring = ring
        with _lock:
            if ring.gen == _generation:     # enable state didn't move
                _rings.append(ring)
    return ring


# ------------------------------------------------------------ recording --
def evt(cat: str, name: str, ph: str = "i", ts: Optional[int] = None,
        dur: int = 0, args: Optional[dict] = None) -> None:
    """Generic event append (ph: Chrome phase — "X" span, "i" instant).
    Callers on hot paths must guard with ``if trace.enabled:``; this
    re-checks only to stay safe against a concurrent disable()."""
    if not enabled:
        return
    _get_ring().append((now() if ts is None else ts, cat, name, ph,
                        dur, args))


def complete(cat: str, name: str, t0_ns: int,
             args: Optional[dict] = None) -> None:
    """One span (ph="X") from ``t0_ns`` (a prior ``trace.now()``) to
    now — the workhorse: instrumentation sites stamp t0 at submit and
    emit the whole span at resolve time, so spans that cross thread
    hops need no begin/end pairing."""
    if not enabled:
        return
    t1 = now()
    _get_ring().append((t0_ns, cat, name, "X", t1 - t0_ns, args))


def instant(cat: str, name: str, args: Optional[dict] = None) -> None:
    if not enabled:
        return
    _get_ring().append((now(), cat, name, "i", 0, args))


# ------------------------------------------------------- enable/disable --
def enable(ring: Optional[int] = None, on_fatal: Optional[bool] = None,
           dump_dir: Optional[str] = None) -> None:
    """Turn tracing on (refcounted: each client that set trace.enable
    holds one reference; the last disable() clears the rings)."""
    global enabled, ring_events, dump_on_fatal, flight_dir
    global _enable_count, _generation, _flight_count
    with _lock:
        if ring is not None:
            r = int(ring)
            if r < 64 or (r & (r - 1)):
                raise ValueError(
                    f"trace ring capacity must be a power of two >= 64, "
                    f"got {r}")
            ring_events = r
        if on_fatal is not None:
            dump_on_fatal = bool(on_fatal)
        if dump_dir is not None:
            flight_dir = dump_dir
        if _enable_count == 0:
            _generation += 1
            _flight_count = 0
            _rings.clear()
        _enable_count += 1
        enabled = True


def disable() -> None:
    """Drop one enable() reference; the last one turns tracing off and
    releases every ring (the conftest leak fixture asserts this)."""
    global enabled, _enable_count
    with _lock:
        if _enable_count > 0:
            _enable_count -= 1
        if _enable_count == 0:
            enabled = False
            _rings.clear()


def active_ring_count() -> int:
    with _lock:
        return len(_rings)


# ----------------------------------------------------------------- dump --
def _collect() -> list[dict]:
    """All rings' events as Chrome trace-event dicts, sorted by ts.
    Rings of exited threads are kept — a dead broker thread's trail is
    exactly what a flight dump needs; disable() frees everything."""
    with _lock:
        rings = list(_rings)
    pid = os.getpid()
    out = []
    for r in rings:
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": r.tid, "args": {"name": r.thread_name}})
        for ts_ns, cat, name, ph, dur_ns, args in r.snapshot():
            e = {"name": name, "cat": cat, "ph": ph, "pid": pid,
                 "tid": r.tid, "ts": ts_ns / 1e3}
            if ph == "X":
                e["dur"] = dur_ns / 1e3
            elif ph == "i":
                e["s"] = "t"
            if args:
                e["args"] = args
            out.append(e)
    out.sort(key=lambda e: e.get("ts", 0))
    return out


def collect_events() -> list[dict]:
    """Public snapshot of every ring as Chrome trace-event dicts —
    the cross-process collection payload (obs/collect.py): workers,
    relays and the rig supervisor ship THIS inline over their control
    channels instead of a file path."""
    return _collect()


def dump(path: str) -> int:
    """Write every ring's events as Chrome trace-event JSON (Perfetto /
    chrome://tracing / scripts/traceview.py). Returns the event count
    (metadata records excluded)."""
    events = _collect()
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return sum(1 for e in events if e["ph"] != "M")


def flight_record(reason: str) -> Optional[str]:
    """Flight-recorder dump: called from the fatal-error, CRC-mismatch
    and request-timeout paths (kafka.set_fatal_error, the fetch verify
    resolvers, broker._scan_timeouts).  Bounded per process; returns
    the dump path or None (disabled / bound reached / IO error)."""
    global _flight_count, last_flight_path
    if not (enabled and dump_on_fatal):
        return None
    with _lock:
        if _flight_count >= FLIGHT_MAX_DUMPS:
            return None
        _flight_count += 1
        n = _flight_count
    safe = "".join(c if c.isalnum() or c in "-_." else "-"
                   for c in reason)[:64]
    d = flight_dir or tempfile.gettempdir()
    path = os.path.join(d, f"tk_flight_{os.getpid()}_{n}_{safe}.json")
    try:
        instant("flight", "flight_record", {"reason": reason})
        dump(path)
    except OSError:
        return None
    last_flight_path = path
    return path
