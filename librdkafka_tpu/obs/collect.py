"""Cross-process trace collection: merge per-process flight-recorder
dumps into ONE Perfetto-loadable timeline (ISSUE 20).

obs/trace.py stops at the process boundary — each fleet worker, the
rig supervisor and every broker relay runs its own rings stamped with
its own ``time.monotonic_ns()``.  This module is the other half:

  * **Clock alignment.**  Every collection channel (worker stdin/
    stdout, rig control socket, relay stdin) does a request/response
    offset exchange: the collector stamps ``t_send``, the peer replies
    with its own ``mono_ns``, the collector stamps ``t_recv``.  The
    peer's clock read happened somewhere inside the round trip, so

        offset = peer_mono - (t_send + t_recv) / 2
        err    = (t_recv - t_send) / 2

    maps peer timestamps into the collector's timebase with a bounded
    error of half the round trip (on Linux CLOCK_MONOTONIC is machine-
    wide, so offsets measure ~0 — the exchange is what PROVES it, and
    keeps the merge correct on any future multi-host topology).

  * **Merge.**  :func:`merge` shifts every event by its process's
    offset, injects ``process_name`` metadata per pid (Perfetto's
    process rail labels) and returns one ts-sorted event list.

  * **Flow stitching.**  Hot paths emit sampled ``flow_*`` instants
    keyed by ``(topic, partition, offset)`` (trace.flow_sample_every);
    :func:`stitch_flows` connects each key's produce -> ack -> fetch ->
    deliver points with Chrome flow events (ph "s"/"t"/"f"), so one
    record's cross-process journey renders as a linked arrow chain.

Temp dump directories handed out by :func:`make_dump_dir` are
registered so the conftest leak fixture can fail any test that loses
one (the fleet driver releases its directory in ``stop()``).
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
from typing import Optional

#: stage order of the per-record flow points (trace instants emitted by
#: client/kafka.py + client/broker.py under trace.flow_sample_every)
FLOW_STAGES = ("flow_produce", "flow_ack", "flow_fetch", "flow_deliver")

_lock = threading.Lock()
_dump_dirs: set[str] = set()


# ------------------------------------------------------ dump dirs --
def make_dump_dir(prefix: str = "tk_obs_") -> str:
    """A registered temp directory for flight dumps / ring dumps; the
    owner must release it (conftest fails leaked ones)."""
    d = tempfile.mkdtemp(prefix=prefix)
    with _lock:
        _dump_dirs.add(d)
    return d


def release_dump_dir(path: str) -> None:
    with _lock:
        _dump_dirs.discard(path)
    shutil.rmtree(path, ignore_errors=True)


def active_dump_dir_count() -> int:
    with _lock:
        return len(_dump_dirs)


# -------------------------------------------------- clock alignment --
def align_offset(t_send_ns: int, peer_mono_ns: int,
                 t_recv_ns: int) -> tuple[int, int]:
    """(offset_ns, err_ns) mapping the peer's monotonic clock into the
    collector's: ``collector_ts = peer_ts + offset_ns``, accurate to
    +/- err_ns (half the observed round trip)."""
    mid = (t_send_ns + t_recv_ns) // 2
    return mid - peer_mono_ns, (t_recv_ns - t_send_ns) // 2


class ProcessDump:
    """One process's contribution: its Chrome events plus the clock
    mapping computed from the collection channel's offset exchange."""

    __slots__ = ("name", "pid", "events", "offset_ns", "err_ns")

    def __init__(self, name: str, pid: int, events: list,
                 offset_ns: int = 0, err_ns: int = 0):
        self.name = name
        self.pid = pid
        self.events = events
        self.offset_ns = offset_ns
        self.err_ns = err_ns


# ------------------------------------------------------------ merge --
def merge(dumps: list[ProcessDump]) -> list[dict]:
    """One ts-sorted Chrome event list across processes: every event
    shifted into the collector's timebase, one ``process_name``
    metadata record per pid, per-process ``clock_err_us`` recorded as
    an arg on the metadata so the bound survives into the artifact."""
    out: list[dict] = []
    for d in dumps:
        off_us = d.offset_ns / 1e3
        out.append({"name": "process_name", "ph": "M", "pid": d.pid,
                    "tid": 0,
                    "args": {"name": d.name,
                             "clock_offset_us": round(off_us, 3),
                             "clock_err_us": round(d.err_ns / 1e3, 3)}})
        for e in d.events:
            e = dict(e)
            e["pid"] = d.pid
            if "ts" in e:
                e["ts"] = e["ts"] + off_us
            out.append(e)
    out.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    return out


# ---------------------------------------------------- flow stitching --
def _flow_key(e: dict) -> Optional[tuple]:
    a = e.get("args") or {}
    if "topic" in a and "partition" in a and "offset" in a:
        return (a["topic"], a["partition"], a["offset"])
    return None


def stitch_flows(events: list[dict]) -> tuple[list[dict], int]:
    """Synthesize Chrome flow events linking each sampled record's
    ``flow_*`` instants in FLOW_STAGES order across processes.

    Returns ``(events + flow events, n_links)`` where a "link" is one
    arrow between two consecutive stitched points.  Points are matched
    purely by ``(topic, partition, offset)`` — the producer and the
    consumer never coordinated beyond the record itself."""
    stage_rank = {n: i for i, n in enumerate(FLOW_STAGES)}
    chains: dict[tuple, list[dict]] = {}
    for e in events:
        if e.get("ph") == "i" and e.get("name") in stage_rank:
            k = _flow_key(e)
            if k is not None:
                chains.setdefault(k, []).append(e)
    flows: list[dict] = []
    links = 0
    fid = 0
    for k in sorted(chains, key=lambda kk: (str(kk[0]), kk[1], kk[2])):
        pts = sorted(chains[k], key=lambda e: (stage_rank[e["name"]],
                                               e.get("ts", 0)))
        if len(pts) < 2:
            continue
        fid += 1
        links += len(pts) - 1
        for i, p in enumerate(pts):
            ph = "s" if i == 0 else ("f" if i == len(pts) - 1 else "t")
            f = {"name": "record_flow", "cat": "flow", "ph": ph,
                 "id": fid, "pid": p["pid"], "tid": p.get("tid", 0),
                 "ts": p.get("ts", 0),
                 "args": {"topic": k[0], "partition": k[1],
                          "offset": k[2], "stage": p["name"]}}
            if ph == "f":
                f["bp"] = "e"
            flows.append(f)
    return events + flows, links


def flow_link_count(events: list[dict]) -> int:
    """Arrows already stitched into ``events`` (ph s/t/f count minus
    one per flow id) — the acceptance probe for merged artifacts."""
    per_id: dict = {}
    for e in events:
        if e.get("ph") in ("s", "t", "f") and e.get("cat") == "flow":
            per_id[e["id"]] = per_id.get(e["id"], 0) + 1
    return sum(n - 1 for n in per_id.values() if n > 1)


# ------------------------------------------------------------ write --
def write(path: str, events: list[dict]) -> int:
    """Perfetto-loadable Chrome trace JSON; returns the non-metadata
    event count (same contract as trace.dump)."""
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return sum(1 for e in events if e.get("ph") != "M")
