"""Offset-based socket buffer helpers (broker transport + mock cluster).

The reference sends straight from segmented buffers via iovecs
(rd_kafka_transport_socket_sendmsg, rdkafka_transport.c:109).  The
Python analog keeps one bytearray per connection and consumes it by
OFFSET: the previous ``del buf[:n]`` pattern memmoved the whole
remaining buffer once per socket chunk (~16MB of GIL-held shifting per
1MB batch).

The memoryview discipline here is load-bearing: a raising ``send()``
pins the traceback — and with it any live buffer export — so the chunk
view must be released in a ``finally`` or a later ``buf.clear()``
raises BufferError.
"""
from __future__ import annotations

import ssl as _ssl
import struct
from typing import Optional

#: consumed-prefix size at which the buffer is compacted even though it
#: has not fully drained (sustained backpressure must not retain every
#: byte ever sent)
COMPACT_THRESHOLD = 1 << 20

_WOULD_BLOCK = (_ssl.SSLWantReadError, _ssl.SSLWantWriteError,
                BlockingIOError, InterruptedError)


def send_from(sock, buf: bytearray,
              off: int) -> tuple[int, bool, Optional[OSError]]:
    """Send buf[off:]; returns (new_off, blocked, error)."""
    err: Optional[OSError] = None
    blocked = False
    mv = memoryview(buf)
    try:
        total = len(mv)
        while off < total:
            chunk = mv[off:]
            try:
                off += sock.send(chunk)
            except _WOULD_BLOCK:
                blocked = True
                break
            except OSError as e:
                err = e
                break
            finally:
                chunk.release()
    finally:
        mv.release()
    return off, blocked, err


def compact_consumed(buf: bytearray, off: int) -> int:
    """Reclaim the consumed prefix; returns the new offset."""
    if off >= len(buf):
        buf.clear()
        return 0
    if off >= COMPACT_THRESHOLD:
        del buf[:off]
        return 0
    return off


class SegWriter:
    """Segment-queue socket write buffer — the actual iovec analog of
    the reference's rd_kafka_transport_socket_sendmsg
    (rdkafka_transport.c:109): request segments (small SegBuf header
    chunks + large spliced RecordBatch bytes) queue WITHOUT being
    copied into one flat buffer, and drain via ``sendmsg`` scatter-
    gather on plain sockets (per-segment ``send`` on TLS / wrapped
    sockets, which lack sendmsg).

    ``queued_total`` / ``sent_total`` are monotonic byte counters — the
    request-boundary bookkeeping (_unsent_req_ends) keys off them."""

    __slots__ = ("_segs", "_off", "queued_total", "sent_total")

    #: max iovecs per sendmsg call (well under any platform IOV_MAX)
    MAX_IOV = 64

    def __init__(self):
        from collections import deque
        self._segs: "deque[memoryview]" = deque()
        self._off = 0                  # consumed prefix of _segs[0]
        self.queued_total = 0
        self.sent_total = 0

    def append(self, segs) -> int:
        """Queue buffer segments (bytes/bytearray/memoryview); returns
        the bytes queued."""
        n = 0
        segq = self._segs
        for s in segs:
            ln = len(s)
            if ln:
                segq.append(s if isinstance(s, memoryview)
                            else memoryview(s))
                n += ln
        self.queued_total += n
        return n

    def pending(self) -> int:
        return self.queued_total - self.sent_total

    def clear(self) -> None:
        for s in self._segs:
            s.release()
        self._segs.clear()
        self._off = 0
        self.queued_total = 0
        self.sent_total = 0

    def _advance(self, n: int) -> None:
        self.sent_total += n
        segq = self._segs
        off = self._off + n
        while segq and off >= len(segq[0]):
            off -= len(segq[0])
            segq.popleft().release()
        self._off = off

    def send(self, sock) -> tuple[int, bool, Optional[OSError]]:
        """Drain as much as the socket accepts; returns
        (bytes_sent_now, blocked, error)."""
        sent = 0
        blocked = False
        err: Optional[OSError] = None
        use_sendmsg = (not isinstance(sock, _ssl.SSLSocket)
                       and hasattr(sock, "sendmsg"))
        segq = self._segs
        while segq:
            try:
                if use_sendmsg:
                    iov = []
                    off = self._off
                    for s in segq:
                        iov.append(s[off:] if off else s)
                        off = 0
                        if len(iov) >= self.MAX_IOV:
                            break
                    n = sock.sendmsg(iov)
                else:
                    head = segq[0]
                    n = sock.send(head[self._off:] if self._off else head)
            except _WOULD_BLOCK:
                blocked = True
                break
            except OSError as e:
                err = e
                break
            if n <= 0:
                blocked = True
                break
            self._advance(n)
            sent += n
        return sent, blocked, err


def extract_frames(buf: bytearray,
                   max_bytes: Optional[int] = None
                   ) -> tuple[list[bytes], Optional[int]]:
    """Pop every complete 4-byte-length-prefixed frame off the front of
    ``buf`` (ONE compaction per call).  Returns (frames, bad_size):
    bad_size is the offending length when a frame exceeds max_bytes or
    is negative — the caller decides how to die."""
    frames: list[bytes] = []
    off = 0
    blen = len(buf)
    while blen - off >= 4:
        (n,) = struct.unpack_from(">i", buf, off)
        if n < 0 or (max_bytes is not None and n > max_bytes):
            if off:
                del buf[:off]
            return frames, n
        if blen - off < 4 + n:
            break
        frames.append(bytes(buf[off + 4:off + 4 + n]))
        off += 4 + n
    if off:
        del buf[:off]
    return frames, None
