"""CRC32C (Castagnoli) and CRC32 — bit-exact with the reference.

The reference implements CRC32C in src/crc32c.c (sw table + SSE4.2 hw path,
unit test vectors at crc32c.c:388) for the MessageSet v2 batch checksum, and
zlib-poly CRC32 (src/rdcrc32.c) for legacy MsgVer0/1 messages.

This module provides:

- ``crc32c(data, crc=0)`` — pure-Python/numpy reference implementation
  (the native C++ provider in ops/native is the fast CPU path).
- ``crc32c_combine(crc_a, crc_b, len_b)`` — GF(2) matrix-power combine, so
  CRCs of adjacent chunks can be merged: this is what makes the checksum
  *parallelizable* — chunk CRCs computed independently (across TPU lanes or
  mesh devices) are folded with an associative combine, the TPU analog of
  the hw-pipelined path in crc32c.c:39.
- Kafka conventions: the v2 record-batch CRC is CRC32C over the batch from
  the Attributes offset onward (RD_KAFKAP_MSGSET_V2_OF_Attributes,
  src/rdkafka_proto.h), stored big-endian unsigned.
"""
from __future__ import annotations

import zlib

import numpy as np

CRC32C_POLY = 0x82F63B78  # reflected Castagnoli polynomial


def _make_table(poly: int) -> np.ndarray:
    table = np.empty(256, dtype=np.uint32)
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (poly if crc & 1 else 0)
        table[i] = crc
    return table


_TABLE = _make_table(CRC32C_POLY)
# Slice-by-8 tables: TABLE8[k][b] = crc of byte b advanced through k+1 zero bytes.
_TABLE8 = np.empty((8, 256), dtype=np.uint32)
_TABLE8[0] = _TABLE
for _k in range(1, 8):
    _TABLE8[_k] = _TABLE[_TABLE8[_k - 1] & 0xFF] ^ (_TABLE8[_k - 1] >> 8)

_T = [t.tolist() for t in _TABLE8]  # python lists are faster to index scalar-wise


def crc32c(data, crc: int = 0) -> int:
    """CRC32C of ``data``, continuing from ``crc`` (pre/post inverted)."""
    crc = (~crc) & 0xFFFFFFFF
    buf = bytes(data)
    n = len(buf)
    t0, t1, t2, t3, t4, t5, t6, t7 = _T
    i = 0
    # slice-by-8 main loop
    while n - i >= 8:
        crc ^= buf[i] | (buf[i + 1] << 8) | (buf[i + 2] << 16) | (buf[i + 3] << 24)
        crc = (t7[crc & 0xFF] ^ t6[(crc >> 8) & 0xFF]
               ^ t5[(crc >> 16) & 0xFF] ^ t4[(crc >> 24) & 0xFF]
               ^ t3[buf[i + 4]] ^ t2[buf[i + 5]]
               ^ t1[buf[i + 6]] ^ t0[buf[i + 7]])
        i += 8
    while i < n:
        crc = t0[(crc ^ buf[i]) & 0xFF] ^ (crc >> 8)
        i += 1
    return (~crc) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# GF(2) combine: crc(A||B) from crc(A), crc(B), len(B).
# Shifting a CRC register through one zero *bit* is a linear map over GF(2);
# we exponentiate the one-byte map to len_b bytes by repeated squaring.
# ---------------------------------------------------------------------------

def _gf2_matrix_times(mat: list[int], vec: int) -> int:
    out = 0
    i = 0
    while vec:
        if vec & 1:
            out ^= mat[i]
        vec >>= 1
        i += 1
    return out


def _gf2_matrix_square(mat: list[int]) -> list[int]:
    return [_gf2_matrix_times(mat, mat[i]) for i in range(32)]


def _zero_operator(poly: int) -> list[list[int]]:
    """Precompute matrices M[k] advancing a CRC through 2^k zero bytes."""
    # one-bit shift operator
    odd = [poly] + [1 << (i - 1) for i in range(1, 32)]
    even = _gf2_matrix_square(odd)   # 2 bits
    odd2 = _gf2_matrix_square(even)  # 4 bits
    m = _gf2_matrix_square(odd2)     # 8 bits = 1 zero byte: M[0]
    mats = [m]
    for _ in range(63):
        m = _gf2_matrix_square(m)
        mats.append(m)
    return mats


_ZERO_OP_C = _zero_operator(CRC32C_POLY)
_ZERO_OP_Z = _zero_operator(0xEDB88320)


def _combine(crc_a: int, crc_b: int, len_b: int, mats: list[list[int]]) -> int:
    if len_b == 0:
        return crc_a
    k = 0
    while len_b:
        if len_b & 1:
            crc_a = _gf2_matrix_times(mats[k], crc_a)
        len_b >>= 1
        k += 1
    return (crc_a ^ crc_b) & 0xFFFFFFFF


def crc32c_combine(crc_a: int, crc_b: int, len_b: int) -> int:
    """CRC32C of concat(A, B) given crc32c(A), crc32c(B), len(B)."""
    return _combine(crc_a, crc_b, len_b, _ZERO_OP_C)


def crc32_combine(crc_a: int, crc_b: int, len_b: int) -> int:
    """zlib-poly CRC32 combine (equivalent of zlib.crc32_combine)."""
    return _combine(crc_a, crc_b, len_b, _ZERO_OP_Z)


def crc32(data, crc: int = 0) -> int:
    """Legacy MsgVer0/1 per-message CRC (zlib polynomial, src/rdcrc32.c)."""
    return zlib.crc32(bytes(data), crc) & 0xFFFFFFFF


#: The byte-advance operator matrices, exported for the JAX kernel
#: (ops/crc_jax.py) which implements the same combine vectorized on TPU.
ZERO_OP_CRC32C = np.array(_ZERO_OP_C, dtype=np.uint32)  # [64][32]
TABLE_CRC32C = _TABLE8  # [8][256] uint32
#: zlib-polynomial twins, for the legacy MsgVer0/1 per-message CRC
#: (reference: src/rdcrc32.c) on the same MXU kernel.
ZERO_OP_CRC32 = np.array(_ZERO_OP_Z, dtype=np.uint32)   # [64][32]
TABLE_CRC32 = _make_table(0xEDB88320)                   # [256] uint32
