"""Segmented zero-copy buffer + read slice.

The rebuild of the reference's single most load-bearing internal API, the
segmented grow-only buffer of src/rdbuf.c (1598 LoC): a chain of segments
where writers can append, rewind (rd_buf_write_seek, rdbuf.c:603),
back-patch earlier bytes (rd_buf_write_update, rdbuf.c:536), and splice in
*read-only referenced* segments without copying (rd_buf_push, rdbuf.c:563)
— which is how compressed MessageSet output replaces the uncompressed
records in place, both on the CPU path and when DMA'd back from the TPU
sidecar. Readers use a cheap ``Slice`` cursor that can narrow to nested
regions (rd_slice_narrow*, rdbuf.c:982) and export iovecs for scatter-
gather socket IO (rd_slice_get_iov, rdbuf.c:1059).
"""
from __future__ import annotations

import struct
from typing import Iterable, Optional

from .crc import crc32, crc32c
from . import varint


class SegBuf:
    """Grow-only segmented write buffer."""

    __slots__ = ("_segs", "_len")

    def __init__(self):
        self._segs: list[bytearray | bytes] = []
        self._len = 0

    def __len__(self) -> int:
        return self._len

    # -- writing ----------------------------------------------------------
    def write(self, data) -> int:
        """Append bytes; returns the absolute offset they were written at."""
        pos = self._len
        if data:
            if self._segs and isinstance(self._segs[-1], bytearray):
                self._segs[-1] += data
            else:
                self._segs.append(bytearray(data))
            self._len += len(data)
        return pos

    def push_ro(self, data) -> int:
        """Splice a read-only segment (no copy) — bytes, bytearray or
        memoryview are kept by reference. Reference: rd_buf_push
        (rdbuf.c:563); this is how a finished RecordBatch rides inside
        a ProduceRequest without being re-copied."""
        pos = self._len
        if len(data):
            # a caller-owned bytearray is wrapped in a memoryview so
            # write() can never extend it in place
            self._segs.append(memoryview(data)
                              if isinstance(data, bytearray) else data)
            self._len += len(data)
        return pos

    def write_seek(self, pos: int) -> None:
        """Rewind the write position, discarding bytes at >= pos."""
        if pos > self._len or pos < 0:
            raise ValueError(f"write_seek({pos}) out of range 0..{self._len}")
        drop = self._len - pos
        while drop:
            seg = self._segs[-1]
            if len(seg) <= drop:
                drop -= len(seg)
                self._segs.pop()
            else:
                keep = len(seg) - drop
                if isinstance(seg, bytearray):
                    del seg[keep:]
                else:  # copy-on-truncate for ro (bytes/memoryview) segment
                    self._segs[-1] = bytearray(seg[:keep])
                drop = 0
        self._len = pos

    def write_update(self, pos: int, data: bytes) -> None:
        """Back-patch ``data`` over bytes previously written at ``pos``.

        Reference: rd_buf_write_update (rdbuf.c:536), used to finalize
        MessageSet headers (length/CRC/attributes) after the records are
        known.
        """
        end = pos + len(data)
        if end > self._len:
            raise ValueError("write_update beyond written length")
        off = 0
        di = 0
        for i, seg in enumerate(self._segs):
            seg_end = off + len(seg)
            if seg_end > pos and off < end:
                s = max(pos, off) - off
                e = min(end, seg_end) - off
                n = e - s
                if not isinstance(seg, bytearray):  # ro: copy-on-write
                    seg = bytearray(seg)
                    self._segs[i] = seg
                seg[s:e] = data[di:di + n]
                di += n
            off = seg_end
            if off >= end:
                break

    # -- struct helpers (big-endian, Kafka wire order) ---------------------
    def write_i8(self, v): return self.write(struct.pack(">b", v))
    def write_i16(self, v): return self.write(struct.pack(">h", v))
    def write_i32(self, v): return self.write(struct.pack(">i", v))
    def write_u32(self, v): return self.write(struct.pack(">I", v & 0xFFFFFFFF))
    def write_i64(self, v): return self.write(struct.pack(">q", v))
    def write_varint(self, v): return self.write(varint.enc_i64(v))
    def write_uvarint(self, v): return self.write(varint.enc_u64(v))

    def update_i32(self, pos, v): self.write_update(pos, struct.pack(">i", v))
    def update_u32(self, pos, v): self.write_update(pos, struct.pack(">I", v & 0xFFFFFFFF))
    def update_i64(self, pos, v): self.write_update(pos, struct.pack(">q", v))
    def update_i16(self, pos, v): self.write_update(pos, struct.pack(">h", v))
    def update_i8(self, pos, v): self.write_update(pos, struct.pack(">b", v))

    # -- reading out ------------------------------------------------------
    def as_bytes(self, start: int = 0, end: Optional[int] = None) -> bytes:
        end = self._len if end is None else end
        if len(self._segs) == 1 and start == 0 and end == self._len:
            return bytes(self._segs[0])
        out = bytearray()
        off = 0
        for seg in self._segs:
            seg_end = off + len(seg)
            if seg_end > start and off < end:
                out += seg[max(start, off) - off:min(end, seg_end) - off]
            off = seg_end
            if off >= end:
                break
        return bytes(out)

    def iovecs(self) -> list[memoryview]:
        """Segment views for scatter-gather sendmsg (rd_buf_get_write_iov)."""
        return [memoryview(s) for s in self._segs if len(s)]

    def slice(self, start: int = 0, end: Optional[int] = None) -> "Slice":
        return Slice(self.as_bytes(start, end))

    def crc32c(self, start: int, end: Optional[int] = None) -> int:
        """CRC32C over a written region (rd_slice_crc32c, rdbuf.c:1113)."""
        return crc32c(self.as_bytes(start, end))


class Slice:
    """Read cursor over a contiguous byte region, with narrowing.

    Reference: rd_slice_t (rdbuf.h) — all response/MessageSet parsing goes
    through this, with underflow raising rather than reading garbage (the
    declarative-macro goto err_parse strategy of rdkafka_buf.h:162).
    """

    __slots__ = ("_mv", "_pos", "_end")

    def __init__(self, data, start: int = 0, end: Optional[int] = None):
        self._mv = memoryview(data) if not isinstance(data, memoryview) else data
        self._pos = start
        self._end = len(self._mv) if end is None else end
        if not (0 <= start <= self._end <= len(self._mv)):
            raise ValueError("bad slice bounds")

    def __len__(self) -> int:
        return self._end - self._pos

    @property
    def offset(self) -> int:
        return self._pos

    def remains(self) -> int:
        return self._end - self._pos

    def _need(self, n: int) -> None:
        if self._end - self._pos < n:
            raise BufUnderflow(
                f"buffer underflow: need {n} bytes, {self._end - self._pos} remain")

    def read(self, n: int) -> bytes:
        self._need(n)
        out = bytes(self._mv[self._pos:self._pos + n])
        self._pos += n
        return out

    def view(self, n: int) -> memoryview:
        self._need(n)
        out = self._mv[self._pos:self._pos + n]
        self._pos += n
        return out

    def skip(self, n: int) -> None:
        self._need(n)
        self._pos += n

    def peek_all(self) -> bytes:
        return bytes(self._mv[self._pos:self._end])

    def read_i8(self): return struct.unpack(">b", self.read(1))[0]
    def read_u8(self): return self.read(1)[0]
    def read_i16(self): return struct.unpack(">h", self.read(2))[0]
    def read_i32(self): return struct.unpack(">i", self.read(4))[0]
    def read_u32(self): return struct.unpack(">I", self.read(4))[0]
    def read_i64(self): return struct.unpack(">q", self.read(8))[0]

    def read_varint(self) -> int:
        v, n = varint.dec_i64(self._mv, self._pos)
        if self._pos + n > self._end:
            raise BufUnderflow("varint crosses slice end")
        self._pos += n
        return v

    def read_uvarint(self) -> int:
        v, n = varint.dec_u64(self._mv, self._pos)
        if self._pos + n > self._end:
            raise BufUnderflow("varint crosses slice end")
        self._pos += n
        return v

    def narrow(self, n: int) -> "Slice":
        """Sub-slice of the next n bytes; advances this cursor past them.

        Reference: rd_slice_narrow_copy + rd_slice_widen (rdbuf.c:982-1056),
        used for nested MessageSet / compressed-payload parsing.
        """
        self._need(n)
        sub = Slice(self._mv, self._pos, self._pos + n)
        self._pos += n
        return sub

    def crc32c(self, crc: int = 0) -> int:
        return crc32c(self._mv[self._pos:self._end], crc)

    def crc32(self, crc: int = 0) -> int:
        return crc32(self._mv[self._pos:self._end], crc)


class BufUnderflow(Exception):
    """Raised on short reads — the parse-error contract for all protocol code."""
