"""librdkafka_tpu.utils"""
