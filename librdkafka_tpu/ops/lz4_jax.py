"""LZ4 block encoder on TPU — bit-exact with the deterministic TPU-greedy
spec shared with ops/native/codec.cpp (tk_lz4_block_compress).

The reference compresses each MessageSet sequentially on the broker thread
(rdkafka_msgset_writer.c:1090 → vendored lz4.c). Its hash-chain match
search is a serial data dependence — useless on a systolic/vector machine.
The TPU-greedy spec was designed so the SAME wire bytes fall out of a
fully data-parallel formulation:

  * Insert-all rule: every position 0..P enters the hash table exactly once,
    in order, regardless of the parse. Hence
        candidate[p] = max { q < p : HASH(src[q:q+4]) == HASH(src[p:p+4]) }
    is parse-independent and computable for ALL positions at once with ONE
    stable argsort by hash (predecessor within equal-hash runs).
  * Match lengths: blocked longest-common-extension — compare 16-byte
    gathers per round, ≤ ceil(273/16)+1 rounds, all positions in parallel.
  * Greedy parse (p jumps by mlen on match, +1 otherwise) is a successor
    graph; the visited set is computed by pointer doubling in log2(N)
    scatter/gather rounds.
  * Token stream: per-sequence byte counts → exclusive scan for output
    offsets → every output byte is computed independently by binary-
    searching its sequence (searchsorted) and evaluating a closed-form
    (token | extension-run | literal gather | offset | match-extension).

Everything is static-shape, sort/scan/gather — XLA-friendly; batches of
blocks are vmapped on the leading axis (the per-toppar batch axis of
SURVEY.md §3.2).

The **fused compress→CRC** variant (ISSUE 17) appends the crc32c kernel
(ops/crc32c_jax.py) to the same launch: one dispatch + one readback
yields the compressed rows AND the checksums of both the compressed and
the raw bytes, so the MessageSet v2 batch CRC can be folded host-side
with crc32c_combine without ever re-scanning the frame.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

from .crc32c_jax import _crc_kernel, _dev_key, _pick_kl, _shift_tables
from .packing import next_pow2, pad_right

I32 = jnp.int32
U32 = jnp.uint32

HASH_BITS = 12
MAXMATCH = 273
MINMATCH = 4


def _bound(n: int) -> int:
    return n + n // 255 + 16


def _extlen(L):
    """Number of length-extension bytes for a literal/match run field."""
    return jnp.where(L >= 15, (L - 15) // 255 + 1, 0)


def _lz4_block_one(data, n, N: int):
    """Compress one (N,)-uint8 buffer of true length n. → ((C,) uint8, len)."""
    C = _bound(N)
    D = N + 2                       # dense sequence-table size (+pseudo, +junk)
    pos = jnp.arange(N, dtype=I32)
    n = n.astype(I32)

    # --- 4-byte little-endian values and hashes at every position --------
    def at(off):
        return data[jnp.clip(pos + off, 0, N - 1)].astype(U32)

    val = at(0) | (at(1) << 8) | (at(2) << 16) | (at(3) << 24)
    h = (val * U32(2654435761)) >> U32(32 - HASH_BITS)

    # --- candidate[p]: predecessor with equal hash --------------------
    # one single-array sort of unique composite keys (hash<<17 | pos)
    # reproduces the stable (hash, pos) order at a fraction of the
    # argsort/pair-sort compile cost (the 64K sort dominated the 35 s
    # XLA compile of the original formulation)
    assert N <= (1 << 17)
    key = (h.astype(I32) << 17) | pos
    skey = jax.lax.sort(key)
    order = skey & ((1 << 17) - 1)
    h_sorted = skey >> 17
    prev_pos = jnp.concatenate([jnp.full((1,), -1, I32), order[:-1]])
    same = jnp.concatenate([jnp.zeros((1,), bool), h_sorted[1:] == h_sorted[:-1]])
    cand_sorted = jnp.where(same, prev_pos, -1)
    cand = jnp.zeros((N,), I32).at[order].set(cand_sorted)

    valid = ((cand >= 0) & (pos - cand <= 65535)
             & (val[jnp.clip(cand, 0, N - 1)] == val)
             & (pos + 12 <= n))

    # --- match lengths: blocked LCE, 16 bytes per round ------------------
    mmax = jnp.minimum(MAXMATCH, n - 5 - pos)
    k16 = jnp.arange(16, dtype=I32)

    def g16(base):
        return data[jnp.clip(base[:, None] + k16[None, :], 0, N - 1)]

    def lce_cond(st):
        return jnp.any(st[1])

    def lce_body(st):
        mlen, active = st
        neq = g16(cand + mlen) != g16(pos + mlen)
        run = jnp.where(neq.any(1), jnp.argmax(neq, 1).astype(I32), I32(16))
        add = jnp.where(active, jnp.minimum(run, mmax - mlen), 0)
        mlen = mlen + add
        active = active & (run == 16) & (mlen < mmax)
        return mlen, active

    mlen0 = jnp.where(valid, I32(MINMATCH), I32(0))
    mlen, _ = jax.lax.while_loop(lce_cond, lce_body,
                                 (mlen0, valid & (mlen0 < mmax)))

    # --- greedy parse via pointer doubling -------------------------------
    # fori_loop keeps the graph one-round-sized (the unrolled version
    # cost ~35 s of XLA compile for N=64K)
    sink = I32(N + 1)
    nxt = jnp.where(valid, pos + mlen, pos + 1)
    jump = jnp.where(pos + 12 <= n, jnp.minimum(nxt, sink), sink)
    J0 = jnp.concatenate([jump, jnp.full((2,), sink, I32)])    # (N+2,)
    on0 = jnp.zeros((N + 2,), bool).at[0].set(True)

    def pd_round(_, st):
        on, J = st
        on = on.at[jnp.where(on, J, sink)].set(True)
        return on, J[J]

    rounds = int(np.ceil(np.log2(N + 2))) + 1
    on, _ = jax.lax.fori_loop(0, rounds, pd_round, (on0, J0))
    match_here = on[:N] & valid

    # --- anchors (end of previous match) and literal runs ----------------
    mend = jnp.where(match_here, pos + mlen, 0)
    cm = jax.lax.cummax(mend)
    anchor = jnp.concatenate([jnp.zeros((1,), I32), cm[:-1]])
    lit = pos - anchor
    final_anchor = cm[-1]
    final_lit = n - final_anchor

    # --- per-sequence output sizes and offsets ---------------------------
    el = _extlen(lit)
    em = _extlen(mlen - MINMATCH)
    sz = jnp.where(match_here, 1 + el + lit + 2 + em, 0)
    csum = jnp.cumsum(sz)
    out_off = csum - sz                 # exclusive
    total_seq = csum[-1]
    S = jnp.sum(match_here.astype(I32))
    efl = jnp.where(final_lit >= 15, (final_lit - 15) // 255 + 1, 0)
    total_out = total_seq + 1 + efl + final_lit

    # --- compact sequences into dense tables (+ pseudo-seq for final run)
    # one fused scatter builds all five tables (separate scatters were a
    # large share of the XLA compile budget)
    di = jnp.where(match_here, jnp.cumsum(match_here.astype(I32)) - 1, D - 1)
    junks = jnp.array([[int(C + 1)], [0], [0], [MINMATCH], [0]], I32)
    vals = jnp.stack([out_off, lit, anchor, mlen, pos - cand])     # (5, N)
    TBL = jnp.broadcast_to(junks, (5, D)).at[:, di].set(vals)
    TBL = TBL.at[:, D - 1].set(junks[:, 0])
    TBL = TBL.at[:3, S].set(jnp.stack([total_seq, final_lit, final_anchor]))
    # searchsorted needs OOF non-decreasing: real entries strictly increase,
    # pseudo = total_seq, padding = C+1.
    OOF = TBL[0]

    # --- materialize every output byte in parallel -----------------------
    j = jnp.arange(C, dtype=I32)
    i = jnp.searchsorted(OOF, j, side="right").astype(I32) - 1
    i = jnp.clip(i, 0, D - 1)
    G = TBL[:, i]                                                  # (5, C)
    r = j - G[0]
    L = G[1]
    elq = _extlen(L)
    A = G[2]
    M = G[3] - MINMATCH
    emq = _extlen(M)
    hasm = i < S
    token = (jnp.minimum(L, 15) << 4) | jnp.where(hasm, jnp.minimum(M, 15), 0)
    off = G[4]
    lit_start = 1 + elq
    lit_end = lit_start + L
    litb = data[jnp.clip(A + r - lit_start, 0, N - 1)].astype(I32)

    mk = r - lit_end - 1                # 1-based index into match-ext run
    byte = jnp.where(mk < emq, 255, (M - 15) % 255)
    byte = jnp.where(r == lit_end + 1, off >> 8, byte)
    byte = jnp.where(r == lit_end, off & 0xFF, byte)
    byte = jnp.where((r >= lit_start) & (r < lit_end), litb, byte)
    byte = jnp.where((r >= 1) & (r <= elq),
                     jnp.where(r < elq, 255, (L - 15) % 255), byte)
    byte = jnp.where(r == 0, token, byte)
    byte = jnp.where(j < total_out, byte, 0)
    return byte.astype(jnp.uint8), total_out


# --------------------------------------------- compile caches / warmup ------
# Three explicit caches replace the former module-global lru_cache on
# _jit_for (ISSUE 17 satellite: compiled kernels survived engine
# close() and escaped the conftest leak fixture):
#
#   _JIT    N -> jitted plain vmapped compress.  Deliberately process-
#           amortized: the bit-exactness suites call
#           lz4_block_compress_many from many short-lived providers and
#           re-paying the 64KB XLA compile per test would blow the
#           tier-1 budget.  Bounded (8 shapes) and cleared by
#           release().
#   _FUSED  N -> jitted fused compress+CRC batch kernel (the engine's
#           device route body).
#   _READY  (B, N, dev) -> AOT-compiled executable, the PR-3 warm-
#           registry shape (ops/crc32c_jax.py): a bucket routes to the
#           CPU provider until its kernel is HERE, so an XLA compile
#           can never stall a hot-path launch.
#
# _FUSED and _READY are engine-owned: AsyncOffloadEngine.close() calls
# release_device_kernels() (like parallel/mesh.py's step cache) and the
# conftest leak fixture asserts device_kernel_count() == 0 afterwards.
_CACHE_LOCK = threading.Lock()
_JIT_MAX = 8
_JIT: dict[int, object] = {}
_FUSED: dict[int, object] = {}
_READY: dict[tuple[int, int, int], object] = {}


def _jit_for(N: int):
    with _CACHE_LOCK:
        fn = _JIT.get(N)
    if fn is None:
        fn = jax.jit(jax.vmap(lambda d, n: _lz4_block_one(d, n, N)))
        with _CACHE_LOCK:
            while len(_JIT) >= _JIT_MAX:
                _JIT.pop(next(iter(_JIT)))
            fn = _JIT.setdefault(N, fn)
    return fn


def _fused_fn(N: int):
    """Un-jitted fused body for one block width: (data (B, N) uint8
    right-padded, lens (B,) int32) -> (comp (B, C) uint8 left-aligned,
    comp_len (B,), crc_comp (B,), crc_raw (B,))."""
    C = _bound(N)
    NC = next_pow2(C)                  # crc kernel wants K*L | 8 shapes
    Kc, Lc = _pick_kl(NC)
    Kr, Lr = _pick_kl(N)
    st_c = _shift_tables(Lc)
    st_r = _shift_tables(Lr)

    def fn(data, lens):
        out, olen = jax.vmap(lambda d, n: _lz4_block_one(d, n, N))(data,
                                                                   lens)
        # the crc kernel wants LEFT-padded rows (leading zeros are a
        # no-op under a zero register); the compress output is left-
        # aligned and zeroed past olen, so a clipped gather right-
        # aligns it safely
        j = jnp.arange(NC, dtype=I32)[None, :]
        src = j - (NC - olen[:, None])
        comp_in = jnp.where(
            src >= 0,
            jnp.take_along_axis(out, jnp.clip(src, 0, C - 1), axis=1),
            jnp.uint8(0))
        crc_comp = _crc_kernel(comp_in.reshape(-1, Kc, Lc), olen, st_c)
        lens32 = lens.astype(I32)
        jr = jnp.arange(N, dtype=I32)[None, :]
        srcr = jr - (N - lens32[:, None])
        raw_in = jnp.where(
            srcr >= 0,
            jnp.take_along_axis(data, jnp.clip(srcr, 0, N - 1), axis=1),
            jnp.uint8(0))
        crc_raw = _crc_kernel(raw_in.reshape(-1, Kr, Lr), lens32, st_r)
        return out, olen, crc_comp, crc_raw

    return fn


def _fused_for(N: int):
    """The jitted fused compress+CRC kernel for block width N."""
    with _CACHE_LOCK:
        fn = _FUSED.get(N)
    if fn is None:
        fn = jax.jit(_fused_fn(N))
        with _CACHE_LOCK:
            fn = _FUSED.setdefault(N, fn)
    return fn


def kernel_ready(B: int, N: int, device=None) -> bool:
    """True once the fused (B, N) compress bucket is compiled for
    ``device`` — same contract as crc32c_jax.kernel_ready."""
    return (B, N, _dev_key(device)) in _READY


def ready_kernel(B: int, N: int, device=None):
    """The warmed AOT executable for a compress bucket, or None."""
    return _READY.get((B, N, _dev_key(device)))


def warm_bucket_count(device=None) -> int:
    """How many fused (B, N) compress buckets are warm on ``device``."""
    dk = _dev_key(device)
    with _CACHE_LOCK:
        return sum(1 for k in _READY if k[2] == dk)


def warm_kernel(B: int, N: int, device=None) -> None:
    """AOT-compile the fused (B, N) compress bucket for ``device`` and
    mark it ready.  Idempotent; the engine's background warmup thread
    is the intended caller (mirrors crc32c_jax.warm_kernel)."""
    key = (B, N, _dev_key(device))
    if key in _READY:
        return
    fn = _fused_for(N)
    sds_kw = {}
    if device is not None and not isinstance(device, int):
        try:
            from jax.sharding import SingleDeviceSharding
            sds_kw = {"sharding": SingleDeviceSharding(device)}
        except Exception:
            sds_kw = {}
    d = jax.ShapeDtypeStruct((B, N), jnp.uint8, **sds_kw)
    ln = jax.ShapeDtypeStruct((B,), jnp.int32, **sds_kw)
    try:
        exe = fn.lower(d, ln).compile()
    except Exception:
        dev = device if device is not None and not isinstance(device, int) \
            else None
        data = np.zeros((B, N), dtype=np.uint8)
        lens = np.zeros((B,), dtype=np.int32)
        np.asarray(fn(*(jax.device_put(a, dev) for a in (data, lens)))[0])
        exe = fn
    with _CACHE_LOCK:
        _READY[key] = exe


def device_kernel_count() -> int:
    """Engine-owned compiled-kernel gauge: the conftest leak fixture
    asserts this is 0 after engine close()."""
    with _CACHE_LOCK:
        return len(_FUSED) + len(_READY)


def release_device_kernels() -> None:
    """Drop the engine-owned fused/AOT kernels (called from
    AsyncOffloadEngine.close(), like mesh.release_step_cache)."""
    with _CACHE_LOCK:
        _FUSED.clear()
        _READY.clear()


def release() -> None:
    """Drop every cached compress kernel, including the process-
    amortized plain-compress jits."""
    with _CACHE_LOCK:
        _JIT.clear()
        _FUSED.clear()
        _READY.clear()


def lz4_block_compress_many(blocks: list[bytes]) -> list[bytes]:
    """Compress many ≤64KB blocks in one vmapped device launch."""
    if not blocks:
        return []
    N = next_pow2(max(len(b) for b in blocks))
    data, lens = pad_right(blocks, N)
    out, olens = _jit_for(N)(data, lens)
    out = np.asarray(out)
    olens = np.asarray(olens)
    return [out[i, :olens[i]].tobytes() for i in range(len(blocks))]
