"""TPU codec provider — the north-star offload (SURVEY.md §7 stage 5).

Replaces the broker-thread compression + CRC hot loops of the reference
(rdkafka_msgset_writer.c:1129 writer_compress, crc32c.c:39) with batched
device launches:

  * lz4: every ≤64KB frame block of every partition batch is compressed in
    ONE vmapped launch (ops/lz4_jax.py); frames are assembled host-side
    byte-identically to the CPU provider (ops/native/codec.cpp
    tk_lz4f_compress — magic | FLG 0x60 | BD 0x40 | HC | blocks | EndMark,
    incompressible blocks stored raw with the high bit set).
  * crc32c: chunk-parallel + GF(2) combine (ops/crc32c_jax.py).
  * gzip/zstd entropy coding and snappy stay on the CPU provider behind the
    same interface for now (SURVEY.md §7 risk list: entropy stages last).

Wire bytes are bit-identical to the CPU provider by construction; the
equivalence suite is tests/test_0018_tpu_codec.py.
"""
from __future__ import annotations

import struct

import numpy as np

from . import cpu as _cpu
from ..analysis.locks import new_lock
from ..analysis.races import shared
from .crc32c_jax import crc32c_many_mxu as _crc32c_many_mxu
from .lz4_jax import lz4_block_compress_many

LZ4F_MAGIC = 0x184D2204
LZ4F_BLOCKSIZE = 65536

_HC = None


def _frame_hc() -> int:
    """Header-checksum byte: (xxh32(FLG|BD) >> 8) & 0xFF — a constant."""
    global _HC
    if _HC is None:
        _HC = (_cpu.xxh32(b"\x60\x40", 0) >> 8) & 0xFF
    return _HC


class TpuCodecProvider:
    """MsgsetCodecProvider with device-offloaded lz4 + crc32c."""

    name = "tpu"
    #: the broker's writer phase may pass per-buffer (topic, weight)
    #: QoS pairs to compress_submit (topic.qos.weight, ISSUE 17)
    accepts_qos = True

    # relaxed lockset declarations (analysis/races.py): engine/mesh
    # handles are created once under tpu.engine_init and only READ
    # lock-free afterwards (object-reference loads are atomic); the
    # crc32 warm flags are written by the warmup thread and read by
    # submitters as a route gate whose false-negative merely keeps a
    # launch on the (bit-identical) CPU path for one more call.
    _engine = shared("tpu.engine", relaxed=True)
    _mesh = shared("tpu.mesh", relaxed=True)
    _crc32_ready = shared("tpu.crc32_ready", relaxed=True)
    _crc32_warming = shared("tpu.crc32_warming", relaxed=True)

    def __init__(self, min_batches: int = 4, warmup: bool = True,
                 mesh_devices: int = 0, lz4_force: bool = False,
                 min_transport_mb_s: float = 100.0,
                 pipeline_depth: int = 2, fanin_us: int = 500,
                 governor: bool = True,
                 engine_warmup: bool | None = None,
                 compile_cache_dir: str = "",
                 compress_device: bool = False):
        # below this many independent buffers a launch isn't worth it;
        # fall back to the CPU provider (identical bytes either way).
        self.min_batches = max(1, int(min_batches))
        # tpu.mesh.devices: how many chips the async engine spreads its
        # per-device dispatch lanes over (0 = all local, 1 = the
        # pre-mesh single-lane engine); >1 also shards the (lz4.force)
        # device encoder's block compression over the same 1-D
        # jax.sharding.Mesh (parallel/mesh.py shard_map scale-out)
        self.mesh_devices = int(mesh_devices or 0)
        # tpu.lz4.force: the device lz4 encoder is measured ~3 orders of
        # magnitude slower than the native CPU path (PERF.md §3 —
        # gather/sort-bound match search), so backend=tpu routes lz4 to
        # CPU and keeps only CRC32C on the MXU unless explicitly forced
        self.lz4_force = bool(lz4_force)
        # Adaptive offload gate: CRC offload only pays when host<->device
        # bandwidth beats the CPU provider's ~1 GB/s CRC rate by enough
        # margin.  On a real TPU VM PCIe measures GB/s and the gate stays
        # open; behind a slow dev tunnel (MB/s) every launch would cost
        # more in transfer than the whole CPU checksum, so the provider
        # self-routes to CPU.  0 disables the gate (always offload).
        self.min_transport_mb_s = float(min_transport_mb_s)
        self.transport_mb_s: float | None = None      # measured by probe
        # tpu.pipeline.depth / tpu.pipeline.fanin.us: the async
        # double-buffered dispatch engine (ops/engine.py).  depth=0
        # disables it — every call dispatches synchronously like r5.
        self.pipeline_depth = int(pipeline_depth)
        self.fanin_us = int(fanin_us)
        # tpu.governor / tpu.warmup / tpu.compile.cache.dir: the
        # adaptive offload governor (ops/engine.py, ISSUE 3).
        # engine_warmup=None inherits this provider's warmup flag so
        # warmup=False test providers stay compile-free.
        self.governor = bool(governor)
        self.engine_warmup = (bool(warmup) if engine_warmup is None
                              else bool(engine_warmup))
        self.compile_cache_dir = compile_cache_dir or None
        # tpu.compress.device (ISSUE 17): open the engine's fused
        # compress→CRC device route for producer lz4.  Off by default —
        # the fused kernel's XLA compiles cost tens of seconds cold, so
        # the route is opt-in and rides the warm registry + persistent
        # compile cache once enabled.
        self.compress_device = bool(compress_device)
        self._engine = None
        self._engine_closed = False
        # eager creation kills the old check-then-create race: two
        # threads hitting _get_engine() concurrently could each have
        # built a DIFFERENT Lock and both entered the critical section
        self._engine_lock = new_lock("tpu.engine_init")
        self._mesh = None
        self._cpu = _cpu.CpuCodecProvider()
        self._warmup_thread = None
        # legacy-CRC device route opens only after its kernel compiled
        # in the background (see crc32_many)
        self._crc32_ready = False
        self._crc32_warming = False
        if warmup:
            # compile the fixed-shape kernels off the critical path (the
            # 64KB lz4 block kernel costs ~20 s of XLA compile; the CRC
            # matmul ~5 s) so first real traffic doesn't stall
            import threading

            def _warm():
                # probe transport FIRST: when the gate is closed every
                # launch self-routes to CPU, so the (expensive, GIL-
                # chewing) XLA compiles would never be used — skip them.
                # Shapes must match real traffic: the lz4 kernel caches
                # per next_pow2(block len) — 64KB is the production
                # block size — and the CRC matmul caches per pow2 batch
                # bucket, so warm the full-chunk bucket too
                try:
                    if not self._offload_pays() and not self.lz4_force:
                        return
                    blk = b"\x00" * LZ4F_BLOCKSIZE
                    if self.lz4_force:
                        lz4_block_compress_many([blk])
                    if self._offload_pays():
                        _crc32c_many_mxu([blk] * self.min_batches)
                except Exception:
                    pass

            self._warmup_thread = threading.Thread(
                target=_warm, daemon=True, name="tpu-codec-warmup")
            self._warmup_thread.start()

    # -------------------------------------------------------------- lz4 --

    def _lz4f_compress_many(self, bufs: list[bytes]) -> list[bytes]:
        # flatten: every 64KB block of every buffer is one device-batch item
        blocks: list[bytes] = []
        spans: list[tuple[int, int]] = []      # (first_block, nblocks) per buf
        for b in bufs:
            b = bytes(b)
            first = len(blocks)
            for pos in range(0, len(b), LZ4F_BLOCKSIZE):
                blocks.append(b[pos:pos + LZ4F_BLOCKSIZE])
            spans.append((first, len(blocks) - first))

        mesh = self._get_mesh()
        if mesh is not None:
            from ..parallel.mesh import shard_compress
            cblocks, _, _ = shard_compress(mesh, blocks, with_crc=False)
        else:
            cblocks = lz4_block_compress_many(blocks)

        out = []
        hdr = struct.pack("<IBBB", LZ4F_MAGIC, 0x60, 0x40, _frame_hc())
        for first, nb in spans:
            parts = [hdr]
            for k in range(nb):
                raw = blocks[first + k]
                comp = cblocks[first + k]
                if len(comp) < len(raw):
                    parts.append(struct.pack("<I", len(comp)))
                    parts.append(comp)
                else:                      # incompressible: store raw
                    parts.append(struct.pack("<I", len(raw) | 0x80000000))
                    parts.append(raw)
            parts.append(b"\x00\x00\x00\x00")  # EndMark
            out.append(b"".join(parts))
        return out

    def wait_warm(self, timeout: float = 120.0) -> None:
        """Block until the async warmup (probe + kernel compiles) ends."""
        t = getattr(self, "_warmup_thread", None)
        if t is not None:
            t.join(timeout)

    #: the probe body, run OUT OF PROCESS (see _probe_transport): a full
    #: round trip (device_put + host readback) is the only sync that is
    #: reliable on every platform (a tunneled device can return from
    #: block_until_ready before bytes land), so the rate counts bytes
    #: moved in BOTH directions
    _PROBE_SRC = (
        "import time\n"
        "import numpy as np\n"
        "import jax\n"
        "h = np.zeros((4, 65536), np.uint8)\n"
        "np.asarray(jax.device_put(h))\n"
        "t0 = time.perf_counter()\n"
        "np.asarray(jax.device_put(h))\n"
        "dt = max(time.perf_counter() - t0, 1e-9)\n"
        "print((2 * h.nbytes / (1 << 20)) / dt)\n")

    _PROBE_CACHE_TTL = 900.0     # transport is stable within a session

    def _probe_transport(self) -> float:
        """Measure host<->device bandwidth once — in a SUBPROCESS, with
        a disk cache.  When the gate routes to CPU (slow tunnel), the
        client process must never initialize the jax runtime: its
        background threads tax every broker/codec thread on small hosts
        (measured ~90k msgs/s off the producer pipeline on a 1-core
        host, VERDICT r4 #3).  A probe failure is cached in-memory as
        0.0: a broken device must not re-raise inside the broker serve
        loop, and must not receive traffic."""
        if self.transport_mb_s is not None:
            return self.transport_mb_s
        import json
        import os
        import subprocess
        import sys
        import tempfile
        import time
        key = os.environ.get("JAX_PLATFORMS", "default") or "default"
        cache = os.path.join(
            tempfile.gettempdir(),
            f"tk_transport_{os.getuid()}_{key.replace(',', '-')}.json")
        try:
            st = os.stat(cache)
            # /tmp is world-writable: only trust a file we own
            if (st.st_uid == os.getuid()
                    and time.time() - st.st_mtime < self._PROBE_CACHE_TTL):
                with open(cache) as f:
                    self.transport_mb_s = float(json.load(f)["mb_s"])
                return self.transport_mb_s
        except Exception:
            pass
        v = 0.0
        try:
            out = subprocess.run([sys.executable, "-c", self._PROBE_SRC],
                                 capture_output=True, timeout=300)
            if out.returncode == 0:
                v = float(out.stdout.split()[-1])
        except Exception:
            v = 0.0
        self.transport_mb_s = v
        if v > 0:
            try:
                tmp = cache + f".{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump({"mb_s": v}, f)
                os.replace(tmp, cache)
            except Exception:
                pass
        return v

    def _offload_pays(self) -> bool:
        """True when the measured transport clears the gate (or the gate
        is disabled).  Probes lazily if the warmup thread hasn't yet."""
        if self.min_transport_mb_s <= 0:
            return True
        return self._probe_transport() >= self.min_transport_mb_s

    def _get_mesh(self):
        if self._mesh is None and self.mesh_devices > 1:
            import jax
            from ..parallel.mesh import make_mesh
            n = min(self.mesh_devices, len(jax.devices()))
            if n > 1:
                self._mesh = make_mesh(n)
        return self._mesh

    # -------------------------------------------------------- interface --

    def compress_many(self, codec: str, bufs: list[bytes], level: int = -1
                      ) -> list[bytes]:
        # lz4 compresses on the native CPU path unless tpu.lz4.force.
        # The forced device encoder emits the deterministic insert-all
        # spec, bit-identical to cpu.lz4f_compress_many(
        # deterministic=True) — it exists to prove bit-exactness, not to
        # win (PERF.md §3); the default route is the CPU fast parse.
        if (codec == "lz4" and self.lz4_force
                and len(bufs) >= self.min_batches):
            return self._lz4f_compress_many(bufs)
        return self._cpu.compress_many(codec, bufs, level)

    def decompress_many(self, codec: str, bufs: list[bytes],
                        size_hints: list[int] | None = None) -> list[bytes]:
        # Always the CPU provider: LZ4 decode is a serial chain of
        # back-reference copies (each sequence reads output earlier
        # sequences wrote), and the measured lane-parallel upper bound
        # on v5e-1 is ~4 MB/s vs ~2 GB/s native — PERF.md §3, decode
        # direction. Both codec directions stay host-side; the tpu
        # backend's win is the CRC seam.
        return self._cpu.decompress_many(codec, bufs, size_hints)

    def decompress_submit(self, codec: str, bufs: list[bytes],
                          size_hints: list[int] | None = None):
        """Pipelined fetch-phase-C decompress: run the native
        ``*_decompress_many`` path on the engine's dispatch thread as a
        host job and return a Ticket, so the fetch-parsing broker
        thread frames the NEXT partition while this one inflates —
        overlapping any in-flight CRC launch too.  None when the
        pipeline is disabled (the caller decompresses synchronously,
        bit-identical bytes either way)."""
        eng = self._get_engine()
        if eng is None:
            return None
        return eng.submit_compute(self._cpu.decompress_many, codec,
                                  bufs, size_hints, host=True)

    def compress_submit(self, codec: str, bufs: list[bytes],
                        level: int = -1, qos=None):
        """Pipelined producer-phase-2 compress.  Two routes (ISSUE 17):

        * **device** — lz4 with ``tpu.compress.device`` on and the
          transport gate open: the engine buckets the 64KB blocks into
          the staging rings and runs the fused compress→CRC kernel, one
          launch + one readback per bucket yielding LZ4F frames that
          carry per-part CRCs (the writer folds the v2 batch CRC with
          crc32c_combine instead of re-scanning).  Bit-identical to
          ``cpu.lz4f_compress_many(deterministic=True)`` by
          construction; the engine's governor may still route any
          bucket back to that CPU encoder on its cost model.
        * **host job** — everything else (non-lz4 codecs, route off):
          run compress_many on the engine's dispatch thread so
          compression of batch k+1 overlaps the in-flight CRC launch of
          batch k.  None when the pipeline is disabled.

        ``qos`` is an optional per-buffer ``(topic, weight)`` list
        (topic.qos.weight): device submissions feed the governor's
        weighted fan-in + shed model; host jobs dispatch in weight
        order."""
        eng = self._get_engine()
        if eng is None:
            return None
        if (codec == "lz4" and self.compress_device
                and (self.lz4_force or self._offload_pays())):
            return eng.submit_compress(
                bufs, qos=qos, window=len(bufs) < self.min_batches)
        weight = (max((w for _, w in qos), default=1.0)
                  if qos else 1.0)
        return eng.submit_compute(self.compress_many, codec, bufs, level,
                                  host=True, weight=weight)

    # ------------------------------------------------- pipelined offload --

    def _get_engine(self):
        """The shared async offload engine (ops/engine.py), created on
        first use.  None when tpu.pipeline.depth=0."""
        if self.pipeline_depth <= 0 or self._engine_closed:
            return None
        if self._engine is None:
            with self._engine_lock:
                if self._engine is None:
                    from .engine import AsyncOffloadEngine
                    self._engine = AsyncOffloadEngine(
                        depth=self.pipeline_depth,
                        fanin_window_s=self.fanin_us / 1e6,
                        min_batches=self.min_batches,
                        cpu_fallback=self._cpu_crc_fallback,
                        cpu_compress_fallback=self._cpu_lz4_fallback,
                        name="tpu-codec-engine",
                        governor=self.governor,
                        warmup=self.engine_warmup,
                        compile_cache_dir=self.compile_cache_dir,
                        mesh_devices=self.mesh_devices)
        return self._engine

    def _cpu_crc_fallback(self, bufs: list[bytes], poly: str) -> list[int]:
        return (self._cpu.crc32c_many(bufs) if poly == "crc32c"
                else self._cpu.crc32_many(bufs))

    def _cpu_lz4_fallback(self, bufs: list[bytes]) -> list[bytes]:
        # Deterministic (TPU-greedy insert-all) spec — bit-exact with
        # the device kernel's output, so governor re-routes / warmup
        # misses / shed jobs produce identical wire bytes.  NOT the
        # CpuCodecProvider fast parse, which emits a different (equally
        # valid) LZ4F stream.
        return _cpu.lz4f_compress_many(
            [bytes(b) for b in bufs], deterministic=True)

    def crc32c_submit(self, bufs: list[bytes]):
        """Async pipelined CRC32C: returns a Ticket resolving to a
        uint32 ndarray (one checksum per buffer, bit-identical to the
        CPU provider), or None when the CPU path is the right route
        (transport gate closed / pipeline disabled) — the caller then
        computes synchronously.  Below-quorum submissions ride the
        engine's bounded fan-in window, merging with other brokers'
        batches into one launch instead of falling back to CPU."""
        if not self._offload_pays():
            return None
        eng = self._get_engine()
        if eng is None:
            return None
        return eng.submit(bufs, poly="crc32c",
                          window=len(bufs) < self.min_batches)

    def crc32_submit(self, bufs: list[bytes]):
        """Async pipelined legacy (zlib-poly) CRC — the crc32 mirror of
        :meth:`crc32c_submit`, feeding the consumer's MsgVer0/1 fetch
        verify.  With the engine warmup on (ISSUE 3) the device path is
        open END TO END: submissions always ride ``_jit_mxu(poly=
        "crc32")`` through the engine, whose warmup gate serves from
        the CPU provider until the bucket's kernel is compiled — the
        first legacy fetches never stall behind an XLA compile and
        stop falling back to unconditional CPU service.  Without the
        engine warmup the pre-governor background-compile gate applies
        (see crc32_many)."""
        if not self._offload_pays():
            return None
        if not self.engine_warmup and not self._crc32_ready:
            self._warm_crc32()
            return None
        eng = self._get_engine()
        if eng is None:
            return None
        return eng.submit(bufs, poly="crc32",
                          window=len(bufs) < self.min_batches)

    def close(self) -> None:
        """Tear down the async engine (drains in-flight launches); the
        provider keeps serving synchronously afterwards — a straggling
        codec job must not respawn a dispatch thread post-close.  A
        provider that built an lz4 mesh also releases the compiled
        sharded-step cache (parallel/mesh.py close-time hook)."""
        self._engine_closed = True
        eng, self._engine = self._engine, None
        if eng is not None:
            eng.close()
        if self._warmup_thread is not None:
            # join the pre-governor background-compile thread: a daemon
            # thread killed inside an XLA compile at interpreter exit
            # aborts the whole process (std::terminate from the
            # orphaned compile thread) — the compile cannot be
            # cancelled, so wait it out like the engine's warmup join
            self._warmup_thread.join(30.0)
            self._warmup_thread = None
        if self._mesh is not None:
            from ..parallel.mesh import release_step_cache
            self._mesh = None
            release_step_cache()

    def crc32c_many(self, bufs: list[bytes]) -> list[int]:
        if len(bufs) >= self.min_batches and self._offload_pays():
            eng = self._get_engine()
            if eng is not None:
                # engine route: persistent staging buffers + bulk
                # readback; window=False — a synchronous caller already
                # at quorum must not pay the fan-in latency
                return eng.submit(bufs, "crc32c",
                                  window=False).result().tolist()
            # ONE GF(2) matmul per 64KB block on the MXU (crc32c_jax.py;
            # 8.5x native CPU at 128x64KB in device time on v5e-1);
            # .tolist() is one vectorized uint32->int conversion, not a
            # per-item host sync
            return np.asarray(_crc32c_many_mxu(bufs)).tolist()
        return self._cpu.crc32c_many(bufs)

    def fused_codec_id(self, codec: str) -> int | None:
        """Fused native batch build is allowed only when this provider
        would route BOTH the compress and the CRC to the CPU path
        anyway (lz4 not forced onto the device, transport gate says
        offload doesn't pay) — then it is exactly the CPU provider's
        fused path.  When the device route is open the 3-phase
        pipeline keeps the batched CRC on the MXU."""
        if self.lz4_force or self._offload_pays():
            return None
        return self._cpu.fused_codec_id(codec)

    def crc32_many(self, bufs: list[bytes]) -> list[int]:
        """Legacy MsgVer0/1 zlib-poly CRC on the same MXU kernel (the
        GF(2) decomposition is polynomial-agnostic; reference hot loop:
        src/rdcrc32.c).

        The crc32 Q-matrix + XLA compile cost seconds and the warmup
        thread only pre-warms the (always-used) crc32c variant — so the
        first legacy fetches serve from the CPU path while a background
        thread compiles; the device route opens once it is ready.
        Stalling the broker IO thread here would blow socket.timeout.ms
        for in-flight requests."""
        if len(bufs) >= self.min_batches and self._offload_pays():
            if self._crc32_ready:
                eng = self._get_engine()
                if eng is not None:
                    return eng.submit(bufs, "crc32",
                                      window=False).result().tolist()
                from .crc32c_jax import crc32_many_mxu
                return np.asarray(crc32_many_mxu(bufs)).tolist()
            self._warm_crc32()
        return self._cpu.crc32_many(bufs)

    def _warm_crc32(self) -> None:
        if self._crc32_warming:
            return
        self._crc32_warming = True

        def _warm():
            try:
                from .crc32c_jax import crc32_many_mxu
                blk = b"\x00" * LZ4F_BLOCKSIZE
                crc32_many_mxu([blk] * self.min_batches)
                self._crc32_ready = True
            except Exception:
                pass        # CPU path keeps serving

        import threading
        threading.Thread(target=_warm, daemon=True,
                         name="tpu-crc32-warmup").start()
