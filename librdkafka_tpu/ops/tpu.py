"""TPU codec provider — the north-star offload (SURVEY.md §7 stage 5).

Replaces the broker-thread compression + CRC hot loops of the reference
(rdkafka_msgset_writer.c:1129 writer_compress, crc32c.c:39) with batched
device launches:

  * lz4: every ≤64KB frame block of every partition batch is compressed in
    ONE vmapped launch (ops/lz4_jax.py); frames are assembled host-side
    byte-identically to the CPU provider (ops/native/codec.cpp
    tk_lz4f_compress — magic | FLG 0x60 | BD 0x40 | HC | blocks | EndMark,
    incompressible blocks stored raw with the high bit set).
  * crc32c: chunk-parallel + GF(2) combine (ops/crc32c_jax.py).
  * gzip/zstd entropy coding and snappy stay on the CPU provider behind the
    same interface for now (SURVEY.md §7 risk list: entropy stages last).

Wire bytes are bit-identical to the CPU provider by construction; the
equivalence suite is tests/test_0018_tpu_codec.py.
"""
from __future__ import annotations

import struct

import numpy as np

from . import cpu as _cpu
from .crc32c_jax import crc32c_many_mxu as _crc32c_many_mxu
from .lz4_jax import lz4_block_compress_many

LZ4F_MAGIC = 0x184D2204
LZ4F_BLOCKSIZE = 65536

_HC = None


def _frame_hc() -> int:
    """Header-checksum byte: (xxh32(FLG|BD) >> 8) & 0xFF — a constant."""
    global _HC
    if _HC is None:
        _HC = (_cpu.xxh32(b"\x60\x40", 0) >> 8) & 0xFF
    return _HC


class TpuCodecProvider:
    """MsgsetCodecProvider with device-offloaded lz4 + crc32c."""

    name = "tpu"

    def __init__(self, min_batches: int = 4, warmup: bool = True,
                 mesh_devices: int = 0):
        # below this many independent buffers a launch isn't worth it;
        # fall back to the CPU provider (identical bytes either way).
        self.min_batches = max(1, int(min_batches))
        # tpu.mesh.devices: >1 shards block compression over a 1-D
        # jax.sharding.Mesh (parallel/mesh.py shard_map scale-out)
        self.mesh_devices = int(mesh_devices or 0)
        self._mesh = None
        self._cpu = _cpu.CpuCodecProvider()
        if warmup:
            # compile the fixed-shape kernels off the critical path (the
            # 64KB lz4 block kernel costs ~20 s of XLA compile; the CRC
            # matmul ~5 s) so first real traffic doesn't stall
            import threading

            def _warm():
                # shapes must match real traffic: the lz4 kernel caches
                # per next_pow2(block len) — 64KB is the production
                # block size — and the CRC matmul caches per pow2 batch
                # bucket, so warm the full-chunk bucket too
                try:
                    blk = b"\x00" * LZ4F_BLOCKSIZE
                    lz4_block_compress_many([blk])
                    _crc32c_many_mxu([blk] * self.min_batches)
                except Exception:
                    pass

            threading.Thread(target=_warm, daemon=True,
                             name="tpu-codec-warmup").start()

    # -------------------------------------------------------------- lz4 --

    def _lz4f_compress_many(self, bufs: list[bytes]) -> list[bytes]:
        # flatten: every 64KB block of every buffer is one device-batch item
        blocks: list[bytes] = []
        spans: list[tuple[int, int]] = []      # (first_block, nblocks) per buf
        for b in bufs:
            b = bytes(b)
            first = len(blocks)
            for pos in range(0, len(b), LZ4F_BLOCKSIZE):
                blocks.append(b[pos:pos + LZ4F_BLOCKSIZE])
            spans.append((first, len(blocks) - first))

        mesh = self._get_mesh()
        if mesh is not None:
            from ..parallel.mesh import shard_compress
            cblocks, _, _ = shard_compress(mesh, blocks, with_crc=False)
        else:
            cblocks = lz4_block_compress_many(blocks)

        out = []
        hdr = struct.pack("<IBBB", LZ4F_MAGIC, 0x60, 0x40, _frame_hc())
        for first, nb in spans:
            parts = [hdr]
            for k in range(nb):
                raw = blocks[first + k]
                comp = cblocks[first + k]
                if len(comp) < len(raw):
                    parts.append(struct.pack("<I", len(comp)))
                    parts.append(comp)
                else:                      # incompressible: store raw
                    parts.append(struct.pack("<I", len(raw) | 0x80000000))
                    parts.append(raw)
            parts.append(b"\x00\x00\x00\x00")  # EndMark
            out.append(b"".join(parts))
        return out

    def _get_mesh(self):
        if self._mesh is None and self.mesh_devices > 1:
            import jax
            from ..parallel.mesh import make_mesh
            n = min(self.mesh_devices, len(jax.devices()))
            if n > 1:
                self._mesh = make_mesh(n)
        return self._mesh

    # -------------------------------------------------------- interface --

    def compress_many(self, codec: str, bufs: list[bytes], level: int = -1
                      ) -> list[bytes]:
        if codec == "lz4" and len(bufs) >= self.min_batches:
            return self._lz4f_compress_many(bufs)
        return self._cpu.compress_many(codec, bufs, level)

    def decompress_many(self, codec: str, bufs: list[bytes],
                        size_hints: list[int] | None = None) -> list[bytes]:
        return self._cpu.decompress_many(codec, bufs, size_hints)

    def crc32c_many(self, bufs: list[bytes]) -> list[int]:
        if len(bufs) >= self.min_batches:
            # ONE GF(2) matmul per 64KB block on the MXU (crc32c_jax.py;
            # 3.9x native CPU at 64x64KB in device time on v5e-1)
            return [int(x) for x in _crc32c_many_mxu(bufs)]
        return self._cpu.crc32c_many(bufs)
