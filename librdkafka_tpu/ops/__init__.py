"""librdkafka_tpu.ops"""
