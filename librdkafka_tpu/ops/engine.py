"""Pipelined async device-offload engine (the producer seam's
double-buffered dispatch axis).

BENCH_r05 showed the device-time CRC kernel at 14.6x the CPU provider
while the end-to-end TPU backend sat at 1.04x: every ``crc32c_many``
call blocked its caller through host->device copy, launch and readback
(ops/tpu.py).  The reference hides exactly this class of latency by
pipelining the msgset writer against broker IO
(rdkafka_msgset_writer.c -> rdkafka_broker.c request queues); this
module gives the offload seam the same overlap:

  * ``submit()`` returns a :class:`Ticket` immediately; a dedicated
    dispatch thread owns every device interaction, keeping up to
    ``depth`` launches in flight so the codec worker frames and
    CRC-patches batch *k* on the host while batch *k+1* executes on the
    device.
  * Host staging buffers are persistent per ``(B, block)`` pow2 bucket
    and recycled through a ring of ``depth + 1`` copies (double
    buffering): filling launch *k+1*'s staging never races launch *k*'s
    in-flight transfer, and no fresh ``pad_left`` allocation is paid per
    call.
  * Cross-submitter micro-batch aggregation: jobs arriving within a
    bounded fan-in window (default 500 us) merge into ONE launch, so
    the ``min_batches`` launch quorum is met at high toppar counts
    instead of each broker's small batch falling back to CPU.
  * Bulk readback: one ``np.asarray`` per launch plus a vectorized
    uint32 view — no per-item ``int(x)`` host sync loop.

The engine never changes bytes: block split, left-padding, the GF(2)
affine term and the host-side combine are exactly ``_crc_many_mxu``
(ops/crc32c_jax.py), and below the launch quorum jobs are served by the
caller-supplied CPU fallback — bit-identical either way.  jax is
imported lazily on the dispatch thread so CPU-only installs importing
this module never pay for it.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

import numpy as np


class Ticket:
    """Handle for one submitted job; resolves to a uint32 ndarray of
    per-buffer checksums (or raises the launch's exception)."""

    __slots__ = ("_ev", "_result", "_exc")

    def __init__(self):
        self._ev = threading.Event()
        self._result: Optional[np.ndarray] = None
        self._exc: Optional[BaseException] = None

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("offload ticket not resolved in time")
        if self._exc is not None:
            raise self._exc
        return self._result

    # dispatch-thread side -------------------------------------------------
    # (first resolution wins: the shutdown sweep failing stragglers must
    # not clobber a result the dispatch thread already delivered)
    def _complete(self, result) -> None:
        if not self._ev.is_set():
            self._result = result
            self._ev.set()

    def _fail(self, exc: BaseException) -> None:
        if not self._ev.is_set():
            self._exc = exc
            self._ev.set()


class SyncTicket:
    """Pre-resolved ticket: the CPU provider's (and any synchronous
    fallback's) ticket-shaped result, so pipelined and synchronous
    codec paths flow through ONE submit/park/resolve code path in the
    broker instead of two diverging branches."""

    __slots__ = ("_result", "_exc")

    def __init__(self, result=None, exc: Optional[BaseException] = None):
        self._result = result
        self._exc = exc

    def done(self) -> bool:
        return True

    def result(self, timeout: Optional[float] = None):
        if self._exc is not None:
            raise self._exc
        return self._result


class _Job:
    __slots__ = ("kind", "bufs", "poly", "ticket", "window", "fn", "args")

    def __init__(self, kind, bufs, poly, ticket, window, fn=None, args=()):
        self.kind = kind            # "crc" | "compute" | "host"
        self.bufs = bufs
        self.poly = poly
        self.ticket = ticket
        self.window = window        # may wait the fan-in window
        self.fn = fn
        self.args = args


class _Staging:
    """Persistent host staging arrays per (B, block) bucket, recycled
    through a ring of ``copies`` buffers so the fill of the next launch
    never overwrites one still feeding an in-flight transfer."""

    def __init__(self, copies: int):
        self.copies = max(2, copies)
        self._rings: dict[tuple[int, int], list[np.ndarray]] = {}
        self._next: dict[tuple[int, int], int] = {}

    def take(self, B: int, N: int) -> np.ndarray:
        key = (B, N)
        ring = self._rings.setdefault(key, [])
        if len(ring) < self.copies:
            arr = np.zeros((B, N), dtype=np.uint8)
            ring.append(arr)
            return arr
        i = self._next.get(key, 0)
        self._next[key] = (i + 1) % self.copies
        arr = ring[i]
        arr.fill(0)
        return arr

    def nbytes(self) -> int:
        return sum(a.nbytes for ring in self._rings.values() for a in ring)


class _Launch:
    """One in-flight device launch awaiting readback."""

    __slots__ = ("kind", "jobs", "spans", "outs", "chunk_lens", "combine",
                 "ticket", "out_tree")

    def __init__(self, kind):
        self.kind = kind
        self.jobs: list[_Job] = []
        self.spans: list[tuple[int, int]] = []   # (first_block, nblocks)/buf
        self.outs: list = []                     # device arrays per chunk
        self.chunk_lens: list[int] = []          # live rows per chunk
        self.combine = None
        self.ticket: Optional[Ticket] = None     # compute kind only
        self.out_tree = None


class AsyncOffloadEngine:
    """Double-buffered producer/consumer pipeline around the MXU CRC
    kernels (and, generically, any jitted step fn via
    :meth:`submit_compute`)."""

    def __init__(self, *, depth: int = 2, fanin_window_s: float = 0.0005,
                 min_batches: int = 4,
                 cpu_fallback: Optional[Callable] = None,
                 name: str = "tpu-engine"):
        # depth: launches kept in flight before the oldest is read back
        self.depth = max(1, int(depth))
        self.fanin_window_s = max(0.0, float(fanin_window_s))
        self.min_batches = max(1, int(min_batches))
        # cpu_fallback(bufs, poly) -> list[int]; serves below-quorum jobs
        self.cpu_fallback = cpu_fallback
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: deque[_Job] = deque()
        self._closed = False
        self._staging = _Staging(copies=self.depth + 1)
        # observability (PERF.md pipeline section)
        self.stats = {"launches": 0, "blocks": 0, "jobs": 0,
                      "aggregated": 0, "cpu_fallback_jobs": 0,
                      "fanin_waits": 0, "host_jobs": 0}
        self._thread = threading.Thread(target=self._main, daemon=True,
                                        name=name)
        self._thread.start()

    # ------------------------------------------------------------ public --
    def submit(self, bufs: list, poly: str = "crc32c",
               window: bool = True) -> Ticket:
        """Queue a CRC job; returns immediately.  ``window=False`` skips
        the fan-in wait (synchronous callers that already meet the
        quorum shouldn't pay the aggregation latency — whatever is
        queued at dispatch time still merges in)."""
        t = Ticket()
        job = _Job("crc", [bytes(b) for b in bufs], poly, t, window)
        with self._cond:
            if self._closed:
                raise RuntimeError("engine closed")
            self._queue.append(job)
            self._cond.notify()
        return t

    def submit_compute(self, fn, *args, host: bool = False) -> Ticket:
        """Generic pipelined dispatch: run ``fn(*args)`` on the dispatch
        thread.  ``host=False`` treats the return value as a tree of
        device arrays with the same in-flight depth and bulk-readback
        discipline (drives models/codec_step.py through the engine);
        ``host=True`` runs a plain host function (e.g. the native
        ``*_decompress_many`` paths of the consumer fetch seam) to
        completion on the dispatch thread and resolves the ticket with
        its raw return value — no jax import, no readback.  A host job
        naturally overlaps any device launch already in flight: the
        device executes while the dispatch thread runs the (GIL-
        releasing) native call."""
        t = Ticket()
        job = _Job("host" if host else "compute", None, None, t, False,
                   fn=fn, args=args)
        with self._cond:
            if self._closed:
                raise RuntimeError("engine closed")
            self._queue.append(job)
            self._cond.notify()
        return t

    def close(self, timeout: float = 30.0) -> None:
        """Stop the dispatch thread.  Outstanding work drains
        deterministically: queued + in-flight jobs are completed by the
        exiting thread, and anything it could not reach (a wedged or
        crashed dispatch thread, or a join timeout) is FAILED rather
        than left to hang its waiter forever in Ticket.result()."""
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._thread.join(timeout)
        if self._thread.is_alive():
            # join timed out: the dispatch thread is wedged (e.g. a hung
            # device launch).  Fail every job still visible so waiters
            # unblock; first-resolution-wins keeps this safe against the
            # thread completing them concurrently.
            with self._cond:
                stranded = self._pop_jobs_locked()
            exc = RuntimeError("offload engine closed (dispatch thread "
                               "did not exit in time)")
            for j in stranded:
                j.ticket._fail(exc)

    # ---------------------------------------------------- dispatch thread --
    def _main(self):
        inflight: deque[_Launch] = deque()
        try:
            self._main_loop(inflight)
        finally:
            # deterministic shutdown: whether the loop exited cleanly
            # (drained) or died on an unexpected error, no ticket may be
            # left unresolved — a parked _PendingFetch/_PendingCodec
            # would otherwise block its thread forever in result()
            with self._cond:
                stranded = self._pop_jobs_locked()
            exc = RuntimeError("offload engine dispatch thread exited")
            for j in stranded:
                j.ticket._fail(exc)
            for rec in inflight:
                if rec.kind == "crc":
                    for j in rec.jobs:
                        j.ticket._fail(exc)
                elif rec.ticket is not None:
                    rec.ticket._fail(exc)

    def _main_loop(self, inflight: deque):
        while True:
            with self._cond:
                if not self._queue and not self._closed:
                    # with launches in flight, linger only briefly: a
                    # pipelining submitter's NEXT job should launch
                    # before the oldest readback blocks this thread
                    self._cond.wait(timeout=0.0002 if inflight else None)
                if self._closed and not self._queue and not inflight:
                    return
                jobs = self._pop_jobs_locked()
            if jobs:
                jobs = self._fanin(jobs)
                for group in self._group(jobs):
                    rec = self._launch(group)
                    if rec is not None:
                        inflight.append(rec)
                    # pipeline full: sync the oldest — the newer
                    # launches keep executing on the device meanwhile
                    while len(inflight) > self.depth:
                        self._readback(inflight.popleft())
                continue            # re-check the queue before syncing
            if inflight:
                # nothing new queued: drain completed work rather than
                # hold results hostage waiting for more submissions
                self._readback(inflight.popleft())

    def _pop_jobs_locked(self) -> list[_Job]:
        jobs = list(self._queue)
        self._queue.clear()
        return jobs

    def _fanin(self, jobs: list[_Job]) -> list[_Job]:
        """Bounded fan-in: when the windowed CRC jobs are below the
        launch quorum, wait up to the window for more submitters (the
        cross-broker micro-batch aggregation) before dispatching."""
        if self.fanin_window_s <= 0:
            return jobs
        nbufs = sum(len(j.bufs) for j in jobs
                    if j.kind == "crc" and j.window)
        if nbufs == 0 or nbufs >= self.min_batches:
            return jobs
        self.stats["fanin_waits"] += 1
        deadline = time.monotonic() + self.fanin_window_s
        with self._cond:
            while nbufs < self.min_batches:
                left = deadline - time.monotonic()
                if left <= 0 or self._closed:
                    break
                self._cond.wait(left)
                more = self._pop_jobs_locked()
                jobs.extend(more)
                nbufs += sum(len(j.bufs) for j in more
                             if j.kind == "crc" and j.window)
        return jobs

    def _group(self, jobs: list[_Job]):
        """Launch groups: CRC jobs merge per polynomial (shared kernel
        shape); compute/host jobs launch individually."""
        by_poly: dict[str, list[_Job]] = {}
        order = []
        for j in jobs:
            if j.kind != "crc":
                order.append([j])
            else:
                if j.poly not in by_poly:
                    by_poly[j.poly] = []
                    order.append(by_poly[j.poly])
                by_poly[j.poly].append(j)
        return order

    # -------------------------------------------------------------- launch --
    def _launch(self, group: list[_Job]) -> Optional[_Launch]:
        try:
            if group[0].kind == "host":
                # host compute (native decompress/compress): runs to
                # completion here, overlapping whatever device launches
                # are already in flight
                job = group[0]
                self.stats["host_jobs"] += 1
                job.ticket._complete(job.fn(*job.args))
                return None
            if group[0].kind == "compute":
                return self._launch_compute(group[0])
            return self._launch_crc(group)
        except Exception as e:
            for j in group:
                j.ticket._fail(e)
            return None

    def _launch_compute(self, job: _Job) -> _Launch:
        rec = _Launch("compute")
        rec.ticket = job.ticket
        rec.out_tree = job.fn(*job.args)     # async dispatch
        return rec

    def _launch_crc(self, group: list[_Job]) -> Optional[_Launch]:
        from ..utils.crc import crc32_combine, crc32c_combine
        from .crc32c_jax import _MXU_BLOCK, _MXU_MAX_B, _term_host
        from .packing import next_pow2

        poly = group[0].poly
        self.stats["jobs"] += len(group)
        if len(group) > 1:
            self.stats["aggregated"] += len(group)

        blk = _MXU_BLOCK
        blocks: list[bytes] = []
        spans: list[tuple[int, int]] = []
        for j in group:
            for b in j.bufs:
                first = len(blocks)
                if not b:
                    spans.append((first, 0))
                    continue
                for pos in range(0, len(b), blk):
                    blocks.append(b[pos:pos + blk])
                spans.append((first, len(blocks) - first))

        if len(blocks) < self.min_batches and self.cpu_fallback is not None:
            # below the launch quorum even after fan-in: the CPU
            # provider serves these (bit-identical), still off the
            # submitter's thread
            self.stats["cpu_fallback_jobs"] += len(group)
            for j in group:
                try:
                    vals = self.cpu_fallback(j.bufs, poly)
                    j.ticket._complete(np.asarray(vals, dtype=np.uint32))
                except Exception as e:
                    j.ticket._fail(e)
            return None

        import jax

        from .crc32c_jax import _jit_mxu

        rec = _Launch("crc")
        rec.jobs = group
        rec.spans = spans
        rec.combine = crc32c_combine if poly == "crc32c" else crc32_combine
        self.stats["launches"] += 1
        self.stats["blocks"] += len(blocks)

        for start in range(0, len(blocks), _MXU_MAX_B):
            chunk = blocks[start:start + _MXU_MAX_B]
            B = next_pow2(len(chunk))
            if len(chunk) >= 64:
                B = max(B, 128)     # MXU tile floor (crc32c_jax.py)
            # persistent staging: one ring buffer per (B, blk) bucket,
            # zeroed + row-filled in place (left pad: leading zeros are
            # a CRC no-op under a zero register)
            data = self._staging.take(B, blk)
            terms = np.zeros((B,), dtype=np.uint32)
            full_term = _term_host(blk, poly)
            for i, b in enumerate(chunk):
                n = len(b)
                data[i, blk - n:] = np.frombuffer(b, dtype=np.uint8)
                terms[i] = (full_term if n == blk
                            else _term_host(n, poly))
            # async dispatch: device_put + kernel launch return
            # immediately; the readback (np.asarray) is the only sync
            d = jax.device_put(data)
            t = jax.device_put(terms)
            rec.outs.append(_jit_mxu(B, blk, poly)(d, t))
            rec.chunk_lens.append(len(chunk))
        return rec

    # ------------------------------------------------------------ readback --
    def _readback(self, rec: _Launch) -> None:
        try:
            if rec.kind == "compute":
                import jax
                rec.ticket._complete(
                    jax.tree_util.tree_map(np.asarray, rec.out_tree))
                return
            self._readback_crc(rec)
        except Exception as e:
            if rec.kind == "compute":
                rec.ticket._fail(e)
            else:
                for j in rec.jobs:
                    j.ticket._fail(e)

    def _readback_crc(self, rec: _Launch) -> None:
        from .crc32c_jax import _MXU_BLOCK
        blk = _MXU_BLOCK
        # ONE bulk host sync per chunk + vectorized uint32 view — no
        # per-item int(x) loop
        parts = [np.asarray(o).astype(np.uint32)[:n]
                 for o, n in zip(rec.outs, rec.chunk_lens)]
        crcs = parts[0] if len(parts) == 1 else np.concatenate(parts)
        # host-side combine of multi-block buffers (µs each), then slice
        # results back out per job in submission order
        it = iter(rec.spans)
        for j in rec.jobs:
            out = np.zeros((len(j.bufs),), dtype=np.uint32)
            for i, b in enumerate(j.bufs):
                first, nb = next(it)
                if nb == 0:
                    continue
                acc = int(crcs[first])
                off = blk
                for k in range(1, nb):
                    acc = rec.combine(acc, int(crcs[first + k]),
                                      min(blk, len(b) - off))
                    off += blk
                out[i] = acc
            j.ticket._complete(out)
