"""Pipelined async device-offload engine (the producer seam's
double-buffered dispatch axis).

BENCH_r05 showed the device-time CRC kernel at 14.6x the CPU provider
while the end-to-end TPU backend sat at 1.04x: every ``crc32c_many``
call blocked its caller through host->device copy, launch and readback
(ops/tpu.py).  The reference hides exactly this class of latency by
pipelining the msgset writer against broker IO
(rdkafka_msgset_writer.c -> rdkafka_broker.c request queues); this
module gives the offload seam the same overlap:

  * ``submit()`` returns a :class:`Ticket` immediately; a dedicated
    dispatch thread owns every device interaction, keeping up to
    ``depth`` launches in flight so the codec worker frames and
    CRC-patches batch *k* on the host while batch *k+1* executes on the
    device.
  * Host staging buffers are persistent per ``(B, block)`` pow2 bucket
    and recycled through a ring of ``depth + 1`` copies (double
    buffering): filling launch *k+1*'s staging never races launch *k*'s
    in-flight transfer, and no fresh ``pad_left`` allocation is paid per
    call.
  * Cross-submitter micro-batch aggregation: jobs arriving within a
    bounded fan-in window (default 500 us) merge into ONE launch, so
    the ``min_batches`` launch quorum is met at high toppar counts
    instead of each broker's small batch falling back to CPU.
  * Bulk readback: one ``np.asarray`` per launch plus a vectorized
    uint32 view — no per-item ``int(x)`` host sync loop.

The engine never changes bytes: block split, left-padding, the GF(2)
affine term and the host-side combine are exactly ``_crc_many_mxu``
(ops/crc32c_jax.py), and below the launch quorum jobs are served by the
caller-supplied CPU fallback — bit-identical either way.  jax is
imported lazily on the dispatch thread so CPU-only installs importing
this module never pay for it.

The ADAPTIVE OFFLOAD GOVERNOR (ISSUE 3) replaces the engine's static
policy layer:

  * **Background warmup** (``warmup=True``): a low-priority thread
    pre-compiles every (B, 64KB) pow2 bucket shape for BOTH
    polynomials plus the fused variant (crc32c_jax.warm_kernel AOT
    compiles, optionally backed by a persistent jax compilation cache,
    ``compile_cache_dir``).  Until a bucket's kernel is ready the
    dispatch thread routes that bucket to the CPU provider — a compile
    stall never blocks a hot-path launch — and requests the missed
    bucket so the warmup thread compiles genuinely-hot shapes first.
  * **Cost-model routing** (``governor=True``): at-quorum groups go to
    whichever side an online model predicts faster — EWMA of per-bucket
    device launch time (measured at readback) vs observed CPU-provider
    ns/byte — with a periodic exploration launch to the unpicked side
    so the model tracks host/tunnel drift.  ``min_batches`` stays a
    hard floor: below it jobs are CPU-served exactly as before.
  * **Adaptive fan-in**: the below-quorum fan-in wait is sized from
    the submission inter-arrival EWMA; ``fanin_window_s`` becomes the
    CAP.  Low-rate traffic stops paying the latency tax (window 0 when
    the next submission won't arrive within the cap), high-rate
    traffic keeps merging into full-tile launches.
  * **Fused multi-poly launches**: crc32c + legacy-crc32 jobs popped
    together merge into ONE padded launch with per-row Q-matrix/term
    selection (crc32c_jax._jit_mxu_fused), halving launch count on
    mixed v2/legacy fetch responses.

Every route is bit-identical by construction; the governor only moves
WHERE a checksum is computed, never WHAT it is.

The MESH-SHARDED DISPATCH LANES (ISSUE 6) spread the engine across
every healthy chip instead of parking 7/8 of them behind the default
device:

  * **Per-device lanes**: each mesh device owns a ``_Lane`` — its own
    persistent ``_Staging`` ring (fills never cross chips) and its own
    in-flight launch deque honoring ``depth`` per lane, so eight chips
    sustain eight pipelines instead of sharing one.
  * **Whole-to-one-lane routing**: a fused launch group below the
    shard threshold goes entirely to the least-loaded lane (fewest
    in-flight launches, then the lane's per-bucket launch-time EWMA,
    then total launches — spreading cold lanes first).
  * **Sharded launches**: a group spanning a mesh multiple
    (``SHARD_MIN_ROWS`` blocks per device) is laid out contiguously
    and shard_mapped over the 1-D batch mesh
    (parallel/mesh.py sharded_crc_step) so every chip checksums its
    row shard concurrently — the single biggest raw-speed multiplier
    (ROADMAP item 1).
  * **Mesh-aware governor**: launch-time EWMAs are per (device,
    bucket); routing compares the BEST device estimate against the CPU
    model, lane selection prefers the measured-faster chip, and the
    background warmup AOT-compiles every bucket on every device
    (device 0 first, so routes open exactly as fast as before) plus
    the sharded steps for the standard buckets.

Wire bytes stay bit-identical on every route: sharding only moves
WHERE each 64KB block's CRC runs — the block split, left-padding,
GF(2) affine term and host-side combine are untouched.

The DEVICE COMPRESS ROUTE (ISSUE 17) makes lz4 a first-class launch
kind exactly the way CRC is one: ``submit_compress`` blocks its
buffers into the (B, 64KB) lane staging rings, launches the FUSED
compress→CRC kernel (ops/lz4_jax.py — one dispatch + one readback
yields the compressed frames AND the checksums of both candidate
block bodies) and assembles LZ4F frames host-side as
:class:`packing.FrameBlob` values carrying per-part CRCs, so the
writer's v2 batch checksum is a µs combine instead of a re-scan.  The
governor grows a parallel pair of compress cost models (device-launch
EWMA per bucket vs CPU ns/byte, explore-every-16) and a per-topic QoS
layer (``topic.qos.weight``): weighted fan-in admission, weight-
ordered dispatch, and — only while every lane is saturated — shedding
of flood topics whose decayed byte share exceeds what their weight
entitles them to.  Every fallback serves the deterministic CPU
encoder, which implements the same TPU-greedy spec bit-for-bit, so
the wire bytes cannot depend on the route taken.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

import numpy as np

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..analysis import lockdep as _lockdep
from ..analysis.locks import new_cond, new_lock
from ..analysis.races import register_slots, shared, shared_dict


class Ticket:
    """Handle for one submitted job; resolves to a uint32 ndarray of
    per-buffer checksums (or raises the launch's exception)."""

    __slots__ = ("_ev", "_result", "_exc")

    def __init__(self):
        self._ev = threading.Event()
        self._result: Optional[np.ndarray] = None
        self._exc: Optional[BaseException] = None

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("offload ticket not resolved in time")
        if self._exc is not None:
            raise self._exc
        return self._result

    # dispatch-thread side -------------------------------------------------
    # (first resolution wins: the shutdown sweep failing stragglers must
    # not clobber a result the dispatch thread already delivered)
    def _complete(self, result) -> None:
        if not self._ev.is_set():
            self._result = result
            self._ev.set()

    def _fail(self, exc: BaseException) -> None:
        if not self._ev.is_set():
            self._exc = exc
            self._ev.set()


class SyncTicket:
    """Pre-resolved ticket: the CPU provider's (and any synchronous
    fallback's) ticket-shaped result, so pipelined and synchronous
    codec paths flow through ONE submit/park/resolve code path in the
    broker instead of two diverging branches."""

    __slots__ = ("_result", "_exc")

    def __init__(self, result=None, exc: Optional[BaseException] = None):
        self._result = result
        self._exc = exc

    def done(self) -> bool:
        return True

    def result(self, timeout: Optional[float] = None):
        if self._exc is not None:
            raise self._exc
        return self._result


class _Job:
    __slots__ = ("kind", "bufs", "poly", "ticket", "window", "fn", "args",
                 "t_submit", "topics", "weight")

    def __init__(self, kind, bufs, poly, ticket, window, fn=None, args=()):
        self.kind = kind            # "crc" | "lz4" | "compute" | "host"
        self.bufs = bufs
        self.poly = poly
        self.ticket = ticket
        self.window = window        # may wait the fan-in window
        self.fn = fn
        self.args = args
        self.t_submit = 0.0         # submit() time (stage_latency)
        self.topics: tuple = ()     # QoS: topics riding this job
        self.weight = 1.0           # QoS: max topic.qos.weight of them


class _Staging:
    """Persistent host staging arrays per (B, block) bucket, recycled
    through a ring of ``copies`` buffers so the fill of the next launch
    never overwrites one still feeding an in-flight transfer."""

    def __init__(self, copies: int):
        self.copies = max(2, copies)
        self._rings: dict[tuple[int, int], list[np.ndarray]] = {}
        self._next: dict[tuple[int, int], int] = {}

    def take(self, B: int, N: int) -> np.ndarray:
        key = (B, N)
        ring = self._rings.setdefault(key, [])
        if len(ring) < self.copies:
            arr = np.zeros((B, N), dtype=np.uint8)
            ring.append(arr)
            return arr
        i = self._next.get(key, 0)
        self._next[key] = (i + 1) % self.copies
        arr = ring[i]
        arr.fill(0)
        return arr

    def nbytes(self) -> int:
        return sum(a.nbytes for ring in self._rings.values() for a in ring)


class _Launch:
    """One in-flight device launch awaiting readback."""

    __slots__ = ("kind", "jobs", "spans", "outs", "chunk_lens",
                 "ticket", "out_tree", "t0", "bucket", "lane", "sharded",
                 "raw_blocks")

    def __init__(self, kind):
        self.kind = kind
        self.jobs: list[_Job] = []
        self.spans: list[tuple[int, int]] = []   # (first_block, nblocks)/buf
        self.outs: list = []                     # device arrays per chunk
        self.chunk_lens: list[int] = []          # live rows per chunk
        self.ticket: Optional[Ticket] = None     # compute kind only
        self.out_tree = None
        self.t0: Optional[float] = None          # launch wall-clock start
        self.bucket: Optional[int] = None        # padded B of first chunk
        self.lane: Optional["_Lane"] = None      # dispatch lane (ISSUE 6)
        self.sharded = False                     # shard_map'd over the mesh
        self.raw_blocks: list = []               # lz4: raw bytes per row


class _Lane:
    """One per-device dispatch lane (ISSUE 6): the device, its private
    staging rings (a fill for lane k never races another lane's
    in-flight transfer), its own in-flight launch deque honoring the
    engine ``depth``, and per-device observability counters feeding
    ``codec_engine.devices[]``.  The whole-mesh sharded launches ride a
    pseudo-lane (``dev_id == -1``) with the same depth discipline."""

    __slots__ = ("dev_id", "device", "staging", "inflight", "launches",
                 "blocks", "jobs", "launch_avg")

    def __init__(self, dev_id: int, device, staging: "_Staging",
                 launch_avg):
        self.dev_id = dev_id
        self.device = device            # jax Device (None: mesh lane)
        self.staging = staging
        self.inflight: deque = deque()  # _Launch records, oldest first
        self.launches = 0
        self.blocks = 0
        self.jobs = 0
        self.launch_avg = launch_avg    # per-device stage_latency window


class _Governor:
    """Online policy state for the adaptive offload governor (ISSUE 3).

    Three tiny models, all O(1) EWMAs:

      * ``interarrival_s`` — CRC submission inter-arrival time, updated
        by submitter threads under the engine lock; sizes the fan-in
        window.
      * ``dev_launch_s[(device, bucket)]`` — per-device per-bucket
        launch latency (dispatch → readback complete), updated on the
        dispatch thread.  Mesh-aware (ISSUE 6): routing compares the
        BEST device's estimate against the CPU model, and the engine's
        lane selection uses the per-lane estimate, so a slow or cold
        chip neither poisons the route decision nor hides behind a
        fast one.  A sharded launch records under every participating
        device (the whole mesh was busy for that window).
      * ``cpu_ns_per_byte`` — the CPU provider's observed checksum
        rate, updated whenever the engine serves a group on CPU.

    ``route`` compares the two cost predictions for an at-quorum group
    and periodically explores the unpicked side so a stale estimate
    cannot pin the router forever (host load and tunnel bandwidth both
    drift)."""

    EWMA_ALPHA = 0.25
    EXPLORE_EVERY = 16
    #: per-topic byte-pressure decay applied at each submission of that
    #: topic (the QoS feedback signal, ISSUE 17)
    QOS_DECAY = 0.75
    #: a topic is shed-eligible while saturated once its decayed byte
    #: share exceeds this multiple of its weight share
    QOS_SHED_RATIO = 1.5

    __slots__ = ("enabled", "fanin_cap_s", "interarrival_s",
                 "_last_submit", "cpu_ns_per_byte", "dev_launch_s",
                 "_since_explore", "_glock", "cpu_comp_ns_per_byte",
                 "dev_comp_launch_s", "_since_explore_comp",
                 "qos_weights", "qos_bytes", "qos_routed", "qos_shed")

    def __init__(self, enabled: bool, fanin_cap_s: float):
        self.enabled = bool(enabled)
        self.fanin_cap_s = float(fanin_cap_s)
        # every EWMA below is mutated under _glock: submitters update
        # the arrival model (note_submit), the dispatch thread updates
        # the device/CPU cost models and the explore counter
        # (note_device/note_cpu/route), and the stats emitter reads
        # snapshots from ITS thread — the --races sweep convicted the
        # old lock-free read-modify-writes (an explore-path route()
        # racing snapshot(), a dropped note_device update)
        self._glock = new_lock("engine.governor")
        self.interarrival_s: Optional[float] = None
        self._last_submit: Optional[float] = None
        self.cpu_ns_per_byte: Optional[float] = None
        # (device id, bucket B) -> launch-time EWMA seconds
        self.dev_launch_s: dict[tuple[int, int], float] = {}
        self._since_explore = 0
        # compress cost models (ISSUE 17) — same shapes as the CRC
        # models, but the two routes never share an estimate: an lz4
        # launch is orders of magnitude heavier than a CRC one
        self.cpu_comp_ns_per_byte: Optional[float] = None
        self.dev_comp_launch_s: dict[tuple[int, int], float] = {}
        self._since_explore_comp = 0
        # per-topic QoS state (ISSUE 17): conf'd weights, decayed byte
        # pressure (the feedback signal), and routed/shed counters for
        # codec_engine.compress.qos
        self.qos_weights: dict[str, float] = {}
        self.qos_bytes: dict[str, float] = {}
        self.qos_routed: dict[str, int] = {}
        self.qos_shed: dict[str, int] = {}

    def _ewma(self, old: Optional[float], v: float) -> float:
        return v if old is None else old + self.EWMA_ALPHA * (v - old)

    # ---- submitter side ----
    def note_submit(self, now: float) -> None:
        with self._glock:
            last, self._last_submit = self._last_submit, now
            if last is not None:
                self.interarrival_s = self._ewma(self.interarrival_s,
                                                 now - last)

    # ---- dispatch-thread side ----
    def fanin_window(self, need: int) -> float:
        """Seconds a below-quorum group should wait for ``need`` more
        buffers.  Static cap until the arrival model has data; zero
        when the mean inter-arrival already exceeds the cap (nothing
        will merge — dispatch now, don't tax latency)."""
        cap = self.fanin_cap_s
        with self._glock:
            ia = self.interarrival_s
        if not self.enabled or ia is None:
            return cap
        if ia >= cap:
            return 0.0
        return min(cap, 2.0 * max(1, need) * ia)

    def note_device(self, bucket: Optional[int], dt: float,
                    dev: int = 0) -> None:
        if bucket is not None:
            key = (dev, bucket)
            with self._glock:
                self.dev_launch_s[key] = self._ewma(
                    self.dev_launch_s.get(key), dt)

    def lane_device_s(self, dev: int, bucket: int) -> Optional[float]:
        """The (device, bucket) launch-time estimate — lane selection's
        tie-break (None: the lane hasn't run this bucket yet)."""
        with self._glock:
            return self.dev_launch_s.get((dev, bucket))

    def best_device_s(self, bucket: int) -> Optional[float]:
        """The fastest known device estimate for a bucket — what the
        CPU-vs-device route decision compares against (the engine will
        pick that lane, or a less-loaded one that can only be busy
        because it is also making progress)."""
        with self._glock:
            best = None
            for (d, b), s in self.dev_launch_s.items():
                if b == bucket and (best is None or s < best):
                    best = s
            return best

    def note_cpu(self, nbytes: int, dt: float) -> None:
        if nbytes > 0:
            with self._glock:
                self.cpu_ns_per_byte = self._ewma(self.cpu_ns_per_byte,
                                                  dt * 1e9 / nbytes)

    def route(self, bucket: int, nbytes: int) -> tuple[str, bool]:
        """('device'|'cpu', explored) for an at-quorum group.  Unknown
        estimates prefer the device — exactly the static policy — so
        configs without governor history behave identically."""
        dev = self.best_device_s(bucket)
        with self._glock:
            cpu = self.cpu_ns_per_byte
            if dev is None or cpu is None:
                return "device", False
            pick = "device" if dev <= nbytes * cpu / 1e9 else "cpu"
            self._since_explore += 1
            if self._since_explore >= self.EXPLORE_EVERY:
                self._since_explore = 0
                return ("cpu" if pick == "device" else "device"), True
            return pick, False

    def snapshot(self) -> dict:
        """JSON-ready gauges for the statistics blob.  dev_launch_ms
        keeps its pre-mesh shape — the best (fastest) device estimate
        per bucket; the full per-device split rides
        codec_engine.devices[]."""
        with self._glock:
            dev_launch = dict(self.dev_launch_s)
            ia = self.interarrival_s
            cpu = self.cpu_ns_per_byte
        best: dict[int, float] = {}
        for (d, b), s in dev_launch.items():
            if b not in best or s < best[b]:
                best[b] = s
        return {
            "enabled": self.enabled,
            "interarrival_us": (None if ia is None
                                else round(ia * 1e6, 1)),
            "cpu_ns_per_byte": (None if cpu is None
                                else round(cpu, 3)),
            "dev_launch_ms": {str(b): round(s * 1e3, 3)
                              for b, s in sorted(best.items())},
        }

    def device_launch_ms(self, dev: int) -> dict:
        """One device's {bucket: ms} EWMAs (codec_engine.devices[])."""
        with self._glock:
            items = sorted(self.dev_launch_s.items())
        return {str(b): round(s * 1e3, 3)
                for (d, b), s in items if d == dev}

    # ---- compress route (ISSUE 17) ----
    def note_topics(self, entries) -> None:
        """Submitter side: fold one compress submission into the QoS
        models — ``entries`` is (topic, weight, nbytes) per topic."""
        with self._glock:
            for topic, w, nbytes in entries:
                self.qos_weights[topic] = float(w)
                self.qos_bytes[topic] = (
                    self.qos_bytes.get(topic, 0.0) * self.QOS_DECAY
                    + float(nbytes))

    def note_device_compress(self, bucket: Optional[int], dt: float,
                             dev: int = 0) -> None:
        if bucket is not None:
            key = (dev, bucket)
            with self._glock:
                self.dev_comp_launch_s[key] = self._ewma(
                    self.dev_comp_launch_s.get(key), dt)

    def note_cpu_compress(self, nbytes: int, dt: float) -> None:
        if nbytes > 0:
            with self._glock:
                self.cpu_comp_ns_per_byte = self._ewma(
                    self.cpu_comp_ns_per_byte, dt * 1e9 / nbytes)

    def lane_compress_s(self, dev: int, bucket: int) -> Optional[float]:
        with self._glock:
            return self.dev_comp_launch_s.get((dev, bucket))

    def route_compress(self, bucket: int, nbytes: int) -> tuple[str, bool]:
        """('device'|'cpu', explored) for an at-quorum compress group —
        the CRC route() shape on the compress cost models (an lz4
        launch and a CRC launch share nothing but the policy)."""
        with self._glock:
            best = None
            for (d, b), s in self.dev_comp_launch_s.items():
                if b == bucket and (best is None or s < best):
                    best = s
            cpu = self.cpu_comp_ns_per_byte
            if best is None or cpu is None:
                return "device", False
            pick = "device" if best <= nbytes * cpu / 1e9 else "cpu"
            self._since_explore_comp += 1
            if self._since_explore_comp >= self.EXPLORE_EVERY:
                self._since_explore_comp = 0
                return ("cpu" if pick == "device" else "device"), True
            return pick, False

    def shed_topics(self, saturated: bool) -> set:
        """Topics whose decayed byte share exceeds QOS_SHED_RATIO × the
        share their conf'd weight entitles them to — ONLY while every
        lane is saturated (QoS never sheds an idle engine) and never
        the whole topic set (something must keep flowing)."""
        if not (self.enabled and saturated):
            return set()
        with self._glock:
            if len(self.qos_weights) < 2:
                return set()
            tot_w = sum(self.qos_weights.values()) or 1.0
            tot_b = sum(self.qos_bytes.values())
            if tot_b <= 0:
                return set()
            out = {t for t, w in self.qos_weights.items()
                   if (self.qos_bytes.get(t, 0.0) / tot_b
                       > self.QOS_SHED_RATIO * (w / tot_w))}
            return out if len(out) < len(self.qos_weights) else set()

    def note_qos(self, topics, *, shed: bool) -> None:
        """Dispatch-thread side: count a job's topics as device-routed
        or shed (codec_engine.compress.qos)."""
        if topics:
            with self._glock:
                tgt = self.qos_shed if shed else self.qos_routed
                for t in topics:
                    tgt[t] = tgt.get(t, 0) + 1

    def compress_models(self) -> dict:
        """The compress cost models for codec_engine.compress.model —
        the governor snapshot() shape on the compress EWMAs."""
        with self._glock:
            dev = dict(self.dev_comp_launch_s)
            cpu = self.cpu_comp_ns_per_byte
        best: dict[int, float] = {}
        for (d, b), s in dev.items():
            if b not in best or s < best[b]:
                best[b] = s
        return {"cpu_ns_per_byte": (None if cpu is None
                                    else round(cpu, 3)),
                "dev_launch_ms": {str(b): round(s * 1e3, 3)
                                  for b, s in sorted(best.items())}}

    def qos_snapshot(self) -> dict:
        """Per-topic {weight, routed, shed} (codec_engine.compress.qos)."""
        with self._glock:
            topics = (set(self.qos_weights) | set(self.qos_routed)
                      | set(self.qos_shed))
            return {t: {"weight": self.qos_weights.get(t, 1.0),
                        "routed": self.qos_routed.get(t, 0),
                        "shed": self.qos_shed.get(t, 0)}
                    for t in sorted(topics)}


# the governor's online models are cross-thread by design — submitters
# feed the arrival EWMA, the dispatch thread the cost models, the
# stats emitter reads snapshots; all serialized under engine.governor
# since ISSUE 10 (the --races sweep convicted the old lock-free RMWs)
register_slots(_Governor, "interarrival_s", "_last_submit",
               "cpu_ns_per_byte", "dev_launch_s", "_since_explore",
               "cpu_comp_ns_per_byte", "dev_comp_launch_s",
               "_since_explore_comp", "qos_weights", "qos_bytes",
               "qos_routed", "qos_shed",
               prefix="engine.governor")


class AsyncOffloadEngine:
    """Double-buffered producer/consumer pipeline around the MXU CRC
    kernels (and, generically, any jitted step fn via
    :meth:`submit_compute`)."""

    #: every bucket shape a launch can produce: next_pow2 has a 64-row
    #: floor (packing.py) and 64-block chunks pad to the 128-row MXU
    #: tile, so B is always one of exactly these three
    WARM_BUCKETS = (64, 128, 256)
    WARM_KINDS = ("crc32c", "crc32", "fused")
    #: minimum blocks PER DEVICE before a group splits across the mesh
    #: (below it, whole-to-one-lane beats the scatter/gather overhead)
    SHARD_MIN_ROWS = 8

    # lockset-checked shared state (analysis/races.py): the submit
    # queue, warm-request queue and closed flag cross submitter /
    # dispatch / warmup threads under engine.queue.  The lane list and
    # gauges are relaxed: lanes are written ONCE under engine.lanes
    # (the pre-ready read outside the lock only ever sees the final
    # value or triggers the locked double-check), and the gauges are
    # single-writer dispatch-thread ints read as snapshots by the
    # stats emitter — atomic under the GIL, torn reads impossible.
    _queue = shared("engine.queue.jobs")
    _warm_requests = shared("engine.warm_requests")
    _closed = shared("engine.closed")
    _lanes = shared("engine.lanes_list", relaxed=True)
    _shard_lane = shared("engine.shard_lane", relaxed=True)
    _lanes_ready = shared("engine.lanes_ready", relaxed=True)
    _inflight_cnt = shared("engine.gauge.inflight", relaxed=True)
    _fanin_last = shared("engine.gauge.fanin", relaxed=True)

    #: max rows per lz4 launch chunk: the compress kernel is far
    #: heavier than the CRC matmul, so chunks stay small enough that a
    #: launch never monopolizes a lane (64 x 64KB = 4 MB staged)
    LZ4_MAX_B = 64

    def __init__(self, *, depth: int = 2, fanin_window_s: float = 0.0005,
                 min_batches: int = 4,
                 cpu_fallback: Optional[Callable] = None,
                 name: str = "tpu-engine",
                 governor: bool = True, warmup: bool = False,
                 compile_cache_dir: Optional[str] = None,
                 mesh_devices: int = 0,
                 cpu_compress_fallback: Optional[Callable] = None):
        # depth: launches kept in flight PER LANE before that lane's
        # oldest is read back
        self.depth = max(1, int(depth))
        self.fanin_window_s = max(0.0, float(fanin_window_s))
        self.min_batches = max(1, int(min_batches))
        # cpu_fallback(bufs, poly) -> list[int]; serves below-quorum jobs
        self.cpu_fallback = cpu_fallback
        # cpu_compress_fallback(bufs) -> list[bytes]: the deterministic
        # (bit-exact with the device kernel) lz4 frame encoder serving
        # below-quorum / unwarmed / cpu-routed / shed compress jobs
        self.cpu_compress_fallback = cpu_compress_fallback
        # the adaptive policy layer; fanin_window_s is its CAP
        self.governor = _Governor(governor, self.fanin_window_s)
        # warmup=True: kernels compile on the background thread and
        # unwarmed buckets route to the CPU provider; warmup=False
        # keeps the old behavior (dispatch thread compiles inline)
        self.warmup_enabled = bool(warmup) and cpu_fallback is not None
        self.compile_cache_dir = compile_cache_dir or None
        # tpu.mesh.devices: how many devices to spread dispatch lanes
        # over — 0 = every visible device, 1 = the pre-mesh single-lane
        # engine.  Lanes resolve lazily on the dispatch/warmup thread
        # (jax stays unimported for host-only workloads).
        self.mesh_devices = int(mesh_devices)
        self._lanes: list[_Lane] = []
        self._shard_lane: Optional[_Lane] = None
        self._lanes_ready = False
        self._lanes_lock = new_lock("engine.lanes")
        self._lock = new_lock("engine.queue")
        self._cond = new_cond("engine.queue", self._lock)
        self._queue: deque[_Job] = deque()
        self._closed = False
        # warm items the dispatch thread missed on — the warmup thread
        # compiles these before continuing its sweep; items are
        # ("kernel", B, kind, dev_id), ("shard", Bs, kind) or
        # ("lz4", B, N, dev_id) (compress buckets warm on demand only:
        # the lz4 kernel's shapes depend on live block sizes)
        self._warm_requests: deque[tuple] = deque()
        # observability (PERF.md pipeline section + governor counters).
        # Declared relaxed: single-writer (the dispatch thread —
        # warmup_compiled moved under engine.queue in ISSUE 10, the one
        # other-thread bump the sweep found) with snapshot readers
        # (tests, the stats emitter); int cell reads are atomic under
        # the GIL.
        self.stats = shared_dict("engine.stats", relaxed=True)
        self.stats.update(
            {"launches": 0, "blocks": 0, "jobs": 0,
             "aggregated": 0, "cpu_fallback_jobs": 0,
             "fanin_waits": 0, "host_jobs": 0,
             # governor decisions (ISSUE 3)
             "fanin_skips": 0, "warmup_miss_jobs": 0,
             "warmup_compiled": 0, "routed_cpu_jobs": 0,
             "explore_routes": 0, "fused_launches": 0,
             # mesh-sharded dispatch (ISSUE 6)
             "sharded_launches": 0})
        # device-compress route counters (ISSUE 17), kept separate from
        # the CRC stats: codec_engine.compress in the statistics JSON.
        # Same discipline as .stats — single-writer dispatch thread
        # (warmup bumps ride the engine lock), snapshot readers.
        self.compress_stats = shared_dict("engine.compress_stats",
                                          relaxed=True)
        self.compress_stats.update(
            {"launches": 0, "blocks": 0, "jobs": 0, "cpu_jobs": 0,
             "warmup_miss_jobs": 0, "routed_cpu_jobs": 0,
             "explore_routes": 0, "fused_crc": 0, "shed_jobs": 0,
             "bytes_in": 0, "bytes_out": 0})
        # per-bucket route split {str(B): {"device": n, "cpu": n}}
        self._comp_routed = shared_dict("engine.compress_routed",
                                        relaxed=True)
        # per-stage latency decomposition (ISSUE 5): windowed
        # HdrHistogram Avgs feeding codec_engine.stage_latency in the
        # stats JSON — submit->launch wait, launch->readback (device),
        # and the host-side reap (combine + slice).  Lazy import: the
        # client package only reaches utils from stats.py, so there is
        # no cycle, but keeping it out of module scope lets
        # `import librdkafka_tpu.ops.engine` stay light.
        from ..client.stats import Avg
        self._Avg = Avg                 # lanes build their own windows
        self.stage_submit_wait = Avg()
        self.stage_launch = Avg()
        self.stage_reap = Avg()
        # instantaneous gauges (codec_engine.gauges): in-flight launch
        # depth and the last fan-in occupancy (buffers present when a
        # below-quorum group stopped waiting)
        self._inflight_cnt = 0
        self._fanin_last = 0
        self._thread = threading.Thread(target=self._main, daemon=True,
                                        name=name)
        self._thread.start()
        self._warmup_thread = None
        if self.warmup_enabled:
            # name contains "engine" so the conftest thread-leak
            # fixture covers it like the dispatch thread
            self._warmup_thread = threading.Thread(
                target=self._warmup_main, daemon=True,
                name=name + "-warmup")
            self._warmup_thread.start()

    # ------------------------------------------------------------ public --
    def submit(self, bufs: list, poly: str = "crc32c",
               window: bool = True) -> Ticket:
        """Queue a CRC job; returns immediately.  ``window=False`` skips
        the fan-in wait (synchronous callers that already meet the
        quorum shouldn't pay the aggregation latency — whatever is
        queued at dispatch time still merges in)."""
        t = Ticket()
        job = _Job("crc", [bytes(b) for b in bufs], poly, t, window)
        job.t_submit = time.perf_counter()
        with self._cond:
            if self._closed:
                raise RuntimeError("engine closed")
            self.governor.note_submit(time.monotonic())
            self._queue.append(job)
            self._cond.notify()
        return t

    def submit_compress(self, bufs: list, *, qos=None,
                        window: bool = True) -> Ticket:
        """Queue a device lz4 compress job; resolves to one assembled
        LZ4F frame per buffer — a :class:`packing.FrameBlob` (bytes
        plus the crc32c of each frame part, from the fused
        compress→CRC launch) on the device route, plain ``bytes`` when
        the deterministic CPU fallback served it.  Bit-identical frames
        either way.  ``qos`` is an optional (topic, weight) pair per
        buffer (topic.qos.weight): the max weight shortens this job's
        fan-in wait and orders it ahead of lighter work; the topic
        byte-pressure feeds the governor's shed decision."""
        t = Ticket()
        job = _Job("lz4", [bytes(b) for b in bufs], None, t, window)
        job.t_submit = time.perf_counter()
        if qos:
            per: dict[str, list] = {}
            wmax = 1.0
            for (topic, w), b in zip(qos, bufs):
                e = per.get(topic)
                if e is None:
                    per[topic] = [float(w), len(b)]
                else:
                    e[1] += len(b)
                wmax = max(wmax, float(w))
            job.topics = tuple(sorted(per))
            job.weight = wmax
            self.governor.note_topics(
                [(topic, w, nb) for topic, (w, nb) in per.items()])
        with self._cond:
            if self._closed:
                raise RuntimeError("engine closed")
            self.governor.note_submit(time.monotonic())
            self._queue.append(job)
            self._cond.notify()
        return t

    def submit_compute(self, fn, *args, host: bool = False,
                       weight: float = 1.0) -> Ticket:
        """Generic pipelined dispatch: run ``fn(*args)`` on the dispatch
        thread.  ``host=False`` treats the return value as a tree of
        device arrays with the same in-flight depth and bulk-readback
        discipline (drives models/codec_step.py through the engine);
        ``host=True`` runs a plain host function (e.g. the native
        ``*_decompress_many`` paths of the consumer fetch seam) to
        completion on the dispatch thread and resolves the ticket with
        its raw return value — no jax import, no readback.  A host job
        naturally overlaps any device launch already in flight: the
        device executes while the dispatch thread runs the (GIL-
        releasing) native call.  ``weight`` is the QoS priority (max
        topic.qos.weight riding the job): the dispatch loop stable-
        sorts popped jobs by descending weight, so a latency topic's
        host compress never queues behind a bulk flood's."""
        t = Ticket()
        job = _Job("host" if host else "compute", None, None, t, False,
                   fn=fn, args=args)
        job.weight = float(weight)
        job.t_submit = time.perf_counter()
        with self._cond:
            if self._closed:
                raise RuntimeError("engine closed")
            self._queue.append(job)
            self._cond.notify()
        return t

    def close(self, timeout: float = 30.0) -> None:
        """Stop the dispatch thread.  Outstanding work drains
        deterministically — PER LANE: every lane's queued + in-flight
        launches are completed by the exiting thread (the _main finally
        sweeps each lane's deque), and anything it could not reach (a
        wedged or crashed dispatch thread, or a join timeout) is FAILED
        rather than left to hang its waiter forever in
        Ticket.result().  A multi-lane engine also releases the mesh
        module's compiled sharded steps (the close-time hook the
        conftest leak fixture asserts)."""
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._thread.join(timeout)
        if self._warmup_thread is not None:
            # the warmup thread checks _closed between kernels; an XLA
            # compile in progress finishes (it cannot be cancelled) and
            # the thread exits — deterministic drain, no leak
            self._warmup_thread.join(timeout)
        import sys
        if self._shard_lane is not None:
            mesh_mod = sys.modules.get("librdkafka_tpu.parallel.mesh")
            if mesh_mod is not None:
                mesh_mod.release_step_cache()
        # the engine-owned fused/AOT compress kernels never outlive the
        # engine (ISSUE 17 satellite — the conftest leak fixture
        # asserts device_kernel_count() == 0); sys.modules guard keeps
        # close() jax-free for host-only engines
        lz4_mod = sys.modules.get("librdkafka_tpu.ops.lz4_jax")
        if lz4_mod is not None:
            lz4_mod.release_device_kernels()
        if self._thread.is_alive():
            # join timed out: the dispatch thread is wedged (e.g. a hung
            # device launch).  Fail every job still visible so waiters
            # unblock; first-resolution-wins keeps this safe against the
            # thread completing them concurrently.
            with self._cond:
                stranded = self._pop_jobs_locked()
            exc = RuntimeError("offload engine closed (dispatch thread "
                               "did not exit in time)")
            for j in stranded:
                j.ticket._fail(exc)

    def warm_wait(self, B: int, poly: str = "crc32c",
                  timeout: float = 120.0, device=None) -> bool:
        """Block until the (B, 64KB, poly) kernel bucket is compiled
        for ``device`` (default: the default device / lane 0 — the
        first the sweep warms); test/bench hook; returns False on
        timeout."""
        from .crc32c_jax import _MXU_BLOCK, kernel_ready
        deadline = time.monotonic() + timeout
        while not kernel_ready(B, _MXU_BLOCK, poly, device=device):
            if time.monotonic() >= deadline or self._is_closed():
                return kernel_ready(B, _MXU_BLOCK, poly, device=device)
            time.sleep(0.02)
        return True

    def _is_closed(self) -> bool:
        """Locked read of the closed flag for the warmup thread and
        test hooks (the dispatch loop reads it under the condvar it
        already holds)."""
        with self._lock:
            return self._closed

    def lz4_warm_wait(self, B: int, N: int, timeout: float = 120.0,
                      device=None) -> bool:
        """Block until the fused (B, N) compress bucket is compiled for
        ``device`` (test/bench hook, the warm_wait shape); returns
        False on timeout."""
        from . import lz4_jax as _lz4
        deadline = time.monotonic() + timeout
        while not _lz4.kernel_ready(B, N, device=device):
            if time.monotonic() >= deadline or self._is_closed():
                return _lz4.kernel_ready(B, N, device=device)
            time.sleep(0.02)
        return True

    def governor_snapshot(self) -> dict:
        """Governor gauges for the statistics JSON (client/stats.py).
        Never imports jax — safe to call from the stats emitter even
        before the first launch."""
        snap = self.governor.snapshot()
        snap["warmup"] = self.warmup_enabled
        return snap

    def compress_snapshot(self) -> dict:
        """The device-compress route blob for the statistics JSON
        (codec_engine.compress, STATISTICS.md): route counters, bytes
        in/out, the per-bucket device/cpu split, the governor's
        compress cost models and the per-topic QoS table.  Never
        imports jax — safe from the stats emitter."""
        snap = dict(self.compress_stats)
        snap["routed"] = {b: dict(v)
                          for b, v in sorted(self._comp_routed.items())}
        snap["model"] = self.governor.compress_models()
        snap["qos"] = self.governor.qos_snapshot()
        return snap

    def stage_latency_snapshot(self) -> dict:
        """Per-stage windowed latency decomposition for the stats JSON
        (codec_engine.stage_latency, STATISTICS.md): submit->launch
        wait, launch->readback (device round trip), the host-side reap,
        and the per-device launch split (``launch_dev``, keyed by
        device id) so launch latency is attributable per chip.  Rolls
        the windows over, like every rd_avg_t emit."""
        return {"submit_wait": self.stage_submit_wait.rollover(),
                "launch": self.stage_launch.rollover(),
                "reap": self.stage_reap.rollover(),
                "launch_dev": {str(ln.dev_id): ln.launch_avg.rollover()
                               for ln in self._lanes}}

    def gauges_snapshot(self) -> dict:
        """Instantaneous pipeline-occupancy gauges (codec_engine.gauges):
        queued jobs not yet popped by the dispatch thread, launches in
        flight awaiting readback, and the buffer count the last fan-in
        window closed with."""
        return {"queue_depth": len(self._queue),
                "inflight_launches": self._inflight_cnt,
                "fanin_occupancy": self._fanin_last}

    def devices_snapshot(self) -> list:
        """Per-device lane gauges for the statistics JSON
        (codec_engine.devices[], STATISTICS.md): launch/block/job
        counts, in-flight depth, the governor's per-bucket launch-time
        EWMAs and the warm-kernel count for each mesh device.  Empty
        until the first launch resolves the lanes.  Never imports jax
        (sys.modules guard) — safe from the stats emitter."""
        import sys
        cj = sys.modules.get("librdkafka_tpu.ops.crc32c_jax")
        out = []
        for ln in self._lanes:
            out.append({
                "id": ln.dev_id,
                "launches": ln.launches,
                "blocks": ln.blocks,
                "jobs": ln.jobs,
                "inflight": len(ln.inflight),
                "dev_launch_ms": self.governor.device_launch_ms(
                    ln.dev_id),
                "warm_buckets": (cj.warm_bucket_count(ln.dev_id)
                                 if cj is not None else 0),
            })
        return out

    # ------------------------------------------------------------- lanes --
    def _get_lanes(self) -> list:
        """Resolve the per-device dispatch lanes (dispatch/warmup
        thread only — imports jax).  mesh_devices=0 takes every visible
        device; a >1 lane count also creates the whole-mesh pseudo-lane
        that tracks sharded launches."""
        if self._lanes_ready:
            return self._lanes
        with self._lanes_lock:
            if self._lanes_ready:
                return self._lanes
            import jax
            devs = jax.devices()
            n = (len(devs) if self.mesh_devices <= 0
                 else min(self.mesh_devices, len(devs)))
            lanes = [_Lane(d.id, d, _Staging(copies=self.depth + 1),
                           self._Avg()) for d in devs[:n]]
            if n > 1:
                self._shard_lane = _Lane(
                    -1, None, _Staging(copies=self.depth + 1),
                    self._Avg())
            self._lanes = lanes
            self._lanes_ready = True
        return self._lanes

    def _all_lanes(self) -> list:
        return (self._lanes + [self._shard_lane]
                if self._shard_lane is not None else self._lanes)

    def _inflight_total(self) -> int:
        return sum(len(ln.inflight) for ln in self._all_lanes())

    def _oldest_lane(self) -> Optional["_Lane"]:
        """The lane holding the oldest in-flight launch (drain order:
        by dispatch time across lanes, so no lane's results are held
        hostage behind a busier one)."""
        best = None
        for ln in self._all_lanes():
            if not ln.inflight:
                continue
            if best is None or ((ln.inflight[0].t0 or 0.0)
                                < (best.inflight[0].t0 or 0.0)):
                best = ln
        return best

    # ----------------------------------------------------- warmup thread --
    def _request_warm(self, item: tuple) -> None:
        """Dispatch-thread side: a launch missed this bucket — move it
        to the front of the warmup queue.  ``item`` is
        ("kernel", B, kind, dev_id) or ("shard", Bs, kind)."""
        with self._lock:
            if item not in self._warm_requests:
                self._warm_requests.append(item)

    def _warmup_main(self):
        """Low-priority sweep compiling every (B, 64KB) bucket for both
        polynomials + the fused variant ON EVERY LANE (device 0 first,
        so routes open exactly as fast as the single-device sweep did,
        then the remaining chips fill in), followed by the sharded
        whole-mesh steps for the standard buckets; items the dispatch
        thread actually missed on jump the queue.  Exits when the
        sweep is complete or the engine closes."""
        try:
            if self.compile_cache_dir:
                # persistent compile cache: kernels compile once per
                # machine instead of once per process
                try:
                    import jax
                    jax.config.update("jax_compilation_cache_dir",
                                      self.compile_cache_dir)
                    for knob, v in (
                            ("jax_persistent_cache_min_compile_time_secs",
                             0),
                            ("jax_persistent_cache_min_entry_size_bytes",
                             0)):
                        try:
                            jax.config.update(knob, v)
                        except Exception:
                            pass
                except Exception:
                    pass
            from .crc32c_jax import _MXU_BLOCK, kernel_ready, warm_kernel
            lanes = self._get_lanes()
            by_id = {ln.dev_id: ln for ln in lanes}
            sweep: list[tuple] = [("kernel", B, kind, ln.dev_id)
                                  for ln in lanes
                                  for B in self.WARM_BUCKETS
                                  for kind in self.WARM_KINDS]
            if len(lanes) > 1:
                # whole-mesh sharded steps for the standard per-shard
                # buckets; odd shapes warm on demand via requests
                sweep += [("shard", Bs, kind)
                          for Bs in self.WARM_BUCKETS
                          for kind in self.WARM_KINDS]
            i = 0
            while True:
                with self._lock:
                    if self._closed:
                        return
                    item = (self._warm_requests.popleft()
                            if self._warm_requests else None)
                if item is None:
                    if i >= len(sweep):
                        return
                    item = sweep[i]
                    i += 1
                try:
                    if item[0] == "kernel":
                        _, B, kind, dev_id = item
                        if kernel_ready(B, _MXU_BLOCK, kind,
                                        device=dev_id):
                            continue
                        lane = by_id.get(dev_id)
                        warm_kernel(B, _MXU_BLOCK, kind,
                                    device=(lane.device if lane
                                            else None))
                    elif item[0] == "lz4":
                        _, B, N, dev_id = item
                        from . import lz4_jax as _lz4
                        if _lz4.kernel_ready(B, N, device=dev_id):
                            continue
                        lane = by_id.get(dev_id)
                        _lz4.warm_kernel(B, N,
                                         device=(lane.device if lane
                                                 else None))
                    else:
                        _, Bs, kind = item
                        from ..parallel import mesh as _mesh
                        ids = [ln.dev_id for ln in lanes]
                        if _mesh.sharded_crc_ready(ids, Bs, _MXU_BLOCK,
                                                   kind):
                            continue
                        _mesh.warm_sharded_crc(
                            [ln.device for ln in lanes], Bs,
                            _MXU_BLOCK, kind)
                    # counted under the engine lock: this is the one
                    # stats write NOT on the dispatch thread (the
                    # --races sweep flagged the bare += here)
                    with self._lock:
                        self.stats["warmup_compiled"] += 1
                except Exception:
                    # a failing compile must never kill warmup; the
                    # bucket simply stays CPU-routed
                    pass
        except Exception:
            pass

    # ---------------------------------------------------- dispatch thread --
    def _main(self):
        try:
            self._main_loop()
        finally:
            # deterministic shutdown: whether the loop exited cleanly
            # (drained) or died on an unexpected error, no ticket may be
            # left unresolved — a parked _PendingFetch/_PendingCodec
            # would otherwise block its thread forever in result().
            # Every LANE fail-or-drains (the PR-2 semantics, per lane):
            # in-flight launches of chip k fail exactly like the
            # single-device engine's did.
            with self._cond:
                stranded = self._pop_jobs_locked()
            exc = RuntimeError("offload engine dispatch thread exited")
            for j in stranded:
                j.ticket._fail(exc)
            for lane in self._all_lanes():
                for rec in lane.inflight:
                    if rec.kind in ("crc", "lz4"):
                        for j in rec.jobs:
                            j.ticket._fail(exc)
                    elif rec.ticket is not None:
                        rec.ticket._fail(exc)
                lane.inflight.clear()

    def _main_loop(self):
        while True:
            with self._cond:
                if not self._queue and not self._closed:
                    # with launches in flight, linger only briefly: a
                    # pipelining submitter's NEXT job should launch
                    # before the oldest readback blocks this thread
                    self._cond.wait(
                        timeout=0.0002 if self._inflight_total() else None)
                if (self._closed and not self._queue
                        and not self._inflight_total()):
                    return
                jobs = self._pop_jobs_locked()
            if jobs:
                jobs = self._fanin(jobs)
                # QoS priority ordering: heavier (latency-sensitive)
                # jobs launch first; the sort is stable, so the default
                # weight 1.0 preserves submission order exactly
                jobs.sort(key=lambda j: -j.weight)
                for group in self._group(jobs):
                    rec = self._launch(group)
                    if rec is not None:
                        lane = rec.lane
                        lane.inflight.append(rec)
                        # lane pipeline full: sync that lane's oldest —
                        # every other lane's launches keep executing on
                        # their chips meanwhile
                        while len(lane.inflight) > self.depth:
                            self._inflight_cnt = self._inflight_total()
                            self._readback(lane.inflight.popleft())
                    self._inflight_cnt = self._inflight_total()
                continue            # re-check the queue before syncing
            lane = self._oldest_lane()
            if lane is not None:
                # nothing new queued: drain completed work rather than
                # hold results hostage waiting for more submissions
                self._readback(lane.inflight.popleft())
                self._inflight_cnt = self._inflight_total()

    def _pop_jobs_locked(self) -> list[_Job]:
        jobs = list(self._queue)
        self._queue.clear()
        return jobs

    def _fanin(self, jobs: list[_Job]) -> list[_Job]:
        """Bounded fan-in: when the windowed CRC jobs are below the
        launch quorum, wait for more submitters (the cross-broker
        micro-batch aggregation) before dispatching.  The wait is sized
        by the governor from the submission inter-arrival EWMA —
        ``fanin_window_s`` is the cap; a zero adaptive window (mean
        inter-arrival beyond the cap: nothing will merge) dispatches
        immediately, so low-rate traffic stops paying the latency
        tax."""
        if self.fanin_window_s <= 0:
            return jobs
        nbufs = sum(len(j.bufs) for j in jobs
                    if j.kind in ("crc", "lz4") and j.window)
        if nbufs == 0 or nbufs >= self.min_batches:
            return jobs
        # weighted admission (ISSUE 17): the heaviest topic riding this
        # window divides the wait — a latency-sensitive topic is not
        # taxed the full aggregation window a bulk topic would be
        wmax = max((j.weight for j in jobs
                    if j.kind in ("crc", "lz4") and j.window),
                   default=1.0)
        window = (self.governor.fanin_window(self.min_batches - nbufs)
                  / max(1.0, wmax))
        if window <= 0:
            self.stats["fanin_skips"] += 1
            self._fanin_last = nbufs
            if _trace.enabled:
                _trace.instant("engine", "fanin_skip",
                               {"bufs": nbufs, "need": self.min_batches})
            return jobs
        self.stats["fanin_waits"] += 1
        t0 = _trace.now() if _trace.enabled else 0
        deadline = time.monotonic() + window
        with self._cond:
            while nbufs < self.min_batches:
                left = deadline - time.monotonic()
                if left <= 0 or self._closed:
                    break
                self._cond.wait(left)
                more = self._pop_jobs_locked()
                jobs.extend(more)
                nbufs += sum(len(j.bufs) for j in more
                             if j.kind in ("crc", "lz4") and j.window)
        self._fanin_last = nbufs
        if t0:
            _trace.complete("engine", "fanin_wait", t0,
                            {"bufs": nbufs, "need": self.min_batches,
                             "window_us": round(window * 1e6, 1)})
        return jobs

    def _group(self, jobs: list[_Job]):
        """Launch groups: CRC jobs merge per polynomial (shared kernel
        shape) — or across BOTH polynomials into one fused launch when
        the governor is on (per-row Q selection, _jit_mxu_fused), so a
        mixed v2/legacy fetch response pays one launch instead of two.
        lz4 compress jobs merge into one group the same way (shared
        fused compress→CRC kernel shape).  Compute/host jobs launch
        individually."""
        by_poly: dict[str, list[_Job]] = {}
        lz4_group: list[_Job] = []
        order = []
        for j in jobs:
            if j.kind == "lz4":
                if not lz4_group:
                    order.append(lz4_group)
                lz4_group.append(j)
            elif j.kind != "crc":
                order.append([j])
            else:
                if j.poly not in by_poly:
                    by_poly[j.poly] = []
                    order.append(by_poly[j.poly])
                by_poly[j.poly].append(j)
        if self.governor.enabled and len(by_poly) > 1:
            # fuse: one merged group replaces the per-poly groups, at
            # the position of the first CRC group (submission order of
            # non-CRC jobs preserved)
            merged = [j for j in jobs if j.kind == "crc"]
            fused_order = []
            placed = False
            for g in order:
                if g and g[0].kind == "crc":
                    if not placed:
                        fused_order.append(merged)
                        placed = True
                else:
                    fused_order.append(g)
            return fused_order
        return order

    # -------------------------------------------------------------- launch --
    def _launch(self, group: list[_Job]) -> Optional[_Launch]:
        try:
            if group[0].kind == "host":
                # host compute (native decompress/compress): runs to
                # completion here, overlapping whatever device launches
                # are already in flight
                job = group[0]
                self.stats["host_jobs"] += 1
                t0 = _trace.now() if _trace.enabled else 0
                job.ticket._complete(job.fn(*job.args))
                if t0:
                    _trace.complete(
                        "engine", "host_job", t0,
                        {"fn": getattr(job.fn, "__name__", "host")})
                return None
            if group[0].kind == "compute":
                return self._launch_compute(group[0])
            if group[0].kind == "lz4":
                return self._launch_lz4(group)
            return self._launch_crc(group)
        except Exception as e:
            for j in group:
                j.ticket._fail(e)
            return None

    def _launch_compute(self, job: _Job) -> _Launch:
        rec = _Launch("compute")
        rec.ticket = job.ticket
        # compute fns place their own arrays; track the launch on lane
        # 0 (the default device) for depth accounting and drain order
        rec.lane = self._get_lanes()[0]
        rec.t0 = time.perf_counter()
        rec.out_tree = job.fn(*job.args)     # async dispatch
        return rec

    def _serve_cpu(self, group: list[_Job], counter: str) -> None:
        """Serve a group on the CPU provider (bit-identical), timing it
        into the governor's CPU cost estimate."""
        self.stats[counter] += len(group)
        t0 = time.perf_counter()
        tr0 = _trace.now() if _trace.enabled else 0
        nbytes = 0
        for j in group:
            try:
                vals = self.cpu_fallback(j.bufs, j.poly)
                j.ticket._complete(np.asarray(vals, dtype=np.uint32))
                nbytes += sum(len(b) for b in j.bufs)
            except Exception as e:
                j.ticket._fail(e)
        self.governor.note_cpu(nbytes, time.perf_counter() - t0)
        if tr0:
            # route decision attached as span args (the governor's
            # reason is exactly the stats counter it bumped)
            _trace.complete("engine", "cpu_serve", tr0,
                            {"route": "cpu", "reason": counter,
                             "jobs": len(group), "bytes": nbytes})

    def _serve_cpu_compress(self, group: list[_Job], counter: str, *,
                            shed: bool = False) -> None:
        """Serve a compress group on the deterministic CPU encoder
        (bit-identical frames by construction — lz4_jax implements the
        same TPU-greedy spec as native/codec.cpp), timing it into the
        governor's compress cost model."""
        self.compress_stats[counter] += len(group)
        t0 = time.perf_counter()
        tr0 = _trace.now() if _trace.enabled else 0
        nbytes = 0
        for j in group:
            try:
                j.ticket._complete(self.cpu_compress_fallback(j.bufs))
                nbytes += sum(len(b) for b in j.bufs)
            except Exception as e:
                j.ticket._fail(e)
            self.governor.note_qos(j.topics, shed=shed)
        self.governor.note_cpu_compress(nbytes,
                                        time.perf_counter() - t0)
        if tr0:
            _trace.complete("engine", "cpu_serve", tr0,
                            {"route": "cpu", "reason": counter,
                             "kind": "compress", "jobs": len(group),
                             "bytes": nbytes})

    def _note_comp_route(self, bucket: int, side: str) -> None:
        """Per-bucket device/cpu route split (codec_engine.compress
        .routed) — dispatch-thread-only writes."""
        d = self._comp_routed.get(str(bucket))
        if d is None:
            d = {"device": 0, "cpu": 0}
            self._comp_routed[str(bucket)] = d
        d[side] += 1

    def _launch_lz4(self, group: list[_Job]) -> Optional[_Launch]:
        """The device compress route (ISSUE 17): blocks bucketed into
        the lane staging rings exactly like CRC, one fused
        compress→CRC launch per chunk, governed by the compress cost
        models.  Every fallback (below-quorum, unwarmed bucket,
        cpu-routed, QoS-shed) serves the deterministic CPU encoder —
        bit-identical frames on every route."""
        from . import lz4_jax as _lz4
        from .packing import LZ4F_BLOCKSIZE, lz4f_frame, next_pow2

        self.compress_stats["jobs"] += len(group)
        can_cpu = self.cpu_compress_fallback is not None

        # QoS shed: while every lane is saturated, flood topics (byte
        # share beyond what their weight entitles them to) divert to
        # the CPU encoder so the device stays available for the
        # latency-sensitive rest — never the whole group
        if can_cpu and len(group) > 1 and self._lanes_ready:
            saturated = (self._inflight_total()
                         >= self.depth * len(self._all_lanes()))
            shed = self.governor.shed_topics(saturated)
            if shed:
                shed_jobs = [j for j in group
                             if j.topics and set(j.topics) <= shed]
                if shed_jobs and len(shed_jobs) < len(group):
                    keep = set(map(id, shed_jobs))
                    group = [j for j in group if id(j) not in keep]
                    self._serve_cpu_compress(shed_jobs, "shed_jobs",
                                             shed=True)

        blk = LZ4F_BLOCKSIZE
        blocks: list[bytes] = []
        spans: list[tuple[int, int]] = []
        for j in group:
            for b in j.bufs:
                first = len(blocks)
                if not b:
                    spans.append((first, 0))
                    continue
                for pos in range(0, len(b), blk):
                    blocks.append(b[pos:pos + blk])
                spans.append((first, len(blocks) - first))

        if len(blocks) < self.min_batches and can_cpu:
            # below the launch quorum even after fan-in: the hard floor
            self._serve_cpu_compress(group, "cpu_jobs")
            return None
        if not blocks:
            # every buffer empty (and no CPU fallback): header+EndMark
            # frames need no device
            for j in group:
                j.ticket._complete([lz4f_frame([]) for _ in j.bufs])
            return None

        N = next_pow2(max(len(b) for b in blocks))
        shapes = [next_pow2(min(self.LZ4_MAX_B, len(blocks) - s), lo=8)
                  for s in range(0, len(blocks), self.LZ4_MAX_B)]

        lanes = self._get_lanes()
        ok = lanes
        if self.warmup_enabled:
            # warmup gate, per lane (the CRC gate shape): with no lane
            # fully warm for these (B, N) buckets, CPU serves and the
            # missed shapes jump the warmup queue
            need = [(B, N) for B in set(shapes)]
            ok = [ln for ln in lanes
                  if all(_lz4.kernel_ready(B, n_, device=ln.dev_id)
                         for B, n_ in need)]
            if not ok:
                want = self._pick_lane(lanes, None)
                for B, n_ in need:
                    self._request_warm(("lz4", B, n_, want.dev_id))
                if can_cpu:
                    self._serve_cpu_compress(group, "warmup_miss_jobs")
                    return None
                ok = lanes

        bucket = shapes[0]
        explored = False
        if self.governor.enabled and can_cpu:
            nbytes = sum(len(b) for b in blocks)
            route, explored = self.governor.route_compress(bucket,
                                                           nbytes)
            if explored:
                self.compress_stats["explore_routes"] += 1
            if route == "cpu":
                self._note_comp_route(bucket, "cpu")
                self._serve_cpu_compress(group, "routed_cpu_jobs")
                return None

        import jax

        lane = min(ok, key=lambda ln: (
            len(ln.inflight),
            self.governor.lane_compress_s(ln.dev_id, bucket) or 0.0,
            ln.launches))
        rec = _Launch("lz4")
        rec.jobs = group
        rec.spans = spans
        rec.raw_blocks = blocks
        rec.lane = lane
        rec.bucket = bucket
        t_launch = time.perf_counter()
        for j in group:
            if j.t_submit:
                self.stage_submit_wait.add((t_launch - j.t_submit) * 1e6)
        rec.t0 = t_launch
        tr0 = _trace.now() if _trace.enabled else 0
        if _metrics.enabled:
            _metrics.counter("engine.launches").inc()
        self.compress_stats["launches"] += 1
        self.compress_stats["blocks"] += len(blocks)
        self.compress_stats["bytes_in"] += sum(len(b) for b in blocks)
        self._note_comp_route(bucket, "device")
        lane.launches += 1
        lane.blocks += len(blocks)
        lane.jobs += len(group)
        for start in range(0, len(blocks), self.LZ4_MAX_B):
            chunk = blocks[start:start + self.LZ4_MAX_B]
            B = next_pow2(len(chunk), lo=8)
            # persistent staging, right-padded (lz4 positions are
            # absolute from the block start — packing.pad_right layout)
            data = lane.staging.take(B, N)
            lens = np.zeros((B,), dtype=np.int32)
            for i, b in enumerate(chunk):
                n = len(b)
                data[i, :n] = np.frombuffer(b, dtype=np.uint8)
                lens[i] = n
            d = jax.device_put(data, lane.device)
            ln_d = jax.device_put(lens, lane.device)
            fn = _lz4.ready_kernel(B, N, device=lane.dev_id)
            if fn is None:
                fn = _lz4._fused_for(N)
            rec.outs.append(fn(d, ln_d))
            rec.chunk_lens.append(len(chunk))
        for j in group:
            self.governor.note_qos(j.topics, shed=False)
        if tr0:
            _trace.complete("engine", "compress_launch", tr0,
                            {"route": "device", "explored": explored,
                             "bucket": bucket, "block": N,
                             "blocks": len(blocks), "jobs": len(group),
                             "device": lane.dev_id})
        return rec

    @staticmethod
    def _bucket_shapes(nblocks: int) -> list[int]:
        """The padded row-counts (B) the launch loop will use for
        ``nblocks`` blocks — the kernel shapes the warmup gate checks."""
        from .crc32c_jax import _MXU_MAX_B
        from .packing import next_pow2
        shapes = []
        for start in range(0, nblocks, _MXU_MAX_B):
            n = min(_MXU_MAX_B, nblocks - start)
            B = next_pow2(n)
            if n >= 64:
                B = max(B, 128)     # MXU tile floor (crc32c_jax.py)
            shapes.append(B)
        return shapes

    @staticmethod
    def _shard_bucket(nrows: int, ndev: int) -> int:
        """Per-shard padded row count for a sharded chunk of ``nrows``
        blocks over ``ndev`` devices.  The pow2 floor is
        SHARD_MIN_ROWS, not the whole-device 64 (a 64-row-per-chip
        floor would stage up to 32 MB of zeros for a small split);
        the 128-row MXU tile floor still applies once a shard fills
        64+ rows, exactly like the whole-device buckets."""
        from .packing import next_pow2
        rows = -(-nrows // ndev)
        Bs = next_pow2(rows, lo=AsyncOffloadEngine.SHARD_MIN_ROWS)
        if rows >= 64:
            Bs = max(Bs, 128)       # MXU tile floor (crc32c_jax.py)
        return Bs

    def _pick_lane(self, lanes: list, bucket: Optional[int]) -> "_Lane":
        """Least-loaded whole-group lane pick: fewest in-flight
        launches first, then the governor's per-device launch-time
        EWMA for this bucket (unknown sorts first — cold chips get
        measured), then total launches (round-robin among equals)."""
        return min(lanes, key=lambda ln: (
            len(ln.inflight),
            self.governor.lane_device_s(ln.dev_id, bucket) or 0.0
            if bucket is not None else 0.0,
            ln.launches))

    def _launch_crc(self, group: list[_Job]) -> Optional[_Launch]:
        from .crc32c_jax import (_MXU_BLOCK, _MXU_MAX_B, _term_host,
                                 kernel_ready, ready_kernel)
        from .packing import next_pow2

        self.stats["jobs"] += len(group)
        if len(group) > 1:
            self.stats["aggregated"] += len(group)

        blk = _MXU_BLOCK
        blocks: list[bytes] = []
        spans: list[tuple[int, int]] = []
        row_poly: list[str] = []         # polynomial of each block row
        for j in group:
            for b in j.bufs:
                first = len(blocks)
                if not b:
                    spans.append((first, 0))
                    continue
                for pos in range(0, len(b), blk):
                    blocks.append(b[pos:pos + blk])
                    row_poly.append(j.poly)
                spans.append((first, len(blocks) - first))

        if len(blocks) < self.min_batches and self.cpu_fallback is not None:
            # below the launch quorum even after fan-in (the governor's
            # hard floor): the CPU provider serves these
            # (bit-identical), still off the submitter's thread
            self._serve_cpu(group, "cpu_fallback_jobs")
            return None

        polys = set(row_poly) or {group[0].poly}
        mixed = len(polys) > 1
        shapes = self._bucket_shapes(len(blocks))
        kinds = ("fused",) if mixed else tuple(polys)

        lanes = self._get_lanes()
        ndev = len(lanes)
        # sharded route (ISSUE 6): a group spanning a mesh multiple
        # splits over every chip via shard_map — bit-identical, only
        # WHERE each block's CRC runs changes
        shard = (ndev > 1
                 and len(blocks) >= ndev * self.SHARD_MIN_ROWS)
        shard_cap = _MXU_MAX_B * ndev
        if shard and self.warmup_enabled:
            from ..parallel.mesh import sharded_crc_ready
            ids = [ln.dev_id for ln in lanes]
            sbuckets = {self._shard_bucket(
                min(shard_cap, len(blocks) - s), ndev)
                for s in range(0, len(blocks), shard_cap)}
            missing = [(Bs, k) for Bs in sbuckets for k in kinds
                       if not sharded_crc_ready(ids, Bs, blk, k)]
            if missing:
                # the sharded step is still compiling: fall back to
                # whole-to-one-lane (never stall), ask for the step
                for Bs, k in missing:
                    self._request_warm(("shard", Bs, k))
                shard = False
        lane = None
        if not shard:
            if self.warmup_enabled:
                # warmup gate, per lane: route to any lane whose
                # kernels are ALL warm; with none warm, CPU serves and
                # the missed shapes jump the warmup queue (requested
                # for the least-loaded lane first)
                need = [(B, k) for B in set(shapes) for k in kinds]
                ok = [ln for ln in lanes
                      if all(kernel_ready(B, blk, k, device=ln.dev_id)
                             for B, k in need)]
                if not ok:
                    want = self._pick_lane(
                        lanes, shapes[0] if shapes else None)
                    for B, k in need:
                        self._request_warm(("kernel", B, k,
                                            want.dev_id))
                    self._serve_cpu(group, "warmup_miss_jobs")
                    return None
            else:
                ok = lanes
            lane = self._pick_lane(ok, shapes[0] if shapes else None)
        explored = False
        if self.governor.enabled and self.cpu_fallback is not None:
            nbytes = sum(len(b) for j in group for b in j.bufs)
            route, explored = self.governor.route(shapes[0], nbytes)
            if explored:
                self.stats["explore_routes"] += 1
            if route == "cpu":
                self._serve_cpu(group, "routed_cpu_jobs")
                return None

        import jax

        rec = _Launch("crc")
        rec.jobs = group
        rec.spans = spans
        rec.sharded = shard
        rec.lane = self._shard_lane if shard else lane
        rec.bucket = (self._shard_bucket(
            min(shard_cap, len(blocks)), ndev) if shard
            else (shapes[0] if shapes else None))
        # submit->launch wait: the queue + fan-in share of each job's
        # pipeline latency (codec_engine.stage_latency.submit_wait)
        t_launch = time.perf_counter()
        for j in group:
            if j.t_submit:
                self.stage_submit_wait.add((t_launch - j.t_submit) * 1e6)
        rec.t0 = t_launch
        tr0 = _trace.now() if _trace.enabled else 0
        if _metrics.enabled:
            _metrics.counter("engine.launches").inc()
        self.stats["launches"] += 1
        if mixed:
            self.stats["fused_launches"] += 1
        self.stats["blocks"] += len(blocks)
        full_terms = {p: _term_host(blk, p) for p in polys}

        if shard:
            self.stats["sharded_launches"] += 1
            self._launch_crc_sharded(rec, lanes, blocks, row_poly,
                                     mixed, polys, full_terms)
        else:
            lane.launches += 1
            lane.blocks += len(blocks)
            lane.jobs += len(group)
            self._launch_crc_lane(rec, lane, blocks, row_poly, mixed,
                                  polys, full_terms)
        if tr0:
            # the async dispatch span; governor + lane decisions ride
            # the args (device: lane id, or -1 for a whole-mesh
            # sharded launch)
            _trace.complete("engine", "device_launch", tr0,
                            {"route": "device", "explored": explored,
                             "fused": mixed, "bucket": rec.bucket,
                             "blocks": len(blocks), "jobs": len(group),
                             "device": rec.lane.dev_id,
                             "sharded": shard})
        return rec

    def _launch_crc_lane(self, rec: _Launch, lane: "_Lane",
                         blocks: list, row_poly: list, mixed: bool,
                         polys: set, full_terms: dict) -> None:
        """Whole-to-one-lane dispatch: every chunk of this group on
        ``lane``'s device, staged from that lane's private rings."""
        import jax

        from .crc32c_jax import (_MXU_BLOCK, _MXU_MAX_B, _term_host,
                                 ready_kernel)
        from .packing import next_pow2
        blk = _MXU_BLOCK
        for start in range(0, len(blocks), _MXU_MAX_B):
            chunk = blocks[start:start + _MXU_MAX_B]
            cpoly = row_poly[start:start + _MXU_MAX_B]
            B = next_pow2(len(chunk))
            if len(chunk) >= 64:
                B = max(B, 128)     # MXU tile floor (crc32c_jax.py)
            # persistent staging: one ring buffer per (B, blk) bucket
            # PER LANE, zeroed + row-filled in place (left pad: leading
            # zeros are a CRC no-op under a zero register)
            data = lane.staging.take(B, blk)
            terms = np.zeros((B,), dtype=np.uint32)
            for i, b in enumerate(chunk):
                n = len(b)
                data[i, blk - n:] = np.frombuffer(b, dtype=np.uint8)
                terms[i] = (full_terms[cpoly[i]] if n == blk
                            else _term_host(n, cpoly[i]))
            # async dispatch: device_put + kernel launch return
            # immediately; the readback (np.asarray) is the only sync.
            # A warmed bucket rides its per-device AOT executable.
            d = jax.device_put(data, lane.device)
            t = jax.device_put(terms, lane.device)
            if mixed:
                sel = np.zeros((B,), dtype=np.uint32)
                for i, p in enumerate(cpoly):
                    if p == "crc32":
                        sel[i] = 1
                fn = ready_kernel(B, blk, "fused", device=lane.dev_id)
                if fn is None:
                    from .crc32c_jax import _jit_mxu_fused
                    fn = _jit_mxu_fused(B, blk)
                rec.outs.append(fn(d, t,
                                   jax.device_put(sel, lane.device)))
            else:
                poly = next(iter(polys))
                fn = ready_kernel(B, blk, poly, device=lane.dev_id)
                if fn is None:
                    from .crc32c_jax import _jit_mxu
                    fn = _jit_mxu(B, blk, poly)
                rec.outs.append(fn(d, t))
            rec.chunk_lens.append(len(chunk))

    def _launch_crc_sharded(self, rec: _Launch, lanes: list,
                            blocks: list, row_poly: list, mixed: bool,
                            polys: set, full_terms: dict) -> None:
        """Whole-mesh dispatch: each chunk laid out (Bs * ndev, 64KB)
        and shard_mapped so every chip checksums its contiguous
        Bs-row shard concurrently (parallel/mesh.py sharded_crc_step).
        Per-device counters record the shared launch on every lane."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import sharded_crc_step
        from .crc32c_jax import _MXU_BLOCK, _MXU_MAX_B, _term_host
        blk = _MXU_BLOCK
        ndev = len(lanes)
        devices = [ln.device for ln in lanes]
        shard_cap = _MXU_MAX_B * ndev
        for start in range(0, len(blocks), shard_cap):
            chunk = blocks[start:start + shard_cap]
            cpoly = row_poly[start:start + shard_cap]
            Bs = self._shard_bucket(len(chunk), ndev)
            Bt = Bs * ndev
            data = self._shard_lane.staging.take(Bt, blk)
            terms = np.zeros((Bt,), dtype=np.uint32)
            for i, b in enumerate(chunk):
                n = len(b)
                data[i, blk - n:] = np.frombuffer(b, dtype=np.uint8)
                terms[i] = (full_terms[cpoly[i]] if n == blk
                            else _term_host(n, cpoly[i]))
            kind = "fused" if mixed else next(iter(polys))
            mesh, fn = sharded_crc_step(devices, Bs, blk, kind)
            row = NamedSharding(mesh, P("batch"))
            d = jax.device_put(data, NamedSharding(mesh,
                                                   P("batch", None)))
            t = jax.device_put(terms, row)
            if mixed:
                sel = np.zeros((Bt,), dtype=np.uint32)
                for i, p in enumerate(cpoly):
                    if p == "crc32":
                        sel[i] = 1
                rec.outs.append(fn(d, t, jax.device_put(sel, row)))
            else:
                rec.outs.append(fn(d, t))
            rec.chunk_lens.append(len(chunk))
            # per-lane share: contiguous row shards — device j owns
            # global rows [j*Bs, (j+1)*Bs); count its live rows
            for ji, ln in enumerate(lanes):
                ln.launches += 1
                ln.blocks += max(0, min(Bs, len(chunk) - ji * Bs))

    # ------------------------------------------------------------ readback --
    def _readback(self, rec: _Launch) -> None:
        if _lockdep.enabled:
            # the device sync below can stall for a full launch round
            # trip — holding any lock here would freeze submitters
            _lockdep.note_blocking("engine.readback")
        try:
            if rec.kind == "compute":
                import jax
                t0 = _trace.now() if _trace.enabled else 0
                rec.ticket._complete(
                    jax.tree_util.tree_map(np.asarray, rec.out_tree))
                if t0:
                    _trace.complete("engine", "readback", t0,
                                    {"kind": "compute"})
                return
            if rec.kind == "lz4":
                self._readback_lz4(rec)
                return
            self._readback_crc(rec)
        except Exception as e:
            if rec.kind == "compute":
                rec.ticket._fail(e)
            else:
                for j in rec.jobs:
                    j.ticket._fail(e)

    def _readback_lz4(self, rec: _Launch) -> None:
        """Bulk-sync a fused compress→CRC launch and assemble the LZ4F
        frames: ONE launch + ONE readback yielded the compressed rows
        AND the checksums of both candidate block bodies, so the
        store-raw choice (comp strictly smaller, the host/native
        encoders' rule) picks its CRC for free and the v2 batch CRC is
        a host-side combine away (FrameBlob.region_crc)."""
        from .packing import lz4f_frame
        tr0 = _trace.now() if _trace.enabled else 0
        comp_rows: list[bytes] = []
        crc_comp: list[int] = []
        crc_raw: list[int] = []
        for o, nlive in zip(rec.outs, rec.chunk_lens):
            out, olen, cc, cr = o
            out = np.asarray(out)
            olen = np.asarray(olen)
            cc = np.asarray(cc).astype(np.uint32)
            cr = np.asarray(cr).astype(np.uint32)
            for i in range(nlive):
                comp_rows.append(out[i, :olen[i]].tobytes())
                crc_comp.append(int(cc[i]))
                crc_raw.append(int(cr[i]))
        if rec.t0 is not None:
            dt = time.perf_counter() - rec.t0
            if rec.lane is not None:
                self.governor.note_device_compress(rec.bucket, dt,
                                                   rec.lane.dev_id)
                rec.lane.launch_avg.add(dt * 1e6)
            else:
                self.governor.note_device_compress(rec.bucket, dt)
            self.stage_launch.add(dt * 1e6)
        t_reap = time.perf_counter()
        self.compress_stats["fused_crc"] += 1
        nframes = 0
        bytes_out = 0
        it = iter(rec.spans)
        for j in rec.jobs:
            frames = []
            for _b in j.bufs:
                first, nb = next(it)
                blob = lz4f_frame(
                    [(comp_rows[first + k], crc_comp[first + k],
                      rec.raw_blocks[first + k], crc_raw[first + k])
                     for k in range(nb)])
                frames.append(blob)
                bytes_out += len(blob)
            nframes += len(frames)
            j.ticket._complete(frames)
        self.compress_stats["bytes_out"] += bytes_out
        if tr0:
            _trace.complete("engine", "fused_crc", tr0,
                            {"bucket": rec.bucket, "frames": nframes,
                             "blocks": len(rec.raw_blocks),
                             "device": (rec.lane.dev_id
                                        if rec.lane is not None
                                        else 0)})
        self.stage_reap.add((time.perf_counter() - t_reap) * 1e6)

    def _readback_crc(self, rec: _Launch) -> None:
        from ..utils.crc import crc32_combine, crc32c_combine
        from .crc32c_jax import _MXU_BLOCK
        blk = _MXU_BLOCK
        tr0 = _trace.now() if _trace.enabled else 0
        # ONE bulk host sync per chunk + vectorized uint32 view — no
        # per-item int(x) loop
        parts = [np.asarray(o).astype(np.uint32)[:n]
                 for o, n in zip(rec.outs, rec.chunk_lens)]
        crcs = parts[0] if len(parts) == 1 else np.concatenate(parts)
        # launch latency feeds the governor's per-(device, bucket)
        # model AND the stage_latency.launch window (dispatch -> bulk
        # sync); a sharded launch records under every participating
        # chip — the whole mesh was busy for that window
        if rec.t0 is not None:
            dt = time.perf_counter() - rec.t0
            if rec.sharded:
                for ln in self._lanes:
                    self.governor.note_device(rec.bucket, dt,
                                              ln.dev_id)
                    ln.launch_avg.add(dt * 1e6)
            elif rec.lane is not None:
                self.governor.note_device(rec.bucket, dt,
                                          rec.lane.dev_id)
                rec.lane.launch_avg.add(dt * 1e6)
            else:
                self.governor.note_device(rec.bucket, dt)
            self.stage_launch.add(dt * 1e6)
        t_reap = time.perf_counter()
        if tr0:
            _trace.complete("engine", "readback", tr0,
                            {"kind": "crc", "bucket": rec.bucket,
                             "jobs": len(rec.jobs),
                             "device": (rec.lane.dev_id
                                        if rec.lane is not None
                                        else 0)})
        # host-side combine of multi-block buffers (µs each), then slice
        # results back out per job in submission order; a fused launch
        # combines each job with ITS polynomial's zero-shift matrices
        it = iter(rec.spans)
        for j in rec.jobs:
            combine = (crc32c_combine if j.poly == "crc32c"
                       else crc32_combine)
            out = np.zeros((len(j.bufs),), dtype=np.uint32)
            for i, b in enumerate(j.bufs):
                first, nb = next(it)
                if nb == 0:
                    continue
                acc = int(crcs[first])
                off = blk
                for k in range(1, nb):
                    acc = combine(acc, int(crcs[first + k]),
                                  min(blk, len(b) - off))
                    off += blk
                out[i] = acc
            j.ticket._complete(out)
        # reap: host-side combine + per-job slice/complete
        self.stage_reap.add((time.perf_counter() - t_reap) * 1e6)
