"""Host-side batch packing helpers shared by the device codec kernels.

The lz4 kernel wants RIGHT-padded rows (positions are absolute from the
block start); the crc32c kernel wants LEFT-padded rows (leading zeros are
a no-op under a zero initial register — see ops/crc32c_jax.py).

Also home of the LZ4F frame shape shared by the fused device compress
route (ISSUE 17): :class:`FrameBlob` is an assembled frame that carries
the crc32c of each of its parts, so the MessageSet v2 batch CRC can be
folded host-side with crc32c_combine instead of re-scanning the frame
bytes the device just produced.
"""
from __future__ import annotations

import struct

import numpy as np

from ..utils.crc import crc32c, crc32c_combine

#: LZ4F defaults matching ops/tpu.py's host assembly and the native
#: encoder (tk_lz4f_compress_many): FLG 0x60 (v01, block-independent),
#: BD 0x40 (64KB max block), HC = (xxh32(FLG||BD) >> 8) & 0xFF = 0x82 —
#: the bit-exactness suite asserts whole-frame equality vs the native
#: encoder, which pins this constant.
LZ4F_MAGIC = 0x184D2204
LZ4F_BLOCKSIZE = 65536
LZ4F_HEADER = struct.pack("<IBBB", LZ4F_MAGIC, 0x60, 0x40, 0x82)
LZ4F_ENDMARK = b"\x00\x00\x00\x00"
_HEADER_CRC = crc32c(LZ4F_HEADER)
_ENDMARK_CRC = crc32c(LZ4F_ENDMARK)


class FrameBlob(bytes):
    """An assembled LZ4F frame plus the crc32c of each of its parts
    (``crc_parts``: ``(crc, len)`` pairs whose concatenation is exactly
    these bytes).  :meth:`region_crc` folds them after an arbitrary
    prefix — the writer patches the v2 batch CRC without the host ever
    scanning the frame body."""

    def __new__(cls, parts):
        self = super().__new__(cls, b"".join(p for p, _ in parts))
        self.crc_parts = tuple((c, len(p)) for p, c in parts)
        return self

    def region_crc(self, prefix: bytes = b"") -> int:
        acc = crc32c(prefix)
        for c, ln in self.crc_parts:
            acc = crc32c_combine(acc, c, ln)
        return acc


def lz4f_frame(bodies) -> FrameBlob:
    """Assemble one LZ4F frame from per-block ``(comp, comp_crc, raw,
    raw_crc)`` tuples.  Block choice matches the host/native encoders
    bit-for-bit: the compressed body iff it is strictly smaller, else
    the raw bytes with the store-raw high bit on the length word."""
    parts = [(LZ4F_HEADER, _HEADER_CRC)]
    for comp, comp_crc, raw, raw_crc in bodies:
        if len(comp) < len(raw):
            word, body, crc = len(comp), comp, comp_crc
        else:
            word, body, crc = len(raw) | 0x80000000, bytes(raw), raw_crc
        prefix = struct.pack("<I", word)
        parts.append((prefix, crc32c(prefix)))
        parts.append((body, crc))
    parts.append((LZ4F_ENDMARK, _ENDMARK_CRC))
    return FrameBlob(parts)


def next_pow2(n: int, lo: int = 64) -> int:
    p = lo
    while p < n:
        p *= 2
    return p


def _pack(buffers: list[bytes], N: int, left: bool) -> tuple[np.ndarray, np.ndarray]:
    B = len(buffers)
    out = np.zeros((B, N), dtype=np.uint8)
    lens = np.zeros((B,), dtype=np.int32)
    for i, b in enumerate(buffers):
        n = len(b)
        lens[i] = n
        if n:
            arr = np.frombuffer(bytes(b), dtype=np.uint8)
            if left:
                out[i, N - n:] = arr
            else:
                out[i, :n] = arr
    return out, lens


def pad_left(buffers: list[bytes], N: int):
    """Right-aligned rows (leading zeros) — the crc32c kernel layout."""
    return _pack(buffers, N, True)


def pad_right(buffers: list[bytes], N: int):
    """Left-aligned rows (trailing zeros) — the lz4 kernel layout."""
    return _pack(buffers, N, False)


def iter_run_records(base, klens, vlens, count, tss=None, hbuf=None,
                     hlens=None):
    """Walk a fast-lane arena run descriptor (the ArenaBatch layout:
    concatenated key||value payloads + raw little-endian length arrays,
    optional int64 timestamp and header-blob side arrays) and yield
    ``(key, value, ts_ms, hblob)`` per record.  Host-side inspection
    seam for the wire-equality gates and parity tests — the produce hot
    path never walks records in Python."""
    kl = np.frombuffer(klens, np.int32)[:count]
    vl = np.frombuffer(vlens, np.int32)[:count]
    ts = np.frombuffer(tss, np.int64)[:count] if tss is not None else None
    hl = (np.frombuffer(hlens, np.int32)[:count]
          if hbuf is not None else None)
    off = 0
    hoff = 0
    for i in range(count):
        k = v = hb = None
        if kl[i] >= 0:
            k = bytes(base[off:off + int(kl[i])])
            off += int(kl[i])
        if vl[i] >= 0:
            v = bytes(base[off:off + int(vl[i])])
            off += int(vl[i])
        if hl is not None and hl[i] > 0:
            hb = bytes(hbuf[hoff:hoff + int(hl[i])])
            hoff += int(hl[i])
        yield k, v, (int(ts[i]) if ts is not None else 0), hb
