"""Host-side batch packing helpers shared by the device codec kernels.

The lz4 kernel wants RIGHT-padded rows (positions are absolute from the
block start); the crc32c kernel wants LEFT-padded rows (leading zeros are
a no-op under a zero initial register — see ops/crc32c_jax.py).
"""
from __future__ import annotations

import numpy as np


def next_pow2(n: int, lo: int = 64) -> int:
    p = lo
    while p < n:
        p *= 2
    return p


def _pack(buffers: list[bytes], N: int, left: bool) -> tuple[np.ndarray, np.ndarray]:
    B = len(buffers)
    out = np.zeros((B, N), dtype=np.uint8)
    lens = np.zeros((B,), dtype=np.int32)
    for i, b in enumerate(buffers):
        n = len(b)
        lens[i] = n
        if n:
            arr = np.frombuffer(bytes(b), dtype=np.uint8)
            if left:
                out[i, N - n:] = arr
            else:
                out[i, :n] = arr
    return out, lens


def pad_left(buffers: list[bytes], N: int):
    """Right-aligned rows (leading zeros) — the crc32c kernel layout."""
    return _pack(buffers, N, True)


def pad_right(buffers: list[bytes], N: int):
    """Left-aligned rows (trailing zeros) — the lz4 kernel layout."""
    return _pack(buffers, N, False)


def iter_run_records(base, klens, vlens, count, tss=None, hbuf=None,
                     hlens=None):
    """Walk a fast-lane arena run descriptor (the ArenaBatch layout:
    concatenated key||value payloads + raw little-endian length arrays,
    optional int64 timestamp and header-blob side arrays) and yield
    ``(key, value, ts_ms, hblob)`` per record.  Host-side inspection
    seam for the wire-equality gates and parity tests — the produce hot
    path never walks records in Python."""
    kl = np.frombuffer(klens, np.int32)[:count]
    vl = np.frombuffer(vlens, np.int32)[:count]
    ts = np.frombuffer(tss, np.int64)[:count] if tss is not None else None
    hl = (np.frombuffer(hlens, np.int32)[:count]
          if hbuf is not None else None)
    off = 0
    hoff = 0
    for i in range(count):
        k = v = hb = None
        if kl[i] >= 0:
            k = bytes(base[off:off + int(kl[i])])
            off += int(kl[i])
        if vl[i] >= 0:
            v = bytes(base[off:off + int(vl[i])])
            off += int(vl[i])
        if hl is not None and hl[i] > 0:
            hb = bytes(hbuf[hoff:hoff + int(hl[i])])
            hoff += int(hl[i])
        yield k, v, (int(ts[i]) if ts is not None else 0), hb
