"""Batched CRC32C on TPU — bit-exact with src/crc32c.c.

The reference computes the MessageSet v2 batch checksum sequentially per
batch on the broker thread (crc32c.c:39 hw path, rd_slice_crc32c at
rdbuf.c:1113).  Here the checksum of MANY partition batches is computed in
one device launch, exploiting two levels of parallelism:

  1. across buffers (the per-toppar batch axis, B), and
  2. within a buffer: the buffer is split into K equal chunks whose raw
     CRCs are computed in parallel lanes and folded with the GF(2)
     zero-shift combine (the same math as utils/crc.py:crc32c_combine).

Bit-exactness strategy (validated against utils/crc.py and the native C++
oracle in tests/test_0018_tpu_codec.py):

  - CRC register folding is GF(2)-linear in (register, data):
        f(~0, data) = f(~0, 0^n) XOR f(0, data)
    and leading zero bytes are a no-op under a zero initial register:
        f(0, 0^m || data) = f(0, data).
    So buffers are LEFT-padded with zeros to a common static shape, the
    padded fold f(0, padded) is computed chunk-parallel, and the length-
    dependent term f(~0, 0^n) is applied per buffer with 31 conditional
    matrix applications (binary exponentiation over the length bits).
  - The chunk scan processes 8 bytes per step with the slice-by-8 tables
    (TABLE_CRC32C, the same tables the CPU path uses).
"""
from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.crc import TABLE_CRC32C, ZERO_OP_CRC32C
from .packing import next_pow2, pad_left

_U32 = jnp.uint32

# slice-by-8 tables as one (8, 256) device-friendly constant
_T8 = np.ascontiguousarray(TABLE_CRC32C)          # [8][256] uint32
# M^(2^k): advance a register through 2^k zero bytes; columns mat[k][i]
_ZOP = np.ascontiguousarray(ZERO_OP_CRC32C[:31])  # [31][32] uint32


def _apply_cols(cols, v):
    """Apply a GF(2) 32x32 matrix (column form, (32,) uint32) to v (B,)."""
    bits = (v[:, None] >> jnp.arange(32, dtype=_U32)[None, :]) & _U32(1)
    terms = jnp.where(bits.astype(bool), cols[None, :], _U32(0))
    return jax.lax.reduce(terms, np.uint32(0),
                          lambda a, b: jax.lax.bitwise_xor(a, b), (1,))


def _mat_cols_pow(nbytes: int) -> np.ndarray:
    """Host-side: columns of M^nbytes (advance register through nbytes zeros)."""
    cols = np.array([1 << i for i in range(32)], dtype=np.uint64)  # identity
    k = 0
    n = nbytes
    while n:
        if n & 1:
            m = ZERO_OP_CRC32C[k].astype(np.uint64)
            out = np.zeros(32, dtype=np.uint64)
            for i in range(32):
                v = cols[i]
                acc = np.uint64(0)
                j = 0
                while v:
                    if v & np.uint64(1):
                        acc ^= m[j]
                    v >>= np.uint64(1)
                    j += 1
                out[i] = acc
            cols = out
        n >>= 1
        k += 1
    return cols.astype(np.uint32)


@lru_cache(maxsize=32)
def _shift_tables(nbytes: int) -> np.ndarray:
    """(4, 256) tables: SHIFT[k][b] = M^nbytes applied to (b << 8k)."""
    cols = _mat_cols_pow(nbytes).astype(np.uint64)
    out = np.zeros((4, 256), dtype=np.uint64)
    for k in range(4):
        for b in range(256):
            v = np.uint64(b) << np.uint64(8 * k)
            acc = np.uint64(0)
            j = 0
            while v:
                if v & np.uint64(1):
                    acc ^= cols[j]
                v >>= np.uint64(1)
                j += 1
            out[k][b] = acc
    return out.astype(np.uint32)


def _crc_kernel(data, lengths, shift_tab):
    """data (B, K, L) uint8 left-padded, lengths (B,) int32 → crc32c (B,)."""
    B, K, L = data.shape
    t8 = jnp.asarray(_T8)

    # --- 1. raw register fold of each chunk, 8 bytes per scan step -------
    d = jnp.transpose(data.reshape(B, K, L // 8, 8), (2, 0, 1, 3))  # (L/8,B,K,8)

    def step(crc, b8):
        b8 = b8.astype(_U32)
        lo = (b8[..., 0] | (b8[..., 1] << 8) | (b8[..., 2] << 16)
              | (b8[..., 3] << 24)) ^ crc
        crc = (t8[7][lo & 0xFF] ^ t8[6][(lo >> 8) & 0xFF]
               ^ t8[5][(lo >> 16) & 0xFF] ^ t8[4][(lo >> 24) & 0xFF]
               ^ t8[3][b8[..., 4]] ^ t8[2][b8[..., 5]]
               ^ t8[1][b8[..., 6]] ^ t8[0][b8[..., 7]])
        return crc, None

    chunk_crcs, _ = jax.lax.scan(step, jnp.zeros((B, K), _U32), d)  # (B, K)

    # --- 2. fold chunks left-to-right: raw = shift_L(raw) ^ chunk_k ------
    st = jnp.asarray(shift_tab)

    def fold(k, raw):
        raw = (st[0][raw & 0xFF] ^ st[1][(raw >> 8) & 0xFF]
               ^ st[2][(raw >> 16) & 0xFF] ^ st[3][(raw >> 24) & 0xFF])
        return raw ^ chunk_crcs[:, k]

    raw = jax.lax.fori_loop(0, K, fold, jnp.zeros((B,), _U32))

    # --- 3. per-length affine term f(~0, 0^n), binary exponentiation -----
    zop = jnp.asarray(_ZOP)
    n = lengths.astype(_U32)
    v = jnp.full((B,), 0xFFFFFFFF, _U32)

    def bit_step(j, v):
        applied = _apply_cols(zop[j], v)
        return jnp.where((n >> j) & 1, applied, v)

    v = jax.lax.fori_loop(0, 31, bit_step, v)
    return ~(raw ^ v)


def _pick_kl(N: int) -> tuple[int, int]:
    """Chunk layout: K parallel lanes of L bytes, L % 8 == 0, K*L == N."""
    K = max(1, min(128, N // 64))
    while N % (K * 8) != 0:
        K //= 2
    return K, N // K


@lru_cache(maxsize=16)
def _jit_for(N: int):
    K, L = _pick_kl(N)
    shift_tab = _shift_tables(L)

    def fn(data, lengths):
        return _crc_kernel(data.reshape(-1, K, L), lengths, shift_tab)

    return jax.jit(fn)




def crc32c_many(buffers: list[bytes]) -> np.ndarray:
    """CRC32C of each buffer in one device launch (uint32 array)."""
    if not buffers:
        return np.zeros((0,), dtype=np.uint32)
    N = next_pow2(max(len(b) for b in buffers))
    data, lens = pad_left(buffers, N)
    return np.asarray(_jit_for(N)(data, lens)).astype(np.uint32)


# ===================================================================== MXU ==
# CRC32C as GF(2) matrix algebra on the systolic array.
#
# The register fold f(0, data) is GF(2)-linear in the data bits, so the
# whole checksum is a matrix-vector product over GF(2).  Decompose per
# 256-byte chunk:  c_k = P · bits_k   (P is a constant 2048x32 bit-matrix:
# column (p*8+k) is the fold of bit k of byte p through the chunk tail),
# then combine      raw = Σ_k S^(K-1-k) · c_k   (S = shift by one chunk).
# Both stages are int8 matmuls with int32 accumulation reduced mod 2 —
# MXU work instead of the byte-table gathers the scan kernel (and every
# CPU implementation, crc32c.c:39) is built from.  Bit-exact by the same
# linearity argument as the scan path (leading zeros under a zero
# register are a no-op; the length term f(~0,0^n) is applied per buffer).

_CHUNK = 256  # bytes per MXU chunk


def _apply_host(cols: np.ndarray, v: int) -> int:
    acc = 0
    i = 0
    v = int(v)
    while v:
        if v & 1:
            acc ^= int(cols[i])
        v >>= 1
        i += 1
    return acc


@lru_cache(maxsize=1)
def _p_matrix() -> np.ndarray:
    """(2048, 32) int8: bit contributions of a 256-byte chunk to its raw CRC."""
    T = TABLE_CRC32C[0]
    P = np.zeros((_CHUNK * 8, 32), dtype=np.int8)
    for p in range(_CHUNK):
        cols = _mat_cols_pow(_CHUNK - 1 - p)
        for k in range(8):
            contrib = _apply_host(cols, int(T[1 << k]))
            P[p * 8 + k] = (contrib >> np.arange(32)) & 1
    return P


@lru_cache(maxsize=16)
def _w_matrix(K: int) -> np.ndarray:
    """(K*32, 32) int8: combine matrices S^(K-1-j) stacked over chunks j."""
    S = _mat_cols_pow(_CHUNK)
    cur = np.array([1 << i for i in range(32)], dtype=np.uint64)  # identity
    mats = []
    for _ in range(K):                      # mats[i] = S^i (column form)
        mats.append(cur.copy())
        cur = np.array([_apply_host(S, int(cur[i])) for i in range(32)],
                       dtype=np.uint64)
    W = np.zeros((K, 32, 32), dtype=np.int8)
    for j in range(K):
        cols = mats[K - 1 - j]
        W[j] = ((cols[:, None] >> np.arange(32, dtype=np.uint64)[None, :])
                & np.uint64(1)).astype(np.int8)
    return W.reshape(K * 32, 32)


def _crc_kernel_mxu(data, lengths, P, W):
    """data (B, N) uint8 left-padded, N = K*256 → crc32c (B,) uint32."""
    B, N = data.shape
    K = N // _CHUNK
    bits = ((data[..., None] >> jnp.arange(8, dtype=jnp.uint8)) & 1)
    bits = bits.reshape(B * K, _CHUNK * 8).astype(jnp.int8)
    counts = jax.lax.dot_general(
        bits, P, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)            # (B*K, 32)
    c = (counts & 1).astype(jnp.int8).reshape(B, K * 32)
    total = jax.lax.dot_general(
        c, W, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)            # (B, 32)
    raw_bits = (total & 1).astype(_U32)
    raw = jax.lax.reduce(
        raw_bits << jnp.arange(32, dtype=_U32)[None, :], np.uint32(0),
        lambda a, b: jax.lax.bitwise_xor(a, b), (1,))

    # per-length affine term f(~0, 0^n), as in the scan kernel
    zop = jnp.asarray(_ZOP)
    n = lengths.astype(_U32)
    v = jnp.full((B,), 0xFFFFFFFF, _U32)

    def bit_step(j, v):
        return jnp.where((n >> j) & 1, _apply_cols(zop[j], v), v)

    v = jax.lax.fori_loop(0, 31, bit_step, v)
    return ~(raw ^ v)


@lru_cache(maxsize=16)
def _jit_mxu(N: int):
    P = jnp.asarray(_p_matrix())
    W = jnp.asarray(_w_matrix(N // _CHUNK))

    def fn(data, lengths):
        return _crc_kernel_mxu(data, lengths, P, W)

    return jax.jit(fn)


def crc32c_many_mxu(buffers: list[bytes]) -> np.ndarray:
    """CRC32C of each buffer via GF(2) matmuls on the MXU."""
    if not buffers:
        return np.zeros((0,), dtype=np.uint32)
    N = max(next_pow2(max(len(b) for b in buffers)), _CHUNK)
    data, lens = pad_left(buffers, N)
    return np.asarray(_jit_mxu(N)(data, lens)).astype(np.uint32)
