"""Batched CRC32C on TPU — bit-exact with src/crc32c.c.

The reference computes the MessageSet v2 batch checksum sequentially per
batch on the broker thread (crc32c.c:39 hw path, rd_slice_crc32c at
rdbuf.c:1113).  Here the checksum of MANY partition batches is computed in
one device launch, exploiting two levels of parallelism:

  1. across buffers (the per-toppar batch axis, B), and
  2. within a buffer: the buffer is split into K equal chunks whose raw
     CRCs are computed in parallel lanes and folded with the GF(2)
     zero-shift combine (the same math as utils/crc.py:crc32c_combine).

Bit-exactness strategy (validated against utils/crc.py and the native C++
oracle in tests/test_0018_tpu_codec.py):

  - CRC register folding is GF(2)-linear in (register, data):
        f(~0, data) = f(~0, 0^n) XOR f(0, data)
    and leading zero bytes are a no-op under a zero initial register:
        f(0, 0^m || data) = f(0, data).
    So buffers are LEFT-padded with zeros to a common static shape, the
    padded fold f(0, padded) is computed chunk-parallel, and the length-
    dependent term f(~0, 0^n) is applied per buffer with 31 conditional
    matrix applications (binary exponentiation over the length bits).
  - The chunk scan processes 8 bytes per step with the slice-by-8 tables
    (TABLE_CRC32C, the same tables the CPU path uses).
"""
from __future__ import annotations

import threading
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.crc import TABLE_CRC32C, ZERO_OP_CRC32C
from .packing import next_pow2, pad_left

_U32 = jnp.uint32

# slice-by-8 tables as one (8, 256) device-friendly constant
_T8 = np.ascontiguousarray(TABLE_CRC32C)          # [8][256] uint32
# M^(2^k): advance a register through 2^k zero bytes; columns mat[k][i]
_ZOP = np.ascontiguousarray(ZERO_OP_CRC32C[:31])  # [31][32] uint32


def _apply_cols(cols, v):
    """Apply a GF(2) 32x32 matrix (column form, (32,) uint32) to v (B,)."""
    bits = (v[:, None] >> jnp.arange(32, dtype=_U32)[None, :]) & _U32(1)
    terms = jnp.where(bits.astype(bool), cols[None, :], _U32(0))
    return jax.lax.reduce(terms, np.uint32(0),
                          lambda a, b: jax.lax.bitwise_xor(a, b), (1,))


def _mat_cols_pow(nbytes: int) -> np.ndarray:
    """Host-side: columns of M^nbytes (advance register through nbytes zeros)."""
    cols = np.array([1 << i for i in range(32)], dtype=np.uint64)  # identity
    k = 0
    n = nbytes
    while n:
        if n & 1:
            m = ZERO_OP_CRC32C[k].astype(np.uint64)
            out = np.zeros(32, dtype=np.uint64)
            for i in range(32):
                v = cols[i]
                acc = np.uint64(0)
                j = 0
                while v:
                    if v & np.uint64(1):
                        acc ^= m[j]
                    v >>= np.uint64(1)
                    j += 1
                out[i] = acc
            cols = out
        n >>= 1
        k += 1
    return cols.astype(np.uint32)


@lru_cache(maxsize=32)
def _shift_tables(nbytes: int) -> np.ndarray:
    """(4, 256) tables: SHIFT[k][b] = M^nbytes applied to (b << 8k)."""
    cols = _mat_cols_pow(nbytes).astype(np.uint64)
    out = np.zeros((4, 256), dtype=np.uint64)
    for k in range(4):
        for b in range(256):
            v = np.uint64(b) << np.uint64(8 * k)
            acc = np.uint64(0)
            j = 0
            while v:
                if v & np.uint64(1):
                    acc ^= cols[j]
                v >>= np.uint64(1)
                j += 1
            out[k][b] = acc
    return out.astype(np.uint32)


def _crc_kernel(data, lengths, shift_tab):
    """data (B, K, L) uint8 left-padded, lengths (B,) int32 → crc32c (B,)."""
    B, K, L = data.shape
    t8 = jnp.asarray(_T8)

    # --- 1. raw register fold of each chunk, 8 bytes per scan step -------
    d = jnp.transpose(data.reshape(B, K, L // 8, 8), (2, 0, 1, 3))  # (L/8,B,K,8)

    def step(crc, b8):
        b8 = b8.astype(_U32)
        lo = (b8[..., 0] | (b8[..., 1] << 8) | (b8[..., 2] << 16)
              | (b8[..., 3] << 24)) ^ crc
        crc = (t8[7][lo & 0xFF] ^ t8[6][(lo >> 8) & 0xFF]
               ^ t8[5][(lo >> 16) & 0xFF] ^ t8[4][(lo >> 24) & 0xFF]
               ^ t8[3][b8[..., 4]] ^ t8[2][b8[..., 5]]
               ^ t8[1][b8[..., 6]] ^ t8[0][b8[..., 7]])
        return crc, None

    chunk_crcs, _ = jax.lax.scan(step, jnp.zeros((B, K), _U32), d)  # (B, K)

    # --- 2. fold chunks left-to-right: raw = shift_L(raw) ^ chunk_k ------
    st = jnp.asarray(shift_tab)

    def fold(k, raw):
        raw = (st[0][raw & 0xFF] ^ st[1][(raw >> 8) & 0xFF]
               ^ st[2][(raw >> 16) & 0xFF] ^ st[3][(raw >> 24) & 0xFF])
        return raw ^ chunk_crcs[:, k]

    raw = jax.lax.fori_loop(0, K, fold, jnp.zeros((B,), _U32))

    # --- 3. per-length affine term f(~0, 0^n), binary exponentiation -----
    zop = jnp.asarray(_ZOP)
    n = lengths.astype(_U32)
    v = jnp.full((B,), 0xFFFFFFFF, _U32)

    def bit_step(j, v):
        applied = _apply_cols(zop[j], v)
        return jnp.where((n >> j) & 1, applied, v)

    v = jax.lax.fori_loop(0, 31, bit_step, v)
    return ~(raw ^ v)


def _pick_kl(N: int) -> tuple[int, int]:
    """Chunk layout: K parallel lanes of L bytes, L % 8 == 0, K*L == N."""
    K = max(1, min(128, N // 64))
    while N % (K * 8) != 0:
        K //= 2
    return K, N // K


@lru_cache(maxsize=16)
def _jit_for(N: int):
    K, L = _pick_kl(N)
    shift_tab = _shift_tables(L)

    def fn(data, lengths):
        return _crc_kernel(data.reshape(-1, K, L), lengths, shift_tab)

    return jax.jit(fn)




def crc32c_many(buffers: list[bytes]) -> np.ndarray:
    """CRC32C of each buffer in one device launch (uint32 array)."""
    if not buffers:
        return np.zeros((0,), dtype=np.uint32)
    N = next_pow2(max(len(b) for b in buffers))
    data, lens = pad_left(buffers, N)
    return np.asarray(_jit_for(N)(data, lens)).astype(np.uint32)


# ===================================================================== MXU ==
# CRC32C as GF(2) matrix algebra on the systolic array.
#
# The register fold f(0, data) is GF(2)-linear in the data bits, so the
# whole checksum is ONE matrix-vector product over GF(2):
#
#     raw = Q · bits,   Q (N*8, 32): row (p*8+k) is the fold of bit k of
#     byte p advanced through the remaining N-1-p zero bytes.
#
# One int8 matmul with int32 accumulation reduced mod 2 — pure MXU work
# instead of the byte-table gathers the scan kernel (and every CPU
# implementation, crc32c.c:39) is built from.  TPU gathers run near one
# element/cycle, so the table formulation can never be fast on this
# hardware; the matmul formulation measured 1.2 ms device time for
# 64×64KB on a v5e-1 vs 4.7 ms for the native CPU provider (3.9×).
#
# Bit-exact by linearity: leading zeros under a zero register are a
# no-op, so buffers are LEFT-padded; the length-dependent affine term
# f(~0, 0^n) is applied on the HOST (31 tiny GF(2) ops per buffer).
#
# Buffers of any size are split into fixed 64KB blocks — one compiled
# shape per batch bucket — and block CRCs are folded host-side with
# crc32c_combine (µs each).  A Pallas variant (_PALLAS=True) fuses the
# bit-plane expansion with the matmul in VMEM; on v5e it measured
# 2.4 ms (grid serialization beats XLA's fusion less well), so the XLA
# path is the default.

_MXU_BLOCK = 65536        # fixed device block; ≥ any msgset batch chunk
_MXU_MAX_B = 256          # max blocks per launch


def _apply_host(cols: np.ndarray, v: int) -> int:
    acc = 0
    i = 0
    v = int(v)
    while v:
        if v & 1:
            acc ^= int(cols[i])
        v >>= 1
        i += 1
    return acc


def _poly_tables(poly: str):
    """(T0 single-byte table, ZERO_OP matrices) for a poly tag. Both
    reflected init=~0 xorout=~0 CRCs share the whole affine machinery;
    only these two constants differ (reference: crc32c.c vs rdcrc32.c)."""
    from ..utils.crc import TABLE_CRC32, ZERO_OP_CRC32
    if poly == "crc32c":
        return TABLE_CRC32C[0].astype(np.uint32), ZERO_OP_CRC32C
    if poly == "crc32":
        return TABLE_CRC32.astype(np.uint32), ZERO_OP_CRC32
    raise ValueError(poly)


@lru_cache(maxsize=4)
def _q_matrix(N: int = _MXU_BLOCK, poly: str = "crc32c") -> np.ndarray:
    """(N*8, 32) int8 bit-contribution matrix, built by one backward
    sweep advancing the 8 single-bit folds through trailing zeros."""
    T0, _ = _poly_tables(poly)
    c = T0[1 << np.arange(8)].astype(np.uint32)      # (8,)
    Q = np.zeros((N, 8, 32), dtype=np.int8)
    ar32 = np.arange(32, dtype=np.uint32)
    for p in range(N - 1, -1, -1):
        Q[p] = ((c[:, None] >> ar32[None, :]) & 1).astype(np.int8)
        c = T0[c & 0xFF] ^ (c >> 8)
    return Q.reshape(N * 8, 32)


def _term_host(n: int, poly: str = "crc32c") -> int:
    """f(~0, 0^n): the length-dependent affine term, host-side."""
    _, zop = _poly_tables(poly)
    v = 0xFFFFFFFF
    k = 0
    while n:
        if n & 1:
            v = _apply_host(zop[k], v)
        n >>= 1
        k += 1
    return v


@lru_cache(maxsize=8)
def _mxu_rows_fn(N: int = _MXU_BLOCK, poly: str = "crc32c"):
    """The un-jitted plane-split kernel body (data (B, N) uint8 left-
    padded, terms (B,) uint32) -> (B,) uint32 — shape-polymorphic in B.
    Shared by :func:`_jit_mxu` (whole-device launches) and the mesh
    shard_map step (parallel/mesh.py sharded_crc_step), so the sharded
    per-chip computation is EXACTLY the single-device kernel applied to
    that chip's row shard — bit-exact by construction."""
    Qp = np.ascontiguousarray(
        _q_matrix(N, poly).reshape(N, 8, 32).transpose(1, 0, 2))
    Qk = [jnp.asarray(Qp[k]) for k in range(8)]     # (N, 32) int8 each
    pow2 = jnp.asarray((1 << np.arange(32)).astype(np.int64)).astype(_U32)

    def fn(data, terms):
        total = None
        for k in range(8):
            plane = ((data >> k) & 1).astype(jnp.int8)       # (B, N)
            r = jax.lax.dot_general(
                plane, Qk[k], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)            # (B, 32)
            total = r if total is None else total + r
        # distinct bit positions never collide: sum == xor here
        raw = jnp.sum(((total & 1).astype(_U32)) * pow2[None, :],
                      axis=1, dtype=_U32)
        return ~(raw ^ terms)

    return fn


@lru_cache(maxsize=16)
def _jit_mxu(B: int, N: int = _MXU_BLOCK, poly: str = "crc32c"):
    """Plane-split MXU kernel (r4): EIGHT (B, N) x (N, 32) int8 dots —
    one per bit plane — instead of one (B, N*8) x (N*8, 32) dot over an
    expanded bit matrix.  XLA fuses the `(data >> k) & 1` plane
    extraction into each dot's operand read, so the 8x bit expansion is
    never materialized in HBM: traffic is 8 streaming reads of the raw
    bytes (64 MB for 128x64KB) and the kernel runs at the bandwidth
    floor — measured 0.07-0.08 ms for 8 MB on v5e-1 (~100 GB/s), 10x
    the r2/r3 single-dot form whose (B, N*8) int8 operand cost 128 MB
    of HBM round trip plus a badly tiled K=524288 contraction."""
    return jax.jit(_mxu_rows_fn(N, poly))


@lru_cache(maxsize=8)
def _mxu_fused_rows_fn(N: int = _MXU_BLOCK):
    """Un-jitted fused multi-poly body (data, terms, sel) -> (B,)
    uint32, shape-polymorphic in B — shared by :func:`_jit_mxu_fused`
    and the mesh shard_map step exactly like :func:`_mxu_rows_fn`."""
    Qc = np.ascontiguousarray(
        _q_matrix(N, "crc32c").reshape(N, 8, 32).transpose(1, 0, 2))
    Ql = np.ascontiguousarray(
        _q_matrix(N, "crc32").reshape(N, 8, 32).transpose(1, 0, 2))
    Qck = [jnp.asarray(Qc[k]) for k in range(8)]
    Qlk = [jnp.asarray(Ql[k]) for k in range(8)]
    pow2 = jnp.asarray((1 << np.arange(32)).astype(np.int64)).astype(_U32)

    def fn(data, terms, sel):
        # sel (B,) uint32: 0 = crc32c row, 1 = legacy crc32 row
        tot_c = tot_l = None
        for k in range(8):
            plane = ((data >> k) & 1).astype(jnp.int8)       # (B, N)
            rc = jax.lax.dot_general(
                plane, Qck[k], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            rl = jax.lax.dot_general(
                plane, Qlk[k], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            tot_c = rc if tot_c is None else tot_c + rc
            tot_l = rl if tot_l is None else tot_l + rl
        raw_c = jnp.sum(((tot_c & 1).astype(_U32)) * pow2[None, :],
                        axis=1, dtype=_U32)
        raw_l = jnp.sum(((tot_l & 1).astype(_U32)) * pow2[None, :],
                        axis=1, dtype=_U32)
        raw = jnp.where(sel != 0, raw_l, raw_c)
        return ~(raw ^ terms)

    return fn


@lru_cache(maxsize=16)
def _jit_mxu_fused(B: int, N: int = _MXU_BLOCK):
    """Fused multi-polynomial launch kernel (ISSUE 3 tentpole #4):
    crc32c and legacy-crc32 rows of the SAME padded (B, N) launch,
    selected per row.  Both Q matrices ride the same eight bit-plane
    dots (the operand read — the bandwidth floor the plane-split kernel
    runs at — is shared; only the 32-column accumulate doubles, a
    rounding error against the (B, N) stream), so a mixed v2/legacy
    fetch response costs ONE launch instead of two.  Bit-exact by
    construction: each row's result is exactly the single-poly kernel's
    for its polynomial."""
    return jax.jit(_mxu_fused_rows_fn(N))


# ------------------------------------------------- warmup / readiness ------
# The adaptive offload governor's compile registry (ISSUE 3): a bucket
# shape routes to the CPU provider until its kernel is HERE, so an XLA
# compile can never stall a hot-path launch.  Values are AOT-compiled
# executables (jit.lower().compile() — compiles without paying one
# throwaway execution) falling back to the jitted fn itself when the
# AOT API is unavailable; storing the executable also makes readiness
# immune to lru_cache eviction of _jit_mxu.
#
# ISSUE 6 makes the registry PER-DEVICE: an AOT executable is bound to
# the device it was lowered for, so the mesh-sharded engine's dispatch
# lanes each need their own warmed copy — keys carry the device id and
# the warmup sweep compiles every bucket on every lane.  ``device=None``
# means the process-default device (jax.devices()[0], id 0 on every
# supported platform), keeping the pre-mesh callers' view intact.
_READY: dict[tuple[int, int, str, int], object] = {}
_READY_LOCK = threading.Lock()


def _dev_key(device) -> int:
    """Registry device component: a Device object's id, a raw int id,
    or 0 for None (the process-default device) — resolved WITHOUT
    importing jax so stats-emitter callers stay light."""
    if device is None:
        return 0
    if isinstance(device, int):
        return device
    return device.id


def kernel_ready(B: int, N: int = _MXU_BLOCK, poly: str = "crc32c",
                 device=None) -> bool:
    """True once the (B, N, poly) bucket kernel is compiled for
    ``device`` (poly: 'crc32c' | 'crc32' | 'fused')."""
    return (B, N, poly, _dev_key(device)) in _READY


def ready_kernel(B: int, N: int = _MXU_BLOCK, poly: str = "crc32c",
                 device=None):
    """The warmed compiled executable for a bucket on a device, or
    None."""
    return _READY.get((B, N, poly, _dev_key(device)))


def warm_bucket_count(device=None) -> int:
    """How many (B, N, poly) buckets are warm on ``device`` — the
    per-device ``warm_buckets`` gauge of codec_engine.devices[]."""
    dk = _dev_key(device)
    with _READY_LOCK:
        return sum(1 for k in _READY if k[3] == dk)


def warm_kernel(B: int, N: int = _MXU_BLOCK, poly: str = "crc32c",
                device=None) -> None:
    """Compile the (B, N, poly) bucket kernel for ``device`` and mark
    it ready.  Idempotent; safe from any thread (the engine's
    background warmup thread is the intended caller).  Per-device AOT
    rides ShapeDtypeStruct shardings (SingleDeviceSharding) so the
    executable is lowered for the target chip; when that API is
    unavailable the fallback executes zeros placed on the device."""
    key = (B, N, poly, _dev_key(device))
    if key in _READY:
        return
    fused = poly == "fused"
    fn = _jit_mxu_fused(B, N) if fused else _jit_mxu(B, N, poly)
    sds_kw = {}
    if device is not None and not isinstance(device, int):
        try:
            from jax.sharding import SingleDeviceSharding
            sds_kw = {"sharding": SingleDeviceSharding(device)}
        except Exception:
            sds_kw = {}
    d = jax.ShapeDtypeStruct((B, N), jnp.uint8, **sds_kw)
    t = jax.ShapeDtypeStruct((B,), jnp.uint32, **sds_kw)
    args = (d, t, jax.ShapeDtypeStruct((B,), jnp.uint32, **sds_kw)) \
        if fused else (d, t)
    try:
        exe = fn.lower(*args).compile()
    except Exception:
        # no AOT path in this jax: compile by executing zeros once,
        # placed on the target device so the jit cache entry matches
        dev = device if device is not None and not isinstance(device, int) \
            else None
        data = np.zeros((B, N), dtype=np.uint8)
        terms = np.zeros((B,), dtype=np.uint32)
        cargs = ((data, terms, np.zeros((B,), np.uint32)) if fused
                 else (data, terms))
        np.asarray(fn(*(jax.device_put(a, dev) for a in cargs)))
        exe = fn
    with _READY_LOCK:
        _READY[key] = exe


@lru_cache(maxsize=16)
def _jit_mxu_pallas(B: int, N: int = _MXU_BLOCK, CB: int = 2048,
                    poly: str = "crc32c"):
    """Pallas variant: bit-plane expansion fused with the matmul in VMEM
    (rows of Q reordered to (chunk, bit-plane, position))."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    NC = N // CB
    Q = _q_matrix(N, poly).reshape(NC, CB, 8, 32).transpose(0, 2, 1, 3)
    Q = jnp.asarray(np.ascontiguousarray(Q.reshape(N * 8, 32)))
    pow2 = jnp.asarray((1 << np.arange(32)).astype(np.int64)).astype(_U32)
    interpret = jax.devices()[0].platform != "tpu"

    def kernel(d_ref, q_ref, o_ref):
        j = pl.program_id(0)
        d = d_ref[:, :].astype(jnp.int32)
        planes = [((d >> k) & 1).astype(jnp.int8) for k in range(8)]
        bits = jnp.concatenate(planes, axis=1)       # (B, 8*CB)
        acc = jax.lax.dot_general(
            bits, q_ref[:, :], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)

        @pl.when(j == 0)
        def _():
            o_ref[:, :] = acc

        @pl.when(j > 0)
        def _():
            o_ref[:, :] += acc

    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, 32), jnp.int32),
        grid=(NC,),
        in_specs=[pl.BlockSpec((B, CB), lambda j: (0, j)),
                  pl.BlockSpec((CB * 8, 32), lambda j: (j, 0))],
        out_specs=pl.BlockSpec((B, 32), lambda j: (0, 0)),
        interpret=interpret)

    def fn(data, terms):
        total = call(data, Q)
        raw = jnp.sum(((total & 1).astype(_U32)) * pow2[None, :],
                      axis=1, dtype=_U32)
        return ~(raw ^ terms)

    return jax.jit(fn)


_FULL_TERMS: dict[str, int] = {}


def crc32c_many_mxu(buffers: list[bytes], *,
                    pallas: bool = False) -> np.ndarray:
    """CRC32C of each buffer via ONE GF(2) matmul per 64KB block on the
    MXU, folded per buffer with crc32c_combine.  Fixed device shapes:
    one XLA compile per batch-size bucket, any buffer length."""
    return _crc_many_mxu(buffers, poly="crc32c", pallas=pallas)


def crc32_many_mxu(buffers: list[bytes], *,
                   pallas: bool = False) -> np.ndarray:
    """Legacy zlib-polynomial CRC32 (MsgVer0/1 per-message checksum,
    reference src/rdcrc32.c) on the same one-matmul MXU kernel — the
    GF(2)-linear decomposition is polynomial-agnostic."""
    return _crc_many_mxu(buffers, poly="crc32", pallas=pallas)


def _crc_many_mxu(buffers: list[bytes], *, poly: str,
                  pallas: bool = False) -> np.ndarray:
    if not buffers:
        return np.zeros((0,), dtype=np.uint32)
    from ..utils.crc import crc32_combine, crc32c_combine
    combine = crc32c_combine if poly == "crc32c" else crc32_combine

    blk = _MXU_BLOCK
    blocks: list[bytes] = []
    spans: list[tuple[int, int]] = []
    for b in buffers:
        b = bytes(b)
        first = len(blocks)
        if not b:
            spans.append((first, 0))
            continue
        for pos in range(0, len(b), blk):
            blocks.append(b[pos:pos + blk])
        spans.append((first, len(blocks) - first))

    if poly not in _FULL_TERMS:
        _FULL_TERMS[poly] = _term_host(blk, poly)
    full_term = _FULL_TERMS[poly]
    crcs = np.zeros((len(blocks),), dtype=np.uint32)
    jit = _jit_mxu_pallas if pallas else _jit_mxu
    for start in range(0, len(blocks), _MXU_MAX_B):
        chunk = blocks[start:start + _MXU_MAX_B]
        # the MXU systolic tile is 128 rows: a 64-row launch leaves the
        # array half idle and runs slower than a zero-padded 128-row one
        # (measured: 64x64KB = 0.77ms raw vs 0.48ms padded). Only pad
        # near the tile size — tiny batches would pay up to 128x in
        # host->device transfer for zeros
        B = next_pow2(len(chunk))
        if len(chunk) >= 64:
            B = max(B, 128)
        data, lens = pad_left(chunk, blk)
        if len(chunk) < B:
            data = np.concatenate(
                [data, np.zeros((B - len(chunk), blk), np.uint8)])
            lens = np.concatenate(
                [lens, np.zeros((B - len(chunk),), lens.dtype)])
        terms = np.array([full_term if n == blk
                          else _term_host(int(n), poly)
                          for n in lens], dtype=np.uint32)
        if pallas:
            out = np.asarray(jit(B, _MXU_BLOCK, 2048, poly)(data, terms))
        else:
            out = np.asarray(jit(B, _MXU_BLOCK, poly)(data, terms))
        out = out.astype(np.uint32)
        crcs[start:start + len(chunk)] = out[:len(chunk)]

    res = np.zeros((len(buffers),), dtype=np.uint32)
    for i, ((first, nb), b) in enumerate(zip(spans, buffers)):
        if nb == 0:
            res[i] = 0
            continue
        acc = int(crcs[first])
        off = blk
        for k in range(1, nb):
            ln = min(blk, len(b) - off)
            acc = combine(acc, int(crcs[first + k]), ln)
            off += blk
        res[i] = acc
    return res
