// Native produce() enqueue lane — the GIL-ceiling fix.
//
// The reference's produce hot path (rd_kafka_toppar_enq_msg called from
// rd_kafka_producev, rdkafka_msg.c:299/rdkafka_broker.c:3242) does zero
// allocations per record: payloads land in preallocated queues and the
// msgset writer walks them.  The Python client paid ~7 µs/message on the
// app thread building a Message object and deque-appending it, then the
// broker thread paid again iterating those objects to feed the native
// framer (tk_frame_v2, codec.cpp:468).
//
// This module is a CPython extension (not ctypes — per-call overhead
// matters at ~1 µs/record): an Arena is a per-toppar growable byte
// buffer + per-record (klen, vlen, enq_us) arrays.  produce() appends
// key/value straight into it in ONE C call; the broker thread take()s a
// contiguous run — base bytes + length arrays — that tk_frame_v2
// consumes directly with no per-record Python work on either side.
// Records default to the batch build time (timestamp=0 = "now"); an
// explicit produce(timestamp=) is stored per record, and headers are
// pre-encoded into a side arena — the framer (tk_frame_v2_run) walks
// all of it natively.  The monotonic enq_us feeds message.timeout.ms
// and latency stats.
//
// Thread contract: every method holds the GIL for its entire (short)
// duration — the GIL is the lock, exactly like the Python deques it
// replaces.  App thread appends; broker thread takes; main thread
// expires/clears.

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <structmember.h>

#include <stdint.h>
#include <string.h>
#include <time.h>
#ifdef __GLIBC__
#include <malloc.h>
#endif

#include <vector>

static inline int64_t now_us(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000000 + ts.tv_nsec / 1000;
}

typedef struct {
    PyObject_HEAD
    uint8_t *buf;        // concatenated key||value payload bytes
    int64_t cap, len;
    int32_t *klens;      // -1 = null key
    int32_t *vlens;      // -1 = null value
    int64_t *enq;        // CLOCK_MONOTONIC µs at append
    int64_t *boff;       // boff[i] = payload offset of record i; boff[count] = len
    // widened eligibility (explicit timestamps + record headers):
    // tss[i] is the record's CreateTime ms (0 = unset -> batch build
    // time); hbuf is a side arena of PRE-ENCODED wire header blobs
    // (count varint + per-header framing, encoded once at produce()
    // time), hoff[i]..hoff[i+1] delimiting record i's blob (empty =
    // no headers).  take() hands the framer these arrays verbatim.
    int64_t *tss;
    uint8_t *hbuf;
    int64_t hcap;
    int64_t *hoff;       // hoff[i] = header-blob offset; hoff[count] = used
    int32_t count, rcap;
    int32_t start;       // first un-taken record (partial takes)
} Arena;

static int arena_grow_buf(Arena *a, int64_t need) {
    if (a->len + need <= a->cap) return 0;
    int64_t ncap = a->cap ? a->cap : 1 << 16;
    while (a->len + need > ncap) ncap *= 2;
    uint8_t *nb = (uint8_t *)PyMem_Realloc(a->buf, ncap);
    if (!nb) { PyErr_NoMemory(); return -1; }
    a->buf = nb;
    a->cap = ncap;
    return 0;
}

static int arena_grow_recs(Arena *a) {
    if (a->count < a->rcap) return 0;
    int32_t ncap = a->rcap ? a->rcap * 2 : 1024;
    int32_t *nk = (int32_t *)PyMem_Realloc(a->klens, ncap * 4);
    if (!nk) { PyErr_NoMemory(); return -1; }
    a->klens = nk;
    int32_t *nv = (int32_t *)PyMem_Realloc(a->vlens, ncap * 4);
    if (!nv) { PyErr_NoMemory(); return -1; }
    a->vlens = nv;
    int64_t *ne = (int64_t *)PyMem_Realloc(a->enq, ncap * 8);
    if (!ne) { PyErr_NoMemory(); return -1; }
    a->enq = ne;
    int64_t *nt = (int64_t *)PyMem_Realloc(a->tss, ncap * 8);
    if (!nt) { PyErr_NoMemory(); return -1; }
    a->tss = nt;
    int64_t *nb = (int64_t *)PyMem_Realloc(a->boff, (ncap + 1) * 8);
    if (!nb) { PyErr_NoMemory(); return -1; }
    a->boff = nb;
    int64_t *nh = (int64_t *)PyMem_Realloc(a->hoff, (ncap + 1) * 8);
    if (!nh) { PyErr_NoMemory(); return -1; }
    a->hoff = nh;
    a->rcap = ncap;
    return 0;
}

static int arena_grow_hbuf(Arena *a, int64_t need) {
    int64_t used = a->hoff[a->count];
    if (used + need <= a->hcap) return 0;
    int64_t ncap = a->hcap ? a->hcap : 1 << 12;
    while (used + need > ncap) ncap *= 2;
    uint8_t *nb = (uint8_t *)PyMem_Realloc(a->hbuf, ncap);
    if (!nb) { PyErr_NoMemory(); return -1; }
    a->hbuf = nb;
    a->hcap = ncap;
    return 0;
}

static void arena_reset(Arena *a) {
    a->count = 0;
    a->start = 0;
    a->len = 0;
    a->boff[0] = 0;
    a->hoff[0] = 0;
}

// Reclaim the consumed prefix: partial takes leave [0, boff[start])
// garbage that would otherwise grow with cumulative produced volume
// under sustained production (the arena never fully drains when
// records arrive faster than the per-batch take cap).
static void arena_compact(Arena *a) {
    int32_t live = a->count - a->start;
    int64_t base = a->boff[a->start];
    int64_t hbase = a->hoff[a->start];
    if (live > 0) {
        memmove(a->buf, a->buf + base, (size_t)(a->len - base));
        memmove(a->klens, a->klens + a->start, (size_t)live * 4);
        memmove(a->vlens, a->vlens + a->start, (size_t)live * 4);
        memmove(a->enq, a->enq + a->start, (size_t)live * 8);
        memmove(a->tss, a->tss + a->start, (size_t)live * 8);
        if (hbase > 0)
            memmove(a->hbuf, a->hbuf + hbase,
                    (size_t)(a->hoff[a->count] - hbase));
        for (int32_t i = 0; i <= live; i++) {
            a->boff[i] = a->boff[a->start + i] - base;
            a->hoff[i] = a->hoff[a->start + i] - hbase;
        }
        a->len -= base;
    } else {
        a->len = 0;
        a->boff[0] = 0;
        a->hoff[0] = 0;
    }
    a->count = live;
    a->start = 0;
}

// Shared append body (arena_append + lane_produce): grow, compact a
// large consumed prefix, copy payloads, stamp the record.  ts_ms is
// the record's CreateTime (0 = unset); hp/hl the pre-encoded header
// blob (hl = 0: no headers).
static int arena_do_append(Arena *a, const char *kp, int64_t kl,
                           const char *vp, int64_t vl, int64_t ts_ms,
                           const uint8_t *hp, int64_t hl) {
    int64_t need = (kl > 0 ? kl : 0) + (vl > 0 ? vl : 0);
    if (a->start > 0
        && (a->boff[a->start] >= (1 << 20) || a->start >= 8192))
        arena_compact(a);
    if (arena_grow_buf(a, need) < 0 || arena_grow_recs(a) < 0) return -1;
    if (hl > 0 && arena_grow_hbuf(a, hl) < 0) return -1;
    if (kl > 0) { memcpy(a->buf + a->len, kp, kl); a->len += kl; }
    if (vl > 0) { memcpy(a->buf + a->len, vp, vl); a->len += vl; }
    int32_t i = a->count;
    a->klens[i] = (int32_t)kl;
    a->vlens[i] = (int32_t)vl;
    a->enq[i] = now_us();
    a->tss[i] = ts_ms;
    int64_t hused = a->hoff[i];
    if (hl > 0) { memcpy(a->hbuf + hused, hp, hl); hused += hl; }
    a->count = i + 1;
    a->boff[a->count] = a->len;
    a->hoff[a->count] = hused;
    return 0;
}

// append(key: bytes|None, value: bytes|None[, ts_ms: int,
//        hblob: bytes|None]) -> remaining count
// ts_ms = 0 means "unset" (batch build time); hblob is a pre-encoded
// wire header blob (see client/arena.py encode_headers).
static PyObject *arena_append(Arena *a, PyObject *const *args,
                              Py_ssize_t nargs) {
    if (nargs < 2 || nargs > 4) {
        PyErr_SetString(PyExc_TypeError,
                        "append(key, value[, ts_ms, hblob])");
        return NULL;
    }
    PyObject *key = args[0], *val = args[1];
    int64_t kl = -1, vl = -1;
    const char *kp = NULL, *vp = NULL;
    if (key != Py_None) {
        if (!PyBytes_Check(key)) {
            PyErr_SetString(PyExc_TypeError, "key must be bytes or None");
            return NULL;
        }
        kl = PyBytes_GET_SIZE(key);
        kp = PyBytes_AS_STRING(key);
    }
    if (val != Py_None) {
        if (!PyBytes_Check(val)) {
            PyErr_SetString(PyExc_TypeError, "value must be bytes or None");
            return NULL;
        }
        vl = PyBytes_GET_SIZE(val);
        vp = PyBytes_AS_STRING(val);
    }
    int64_t ts_ms = 0;
    if (nargs >= 3) {
        ts_ms = PyLong_AsLongLong(args[2]);
        if (PyErr_Occurred()) return NULL;
    }
    const uint8_t *hp = NULL;
    int64_t hl = 0;
    if (nargs == 4 && args[3] != Py_None) {
        if (!PyBytes_Check(args[3])) {
            PyErr_SetString(PyExc_TypeError, "hblob must be bytes or None");
            return NULL;
        }
        hl = PyBytes_GET_SIZE(args[3]);
        hp = (const uint8_t *)PyBytes_AS_STRING(args[3]);
    }
    if (arena_do_append(a, kp, kl, vp, vl, ts_ms, hp, hl) < 0) return NULL;
    return PyLong_FromLong(a->count - a->start);
}

// take(max_count, max_bytes)
//   -> (base, klens, vlens, count, nbytes, enq_first_us, enq_last_us,
//       tss|None, hbuf|None, hlens|None)
//      | None when empty
// tss is raw int64 timestamps (ms, 0 = unset) ONLY when some record in
// the run carries an explicit timestamp; hbuf/hlens (concatenated
// pre-encoded header blobs + raw int32 per-record blob lengths) ONLY
// when some record carries headers.  The all-default run — the hot
// shape — keeps the original 3-buffer descriptor (plus three Nones) so
// the framer's zero-delta path stays allocation-minimal.
static PyObject *arena_take(Arena *a, PyObject *const *args,
                            Py_ssize_t nargs) {
    if (nargs != 2) {
        PyErr_SetString(PyExc_TypeError, "take(max_count, max_bytes)");
        return NULL;
    }
    int64_t max_count = PyLong_AsLongLong(args[0]);
    int64_t max_bytes = PyLong_AsLongLong(args[1]);
    if (PyErr_Occurred()) return NULL;
    int32_t avail = a->count - a->start;
    if (avail <= 0) Py_RETURN_NONE;
    int32_t n = 0;
    int64_t nb = 0;
    int ts_any = 0;
    while (n < avail && n < max_count) {
        int64_t rl = a->boff[a->start + n + 1] - a->boff[a->start + n];
        if (n > 0 && nb + rl > max_bytes) break;
        nb += rl;
        if (a->tss[a->start + n]) ts_any = 1;
        n++;
    }
    int32_t s = a->start;
    int64_t h_total = a->hoff[s + n] - a->hoff[s];
    PyObject *base = PyBytes_FromStringAndSize(
        (const char *)(a->buf + a->boff[s]), nb);
    PyObject *kb = PyBytes_FromStringAndSize((const char *)(a->klens + s),
                                             (Py_ssize_t)n * 4);
    PyObject *vb = PyBytes_FromStringAndSize((const char *)(a->vlens + s),
                                             (Py_ssize_t)n * 4);
    PyObject *tsb = NULL, *hb = NULL, *hlb = NULL;
    if (ts_any)
        tsb = PyBytes_FromStringAndSize((const char *)(a->tss + s),
                                        (Py_ssize_t)n * 8);
    if (h_total > 0) {
        hb = PyBytes_FromStringAndSize(
            (const char *)(a->hbuf + a->hoff[s]), (Py_ssize_t)h_total);
        hlb = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)n * 4);
        if (hlb) {
            int32_t *hl = (int32_t *)PyBytes_AS_STRING(hlb);
            for (int32_t i = 0; i < n; i++)
                hl[i] = (int32_t)(a->hoff[s + i + 1] - a->hoff[s + i]);
        }
    }
    if (!base || !kb || !vb || (ts_any && !tsb)
        || (h_total > 0 && (!hb || !hlb))) {
        Py_XDECREF(base); Py_XDECREF(kb); Py_XDECREF(vb);
        Py_XDECREF(tsb); Py_XDECREF(hb); Py_XDECREF(hlb);
        return NULL;
    }
    int64_t ef = a->enq[s], el = a->enq[s + n - 1];
    a->start = s + n;
    if (a->start == a->count) arena_reset(a);
    if (!tsb) { tsb = Py_None; Py_INCREF(tsb); }
    if (!hb) { hb = Py_None; Py_INCREF(hb); }
    if (!hlb) { hlb = Py_None; Py_INCREF(hlb); }
    PyObject *r = Py_BuildValue("(NNNiLLLNNN)", base, kb, vb, (int)n,
                                (long long)nb, (long long)ef, (long long)el,
                                tsb, hb, hlb);
    return r;
}

// expire(cutoff_us) -> (count, nbytes): drop the prefix enqueued at or
// before cutoff_us (message.timeout.ms scan)
static PyObject *arena_expire(Arena *a, PyObject *arg) {
    int64_t cutoff = PyLong_AsLongLong(arg);
    if (PyErr_Occurred()) return NULL;
    int32_t n = 0;
    int64_t nb = 0;
    while (a->start < a->count && a->enq[a->start] <= cutoff) {
        nb += a->boff[a->start + 1] - a->boff[a->start];
        a->start++;
        n++;
    }
    if (a->start == a->count) arena_reset(a);
    return Py_BuildValue("(iL)", (int)n, (long long)nb);
}

// Materialize records [start, start+n) as (key|None, value|None, ts_ms,
// hblob|None) tuples — shared by expire_records and drain_records.
static PyObject *arena_record_tuples(Arena *a, int32_t n) {
    PyObject *list = PyList_New(n);
    if (!list) return NULL;
    for (int32_t i = 0; i < n; i++) {
        int32_t r = a->start + i;
        int64_t off = a->boff[r];
        int32_t kl = a->klens[r], vl = a->vlens[r];
        int64_t hl = a->hoff[r + 1] - a->hoff[r];
        PyObject *k, *v, *ts, *h;
        if (kl < 0) { k = Py_None; Py_INCREF(k); }
        else {
            k = PyBytes_FromStringAndSize((const char *)(a->buf + off), kl);
            off += kl;
        }
        if (vl < 0) { v = Py_None; Py_INCREF(v); }
        else
            v = PyBytes_FromStringAndSize((const char *)(a->buf + off), vl);
        ts = PyLong_FromLongLong(a->tss[r]);
        if (hl > 0)
            h = PyBytes_FromStringAndSize(
                (const char *)(a->hbuf + a->hoff[r]), (Py_ssize_t)hl);
        else { h = Py_None; Py_INCREF(h); }
        if (!k || !v || !ts || !h) {
            Py_XDECREF(k); Py_XDECREF(v); Py_XDECREF(ts); Py_XDECREF(h);
            Py_DECREF(list);
            return NULL;
        }
        PyObject *t = PyTuple_Pack(4, k, v, ts, h);
        Py_DECREF(k); Py_DECREF(v); Py_DECREF(ts); Py_DECREF(h);
        if (!t) { Py_DECREF(list); return NULL; }
        PyList_SET_ITEM(list, i, t);
    }
    return list;
}

// expire_records(cutoff_us) -> [(key, value, ts_ms, hblob|None), ...]:
// drop the prefix enqueued at or before cutoff_us, MATERIALIZED — the
// message.timeout.ms scan uses this instead of expire() when a
// delivery-report consumer needs the records for error DRs
static PyObject *arena_expire_records(Arena *a, PyObject *arg) {
    int64_t cutoff = PyLong_AsLongLong(arg);
    if (PyErr_Occurred()) return NULL;
    int32_t n = 0;
    while (a->start + n < a->count && a->enq[a->start + n] <= cutoff)
        n++;
    PyObject *list = arena_record_tuples(a, n);
    if (!list) return NULL;
    a->start += n;
    if (a->start == a->count) arena_reset(a);
    return list;
}

// clear() -> (count, nbytes): drop everything (purge)
static PyObject *arena_clear(Arena *a, PyObject *Py_UNUSED(ignored)) {
    int32_t n = a->count - a->start;
    int64_t nb = a->boff[a->count] - a->boff[a->start];
    arena_reset(a);
    return Py_BuildValue("(iL)", (int)n, (long long)nb);
}

// drain_records() -> [(key, value, ts_ms, hblob|None), ...]: demotion
// path when a toppar mixes fast-lane and Message traffic (rare; FIFO
// preserved by converting the arena prefix into Message objects)
static PyObject *arena_drain_records(Arena *a, PyObject *Py_UNUSED(ig)) {
    int32_t n = a->count - a->start;
    PyObject *list = arena_record_tuples(a, n);
    if (!list) return NULL;
    arena_reset(a);
    return list;
}

static PyObject *arena_first_enq_us(Arena *a, PyObject *Py_UNUSED(ig)) {
    if (a->start >= a->count) return PyLong_FromLong(-1);
    return PyLong_FromLongLong(a->enq[a->start]);
}

static PyObject *arena_nbytes(Arena *a, PyObject *Py_UNUSED(ig)) {
    return PyLong_FromLongLong(a->boff[a->count] - a->boff[a->start]);
}

static Py_ssize_t arena_length(PyObject *self) {
    Arena *a = (Arena *)self;
    return a->count - a->start;
}

static PyObject *arena_new(PyTypeObject *type, PyObject *args,
                           PyObject *kwds) {
    Arena *a = (Arena *)type->tp_alloc(type, 0);
    if (!a) return NULL;
    a->buf = NULL; a->cap = 0; a->len = 0;
    a->klens = NULL; a->vlens = NULL; a->enq = NULL;
    a->tss = NULL; a->hbuf = NULL; a->hcap = 0;
    a->boff = (int64_t *)PyMem_Malloc(8);
    a->hoff = (int64_t *)PyMem_Malloc(8);
    if (!a->boff || !a->hoff) { Py_DECREF(a); return PyErr_NoMemory(); }
    a->boff[0] = 0;
    a->hoff[0] = 0;
    a->count = 0; a->rcap = 0; a->start = 0;
    return (PyObject *)a;
}

static void arena_dealloc(Arena *a) {
    PyMem_Free(a->buf);
    PyMem_Free(a->klens);
    PyMem_Free(a->vlens);
    PyMem_Free(a->enq);
    PyMem_Free(a->tss);
    PyMem_Free(a->hbuf);
    PyMem_Free(a->boff);
    PyMem_Free(a->hoff);
    Py_TYPE(a)->tp_free((PyObject *)a);
}

// ============================================================ Lane =====
//
// The whole produce() hot path as ONE C call: argument parsing,
// eligibility, queue-full accounting, toppar lookup, arena append.
// The Python wrapper binds the public Producer.produce directly to
// Lane.produce; ineligible calls tail into the stored Python fallback
// (the Message path).  Counters live here — C methods are atomic under
// the GIL, replacing the Python-side msg_cnt lock for the hot path.

typedef struct {
    PyObject_HEAD
    PyObject *map;        // dict {(topic, partition) -> (Arena, toppar)}
    PyObject *fallback;   // rk._produce_slow(topic, value, key, ...)
    PyObject *wake;       // rk._wake_fast(toppar) on empty->non-empty
    // hot-path lookup cache: per-topic partition-indexed entry lists
    // (the tuple-pack + dict-hash per produce() measured ~40% of the
    // enqueue cost). cache_topic/cache_entries are the last-used fast
    // slot (pointer-identity hit); cache_map keeps every topic's list
    // so multi-topic round-robin pays one str-keyed dict get per
    // switch, not a list rebuild. Maintained by map_set/map_del —
    // Python must mutate the map through those, not directly.
    PyObject *cache_topic;    // strong ref, may be NULL
    PyObject *cache_entries;  // strong PyList of entry|None, may be NULL
    PyObject *cache_map;      // strong dict {topic -> PyList}, may be NULL
    // native auto-partition: {topic -> (partition_cnt, mode)} installed
    // by Python once metadata is known (part_set) and invalidated on
    // metadata change (part_del).  mode 1 = "murmur2" (null/empty key
    // hashes as b""), mode 2 = "murmur2_random" (falsy key falls back
    // to the Python random partitioner).
    PyObject *part_map;
    int64_t msg_cnt, msg_bytes;
    int64_t max_msgs, max_bytes;
    int64_t copy_max;     // message.copy.max.bytes: larger values keep a
                          // Python reference (Message path) instead of
                          // being copied into the arena
    int enabled;          // conf-level eligibility (no DR consumers)
    int fatal;            // set_fatal_error happened: produce must raise
    // engagement accounting (satellite: arena.engaged / per-reason
    // fallback breakdown in stats JSON) — GIL-atomic like msg_cnt
    int64_t c_engaged;       // records appended via the fast lane
    int64_t c_fb_disabled;   // lane disabled / fatal / bad call shape
    int64_t c_fb_shape;      // non-bytes payloads, callbacks, opaque...
    int64_t c_fb_oversize;   // payload or header blob > copy_max
    int64_t c_fb_qfull;      // queue-full: slow path raises
    int64_t c_fb_noent;      // toppar not registered yet (first sight)
    int64_t c_fb_autopart;   // partition=UA with no native partitioner
} Lane;

static PyObject *lane_new(PyTypeObject *type, PyObject *args,
                          PyObject *kwds) {
    Lane *l = (Lane *)type->tp_alloc(type, 0);
    if (!l) return NULL;
    l->map = PyDict_New();
    if (!l->map) { Py_DECREF(l); return NULL; }
    l->fallback = NULL;
    l->wake = NULL;
    l->cache_topic = NULL;
    l->cache_entries = NULL;
    l->cache_map = NULL;
    l->part_map = PyDict_New();
    if (!l->part_map) { Py_DECREF(l); return NULL; }
    l->msg_cnt = 0; l->msg_bytes = 0;
    l->max_msgs = 100000; l->max_bytes = 1LL << 30;
    l->copy_max = 65535;
    l->enabled = 0; l->fatal = 0;
    l->c_engaged = 0;
    l->c_fb_disabled = 0; l->c_fb_shape = 0; l->c_fb_oversize = 0;
    l->c_fb_qfull = 0; l->c_fb_noent = 0; l->c_fb_autopart = 0;
    return (PyObject *)l;
}

// GC support: Lane participates in a reference cycle by design
// (Kafka -> _lane -> fallback/wake bound methods -> Kafka), so it must
// be traversable or every producer instance leaks permanently.
static int lane_traverse(Lane *l, visitproc visit, void *arg) {
    Py_VISIT(l->map);
    Py_VISIT(l->fallback);
    Py_VISIT(l->wake);
    Py_VISIT(l->cache_topic);
    Py_VISIT(l->cache_entries);
    Py_VISIT(l->cache_map);
    Py_VISIT(l->part_map);
    return 0;
}

static int lane_clear(Lane *l) {
    Py_CLEAR(l->map);
    Py_CLEAR(l->fallback);
    Py_CLEAR(l->wake);
    Py_CLEAR(l->cache_topic);
    Py_CLEAR(l->cache_entries);
    Py_CLEAR(l->cache_map);
    Py_CLEAR(l->part_map);
    return 0;
}

static void lane_cache_invalidate(Lane *l) {
    Py_CLEAR(l->cache_topic);
    Py_CLEAR(l->cache_entries);
    Py_CLEAR(l->cache_map);
}

// map_set(topic, partition, entry): install an (Arena, toppar) entry.
// The ONLY legal way to mutate lane.map (keeps the lookup cache sound).
static PyObject *lane_map_set(Lane *l, PyObject *const *args,
                              Py_ssize_t nargs) {
    if (nargs != 3) {
        PyErr_SetString(PyExc_TypeError, "map_set(topic, partition, entry)");
        return NULL;
    }
    PyObject *key = PyTuple_Pack(2, args[0], args[1]);
    if (!key) return NULL;
    int r = PyDict_SetItem(l->map, key, args[2]);
    Py_DECREF(key);
    if (r < 0) return NULL;
    lane_cache_invalidate(l);
    Py_RETURN_NONE;
}

// map_del(topic, partition) -> removed entry | None
static PyObject *lane_map_del(Lane *l, PyObject *const *args,
                              Py_ssize_t nargs) {
    if (nargs != 2) {
        PyErr_SetString(PyExc_TypeError, "map_del(topic, partition)");
        return NULL;
    }
    PyObject *key = PyTuple_Pack(2, args[0], args[1]);
    if (!key) return NULL;
    PyObject *ent = PyDict_GetItemWithError(l->map, key);  // borrowed
    if (!ent) {
        Py_DECREF(key);
        if (PyErr_Occurred()) return NULL;
        Py_RETURN_NONE;
    }
    Py_INCREF(ent);
    if (PyDict_DelItem(l->map, key) < 0) {
        Py_DECREF(key); Py_DECREF(ent);
        return NULL;
    }
    Py_DECREF(key);
    lane_cache_invalidate(l);
    return ent;
}

// part_set(topic, partition_cnt, mode): enable native auto-partition
// for the topic.  mode 1 = "murmur2", mode 2 = "murmur2_random" (falsy
// keys still fall back to the Python random partitioner).
static PyObject *lane_part_set(Lane *l, PyObject *const *args,
                               Py_ssize_t nargs) {
    if (nargs != 3) {
        PyErr_SetString(PyExc_TypeError,
                        "part_set(topic, partition_cnt, mode)");
        return NULL;
    }
    if (!PyLong_Check(args[1]) || !PyLong_Check(args[2])) {
        PyErr_SetString(PyExc_TypeError, "cnt and mode must be int");
        return NULL;
    }
    PyObject *ent = PyTuple_Pack(2, args[1], args[2]);
    if (!ent) return NULL;
    int r = PyDict_SetItem(l->part_map, args[0], ent);
    Py_DECREF(ent);
    if (r < 0) return NULL;
    Py_RETURN_NONE;
}

// part_del(topic): drop the topic's auto-partition entry (metadata
// change invalidates the cached partition count)
static PyObject *lane_part_del(Lane *l, PyObject *arg) {
    if (PyDict_Contains(l->part_map, arg) == 1
        && PyDict_DelItem(l->part_map, arg) < 0)
        return NULL;
    if (PyErr_Occurred()) return NULL;
    Py_RETURN_NONE;
}

// counters() -> {"engaged": n, "fallback": {reason: n, ...}}
static PyObject *lane_counters(Lane *l, PyObject *Py_UNUSED(ig)) {
    return Py_BuildValue(
        "{s:L,s:{s:L,s:L,s:L,s:L,s:L,s:L}}",
        "engaged", (long long)l->c_engaged,
        "fallback",
        "disabled", (long long)l->c_fb_disabled,
        "shape", (long long)l->c_fb_shape,
        "oversize", (long long)l->c_fb_oversize,
        "queue_full", (long long)l->c_fb_qfull,
        "no_entry", (long long)l->c_fb_noent,
        "auto_partition", (long long)l->c_fb_autopart);
}

static void lane_dealloc(Lane *l) {
    PyObject_GC_UnTrack(l);
    lane_clear(l);
    Py_TYPE(l)->tp_free((PyObject *)l);
}

// configure(fallback, wake, max_msgs, max_bytes[, copy_max])
static PyObject *lane_configure(Lane *l, PyObject *const *args,
                                Py_ssize_t nargs) {
    if (nargs != 4 && nargs != 5) {
        PyErr_SetString(
            PyExc_TypeError,
            "configure(fallback, wake, max_msgs, max_bytes[, copy_max])");
        return NULL;
    }
    Py_INCREF(args[0]); Py_XSETREF(l->fallback, args[0]);
    Py_INCREF(args[1]); Py_XSETREF(l->wake, args[1]);
    l->max_msgs = PyLong_AsLongLong(args[2]);
    l->max_bytes = PyLong_AsLongLong(args[3]);
    if (nargs == 5) l->copy_max = PyLong_AsLongLong(args[4]);
    if (PyErr_Occurred()) return NULL;
    Py_RETURN_NONE;
}

// acct(dn, dbytes) -> (msg_cnt, msg_bytes): shared accounting for the
// Message path / DR / purge / timeout sites (atomic under the GIL)
static PyObject *lane_acct(Lane *l, PyObject *const *args,
                           Py_ssize_t nargs) {
    if (nargs != 2) {
        PyErr_SetString(PyExc_TypeError, "acct(dn, dbytes)");
        return NULL;
    }
    l->msg_cnt += PyLong_AsLongLong(args[0]);
    l->msg_bytes += PyLong_AsLongLong(args[1]);
    if (PyErr_Occurred()) return NULL;
    return Py_BuildValue("(LL)", (long long)l->msg_cnt,
                         (long long)l->msg_bytes);
}

// full() -> bool: queue-full check for the Message path
static PyObject *lane_full(Lane *l, PyObject *const *args,
                           Py_ssize_t nargs) {
    int64_t sz = 0;
    if (nargs == 1) sz = PyLong_AsLongLong(args[0]);
    return PyBool_FromLong(l->msg_cnt >= l->max_msgs
                           || l->msg_bytes + sz > l->max_bytes);
}

static const char *const lane_kwnames[] = {
    "topic", "value", "key", "partition", "on_delivery", "timestamp",
    "headers", "opaque", NULL};
// interned kwname objects (module init): caller kwnames are interned by
// CPython, so pointer equality is the common case
static PyObject *lane_kw_interned[8];
static PyObject *k_error_interned;   // per-item "error" key (produce_batch)

// toppar-entry lookup with the last-topic cache (shared by produce and
// produce_batch).  Returns a BORROWED entry or NULL (NULL + raised
// error = real failure; NULL without = unknown toppar).
static PyObject *lane_lookup(Lane *l, PyObject *topic, int64_t part,
                             PyObject *part_o) {
    if (topic == l->cache_topic && l->cache_entries
        && part < PyList_GET_SIZE(l->cache_entries)) {
        PyObject *ent = PyList_GET_ITEM(l->cache_entries, part);
        if (ent != Py_None) return ent;
    }
    PyObject *tmp = NULL;
    if (!part_o) { tmp = PyLong_FromLongLong(part); part_o = tmp; }
    if (!part_o) return NULL;
    PyObject *kt = PyTuple_Pack(2, topic, part_o);
    Py_XDECREF(tmp);
    if (!kt) return NULL;
    PyObject *ent = PyDict_GetItemWithError(l->map, kt);
    Py_DECREF(kt);
    if (!ent) return NULL;
    // populate the cache: each topic keeps its own entries list in
    // cache_map (str-keyed, hash cached in the str object), so a
    // multi-topic round-robin switches lists instead of rebuilding
    // them. The fast slot is repointed ONLY after every allocation
    // succeeded — a poisoned slot would route records to the wrong
    // topic's arena.
    if (l->cache_topic != topic) {
        if (!l->cache_map) {
            l->cache_map = PyDict_New();
            if (!l->cache_map) return NULL;
        }
        PyObject *lst = PyDict_GetItemWithError(l->cache_map, topic);
        if (!lst) {
            if (PyErr_Occurred()) return NULL;
            lst = PyList_New(0);
            if (!lst) return NULL;
            if (PyDict_SetItem(l->cache_map, topic, lst) < 0) {
                Py_DECREF(lst);
                return NULL;
            }
            Py_DECREF(lst);          // the dict's reference keeps it
        }
        Py_INCREF(topic);
        Py_XSETREF(l->cache_topic, topic);
        Py_INCREF(lst);
        Py_XSETREF(l->cache_entries, lst);
    }
    while (PyList_GET_SIZE(l->cache_entries) <= part) {
        if (PyList_Append(l->cache_entries, Py_None) < 0) return NULL;
    }
    Py_INCREF(ent);
    PyList_SetItem(l->cache_entries, part, ent);
    return ent;
}

// Java-compatible murmur2 (utils/hash.py murmur2; reference
// rd_murmur2, rdmurmur2.c:19) — trailing bytes read as SIGNED chars,
// exactly like org.apache.kafka.common.utils.Utils.murmur2.
static uint32_t tk_murmur2(const uint8_t *data, int64_t n) {
    const uint32_t M = 0x5BD1E995u;
    uint32_t h = 0x9747B28Cu ^ (uint32_t)n;
    int64_t i = 0;
    for (; n - i >= 4; i += 4) {
        uint32_t k = (uint32_t)data[i] | ((uint32_t)data[i + 1] << 8)
                   | ((uint32_t)data[i + 2] << 16)
                   | ((uint32_t)data[i + 3] << 24);
        k *= M;
        k ^= k >> 24;
        k *= M;
        h *= M;
        h ^= k;
    }
    switch (n - i) {
    case 3: h ^= (uint32_t)(int8_t)data[i + 2] << 16; /* fallthrough */
    case 2: h ^= (uint32_t)(int8_t)data[i + 1] << 8;  /* fallthrough */
    case 1: h ^= (uint32_t)(int8_t)data[i];
            h *= M;
    }
    h ^= h >> 13;
    h *= M;
    h ^= h >> 15;
    return h;
}

// zigzag varint append (protocol/varint.enc_i64 semantics)
static void hv_varint(std::vector<uint8_t> &v, int64_t val) {
    uint64_t z = ((uint64_t)val << 1) ^ (uint64_t)(val >> 63);
    while (z >= 0x80) { v.push_back((uint8_t)(z | 0x80)); z >>= 7; }
    v.push_back((uint8_t)z);
}

// Encode produce(headers=...) into the record's wire header framing
// (count varint + per-header key/value framing) — the exact bytes
// MsgsetWriterV2._build_py emits.  Accepts a tuple/list of (str|bytes,
// bytes|None) 2-tuples; anything else returns -1 with NO exception
// pending (the caller falls back to the Python Message path, which
// owns the full normalization/raising semantics).
static int encode_headers_blob(PyObject *hdrs, std::vector<uint8_t> &out) {
    int is_tuple = PyTuple_Check(hdrs);
    if (!is_tuple && !PyList_Check(hdrs)) return -1;
    Py_ssize_t nh = is_tuple ? PyTuple_GET_SIZE(hdrs)
                             : PyList_GET_SIZE(hdrs);
    out.clear();
    hv_varint(out, nh);
    for (Py_ssize_t i = 0; i < nh; i++) {
        PyObject *it = is_tuple ? PyTuple_GET_ITEM(hdrs, i)
                                : PyList_GET_ITEM(hdrs, i);
        if (!PyTuple_Check(it) || PyTuple_GET_SIZE(it) != 2) return -1;
        PyObject *hk = PyTuple_GET_ITEM(it, 0);
        PyObject *hv = PyTuple_GET_ITEM(it, 1);
        const char *kp;
        Py_ssize_t kl;
        if (PyUnicode_Check(hk)) {
            kp = PyUnicode_AsUTF8AndSize(hk, &kl);
            if (!kp) { PyErr_Clear(); return -1; }
        } else if (PyBytes_Check(hk)) {
            kp = PyBytes_AS_STRING(hk);
            kl = PyBytes_GET_SIZE(hk);
        } else {
            return -1;
        }
        hv_varint(out, kl);
        out.insert(out.end(), (const uint8_t *)kp,
                   (const uint8_t *)kp + kl);
        if (hv == Py_None) {
            hv_varint(out, -1);
        } else if (PyBytes_Check(hv)) {
            Py_ssize_t vl = PyBytes_GET_SIZE(hv);
            hv_varint(out, vl);
            const char *vp = PyBytes_AS_STRING(hv);
            out.insert(out.end(), (const uint8_t *)vp,
                       (const uint8_t *)vp + vl);
        } else {
            return -1;
        }
    }
    return 0;
}

// per-thread header-blob scratch for lane_produce (file scope so the
// eligibility gotos never jump over its declaration)
static thread_local std::vector<uint8_t> lane_hscratch;

// produce(topic, value=None, key=None, partition=-1, on_delivery=None,
//         timestamp=0, headers=(), opaque=None)
// The public producer entry point.  Eligible records append straight
// into the per-toppar arena; everything else tail-calls the fallback.
// Widened eligibility (ISSUE 16): explicit non-negative timestamps,
// record headers (pre-encoded into the side arena), and partition=UA
// via native murmur2 auto-partition when Python installed a part_map
// entry for the topic.
static PyObject *lane_produce(Lane *l, PyObject *const *args,
                              Py_ssize_t nargs, PyObject *kwnames) {
    PyObject *argv[8] = {NULL, NULL, NULL, NULL, NULL, NULL, NULL, NULL};
    if (nargs > 8) { // >8 positionals: fallback raises the proper TypeError
        if (!l->fallback) {
            PyErr_SetString(PyExc_RuntimeError, "lane fallback not set");
            return NULL;
        }
        return PyObject_Vectorcall(l->fallback, args, nargs, kwnames);
    }
    Py_ssize_t npos = nargs;
    for (Py_ssize_t i = 0; i < npos; i++) argv[i] = args[i];
    int eligible_kw = 1;
    if (kwnames) {
        Py_ssize_t nkw = PyTuple_GET_SIZE(kwnames);
        for (Py_ssize_t i = 0; i < nkw; i++) {
            PyObject *name = PyTuple_GET_ITEM(kwnames, i);
            int hit = 0;
            for (int j = 0; lane_kwnames[j]; j++) {
                if (name == lane_kw_interned[j]
                    || PyObject_RichCompareBool(name, lane_kw_interned[j],
                                                Py_EQ) == 1) {
                    if (j < npos) {
                        // duplicate positional+keyword: route to the
                        // Python fallback for the proper TypeError
                        eligible_kw = 0;
                        break;
                    }
                    argv[j] = args[nargs + i];
                    hit = 1;
                    break;
                }
            }
            if (!eligible_kw) break;
            if (!hit) { eligible_kw = 0; argv[0] = NULL; break; }
        }
    }
    PyObject *topic = argv[0], *value = argv[1], *key = argv[2];
    PyObject *partition = argv[3];
    PyObject *part_o = NULL;     // PyLong for lane_lookup (may be arg)
    const uint8_t *hp = NULL;
    int64_t hl = 0;
    int64_t ts_ms = 0;
    long long part = -1;
    if (!l->enabled || l->fatal) { l->c_fb_disabled++; goto fallback; }
    if (!eligible_kw || topic == NULL || !PyUnicode_Check(topic)
        || !(value == NULL || value == Py_None || PyBytes_Check(value))
        || !(key == NULL || key == Py_None || PyBytes_Check(key))
        || (partition != NULL && !PyLong_Check(partition))
        || !(argv[4] == NULL || argv[4] == Py_None)      // on_delivery
        || !(argv[7] == NULL || argv[7] == Py_None)) {   // opaque
        l->c_fb_shape++;
        goto fallback;
    }
    if (argv[5] != NULL) {                               // timestamp
        if (!PyLong_Check(argv[5])) { l->c_fb_shape++; goto fallback; }
        ts_ms = PyLong_AsLongLong(argv[5]);
        if (ts_ms < 0 || PyErr_Occurred()) {
            PyErr_Clear();
            l->c_fb_shape++;
            goto fallback;
        }
    }
    if (argv[6] != NULL && argv[6] != Py_None) {         // headers
        int empty =
            (PyTuple_Check(argv[6]) && PyTuple_GET_SIZE(argv[6]) == 0)
            || (PyList_Check(argv[6]) && PyList_GET_SIZE(argv[6]) == 0);
        if (!empty) {
            if (encode_headers_blob(argv[6], lane_hscratch) < 0) {
                l->c_fb_shape++;
                goto fallback;
            }
            hp = lane_hscratch.data();
            hl = (int64_t)lane_hscratch.size();
        }
    }
    if (partition != NULL) {
        part = PyLong_AsLongLong(partition);
        if (PyErr_Occurred()) {
            PyErr_Clear();
            l->c_fb_shape++;
            goto fallback;
        }
        part_o = partition;
    }
    if (part < 0) {
        // partition=UA: native murmur2 auto-partition.  part_map is
        // installed by Python only for the murmur2-family partitioners
        // once the topic's partition count is known (and dropped on
        // metadata change), so a hit here is bit-exact vs the Python
        // partitioner.
        PyObject *pe = PyDict_GetItemWithError(l->part_map, topic);
        if (!pe) {
            if (PyErr_Occurred()) return NULL;
            l->c_fb_autopart++;
            goto fallback;
        }
        long long cnt = PyLong_AsLongLong(PyTuple_GET_ITEM(pe, 0));
        long long mode = PyLong_AsLongLong(PyTuple_GET_ITEM(pe, 1));
        int keyed = key != NULL && key != Py_None
                    && PyBytes_GET_SIZE(key) > 0;
        if (cnt <= 0 || (mode == 2 && !keyed)) {
            // murmur2_random routes falsy keys through the Python
            // random partitioner — not reproducible here
            l->c_fb_autopart++;
            goto fallback;
        }
        const uint8_t *kd = keyed
            ? (const uint8_t *)PyBytes_AS_STRING(key)
            : (const uint8_t *)"";
        int64_t kn = keyed ? PyBytes_GET_SIZE(key) : 0;
        part = (long long)((tk_murmur2(kd, kn) & 0x7FFFFFFFu)
                           % (uint32_t)cnt);
        part_o = NULL;           // lane_lookup builds the PyLong
    }
    {
        // last-topic cache: pointer-identity topic + partition index
        // replaces tuple-pack + dict-hash on the steady-state path
        PyObject *ent = lane_lookup(l, topic, part, part_o);
        if (!ent) {
            if (PyErr_Occurred()) return NULL;
            l->c_fb_noent++;
            goto fallback;       // first sight: Python sets the entry up
        }
        Arena *a = (Arena *)PyTuple_GET_ITEM(ent, 0);
        int64_t kl = (key && key != Py_None) ? PyBytes_GET_SIZE(key) : -1;
        int64_t vl = (value && value != Py_None)
                         ? PyBytes_GET_SIZE(value) : -1;
        int64_t sz = (kl > 0 ? kl : 0) + (vl > 0 ? vl : 0);
        if (sz > l->copy_max || hl > l->copy_max) {
            l->c_fb_oversize++;
            goto fallback;      // message.copy.max.bytes (and the
                                // message.max.bytes cap the caller
                                // folds in): keep a reference /
                                // let the slow path size-check
        }
        if (l->msg_cnt >= l->max_msgs
            || l->msg_bytes + sz > l->max_bytes) {
            l->c_fb_qfull++;
            goto fallback;      // slow path raises _QUEUE_FULL
        }
        if (arena_do_append(
                a, kl >= 0 ? PyBytes_AS_STRING(key) : NULL, kl,
                vl >= 0 ? PyBytes_AS_STRING(value) : NULL, vl,
                ts_ms, hp, hl) < 0)
            return NULL;
        l->msg_cnt += 1;
        l->msg_bytes += sz;
        l->c_engaged += 1;
        if (a->count - a->start == 1 && l->wake) {
            // empty -> non-empty: wake the leader broker
            PyObject *tp = PyTuple_GET_ITEM(ent, 1);
            PyObject *r = PyObject_CallOneArg(l->wake, tp);
            if (!r) return NULL;
            Py_DECREF(r);
        }
        Py_RETURN_NONE;
    }
    // slow path: the Python Message pipeline (also first-sight setup)
fallback:
    // eligibility parsing may have left an OverflowError pending (e.g.
    // partition or timestamp outside int64) — clear before calling out
    if (PyErr_Occurred()) PyErr_Clear();
    if (!l->fallback) {
        PyErr_SetString(PyExc_RuntimeError, "lane fallback not set");
        return NULL;
    }
    return PyObject_Vectorcall(l->fallback, args, nargs, kwnames);
}

// produce_batch(topic, msgs, start, default_partition)
//   -> (next_index, appended)
// Append eligible dict records from msgs[start:] straight into their
// toppar arenas without a Python frame per record (the C analog of
// rd_kafka_produce_batch, rdkafka_msg.c:478).  Stops at the first item
// needing the Python path (headers/timestamp/opaque/oversize/queue-full/
// unknown toppar) and returns its index so the wrapper can handle that
// ONE item (preserving FIFO and per-item error semantics) and re-enter.
static PyObject *lane_produce_batch(Lane *l, PyObject *const *args,
                                    Py_ssize_t nargs) {
    if (nargs != 4) {
        PyErr_SetString(PyExc_TypeError,
                        "produce_batch(topic, msgs, start, default_part)");
        return NULL;
    }
    PyObject *topic = args[0], *msgs = args[1];
    int64_t start = PyLong_AsLongLong(args[2]);
    int64_t defpart = PyLong_AsLongLong(args[3]);
    if (PyErr_Occurred()) return NULL;
    if (!PyList_Check(msgs)) {
        PyErr_SetString(PyExc_TypeError, "msgs must be a list");
        return NULL;
    }
    int64_t n = PyList_GET_SIZE(msgs);
    int64_t appended = 0, i = start;
    PyObject *k_value = lane_kw_interned[1], *k_key = lane_kw_interned[2];
    PyObject *k_part = lane_kw_interned[3], *k_ts = lane_kw_interned[5];
    PyObject *k_hdrs = lane_kw_interned[6];
    if (!(l->enabled && !l->fatal && PyUnicode_Check(topic)))
        return Py_BuildValue("(LL)", (long long)start, 0LL);
    for (; i < n; i++) {
        PyObject *m = PyList_GET_ITEM(msgs, i);
        if (!PyDict_Check(m)) break;
        PyObject *value = PyDict_GetItemWithError(m, k_value);
        if (!value && PyErr_Occurred()) return NULL;
        PyObject *key = PyDict_GetItemWithError(m, k_key);
        if (!key && PyErr_Occurred()) return NULL;
        PyObject *part_o = PyDict_GetItemWithError(m, k_part);
        if (!part_o && PyErr_Occurred()) return NULL;
        PyObject *ts = PyDict_GetItemWithError(m, k_ts);
        if (!ts && PyErr_Occurred()) return NULL;
        PyObject *hdrs = PyDict_GetItemWithError(m, k_hdrs);
        if (!hdrs && PyErr_Occurred()) return NULL;
        int64_t part = defpart;
        if (part_o) {
            if (!PyLong_Check(part_o)) break;
            part = PyLong_AsLongLong(part_o);
            if (PyErr_Occurred()) { PyErr_Clear(); break; }
        }
        int ok =
            part >= 0
            && (value == NULL || value == Py_None || PyBytes_Check(value))
            && (key == NULL || key == Py_None || PyBytes_Check(key))
            && (ts == NULL || (PyLong_Check(ts)
                               && PyLong_AsLongLong(ts) == 0))
            && (hdrs == NULL || hdrs == Py_None
                || (PyTuple_Check(hdrs) && PyTuple_GET_SIZE(hdrs) == 0)
                || (PyList_Check(hdrs) && PyList_GET_SIZE(hdrs) == 0));
        if (!ok) {
            // a timestamp outside int64 leaves OverflowError pending —
            // clear it before handing the item to the Python path
            if (PyErr_Occurred()) PyErr_Clear();
            break;
        }
        // toppar lookup via the same last-topic cache as produce()
        PyObject *ent = lane_lookup(l, topic, part, part_o);
        if (!ent) {
            if (PyErr_Occurred()) return NULL;
            break;                 // unknown toppar: Python sets it up
        }
        int64_t kl = (key && key != Py_None) ? PyBytes_GET_SIZE(key) : -1;
        int64_t vl = (value && value != Py_None)
                         ? PyBytes_GET_SIZE(value) : -1;
        int64_t sz = (kl > 0 ? kl : 0) + (vl > 0 ? vl : 0);
        if (sz > l->copy_max) break;
        if (l->msg_cnt >= l->max_msgs || l->msg_bytes + sz > l->max_bytes)
            break;                 // Python raises/records _QUEUE_FULL
        Arena *a = (Arena *)PyTuple_GET_ITEM(ent, 0);
        if (arena_do_append(
                a, kl >= 0 ? PyBytes_AS_STRING(key) : NULL, kl,
                vl >= 0 ? PyBytes_AS_STRING(value) : NULL, vl,
                0, NULL, 0) < 0)
            return NULL;
        l->msg_cnt += 1;
        l->msg_bytes += sz;
        l->c_engaged += 1;
        appended++;
        if (a->count - a->start == 1 && l->wake) {
            PyObject *tp = PyTuple_GET_ITEM(ent, 1);
            PyObject *r = PyObject_CallOneArg(l->wake, tp);
            if (!r) return NULL;
            Py_DECREF(r);
        }
        // clear a stale per-item error from a previous attempt
        if (k_error_interned
            && PyDict_Contains(m, k_error_interned) == 1)
            PyDict_DelItem(m, k_error_interned);
    }
    return Py_BuildValue("(LL)", (long long)i, (long long)appended);
}

// produce_raw(topic, partition, base_addr, klens_addr, vlens_addr,
//             count) -> appended count | -1 (toppar not registered)
// The C-ABI batch lane (capi tk_produce_batch): the caller hands the
// ARENA-LAYOUT arrays (concatenated key||value bytes + int32 len
// arrays, -1 = null) by address and the whole run appends in one
// GIL-held native pass — the reference's rd_kafka_produce_batch with
// the enqueue lane's memory layout. Stops early on queue-full.
static PyObject *lane_produce_raw(Lane *l, PyObject *const *args,
                                  Py_ssize_t nargs) {
    if (nargs != 6) {
        PyErr_SetString(PyExc_TypeError,
                        "produce_raw(topic, partition, base_addr, "
                        "klens_addr, vlens_addr, count)");
        return NULL;
    }
    PyObject *topic = args[0];
    int64_t part = PyLong_AsLongLong(args[1]);
    const uint8_t *base = (const uint8_t *)PyLong_AsVoidPtr(args[2]);
    const int32_t *klens = (const int32_t *)PyLong_AsVoidPtr(args[3]);
    const int32_t *vlens = (const int32_t *)PyLong_AsVoidPtr(args[4]);
    int64_t count = PyLong_AsLongLong(args[5]);
    if (PyErr_Occurred()) return NULL;
    if (!(l->enabled && !l->fatal && part >= 0 && PyUnicode_Check(topic)))
        return PyLong_FromLong(-1);
    PyObject *ent = lane_lookup(l, topic, part, NULL);
    if (!ent) {
        if (PyErr_Occurred()) return NULL;
        return PyLong_FromLong(-1);
    }
    Arena *a = (Arena *)PyTuple_GET_ITEM(ent, 0);
    int was_empty = (a->count == a->start);
    const uint8_t *src = base;
    int64_t i = 0;
    for (; i < count; i++) {
        int64_t kl = klens[i], vl = vlens[i];
        int64_t sz = (kl > 0 ? kl : 0) + (vl > 0 ? vl : 0);
        if (sz > l->copy_max) break;
        if (l->msg_cnt >= l->max_msgs || l->msg_bytes + sz > l->max_bytes)
            break;
        const uint8_t *kp = kl > 0 ? src : NULL;
        if (kl > 0) src += kl;
        const uint8_t *vp = vl > 0 ? src : NULL;
        if (vl > 0) src += vl;
        if (arena_do_append(a, (const char *)kp, kl,
                            (const char *)vp, vl, 0, NULL, 0) < 0)
            return NULL;
        l->msg_cnt += 1;
        l->msg_bytes += sz;
        l->c_engaged += 1;
    }
    if (i > 0 && was_empty && l->wake) {
        PyObject *tp = PyTuple_GET_ITEM(ent, 1);
        PyObject *r = PyObject_CallOneArg(l->wake, tp);
        if (!r) return NULL;
        Py_DECREF(r);
    }
    return PyLong_FromLongLong(i);
}

// murmur2_partition(key: bytes, partition_cnt: int) -> int
// Module-level parity hook: the exact partition lane_produce computes
// natively, exported so tests can sweep it against utils/hash.py.
static PyObject *mod_murmur2_partition(PyObject *Py_UNUSED(self),
                                       PyObject *const *args,
                                       Py_ssize_t nargs) {
    if (nargs != 2 || !PyBytes_Check(args[0])) {
        PyErr_SetString(PyExc_TypeError,
                        "murmur2_partition(key: bytes, cnt: int)");
        return NULL;
    }
    long long cnt = PyLong_AsLongLong(args[1]);
    if (PyErr_Occurred()) return NULL;
    if (cnt <= 0) {
        PyErr_SetString(PyExc_ValueError, "partition_cnt must be > 0");
        return NULL;
    }
    uint32_t h = tk_murmur2((const uint8_t *)PyBytes_AS_STRING(args[0]),
                            PyBytes_GET_SIZE(args[0]));
    return PyLong_FromUnsignedLong((h & 0x7FFFFFFFu) % (uint32_t)cnt);
}

// ==================================================== fused builder =====
//
// build_batch: ArenaBatch -> complete wire RecordBatch (v2 header +
// records, compressed, CRC patched) in ONE call with the GIL released.
// The 3-phase Python pipeline (frame -> compress_many -> assemble ->
// patch_crc) moves each 1MB batch through ~5 user-space copies plus
// per-phase ctypes glue; on a 1-core host that memory traffic IS the
// producer ceiling.  Fusing drops it to: frame into a reused scratch,
// compress scratch -> the output bytes, header+CRC in place.
// (Reference: rd_kafka_msgset_writer_finalize does header+CRC in place
// on the accumulated rd_buf, rdkafka_msgset_writer.c:1230.)
//
// The codec functions live in codec.cpp, compiled into this extension
// (build.py links both translation units).

extern "C" {
int64_t tk_frame_v2_bound(int64_t payload_bytes, int count);
int64_t tk_frame_v2(const uint8_t *base, const int32_t *klens,
                    const int32_t *vlens, const int64_t *ts_deltas,
                    int count, uint8_t *out, int64_t cap);
int64_t tk_frame_v2_run(const uint8_t *base, const int32_t *klens,
                        const int32_t *vlens, const int64_t *tss,
                        int64_t now_ms, const uint8_t *hbuf,
                        const int32_t *hlens, int count,
                        uint8_t *out, int64_t cap,
                        int64_t *first_ts, int64_t *max_ts);
int64_t tk_lz4f_bound(int64_t n);
int64_t tk_lz4f_compress_fast(const uint8_t *src, int64_t n,
                              uint8_t *dst, int64_t cap);
int64_t tk_lz4f_decompress(const uint8_t *src, int64_t n,
                           uint8_t *dst, int64_t cap);
int64_t tk_snappy_bound(int64_t n);
int64_t tk_snappy_compress(const uint8_t *src, int64_t n,
                           uint8_t *dst, int64_t cap);
int64_t tk_snappy_uncompressed_length(const uint8_t *src, int64_t n);
int64_t tk_lz4f_decompressed_size(const uint8_t *src, int64_t n);
int64_t tk_snappy_decompress(const uint8_t *src, int64_t n,
                             uint8_t *dst, int64_t cap);
uint32_t tk_crc32c(const uint8_t *p, int64_t n, uint32_t crc);
}

// RecordBatch v2 header layout (public Apache Kafka protocol; mirrors
// proto.py V2_OF_* and reference rdkafka_proto.h RD_KAFKAP_MSGSET_V2_OF_*)
static const int64_t V2_HDR = 61;
static const int64_t V2_OF_CRC = 17;
static const int64_t V2_OF_ATTR = 21;

static inline void be16(uint8_t *p, uint16_t v) {
    p[0] = (uint8_t)(v >> 8); p[1] = (uint8_t)v;
}
static inline void be32(uint8_t *p, uint32_t v) {
    p[0] = (uint8_t)(v >> 24); p[1] = (uint8_t)(v >> 16);
    p[2] = (uint8_t)(v >> 8); p[3] = (uint8_t)v;
}
static inline void be64(uint8_t *p, uint64_t v) {
    be32(p, (uint32_t)(v >> 32)); be32(p + 4, (uint32_t)v);
}

// build_batch(base, klens, vlens, count, now_ms, pid, epoch, base_seq,
//             codec_id[, attr_flags[, tss, hbuf, hlens]]) -> bytes
// codec_id: 0 none, 2 snappy, 3 lz4 (the wire attribute values).
// attr_flags: extra v2 attribute bits OR'd into the attribute word
// (the transactional bit 0x10 for EOS batches; codec bits still come
// from the compression outcome).
// tss/hbuf/hlens (each bytes|None) are the arena run's per-record
// explicit-timestamp int64s and pre-encoded header blobs; with all
// three None every record carries now_ms (fast-lane default) so
// first=max=now_ms and every delta is 0 — exactly what
// MsgsetWriterV2._build_py emits for the same records.
static PyObject *mod_build_batch(PyObject *Py_UNUSED(self),
                                 PyObject *const *args, Py_ssize_t nargs) {
    if (nargs != 9 && nargs != 10 && nargs != 13) {
        PyErr_SetString(PyExc_TypeError,
                        "build_batch(base, klens, vlens, count, now_ms, "
                        "pid, epoch, base_seq, codec_id[, attr_flags"
                        "[, tss, hbuf, hlens]])");
        return NULL;
    }
    Py_buffer base, kb, vb;
    Py_buffer tsb = {0}, hb = {0}, hlb = {0};
    int has_ts = 0, has_h = 0;
    if (PyObject_GetBuffer(args[0], &base, PyBUF_SIMPLE) < 0) return NULL;
    if (PyObject_GetBuffer(args[1], &kb, PyBUF_SIMPLE) < 0) {
        PyBuffer_Release(&base); return NULL;
    }
    if (PyObject_GetBuffer(args[2], &vb, PyBUF_SIMPLE) < 0) {
        PyBuffer_Release(&base); PyBuffer_Release(&kb); return NULL;
    }
    int64_t count = PyLong_AsLongLong(args[3]);
    int64_t now_ms = PyLong_AsLongLong(args[4]);
    int64_t pid = PyLong_AsLongLong(args[5]);
    int64_t epoch = PyLong_AsLongLong(args[6]);
    int64_t base_seq = PyLong_AsLongLong(args[7]);
    int64_t codec = PyLong_AsLongLong(args[8]);
    int64_t attr_flags = nargs >= 10 ? PyLong_AsLongLong(args[9]) : 0;
    PyObject *out = NULL;
    if (PyErr_Occurred()) goto done;
    if (nargs == 13) {
        if (args[10] != Py_None) {
            if (PyObject_GetBuffer(args[10], &tsb, PyBUF_SIMPLE) < 0)
                goto done;
            has_ts = 1;
        }
        if (args[11] != Py_None) {
            if (PyObject_GetBuffer(args[11], &hb, PyBUF_SIMPLE) < 0)
                goto done;
            has_h = 1;
            if (args[12] == Py_None
                || PyObject_GetBuffer(args[12], &hlb, PyBUF_SIMPLE) < 0) {
                if (!PyErr_Occurred())
                    PyErr_SetString(PyExc_ValueError,
                                    "build_batch: hbuf without hlens");
                goto done;
            }
        }
    }
    if (count <= 0 || (int64_t)kb.len < count * 4
        || (int64_t)vb.len < count * 4
        || (has_ts && (int64_t)tsb.len < count * 8)
        || (has_h && (int64_t)hlb.len < count * 4)
        || (codec != 0 && codec != 2 && codec != 3)) {
        PyErr_SetString(PyExc_ValueError, "build_batch: bad arguments");
        goto done;
    }
    {
        int64_t fbound = tk_frame_v2_bound(
            base.len + (has_h ? (int64_t)hb.len : 0), (int)count);
        // worst-case payload: compressed bound, or the raw records when
        // incompressible (stored plain, attributes codec bits = 0)
        int64_t cap;
        if (codec == 3) cap = tk_lz4f_bound(fbound);
        else if (codec == 2) cap = tk_snappy_bound(fbound);
        else cap = fbound;
        if (cap < fbound) cap = fbound;
        out = PyBytes_FromStringAndSize(NULL, V2_HDR + cap);
        if (!out) goto done;
        uint8_t *o = (uint8_t *)PyBytes_AS_STRING(out);
        int64_t rlen = -1, plen = -1;
        int64_t first_ts = now_ms, max_ts = now_ms;
        int attr_codec = 0;
        const int64_t *tss_p =
            has_ts ? (const int64_t *)tsb.buf : NULL;
        const uint8_t *hbuf_p = has_h ? (const uint8_t *)hb.buf : NULL;
        const int32_t *hlens_p = has_h ? (const int32_t *)hlb.buf : NULL;
        // per-thread scratch for the uncompressed records (reused
        // across batches; freed when the thread exits)
        static thread_local std::vector<uint8_t> scratch;
        Py_BEGIN_ALLOW_THREADS
        if (codec == 0) {
            rlen = tk_frame_v2_run((const uint8_t *)base.buf,
                                   (const int32_t *)kb.buf,
                                   (const int32_t *)vb.buf,
                                   tss_p, now_ms, hbuf_p, hlens_p,
                                   (int)count, o + V2_HDR, cap,
                                   &first_ts, &max_ts);
            plen = rlen;
        } else {
            if ((int64_t)scratch.size() < fbound)
                scratch.resize((size_t)fbound);
            rlen = tk_frame_v2_run((const uint8_t *)base.buf,
                                   (const int32_t *)kb.buf,
                                   (const int32_t *)vb.buf,
                                   tss_p, now_ms, hbuf_p, hlens_p,
                                   (int)count, scratch.data(), fbound,
                                   &first_ts, &max_ts);
            if (rlen >= 0) {
                int64_t clen =
                    codec == 3
                        ? tk_lz4f_compress_fast(scratch.data(), rlen,
                                                o + V2_HDR, cap)
                        : tk_snappy_compress(scratch.data(), rlen,
                                             o + V2_HDR, cap);
                if (clen >= 0 && clen < rlen) {
                    plen = clen;
                    attr_codec = (int)codec;
                } else {          // incompressible: store plain
                    memcpy(o + V2_HDR, scratch.data(), (size_t)rlen);
                    plen = rlen;
                }
            }
        }
        if (rlen >= 0) {
            be64(o, 0);                               // BaseOffset
            be32(o + 8, (uint32_t)(V2_HDR - 12 + plen));  // Length
            // PartitionLeaderEpoch=0, matching the reference writer
            // (rdkafka_msgset_writer.c:368) and MsgsetWriterV2.assemble
            be32(o + 12, 0);
            o[16] = 2;                                // Magic
            be32(o + V2_OF_CRC, 0);                   // CRC placeholder
            be16(o + V2_OF_ATTR, (uint16_t)(attr_codec | attr_flags));
            be32(o + 23, (uint32_t)(count - 1));      // LastOffsetDelta
            be64(o + 27, (uint64_t)first_ts);         // FirstTimestamp
            be64(o + 35, (uint64_t)max_ts);           // MaxTimestamp
            be64(o + 43, (uint64_t)pid);
            be16(o + 51, (uint16_t)epoch);
            be32(o + 53, (uint32_t)base_seq);
            be32(o + 57, (uint32_t)count);
            be32(o + V2_OF_CRC,
                 tk_crc32c(o + V2_OF_ATTR, V2_HDR - V2_OF_ATTR + plen, 0));
        }
        Py_END_ALLOW_THREADS
        if (rlen < 0) {
            Py_CLEAR(out);
            PyErr_SetString(PyExc_ValueError,
                            "build_batch: frame capacity shortfall");
            goto done;
        }
        if (_PyBytes_Resize(&out, V2_HDR + plen) < 0) out = NULL;
    }
done:
    PyBuffer_Release(&base);
    PyBuffer_Release(&kb);
    PyBuffer_Release(&vb);
    if (has_ts) PyBuffer_Release(&tsb);
    if (has_h) {
        PyBuffer_Release(&hb);
        if (hlb.obj) PyBuffer_Release(&hlb);
    }
    return out;
}

// ============================================ fetch materialization =====
//
// materialize_v2: bulk-create delivery-ready client Message objects
// straight off tk_parse_v2's field table.  The Python loop sets 18
// slot attributes per record through bytecode (~1.5-2 us/record — the
// consumer budget); here each Message is tp_alloc + direct slot-offset
// stores.  Slot offsets come from the class's member descriptors, so
// this tracks the Python class definition (a missing slot fails loudly
// at first call, not per record).
// (Reference analog: rd_kafka_msgset_reader_msg_parse builds rko_msg
// structs inline, rdkafka_msgset_reader.c:902.)

#include <descrobject.h>

static const char *const MSG_SLOTS[] = {
    "topic", "partition", "key", "value", "headers", "offset",
    "timestamp", "timestamp_type", "error", "opaque", "msgid",
    "retries", "status", "enq_time", "ts_backoff", "latency_us",
    "on_delivery", "size", NULL};
enum {
    S_TOPIC, S_PARTITION, S_KEY, S_VALUE, S_HEADERS, S_OFFSET,
    S_TIMESTAMP, S_TSTYPE, S_ERROR, S_OPAQUE, S_MSGID,
    S_RETRIES, S_STATUS, S_ENQ, S_BACKOFF, S_LATENCY,
    S_ONDEL, S_SIZE, S_NSLOTS};

static PyTypeObject *msg_type_cached = NULL;
static Py_ssize_t msg_slot_off[S_NSLOTS];

static int resolve_msg_slots(PyTypeObject *type) {
    for (int i = 0; MSG_SLOTS[i]; i++) {
        PyObject *d = PyDict_GetItemString(type->tp_dict, MSG_SLOTS[i]);
        if (!d || !PyObject_TypeCheck(d, &PyMemberDescr_Type)) {
            PyErr_Format(PyExc_TypeError,
                         "materialize_v2: %s.%s is not a slot member",
                         type->tp_name, MSG_SLOTS[i]);
            return -1;
        }
        msg_slot_off[i] = ((PyMemberDescrObject *)d)->d_member->offset;
    }
    msg_type_cached = type;
    return 0;
}

static inline void slot_set(PyObject *m, int slot, PyObject *v) {
    // tp_alloc zeroed the slot; store a NEW reference (caller increfs)
    *(PyObject **)((char *)m + msg_slot_off[slot]) = v;
}

// materialize_v2(msg_type, records: bytes, fields_addr: int, n: int,
//                topic: str, partition: int, base_off: int, fo: int,
//                base_ts: int, append_ts: int, log_append: int,
//                tstype: int, status: object)
//   -> (list[Message], total_payload_bytes, header_fixups | None)
// header_fixups: [(list_index, ho, nh), ...] for records with headers —
// the (rare) header parse stays in Python.
static PyObject *mod_materialize_v2(PyObject *Py_UNUSED(self),
                                    PyObject *const *args,
                                    Py_ssize_t nargs) {
    if (nargs != 13) {
        PyErr_SetString(PyExc_TypeError, "materialize_v2: 13 args");
        return NULL;
    }
    PyTypeObject *type = (PyTypeObject *)args[0];
    if (!PyType_Check(args[0])) {
        PyErr_SetString(PyExc_TypeError, "arg 0 must be the Message type");
        return NULL;
    }
    if (type != msg_type_cached && resolve_msg_slots(type) < 0)
        return NULL;
    Py_buffer rb;
    if (PyObject_GetBuffer(args[1], &rb, PyBUF_SIMPLE) < 0) return NULL;
    const int64_t *fields = (const int64_t *)PyLong_AsVoidPtr(args[2]);
    int64_t n = PyLong_AsLongLong(args[3]);
    PyObject *topic = args[4];
    int64_t partition = PyLong_AsLongLong(args[5]);
    int64_t base_off = PyLong_AsLongLong(args[6]);
    int64_t fo = PyLong_AsLongLong(args[7]);
    int64_t base_ts = PyLong_AsLongLong(args[8]);
    PyObject *append_ts_obj = args[9];      // PyLong (shared when log_append)
    int log_append = (int)PyLong_AsLong(args[10]);
    PyObject *tstype = args[11];
    PyObject *status = args[12];
    if (PyErr_Occurred()) { PyBuffer_Release(&rb); return NULL; }
    const char *rbase = (const char *)rb.buf;
    int64_t rblen = rb.len;

    PyObject *list = PyList_New(0);
    PyObject *fixups = NULL;
    PyObject *part_obj = PyLong_FromLongLong(partition);
    PyObject *zero = PyLong_FromLong(0);
    PyObject *fzero = PyFloat_FromDouble(0.0);
    int64_t total = 0;
    // one-entry timestamp memo: fast-lane batches carry one timestamp
    int64_t ts_memo_v = INT64_MIN;
    PyObject *ts_memo = NULL;
    if (!list || !part_obj || !zero || !fzero) goto fail;
    for (int64_t i = 0; i < n; i++) {
        const int64_t *f = fields + i * 8;
        int64_t off = base_off + f[1];
        if (off < fo) continue;
        int64_t ko = f[2], kl = f[3], vo = f[4], vl = f[5];
        if (kl > 0 && (ko < 0 || ko + kl > rblen)) goto bounds;
        if (vl > 0 && (vo < 0 || vo + vl > rblen)) goto bounds;
        {
            PyObject *m = type->tp_alloc(type, 0);
            if (!m) goto fail;
            PyObject *key, *value, *headers, *off_o, *ts_o, *size_o;
            if (kl >= 0) key = PyBytes_FromStringAndSize(rbase + ko, kl);
            else { key = Py_None; Py_INCREF(key); }
            if (vl >= 0) value = PyBytes_FromStringAndSize(rbase + vo, vl);
            else { value = Py_None; Py_INCREF(value); }
            headers = PyList_New(0);
            off_o = PyLong_FromLongLong(off);
            if (log_append) {
                ts_o = append_ts_obj; Py_INCREF(ts_o);
            } else {
                int64_t tsv = base_ts + f[0];
                if (tsv != ts_memo_v || !ts_memo) {
                    Py_XDECREF(ts_memo);
                    ts_memo = PyLong_FromLongLong(tsv);
                    ts_memo_v = tsv;
                }
                ts_o = ts_memo; Py_XINCREF(ts_o);
            }
            int64_t sz = (vl > 0 ? vl : 0) + (kl > 0 ? kl : 0);
            size_o = PyLong_FromLongLong(sz);
            if (!key || !value || !headers || !off_o || !ts_o || !size_o) {
                Py_XDECREF(key); Py_XDECREF(value); Py_XDECREF(headers);
                Py_XDECREF(off_o); Py_XDECREF(ts_o); Py_XDECREF(size_o);
                Py_DECREF(m);
                goto fail;
            }
            Py_INCREF(topic);  slot_set(m, S_TOPIC, topic);
            Py_INCREF(part_obj); slot_set(m, S_PARTITION, part_obj);
            slot_set(m, S_KEY, key);
            slot_set(m, S_VALUE, value);
            slot_set(m, S_HEADERS, headers);
            slot_set(m, S_OFFSET, off_o);
            slot_set(m, S_TIMESTAMP, ts_o);
            Py_INCREF(tstype); slot_set(m, S_TSTYPE, tstype);
            Py_INCREF(Py_None); slot_set(m, S_ERROR, Py_None);
            Py_INCREF(Py_None); slot_set(m, S_OPAQUE, Py_None);
            Py_INCREF(zero); slot_set(m, S_MSGID, zero);
            Py_INCREF(zero); slot_set(m, S_RETRIES, zero);
            Py_INCREF(status); slot_set(m, S_STATUS, status);
            Py_INCREF(fzero); slot_set(m, S_ENQ, fzero);
            Py_INCREF(fzero); slot_set(m, S_BACKOFF, fzero);
            Py_INCREF(zero); slot_set(m, S_LATENCY, zero);
            Py_INCREF(Py_None); slot_set(m, S_ONDEL, Py_None);
            slot_set(m, S_SIZE, size_o);
            PyObject_GC_UnTrack(m);   // acyclic leaves only (see lazy)
            total += sz;
            if (PyList_Append(list, m) < 0) { Py_DECREF(m); goto fail; }
            Py_DECREF(m);
            if (f[7] > 0) {            // record carries headers: fix up
                if (!fixups) {
                    fixups = PyList_New(0);
                    if (!fixups) goto fail;
                }
                PyObject *t = Py_BuildValue(
                    "(nLL)", PyList_GET_SIZE(list) - 1,
                    (long long)f[6], (long long)f[7]);
                if (!t || PyList_Append(fixups, t) < 0) {
                    Py_XDECREF(t); goto fail;
                }
                Py_DECREF(t);
            }
        }
    }
    {
        PyObject *r = Py_BuildValue("(OLO)", list, (long long)total,
                                    fixups ? fixups : Py_None);
        Py_DECREF(list);
        Py_XDECREF(fixups);
        Py_XDECREF(ts_memo);
        Py_DECREF(part_obj); Py_DECREF(zero); Py_DECREF(fzero);
        PyBuffer_Release(&rb);
        return r;
    }
bounds:
    PyErr_SetString(PyExc_ValueError,
                    "materialize_v2: record field out of bounds");
fail:
    Py_XDECREF(list);
    Py_XDECREF(fixups);
    Py_XDECREF(ts_memo);
    Py_XDECREF(part_obj); Py_XDECREF(zero); Py_XDECREF(fzero);
    PyBuffer_Release(&rb);
    return NULL;
}

// crc32c_many(buffers) -> list[int]
// Per-buffer CRC32C with no join copy: the ctypes provider path
// concatenated every region into one contiguous base first (a ~2 GB/s
// memcpy in front of a ~15 GB/s hardware CRC).
static PyObject *mod_crc32c_many(PyObject *Py_UNUSED(self),
                                 PyObject *const *args,
                                 Py_ssize_t nargs) {
    if (nargs != 1) {
        PyErr_SetString(PyExc_TypeError, "crc32c_many(buffers)");
        return NULL;
    }
    PyObject *seq = PySequence_Fast(args[0], "crc32c_many: not a sequence");
    if (!seq) return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject *out = PyList_New(n);
    if (!out) { Py_DECREF(seq); return NULL; }
    std::vector<Py_buffer> bufs((size_t)n);
    Py_ssize_t got = 0;
    for (; got < n; got++) {
        if (PyObject_GetBuffer(PySequence_Fast_GET_ITEM(seq, got),
                               &bufs[got], PyBUF_SIMPLE) < 0)
            break;
    }
    if (got == n) {
        std::vector<uint32_t> crcs((size_t)n);
        Py_BEGIN_ALLOW_THREADS
        for (Py_ssize_t i = 0; i < n; i++)
            crcs[i] = tk_crc32c((const uint8_t *)bufs[i].buf,
                                bufs[i].len, 0);
        Py_END_ALLOW_THREADS
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *v = PyLong_FromUnsignedLong(crcs[i]);
            if (!v) { Py_CLEAR(out); break; }
            PyList_SET_ITEM(out, i, v);
        }
    } else {
        Py_CLEAR(out);
    }
    for (Py_ssize_t i = 0; i < got; i++) PyBuffer_Release(&bufs[i]);
    Py_DECREF(seq);
    return out;
}

// decompress_many(codec_id, buffers, hints|None) -> list[bytes|None]
// codec_id: 3 lz4-frame, 2 raw snappy.  Output bytes are written in
// place (alloc, decompress with the GIL released, shrink) — no join of
// the inputs, no string_at copy of the outputs.  A buffer that fails
// comes back None (caller falls back / errors the batch).
static PyObject *mod_decompress_many(PyObject *Py_UNUSED(self),
                                     PyObject *const *args,
                                     Py_ssize_t nargs) {
    if (nargs != 3) {
        PyErr_SetString(PyExc_TypeError,
                        "decompress_many(codec_id, buffers, hints)");
        return NULL;
    }
    int64_t codec = PyLong_AsLongLong(args[0]);
    if (PyErr_Occurred()) return NULL;
    if (codec != 2 && codec != 3) {
        PyErr_SetString(PyExc_ValueError, "codec_id must be 2 or 3");
        return NULL;
    }
    PyObject *seq = PySequence_Fast(args[1],
                                    "decompress_many: not a sequence");
    if (!seq) return NULL;
    PyObject *hints = args[2] == Py_None ? NULL : args[2];
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject *out = PyList_New(n);
    if (!out) { Py_DECREF(seq); return NULL; }
    for (Py_ssize_t i = 0; i < n; i++) {
        Py_buffer src;
        if (PyObject_GetBuffer(PySequence_Fast_GET_ITEM(seq, i), &src,
                               PyBUF_SIMPLE) < 0) {
            Py_DECREF(seq); Py_DECREF(out);
            return NULL;
        }
        int64_t cap = 0;
        if (hints) {
            PyObject *h = PySequence_GetItem(hints, i);
            if (h) { cap = PyLong_AsLongLong(h); Py_DECREF(h); }
            if (PyErr_Occurred()) PyErr_Clear();
        }
        if (codec == 2) {
            int64_t ul = tk_snappy_uncompressed_length(
                (const uint8_t *)src.buf, src.len);
            if (ul >= 0 && ul > cap) cap = ul;
        } else if (cap <= 0) {
            // lz4: exact size by a write-free sequence walk — the 4x
            // guess below re-decodes high-ratio batches (40x is normal
            // for templated payloads) through the retry loop
            int64_t ul = tk_lz4f_decompressed_size(
                (const uint8_t *)src.buf, src.len);
            if (ul > 0) cap = ul;
        }
        if (cap <= 0) cap = 4 * src.len + (64 << 10);
        PyObject *b = NULL;
        int64_t r = -4;
        // untrusted input: never let the retry doubling request more
        // than the format's max expansion (~255:1 for lz4; snappy's
        // preamble is authoritative but bounded the same way)
        const int64_t cap_max = 256 * src.len + (64 << 10);
        if (cap > cap_max) cap = cap_max;
        for (int attempt = 0; attempt < 8; attempt++) {
            b = PyBytes_FromStringAndSize(NULL, cap);
            if (!b) break;
            uint8_t *dst = (uint8_t *)PyBytes_AS_STRING(b);
            Py_BEGIN_ALLOW_THREADS
            r = codec == 3
                    ? tk_lz4f_decompress((const uint8_t *)src.buf,
                                         src.len, dst, cap)
                    : tk_snappy_decompress((const uint8_t *)src.buf,
                                           src.len, dst, cap);
            Py_END_ALLOW_THREADS
            if (r != -4) break;          // -4 = capacity shortfall
            Py_DECREF(b); b = NULL;
            cap *= 4;
            if (cap > cap_max) {
                if (cap / 4 >= cap_max) break;   // already tried max
                cap = cap_max;
            }
        }
        PyBuffer_Release(&src);
        if (b && r >= 0 && _PyBytes_Resize(&b, r) == 0) {
            PyList_SET_ITEM(out, i, b);
        } else {
            Py_XDECREF(b);
            if (PyErr_Occurred()) PyErr_Clear();
            Py_INCREF(Py_None);
            PyList_SET_ITEM(out, i, Py_None);
        }
    }
    Py_DECREF(seq);
    return out;
}

// materialize_arena(msg_type, base, klens, vlens, count, topic,
//                   partition, base_offset, msgid_base, enq_time,
//                   retries, status, error) -> list[Message]
// Bulk Message creation from the ARENA layout (concatenated key||value
// + int32 len arrays) — the delivery-report path's ArenaBatch
// materialization (kafka.dr_msgq), same slot-store scheme as
// materialize_v2.  base_offset < 0 stores offset -1 per message.
static PyObject *mod_materialize_arena(PyObject *Py_UNUSED(self),
                                       PyObject *const *args,
                                       Py_ssize_t nargs) {
    if (nargs != 13) {
        PyErr_SetString(PyExc_TypeError, "materialize_arena: 13 args");
        return NULL;
    }
    PyTypeObject *type = (PyTypeObject *)args[0];
    if (!PyType_Check(args[0])) {
        PyErr_SetString(PyExc_TypeError, "arg 0 must be the Message type");
        return NULL;
    }
    if (type != msg_type_cached && resolve_msg_slots(type) < 0)
        return NULL;
    Py_buffer base, kb, vb;
    if (PyObject_GetBuffer(args[1], &base, PyBUF_SIMPLE) < 0) return NULL;
    if (PyObject_GetBuffer(args[2], &kb, PyBUF_SIMPLE) < 0) {
        PyBuffer_Release(&base); return NULL;
    }
    if (PyObject_GetBuffer(args[3], &vb, PyBUF_SIMPLE) < 0) {
        PyBuffer_Release(&base); PyBuffer_Release(&kb); return NULL;
    }
    int64_t count = PyLong_AsLongLong(args[4]);
    PyObject *topic = args[5];
    int64_t partition = PyLong_AsLongLong(args[6]);
    int64_t base_off = PyLong_AsLongLong(args[7]);
    int64_t msgid_base = PyLong_AsLongLong(args[8]);
    PyObject *enq_time = args[9];       // float (shared)
    PyObject *retries = args[10];       // int (shared)
    PyObject *status = args[11];
    PyObject *error = args[12];         // KafkaError | None (shared)
    PyObject *list = NULL, *part_obj = NULL, *ts_obj = NULL;
    PyObject *fzero = NULL, *zero = NULL;
    const int32_t *kl = (const int32_t *)kb.buf;
    const int32_t *vl = (const int32_t *)vb.buf;
    const char *src = (const char *)base.buf;
    int64_t remain = base.len;
    if (PyErr_Occurred()) goto done;
    if (count < 0 || (int64_t)kb.len < count * 4
        || (int64_t)vb.len < count * 4) {
        PyErr_SetString(PyExc_ValueError, "materialize_arena: bad args");
        goto done;
    }
    list = PyList_New(0);
    part_obj = PyLong_FromLongLong(partition);
    {
        // fast-lane records carry no per-record wall clock; DR messages
        // report the materialization time (Message.__init__ behavior)
        struct timespec ts;
        clock_gettime(CLOCK_REALTIME, &ts);
        ts_obj = PyLong_FromLongLong((int64_t)ts.tv_sec * 1000
                                     + ts.tv_nsec / 1000000);
    }
    fzero = PyFloat_FromDouble(0.0);
    zero = PyLong_FromLong(0);
    if (!list || !part_obj || !ts_obj || !fzero || !zero) goto fail;
    for (int64_t i = 0; i < count; i++) {
        int64_t k_len = kl[i], v_len = vl[i];
        int64_t need = (k_len > 0 ? k_len : 0) + (v_len > 0 ? v_len : 0);
        if (need > remain) {
            PyErr_SetString(PyExc_ValueError,
                            "materialize_arena: short base buffer");
            goto fail;
        }
        PyObject *m = type->tp_alloc(type, 0);
        if (!m) goto fail;
        PyObject *key, *value, *headers, *off_o, *msgid_o, *size_o;
        if (k_len >= 0) {
            key = PyBytes_FromStringAndSize(src, k_len);
            src += k_len; remain -= k_len;
        } else { key = Py_None; Py_INCREF(key); }
        if (v_len >= 0) {
            value = PyBytes_FromStringAndSize(src, v_len);
            src += v_len; remain -= v_len;
        } else { value = Py_None; Py_INCREF(value); }
        headers = PyList_New(0);
        off_o = PyLong_FromLongLong(base_off >= 0 ? base_off + i : -1);
        msgid_o = PyLong_FromLongLong(msgid_base + i);
        size_o = PyLong_FromLongLong((k_len > 0 ? k_len : 0)
                                     + (v_len > 0 ? v_len : 0));
        if (!key || !value || !headers || !off_o || !msgid_o || !size_o) {
            Py_XDECREF(key); Py_XDECREF(value); Py_XDECREF(headers);
            Py_XDECREF(off_o); Py_XDECREF(msgid_o); Py_XDECREF(size_o);
            Py_DECREF(m);
            goto fail;
        }
        Py_INCREF(topic);  slot_set(m, S_TOPIC, topic);
        Py_INCREF(part_obj); slot_set(m, S_PARTITION, part_obj);
        slot_set(m, S_KEY, key);
        slot_set(m, S_VALUE, value);
        slot_set(m, S_HEADERS, headers);
        slot_set(m, S_OFFSET, off_o);
        Py_INCREF(ts_obj); slot_set(m, S_TIMESTAMP, ts_obj);
        Py_INCREF(zero); slot_set(m, S_TSTYPE, zero);
        Py_INCREF(error); slot_set(m, S_ERROR, error);
        Py_INCREF(Py_None); slot_set(m, S_OPAQUE, Py_None);
        slot_set(m, S_MSGID, msgid_o);
        Py_INCREF(retries); slot_set(m, S_RETRIES, retries);
        Py_INCREF(status); slot_set(m, S_STATUS, status);
        Py_INCREF(enq_time); slot_set(m, S_ENQ, enq_time);
        Py_INCREF(fzero); slot_set(m, S_BACKOFF, fzero);
        Py_INCREF(zero); slot_set(m, S_LATENCY, zero);
        Py_INCREF(Py_None); slot_set(m, S_ONDEL, Py_None);
        slot_set(m, S_SIZE, size_o);
        PyObject_GC_UnTrack(m);       // acyclic leaves only (see lazy)
        if (PyList_Append(list, m) < 0) { Py_DECREF(m); goto fail; }
        Py_DECREF(m);
    }
    goto done;
fail:
    Py_CLEAR(list);
done:
    Py_XDECREF(part_obj); Py_XDECREF(ts_obj);
    Py_XDECREF(fzero); Py_XDECREF(zero);
    PyBuffer_Release(&base);
    PyBuffer_Release(&kb);
    PyBuffer_Release(&vb);
    return list;
}

// ------------- r5: lazy fetch materialization + delivery cursor -------
// FetchMessage (client/msg.py) stores the shared records buffer plus
// packed (offset<<32 | len) ints; .value/.key slice lazily in Python.
// Cuts the per-record cost from ~874 ns (PyBytes value copy) to the
// tp_alloc + a handful of stores (VERDICT r4 #1; reference analog:
// rko_msg points into the fetch buffer, rdkafka_msgset_reader.c:715).

static const char *const FM_SLOTS[] = {
    "topic", "partition", "offset", "timestamp", "timestamp_type",
    "error", "status", "_buf", "_v", "_k", "_h", NULL};
enum { F_TOPIC, F_PART, F_OFFSET, F_TS, F_TSTYPE, F_ERROR, F_STATUS,
       F_BUF, F_V, F_K, F_H, F_NSLOTS };
static PyTypeObject *fm_type_cached = NULL;
static Py_ssize_t fm_slot_off[F_NSLOTS];

static int resolve_fm_slots(PyTypeObject *type) {
    for (int i = 0; FM_SLOTS[i]; i++) {
        PyObject *d = PyDict_GetItemString(type->tp_dict, FM_SLOTS[i]);
        if (!d || !PyObject_TypeCheck(d, &PyMemberDescr_Type)) {
            PyErr_Format(PyExc_TypeError,
                         "materialize_v2_lazy: %s.%s is not a slot member",
                         type->tp_name, FM_SLOTS[i]);
            return -1;
        }
        fm_slot_off[i] = ((PyMemberDescrObject *)d)->d_member->offset;
    }
    fm_type_cached = type;
    return 0;
}

static inline void fslot_set(PyObject *m, int slot, PyObject *v) {
    *(PyObject **)((char *)m + fm_slot_off[slot]) = v;
}

// materialize_v2_lazy(fm_type, records, fields_addr, n, topic,
//                     partition, base_off, fo, base_ts, append_ts,
//                     log_append, tstype)
//   -> (list[FetchMessage], total_payload_bytes, header_fixups | None)
static PyObject *mod_materialize_v2_lazy(PyObject *Py_UNUSED(self),
                                         PyObject *const *args,
                                         Py_ssize_t nargs) {
    if (nargs != 13) {
        PyErr_SetString(PyExc_TypeError, "materialize_v2_lazy: 13 args");
        return NULL;
    }
    PyTypeObject *type = (PyTypeObject *)args[0];
    if (!PyType_Check(args[0])) {
        PyErr_SetString(PyExc_TypeError,
                        "arg 0 must be the FetchMessage type");
        return NULL;
    }
    if (type != fm_type_cached && resolve_fm_slots(type) < 0)
        return NULL;
    PyObject *records = args[1];
    Py_buffer rb;
    if (PyObject_GetBuffer(records, &rb, PyBUF_SIMPLE) < 0) return NULL;
    const int64_t *fields = (const int64_t *)PyLong_AsVoidPtr(args[2]);
    int64_t n = PyLong_AsLongLong(args[3]);
    PyObject *topic = args[4];
    int64_t partition = PyLong_AsLongLong(args[5]);
    int64_t base_off = PyLong_AsLongLong(args[6]);
    int64_t fo = PyLong_AsLongLong(args[7]);
    int64_t base_ts = PyLong_AsLongLong(args[8]);
    PyObject *append_ts_obj = args[9];      // PyLong (shared, log_append)
    int log_append = (int)PyLong_AsLong(args[10]);
    PyObject *tstype = args[11];
    PyObject *status = args[12];
    if (PyErr_Occurred()) { PyBuffer_Release(&rb); return NULL; }
    int64_t rblen = rb.len;
    PyBuffer_Release(&rb);   // `records` object itself is what we keep

    PyObject *list = PyList_New(0);
    PyObject *fixups = NULL;
    PyObject *part_obj = PyLong_FromLongLong(partition);
    int64_t total = 0;
    int64_t ts_memo_v = INT64_MIN;
    PyObject *ts_memo = NULL;
    if (!list || !part_obj) goto fail;
    for (int64_t i = 0; i < n; i++) {
        const int64_t *f = fields + i * 8;
        int64_t off = base_off + f[1];
        if (off < fo) continue;
        int64_t ko = f[2], kl = f[3], vo = f[4], vl = f[5];
        if (kl > 0 && (ko < 0 || ko + kl > rblen)) goto bounds;
        if (vl > 0 && (vo < 0 || vo + vl > rblen)) goto bounds;
        {
            PyObject *m = type->tp_alloc(type, 0);
            if (!m) goto fail;
            PyObject *off_o = PyLong_FromLongLong(off);
            PyObject *ts_o;
            if (log_append) {
                ts_o = append_ts_obj; Py_INCREF(ts_o);
            } else {
                int64_t tsv = base_ts + f[0];
                if (tsv != ts_memo_v || !ts_memo) {
                    Py_XDECREF(ts_memo);
                    ts_memo = PyLong_FromLongLong(tsv);
                    ts_memo_v = tsv;
                }
                ts_o = ts_memo; Py_XINCREF(ts_o);
            }
            PyObject *v_o, *k_o;
            if (vl >= 0) v_o = PyLong_FromLongLong((vo << 32) | vl);
            else { v_o = Py_None; Py_INCREF(v_o); }
            if (kl >= 0) k_o = PyLong_FromLongLong((ko << 32) | kl);
            else { k_o = Py_None; Py_INCREF(k_o); }
            if (!off_o || !ts_o || !v_o || !k_o) {
                Py_XDECREF(off_o); Py_XDECREF(ts_o);
                Py_XDECREF(v_o); Py_XDECREF(k_o); Py_DECREF(m);
                goto fail;
            }
            Py_INCREF(topic);    fslot_set(m, F_TOPIC, topic);
            Py_INCREF(part_obj); fslot_set(m, F_PART, part_obj);
            fslot_set(m, F_OFFSET, off_o);
            fslot_set(m, F_TS, ts_o);
            Py_INCREF(tstype);   fslot_set(m, F_TSTYPE, tstype);
            Py_INCREF(Py_None);  fslot_set(m, F_ERROR, Py_None);
            Py_INCREF(status);   fslot_set(m, F_STATUS, status);
            Py_INCREF(records);  fslot_set(m, F_BUF, records);
            fslot_set(m, F_V, v_o);
            fslot_set(m, F_K, k_o);
            Py_INCREF(Py_None);  fslot_set(m, F_H, Py_None);
            // every slot holds an acyclic leaf (str/int/bytes/None);
            // untrack so a deep fetched-message backlog costs the
            // cyclic GC nothing — gen2 passes over a 300k-message
            // queue measured 2.5x off the whole consume rate (the
            // tuple-of-atomics untrack rationale, CPython gcmodule)
            PyObject_GC_UnTrack(m);
            total += (vl > 0 ? vl : 0) + (kl > 0 ? kl : 0);
            if (PyList_Append(list, m) < 0) { Py_DECREF(m); goto fail; }
            Py_DECREF(m);
            if (f[7] > 0) {            // record carries headers: fix up
                if (!fixups) {
                    fixups = PyList_New(0);
                    if (!fixups) goto fail;
                }
                PyObject *t = Py_BuildValue(
                    "(nLL)", PyList_GET_SIZE(list) - 1,
                    (long long)f[6], (long long)f[7]);
                if (!t || PyList_Append(fixups, t) < 0) {
                    Py_XDECREF(t); goto fail;
                }
                Py_DECREF(t);
            }
        }
    }
    {
        PyObject *r = Py_BuildValue("(OLO)", list, (long long)total,
                                    fixups ? fixups : Py_None);
        Py_DECREF(list);
        Py_XDECREF(fixups);
        Py_XDECREF(ts_memo);
        Py_DECREF(part_obj);
        return r;
    }
bounds:
    PyErr_SetString(PyExc_ValueError,
                    "materialize_v2_lazy: record field out of bounds");
fail:
    Py_XDECREF(list);
    Py_XDECREF(fixups);
    Py_XDECREF(ts_memo);
    Py_XDECREF(part_obj);
    return NULL;
}

// materialize_arena_lazy(fm_type, base, klens, vlens, count, topic,
//                        partition, base_offset, ts_ms, tstype,
//                        status, error) -> list[FetchMessage]
// The DR-path analog of materialize_v2_lazy: delivery-report messages
// hold the arena batch's base buffer + packed offsets; key/value bytes
// are created only if the app's DR callback reads them (most read
// only error/offset/topic). Reference analog: DR event batching,
// rd_kafka_event_message_array (rdkafka_event.c:33).
static PyObject *mod_materialize_arena_lazy(PyObject *Py_UNUSED(self),
                                            PyObject *const *args,
                                            Py_ssize_t nargs) {
    if (nargs != 12) {
        PyErr_SetString(PyExc_TypeError, "materialize_arena_lazy: 12 args");
        return NULL;
    }
    PyTypeObject *type = (PyTypeObject *)args[0];
    if (!PyType_Check(args[0])) {
        PyErr_SetString(PyExc_TypeError,
                        "arg 0 must be the FetchMessage type");
        return NULL;
    }
    if (type != fm_type_cached && resolve_fm_slots(type) < 0)
        return NULL;
    PyObject *base_obj = args[1];
    Py_buffer base, kb, vb;
    if (PyObject_GetBuffer(base_obj, &base, PyBUF_SIMPLE) < 0) return NULL;
    if (PyObject_GetBuffer(args[2], &kb, PyBUF_SIMPLE) < 0) {
        PyBuffer_Release(&base); return NULL;
    }
    if (PyObject_GetBuffer(args[3], &vb, PyBUF_SIMPLE) < 0) {
        PyBuffer_Release(&base); PyBuffer_Release(&kb); return NULL;
    }
    int64_t count = PyLong_AsLongLong(args[4]);
    PyObject *topic = args[5];
    int64_t partition = PyLong_AsLongLong(args[6]);
    int64_t base_off = PyLong_AsLongLong(args[7]);
    PyObject *ts_obj = args[8];       // PyLong ms (shared)
    PyObject *tstype = args[9];
    PyObject *status = args[10];
    PyObject *error = args[11];       // KafkaError | None (shared)
    const int32_t *kl = (const int32_t *)kb.buf;
    const int32_t *vl = (const int32_t *)vb.buf;
    int64_t blen = base.len;
    PyObject *list = NULL, *part_obj = NULL;
    if (PyErr_Occurred()) goto done;
    if (count < 0 || (int64_t)kb.len < count * 4
        || (int64_t)vb.len < count * 4) {
        PyErr_SetString(PyExc_ValueError, "materialize_arena_lazy: bad args");
        goto done;
    }
    list = PyList_New(0);
    part_obj = PyLong_FromLongLong(partition);
    if (!list || !part_obj) goto fail;
    {
        int64_t off = 0;
        for (int64_t i = 0; i < count; i++) {
            int64_t k_len = kl[i], v_len = vl[i];
            int64_t need = (k_len > 0 ? k_len : 0) + (v_len > 0 ? v_len : 0);
            if (off + need > blen) {
                PyErr_SetString(PyExc_ValueError,
                                "materialize_arena_lazy: short base");
                goto fail;
            }
            PyObject *m = type->tp_alloc(type, 0);
            if (!m) goto fail;
            PyObject *k_o, *v_o;
            if (k_len >= 0) {
                k_o = PyLong_FromLongLong((off << 32) | k_len);
                off += k_len;
            } else { k_o = Py_None; Py_INCREF(k_o); }
            if (v_len >= 0) {
                v_o = PyLong_FromLongLong((off << 32) | v_len);
                off += v_len;
            } else { v_o = Py_None; Py_INCREF(v_o); }
            PyObject *off_o = PyLong_FromLongLong(
                base_off >= 0 ? base_off + i : -1);
            if (!k_o || !v_o || !off_o) {
                Py_XDECREF(k_o); Py_XDECREF(v_o); Py_XDECREF(off_o);
                Py_DECREF(m); goto fail;
            }
            Py_INCREF(topic);    fslot_set(m, F_TOPIC, topic);
            Py_INCREF(part_obj); fslot_set(m, F_PART, part_obj);
            fslot_set(m, F_OFFSET, off_o);
            Py_INCREF(ts_obj);   fslot_set(m, F_TS, ts_obj);
            Py_INCREF(tstype);   fslot_set(m, F_TSTYPE, tstype);
            Py_INCREF(error);    fslot_set(m, F_ERROR, error);
            Py_INCREF(status);   fslot_set(m, F_STATUS, status);
            Py_INCREF(base_obj); fslot_set(m, F_BUF, base_obj);
            fslot_set(m, F_V, v_o);
            fslot_set(m, F_K, k_o);
            Py_INCREF(Py_None);  fslot_set(m, F_H, Py_None);
            PyObject_GC_UnTrack(m);   // acyclic leaves only
            if (PyList_Append(list, m) < 0) { Py_DECREF(m); goto fail; }
            Py_DECREF(m);
        }
    }
    goto done;
fail:
    Py_CLEAR(list);
done:
    Py_XDECREF(part_obj);
    PyBuffer_Release(&base);
    PyBuffer_Release(&kb);
    PyBuffer_Release(&vb);
    return list;
}

// Delivery cursor: the consumer app thread's per-message walk
// (consumer._next_pending's inner loop) as one C call per message —
// staleness barrier, assignment check, offset advance
// (reference: rd_kafka_q_serve_rkmessages, rdkafka_queue.c:519).

static const char *const TP_SLOTS[] = {
    "version", "app_offset", "stored_offset", NULL};
enum { T_VERSION, T_APPOFF, T_STOREDOFF, T_NSLOTS };
static PyTypeObject *tp_type_cached = NULL;
static Py_ssize_t tp_slot_off[T_NSLOTS];

static int resolve_tp_slots(PyTypeObject *type) {
    for (int i = 0; TP_SLOTS[i]; i++) {
        PyObject *d = PyDict_GetItemString(type->tp_dict, TP_SLOTS[i]);
        if (!d || !PyObject_TypeCheck(d, &PyMemberDescr_Type)) {
            PyErr_Format(PyExc_TypeError,
                         "cursor: %s.%s is not a slot member",
                         type->tp_name, TP_SLOTS[i]);
            return -1;
        }
        tp_slot_off[i] = ((PyMemberDescrObject *)d)->d_member->offset;
    }
    tp_type_cached = type;
    return 0;
}

typedef struct {
    PyObject_HEAD
    PyObject *tp;        // Toppar (slotted)
    PyObject *msgs;      // list of messages
    PyObject *key;       // (topic, partition)
    long long ver;
    Py_ssize_t i, n;
} TkCursor;

static void cursor_dealloc(TkCursor *c) {
    Py_XDECREF(c->tp);
    Py_XDECREF(c->msgs);
    Py_XDECREF(c->key);
    Py_TYPE(c)->tp_free((PyObject *)c);
}

// cursor.next(assignment, auto_store) -> message | None (exhausted)
static PyObject *cursor_next_m(TkCursor *c, PyObject *const *args,
                               Py_ssize_t nargs) {
    if (nargs != 2) {
        PyErr_SetString(PyExc_TypeError, "next(assignment, auto_store)");
        return NULL;
    }
    PyObject *assignment = args[0];
    int auto_store = PyObject_IsTrue(args[1]);
    if (auto_store < 0) return NULL;
    char *tpb = (char *)c->tp;
    while (c->i < c->n) {
        PyObject *m = PyList_GET_ITEM(c->msgs, c->i);
        c->i++;
        // staleness barrier: seek()/pause()/rebalance bump tp.version
        PyObject *vo = *(PyObject **)(tpb + tp_slot_off[T_VERSION]);
        long long ver = vo ? PyLong_AsLongLong(vo) : -1;
        if (ver != c->ver) continue;
        int in_asgn = PySequence_Contains(assignment, c->key);
        if (in_asgn < 0) return NULL;
        if (!in_asgn) continue;           // revoked: drop
        PyObject *off_obj;
        if (Py_TYPE(m) == fm_type_cached) {
            off_obj = *(PyObject **)((char *)m + fm_slot_off[F_OFFSET]);
            Py_XINCREF(off_obj);
        } else {
            off_obj = PyObject_GetAttrString(m, "offset");
        }
        if (!off_obj) return NULL;
        long long off1 = PyLong_AsLongLong(off_obj) + 1;
        Py_DECREF(off_obj);
        if (off1 == 0 && PyErr_Occurred()) return NULL;
        PyObject *off1_o = PyLong_FromLongLong(off1);
        if (!off1_o) return NULL;
        PyObject **slot = (PyObject **)(tpb + tp_slot_off[T_APPOFF]);
        Py_XDECREF(*slot);
        *slot = off1_o;                    // steals the new ref
        if (auto_store) {
            slot = (PyObject **)(tpb + tp_slot_off[T_STOREDOFF]);
            Py_INCREF(off1_o);
            Py_XDECREF(*slot);
            *slot = off1_o;
        }
        Py_INCREF(m);
        return m;
    }
    Py_RETURN_NONE;
}

static PyMethodDef cursor_methods[] = {
    {"next", (PyCFunction)(void (*)(void))cursor_next_m, METH_FASTCALL,
     "next(assignment, auto_store) -> message | None"},
    {NULL, NULL, 0, NULL}};

static PyTypeObject CursorType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    "tk_enqlane.Cursor",           /* tp_name */
    sizeof(TkCursor),              /* tp_basicsize */
};

// cursor_new(tp, msgs, ver, key) -> Cursor
static PyObject *mod_cursor_new(PyObject *Py_UNUSED(self),
                                PyObject *const *args, Py_ssize_t nargs) {
    if (nargs != 4 || !PyList_Check(args[1])) {
        PyErr_SetString(PyExc_TypeError, "cursor_new(tp, msgs, ver, key)");
        return NULL;
    }
    PyTypeObject *tpt = Py_TYPE(args[0]);
    if (tpt != tp_type_cached && resolve_tp_slots(tpt) < 0)
        return NULL;
    long long ver = PyLong_AsLongLong(args[2]);
    if (ver == -1 && PyErr_Occurred()) return NULL;
    TkCursor *c = PyObject_New(TkCursor, &CursorType);
    if (!c) return NULL;
    Py_INCREF(args[0]); c->tp = args[0];
    Py_INCREF(args[1]); c->msgs = args[1];
    Py_INCREF(args[3]); c->key = args[3];
    c->ver = ver;
    c->i = 0;
    c->n = PyList_GET_SIZE(args[1]);
    return (PyObject *)c;
}

static PyMethodDef module_methods[] = {
    {"build_batch", (PyCFunction)(void (*)(void))mod_build_batch,
     METH_FASTCALL,
     "build_batch(base, klens, vlens, count, now_ms, pid, epoch, "
     "base_seq, codec_id[, attr_flags[, tss, hbuf, hlens]]) -> wire "
     "RecordBatch bytes"},
    {"materialize_arena",
     (PyCFunction)(void (*)(void))mod_materialize_arena, METH_FASTCALL,
     "materialize_arena(...) -> list[Message] (arena layout)"},
    {"materialize_v2", (PyCFunction)(void (*)(void))mod_materialize_v2,
     METH_FASTCALL,
     "materialize_v2(...) -> (messages, total_bytes, header_fixups)"},
    {"materialize_v2_lazy",
     (PyCFunction)(void (*)(void))mod_materialize_v2_lazy, METH_FASTCALL,
     "materialize_v2_lazy(...) -> (messages, total_bytes, fixups); "
     "messages hold lazy (buffer, packed-offset) payload refs"},
    {"cursor_new", (PyCFunction)(void (*)(void))mod_cursor_new,
     METH_FASTCALL,
     "cursor_new(tp, msgs, ver, key) -> delivery Cursor"},
    {"materialize_arena_lazy",
     (PyCFunction)(void (*)(void))mod_materialize_arena_lazy,
     METH_FASTCALL,
     "materialize_arena_lazy(...) -> list[FetchMessage] (DR path; "
     "key/value created lazily from the arena base buffer)"},
    {"crc32c_many", (PyCFunction)(void (*)(void))mod_crc32c_many,
     METH_FASTCALL, "crc32c_many(buffers) -> list[int] (no join copy)"},
    {"murmur2_partition",
     (PyCFunction)(void (*)(void))mod_murmur2_partition, METH_FASTCALL,
     "murmur2_partition(key, cnt) -> int (Java-compatible parity hook)"},
    {"decompress_many", (PyCFunction)(void (*)(void))mod_decompress_many,
     METH_FASTCALL,
     "decompress_many(codec_id, buffers, hints) -> list[bytes|None]"},
    {NULL, NULL, 0, NULL}};

static PyMemberDef lane_members[] = {
    {"map", T_OBJECT_EX, offsetof(Lane, map), READONLY,
     "{(topic, partition) -> (Arena, toppar)}"},
    {"enabled", T_INT, offsetof(Lane, enabled), 0,
     "conf-level fast-lane eligibility"},
    {"fatal", T_INT, offsetof(Lane, fatal), 0,
     "fatal error pending: produce raises"},
    {NULL}};

static PyObject *lane_get_msg_cnt(Lane *l, void *c) {
    return PyLong_FromLongLong(l->msg_cnt);
}
static PyObject *lane_get_msg_bytes(Lane *l, void *c) {
    return PyLong_FromLongLong(l->msg_bytes);
}
static PyGetSetDef lane_getset[] = {
    {"msg_cnt", (getter)lane_get_msg_cnt, NULL, "queued+inflight msgs"},
    {"msg_bytes", (getter)lane_get_msg_bytes, NULL, "queued bytes"},
    {NULL}};

static PyMethodDef lane_methods[] = {
    {"produce", (PyCFunction)(void (*)(void))lane_produce,
     METH_FASTCALL | METH_KEYWORDS, "the public produce() entry point"},
    {"configure", (PyCFunction)(void (*)(void))lane_configure,
     METH_FASTCALL, "configure(fallback, wake, max_msgs, max_bytes)"},
    {"acct", (PyCFunction)(void (*)(void))lane_acct, METH_FASTCALL,
     "acct(dn, dbytes) -> (msg_cnt, msg_bytes)"},
    {"full", (PyCFunction)(void (*)(void))lane_full, METH_FASTCALL,
     "full(sz=0) -> bool"},
    {"map_set", (PyCFunction)(void (*)(void))lane_map_set, METH_FASTCALL,
     "map_set(topic, partition, entry): install a fast-lane entry"},
    {"map_del", (PyCFunction)(void (*)(void))lane_map_del, METH_FASTCALL,
     "map_del(topic, partition) -> removed entry | None"},
    {"produce_batch", (PyCFunction)(void (*)(void))lane_produce_batch,
     METH_FASTCALL,
     "produce_batch(topic, msgs, start, default_part) -> (next, appended)"},
    {"produce_raw", (PyCFunction)(void (*)(void))lane_produce_raw,
     METH_FASTCALL,
     "produce_raw(topic, part, base_addr, klens_addr, vlens_addr, n)"},
    {"part_set", (PyCFunction)(void (*)(void))lane_part_set,
     METH_FASTCALL,
     "part_set(topic, partition_cnt, mode): native auto-partition"},
    {"part_del", (PyCFunction)lane_part_del, METH_O,
     "part_del(topic): drop the auto-partition entry"},
    {"counters", (PyCFunction)lane_counters, METH_NOARGS,
     "counters() -> {'engaged': n, 'fallback': {reason: n}}"},
    {NULL, NULL, 0, NULL}};

static PyTypeObject LaneType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    "tk_enqlane.Lane",             /* tp_name */
    sizeof(Lane),                  /* tp_basicsize */
};

static PyMethodDef arena_methods[] = {
    {"append", (PyCFunction)(void (*)(void))arena_append, METH_FASTCALL,
     "append(key, value[, ts_ms, hblob]) -> remaining record count"},
    {"take", (PyCFunction)(void (*)(void))arena_take, METH_FASTCALL,
     "take(max_count, max_bytes) -> run tuple or None"},
    {"expire", (PyCFunction)arena_expire, METH_O,
     "expire(cutoff_us) -> (count, nbytes) dropped"},
    {"expire_records", (PyCFunction)arena_expire_records, METH_O,
     "expire_records(cutoff_us) -> [(key, value, ts, hblob), ...]"},
    {"clear", (PyCFunction)arena_clear, METH_NOARGS,
     "clear() -> (count, nbytes) dropped"},
    {"drain_records", (PyCFunction)arena_drain_records, METH_NOARGS,
     "drain_records() -> [(key, value, ts, hblob), ...] and reset"},
    {"first_enq_us", (PyCFunction)arena_first_enq_us, METH_NOARGS,
     "first_enq_us() -> int64 (-1 when empty)"},
    {"nbytes", (PyCFunction)arena_nbytes, METH_NOARGS,
     "nbytes() -> payload bytes queued"},
    {NULL, NULL, 0, NULL}};

static PySequenceMethods arena_as_sequence = {
    arena_length,   /* sq_length */
};

static PyTypeObject ArenaType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    "tk_enqlane.Arena",            /* tp_name */
    sizeof(Arena),                 /* tp_basicsize */
};

static struct PyModuleDef enqlane_module = {
    PyModuleDef_HEAD_INIT, "tk_enqlane",
    "Native per-toppar produce() enqueue arena", -1, module_methods};

PyMODINIT_FUNC PyInit_tk_enqlane(void) {
#ifdef __GLIBC__
    // ~1MB decompressed-batch buffers sit above glibc's default mmap
    // threshold: every fetch batch costs mmap + page-fault + kernel
    // zeroing + munmap TLB churn (measured 186 MB/s effective decode
    // cold vs 2 GB/s once glibc recycles; behind a lazy-paging VM a
    // first touch measured ~21 us/page). Raise the thresholds so
    // batch-sized allocations live on the recycling heap; glibc's own
    // dynamic tuning does the same — but only after the first drain
    // has already paid the 10x. Process-wide policy, so the embedding
    // app can veto it: TKAFKA_MALLOC_TUNE=0.
    const char *tune = getenv("TKAFKA_MALLOC_TUNE");
    if (!tune || strcmp(tune, "0") != 0) {
        mallopt(M_MMAP_THRESHOLD, 64 << 20);
        mallopt(M_TRIM_THRESHOLD, 512 << 20);
    }
#endif
    CursorType.tp_dealloc = (destructor)cursor_dealloc;
    CursorType.tp_flags = Py_TPFLAGS_DEFAULT;
    CursorType.tp_methods = cursor_methods;
    if (PyType_Ready(&CursorType) < 0) return NULL;
    ArenaType.tp_dealloc = (destructor)arena_dealloc;
    ArenaType.tp_flags = Py_TPFLAGS_DEFAULT;
    ArenaType.tp_methods = arena_methods;
    ArenaType.tp_new = arena_new;
    ArenaType.tp_as_sequence = &arena_as_sequence;
    if (PyType_Ready(&ArenaType) < 0) return NULL;
    for (int j = 0; lane_kwnames[j]; j++) {
        lane_kw_interned[j] = PyUnicode_InternFromString(lane_kwnames[j]);
        if (!lane_kw_interned[j]) return NULL;
    }
    k_error_interned = PyUnicode_InternFromString("error");
    if (!k_error_interned) return NULL;
    LaneType.tp_dealloc = (destructor)lane_dealloc;
    LaneType.tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC;
    LaneType.tp_traverse = (traverseproc)lane_traverse;
    LaneType.tp_clear = (inquiry)lane_clear;
    LaneType.tp_methods = lane_methods;
    LaneType.tp_members = lane_members;
    LaneType.tp_getset = lane_getset;
    LaneType.tp_new = lane_new;
    if (PyType_Ready(&LaneType) < 0) return NULL;
    PyObject *m = PyModule_Create(&enqlane_module);
    if (!m) return NULL;
    Py_INCREF(&ArenaType);
    if (PyModule_AddObject(m, "Arena", (PyObject *)&ArenaType) < 0) {
        Py_DECREF(&ArenaType);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&LaneType);
    if (PyModule_AddObject(m, "Lane", (PyObject *)&LaneType) < 0) {
        Py_DECREF(&LaneType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
