// codec.cpp — native CPU codec provider for librdkafka_tpu.
//
// Self-contained implementations (no third-party code) of:
//   - CRC32C (Castagnoli, slice-by-8)            [ref: src/crc32c.c]
//   - xxHash32 (needed for the LZ4 frame header checksum)
//   - LZ4 block + frame compress / decompress     [ref: vendored lz4*.c + src/rdkafka_lz4.c]
//   - Snappy raw compress / decompress            [ref: vendored src/snappy.c]
//
// The LZ4 *encoder* follows the deterministic "TPU-greedy" spec shared with
// the JAX/Pallas provider (ops/lz4_jax.py): 12-bit multiplicative hash,
// candidate = most recent previous position with the same hash (every
// position's hash is inserted, including match interiors), greedy parse,
// match length capped at MAXMATCH, last-5-literals / 12-byte-tail rules per
// the public LZ4 block spec. Both providers therefore emit bit-identical,
// spec-compliant LZ4 streams — the bit-exactness contract of BASELINE.json.
//
// Build: g++ -O3 -shared -fPIC (see build.py). Exposed via ctypes.

#include <cstdint>
#include <cstring>
#include <cstdlib>
#if defined(__x86_64__) || defined(__i386__)
#include <cpuid.h>
#endif

#define EXPORT extern "C" __attribute__((visibility("default")))

// ---------------------------------------------------------------- crc32c --
// Runtime hw/sw dispatch like the reference (crc32c.c:39 SSE4.2 path,
// :138 runtime detect): the x86 crc32 instruction computes this exact
// (Castagnoli, reflected) polynomial at ~1 cycle per 8 bytes vs ~3-4
// cycles for the slice-by-8 table fold.

static uint32_t crc32c_tab[8][256];

static void crc32c_init_once() {
    const uint32_t poly = 0x82F63B78u;
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++) c = (c >> 1) ^ (poly & (0u - (c & 1)));
        crc32c_tab[0][i] = c;
    }
    for (int k = 1; k < 8; k++)
        for (uint32_t i = 0; i < 256; i++)
            crc32c_tab[k][i] = crc32c_tab[0][crc32c_tab[k-1][i] & 0xFF] ^ (crc32c_tab[k-1][i] >> 8);
}

static void crc32c_init() {
    // function-local static: race-free one-time init (the done-flag
    // form raced between broker threads — TSAN tier, test_0124)
    static const bool done = (crc32c_init_once(), true);
    (void)done;
}

static uint32_t crc32c_sw(const uint8_t *p, int64_t n, uint32_t crc) {
    crc32c_init();
    crc = ~crc;
    while (n >= 8) {
        uint32_t lo, hi;
        memcpy(&lo, p, 4); memcpy(&hi, p + 4, 4);
        crc ^= lo;
        crc = crc32c_tab[7][crc & 0xFF] ^ crc32c_tab[6][(crc >> 8) & 0xFF]
            ^ crc32c_tab[5][(crc >> 16) & 0xFF] ^ crc32c_tab[4][crc >> 24]
            ^ crc32c_tab[3][hi & 0xFF] ^ crc32c_tab[2][(hi >> 8) & 0xFF]
            ^ crc32c_tab[1][(hi >> 16) & 0xFF] ^ crc32c_tab[0][hi >> 24];
        p += 8; n -= 8;
    }
    while (n-- > 0) crc = crc32c_tab[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
    return ~crc;
}

// GF(2) zero-advance: zshift[k] = columns of M^(2^k), where M advances
// a raw CRC register through one zero byte (the combine math of
// crc32c_combine, utils/crc.py, in C). Used to stitch the 3-stream
// hardware fold back together.
static uint32_t crc32c_zshift[64][32];

static void crc32c_zshift_init_once() {
    crc32c_init();
    for (int j = 0; j < 32; j++) {       // M^1: one zero byte
        uint32_t reg = 1u << j;
        crc32c_zshift[0][j] = crc32c_tab[0][reg & 0xFF] ^ (reg >> 8);
    }
    for (int k = 1; k < 64; k++)         // M^(2^k) = (M^(2^(k-1)))^2
        for (int j = 0; j < 32; j++) {
            uint32_t v = crc32c_zshift[k - 1][j], acc = 0;
            for (int b = 0; v; b++, v >>= 1)
                if (v & 1) acc ^= crc32c_zshift[k - 1][b];
            crc32c_zshift[k][j] = acc;
        }
}

static void crc32c_zshift_init() {
    static const bool done = (crc32c_zshift_init_once(), true);
    (void)done;
}

// advance raw register `reg` through `n` zero bytes
static uint32_t crc32c_shift(uint32_t reg, int64_t n) {
    crc32c_zshift_init();
    for (int k = 0; n; k++, n >>= 1) {
        if (n & 1) {
            uint32_t acc = 0, v = reg;
            for (int b = 0; v; b++, v >>= 1)
                if (v & 1) acc ^= crc32c_zshift[k][b];
            reg = acc;
        }
    }
    return reg;
}

#if defined(__x86_64__)
__attribute__((target("sse4.2")))
static uint64_t crc32c_hw_fold(const uint8_t *p, int64_t n, uint64_t c) {
    while (n >= 8) {
        uint64_t v;
        memcpy(&v, p, 8);
        c = __builtin_ia32_crc32di(c, v);
        p += 8; n -= 8;
    }
    uint32_t cc = (uint32_t)c;
    while (n-- > 0) cc = __builtin_ia32_crc32qi(cc, *p++);
    return cc;
}

__attribute__((target("sse4.2")))
static uint32_t crc32c_hw(const uint8_t *p, int64_t n, uint32_t crc) {
    uint64_t c0 = ~crc;
    // the crc32 instruction is 1/cycle throughput but 3-cycle latency:
    // a single dependent chain runs at 1/3 peak. Three independent
    // contiguous thirds fold in parallel and are stitched with the
    // GF(2) zero-advance (same math as crc32c_combine).
    if (n >= 3 * 64) {
        int64_t L = (n / 3) & ~7LL;          // 8-byte aligned lane length
        const uint8_t *a = p, *b = p + L, *cst = p + 2 * L;
        uint64_t ca = c0, cb = 0, cc = 0;
        for (int64_t i = 0; i < L; i += 8) {
            uint64_t va, vb, vc;
            memcpy(&va, a + i, 8);
            memcpy(&vb, b + i, 8);
            memcpy(&vc, cst + i, 8);
            ca = __builtin_ia32_crc32di(ca, va);
            cb = __builtin_ia32_crc32di(cb, vb);
            cc = __builtin_ia32_crc32di(cc, vc);
        }
        int64_t tail = n - 3 * L;            // fold [3L, n) into lane C
        cc = crc32c_hw_fold(p + 3 * L, tail, cc);
        uint32_t reg = crc32c_shift((uint32_t)ca, L + L + tail)
                     ^ crc32c_shift((uint32_t)cb, L + tail)
                     ^ (uint32_t)cc;
        return ~reg;
    }
    return ~(uint32_t)crc32c_hw_fold(p, n, c0);
}

static bool cpu_has_sse42() {
    unsigned a = 0, b = 0, c = 0, d = 0;
    if (!__get_cpuid(1, &a, &b, &c, &d)) return false;
    return (c & (1u << 20)) != 0;
}
#endif

typedef uint32_t (*crc32c_fn)(const uint8_t *, int64_t, uint32_t);

static crc32c_fn crc32c_pick() {
#if defined(__x86_64__)
    if (cpu_has_sse42()) return crc32c_hw;
#endif
    return crc32c_sw;
}

EXPORT uint32_t tk_crc32c(const uint8_t *p, int64_t n, uint32_t crc) {
    // function-local static: C++11 guarantees race-free one-time init
    // (the lazy nullable-pointer form was a data race between broker
    // threads — caught by the TSAN tier, tests/test_0124_tsan.py)
    static const crc32c_fn impl = crc32c_pick();
    return impl(p, n, crc);
}

// sw path kept callable for tests (hw/sw bit-exactness cross-check)
EXPORT uint32_t tk_crc32c_sw(const uint8_t *p, int64_t n, uint32_t crc) {
    return crc32c_sw(p, n, crc);
}

// Batched CRC over many slices of one base buffer (one call per launch).
EXPORT void tk_crc32c_many(const uint8_t *base, const int64_t *offs,
                           const int64_t *lens, uint32_t *out, int count) {
    for (int i = 0; i < count; i++)
        out[i] = tk_crc32c(base + offs[i], lens[i], 0);
}

// ----------------------------------------------------------------- xxh32 --

static const uint32_t XP1 = 2654435761u, XP2 = 2246822519u, XP3 = 3266489917u,
                      XP4 = 668265263u, XP5 = 374761393u;

static inline uint32_t rotl32(uint32_t x, int r) { return (x << r) | (x >> (32 - r)); }
static inline uint32_t rd32le(const uint8_t *p) { uint32_t v; memcpy(&v, p, 4); return v; }
static inline uint16_t rd16le(const uint8_t *p) { uint16_t v; memcpy(&v, p, 2); return v; }

EXPORT uint32_t tk_xxh32(const uint8_t *p, int64_t n, uint32_t seed) {
    const uint8_t *end = p + n;
    uint32_t h;
    if (n >= 16) {
        uint32_t v1 = seed + XP1 + XP2, v2 = seed + XP2, v3 = seed, v4 = seed - XP1;
        const uint8_t *lim = end - 16;
        do {
            v1 = rotl32(v1 + rd32le(p) * XP2, 13) * XP1; p += 4;
            v2 = rotl32(v2 + rd32le(p) * XP2, 13) * XP1; p += 4;
            v3 = rotl32(v3 + rd32le(p) * XP2, 13) * XP1; p += 4;
            v4 = rotl32(v4 + rd32le(p) * XP2, 13) * XP1; p += 4;
        } while (p <= lim);
        h = rotl32(v1, 1) + rotl32(v2, 7) + rotl32(v3, 12) + rotl32(v4, 18);
    } else {
        h = seed + XP5;
    }
    h += (uint32_t)n;
    while (p + 4 <= end) { h = rotl32(h + rd32le(p) * XP3, 17) * XP4; p += 4; }
    while (p < end)      { h = rotl32(h + (*p++) * XP5, 11) * XP1; }
    h ^= h >> 15; h *= XP2; h ^= h >> 13; h *= XP3; h ^= h >> 16;
    return h;
}

// ------------------------------------------------------- LZ4 block encode --
//
// Deterministic TPU-greedy spec (shared with ops/lz4_jax.py):
//   HASH(x32le) = (x * 2654435761u) >> 20          (4096-entry table)
//   candidate   = previous position with same hash (insert ALL positions)
//   match iff   cand >= 0, p-cand <= 65535, 4-byte prefix equal
//   mlen        = longest common prefix, capped at min(MAXMATCH, n-5-p)
//   parse       = greedy left-to-right; main loop stops at p+12 > n

static const int LZ4_HASH_BITS = 12;
static const int LZ4_MAXMATCH = 273;

static inline uint32_t lz4_hash(uint32_t x) {
    return (x * 2654435761u) >> (32 - LZ4_HASH_BITS);
}

EXPORT int64_t tk_lz4_block_bound(int64_t n) { return n + n / 255 + 16; }

EXPORT int64_t tk_lz4_block_compress(const uint8_t *src, int64_t n,
                                     uint8_t *dst, int64_t cap) {
    if (n < 0 || cap < tk_lz4_block_bound(n)) return -1;
    int32_t table[1 << LZ4_HASH_BITS];
    memset(table, -1, sizeof(table));
    int64_t anchor = 0, p = 0, o = 0;
    while (p + 12 <= n) {
        uint32_t seq = rd32le(src + p);
        uint32_t h = lz4_hash(seq);
        int64_t cand = table[h];
        table[h] = (int32_t)p;
        if (cand >= 0 && p - cand <= 65535 && rd32le(src + cand) == seq) {
            int64_t mmax = n - 5 - p;
            if (mmax > LZ4_MAXMATCH) mmax = LZ4_MAXMATCH;
            int64_t mlen = 4;
            while (mlen < mmax && src[cand + mlen] == src[p + mlen]) mlen++;
            // emit sequence: literals [anchor, p), then match (offset, mlen)
            int64_t lit = p - anchor;
            uint8_t *tok = dst + o++;
            if (lit >= 15) {
                *tok = 0xF0;
                int64_t rem = lit - 15;
                while (rem >= 255) { dst[o++] = 255; rem -= 255; }
                dst[o++] = (uint8_t)rem;
            } else *tok = (uint8_t)(lit << 4);
            memcpy(dst + o, src + anchor, lit); o += lit;
            uint16_t off = (uint16_t)(p - cand);
            dst[o++] = off & 0xFF; dst[o++] = off >> 8;
            int64_t mrem = mlen - 4;
            if (mrem >= 15) {
                *tok |= 0x0F;
                mrem -= 15;
                while (mrem >= 255) { dst[o++] = 255; mrem -= 255; }
                dst[o++] = (uint8_t)mrem;
            } else *tok |= (uint8_t)mrem;
            // insert-all: match interior positions also enter the table
            for (int64_t q = p + 1; q < p + mlen && q + 4 <= n; q++)
                table[lz4_hash(rd32le(src + q))] = (int32_t)q;
            p += mlen;
            anchor = p;
        } else {
            p += 1;
        }
    }
    // final literal run
    int64_t lit = n - anchor;
    uint8_t *tok = dst + o++;
    if (lit >= 15) {
        *tok = 0xF0;
        int64_t rem = lit - 15;
        while (rem >= 255) { dst[o++] = 255; rem -= 255; }
        dst[o++] = (uint8_t)rem;
    } else *tok = (uint8_t)(lit << 4);
    memcpy(dst + o, src + anchor, lit); o += lit;
    return o;
}

// -------------------------------------------------- LZ4 block encode, fast --
//
// Throughput-first encoder for the CPU provider's default path. Same
// public LZ4 block format (any decoder accepts it), different parse:
//   - 13-bit hash over 5 bytes, insert only at sequence starts plus two
//     interior anchor points (match interiors are skipped — on
//     compressible streams this is the difference between ~100 MB/s for
//     the insert-all deterministic spec above and >500 MB/s here)
//   - miss acceleration: step grows every 64 consecutive misses
//   - 8-bytes-at-a-time match extension (XOR + count-trailing-zeros)
// The deterministic insert-all encoder above remains the
// compression.backend=tpu bit-exactness contract; this one is what the
// broker hot path uses (reference ships vendored lz4 fast mode for the
// same role, rdkafka_lz4.c + lz4.c).

static const int LZ4F_HASH_BITS = 13;

static inline uint32_t lz4_hash5(uint64_t x) {
    return (uint32_t)(((x << 24) * 889523592379ULL) >> (64 - LZ4F_HASH_BITS));
}

EXPORT int64_t tk_lz4_block_compress_fast(const uint8_t *src, int64_t n,
                                          uint8_t *dst, int64_t cap) {
    if (n < 0 || cap < tk_lz4_block_bound(n)) return -1;
    if (n < 13) {   // too short for the main loop: all-literal block
        int64_t o = 0;
        uint8_t *tok = dst + o++;
        *tok = (uint8_t)(n << 4);
        memcpy(dst + o, src, n);
        return o + n;
    }
    int32_t table[1 << LZ4F_HASH_BITS];
    memset(table, -1, sizeof(table));
    int64_t anchor = 0, p = 0, o = 0;
    const int64_t mflimit = n - 12;      // last match must start before
    int64_t misses = 1 << 6;
    while (p <= mflimit) {
        uint64_t seq8;
        memcpy(&seq8, src + p, 8);
        uint32_t h = lz4_hash5(seq8);
        int64_t cand = table[h];
        table[h] = (int32_t)p;
        if (cand < 0 || p - cand > 65535
            || (uint32_t)seq8 != rd32le(src + cand)) {
            p += (misses++ >> 6);
            continue;
        }
        misses = 1 << 6;
        // back-extend over pending literals (free compression)
        while (p > anchor && cand > 0 && src[p - 1] == src[cand - 1]) {
            p--; cand--;
        }
        // forward extension, 8 bytes at a time
        int64_t mlen = 4;
        const int64_t safe = n - 5;      // last 5 bytes must be literals
        {
            int64_t q = p + 4, c = cand + 4;
            while (q + 8 <= safe) {
                uint64_t a, b;
                memcpy(&a, src + q, 8);
                memcpy(&b, src + c, 8);
                uint64_t x = a ^ b;
                if (x) { mlen += __builtin_ctzll(x) >> 3; goto emit; }
                q += 8; c += 8; mlen += 8;
            }
            while (q < safe && src[q] == src[c]) { q++; c++; mlen++; }
        }
    emit:;
        int64_t lit = p - anchor;
        uint8_t *tok = dst + o++;
        if (lit >= 15) {
            *tok = 0xF0;
            int64_t rem = lit - 15;
            while (rem >= 255) { dst[o++] = 255; rem -= 255; }
            dst[o++] = (uint8_t)rem;
        } else *tok = (uint8_t)(lit << 4);
        memcpy(dst + o, src + anchor, lit); o += lit;
        uint16_t off = (uint16_t)(p - cand);
        dst[o++] = off & 0xFF; dst[o++] = off >> 8;
        int64_t mrem = mlen - 4;
        if (mrem >= 15) {
            *tok |= 0x0F;
            mrem -= 15;
            while (mrem >= 255) { dst[o++] = 255; mrem -= 255; }
            dst[o++] = (uint8_t)mrem;
        } else *tok |= (uint8_t)mrem;
        // two interior anchors keep long-range matches findable without
        // the insert-all tax
        if (p + 2 + 8 <= n)
            { uint64_t v; memcpy(&v, src + p + 2, 8);
              table[lz4_hash5(v)] = (int32_t)(p + 2); }
        p += mlen;
        if (p - 2 >= 0 && p - 2 + 8 <= n)
            { uint64_t v; memcpy(&v, src + p - 2, 8);
              table[lz4_hash5(v)] = (int32_t)(p - 2); }
        anchor = p;
    }
    // final literal run
    int64_t lit = n - anchor;
    uint8_t *tok = dst + o++;
    if (lit >= 15) {
        *tok = 0xF0;
        int64_t rem = lit - 15;
        while (rem >= 255) { dst[o++] = 255; rem -= 255; }
        dst[o++] = (uint8_t)rem;
    } else *tok = (uint8_t)(lit << 4);
    memcpy(dst + o, src + anchor, lit); o += lit;
    return o;
}

// ------------------------------------------------------- LZ4 block decode --

// hist = decoded bytes present before dst (for linked-block frames whose
// matches reach into previous blocks).
static int64_t lz4_block_decompress_hist(const uint8_t *src, int64_t n,
                                         uint8_t *dst, int64_t cap,
                                         int64_t hist) {
    int64_t i = 0, o = 0;
    while (i < n) {
        uint8_t tok = src[i++];
        int64_t lit = tok >> 4;
        if (lit == 15) {
            uint8_t b;
            do { if (i >= n) return -1; b = src[i++]; lit += b; } while (b == 255);
        }
        if (i + lit > n) return -1;
        if (o + lit > cap) return -4;
        memcpy(dst + o, src + i, lit); i += lit; o += lit;
        if (i == n) break;            // last sequence: literals only
        if (i + 2 > n) return -1;
        int64_t off = rd16le(src + i); i += 2;
        if (off == 0 || off > o + hist) return -1;
        int64_t mlen = (tok & 0x0F) + 4;
        if ((tok & 0x0F) == 15) {
            uint8_t b;
            do { if (i >= n) return -1; b = src[i++]; mlen += b; } while (b == 255);
        }
        if (o + mlen > cap) return -4;
        const uint8_t *m = dst + o - off;
        if (off >= 16 && o + mlen + 16 <= cap) {
            // wild copy: 16-byte chunks may overshoot mlen by up to 15
            // bytes — safe inside cap, and the tail is overwritten by
            // the next sequence's literals (liblz4's own fast path)
            for (int64_t k = 0; k < mlen; k += 16)
                memcpy(dst + o + k, m + k, 16);
        } else if (off >= 8) {
            // non-overlapping at word granularity: 8-byte strided copy
            // (the byte loop measured ~0.6 GB/s on the fetch path)
            int64_t k = 0;
            for (; k + 8 <= mlen; k += 8) memcpy(dst + o + k, m + k, 8);
            for (; k < mlen; k++) dst[o + k] = m[k];
        } else if (mlen <= off * 2) {
            for (int64_t k = 0; k < mlen; k++) dst[o + k] = m[k];
        } else {
            // small-offset overlap (RLE-ish data): pattern doubling —
            // seed one period, then double the written segment with
            // non-overlapping memcpys (log2 copies instead of a byte
            // loop; this path measured 340 MB/s byte-at-a-time)
            for (int64_t k = 0; k < off; k++) dst[o + k] = m[k];
            int64_t seg = off;
            while (seg < mlen) {
                int64_t c = seg <= mlen - seg ? seg : mlen - seg;
                memcpy(dst + o + seg, dst + o, c);
                seg += c;
            }
        }
        o += mlen;
    }
    return o;
}

EXPORT int64_t tk_lz4_block_decompress(const uint8_t *src, int64_t n,
                                       uint8_t *dst, int64_t cap) {
    return lz4_block_decompress_hist(src, n, dst, cap, 0);
}

// ------------------------------------------------------------- LZ4 frame --
//
// Frame layout per the public LZ4 Frame spec v1.6.1:
//   magic 0x184D2204 | FLG | BD | HC | blocks... | EndMark(0) [| C.Checksum]
// We write: version=01, block-independent, 64KB max block, no content
// checksum/size (FLG=0x60, BD=0x40). The reader accepts any compliant
// frame, incl. linked blocks (decoded into one contiguous buffer so
// back-references across blocks resolve naturally) and content checksums.
// [ref behavior: rdkafka_lz4.c:168,330]

static const uint32_t LZ4F_MAGIC = 0x184D2204u;
static const int64_t LZ4F_BLOCKSIZE = 65536;

EXPORT int64_t tk_lz4f_bound(int64_t n) {
    int64_t blocks = n / LZ4F_BLOCKSIZE + 1;
    return 7 + n + n / 255 + blocks * 20 + 8;
}

static int64_t lz4f_compress_impl(const uint8_t *src, int64_t n,
                                  uint8_t *dst, int64_t cap,
                                  int64_t (*block)(const uint8_t *, int64_t,
                                                   uint8_t *, int64_t)) {
    if (cap < tk_lz4f_bound(n)) return -1;
    int64_t o = 0;
    uint32_t magic = LZ4F_MAGIC;
    memcpy(dst + o, &magic, 4); o += 4;
    dst[o++] = 0x60;  // FLG: version=01, B.Indep=1
    dst[o++] = 0x40;  // BD: 64KB max block size
    dst[o] = (uint8_t)(tk_xxh32(dst + 4, 2, 0) >> 8); o++;  // HC
    for (int64_t pos = 0; pos < n; pos += LZ4F_BLOCKSIZE) {
        int64_t blen = n - pos < LZ4F_BLOCKSIZE ? n - pos : LZ4F_BLOCKSIZE;
        int64_t csize = block(src + pos, blen, dst + o + 4, cap - o - 4);
        if (csize < 0) return -1;
        uint32_t hdr;
        if (csize < blen) {
            hdr = (uint32_t)csize;
        } else {  // incompressible: store raw with high bit set
            hdr = (uint32_t)blen | 0x80000000u;
            memcpy(dst + o + 4, src + pos, blen);
            csize = blen;
        }
        memcpy(dst + o, &hdr, 4); o += 4 + csize;
    }
    uint32_t endmark = 0;
    memcpy(dst + o, &endmark, 4); o += 4;
    return o;
}

EXPORT int64_t tk_lz4f_compress(const uint8_t *src, int64_t n,
                                uint8_t *dst, int64_t cap) {
    return lz4f_compress_impl(src, n, dst, cap, tk_lz4_block_compress);
}

// Fast-parse frame: same spec-compliant wire format, throughput-first
// block encoder (the broker hot path's default).
EXPORT int64_t tk_lz4f_compress_fast(const uint8_t *src, int64_t n,
                                     uint8_t *dst, int64_t cap) {
    return lz4f_compress_impl(src, n, dst, cap, tk_lz4_block_compress_fast);
}

EXPORT int64_t tk_lz4f_decompress(const uint8_t *src, int64_t n,
                                  uint8_t *dst, int64_t cap) {
    int64_t i = 0, o = 0;
    if (n < 7) return -1;
    uint32_t magic = rd32le(src);
    if (magic != LZ4F_MAGIC) return -2;
    i = 4;
    uint8_t flg = src[i], bd = src[i + 1];
    (void)bd;
    if ((flg >> 6) != 1) return -3;            // version
    bool has_csize = flg & 0x08, has_cchk = flg & 0x04, has_dict = flg & 0x01;
    bool has_bchk = flg & 0x10;
    i += 2;
    if (has_csize) i += 8;
    if (has_dict) i += 4;
    i += 1;  // HC (not verified on read; transport has its own integrity)
    if (i > n) return -1;
    while (true) {
        if (i + 4 > n) return -1;
        uint32_t hdr = rd32le(src + i); i += 4;
        if (hdr == 0) break;  // EndMark
        bool raw = hdr & 0x80000000u;
        int64_t bsz = hdr & 0x7FFFFFFF;
        if (i + bsz > n) return -1;
        if (raw) {
            if (o + bsz > cap) return -4;
            memcpy(dst + o, src + i, bsz); o += bsz;
        } else {
            int64_t dsz = lz4_block_decompress_hist(src + i, bsz, dst + o,
                                                    cap - o, o);
            if (dsz < 0) return dsz == -4 ? -4 : -5;
            o += dsz;
        }
        i += bsz;
        if (has_bchk) i += 4;
    }
    if (has_cchk) {
        if (i + 4 > n) return -1;
        if (rd32le(src + i) != tk_xxh32(dst, o, 0)) return -6;
    }
    return o;
}

// --------------------------------------------------------------- snappy ---
//
// Raw snappy block format (public spec: format_description.txt):
//   preamble = uvarint uncompressed length
//   elements: tag&3 == 0 literal / 1 copy-1byte-offset / 2 copy-2byte / 3 copy-4byte
// Encoder is a fast-parse greedy scheme (r5): uncapped matches emitted
// as chained <=64-byte copy tags, sparse table seeding, miss
// acceleration — any spec-valid stream is legal snappy, and both the
// fused and 3-phase paths share THIS function so their wire bytes
// stay identical (test_0122). A TPU snappy provider would need its
// own deterministic spec, as the lz4 one has.
// [ref: vendored src/snappy.c; java-framing compat handled in msgset reader]

static const int SN_HASH_BITS = 12;

static inline uint32_t sn_hash(uint32_t x) {
    return (x * 2654435761u) >> (32 - SN_HASH_BITS);
}

EXPORT int64_t tk_snappy_bound(int64_t n) { return 32 + n + n / 6; }

EXPORT int64_t tk_snappy_compress(const uint8_t *src, int64_t n,
                                  uint8_t *dst, int64_t cap) {
    if (cap < tk_snappy_bound(n)) return -1;
    int64_t o = 0;
    // preamble: uncompressed length uvarint
    uint64_t v = (uint64_t)n;
    do { uint8_t b = v & 0x7F; v >>= 7; dst[o++] = b | (v ? 0x80 : 0); } while (v);

    auto emit_literal = [&](int64_t from, int64_t len) {
        while (len > 0) {
            int64_t l = len;  // snappy literals can be up to 2^32; chunk at 2^16 for 2-byte len
            if (l > 65536) l = 65536;
            if (l <= 60) dst[o++] = (uint8_t)((l - 1) << 2);
            else if (l <= 256) { dst[o++] = 60 << 2; dst[o++] = (uint8_t)(l - 1); }
            else { dst[o++] = 61 << 2; dst[o++] = (uint8_t)((l - 1) & 0xFF);
                   dst[o++] = (uint8_t)((l - 1) >> 8); }
            memcpy(dst + o, src + from, l); o += l; from += l; len -= l;
        }
    };
    auto emit_copy = [&](int64_t off, int64_t len) {
        // len in [4,64]; use copy-1 when len<=11 && off<2048, else copy-2
        if (len <= 11 && off < 2048) {
            dst[o++] = (uint8_t)(1 | ((len - 4) << 2) | ((off >> 8) << 5));
            dst[o++] = (uint8_t)(off & 0xFF);
        } else {
            dst[o++] = (uint8_t)(2 | ((len - 1) << 2));
            dst[o++] = (uint8_t)(off & 0xFF); dst[o++] = (uint8_t)(off >> 8);
        }
    };

    // fast-parse loop (r5; the same techniques as
    // tk_lz4_block_compress_fast): 8-byte XOR/ctz match extension,
    // uncapped matches emitted as chained <=64-byte copy tags (what
    // libsnappy does), sparse table seeding at match ends instead of
    // insert-all over interiors, and miss-acceleration strides through
    // incompressible runs. The old insert-all loop measured 1.8 us per
    // 1KB record in the fused batch builder vs lz4's 0.2.
    int32_t table[1 << SN_HASH_BITS];
    memset(table, -1, sizeof(table));
    int64_t anchor = 0, p = 0;
    while (p + 12 <= n) {
        uint32_t seq = rd32le(src + p);
        uint32_t h = sn_hash(seq);
        int64_t cand = table[h];
        table[h] = (int32_t)p;
        if (cand >= 0 && p - cand <= 65535 && rd32le(src + cand) == seq) {
            int64_t maxm = n - p;
            int64_t mlen = 4;
            while (mlen + 8 <= maxm) {
                uint64_t a, b;
                memcpy(&a, src + cand + mlen, 8);
                memcpy(&b, src + p + mlen, 8);
                uint64_t x = a ^ b;
                if (x) { mlen += __builtin_ctzll(x) >> 3; break; }
                mlen += 8;
            }
            if (mlen + 8 > maxm)
                while (mlen < maxm && src[cand + mlen] == src[p + mlen])
                    mlen++;
            emit_literal(anchor, p - anchor);
            int64_t off = p - cand, rem = mlen;
            while (rem >= 68) { emit_copy(off, 64); rem -= 64; }
            if (rem > 64) { emit_copy(off, 60); rem -= 60; }
            emit_copy(off, rem);           /* rem in [4, 64] */
            int64_t end = p + mlen;
            if (end - 1 > p && end + 3 <= n)
                table[sn_hash(rd32le(src + end - 1))] = (int32_t)(end - 1);
            if (end - 2 > p && end + 2 <= n)
                table[sn_hash(rd32le(src + end - 2))] = (int32_t)(end - 2);
            p = end;
            anchor = p;
        } else {
            p += 1 + ((uint32_t)(p - anchor) >> 7);
        }
    }
    emit_literal(anchor, n - anchor);
    return o;
}

EXPORT int64_t tk_snappy_uncompressed_length(const uint8_t *src, int64_t n) {
    uint64_t v = 0; int shift = 0; int64_t i = 0;
    while (true) {
        if (i >= n || shift > 35) return -1;
        uint8_t b = src[i++];
        v |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) return (int64_t)v;
        shift += 7;
    }
}

EXPORT int64_t tk_snappy_decompress(const uint8_t *src, int64_t n,
                                    uint8_t *dst, int64_t cap) {
    // skip preamble
    int64_t i = 0;
    while (i < n && (src[i] & 0x80)) i++;
    if (i++ >= n) return -1;
    int64_t o = 0;
    while (i < n) {
        uint8_t tag = src[i++];
        int t = tag & 3;
        if (t == 0) {                       // literal
            int64_t len = (tag >> 2) + 1;
            if (len > 60) {
                int nb = (int)len - 60;
                if (i + nb > n) return -1;
                len = 0;
                for (int k = nb - 1; k >= 0; k--) len = (len << 8) | src[i + k];
                len += 1; i += nb;
            }
            if (i + len > n || o + len > cap) return -1;
            memcpy(dst + o, src + i, len); i += len; o += len;
        } else {
            int64_t len, off;
            if (t == 1) {
                len = ((tag >> 2) & 7) + 4;
                if (i >= n) return -1;
                off = ((int64_t)(tag >> 5) << 8) | src[i++];
            } else if (t == 2) {
                len = (tag >> 2) + 1;
                if (i + 2 > n) return -1;
                off = rd16le(src + i); i += 2;
            } else {
                len = (tag >> 2) + 1;
                if (i + 4 > n) return -1;
                off = rd32le(src + i); i += 4;
            }
            if (off == 0 || off > o || o + len > cap) return -1;
            const uint8_t *m = dst + o - off;
            for (int64_t k = 0; k < len; k++) dst[o + k] = m[k];
            o += len;
        }
    }
    return o;
}

// ---------------------------------------------------- v2 record framing --
//
// Frame a run of messages into the MessageSet v2 records wire layout
// (reference hot loop: rd_kafka_msgset_writer_write_msg_v2,
// rdkafka_msgset_writer.c:653 — per-record varint framing).  One call per
// batch; the GIL is released for the duration, so framing overlaps the
// app thread's produce() loop.  Headers are framed by the Python fallback.
//
// Layout per record: [len vi][attr=0][ts_delta vi][offset_delta vi]
//                    [klen vi][key][vlen vi][value][header_cnt vi = 0]

static inline int vi_size(int64_t v) {
    uint64_t u = ((uint64_t)v << 1) ^ (uint64_t)(v >> 63);  // zigzag
    int n = 1;
    while (u >= 0x80) { u >>= 7; n++; }
    return n;
}

static inline uint8_t *vi_put(uint8_t *p, int64_t v) {
    uint64_t u = ((uint64_t)v << 1) ^ (uint64_t)(v >> 63);
    while (u >= 0x80) { *p++ = (uint8_t)(u | 0x80); u >>= 7; }
    *p++ = (uint8_t)u;
    return p;
}

// bytes needed in the worst case for `count` records over `payload_bytes`
EXPORT int64_t tk_frame_v2_bound(int64_t payload_bytes, int count) {
    return payload_bytes + (int64_t)count * 40 + 64;
}

// base: concatenated key||value bytes per message, in order
// klens/vlens: -1 = null
// ts_deltas: timestamp - first_timestamp per message
// Returns bytes written, or -1 on capacity shortfall.
EXPORT int64_t tk_frame_v2(const uint8_t *base, const int32_t *klens,
                           const int32_t *vlens, const int64_t *ts_deltas,
                           int count, uint8_t *out, int64_t cap) {
    uint8_t *p = out;
    const uint8_t *end = out + cap;
    const uint8_t *src = base;
    for (int i = 0; i < count; i++) {
        int64_t kl = klens[i], vl = vlens[i];
        int64_t body = 1 + vi_size(ts_deltas[i]) + vi_size(i)
                     + vi_size(kl) + (kl > 0 ? kl : 0)
                     + vi_size(vl) + (vl > 0 ? vl : 0)
                     + 1;                       // header count varint(0)
        if (p + vi_size(body) + body > end) return -1;
        p = vi_put(p, body);
        *p++ = 0;                               // record attributes
        p = vi_put(p, ts_deltas[i]);
        p = vi_put(p, i);                       // offset delta
        p = vi_put(p, kl);
        if (kl > 0) { memcpy(p, src, kl); p += kl; src += kl; }
        p = vi_put(p, vl);
        if (vl > 0) { memcpy(p, src, vl); p += vl; src += vl; }
        *p++ = 0;                               // varint(0) headers
    }
    return p - out;
}

// Run-native framer for the widened fast lane: per-record timestamps
// (0 = unset -> now_ms, matching the slow path's "timestamp and
// timestamp > 0 else now" rule) and PRE-ENCODED header blobs (each
// blob already carries its header-count varint + per-header framing —
// the enqueue lane encodes them once at produce() time).  tss/hbuf/
// hlens may be NULL: NULL tss means every record stamps now_ms (zero
// deltas), NULL hlens means every record writes varint(0) headers.
// first/max effective timestamps come back for the v2 batch header.
EXPORT int64_t tk_frame_v2_run(const uint8_t *base, const int32_t *klens,
                               const int32_t *vlens, const int64_t *tss,
                               int64_t now_ms, const uint8_t *hbuf,
                               const int32_t *hlens, int count,
                               uint8_t *out, int64_t cap,
                               int64_t *first_ts, int64_t *max_ts) {
    uint8_t *p = out;
    const uint8_t *end = out + cap;
    const uint8_t *src = base;
    const uint8_t *hsrc = hbuf;
    int64_t f = now_ms, mx = now_ms;
    for (int i = 0; i < count; i++) {
        int64_t ts = (tss && tss[i] > 0) ? tss[i] : now_ms;
        if (i == 0) { f = ts; mx = ts; }
        else if (ts > mx) mx = ts;
        int64_t d = ts - f;                     // may be negative
        int64_t kl = klens[i], vl = vlens[i];
        int64_t hl = hlens ? hlens[i] : 0;
        int64_t body = 1 + vi_size(d) + vi_size(i)
                     + vi_size(kl) + (kl > 0 ? kl : 0)
                     + vi_size(vl) + (vl > 0 ? vl : 0)
                     + (hl > 0 ? hl : 1);
        if (p + vi_size(body) + body > end) return -1;
        p = vi_put(p, body);
        *p++ = 0;                               // record attributes
        p = vi_put(p, d);
        p = vi_put(p, i);                       // offset delta
        p = vi_put(p, kl);
        if (kl > 0) { memcpy(p, src, kl); p += kl; src += kl; }
        p = vi_put(p, vl);
        if (vl > 0) { memcpy(p, src, vl); p += vl; src += vl; }
        if (hl > 0) { memcpy(p, hsrc, hl); p += hl; hsrc += hl; }
        else *p++ = 0;                          // varint(0) headers
    }
    if (first_ts) *first_ts = f;
    if (max_ts) *max_ts = mx;
    return p - out;
}

// ------------------------------------------------------ batched parallel --
//
// The provider seam (SURVEY.md §3.2) hands MANY independent per-partition
// batches at once; unlike the reference — which compresses each batch
// sequentially on its broker thread (rdkafka_msgset_writer.c:1129) — the
// batch axis parallelizes across cores here.  Inputs are packed into one
// contiguous base buffer with offsets; outputs go to caller-provided
// per-item regions (capacity >= tk_lz4f_bound).

#include <thread>
#include <atomic>
#include <vector>

static void lz4f_compress_many_impl(
    const uint8_t *base, const int64_t *offs, const int64_t *lens, int n,
    uint8_t *outbase, const int64_t *out_offs, int64_t *out_lens,
    int nthreads,
    int64_t (*one)(const uint8_t *, int64_t, uint8_t *, int64_t)) {
    if (n <= 0) return;
    unsigned hw = std::thread::hardware_concurrency();
    int nt = nthreads > 0 ? nthreads : (hw ? (int)hw : 4);
    if (nt > n) nt = n;
    std::atomic<int> next(0);
    auto work = [&]() {
        int i;
        while ((i = next.fetch_add(1)) < n) {
            out_lens[i] = one(base + offs[i], lens[i],
                              outbase + out_offs[i],
                              tk_lz4f_bound(lens[i]));
        }
    };
    if (nt == 1) { work(); return; }
    std::vector<std::thread> ts;
    for (int t = 0; t < nt; t++) ts.emplace_back(work);
    for (auto &t : ts) t.join();
}

EXPORT void tk_lz4f_compress_many(const uint8_t *base, const int64_t *offs,
                                  const int64_t *lens, int n,
                                  uint8_t *outbase, const int64_t *out_offs,
                                  int64_t *out_lens, int nthreads) {
    lz4f_compress_many_impl(base, offs, lens, n, outbase, out_offs,
                            out_lens, nthreads, tk_lz4f_compress);
}

EXPORT void tk_lz4f_compress_many_fast(
    const uint8_t *base, const int64_t *offs, const int64_t *lens, int n,
    uint8_t *outbase, const int64_t *out_offs, int64_t *out_lens,
    int nthreads) {
    lz4f_compress_many_impl(base, offs, lens, n, outbase, out_offs,
                            out_lens, nthreads, tk_lz4f_compress_fast);
}

EXPORT void tk_snappy_compress_many(const uint8_t *base, const int64_t *offs,
                                    const int64_t *lens, int n,
                                    uint8_t *outbase, const int64_t *out_offs,
                                    int64_t *out_lens, int nthreads) {
    if (n <= 0) return;
    unsigned hw = std::thread::hardware_concurrency();
    int nt = nthreads > 0 ? nthreads : (hw ? (int)hw : 4);
    if (nt > n) nt = n;
    std::atomic<int> next(0);
    auto work = [&]() {
        int i;
        while ((i = next.fetch_add(1)) < n) {
            out_lens[i] = tk_snappy_compress(base + offs[i], lens[i],
                                             outbase + out_offs[i],
                                             tk_snappy_bound(lens[i]));
        }
    };
    if (nt == 1) { work(); return; }
    std::vector<std::thread> ts;
    for (int t = 0; t < nt; t++) ts.emplace_back(work);
    for (auto &t : ts) t.join();
}

// Exact decompressed size by a write-free sequence walk (the lz4 frame
// format carries no content size with our FLG; a wrong capacity guess
// costs full re-decodes — the snappy preamble-length pattern, but
// computed). ~#sequences work, not #bytes.
static int64_t lz4_block_decompressed_size(const uint8_t *src, int64_t n) {
    int64_t i = 0, o = 0;
    while (i < n) {
        uint8_t tok = src[i++];
        int64_t lit = tok >> 4;
        if (lit == 15) {
            uint8_t b;
            do { if (i >= n) return -1; b = src[i++]; lit += b; } while (b == 255);
        }
        if (i + lit > n) return -1;
        i += lit; o += lit;
        if (i == n) break;
        if (i + 2 > n) return -1;
        i += 2;
        int64_t mlen = (tok & 0x0F) + 4;
        if ((tok & 0x0F) == 15) {
            uint8_t b;
            do { if (i >= n) return -1; b = src[i++]; mlen += b; } while (b == 255);
        }
        o += mlen;
    }
    return o;
}

EXPORT int64_t tk_lz4f_decompressed_size(const uint8_t *src, int64_t n) {
    int64_t i = 0, o = 0;
    // the result sizes an allocation BEFORE any decode validates the
    // data, and the input is untrusted network bytes — clamp to the
    // lz4 format's own max expansion (~255:1 via run-length extension
    // bytes) so a corrupt frame cannot request terabytes
    const int64_t max_out = n * 256 + (64 << 10);
    if (n < 7) return -1;
    if (rd32le(src) != LZ4F_MAGIC) return -2;
    i = 4;
    uint8_t flg = src[i];
    if ((flg >> 6) != 1) return -3;
    bool has_csize = flg & 0x08, has_dict = flg & 0x01;
    bool has_bchk = flg & 0x10;
    i += 2;
    if (has_csize) {
        // content size present: trust the header field within bounds
        if (i + 8 > n) return -1;
        int64_t cs;
        memcpy(&cs, src + i, 8);
        if (cs < 0 || cs > max_out) return -6;
        return cs;
    }
    if (has_dict) i += 4;
    i += 1;
    while (true) {
        if (i + 4 > n) return -1;
        uint32_t hdr = rd32le(src + i); i += 4;
        if (hdr == 0) break;
        bool raw = hdr & 0x80000000u;
        int64_t bsz = hdr & 0x7FFFFFFF;
        if (i + bsz > n) return -1;
        if (raw) o += bsz;
        else {
            int64_t d = lz4_block_decompressed_size(src + i, bsz);
            if (d < 0) return -5;
            o += d;
        }
        if (o > max_out) return -6;
        i += bsz;
        if (has_bchk) i += 4;
    }
    return o;
}

EXPORT void tk_lz4f_decompressed_size_many(const uint8_t *base,
                                           const int64_t *offs,
                                           const int64_t *lens, int n,
                                           int64_t *out_sizes) {
    for (int i = 0; i < n; i++)
        out_sizes[i] = tk_lz4f_decompressed_size(base + offs[i], lens[i]);
}

EXPORT void tk_lz4f_decompress_many(const uint8_t *base, const int64_t *offs,
                                    const int64_t *lens, int n,
                                    uint8_t *outbase, const int64_t *out_offs,
                                    const int64_t *out_caps,
                                    int64_t *out_lens, int nthreads) {
    if (n <= 0) return;
    unsigned hw = std::thread::hardware_concurrency();
    int nt = nthreads > 0 ? nthreads : (hw ? (int)hw : 4);
    if (nt > n) nt = n;
    std::atomic<int> next(0);
    auto work = [&]() {
        int i;
        while ((i = next.fetch_add(1)) < n) {
            out_lens[i] = tk_lz4f_decompress(base + offs[i], lens[i],
                                             outbase + out_offs[i],
                                             out_caps[i]);
        }
    };
    if (nt == 1) { work(); return; }
    std::vector<std::thread> ts;
    for (int t = 0; t < nt; t++) ts.emplace_back(work);
    for (auto &t : ts) t.join();
}

EXPORT void tk_snappy_decompress_many(const uint8_t *base, const int64_t *offs,
                                      const int64_t *lens, int n,
                                      uint8_t *outbase,
                                      const int64_t *out_offs,
                                      const int64_t *out_caps,
                                      int64_t *out_lens, int nthreads) {
    if (n <= 0) return;
    unsigned hw = std::thread::hardware_concurrency();
    int nt = nthreads > 0 ? nthreads : (hw ? (int)hw : 4);
    if (nt > n) nt = n;
    std::atomic<int> next(0);
    auto work = [&]() {
        int i;
        while ((i = next.fetch_add(1)) < n) {
            out_lens[i] = tk_snappy_decompress(base + offs[i], lens[i],
                                               outbase + out_offs[i],
                                               out_caps[i]);
        }
    };
    if (nt == 1) { work(); return; }
    std::vector<std::thread> ts;
    for (int t = 0; t < nt; t++) ts.emplace_back(work);
    for (auto &t : ts) t.join();
}

// ---------------------------------------------------------------------------
// MessageSet v2 record parsing (the consumer hot loop: the Python
// varint walk was ~40% of consume time). Emits 8 int64 fields per
// record into `out`:
//   [ts_delta, off_delta, key_off, key_len, val_off, val_len,
//    hdrs_off, n_headers]
// key/val offsets index into the records payload; -1 length = null.
// Returns the record count parsed, or -1 on malformed input.
static inline int vi_dec(const uint8_t *p, const uint8_t *end, int64_t *out) {
    uint64_t u = 0;
    int shift = 0, i = 0;
    while (p + i < end && i < 10) {
        uint8_t b = p[i++];
        u |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) {
            *out = (int64_t)(u >> 1) ^ -(int64_t)(u & 1);  // zig-zag
            return i;
        }
        shift += 7;
    }
    return -1;
}

EXPORT int64_t tk_parse_v2(const uint8_t *buf, int64_t n, int64_t max_recs,
                           int64_t *out) {
    // NOTE: all bounds checks are in LENGTH space (len > rend - p), not
    // pointer space (p + len > rend) — the lengths come off the wire
    // and p + INT64_MAX is undefined behavior the optimizer may exploit
    const uint8_t *p = buf, *end = buf + n;
    int64_t cnt = 0;
    while (p < end && cnt < max_recs) {
        int64_t rec_len;
        int c = vi_dec(p, end, &rec_len);
        if (c < 0 || rec_len < 0) return -1;
        p += c;
        if (rec_len > end - p) return -1;
        const uint8_t *rend = p + rec_len;
        if (p >= rend) return -1;
        p += 1;                                   // record attributes
        int64_t ts_delta, off_delta, klen, vlen, nh;
        if ((c = vi_dec(p, rend, &ts_delta)) < 0) return -1;
        p += c;
        if ((c = vi_dec(p, rend, &off_delta)) < 0) return -1;
        p += c;
        if ((c = vi_dec(p, rend, &klen)) < 0) return -1;
        p += c;
        int64_t key_off = p - buf;
        if (klen > 0) {
            if (klen > rend - p) return -1;
            p += klen;
        }
        if ((c = vi_dec(p, rend, &vlen)) < 0) return -1;
        p += c;
        int64_t val_off = p - buf;
        if (vlen > 0) {
            if (vlen > rend - p) return -1;
            p += vlen;
        }
        if ((c = vi_dec(p, rend, &nh)) < 0) return -1;
        p += c;
        int64_t hdrs_off = p - buf;           // first header record
        if (nh < 0) return -1;
        // validate the header section stays inside the record — the
        // Python side re-walks it unnarrowed, so a malformed length
        // must fail HERE, not silently read the next record's bytes
        for (int64_t h = 0; h < nh; h++) {
            int64_t hkl, hvl;
            if ((c = vi_dec(p, rend, &hkl)) < 0 || hkl < 0) return -1;
            p += c;
            if (hkl > rend - p) return -1;
            p += hkl;
            if ((c = vi_dec(p, rend, &hvl)) < 0) return -1;
            p += c;
            if (hvl > 0) {
                if (hvl > rend - p) return -1;
                p += hvl;
            }
        }
        if (p != rend) return -1;             // trailing garbage
        int64_t *row = out + cnt * 8;
        row[0] = ts_delta; row[1] = off_delta;
        row[2] = key_off;  row[3] = klen;
        row[4] = val_off;  row[5] = vlen;
        row[6] = hdrs_off; row[7] = nh;
        cnt++;
    }
    return (p == end || cnt == max_recs) ? cnt : -1;
}
