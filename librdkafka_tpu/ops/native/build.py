"""Build the native libraries (g++ → .so), cached by mtime.

Two artifacts:
  _codec.so     — plain shared library reached via ctypes (codec.cpp)
  tk_enqlane.so — CPython extension module (enqlane.cpp; ctypes call
                  overhead would eat the enqueue lane's win)
"""
from __future__ import annotations

import os
import subprocess
import sysconfig
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "codec.cpp")
SO = os.path.join(_DIR, "_codec.so")
ENQ_SRC = os.path.join(_DIR, "enqlane.cpp")
ENQ_SO = os.path.join(_DIR, "tk_enqlane.so")
_lock = threading.Lock()


def _compile(src, so: str, extra: list[str]) -> str:
    srcs = [src] if isinstance(src, str) else list(src)
    if (os.path.exists(so)
            and all(os.path.getmtime(so) >= os.path.getmtime(s)
                    for s in srcs)):
        return so
    tmp = so + ".tmp"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           *extra, "-o", tmp, *srcs]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so)
    return so


def _loadable(so: str) -> bool:
    """A shipped .so can be foreign (sdist built on another arch/libc);
    trust it only if ctypes can actually load it."""
    import ctypes
    try:
        ctypes.CDLL(so)
        return True
    except OSError:
        return False


def build(force: bool = False) -> str:
    """Compile codec.cpp to a shared library if stale; returns the .so path."""
    with _lock:
        if force and os.path.exists(SO):
            os.remove(SO)
        so = _compile(SRC, SO, ["-fvisibility=hidden"])
        if not _loadable(so):
            os.remove(so)               # wrong-platform prebuilt: rebuild
            so = _compile(SRC, SO, ["-fvisibility=hidden"])
        return so


def build_enqlane(force: bool = False) -> str:
    """Compile the tk_enqlane CPython extension if stale; returns path.
    codec.cpp is linked in too: the fused batch builder (build_batch)
    calls its framing/codec/CRC functions directly."""
    with _lock:
        if force and os.path.exists(ENQ_SO):
            os.remove(ENQ_SO)
        inc = sysconfig.get_paths()["include"]
        return _compile([ENQ_SRC, SRC], ENQ_SO, ["-I" + inc])


def load_enqlane():
    """Import the tk_enqlane extension module (building if stale). A
    shipped wrong-platform binary gets one rebuild before giving up."""
    import importlib.machinery
    import importlib.util

    def _load(path):
        loader = importlib.machinery.ExtensionFileLoader("tk_enqlane", path)
        spec = importlib.util.spec_from_loader("tk_enqlane", loader)
        mod = importlib.util.module_from_spec(spec)
        loader.exec_module(mod)
        return mod

    try:
        return _load(build_enqlane())
    except ImportError:
        return _load(build_enqlane(force=True))
