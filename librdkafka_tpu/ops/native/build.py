"""Build the native codec library (g++ → _codec.so), cached by mtime."""
from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "codec.cpp")
SO = os.path.join(_DIR, "_codec.so")
_lock = threading.Lock()


def build(force: bool = False) -> str:
    """Compile codec.cpp to a shared library if stale; returns the .so path."""
    with _lock:
        if (not force and os.path.exists(SO)
                and os.path.getmtime(SO) >= os.path.getmtime(SRC)):
            return SO
        tmp = SO + ".tmp"
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
               "-fvisibility=hidden", "-o", tmp, SRC]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, SO)
        return SO
