"""CPU codec provider — ctypes bindings over the native C++ library.

This is the default ``compression.backend=cpu`` provider implementing the
MsgsetCodecProvider interface (SURVEY.md §7 stage 5): compress / decompress /
crc32c over one or many buffers. gzip rides Python's zlib; zstd rides the
zstandard module; lz4 and snappy are our own native implementations
(ops/native/codec.cpp), bit-identical with the TPU provider by shared spec.
"""
from __future__ import annotations

import ctypes
import gzip as _gzip
import io
import struct
import zlib

import numpy as np

from .native.build import build

_lib = None


def lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        so = build()
        L = ctypes.CDLL(so)
        i64, u8p, u32 = ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32
        i64p, u32p = ctypes.POINTER(i64), ctypes.POINTER(u32)
        L.tk_crc32c.restype = u32
        L.tk_crc32c.argtypes = [ctypes.c_char_p, i64, u32]
        L.tk_crc32c_many.restype = None
        L.tk_crc32c_many.argtypes = [ctypes.c_char_p, i64p, i64p, u32p, ctypes.c_int]
        L.tk_xxh32.restype = u32
        L.tk_xxh32.argtypes = [ctypes.c_char_p, i64, u32]
        L.tk_parse_v2.restype = i64
        L.tk_parse_v2.argtypes = [ctypes.c_char_p, i64, i64, i64p]
        for name in ("tk_lz4_block_compress", "tk_lz4_block_compress_fast",
                     "tk_lz4_block_decompress",
                     "tk_lz4f_compress", "tk_lz4f_compress_fast",
                     "tk_lz4f_decompress",
                     "tk_snappy_compress", "tk_snappy_decompress"):
            fn = getattr(L, name)
            fn.restype = i64
            fn.argtypes = [ctypes.c_char_p, i64, u8p, i64]
        for name in ("tk_lz4f_compress_many", "tk_lz4f_compress_many_fast",
                     "tk_snappy_compress_many"):
            fn = getattr(L, name)
            fn.restype = None
            fn.argtypes = [ctypes.c_char_p, i64p, i64p, ctypes.c_int,
                           u8p, i64p, i64p, ctypes.c_int]
        for name in ("tk_lz4f_decompress_many", "tk_snappy_decompress_many"):
            fn = getattr(L, name)
            fn.restype = None
            fn.argtypes = [ctypes.c_char_p, i64p, i64p, ctypes.c_int,
                           u8p, i64p, i64p, i64p, ctypes.c_int]
        i32p = ctypes.POINTER(ctypes.c_int32)
        L.tk_frame_v2_bound.restype = i64
        L.tk_frame_v2_bound.argtypes = [i64, ctypes.c_int]
        L.tk_frame_v2.restype = i64
        L.tk_frame_v2.argtypes = [ctypes.c_char_p, i32p, i32p, i64p,
                                  ctypes.c_int, u8p, i64]
        L.tk_frame_v2_run.restype = i64
        L.tk_frame_v2_run.argtypes = [ctypes.c_char_p, i32p, i32p, i64p,
                                      i64, ctypes.c_char_p, i32p,
                                      ctypes.c_int, u8p, i64, i64p, i64p]
        for name in ("tk_lz4f_bound", "tk_snappy_bound", "tk_lz4_block_bound",
                     "tk_snappy_uncompressed_length"):
            fn = getattr(L, name)
            fn.restype = i64
        L.tk_lz4f_bound.argtypes = [i64]
        L.tk_snappy_bound.argtypes = [i64]
        L.tk_lz4_block_bound.argtypes = [i64]
        L.tk_snappy_uncompressed_length.argtypes = [ctypes.c_char_p, i64]
        L.tk_lz4f_decompressed_size.restype = i64
        L.tk_lz4f_decompressed_size.argtypes = [ctypes.c_char_p, i64]
        _lib = L
    return _lib


def _outbuf(cap: int):
    buf = ctypes.create_string_buffer(cap)
    return buf, ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8))


def crc32c(data: bytes, crc: int = 0) -> int:
    return lib().tk_crc32c(bytes(data), len(data), crc)


def xxh32(data: bytes, seed: int = 0) -> int:
    return lib().tk_xxh32(bytes(data), len(data), seed)


# ------------------------------------------------------------------- lz4 ---

def lz4_block_compress(data: bytes) -> bytes:
    data = bytes(data)
    cap = lib().tk_lz4_block_bound(len(data))
    buf, p = _outbuf(cap)
    r = lib().tk_lz4_block_compress(data, len(data), p, cap)
    if r < 0:
        raise ValueError("lz4 block compress failed")
    return buf.raw[:r]


def lz4_block_decompress(data: bytes, uncompressed_size: int) -> bytes:
    data = bytes(data)
    buf, p = _outbuf(uncompressed_size)
    r = lib().tk_lz4_block_decompress(data, len(data), p, uncompressed_size)
    if r < 0:
        raise ValueError(f"lz4 block decompress failed ({r})")
    return buf.raw[:r]


def lz4_compress(data: bytes, *, deterministic: bool = True) -> bytes:
    """LZ4 frame compress (Kafka MsgVer2 lz4 wire format).

    ``deterministic=True`` (default) uses the insert-all greedy encoder
    that is the bit-exactness contract shared with the TPU kernel
    (ops/lz4_jax.py); ``False`` uses the throughput-first fast parse
    (same spec-compliant format, ~6x faster — what the broker hot path
    ships)."""
    data = bytes(data)
    cap = lib().tk_lz4f_bound(len(data))
    buf, p = _outbuf(cap)
    fn = (lib().tk_lz4f_compress if deterministic
          else lib().tk_lz4f_compress_fast)
    r = fn(data, len(data), p, cap)
    if r < 0:
        raise ValueError("lz4 frame compress failed")
    return buf.raw[:r]


def lz4_decompress(data: bytes, size_hint: int = 0) -> bytes:
    data = bytes(data)
    # hard ceiling: LZ4 cannot expand beyond ~255x input, so corruption
    # that masquerades as a capacity shortfall (-4) fails after one grow
    # instead of ballooning toward a fixed 1GB cap
    limit = 255 * len(data) + (1 << 16)
    cap = max(size_hint, 4 * len(data) + (1 << 16))
    while True:
        buf, p = _outbuf(cap)
        r = lib().tk_lz4f_decompress(data, len(data), p, cap)
        if r == -4 and cap < limit:      # output too small: grow and retry
            cap = min(cap * 4, limit)
            continue
        if r < 0:
            raise ValueError(f"lz4 frame decompress failed ({r})")
        return buf.raw[:r]


# ---------------------------------------------------------------- snappy ---

def snappy_compress(data: bytes) -> bytes:
    data = bytes(data)
    cap = lib().tk_snappy_bound(len(data))
    buf, p = _outbuf(cap)
    r = lib().tk_snappy_compress(data, len(data), p, cap)
    if r < 0:
        raise ValueError("snappy compress failed")
    return buf.raw[:r]


def snappy_decompress(data: bytes) -> bytes:
    data = bytes(data)
    size = lib().tk_snappy_uncompressed_length(data, len(data))
    if size < 0:
        raise ValueError("bad snappy preamble")
    buf, p = _outbuf(max(size, 1))
    r = lib().tk_snappy_decompress(data, len(data), p, size)
    if r != size:
        raise ValueError(f"snappy decompress failed ({r} != {size})")
    return buf.raw[:size]


SNAPPY_JAVA_MAGIC = b"\x82SNAPPY\x00"


def snappy_java_decompress(data: bytes) -> bytes:
    """Decompress snappy-java framed stream (magic + per-chunk blocks).

    Old Java producers emit this framing inside MessageSets; the reference
    detects and unframes it in rdkafka_msgset_reader.c (~:300).
    """
    if not isinstance(data, bytes):
        data = bytes(data)             # memoryview from the fetch path
    if not data.startswith(SNAPPY_JAVA_MAGIC):
        return snappy_decompress(data)
    out = io.BytesIO()
    i = len(SNAPPY_JAVA_MAGIC) + 8  # magic + version(4) + compatible(4)
    while i + 4 <= len(data):
        (chunk_len,) = struct.unpack(">i", data[i:i + 4])
        i += 4
        out.write(snappy_decompress(data[i:i + chunk_len]))
        i += chunk_len
    return out.getvalue()


# -------------------------------------------------------- record framing ---

def _frame_outbuf(cap: int):
    """Un-zeroed output buffer for the framer: create_string_buffer
    memsets its whole capacity and .raw copies it back out — ~2 MB of
    wasted traffic per 1 MB batch on the hot path (measured 0.9 us/msg).
    np.empty allocates without clearing; string_at extracts exactly the
    bytes written."""
    buf = np.empty(cap, dtype=np.uint8)
    return buf, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def frame_v2(base: bytes, klens: list[int], vlens: list[int],
             ts_deltas: list[int]) -> bytes:
    """Frame a batch of records into MessageSet v2 record wire layout in
    one native call (GIL released — framing overlaps the app thread).
    base = concatenated key||value bytes; klen/vlen -1 = null."""
    L = lib()
    count = len(klens)
    ka = np.array(klens, dtype=np.int32)
    va = np.array(vlens, dtype=np.int32)
    ta = np.array(ts_deltas, dtype=np.int64)
    cap = L.tk_frame_v2_bound(len(base), count)
    buf, p = _frame_outbuf(cap)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    r = L.tk_frame_v2(base, ka.ctypes.data_as(i32p),
                      va.ctypes.data_as(i32p), ta.ctypes.data_as(i64p),
                      count, p, cap)
    if r < 0:
        raise ValueError("tk_frame_v2 capacity shortfall")
    return ctypes.string_at(buf.ctypes.data, int(r))


def frame_v2_raw(base: bytes, klens: bytes, vlens: bytes,
                 count: int) -> bytes:
    """frame_v2 for the native enqueue lane: klens/vlens arrive as raw
    int32 arrays straight from the arena (no per-record Python work) and
    all timestamp deltas are zero (fast-lane records carry timestamp=0 =
    batch build time)."""
    L = lib()
    zeros = np.zeros(count, dtype=np.int64)
    cap = L.tk_frame_v2_bound(len(base), count)
    buf, p = _frame_outbuf(cap)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    ka = np.frombuffer(klens, dtype=np.int32)
    va = np.frombuffer(vlens, dtype=np.int32)
    r = L.tk_frame_v2(base, ka.ctypes.data_as(i32p),
                      va.ctypes.data_as(i32p), zeros.ctypes.data_as(i64p),
                      count, p, cap)
    if r < 0:
        raise ValueError("tk_frame_v2 capacity shortfall")
    return ctypes.string_at(buf.ctypes.data, int(r))


def frame_v2_run(base: bytes, klens: bytes, vlens: bytes, count: int,
                 now_ms: int, tss: bytes | None = None,
                 hbuf: bytes | None = None, hlens: bytes | None = None,
                 ) -> tuple[bytes, int, int]:
    """Run-native framing for widened arena runs: per-record explicit
    timestamps (raw int64 array; 0 = unset -> now_ms) and pre-encoded
    header blobs (hbuf concatenation + raw int32 lens) straight from the
    arena side buffers.  Returns (records, first_ts, max_ts) — the
    header timestamps the batch assembler needs."""
    L = lib()
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    ta = np.frombuffer(tss, dtype=np.int64) if tss is not None else None
    ha = np.frombuffer(hlens, dtype=np.int32) if hlens is not None else None
    cap = L.tk_frame_v2_bound(len(base) + (len(hbuf) if hbuf else 0), count)
    buf, p = _frame_outbuf(cap)
    ka = np.frombuffer(klens, dtype=np.int32)
    va = np.frombuffer(vlens, dtype=np.int32)
    first = ctypes.c_int64(now_ms)
    last = ctypes.c_int64(now_ms)
    r = L.tk_frame_v2_run(
        base, ka.ctypes.data_as(i32p), va.ctypes.data_as(i32p),
        ta.ctypes.data_as(i64p) if ta is not None else None,
        now_ms, hbuf, ha.ctypes.data_as(i32p) if ha is not None else None,
        count, p, cap, ctypes.byref(first), ctypes.byref(last))
    if r < 0:
        raise ValueError("tk_frame_v2_run capacity shortfall")
    return (ctypes.string_at(buf.ctypes.data, int(r)),
            int(first.value), int(last.value))


# ------------------------------------------------------------- gzip/zstd ---

def gzip_compress(data: bytes, level: int = -1) -> bytes:
    if level < 0:
        level = 6
    co = zlib.compressobj(level, zlib.DEFLATED, 31)  # 31 = gzip wrapper
    return co.compress(bytes(data)) + co.flush()


def gzip_decompress(data: bytes) -> bytes:
    return _gzip.decompress(bytes(data))


def zstd_compress(data: bytes, level: int = -1) -> bytes:
    import zstandard
    return zstandard.ZstdCompressor(level=level if level > 0 else 3).compress(bytes(data))


def zstd_decompress(data: bytes, size_hint: int = 0) -> bytes:
    import zstandard
    return zstandard.ZstdDecompressor().decompress(
        bytes(data), max_output_size=max(size_hint, 8 * len(data) + (1 << 20)))


# --------------------------------------------------------------- batched ---

def crc32c_many(buffers: list[bytes]) -> np.ndarray:
    """CRC32C of each buffer in one native call (the per-toppar batch axis)."""
    base = b"".join(bytes(b) for b in buffers)
    lens = np.array([len(b) for b in buffers], dtype=np.int64)
    offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
    out = np.zeros(len(buffers), dtype=np.uint32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    lib().tk_crc32c_many(base, offs.ctypes.data_as(i64p),
                         lens.ctypes.data_as(i64p),
                         out.ctypes.data_as(u32p), len(buffers))
    return out


def _compress_many_parallel(fn_name: str, bound_name: str,
                            bufs: list[bytes]) -> list[bytes]:
    """One native call compressing all buffers across a thread pool —
    the batch axis the reference's per-broker-thread design serializes."""
    if not bufs:
        return []
    L = lib()
    base = b"".join(bytes(b) for b in bufs)
    lens = np.array([len(b) for b in bufs], dtype=np.int64)
    offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
    bound = getattr(L, bound_name)
    caps = np.array([bound(int(n)) for n in lens], dtype=np.int64)
    out_offs = np.concatenate([[0], np.cumsum(caps)[:-1]]).astype(np.int64)
    # np.empty, not create_string_buffer: the latter memsets the whole
    # multi-MB slab before the encoder overwrites it anyway
    out = np.empty(int(caps.sum()), dtype=np.uint8)
    out_lens = np.zeros(len(bufs), dtype=np.int64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    getattr(L, fn_name)(
        base, offs.ctypes.data_as(i64p), lens.ctypes.data_as(i64p),
        len(bufs), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out_offs.ctypes.data_as(i64p), out_lens.ctypes.data_as(i64p), 0)
    res = []
    addr = out.ctypes.data
    for i in range(len(bufs)):
        r = int(out_lens[i])
        if r < 0:
            raise ValueError(f"{fn_name} item {i} failed ({r})")
        o = int(out_offs[i])
        # string_at copies just [o, o+r) — .raw would copy the WHOLE
        # output slab per item (O(n^2) bytes; measured 5x the encode
        # cost at 8x900KB batches)
        res.append(ctypes.string_at(addr + o, r))
    return res


def lz4f_compress_many(bufs: list[bytes], *,
                       deterministic: bool = False) -> list[bytes]:
    """Batched lz4 frame compress. The default is the fast-parse
    encoder (the reference likewise ships lz4's fast mode on its hot
    path, rdkafka_lz4.c); ``deterministic=True`` selects the insert-all
    greedy spec shared bit-for-bit with the TPU kernel."""
    fn = ("tk_lz4f_compress_many" if deterministic
          else "tk_lz4f_compress_many_fast")
    return _compress_many_parallel(fn, "tk_lz4f_bound", bufs)


def snappy_compress_many(bufs: list[bytes]) -> list[bytes]:
    return _compress_many_parallel("tk_snappy_compress_many",
                                   "tk_snappy_bound", bufs)


def _decompress_many_parallel(fn_name: str, bufs: list[bytes],
                              caps: list[int]) -> list[bytes | None]:
    """Batched native decompress; items that fail come back as None so
    the caller can fall back to the grow-and-retry single path."""
    if not bufs:
        return []
    L = lib()
    base = b"".join(bytes(b) for b in bufs)
    lens = np.array([len(b) for b in bufs], dtype=np.int64)
    offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
    caps_a = np.array([max(int(c), 1) for c in caps], dtype=np.int64)
    out_offs = np.concatenate([[0], np.cumsum(caps_a)[:-1]]).astype(np.int64)
    out = np.empty(max(int(caps_a.sum()), 1), dtype=np.uint8)
    out_lens = np.zeros(len(bufs), dtype=np.int64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    getattr(L, fn_name)(
        base, offs.ctypes.data_as(i64p), lens.ctypes.data_as(i64p),
        len(bufs), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out_offs.ctypes.data_as(i64p), caps_a.ctypes.data_as(i64p),
        out_lens.ctypes.data_as(i64p), 0)
    res: list[bytes | None] = []
    addr = out.ctypes.data
    for i in range(len(bufs)):
        r = int(out_lens[i])
        if r < 0:
            res.append(None)
        else:
            o = int(out_offs[i])
            res.append(ctypes.string_at(addr + o, r))  # not .raw: no O(n^2)
    return res


def lz4f_decompress_many(bufs: list[bytes],
                         size_hints: list[int] | None = None) -> list[bytes]:
    hints = size_hints or [0] * len(bufs)
    # trust a provided size hint; without one, a write-free native
    # sequence walk yields the EXACT size (the lz4 frame header carries
    # none with our FLG) — a guessed capacity on high-ratio batches
    # (40x is normal for templated payloads) fell through to the
    # grow-and-retry path, re-decoding each batch several times
    # (measured 390 MB/s effective vs 10.9 GB/s for the decoder proper)
    L = lib()
    caps = [h if h > 0 else 0 for h in hints]
    for i, b in enumerate(bufs):
        if caps[i] <= 0:
            sz = L.tk_lz4f_decompressed_size(bytes(b), len(b))
            caps[i] = sz if sz > 0 else 4 * len(b) + (1 << 16)
    out = _decompress_many_parallel("tk_lz4f_decompress_many", bufs, caps)
    return [o if o is not None else lz4_decompress(b, h)
            for o, b, h in zip(out, bufs, hints)]


def snappy_decompress_many(bufs: list[bytes]) -> list[bytes]:
    if not bufs:
        return []
    L = lib()
    caps = [L.tk_snappy_uncompressed_length(bytes(b), len(b)) for b in bufs]
    if any(c < 0 for c in caps):
        raise ValueError("bad snappy preamble")
    # preamble is untrusted network data sizing an allocation: clamp to
    # the format's max expansion before anything is decoded
    if any(c > 256 * len(b) + (64 << 10) for c, b in zip(caps, bufs)):
        raise ValueError("snappy preamble exceeds max expansion")
    out = _decompress_many_parallel("tk_snappy_decompress_many", bufs, caps)
    if any(o is None for o in out):
        raise ValueError("snappy decompress failed")
    return out  # type: ignore[return-value]


# codec registry: name -> (compress(data, level), decompress(data, size_hint))
CODECS = {
    "gzip": (lambda d, lvl=-1: gzip_compress(d, lvl),
             lambda d, hint=0: gzip_decompress(d)),
    "snappy": (lambda d, lvl=-1: snappy_compress(d),
               lambda d, hint=0: snappy_java_decompress(d)),
    "lz4": (lambda d, lvl=-1: lz4_compress(d),
            lambda d, hint=0: lz4_decompress(d, hint)),
    "zstd": (lambda d, lvl=-1: zstd_compress(d, lvl),
             lambda d, hint=0: zstd_decompress(d, hint)),
}


_EXT = None
_EXT_ERR = False


def _ext():
    """The tk_enqlane extension's batched codec entry points (no-join
    crc32c_many / in-place decompress_many), or None."""
    global _EXT, _EXT_ERR
    if _EXT is None and not _EXT_ERR:
        try:
            from .native.build import load_enqlane
            m = load_enqlane()
            _EXT = m if hasattr(m, "crc32c_many") else None
            if _EXT is None:
                _EXT_ERR = True
        except Exception:
            _EXT_ERR = True
    return _EXT


class CpuCodecProvider:
    """The msgset codec provider interface (SURVEY.md §7 stage 5).

    compress_many / decompress_many / crc32c_many over independent
    per-partition batches; the TPU provider (ops/tpu.py) implements the
    same interface with one vmapped device launch.
    """

    name = "cpu"

    def compress_many(self, codec: str, bufs: list[bytes], level: int = -1
                      ) -> list[bytes]:
        if not bufs:
            return []
        # lz4/snappy: ONE native call, batch parallelized across cores
        # (the per-toppar batch axis the reference serializes on its
        # broker threads, rdkafka_msgset_writer.c:1129)
        if codec == "lz4":
            return lz4f_compress_many(bufs)
        if codec == "snappy":
            return snappy_compress_many(bufs)
        comp = CODECS[codec][0]
        return [comp(b, level) for b in bufs]

    def decompress_many(self, codec: str, bufs: list[bytes],
                        size_hints: list[int] | None = None) -> list[bytes]:
        if not bufs:
            return []
        if codec in ("lz4", "snappy"):
            ext = _ext()
            if (ext is not None and codec == "snappy" and any(
                    bytes(b).startswith(SNAPPY_JAVA_MAGIC)
                    for b in bufs)):
                ext = None           # java framing: python reader below
            if ext is not None:
                out = ext.decompress_many(3 if codec == "lz4" else 2,
                                          bufs, size_hints)
                if None not in out:
                    return out
                # isolate failures through the grow-and-retry path
                return [o if o is not None else
                        self.decompress_one(codec, b, h)
                        for o, b, h in zip(
                            out, bufs,
                            size_hints or [0] * len(bufs))]
        if codec == "lz4":
            return lz4f_decompress_many(bufs, size_hints)
        if codec == "snappy" and not any(
                bytes(b).startswith(SNAPPY_JAVA_MAGIC) for b in bufs):
            return snappy_decompress_many(bufs)
        dec = CODECS[codec][1]
        hints = size_hints or [0] * len(bufs)
        return [dec(b, h) for b, h in zip(bufs, hints)]

    def decompress_one(self, codec: str, buf: bytes, hint: int = 0):
        return CODECS[codec][1](buf, hint)

    def crc32c_many(self, bufs: list[bytes]) -> list[int]:
        ext = _ext()
        if ext is not None:
            # per-buffer hardware CRC with no join copy (enqlane.cpp)
            return ext.crc32c_many(bufs)
        return [int(x) for x in crc32c_many(bufs)]

    # ------------------------------------------------ ticket-shaped seam --
    # The async offload submit interface, resolved eagerly: the work
    # runs synchronously right here (no dispatch thread — backend=cpu
    # spawns nothing), but callers get the same Ticket contract as the
    # TPU provider, so the broker's fetch/codec pipelines run ONE
    # submit/park/resolve code path for both backends and tier-1
    # exercises the pipelined path on every test run.

    def crc32c_submit(self, bufs: list[bytes]):
        from .engine import SyncTicket
        return SyncTicket(np.asarray(self.crc32c_many(bufs),
                                     dtype=np.uint32))

    def crc32_submit(self, bufs: list[bytes]):
        from .engine import SyncTicket
        return SyncTicket(np.asarray(self.crc32_many(bufs),
                                     dtype=np.uint32))

    def decompress_submit(self, codec: str, bufs: list[bytes],
                          size_hints: list[int] | None = None):
        from .engine import SyncTicket
        return SyncTicket(self.decompress_many(codec, bufs, size_hints))

    def fused_codec_id(self, codec: str) -> int | None:
        """Wire attribute id when the fused native batch builder
        (tk_enqlane.build_batch: frame+compress+CRC+header in one
        GIL-released call) is equivalent to this provider's 3-phase
        path for ``codec``; None keeps the 3-phase pipeline.  The
        fused lz4/snappy encoders are the same native functions
        compress_many dispatches to, so wire bytes are identical."""
        return {"none": 0, "snappy": 2, "lz4": 3}.get(codec)

    def crc32_many(self, bufs: list[bytes]) -> list[int]:
        """Legacy MsgVer0/1 zlib-poly CRC (reference: src/rdcrc32.c)."""
        import zlib
        return [zlib.crc32(bytes(b)) & 0xFFFFFFFF for b in bufs]
