"""The flagship batched codec step (single- and multi-chip entry).

``batched_codec_step(block_bytes, n_blocks)`` builds a jittable function
mapping ``(data[B, N] uint8, lens[B] int32)`` →
``(compressed[B, N+overhead] uint8, out_lens[B] int32, crcs[B] uint32)``:
a vmapped deterministic lz4 block encode plus the one-matmul MXU CRC32C
kernel over all B independent partition batches in one launch — the
shape the producer's device offload path feeds
(ops/tpu.py TpuCodecProvider; reference hot loops:
rdkafka_msgset_writer.c:1129, crc32c.c:39).
"""
from __future__ import annotations

import numpy as np


def batched_codec_step(block_bytes: int = 4096, n_blocks: int = 8):
    """Returns the jittable step fn for B=n_blocks batches of
    ``block_bytes`` each. Import cost is deferred so CPU-only installs
    never pay for jax."""
    import jax

    from ..ops.crc32c_jax import _crc_kernel, _pick_kl, _shift_tables
    from ..ops.lz4_jax import _lz4_block_one

    N, B = block_bytes, n_blocks
    K, L = _pick_kl(N)
    shift_tab = _shift_tables(L)

    def step(data, lens):
        out, olen = jax.vmap(
            lambda d, n: _lz4_block_one(d, n, N))(data, lens)
        crc = _crc_kernel(data.reshape(B, K, L), lens, shift_tab)
        return out, olen, crc

    return step


def pipelined_codec_step(engine, block_bytes: int = 4096,
                         n_blocks: int = 8):
    """Drive the fused batched codec step through the async offload
    engine (ops/engine.py): returns ``submit(data, lens) -> Ticket``.
    The engine's dispatch thread owns the launch and keeps up to its
    configured depth in flight, so a caller can overlap host-side batch
    prep of step *k+1* with step *k*'s device execution — the same
    double-buffered discipline the producer CRC seam uses.  Each ticket
    resolves to the host tuple ``(compressed, out_lens, crcs)`` via one
    bulk readback."""
    import jax

    step = jax.jit(batched_codec_step(block_bytes, n_blocks))

    def submit(data, lens):
        return engine.submit_compute(step, data, lens)

    return submit


def example_inputs(block_bytes: int = 4096, n_blocks: int = 8, seed: int = 0):
    """Deterministic example (data, lens) matching batched_codec_step."""
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 64, (n_blocks, block_bytes), dtype=np.uint8)
    lens = np.full((n_blocks,), block_bytes, dtype=np.int32)
    return data, lens
