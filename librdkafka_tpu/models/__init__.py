"""librdkafka_tpu.models — the flagship jittable codec-pipeline models.

A Kafka client has no neural "model"; the framework's equivalent of a
flagship model is the batched MessageSet codec step: many independent
per-partition batches encoded (lz4 block format) and checksummed
(CRC32C via the one-matmul MXU kernel) in ONE compiled launch — the TPU
offload of the reference's hot loops (rdkafka_msgset_writer.c:1129
compress, crc32c.c:39 checksum). ``__graft_entry__.entry()`` delegates
here.
"""
from .codec_step import (batched_codec_step, example_inputs,
                         pipelined_codec_step)

__all__ = ["batched_codec_step", "example_inputs",
           "pipelined_codec_step"]
