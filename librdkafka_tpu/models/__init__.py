"""librdkafka_tpu.models"""
