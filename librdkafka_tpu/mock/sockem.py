"""sockem — socket emulation / network shaping shim.

Rebuild of the reference's tests/sockem.c (805 LoC): a proxy interposed
on each broker connection via the client's ``connect_cb`` conf hook
(the reference interposes through ``socket_cb``/``connect_cb``,
rdkafka_conf.c), applying scriptable network conditions
(tests/sockem.h:63-75 semantics):

  - ``delay`` / ``jitter``: per-direction latency in ms
  - ``rate``: bandwidth cap in bytes/sec
  - ``max_write``: partial writes — at most N bytes forwarded per
    send(), so a request/response frame arrives in many small pieces
    (reference sockem.c "txsize"; exercises frame reassembly)
  - ``rx_drop`` / ``tx_drop``: one-direction partition — data in that
    direction (rx = broker->client, tx = client->broker) is silently
    discarded while set, the classic half-open network partition
  - ``kill()``: drop connections mid-flight (mid-request)

Settings apply live to established connections — the knob set can be
changed while requests are in flight, which is what the reference's
retry/timeout tests (0075-retry.c, 0088-produce_metadata_timeout.c,
0093-holb.c) are built on.

Usage::

    sockem = Sockem(delay=0)
    p = Producer({..., "connect_cb": sockem.connect_cb})
    ...
    sockem.set(delay=2000)      # all connections now add 2s latency
    sockem.kill_all()           # drop every connection mid-flight
"""
from __future__ import annotations

import random
import socket
import threading
import time
from typing import Optional

from ..analysis.locks import new_lock
from ..analysis.races import shared


class _Pump(threading.Thread):  # lint: ok shared-state
    """One direction of one proxied connection.

    shared-state pragma: the pump owns no mutable state of its own —
    it reads the em's live knobs (declared on Sockem) and the conn's
    dead flag (single close()-writer, benign stale read of one poll
    interval)."""

    def __init__(self, conn: "SockemConn", src: socket.socket,
                 dst: socket.socket, label: str):
        super().__init__(daemon=True, name=f"sockem-{label}")
        self.conn = conn
        self.src = src
        self.dst = dst
        self.label = label          # "tx" (client->broker) or "rx"

    def run(self):
        em = self.conn.em
        try:
            while not self.conn.dead:
                try:
                    data = self.src.recv(65536)
                except socket.timeout:
                    continue
                except OSError:
                    break
                if not data:
                    break
                # one-direction partition: silently discard this
                # direction's traffic while the drop flag is set (the
                # peer still sees an established connection — exactly a
                # half-open network partition, not a close)
                if (em.tx_drop if self.label == "tx" else em.rx_drop):
                    continue
                # latency: hold the chunk for delay ± jitter
                d = em.delay_s
                if em.jitter_s:
                    d += random.uniform(0, em.jitter_s)
                if d > 0:
                    time.sleep(d)
                # bandwidth cap: pace the write
                if em.rate > 0:
                    time.sleep(len(data) / em.rate)
                if self.conn.dead:
                    break
                # retry on send timeout: a momentarily-full socketpair
                # buffer must stall the pump, not kill the connection
                while data and not self.conn.dead:
                    # partial writes: cap each send at max_write bytes
                    # so one frame lands in many pieces (live-settable,
                    # like delay/rate — re-read every iteration)
                    mw = em.max_write
                    chunk = data[:mw] if mw > 0 else data
                    try:
                        n = self.dst.send(chunk)
                        data = data[n:]
                    except socket.timeout:
                        continue
                    except OSError:
                        return
                if data:
                    break
        finally:
            self.conn.close()


class SockemConn:
    """A proxied broker connection (reference: sockem_t)."""

    # relaxed: dead is written once by close() under sockem.conn; the
    # two pump threads poll it lock-free (a stale False costs one 0.1s
    # poll interval before the socket error surfaces anyway)
    dead = shared("sockem.conn.dead", relaxed=True)

    def __init__(self, em: "Sockem", real: socket.socket):
        self.em = em
        self.real = real
        # the socket handed to the broker thread and our end of it
        self.app_side, self.shim_side = socket.socketpair()
        self.dead = False
        self._lock = new_lock("sockem.conn")
        # short poll timeout so live setting changes & kills apply fast
        self.real.settimeout(0.1)
        self.shim_side.settimeout(0.1)
        self._up = _Pump(self, self.shim_side, self.real, "tx")
        self._down = _Pump(self, self.real, self.shim_side, "rx")
        self._up.start()
        self._down.start()

    def close(self):
        with self._lock:
            if self.dead:
                return
            self.dead = True
        for s in (self.real, self.shim_side):
            try:
                s.close()
            except OSError:
                pass
        # do NOT close app_side: the broker owns it and must observe the
        # peer-close (recv()==b"") itself, like a real dropped connection


class Sockem:
    """Factory + live control panel for emulated connections."""

    # relaxed: the live shaping knobs are written by the controlling
    # (test/chaos) thread via set() and read per-chunk by pump threads
    # — float/int/bool snapshots; applying a setting one chunk late is
    # within the emulation's contract.  conns mutations hold sockem.em.
    delay_s = shared("sockem.delay_s", relaxed=True)
    jitter_s = shared("sockem.jitter_s", relaxed=True)
    rate = shared("sockem.rate", relaxed=True)
    max_write = shared("sockem.max_write", relaxed=True)
    rx_drop = shared("sockem.rx_drop", relaxed=True)
    tx_drop = shared("sockem.tx_drop", relaxed=True)

    def __init__(self, *, delay_ms: float = 0, jitter_ms: float = 0,
                 rate_bps: int = 0, max_write: int = 0,
                 rx_drop: bool = False, tx_drop: bool = False):
        self.delay_s = delay_ms / 1000.0
        self.jitter_s = jitter_ms / 1000.0
        self.rate = rate_bps
        self.max_write = max_write
        self.rx_drop = rx_drop
        self.tx_drop = tx_drop
        self.conns: list[SockemConn] = []
        self._lock = new_lock("sockem.em")
        self.connect_count = 0

    # -------------------------------------------------------- live knobs --
    def set(self, *, delay_ms: Optional[float] = None,
            jitter_ms: Optional[float] = None,
            rate_bps: Optional[int] = None,
            max_write: Optional[int] = None,
            rx_drop: Optional[bool] = None,
            tx_drop: Optional[bool] = None) -> None:
        """Change conditions for all current and future connections
        (reference: sockem_set 'delay'/'jitter'/'rate', sockem.c)."""
        if delay_ms is not None:
            self.delay_s = delay_ms / 1000.0
        if jitter_ms is not None:
            self.jitter_s = jitter_ms / 1000.0
        if rate_bps is not None:
            self.rate = rate_bps
        if max_write is not None:
            self.max_write = max_write
        if rx_drop is not None:
            self.rx_drop = rx_drop
        if tx_drop is not None:
            self.tx_drop = tx_drop

    def kill_all(self) -> int:
        """Drop every live connection mid-flight. Returns count killed."""
        return self.kill()

    def kill(self, count: Optional[int] = None) -> int:
        """Drop live connections mid-flight, oldest (connect order)
        first; ``count=None`` kills all. Returns count killed."""
        with self._lock:
            conns = [c for c in self.conns if not c.dead]
        n = 0
        for c in conns if count is None else conns[:count]:
            c.close()
            n += 1
        self._gc()
        return n

    def _gc(self):
        with self._lock:
            self.conns = [c for c in self.conns if not c.dead]

    @property
    def live_connections(self) -> int:
        self._gc()
        with self._lock:
            return len(self.conns)

    # ------------------------------------------------------- conf hook ----
    def connect_cb(self, host: str, port: int, timeout: float
                   ) -> socket.socket:
        """Plug into client conf: ``{"connect_cb": sockem.connect_cb}``."""
        real = socket.create_connection((host, port), timeout=timeout)
        conn = SockemConn(self, real)
        with self._lock:
            self.conns.append(conn)
            self.connect_count += 1
        return conn.app_side
