"""Run the mock cluster as a standalone process — one-process mode and
the ISSUE-9 supervised **multi-process** mode.

One-process mode (the PR-1 interop/bench shape)::

    python -m librdkafka_tpu.mock.standalone [--brokers N]
        [--partitions N] [--topic NAME:PARTS ...]

prints ``bootstrap.servers`` on the first stdout line and serves until
killed: an external client gets brokers that do not share its
GIL/process, but all N brokers still live in THIS one interpreter.

Supervised mode (``--supervise``) is the out-of-process chaos tier::

    python -m librdkafka_tpu.mock.standalone --supervise --brokers 3

The parent becomes a **supervisor**: it holds the storage/controller
plane (a MockCluster on internal ports — the state an acks=all quorum
would preserve) and spawns one OS process per broker (`_relay.py`,
pure stdlib) binding that broker's PUBLIC port.  Faults then hit real
processes: ``kill -9`` loses half-written frames and refuses connects,
``SIGSTOP``/``SIGCONT`` model GC-pause/VM-freeze brownouts — none of
which the in-process tier can express (see CHAOS.md).

Handshake: the first stdout line is one JSON object::

    {"bootstrap": "127.0.0.1:p1,...", "control": <port>,
     "pid": <supervisor pid>, "brokers": {"1": {"port": p, "pid": pid}}}

Control plane: a line protocol on the control port — one command line
in, one JSON line out::

    kill9 <id>       SIGKILL broker <id>'s process, reap it, migrate
                     leadership+coordinator off it (reply carries pid,
                     exit status and the migration summary)
    stop <id>        SIGSTOP (freeze); cont <id> thaws
    restart <id>     respawn a killed broker on the SAME public port
    status           liveness/pids/ports/leaders/metadata_version
    coordinator <k>  coordinator broker for group/txn key <k>
    leader <t> <p> <b>   migrate partition leadership
    shutdown         kill every broker process and exit

Environment fault library (ISSUE 11 — faults a kill/stop schedule
cannot express; each maps to a chaos ``env_*`` verb):

    eio <id|0> <1|0>     disk-full/EIO window on the storage plane
                         (0 = every broker): Produce returns
                         KAFKA_STORAGE_ERROR until healed
    skew <id> <ms>       clock skew: broker <id>'s wall clock reads
                         <ms> off true (0 heals)
    rlimit <id> <bytes>  memory pressure: soft RLIMIT_AS on the
                         broker's relay process via prlimit
                         (0 restores infinity)

Observability verbs (ISSUE 20, OBSERVABILITY.md):

    trace <0|1>      rig-wide tracing: the supervisor's obs/trace.py
                     rings plus every relay's (relay stdin command)
    clock            reply carries mono_ns — the collector's offset
                     exchange (obs/collect.align_offset)
    trace_dump       the rig's whole merged-timeline contribution:
                     supervisor + per-relay ring dumps inline, relays
                     clock-aligned to the supervisor
    brownout <id> <json> asymmetric partition: forward one-direction
                         rx/tx drop + latency knobs to the relay's
                         stdin (see mock/_relay.py); all-zero heals

The supervisor exits on ``shutdown`` or when its stdin reaches EOF
(the launching ClusterHandle died) — and each relay watches ITS stdin
the same way, so no broker process can outlive the rig.
"""
from __future__ import annotations

import argparse
import json
import os
import selectors
import signal
import socket
import subprocess
import sys
import threading
import time

from ..analysis.locks import new_cond
from ..obs import collect as _obs_collect
from ..obs import trace as _trace
from .cluster import MockCluster

_RELAY = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "_relay.py")


class Supervisor:  # lint: ok shared-state
    """Parent of one relay OS process per broker; owns the MockCluster
    storage/controller plane and the line-protocol control socket.

    All child waits go through ``Popen.wait`` (reaper threads) or
    condvar waits — no sleep-polling anywhere in the wait paths.

    shared-state pragma: the proc/port/pid tables are mutated only
    under ``mock.supervisor`` (the condvar's lock serializes the ctl
    loop against the reaper threads); cross-PROCESS state is the relay
    handshake, not shared memory."""

    def __init__(self, num_brokers: int, topics=None,
                 default_partitions: int = 4, retention_bytes: int = 0):
        self.cluster = MockCluster(num_brokers=num_brokers, topics=topics,
                                   default_partitions=default_partitions,
                                   retention_bytes=retention_bytes)
        self.num_brokers = num_brokers
        self._cond = new_cond("mock.supervisor")
        self.procs: dict[int, subprocess.Popen] = {}
        self.public_ports: dict[int, int] = {}
        self.pids: dict[int, int] = {}
        self.exited: dict[int, int] = {}      # broker -> last exit status
        self.migrated: dict[int, list] = {}   # broker -> last kill summary
        self.down: set[int] = set()
        self.paused: set[int] = set()
        #: leftover relay-stdout bytes per broker (brownout acks)
        self._rbufs: dict[int, bytearray] = {}
        #: rig-side tracing (ISSUE 20): ``trace 1`` enables the
        #: supervisor's own rings AND every relay's (stdin command)
        self._tracing = False
        self.shutdown = threading.Event()

        for b in range(1, num_brokers + 1):
            self._spawn(b, 0)
        self._ctl_ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._ctl_ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._ctl_ls.bind(("127.0.0.1", 0))
        self._ctl_ls.listen(8)
        self._ctl_ls.setblocking(False)
        self.control_port = self._ctl_ls.getsockname()[1]
        self._ctl_thread = threading.Thread(target=self._ctl_loop,
                                            name="standalone-ctl",
                                            daemon=True)
        self._ctl_thread.start()

    # ------------------------------------------------------- lifecycle --
    def _spawn(self, b: int, port: int) -> dict:
        """Start broker ``b``'s relay process on ``port`` (0 =
        ephemeral) and register it; returns the relay handshake."""
        proc = subprocess.Popen(
            [sys.executable, _RELAY, "--broker-id", str(b),
             "--port", str(port),
             "--upstream", f"127.0.0.1:{self.cluster._ports[b]}"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL)
        line = proc.stdout.readline()
        if not line:
            rc = proc.wait()
            raise RuntimeError(f"broker {b} relay died at startup "
                               f"(exit {rc}, port {port})")
        hs = json.loads(line)
        with self._cond:
            self.procs[b] = proc
            self.public_ports[b] = hs["port"]
            self.pids[b] = hs["pid"]
            self.down.discard(b)
            self.exited.pop(b, None)
        self.cluster.set_advertised_port(b, hs["port"])
        threading.Thread(target=self._reap, args=(b, proc),
                         name=f"standalone-reap-{b}-{hs['pid']}",
                         daemon=True).start()
        if self._tracing:
            # a relay respawned mid-trace (restart verb) joins the
            # rig-wide trace session like its predecessor
            self._relay_cmd(b, {"trace": 1})
        return hs

    def _reap(self, b: int, proc: subprocess.Popen) -> None:
        """Blocks in ``Popen.wait`` until broker ``b``'s process dies
        (kill9 command or an outside ``kill -9 <pid>``), then runs the
        controller reaction: mark down, migrate leadership."""
        rc = proc.wait()
        with self._cond:
            if self.procs.get(b) is not proc:
                return          # already superseded by a restart
            self.exited[b] = rc if rc is not None else -1
            self.down.add(b)
            self.paused.discard(b)
        info = self.cluster.kill_broker(b)
        with self._cond:
            self.migrated[b] = [list(m) for m in info["migrated"]]
            self._cond.notify_all()

    def close(self) -> None:
        self.shutdown.set()
        if self._tracing:
            self._tracing = False
            _trace.disable()
        with self._cond:
            procs = dict(self.procs)
        for proc in procs.values():
            try:
                proc.kill()     # SIGKILL terminates stopped children too
            except (ProcessLookupError, OSError):
                pass
        for proc in procs.values():
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        self.cluster.stop()
        try:
            self._ctl_ls.close()
        except OSError:
            pass

    # --------------------------------------------------------- control --
    def handshake(self) -> dict:
        with self._cond:
            return {
                "bootstrap": ",".join(
                    f"127.0.0.1:{self.public_ports[b]}"
                    for b in sorted(self.public_ports)),
                "control": self.control_port,
                "pid": os.getpid(),
                "brokers": {str(b): {"port": self.public_ports[b],
                                     "pid": self.pids[b]}
                            for b in sorted(self.public_ports)},
            }

    def _cmd_kill9(self, b: int) -> dict:
        with self._cond:
            proc = self.procs.get(b)
            if proc is None or b in self.down:
                return {"error": f"broker {b} is not running"}
            pid = self.pids[b]
        try:
            proc.send_signal(signal.SIGKILL)    # kills SIGSTOPped ones too
        except (ProcessLookupError, OSError):
            pass
        with self._cond:
            ok = self._cond.wait_for(
                lambda: self.exited.get(b) is not None, timeout=15)
            if not ok:
                return {"error": f"broker {b} did not reap within 15s"}
            return {"ok": True, "broker": b, "pid": pid,
                    "exit": self.exited.get(b),
                    "migrated": self.migrated.get(b, [])}

    def _cmd_restart(self, b: int) -> dict:
        with self._cond:
            if b not in self.down:
                return {"error": f"broker {b} is not down"}
            port = self.public_ports[b]
        # storage plane first: the relay must find its upstream alive
        self.cluster.restart_broker(b)
        try:
            hs = self._spawn(b, port)
        except (RuntimeError, OSError) as e:
            self.cluster.set_broker_down(b, True)
            return {"error": f"restart failed: {e}"}
        return {"ok": True, "broker": b, "pid": hs["pid"],
                "port": hs["port"]}

    def _cmd_pause(self, b: int) -> dict:
        with self._cond:
            if self.procs.get(b) is None or b in self.down:
                return {"error": f"broker {b} is not running"}
            if b in self.paused:
                return {"ok": True, "broker": b, "skipped": "paused"}
            pid = self.pids[b]
            self.paused.add(b)
        try:
            os.kill(pid, signal.SIGSTOP)
        except (ProcessLookupError, OSError) as e:
            return {"error": f"SIGSTOP failed: {e}"}
        return {"ok": True, "broker": b, "pid": pid}

    def _cmd_cont(self, b: int) -> dict:
        with self._cond:
            if b not in self.paused:
                return {"ok": True, "broker": b, "skipped": "not_paused"}
            pid = self.pids[b]
            self.paused.discard(b)
        try:
            os.kill(pid, signal.SIGCONT)
        except (ProcessLookupError, OSError) as e:
            return {"error": f"SIGCONT failed: {e}"}
        return {"ok": True, "broker": b, "pid": pid}

    def _cmd_rlimit(self, b: int, nbytes: int) -> dict:
        """Memory pressure on broker ``b``'s relay process: lower its
        soft RLIMIT_AS (hard limit stays infinite so the verb heals
        without privileges).  ``nbytes=0`` restores infinity."""
        import resource
        with self._cond:
            if self.procs.get(b) is None or b in self.down:
                return {"error": f"broker {b} is not running"}
            pid = self.pids[b]
        soft = resource.RLIM_INFINITY if nbytes <= 0 else int(nbytes)
        try:
            old = resource.prlimit(pid, resource.RLIMIT_AS,
                                   (soft, resource.RLIM_INFINITY))
        except (OSError, ValueError) as e:
            return {"error": f"prlimit failed: {e}"}
        return {"ok": True, "broker": b, "pid": pid,
                "soft": -1 if soft == resource.RLIM_INFINITY else soft,
                "old_soft": (-1 if old[0] == resource.RLIM_INFINITY
                             else old[0])}

    def _cmd_brownout(self, b: int, knobs: dict) -> dict:
        """Asymmetric-partition brownout: forward the knob set to the
        relay's stdin and wait for its ack line.  Refused for paused
        brokers (a SIGSTOPped relay cannot ack — and SIGCONT would
        already be the right verb to end THAT fault)."""
        with self._cond:
            proc = self.procs.get(b)
            if proc is None or b in self.down:
                return {"error": f"broker {b} is not running"}
            if b in self.paused:
                return {"error": f"broker {b} is paused (SIGSTOP); "
                                 "cont it before a brownout"}
        line = json.dumps({"set": knobs},
                          separators=(",", ":")).encode() + b"\n"
        try:
            proc.stdin.write(line)
            proc.stdin.flush()
        except (OSError, ValueError) as e:
            return {"error": f"relay stdin write failed: {e}"}
        ack = self._read_relay_line(b, proc, timeout=5.0)
        if ack is None or not ack.get("ok"):
            return {"error": f"relay did not ack brownout: {ack}"}
        return {"ok": True, "broker": b, "knobs": ack.get("knobs")}

    def _relay_cmd(self, b: int, obj: dict, timeout: float = 5.0):
        """One JSON command to broker ``b``'s relay stdin, one ack line
        back (None when the relay is down/paused or never acks)."""
        with self._cond:
            proc = self.procs.get(b)
            if proc is None or b in self.down or b in self.paused:
                return None
        line = json.dumps(obj, separators=(",", ":")).encode() + b"\n"
        try:
            proc.stdin.write(line)
            proc.stdin.flush()
        except (OSError, ValueError):
            return None
        return self._read_relay_line(b, proc, timeout=timeout)

    def _cmd_trace(self, on: int) -> dict:
        """Rig-wide trace switch: the supervisor's rings plus a
        ``{"trace": n}`` command to every alive relay."""
        if on and not self._tracing:
            self._tracing = True
            _trace.enable()
        elif not on and self._tracing:
            self._tracing = False
            _trace.disable()
        with self._cond:
            alive = sorted(b for b in self.procs if b not in self.down)
        acks = {}
        for b in alive:
            ack = self._relay_cmd(b, {"trace": int(bool(on))})
            acks[str(b)] = bool(ack and ack.get("ok"))
        return {"ok": True, "trace": bool(on), "relays": acks}

    def _cmd_trace_dump(self) -> dict:
        """The rig's whole contribution to a merged timeline: the
        supervisor's ring dump plus every alive relay's, each relay
        clock-aligned to the SUPERVISOR via a stdin round trip (the
        collecting client aligns the supervisor to itself with the
        ``clock`` verb and adds the offsets)."""
        procs = [{"name": "supervisor", "pid": os.getpid(),
                  "offset_ns": 0, "err_ns": 0,
                  "events": (_trace.collect_events()
                             if self._tracing else [])}]
        with self._cond:
            alive = sorted(b for b in self.procs if b not in self.down)
        for b in alive:
            t_send = time.monotonic_ns()
            ck = self._relay_cmd(b, {"clock": 1})
            t_recv = time.monotonic_ns()
            dump = self._relay_cmd(b, {"trace_dump": 1}, timeout=10.0)
            if not dump or not dump.get("ok"):
                continue
            off = err = 0
            if ck and ck.get("ok"):
                off, err = _obs_collect.align_offset(
                    t_send, ck["mono_ns"], t_recv)
            procs.append({"name": f"relay-{b}", "pid": dump.get("pid"),
                          "offset_ns": off, "err_ns": err,
                          "events": dump.get("events", [])})
        return {"ok": True, "procs": procs}

    def _read_relay_line(self, b: int, proc, timeout: float):
        """One JSON line from the relay's stdout (raw fd + per-broker
        leftover buffer; the buffered handshake readline left nothing
        behind — the relay writes strictly one line per event)."""
        buf = self._rbufs.setdefault(b, bytearray())
        fd = proc.stdout.fileno()
        deadline = time.monotonic() + timeout
        sel = selectors.DefaultSelector()
        try:
            sel.register(fd, selectors.EVENT_READ)
        except (OSError, ValueError):
            return None
        try:
            while b"\n" not in buf:
                left = deadline - time.monotonic()
                if left <= 0 or not sel.select(timeout=left):
                    return None
                try:
                    chunk = os.read(fd, 4096)
                except OSError:
                    return None
                if not chunk:
                    return None
                buf += chunk
        finally:
            sel.close()
        raw, _, rest = bytes(buf).partition(b"\n")
        self._rbufs[b] = bytearray(rest)
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def _cmd_status(self) -> dict:
        with self._cond:
            snap = {
                "ok": True,
                "alive": sorted(set(range(1, self.num_brokers + 1))
                                - self.down),
                "down": sorted(self.down),
                "paused": sorted(self.paused),
                "brokers": {str(b): {"port": self.public_ports.get(b),
                                     "pid": self.pids.get(b)}
                            for b in range(1, self.num_brokers + 1)},
            }
        with self.cluster._lock:
            snap["controller"] = self.cluster.controller_id
            snap["metadata_version"] = self.cluster.metadata_version
            snap["topics"] = {t: [p.leader for p in parts]
                              for t, parts in self.cluster.topics.items()}
            snap["storage_err"] = sorted(self.cluster._storage_err)
            snap["clock_skews"] = {str(b): s for b, s in
                                   self.cluster._clock_skew_ms.items()}
        return snap

    def _dispatch(self, line: str) -> dict:
        parts = line.split()
        if not parts:
            return {"error": "empty command"}
        cmd, args = parts[0], parts[1:]
        try:
            if cmd == "kill9":
                return self._cmd_kill9(int(args[0]))
            if cmd == "stop":
                return self._cmd_pause(int(args[0]))
            if cmd == "cont":
                return self._cmd_cont(int(args[0]))
            if cmd == "restart":
                return self._cmd_restart(int(args[0]))
            if cmd == "status":
                return self._cmd_status()
            if cmd == "coordinator":
                return {"ok": True,
                        "broker": self.cluster.coordinator_for(args[0])}
            if cmd == "leader":
                self.cluster.set_partition_leader(
                    args[0], int(args[1]), int(args[2]))
                return {"ok": True}
            if cmd == "create_topic":
                self.cluster.create_topic(args[0], int(args[1]))
                return {"ok": True}
            if cmd == "eio":
                b = int(args[0])
                info = self.cluster.set_storage_error(
                    b or None, bool(int(args[1])))
                return {"ok": True, "broker": b, **info}
            if cmd == "skew":
                b = int(args[0])
                self.cluster.set_clock_skew(b, float(args[1]))
                return {"ok": True, "broker": b,
                        "skew_ms": float(args[1])}
            if cmd == "rlimit":
                return self._cmd_rlimit(int(args[0]), int(args[1]))
            if cmd == "brownout":
                return self._cmd_brownout(
                    int(args[0]), json.loads(" ".join(args[1:])))
            if cmd == "trace":
                return self._cmd_trace(int(args[0]))
            if cmd == "clock":
                return {"ok": True, "mono_ns": time.monotonic_ns()}
            if cmd == "trace_dump":
                return self._cmd_trace_dump()
            if cmd == "shutdown":
                self.shutdown.set()
                return {"ok": True, "bye": True}
        except (ValueError, IndexError, KeyError) as e:
            return {"error": f"{cmd}: {e!r}"}
        return {"error": f"unknown command {cmd!r}"}

    def _ctl_loop(self) -> None:
        sel = selectors.DefaultSelector()
        sel.register(self._ctl_ls, selectors.EVENT_READ, "accept")
        bufs: dict[socket.socket, bytearray] = {}
        while not self.shutdown.is_set():
            try:
                events = sel.select(timeout=0.2)
            except OSError:
                break
            for key, _mask in events:
                if key.data == "accept":
                    try:
                        s, _ = self._ctl_ls.accept()
                    except OSError:
                        continue
                    bufs[s] = bytearray()
                    sel.register(s, selectors.EVENT_READ, "conn")
                    continue
                s = key.fileobj
                try:
                    data = s.recv(4096)
                except OSError:
                    data = b""
                if not data:
                    try:
                        sel.unregister(s)
                    except (KeyError, ValueError):
                        pass
                    s.close()
                    bufs.pop(s, None)
                    continue
                bufs[s] += data
                while b"\n" in bufs[s]:
                    raw, _, rest = bytes(bufs[s]).partition(b"\n")
                    bufs[s] = bytearray(rest)
                    line_s = raw.decode(errors="replace").strip()
                    t0 = _trace.now() if _trace.enabled else 0
                    resp = self._dispatch(line_s)
                    if t0:
                        _trace.complete(
                            "rig", "ctl_cmd", t0,
                            {"cmd": line_s.split()[0] if line_s else ""})
                    try:
                        s.sendall(json.dumps(resp).encode() + b"\n")
                    except OSError:
                        pass


def _supervise_main(args) -> int:
    topics = {}
    for spec in args.topic:
        name, _, parts = spec.partition(":")
        topics[name] = int(parts or args.partitions)
    sup = Supervisor(num_brokers=args.brokers, topics=topics or None,
                     default_partitions=args.partitions,
                     retention_bytes=args.retention_mb << 20)
    print(json.dumps(sup.handshake()), flush=True)

    def _stdin_watch():
        try:
            while sys.stdin.buffer.read(4096):
                pass
        except (OSError, ValueError):
            pass
        sup.shutdown.set()

    threading.Thread(target=_stdin_watch, name="standalone-stdin",
                     daemon=True).start()
    try:
        sup.shutdown.wait()
    except KeyboardInterrupt:
        pass
    finally:
        sup.close()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--brokers", type=int, default=1)
    ap.add_argument("--partitions", type=int, default=4)
    ap.add_argument("--topic", action="append", default=[],
                    metavar="NAME:PARTS")
    ap.add_argument("--seconds", type=float, default=0,
                    help="exit after this long (0 = run until killed; "
                         "one-process mode only)")
    ap.add_argument("--retention-mb", type=int, default=0,
                    help="per-partition log retention cap in MB "
                         "(0 = unbounded)")
    ap.add_argument("--supervise", action="store_true",
                    help="multi-process mode: one OS process per broker "
                         "+ a control socket (the out-of-process chaos "
                         "tier; see CHAOS.md)")
    args = ap.parse_args(argv)

    if args.supervise:
        return _supervise_main(args)

    topics = {}
    for spec in args.topic:
        name, _, parts = spec.partition(":")
        topics[name] = int(parts or args.partitions)

    cluster = MockCluster(num_brokers=args.brokers,
                          topics=topics or None,
                          default_partitions=args.partitions,
                          retention_bytes=args.retention_mb << 20)
    print(cluster.bootstrap_servers(), flush=True)
    try:
        parent = os.getppid()
        deadline = time.monotonic() + args.seconds if args.seconds else None
        while deadline is None or time.monotonic() < deadline:
            time.sleep(0.5)
            # a SIGKILLed parent (bench timeout, crashed harness)
            # reparents us to init: exit instead of lingering as an
            # orphan eating the benchmark host's CPU
            if os.getppid() != parent:
                break
    except KeyboardInterrupt:
        pass
    finally:
        cluster.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
