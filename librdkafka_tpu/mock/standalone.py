"""Run the mock cluster as a standalone process.

    python -m librdkafka_tpu.mock.standalone [--brokers N]
        [--partitions N] [--topic NAME:PARTS ...]

Prints ``bootstrap.servers`` on the first stdout line, then serves
until killed (or until --seconds elapses). This is how external
processes — the reference's rdkafka_performance in the interop tier,
the benchmark's producer, or any client under test — get a broker that
does NOT share the client's GIL/process (the role a real Kafka broker
plays for the reference's test rig)."""
from __future__ import annotations

import argparse
import sys
import time

from .cluster import MockCluster


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--brokers", type=int, default=1)
    ap.add_argument("--partitions", type=int, default=4)
    ap.add_argument("--topic", action="append", default=[],
                    metavar="NAME:PARTS")
    ap.add_argument("--seconds", type=float, default=0,
                    help="exit after this long (0 = run until killed)")
    ap.add_argument("--retention-mb", type=int, default=0,
                    help="per-partition log retention cap in MB "
                         "(0 = unbounded)")
    args = ap.parse_args(argv)

    topics = {}
    for spec in args.topic:
        name, _, parts = spec.partition(":")
        topics[name] = int(parts or args.partitions)

    cluster = MockCluster(num_brokers=args.brokers,
                          topics=topics or None,
                          default_partitions=args.partitions,
                          retention_bytes=args.retention_mb << 20)
    print(cluster.bootstrap_servers(), flush=True)
    try:
        import os
        parent = os.getppid()
        deadline = time.monotonic() + args.seconds if args.seconds else None
        while deadline is None or time.monotonic() < deadline:
            time.sleep(0.5)
            # a SIGKILLed parent (bench timeout, crashed harness)
            # reparents us to init: exit instead of lingering as an
            # orphan eating the benchmark host's CPU
            if os.getppid() != parent:
                break
    except KeyboardInterrupt:
        pass
    finally:
        cluster.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
