"""In-process mock Kafka cluster.

The rebuild of the reference's mock broker (src/rdkafka_mock.c:1772 +
rdkafka_mock_handlers.c:1483): real TCP listeners per mock broker served
from one cluster thread, an in-memory log that stores produced MessageSets
**verbatim as byte blobs** (rdkafka_mock_int.h:93-100) and returns them to
Fetch — so producer wire bytes are round-trippable and byte-comparable —
plus scriptable fault injection (per-ApiKey error stacks, RTT delays,
leader changes, coordinator selection; reference rdkafka_mock.c:1382-1445).

Created implicitly by ``test.mock.num.brokers`` in client config, or
directly via ``MockCluster(num_brokers=3)``.
"""
from __future__ import annotations

import selectors
import socket
import ssl as _ssl
import struct
import threading
import time
import zlib
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..client.errors import Err
from ..protocol import apis, proto
from ..protocol.apis import APIS
from ..protocol.msgset import read_batch_header
from ..utils import sockbuf
from ..protocol.proto import ApiKey
from ..utils.buf import Slice
from ..analysis import lockdep as _lockdep
from ..analysis.locks import new_rlock
from ..analysis.races import shared_dict

_TOPIC_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-")


def _valid_topic_name(name: str) -> bool:
    """Kafka topic-name rules (broker-side validation the real cluster
    applies): 1-249 chars of [a-zA-Z0-9._-], not '.'/'..'."""
    return (0 < len(name) <= 249 and name not in (".", "..")
            and set(name) <= _TOPIC_CHARS)


@dataclass
class MockPartition:
    topic: str
    id: int
    leader: int
    replicas: list[int]
    start_offset: int = 0
    end_offset: int = 0
    # the log: (base_offset, raw_messageset_bytes)
    log: list[tuple[int, bytes]] = field(default_factory=list)
    # idempotence: (pid, epoch) -> next expected base sequence
    pid_seqs: dict[tuple[int, int], int] = field(default_factory=dict)
    # size-based retention (real brokers: log.retention.bytes); 0 = keep
    # everything. Oldest batches are dropped and start_offset advances.
    retention_bytes: int = 0
    log_bytes: int = 0
    # KIP-392: broker id nominated as preferred read replica for v11+
    # consumer fetches (None = leader serves); the reference mock's
    # rd_kafka_mock_partition_set_follower equivalent
    follower_id: Optional[int] = None
    # aborted-transaction index: [{"producer_id", "first_offset",
    # "last_offset"}] — reported to read_committed fetches whose range
    # overlaps (real brokers: the .txnindex sidecar file)
    aborted: list = field(default_factory=list)
    # open (un-ended) transactions touching this partition:
    # pid -> first data offset; bounds the last stable offset
    open_txns: dict = field(default_factory=dict)

    def lso(self) -> int:
        """Last stable offset: first offset still inside an open
        transaction, or the log end when none is open."""
        if self.open_txns:
            return min(self.open_txns.values())
        return self.end_offset

    def append(self, blob: bytes) -> int:
        """Append a produced MessageSet verbatim; returns assigned base
        offset. v2 blobs get their BaseOffset field patched (outside the
        CRC'd region), exactly what a real broker does."""
        base = self.end_offset
        count = 1
        if len(blob) >= proto.V2_HEADER_SIZE and blob[proto.V2_OF_Magic] == 2:
            blob = struct.pack(">q", base) + blob[8:]
            count = struct.unpack(
                ">i", blob[proto.V2_OF_RecordCount:proto.V2_OF_RecordCount + 4])[0]
        else:
            # legacy v0/v1: count messages by walking the set
            count = 0
            sl = Slice(blob)
            while sl.remains() >= 12:
                sl.skip(8)
                sz = sl.read_i32()
                if sl.remains() < sz:
                    break
                sl.skip(sz)
                count += 1
            count = max(count, 1)
        self.log.append((base, blob))
        self.log_bytes += len(blob)
        self.end_offset = base + count
        if self.retention_bytes > 0:
            while len(self.log) > 1 and self.log_bytes > self.retention_bytes:
                _old_base, old_blob = self.log.pop(0)
                self.log_bytes -= len(old_blob)
                self.start_offset = self.log[0][0]
        return base

    def read_from(self, offset: int, max_bytes: int,
                  max_offset: Optional[int] = None) -> bytes:
        """``max_offset`` caps the read below the LSO for
        read_committed fetches: batches of a still-open transaction
        must not reach isolation-level-1 consumers (real brokers stop
        at the last stable offset)."""
        out = bytearray()
        for base, blob in self.log:
            # include any blob whose range covers/starts-after the offset
            if base + self._blob_count(blob) <= offset:
                continue
            if max_offset is not None and base >= max_offset:
                break
            out += blob
            if len(out) >= max_bytes:
                break
        return bytes(out)

    @staticmethod
    def _blob_count(blob: bytes) -> int:
        if len(blob) >= proto.V2_HEADER_SIZE and blob[proto.V2_OF_Magic] == 2:
            return struct.unpack(
                ">i", blob[proto.V2_OF_RecordCount:proto.V2_OF_RecordCount + 4])[0]
        return 1


@dataclass
class GroupMember:
    member_id: str
    client_id: str
    client_host: str
    protocols: list[tuple[str, bytes]] = field(default_factory=list)
    assignment: bytes = b""
    metadata: bytes = b""
    last_heartbeat: float = field(default_factory=time.monotonic)
    session_timeout_ms: int = 10000
    # connection wanting the pending JoinGroup response: (conn, corrid)
    pending_join: Optional[tuple] = None


@dataclass
class MockGroup:
    group_id: str
    state: str = "Empty"   # Empty/PreparingRebalance/CompletingRebalance/Stable
    generation: int = 0
    protocol_type: str = ""
    protocol: str = ""
    leader: str = ""
    members: dict[str, GroupMember] = field(default_factory=dict)
    offsets: dict[tuple[str, int], tuple[int, Optional[str]]] = field(default_factory=dict)
    rebalance_deadline: float = 0.0
    # KIP-134 initial-rebalance hold: the first generation of a fresh
    # group stays open until this stamp (see MockCluster
    # group_initial_rebalance_delay_ms)
    hold_until: float = 0.0
    pending_syncs: list[tuple] = field(default_factory=list)  # (conn, corrid, member_id)
    # ownership book (ISSUE 12): (topic, partition) -> member_id as of
    # the LAST completed sync, plus the cooperative-protocol violations
    # the validator caught — a partition handed to a new owner in the
    # same generation its old owner still held it (KIP-429 forbids the
    # move without an intermediate revoke generation), or double-owned
    # within one generation.  Tests assert the list stays empty.
    owned: dict[tuple[str, int], str] = field(default_factory=dict)
    validation_errors: list[dict] = field(default_factory=list)


@dataclass
class MockTransaction:
    """Transaction-coordinator state for one transactional.id
    (reference: the 2.x broker's TransactionMetadata; the v1.3.0 mock
    has no coordinator role at all)."""
    tid: str
    pid: int
    epoch: int = -1
    state: str = "Empty"   # Empty/Ongoing/CompleteCommit/CompleteAbort
    # (topic, partition) -> first data offset of the CURRENT txn
    # (None until the first transactional batch lands there)
    partitions: dict = field(default_factory=dict)
    groups: set = field(default_factory=set)
    # group -> {(topic, partition): (offset, metadata)} staged by
    # TxnOffsetCommit, applied to the group at EndTxn(commit)
    pending_offsets: dict = field(default_factory=dict)


class _Conn:
    def __init__(self, sock: socket.socket, broker_id: int):
        self.sock = sock
        self.broker_id = broker_id
        self.rbuf = bytearray()
        self.wbuf = bytearray()
        self.wbuf_off = 0           # consumed prefix (offset send)
        self.closed = False
        self.handshaking = False    # TLS handshake in progress
        self.sasl_mech = ""         # mechanism from SaslHandshake
        self.scram = None           # server-side SCRAM exchange state


class MockCluster:
    """In-process fake Kafka cluster over real localhost TCP sockets."""

    def __init__(self, num_brokers: int = 3, topics: Optional[dict] = None,
                 auto_create_topics: bool = True, default_partitions: int = 4,
                 tls: Optional[dict] = None,
                 sasl_users: Optional[dict] = None,
                 broker_version: Optional[str] = None,
                 retention_bytes: int = 0,
                 group_initial_rebalance_delay_ms: int = 0):
        """``group_initial_rebalance_delay_ms``: real brokers hold a
        brand-new (Empty) group's FIRST rebalance open for
        ``group.initial.rebalance.delay.ms`` (default 3000 there, 0
        here to keep tests instant) so a starting fleet joins one
        generation instead of the first member grabbing every
        partition and immediately redistributing — exactly the
        mass-move the cooperative assignor otherwise pays for.

        ``tls``: enable the TLS listener mode —
        ``{"certfile": ..., "keyfile": ..., "cafile": ...,
        "require_client_cert": bool}``. All mock brokers then speak TLS
        (like a real cluster with an SSL listener); clients must set
        ``security.protocol=ssl``/``sasl_ssl``.

        ``sasl_users``: ``{username: password}`` credential table. When
        set, PLAIN checks credentials and SCRAM runs the full RFC 5802
        server-side exchange (salted PBKDF2 verifier, client-proof
        verification, server signature); when None, PLAIN accepts any
        non-empty credentials and SCRAM is rejected (the server needs a
        real password to derive keys)."""
        self.num_brokers = num_brokers
        self.sasl_users = sasl_users
        # emulate an old broker: closes the connection on ApiVersions
        # when < 0.10 (the real pre-0.10 behavior clients must survive)
        self.broker_version = broker_version
        if broker_version is not None:
            from ..client.feature import _parse_version
            self._bv_tuple = _parse_version(broker_version)
        self._tls_ctx = None
        if tls:
            from ..client.tls import make_server_ctx
            self._tls_ctx = make_server_ctx(
                tls["certfile"], tls["keyfile"], tls.get("cafile"),
                tls.get("require_client_cert", False))
        self.auto_create_topics = auto_create_topics
        self.default_partitions = default_partitions
        # per-partition size retention for long-running/benchmark use
        # (real brokers: log.retention.bytes); 0 keeps everything
        self.retention_bytes = retention_bytes
        self.group_initial_delay_s = group_initial_rebalance_delay_ms \
            / 1000.0
        # the cluster tables are declared shared (analysis/races.py),
        # RELAXED with one justification: every handler and chaos
        # controller hook (kill/restart/migrate from the scheduler
        # thread) mutates them under mock.cluster, but tests are the
        # mock's second client — the driver thread inspects
        # ``cluster.topics[...]`` / ``cluster.groups[...]`` lock-free
        # by design (snapshot peeks of a test fixture).  The sweep
        # still tracks them, so a genuinely unlocked HANDLER mutation
        # shows up in the relaxed report's stacks.
        self.topics: dict[str, list[MockPartition]] = \
            shared_dict("mock.topics", relaxed=True)
        self.groups: dict[str, MockGroup] = \
            shared_dict("mock.groups", relaxed=True)
        self.cluster_id = "mockCluster"
        self.controller_id = 1
        self._next_pid = 1
        # transaction-coordinator role: per-transactional.id state +
        # the pid -> tid reverse map the Produce path fences through
        self.transactions: dict[str, MockTransaction] = \
            shared_dict("mock.transactions", relaxed=True)
        self._pid_tid: dict[int, str] = \
            shared_dict("mock.pid_tid", relaxed=True)
        # KIP-227 incremental fetch session cache (ISSUE 14): one entry
        # per negotiated session — {session_id: {broker, epoch, book,
        # last}} where `book` is the per-session partition state
        # {(topic, partition): {fetch_offset, max_bytes}} and `epoch`
        # the NEXT expected request epoch.  Bounded (LRU eviction at
        # fetch_session_slots, like a real broker's
        # max.incremental.fetch.session.cache.slots); a broker's
        # sessions die with it (set_broker_down) — the cache is broker
        # memory, which is exactly what the chaos kill tests assert.
        self._fetch_sessions: dict[int, dict] = \
            shared_dict("mock.fetch_sessions", relaxed=True)
        self._next_session_id = 1
        self.fetch_session_slots = 1000
        self._lock = new_rlock("mock.cluster")
        # fault injection
        self._err_stacks: dict[int, deque] = defaultdict(deque)
        self._rtt_ms: dict[int, float] = {}           # broker_id -> delay
        self._throttle_ms: dict[int, int] = {}        # broker_id -> report
        self._down: set[int] = set()
        # SIGSTOP analog (chaos proc_pause): a paused broker stops
        # reading and writing but its listener stays bound — connects
        # succeed (kernel backlog) and then freeze, exactly what a
        # GC-paused/VM-frozen broker looks like from the client
        self._paused: set[int] = set()
        # environment fault library (ISSUE 11): brokers whose storage
        # plane is "full"/EIO — every Produce they lead returns
        # KAFKA_STORAGE_ERROR (retriable: real brokers do exactly this
        # on a failed log dir) until the window heals
        self._storage_err: set[int] = set()
        # per-broker wall-clock skew in ms, reflected in every
        # timestamp this broker reports (log_append_time, ListOffsets)
        self._clock_skew_ms: dict[int, float] = {}
        # out-of-process tier: the standalone supervisor fronts each
        # internal listener with a relay OS process on a public port;
        # metadata/FindCoordinator must advertise THAT port or clients
        # would bypass the killable process entirely
        self._advertised: dict[int, int] = {}
        self.request_log: list[tuple[int, int]] = []  # (broker_id, api_key)
        # AlterConfigs store: (resource_type, name) -> {conf: value}
        self._resource_configs: dict[tuple, dict] = {}

        self._listeners: dict[int, socket.socket] = {}
        self._ports: dict[int, int] = {}
        self._sel = selectors.DefaultSelector()
        self._conns: list[_Conn] = []
        # deferred work: (due_monotonic, callable)
        self._deferred: list[tuple[float, Callable]] = []
        # parked fetches: (deadline, conn, corrid, parsed_request)
        self._parked_fetches: list = []
        self._stop = threading.Event()
        # controller bookkeeping: bumped on every leadership /
        # broker-liveness change (a real controller bumps the metadata
        # epoch; clients here refresh via NOT_LEADER/connection errors,
        # tests and the chaos oracle observe this counter)
        self.metadata_version = 1

        for b in range(1, num_brokers + 1):
            self._open_listener(b)

        if topics:
            for name, nparts in topics.items():
                self.create_topic(name, nparts)

        self._thread = threading.Thread(target=self._run, name="mock-cluster",
                                        daemon=True)
        self._thread.start()

    def _open_listener(self, broker_id: int) -> None:
        """Bind + register broker ``broker_id``'s TCP listener. First
        call picks an ephemeral port; later calls (broker restart)
        rebind the SAME port so clients' cached metadata stays valid."""
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind(("127.0.0.1", self._ports.get(broker_id, 0)))
        ls.listen(64)
        ls.setblocking(False)
        self._listeners[broker_id] = ls
        self._ports[broker_id] = ls.getsockname()[1]
        self._sel.register(ls, selectors.EVENT_READ, ("accept", broker_id))

    def _close_listener(self, broker_id: int) -> None:
        ls = self._listeners.get(broker_id)
        if ls is None:
            return
        try:
            self._sel.unregister(ls)
        except (KeyError, ValueError):
            pass
        try:
            ls.close()
        except OSError:
            pass
        del self._listeners[broker_id]

    # ------------------------------------------------------------- public --
    def bootstrap_servers(self) -> str:
        return ",".join(f"127.0.0.1:{self.advertised_port(b)}"
                        for b in self._ports)

    def advertised_port(self, broker_id: int) -> int:
        """The port clients should be told about: the broker's relay
        process port in the out-of-process tier, else its own."""
        return self._advertised.get(broker_id, self._ports[broker_id])

    def set_advertised_port(self, broker_id: int, port: int) -> None:
        with self._lock:
            self._advertised[broker_id] = port

    def create_topic(self, name: str, partitions: int = None,
                     replication: int = 1) -> None:
        with self._lock:
            if name in self.topics:
                return
            n = partitions or self.default_partitions
            self.topics[name] = [self._new_partition(name, i)
                                 for i in range(n)]

    def _new_partition(self, topic: str, i: int) -> MockPartition:
        leader = (i % self.num_brokers) + 1
        if leader in self._down:
            # a topic created mid-storm must not be born with a dead
            # leader — place it on the next alive broker in the ring
            leader = self._next_alive(leader) or leader
        return MockPartition(topic=topic, id=i,
                             leader=leader, replicas=[leader],
                             retention_bytes=self.retention_bytes)

    def _next_alive(self, after: int) -> Optional[int]:
        """Next alive broker in ring order after ``after``; None when
        every broker is down."""
        for k in range(1, self.num_brokers + 1):
            b = ((after - 1 + k) % self.num_brokers) + 1
            if b not in self._down:
                return b
        return None

    def alive_brokers(self) -> list[int]:
        with self._lock:
            return [b for b in range(1, self.num_brokers + 1)
                    if b not in self._down]

    def partition(self, topic: str, part: int) -> MockPartition:
        return self.topics[topic][part]

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
        for ls in self._listeners.values():
            ls.close()
        for c in self._conns:
            try:
                c.sock.close()
            except OSError:
                pass

    # -- fault injection (reference: rd_kafka_mock_push_request_errors etc) --
    def push_request_errors(self, api: ApiKey, errors: list[Err]) -> None:
        with self._lock:
            self._err_stacks[int(api)].extend(errors)

    def set_rtt(self, broker_id: int, rtt_ms: float) -> None:
        self._rtt_ms[broker_id] = rtt_ms

    def set_broker_throttle(self, broker_id: int, throttle_ms: int) -> None:
        """Report this throttle_time in every response from the broker
        (reference rd_kafka_mock throttle injection)."""
        with self._lock:
            self._throttle_ms[broker_id] = throttle_ms

    def set_broker_down(self, broker_id: int, down: bool = True) -> None:
        """Take a broker down (or back up). Down means the LISTENER is
        closed — new connects get ECONNREFUSED, so clients exercise the
        real connect-retry/backoff path — and every established
        connection is dropped mid-flight. Up rebinds the same port.

        This is liveness only; ``kill_broker`` adds the controller's
        reaction (leadership + coordinator reassignment)."""
        with self._lock:
            if down:
                if broker_id in self._down:
                    return
                self._paused.discard(broker_id)     # SIGKILL beats SIGSTOP
                self._down.add(broker_id)
                self._close_listener(broker_id)
                for c in list(self._conns):
                    if c.broker_id == broker_id:
                        self._close(c)
                # fetch sessions are broker MEMORY: they die with the
                # broker — a reconnecting client's incremental fetch
                # gets FETCH_SESSION_ID_NOT_FOUND and renegotiates
                for sid in [sid for sid, s in self._fetch_sessions.items()
                            if s["broker"] == broker_id]:
                    del self._fetch_sessions[sid]
            else:
                if broker_id not in self._down:
                    return
                self._down.discard(broker_id)
                self._open_listener(broker_id)
            self.metadata_version += 1

    # ------------------------------- controller role (chaos subsystem) ----
    def kill_broker(self, broker_id: int) -> dict:
        """Broker death as the controller sees it: close the listener
        (new connects refused), drop in-flight connections, and move
        partition leadership + controller id off the dead broker onto
        alive replicas (coordinator placement follows automatically —
        ``coordinator_for`` only ever names alive brokers). Returns a
        summary dict (migrated leaders) for chaos timelines/tests."""
        migrated = []
        self.set_broker_down(broker_id, True)
        with self._lock:
            for tname, parts in self.topics.items():
                for p in parts:
                    if p.leader != broker_id:
                        continue
                    new = next((r for r in p.replicas
                                if r not in self._down), None)
                    new = new or self._next_alive(broker_id)
                    if new is None:
                        continue        # whole cluster is down
                    p.leader = new
                    if new not in p.replicas:
                        p.replicas.append(new)
                    migrated.append((tname, p.id, broker_id, new))
            if self.controller_id == broker_id:
                self.controller_id = self._next_alive(broker_id) or broker_id
            self.metadata_version += 1
        return {"broker": broker_id, "migrated": migrated}

    def restart_broker(self, broker_id: int) -> dict:
        """Bring a killed broker back: rebind its listener on the same
        port. Leadership stays where the kill moved it (a real cluster
        fails back only on preferred-leader election, which a chaos
        schedule scripts explicitly via ``leader_migrate``)."""
        self.set_broker_down(broker_id, False)
        return {"broker": broker_id}

    def kill9(self, broker_id: int) -> dict:
        """In-process stand-in for the chaos ``proc_kill9`` verb: same
        controller reaction as ``kill_broker``.  The out-of-process
        tier (``mock/external.py`` ClusterHandle) implements the same
        method with a real ``SIGKILL`` of the broker's relay process —
        the schedule DSL targets whichever cluster object it was given
        through this one name."""
        return self.kill_broker(broker_id)

    def pause_broker(self, broker_id: int) -> dict:
        """SIGSTOP analog (chaos ``proc_pause``): freeze the broker —
        stop reading its connections and flushing its responses, stop
        accepting (pending connects sit in the kernel backlog exactly
        as they would against a SIGSTOPped process).  Metadata still
        advertises it: a GC-paused broker is alive, just unresponsive,
        so clients walk the request-timeout path, not connect-refused.
        The out-of-process tier sends a real ``SIGSTOP``."""
        with self._lock:
            if broker_id in self._paused or broker_id in self._down:
                return {"broker": broker_id, "skipped": True}
            self._paused.add(broker_id)
            ls = self._listeners.get(broker_id)
            if ls is not None:
                try:
                    self._sel.unregister(ls)
                except (KeyError, ValueError):
                    pass
            for c in self._conns:
                if c.broker_id == broker_id and not c.closed:
                    try:
                        self._sel.unregister(c.sock)
                    except (KeyError, ValueError):
                        pass
        return {"broker": broker_id}

    def resume_broker(self, broker_id: int) -> dict:
        """SIGCONT analog: thaw a paused broker — re-register listener
        and connections and flush whatever queued while frozen."""
        with self._lock:
            if broker_id not in self._paused:
                return {"broker": broker_id, "skipped": True}
            self._paused.discard(broker_id)
            ls = self._listeners.get(broker_id)
            if ls is not None:
                try:
                    self._sel.register(ls, selectors.EVENT_READ,
                                       ("accept", broker_id))
                except (KeyError, ValueError):
                    pass
            thaw = [c for c in self._conns
                    if c.broker_id == broker_id and not c.closed]
            for c in thaw:
                try:
                    self._sel.register(c.sock, selectors.EVENT_READ,
                                       ("conn", c))
                except (KeyError, ValueError):
                    pass
        for c in thaw:
            self._flush(c)
        return {"broker": broker_id}

    def paused_brokers(self) -> list[int]:
        with self._lock:
            return sorted(self._paused)

    # ------------------------- environment fault library (ISSUE 11) --
    def set_storage_error(self, broker_id: Optional[int] = None,
                          on: bool = True) -> dict:
        """Disk-full/EIO window on the storage plane (chaos
        ``env_eio``): every Produce led by an affected broker returns
        ``KAFKA_STORAGE_ERROR`` — the retriable error a real broker
        raises when its log dir fails — until the window heals.
        ``broker_id=None`` applies cluster-wide (all brokers)."""
        with self._lock:
            ids = ([broker_id] if broker_id
                   else list(range(1, self.num_brokers + 1)))
            for b in ids:
                if on:
                    self._storage_err.add(b)
                else:
                    self._storage_err.discard(b)
            return {"brokers": sorted(self._storage_err), "on": on}

    def storage_error_brokers(self) -> list[int]:
        with self._lock:
            return sorted(self._storage_err)

    def set_clock_skew(self, broker_id: int, skew_ms: float = 0.0) -> dict:
        """Clock-skew fault (chaos ``env_skew``): broker
        ``broker_id``'s wall clock reads ``skew_ms`` off true — every
        wall timestamp it reports (Produce ``log_append_time``,
        ``broker_clock_ms``) shifts accordingly.  0 restores a true
        clock."""
        with self._lock:
            if skew_ms:
                self._clock_skew_ms[broker_id] = float(skew_ms)
            else:
                self._clock_skew_ms.pop(broker_id, None)
            return {"broker": broker_id, "skew_ms": skew_ms}

    def broker_clock_ms(self, broker_id: int) -> int:
        """This broker's idea of wall-clock now, in ms (true clock +
        any injected skew)."""
        with self._lock:
            skew = self._clock_skew_ms.get(broker_id, 0.0)
        return int(time.time() * 1000.0 + skew)

    def clock_skews(self) -> dict[int, float]:
        with self._lock:
            return dict(self._clock_skew_ms)

    def rolling_restart(self, pause_s: float = 0.5) -> None:
        """Kill + restart every broker in id order, one at a time,
        waiting ``pause_s`` between steps (blocking convenience; chaos
        schedules script the same thing with precise timing)."""
        for b in range(1, self.num_brokers + 1):
            self.kill_broker(b)
            time.sleep(pause_s)
            self.restart_broker(b)
            time.sleep(pause_s)

    def set_partition_leader(self, topic: str, part: int, broker_id: int):
        with self._lock:
            p = self.topics[topic][part]
            p.leader = broker_id
            if broker_id not in p.replicas:
                p.replicas.append(broker_id)
            self.metadata_version += 1

    def coordinator_for(self, group: str) -> int:
        """Group/txn coordinator placement: hash ring, skipping dead
        brokers — when a coordinator dies, FindCoordinator immediately
        names the next alive broker (state is cluster-global here, so
        the successor serves seamlessly, like a real coordinator
        failover after __consumer_offsets replay)."""
        # stable hash (NOT builtin hash(): PYTHONHASHSEED randomizes it
        # per interpreter, and the out-of-process replay contract needs
        # the same key to land on the same broker across supervisor
        # launches — same seed => identical replay_key, ISSUE 9)
        base = (zlib.crc32(group.encode()) % self.num_brokers) + 1
        if base not in self._down:
            return base
        return self._next_alive(base) or base

    # -------------------------------------------------------------- loop ---
    def _run(self):
        while not self._stop.is_set():
            if _lockdep.enabled:
                _lockdep.note_blocking("mock.select")
            events = self._sel.select(timeout=0.005)
            now = time.monotonic()
            for key, mask in events:
                kind = key.data[0]
                if kind == "accept":
                    broker_id = key.data[1]
                    if broker_id in self._down:
                        try:
                            s, _ = key.fileobj.accept()
                            s.close()
                        except OSError:
                            pass
                        continue
                    try:
                        s, _ = key.fileobj.accept()
                    except OSError:
                        continue
                    s.setblocking(False)
                    conn = _Conn(s, broker_id)
                    if self._tls_ctx is not None:
                        try:
                            conn.sock = self._tls_ctx.wrap_socket(
                                s, server_side=True,
                                do_handshake_on_connect=False)
                            conn.handshaking = True
                        except (OSError, ValueError):
                            s.close()
                            continue
                    self._conns.append(conn)
                    self._sel.register(conn.sock, selectors.EVENT_READ,
                                       ("conn", conn))
                else:
                    conn = key.data[1]
                    if mask & selectors.EVENT_READ:
                        self._read(conn)
                    if mask & selectors.EVENT_WRITE:
                        self._flush(conn)
            # deferred responses (rtt injection) and group timers
            with self._lock:
                due = [d for d in self._deferred if d[0] <= now]
                self._deferred = [d for d in self._deferred if d[0] > now]
            for _, fn in due:
                fn()
            self._serve_parked_fetches(now)
            self._serve_group_timers(now)

    def _hs_serve(self, conn: _Conn) -> bool:
        """Advance a server-side TLS handshake; True once established."""
        try:
            conn.sock.do_handshake()
        except _ssl.SSLWantReadError:
            return False
        except _ssl.SSLWantWriteError:
            try:
                self._sel.modify(conn.sock,
                                 selectors.EVENT_READ | selectors.EVENT_WRITE,
                                 ("conn", conn))
            except (KeyError, ValueError):
                pass
            return False
        except (OSError, _ssl.SSLError):
            self._close(conn)
            return False
        conn.handshaking = False
        try:
            self._sel.modify(conn.sock, selectors.EVENT_READ, ("conn", conn))
        except (KeyError, ValueError):
            pass
        return True

    def _read(self, conn: _Conn):
        if conn.broker_id in self._paused:
            return              # race: event dequeued as the freeze hit
        if conn.handshaking:
            self._hs_serve(conn)
            return
        try:
            data = conn.sock.recv(262144)
        except (BlockingIOError, _ssl.SSLWantReadError, _ssl.SSLWantWriteError):
            return
        except OSError:
            self._close(conn)
            return
        if not data:
            self._close(conn)
            return
        conn.rbuf += data
        # drain SSL-layer buffered records invisible to the selector
        while self._tls_ctx is not None:
            try:
                if not conn.sock.pending():
                    break
                more = conn.sock.recv(262144)
            except (OSError, ValueError):
                break
            if not more:
                break
            conn.rbuf += more
        # offset-based frame walk: one compaction per recv burst instead
        # of a memmove per request (1MB Produce requests arrive in ~64KB
        # chunks; per-frame `del` shifted the tail every time)
        frames, bad = sockbuf.extract_frames(conn.rbuf)
        for payload in frames:
            self._handle(conn, payload)
            if conn.closed:
                return
        if bad is not None:
            self._close(conn)

    def _close(self, conn: _Conn):
        if conn.closed:
            return
        conn.closed = True
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        if conn in self._conns:
            self._conns.remove(conn)

    def _send(self, conn: _Conn, data: bytes):
        if conn.closed:
            return
        conn.wbuf += data
        self._flush(conn)

    def _flush(self, conn: _Conn):
        if conn.closed or conn.broker_id in self._paused:
            # frozen broker (pause_broker): responses queue in wbuf and
            # flush on resume — nothing leaves a SIGSTOPped process
            return
        if conn.handshaking:
            self._hs_serve(conn)
            return
        off, blocked, err = sockbuf.send_from(conn.sock, conn.wbuf,
                                              conn.wbuf_off)
        conn.wbuf_off = sockbuf.compact_consumed(conn.wbuf, off)
        if err is not None:
            self._close(conn)
            return
        if blocked:
            try:
                self._sel.modify(conn.sock,
                                 selectors.EVENT_READ | selectors.EVENT_WRITE,
                                 ("conn", conn))
            except (KeyError, ValueError):
                pass
            return
        try:
            self._sel.modify(conn.sock, selectors.EVENT_READ, ("conn", conn))
        except (KeyError, ValueError):
            pass

    # ---------------------------------------------------------- dispatch ---
    def _handle(self, conn: _Conn, payload: bytes):
        try:
            hdr, body = apis.parse_request(payload)
        except Exception:
            self._close(conn)
            return
        api = ApiKey(hdr["api_key"])
        corrid = hdr["correlation_id"]
        self.request_log.append((conn.broker_id, int(api)))

        # scripted error stack for this api?
        inject: Optional[Err] = None
        with self._lock:
            stack = self._err_stacks.get(int(api))
            if stack:
                inject = stack.popleft()

        # legacy-broker emulation: pre-0.10 brokers do not know
        # ApiVersions and close the connection on unknown requests
        if (self.broker_version is not None
                and api == ApiKey.ApiVersions
                and self._bv_tuple < (0, 10, 0)):
            self._close(conn)
            return

        handler = getattr(self, f"_h_{api.name}", None)
        if handler is None:
            self._close(conn)
            return
        resp = handler(conn, corrid, hdr, body, inject)
        if resp is None:
            return  # parked (fetch/join) — handler responds later
        self._respond(conn, corrid, api, resp, version=hdr["api_version"])

    def _respond(self, conn: _Conn, corrid: int, api: ApiKey, body: dict,
                 version: int | None = None):
        tt = self._throttle_ms.get(conn.broker_id)
        if tt and isinstance(body, dict) and "throttle_time_ms" in body:
            body = dict(body)
            body["throttle_time_ms"] = tt
        wire = apis.build_response(api, corrid, body, version=version)
        rtt = self._rtt_ms.get(conn.broker_id, 0)
        if rtt > 0:
            with self._lock:
                self._deferred.append((time.monotonic() + rtt / 1000.0,
                                       lambda: self._send(conn, wire)))
        else:
            self._send(conn, wire)

    # ---------------------------------------------------------- handlers ---
    def _h_ApiVersions(self, conn, corrid, hdr, body, inject):
        if self.broker_version is not None:
            from ..client.feature import fallback_api_versions
            av = fallback_api_versions(self.broker_version)
            vers = [{"api_key": k, "min_version": 0, "max_version": v}
                    for k, v in av.items()]
        else:
            vers = [{"api_key": int(k), "min_version": 0, "max_version": v}
                    for k, (v, _, _) in APIS.items()]
        return {"error_code": (inject.wire if inject else 0),
                "api_versions": vers}

    def _h_Metadata(self, conn, corrid, hdr, body, inject):
        with self._lock:
            names = body["topics"]
            # v4+ request flag (KIP-204): a False flag suppresses broker
            # auto-creation even when the cluster allows it
            allow = body.get("allow_auto_topic_creation", True)
            # Metadata v1+ semantics (ISSUE 14 satellite): ONLY a null
            # topic array enumerates everything; an EMPTY array means
            # "no topics" — a brokers-only liveness probe.  The old
            # conflation materialized the full topic table for clients
            # that asked for nothing.
            if names is None:
                names = list(self.topics)
            elif names and self.auto_create_topics and allow:
                for t in names:
                    if t not in self.topics and _valid_topic_name(t):
                        self.create_topic(t)
            topics = []
            for t in names:
                if t not in self.topics and not _valid_topic_name(t):
                    # real brokers reject bad names with
                    # INVALID_TOPIC_EXCEPTION (reference test
                    # 0057-invalid_topic); existence wins so a fixture-
                    # created topic always serves
                    topics.append({"error_code": Err.TOPIC_EXCEPTION.wire,
                                   "topic": t, "is_internal": False,
                                   "partitions": []})
                    continue
                if t not in self.topics:
                    topics.append({"error_code": Err.UNKNOWN_TOPIC_OR_PART.wire,
                                   "topic": t, "is_internal": False,
                                   "partitions": []})
                    continue
                parts = [{"error_code": 0, "partition": p.id,
                          "leader": p.leader if p.leader not in self._down else -1,
                          "replicas": p.replicas, "isr": p.replicas}
                         for p in self.topics[t]]
                topics.append({"error_code": inject.wire if inject else 0,
                               "topic": t, "is_internal": False,
                               "partitions": parts})
            brokers = [{"node_id": b, "host": "127.0.0.1",
                        "port": self.advertised_port(b), "rack": None}
                       for b in self._ports if b not in self._down]
        return {"throttle_time_ms": 0,   # serialized for v3+ only
                "brokers": brokers, "cluster_id": self.cluster_id,
                "controller_id": self.controller_id, "topics": topics}

    def _h_Produce(self, conn, corrid, hdr, body, inject):
        out_topics = []
        with self._lock:
            # env_eio: this broker's log dir is "failed" — refuse every
            # append with the retriable storage error a real broker
            # raises, without touching the log (nothing is persisted)
            storage_dead = conn.broker_id in self._storage_err
            skew = self._clock_skew_ms.get(conn.broker_id)
            la_time = (int(time.time() * 1000.0 + skew)
                       if skew is not None else -1)
            for t in body["topics"]:
                tp = {"topic": t["topic"], "partitions": []}
                for p in t["partitions"]:
                    err = Err.NO_ERROR
                    base = -1
                    part = None
                    # REQUEST_TIMED_OUT injection emulates "broker committed
                    # but the response was lost": append, THEN error — the
                    # scenario behind idempotent dup-seq handling (reference
                    # test 0094-idempotence_msg_timeout)
                    if inject and inject != Err.REQUEST_TIMED_OUT:
                        err = inject
                    elif t["topic"] not in self.topics or \
                            p["partition"] >= len(self.topics[t["topic"]]):
                        err = Err.UNKNOWN_TOPIC_OR_PART
                    else:
                        part = self.topics[t["topic"]][p["partition"]]
                        if part.leader != conn.broker_id:
                            err = Err.NOT_LEADER_FOR_PARTITION
                            part = None
                        elif storage_dead:
                            err = Err.KAFKA_STORAGE_ERROR
                            part = None
                    if part is not None:
                        blob = p["records"]
                        err, base = self._produce_to(part, blob)
                        if inject:
                            err, base = inject, -1
                    tp["partitions"].append(
                        {"partition": p["partition"], "error_code": err.wire,
                         "base_offset": base, "log_append_time": la_time})
                out_topics.append(tp)
        if body["acks"] == 0:
            return None  # no response for acks=0
        return {"topics": out_topics, "throttle_time_ms": 0}

    def _produce_to(self, part: MockPartition, blob: bytes) -> tuple[Err, int]:
        # idempotence checks for v2 batches (reference mock_handlers Produce)
        txn = None
        info = None
        if (len(blob) >= proto.V2_HEADER_SIZE
                and blob[proto.V2_OF_Magic] == 2):
            try:
                info = read_batch_header(Slice(blob))
            except Exception:
                return Err.INVALID_MSG, -1
            if info.producer_id >= 0:
                # epoch fencing precedes everything: a zombie's stale
                # epoch must never append (real broker ProducerStateManager)
                tid = self._pid_tid.get(info.producer_id)
                txn = self.transactions.get(tid) if tid else None
                if txn is not None and info.producer_epoch != txn.epoch:
                    return (Err.PRODUCER_FENCED
                            if info.producer_epoch < txn.epoch
                            else Err.INVALID_PRODUCER_EPOCH), -1
                if info.is_transactional:
                    if txn is None:
                        return Err.INVALID_PRODUCER_ID_MAPPING, -1
                    if (part.topic, part.id) not in txn.partitions:
                        # transactional data requires AddPartitionsToTxn
                        # first — the coordinator can't write a marker
                        # for a partition it never heard of
                        return Err.INVALID_TXN_STATE, -1
                key = (info.producer_id, info.producer_epoch)
                expected = part.pid_seqs.get(key, 0)
                if info.base_sequence != expected:
                    if info.base_sequence < expected:
                        return Err.DUPLICATE_SEQUENCE_NUMBER, -1
                    return Err.OUT_OF_ORDER_SEQUENCE_NUMBER, -1
                part.pid_seqs[key] = info.base_sequence + info.record_count
        base = part.append(blob)
        if info is not None and info.is_transactional and txn is not None:
            # first data offset of this txn in this partition: feeds
            # the aborted-txn index entry and pins the LSO
            tkey = (part.topic, part.id)
            if txn.partitions.get(tkey) is None:
                txn.partitions[tkey] = base
            part.open_txns.setdefault(info.producer_id, base)
        return Err.NO_ERROR, base

    def set_follower(self, topic: str, partition: int,
                     broker_id: Optional[int]) -> None:
        """Nominate (or clear) a preferred read replica for v11+
        fetches (reference: rd_kafka_mock_partition_set_follower)."""
        with self._lock:
            self.topics[topic][partition].follower_id = broker_id

    # ------------------------------------------------------------------
    # KIP-227 incremental fetch sessions (ISSUE 14)

    def _session_error(self, err: Err) -> dict:
        """Top-level session error: empty topics, client renegotiates."""
        return {"throttle_time_ms": 0, "error_code": err.wire,
                "session_id": 0, "topics": []}

    def _evict_fetch_sessions_locked(self) -> None:
        """LRU-evict past the cache cap (mirrors the real broker's
        max.incremental.fetch.session.cache.slots). Lock held."""
        while len(self._fetch_sessions) > self.fetch_session_slots:
            victim = min(self._fetch_sessions,
                         key=lambda sid: self._fetch_sessions[sid]["last"])
            del self._fetch_sessions[victim]

    def evict_fetch_sessions(self, broker_id: Optional[int] = None) -> int:
        """Test hook: drop cached fetch sessions (all, or one broker's).
        The next incremental fetch gets FETCH_SESSION_ID_NOT_FOUND."""
        with self._lock:
            doomed = [sid for sid, s in self._fetch_sessions.items()
                      if broker_id is None or s["broker"] == broker_id]
            for sid in doomed:
                del self._fetch_sessions[sid]
            return len(doomed)

    def fetch_session_ids(self, broker_id: Optional[int] = None) -> list:
        """Test hook: session ids cached (for one broker, or all)."""
        with self._lock:
            return [sid for sid, s in self._fetch_sessions.items()
                    if broker_id is None or s["broker"] == broker_id]

    @staticmethod
    def _session_book_merge(book: dict, body: dict) -> None:
        """Fold a request's partition list + forgotten list into the
        session book {(topic, partition): {fetch_offset, max_bytes}}."""
        for ft in body.get("forgotten_topics") or []:
            for p in ft["partitions"]:
                book.pop((ft["topic"], p), None)
        for t in body["topics"]:
            for p in t["partitions"]:
                book[(t["topic"], p["partition"])] = {
                    "fetch_offset": p["fetch_offset"],
                    "max_bytes": p["max_bytes"]}

    @staticmethod
    def _session_body(body: dict, book: dict) -> dict:
        """Materialize the effective fetch body from a session book —
        the incremental request named only CHANGES; the broker serves
        its cached view of the full interest set."""
        by_topic: dict = {}
        for (t, p), st in book.items():
            by_topic.setdefault(t, []).append(
                {"partition": p, "fetch_offset": st["fetch_offset"],
                 "max_bytes": st["max_bytes"]})
        eff = dict(body)
        eff["topics"] = [{"topic": t, "partitions": ps}
                         for t, ps in sorted(by_topic.items())]
        return eff

    def _h_Fetch(self, conn, corrid, hdr, body, inject):
        now = time.monotonic()
        ver = hdr["api_version"]
        epoch = body.get("session_epoch", -1)
        sess = None           # (session_id, incremental-response?)
        eff_body = body
        if ver >= 7 and epoch != -1:
            with self._lock:
                if epoch == 0:
                    # FULL_FETCH establishing a session: cache the whole
                    # partition book, answer with a broker-assigned id
                    sid = self._next_session_id
                    self._next_session_id += 1
                    book: dict = {}
                    self._session_book_merge(book, body)
                    self._fetch_sessions[sid] = {
                        "broker": conn.broker_id, "epoch": 1,
                        "book": book, "last": now}
                    self._evict_fetch_sessions_locked()
                    sess = (sid, False)   # full response this once
                else:
                    sid = body.get("session_id", 0)
                    s = self._fetch_sessions.get(sid)
                    if s is None or s["broker"] != conn.broker_id:
                        return self._session_error(
                            Err.FETCH_SESSION_ID_NOT_FOUND)
                    if epoch != s["epoch"]:
                        return self._session_error(
                            Err.INVALID_FETCH_SESSION_EPOCH)
                    self._session_book_merge(s["book"], body)
                    s["epoch"] += 1
                    s["last"] = now
                    sess = (sid, True)
                    eff_body = self._session_body(body, s["book"])
        resp = self._try_fetch(conn, eff_body, inject, ver=ver,
                               incremental=bool(sess and sess[1]))
        if resp is not None:
            if sess is not None:
                resp["error_code"] = 0
                resp["session_id"] = sess[0]
            return resp
        # no data yet: park until max_wait or data arrives
        deadline = now + body["max_wait_time"] / 1000.0
        self._parked_fetches.append((deadline, conn, corrid, eff_body,
                                     ver, sess))
        return None

    def _try_fetch(self, conn, body, inject, force: bool = False,
                   ver: int = 4, incremental: bool = False):
        """Build a fetch response, or None if empty and not forced."""
        any_data = False
        any_err = False
        out_topics = []
        with self._lock:
            for t in body["topics"]:
                tp = {"topic": t["topic"], "partitions": []}
                for p in t["partitions"]:
                    err = Err.NO_ERROR
                    records = b""
                    hwm = lso = -1
                    preferred = -1
                    if inject:
                        err = inject
                    elif t["topic"] not in self.topics or \
                            p["partition"] >= len(self.topics[t["topic"]]):
                        err = Err.UNKNOWN_TOPIC_OR_PART
                    else:
                        part = self.topics[t["topic"]][p["partition"]]
                        serves = (part.leader == conn.broker_id
                                  or part.follower_id == conn.broker_id)
                        if not serves:
                            err = Err.NOT_LEADER_FOR_PARTITION
                        elif (part.leader == conn.broker_id
                              and part.follower_id is not None
                              and part.follower_id != conn.broker_id
                              and part.follower_id not in self._down
                              and ver >= 11):
                            # KIP-392 redirect: the leader answers a
                            # v11 fetch with the nominated follower and
                            # NO records (real broker behavior)
                            hwm = lso = part.end_offset
                            preferred = part.follower_id
                        else:
                            hwm = part.end_offset
                            lso = part.lso()
                            off = p["fetch_offset"]
                            # read_committed fetches stop at the LSO:
                            # data of a still-open transaction is not
                            # stable yet (real broker behavior)
                            cap = (lso if body.get("isolation_level", 0)
                                   == 1 else part.end_offset)
                            if off < part.start_offset or off > part.end_offset:
                                err = Err.OFFSET_OUT_OF_RANGE
                            elif off < cap:
                                records = part.read_from(
                                    off, p["max_bytes"],
                                    max_offset=cap)
                    if err != Err.NO_ERROR:
                        any_err = True
                    if records:
                        any_data = True
                    aborted = []
                    if body.get("isolation_level", 0) == 1 and records:
                        # read_committed: report only aborted-txn ranges
                        # overlapping the fetched span — an entry whose
                        # ABORT marker precedes the fetch offset must
                        # not be re-reported or the client would filter
                        # later committed data from the same pid
                        # (txn index maintained by EndTxn, also
                        # test-seedable via part.aborted;
                        # "last_offset" = abort marker offset)
                        aborted = [
                            a for a in part.aborted or []
                            if a.get("last_offset", 1 << 62)
                            >= p["fetch_offset"]]
                    if preferred != -1:
                        any_data = True      # redirects return immediately
                    if incremental and not records \
                            and err == Err.NO_ERROR and preferred == -1:
                        # KIP-227: incremental responses OMIT unchanged
                        # empty partitions — the whole point of the
                        # session; steady-state long-poll answers are
                        # O(partitions-with-data), not O(interest set)
                        continue
                    tp["partitions"].append(
                        {"partition": p["partition"], "error_code": err.wire,
                         "high_watermark": hwm, "last_stable_offset": lso,
                         "aborted_transactions": aborted,
                         "preferred_read_replica": preferred,
                         "records": records})
                if tp["partitions"]:
                    out_topics.append(tp)
        if not any_data and not any_err and not force:
            return None
        return {"throttle_time_ms": 0, "topics": out_topics}

    def _serve_parked_fetches(self, now: float):
        still = []
        for deadline, conn, corrid, body, ver, sess in self._parked_fetches:
            if conn.closed:
                continue
            resp = self._try_fetch(conn, body, None,
                                   force=(now >= deadline), ver=ver,
                                   incremental=bool(sess and sess[1]))
            if resp is not None:
                if sess is not None:
                    resp["error_code"] = 0
                    resp["session_id"] = sess[0]
                self._respond(conn, corrid, ApiKey.Fetch, resp, version=ver)
            else:
                still.append((deadline, conn, corrid, body, ver, sess))
        self._parked_fetches = still

    def _h_ListOffsets(self, conn, corrid, hdr, body, inject):
        out = []
        with self._lock:
            for t in body["topics"]:
                tp = {"topic": t["topic"], "partitions": []}
                for p in t["partitions"]:
                    err = Err.NO_ERROR
                    offset = -1
                    if inject:
                        err = inject
                    elif t["topic"] not in self.topics:
                        err = Err.UNKNOWN_TOPIC_OR_PART
                    else:
                        part = self.topics[t["topic"]][p["partition"]]
                        ts = p["timestamp"]
                        if ts == proto.OFFSET_BEGINNING:
                            offset = part.start_offset
                        elif ts == proto.OFFSET_END:
                            offset = part.end_offset
                        else:
                            # timestamp lookup (offsets_for_times): the
                            # earliest offset whose batch could contain
                            # ts, from the stored batch headers
                            offset = -1
                            for base, blob in part.log:
                                if (len(blob) < proto.V2_HEADER_SIZE
                                        or blob[proto.V2_OF_Magic] != 2):
                                    continue
                                max_ts = struct.unpack_from(
                                    ">q", blob, proto.V2_OF_MaxTimestamp)[0]
                                if max_ts >= ts:
                                    offset = base
                                    break
                    tp["partitions"].append(
                        {"partition": p["partition"], "error_code": err.wire,
                         "timestamp": -1, "offset": offset,
                         # plural form for ListOffsets v0 responses
                         "offsets": [offset] if offset >= 0 else []})
                out.append(tp)
        return {"topics": out}

    # ------------------------------------------------------ group machinery --
    def _h_FindCoordinator(self, conn, corrid, hdr, body, inject):
        if inject:
            return {"throttle_time_ms": 0, "error_code": inject.wire,
                    "error_message": None, "node_id": -1, "host": "",
                    "port": -1}
        b = self.coordinator_for(body["key"])
        return {"throttle_time_ms": 0, "error_code": 0, "error_message": None,
                "node_id": b, "host": "127.0.0.1",
                "port": self.advertised_port(b)}

    def _group(self, gid: str) -> MockGroup:
        with self._lock:
            if gid not in self.groups:
                self.groups[gid] = MockGroup(group_id=gid)
            return self.groups[gid]

    def _member_id_for(self, g, body, client_id):
        """Static members (group.instance.id) keep a stable member_id
        across restarts (KIP-345); dynamic members get a fresh one."""
        inst = body.get("group_instance_id")
        if inst:
            for m in g.members.values():
                if getattr(m, "instance_id", None) == inst:
                    return m.member_id
            return f"{client_id}-static-{inst}"
        return None

    def _h_JoinGroup(self, conn, corrid, hdr, body, inject):
        if inject:
            return {"throttle_time_ms": 0, "error_code": inject.wire,
                    "generation_id": -1, "protocol": "", "leader_id": "",
                    "member_id": body["member_id"], "members": []}
        g = self._group(body["group_id"])
        with self._lock:
            member_id = body["member_id"]
            static_id = self._member_id_for(g, body,
                                            hdr["client_id"] or "member")
            if static_id is not None:
                member_id = static_id
                m = g.members.get(member_id)
                if m is not None and g.state == "Stable" \
                        and self._static_rejoin_ok(m, body):
                    # KIP-345 static rejoin fast path: a known
                    # group.instance.id returning while the group is
                    # Stable reclaims its slot at the CURRENT
                    # generation — no rebalance, nobody else revokes
                    # anything; SyncGroup serves the retained
                    # assignment (real broker behavior for static
                    # members inside session.timeout.ms)
                    m.protocols = [(p["name"], p["metadata"])
                                   for p in body["protocols"]]
                    m.metadata = m.protocols[0][1] if m.protocols else b""
                    m.session_timeout_ms = body["session_timeout"]
                    m.last_heartbeat = time.monotonic()
                    members_meta = [
                        {"member_id": mm.member_id,
                         "group_instance_id": getattr(mm, "instance_id",
                                                      None),
                         "metadata": dict(mm.protocols).get(g.protocol,
                                                            b"")}
                        for mm in g.members.values()]
                    return {"throttle_time_ms": 0, "error_code": 0,
                            "generation_id": g.generation,
                            "protocol": g.protocol, "leader_id": g.leader,
                            "member_id": member_id,
                            "members": (members_meta
                                        if member_id == g.leader else [])}
            if not member_id:
                member_id = f"{hdr['client_id'] or 'member'}-{len(g.members) + 1}-{int(time.monotonic()*1e6) & 0xFFFF}"
            m = g.members.get(member_id)
            if m is None:
                m = GroupMember(member_id=member_id,
                                client_id=hdr["client_id"] or "",
                                client_host="/127.0.0.1")
                m.instance_id = body.get("group_instance_id")
                g.members[member_id] = m
            m.protocols = [(p["name"], p["metadata"]) for p in body["protocols"]]
            m.metadata = m.protocols[0][1] if m.protocols else b""
            m.session_timeout_ms = body["session_timeout"]
            m.last_heartbeat = time.monotonic()
            g.protocol_type = body["protocol_type"]
            m.pending_join = (conn, corrid, hdr["api_version"])
            if g.state in ("Empty", "Stable", "CompletingRebalance"):
                was_empty = g.state == "Empty"
                g.state = "PreparingRebalance"
                g.rebalance_deadline = time.monotonic() + min(
                    body.get("rebalance_timeout", 3000), 3000) / 1000.0
                if was_empty and self.group_initial_delay_s > 0:
                    # KIP-134 group.initial.rebalance.delay.ms: hold
                    # the FIRST generation open so a starting fleet
                    # joins together
                    g.hold_until = (time.monotonic()
                                    + self.group_initial_delay_s)
                    g.rebalance_deadline = max(g.rebalance_deadline,
                                               g.hold_until)
            # complete immediately if every member has rejoined
            self._maybe_complete_join(g)
        return None  # parked; responded by _maybe_complete_join / timer

    @staticmethod
    def _static_rejoin_ok(m, body) -> bool:
        """Whether a known static member's JoinGroup may take the
        no-rebalance fast path: its effective subscription (protocol
        names + topic lists) must be unchanged, AND it must be either
        a fresh restart reclaiming its slot (empty member_id — the new
        instance never knew its id) or the live member itself.  A LIVE
        cooperative member rejoining after an incremental revoke
        carries a CHANGED owned_partitions set and an explicit
        member_id — that rejoin exists to trigger the next generation
        and must NOT be swallowed (real GroupCoordinator semantics:
        updateMemberAndRebalance when the protocols changed)."""
        from ..client.assignor import subscription_decode

        def sig(protocols):
            out = []
            for name, meta in protocols:
                try:
                    out.append((name, tuple(
                        subscription_decode(meta)["topics"])))
                except Exception:
                    out.append((name, bytes(meta)))
            return out

        new = [(p["name"], bytes(p["metadata"])) for p in body["protocols"]]
        old = [(n, bytes(b)) for n, b in m.protocols]
        if not body["member_id"]:
            # fresh restart reclaiming the slot: the new instance never
            # knew its owned set, so compare topics only
            return sig(new) == sig(old)
        # live member: byte-exact metadata match — a cooperative
        # rejoin after an incremental revoke differs in
        # owned_partitions and must trigger the next generation
        return body["member_id"] == m.member_id and new == old

    def _maybe_complete_join(self, g: MockGroup):
        if g.state != "PreparingRebalance":
            return
        if time.monotonic() < g.hold_until:
            return          # initial-rebalance delay window still open
        if any(m.pending_join is None for m in g.members.values()):
            return
        self._complete_join(g)

    def _complete_join(self, g: MockGroup):
        # drop members that never rejoined
        g.members = {mid: m for mid, m in g.members.items()
                     if m.pending_join is not None}
        if not g.members:
            g.state = "Empty"
            return
        g.generation += 1
        # pick first common protocol
        proto_names = None
        for m in g.members.values():
            names = [n for n, _ in m.protocols]
            proto_names = names if proto_names is None else \
                [n for n in proto_names if n in names]
        g.protocol = proto_names[0] if proto_names else ""
        g.leader = next(iter(g.members))
        g.state = "CompletingRebalance"
        members_meta = [
            {"member_id": m.member_id,
             "group_instance_id": getattr(m, "instance_id", None),
             "metadata": dict(m.protocols).get(g.protocol, b"")}
            for m in g.members.values()]
        for m in g.members.values():
            conn, corrid, jver = m.pending_join
            m.pending_join = None
            body = {"throttle_time_ms": 0, "error_code": 0,
                    "generation_id": g.generation, "protocol": g.protocol,
                    "leader_id": g.leader, "member_id": m.member_id,
                    "members": members_meta if m.member_id == g.leader else []}
            self._respond(conn, corrid, ApiKey.JoinGroup, body, version=jver)

    def _serve_group_timers(self, now: float):
        with self._lock:
            for g in self.groups.values():
                if g.state == "PreparingRebalance" and now >= g.rebalance_deadline:
                    # rebalance window expired: complete with who rejoined
                    self._complete_join(g)
                # session timeout enforcement
                dead = [mid for mid, m in g.members.items()
                        if m.pending_join is None and g.state == "Stable"
                        and now - m.last_heartbeat >
                        m.session_timeout_ms / 1000.0]
                for mid in dead:
                    del g.members[mid]
                    if g.members:
                        g.state = "PreparingRebalance"
                        g.rebalance_deadline = now + 3.0
                    else:
                        g.state = "Empty"

    def _h_SyncGroup(self, conn, corrid, hdr, body, inject):
        if inject:
            return {"throttle_time_ms": 0, "error_code": inject.wire,
                    "assignment": b""}
        g = self._group(body["group_id"])
        with self._lock:
            if body["generation_id"] != g.generation or \
                    body["member_id"] not in g.members:
                return {"throttle_time_ms": 0,
                        "error_code": Err.ILLEGAL_GENERATION.wire,
                        "assignment": b""}
            if g.state == "PreparingRebalance":
                return {"throttle_time_ms": 0,
                        "error_code": Err.REBALANCE_IN_PROGRESS.wire,
                        "assignment": b""}
            if body["member_id"] == g.leader:
                for a in body["assignments"]:
                    if a["member_id"] in g.members:
                        g.members[a["member_id"]].assignment = a["assignment"]
                self._validate_group_assignment(g)
                g.state = "Stable"
                # flush parked syncs; a parked member that was dropped
                # meanwhile (never rejoined before the rebalance window
                # closed — heavy churn does this constantly) gets
                # UNKNOWN_MEMBER_ID so it re-joins, never a KeyError
                for (pconn, pcorrid, pmid, pver) in g.pending_syncs:
                    if pmid in g.members:
                        body = {"throttle_time_ms": 0, "error_code": 0,
                                "assignment": g.members[pmid].assignment}
                    else:
                        body = {"throttle_time_ms": 0,
                                "error_code": Err.UNKNOWN_MEMBER_ID.wire,
                                "assignment": b""}
                    self._respond(pconn, pcorrid, ApiKey.SyncGroup, body,
                                  version=pver)
                g.pending_syncs.clear()
                return {"throttle_time_ms": 0, "error_code": 0,
                        "assignment": g.members[g.leader].assignment}
            if g.state == "Stable":
                return {"throttle_time_ms": 0, "error_code": 0,
                        "assignment": g.members[body["member_id"]].assignment}
            g.pending_syncs.append((conn, corrid, body["member_id"],
                                    hdr["api_version"]))
            return None

    def _validate_group_assignment(self, g: MockGroup):
        """ISSUE 12 ownership validation (called under ``self._lock``
        when a leader sync lands): decode every member's embedded-
        protocol assignment, flag (a) partitions owned by two members
        in ONE generation and (b) — for COOPERATIVE protocols — a
        partition handed to a new owner in the same generation its
        previous owner lost it (KIP-429 requires an intermediate
        generation where nobody owns it).  Violations are recorded in
        ``g.validation_errors`` for tests/oracles; the wire response
        is unchanged (a real broker treats assignments as opaque)."""
        from ..client.assignor import ASSIGNOR_PROTOCOLS, assignment_decode
        new_owned: dict[tuple[str, int], str] = {}
        for mid, m in g.members.items():
            try:
                asn = assignment_decode(m.assignment or b"")
            except Exception:
                continue            # opaque/foreign protocol bytes
            for t, ps in asn.items():
                for p in ps:
                    prev = new_owned.get((t, p))
                    if prev is not None and prev != mid:
                        g.validation_errors.append(
                            {"kind": "double_owner", "gen": g.generation,
                             "topic": t, "partition": p,
                             "members": sorted((prev, mid))})
                    new_owned[(t, p)] = mid
        if ASSIGNOR_PROTOCOLS.get(g.protocol) == "COOPERATIVE":
            for tp, mid in new_owned.items():
                old = g.owned.get(tp)
                if old is not None and old != mid and old in g.members:
                    g.validation_errors.append(
                        {"kind": "moved_without_revoke",
                         "gen": g.generation, "topic": tp[0],
                         "partition": tp[1], "from": old, "to": mid})
        g.owned = new_owned

    def _h_Heartbeat(self, conn, corrid, hdr, body, inject):
        if inject:
            return {"throttle_time_ms": 0, "error_code": inject.wire}
        g = self._group(body["group_id"])
        with self._lock:
            m = g.members.get(body["member_id"])
            if m is None:
                return {"throttle_time_ms": 0,
                        "error_code": Err.UNKNOWN_MEMBER_ID.wire}
            if body["generation_id"] != g.generation:
                return {"throttle_time_ms": 0,
                        "error_code": Err.ILLEGAL_GENERATION.wire}
            m.last_heartbeat = time.monotonic()
            if g.state == "PreparingRebalance":
                return {"throttle_time_ms": 0,
                        "error_code": Err.REBALANCE_IN_PROGRESS.wire}
        return {"throttle_time_ms": 0, "error_code": 0}

    def _h_LeaveGroup(self, conn, corrid, hdr, body, inject):
        g = self._group(body["group_id"])
        with self._lock:
            g.members.pop(body["member_id"], None)
            if g.members:
                g.state = "PreparingRebalance"
                g.rebalance_deadline = time.monotonic() + 3.0
                self._maybe_complete_join(g)
            else:
                g.state = "Empty"
        return {"throttle_time_ms": 0, "error_code": 0}

    def _h_OffsetCommit(self, conn, corrid, hdr, body, inject):
        g = self._group(body["group_id"])
        out = []
        with self._lock:
            # generation/membership validation (ISSUE 12; real broker
            # GroupCoordinator semantics): a group-member commit
            # (generation >= 0) must name a live member at the current
            # generation — a fenced/zombie member's commit is rejected
            # so its offsets can't clobber the new owner's.  Simple
            # consumers commit with generation -1 and skip the check.
            gen_err = Err.NO_ERROR
            if body.get("generation_id", -1) >= 0:
                if body.get("member_id") not in g.members:
                    gen_err = Err.UNKNOWN_MEMBER_ID
                elif body["generation_id"] != g.generation:
                    gen_err = Err.ILLEGAL_GENERATION
            for t in body["topics"]:
                tp = {"topic": t["topic"], "partitions": []}
                for p in t["partitions"]:
                    err = inject or gen_err or Err.NO_ERROR
                    if err == Err.NO_ERROR:
                        g.offsets[(t["topic"], p["partition"])] = (
                            p["offset"], p["metadata"])
                    tp["partitions"].append({"partition": p["partition"],
                                             "error_code": err.wire})
                out.append(tp)
        return {"topics": out}

    def _h_OffsetFetch(self, conn, corrid, hdr, body, inject):
        g = self._group(body["group_id"])
        out = []
        with self._lock:
            for t in body["topics"] or []:
                tp = {"topic": t["topic"], "partitions": []}
                for pid in t["partitions"]:
                    off, meta = g.offsets.get((t["topic"], pid), (-1, None))
                    tp["partitions"].append(
                        {"partition": pid, "offset": off, "metadata": meta,
                         "error_code": inject.wire if inject else 0})
                out.append(tp)
        return {"topics": out}

    # ----------------------------------------------------------- producer --
    #: broker-side transaction.max.timeout.ms (real default)
    MAX_TXN_TIMEOUT_MS = 900000

    def _h_InitProducerId(self, conn, corrid, hdr, body, inject):
        if inject:
            return {"throttle_time_ms": 0, "error_code": inject.wire,
                    "producer_id": -1, "producer_epoch": -1}
        tid = body.get("transactional_id")
        if not tid:
            # plain idempotent producer: fresh pid, epoch 0
            with self._lock:
                pid = self._next_pid
                self._next_pid += 1
            return {"throttle_time_ms": 0, "error_code": 0,
                    "producer_id": pid, "producer_epoch": 0}
        # transactional: the id is pinned to its coordinator, keeps its
        # pid across re-inits, and every re-init BUMPS THE EPOCH —
        # fencing any older instance (zombie) still holding the old one
        fail = {"throttle_time_ms": 0, "producer_id": -1,
                "producer_epoch": -1}
        tmo = body.get("transaction_timeout_ms", 60000)
        if tmo <= 0 or tmo > self.MAX_TXN_TIMEOUT_MS:
            return {**fail,
                    "error_code": Err.INVALID_TRANSACTION_TIMEOUT.wire}
        with self._lock:
            if conn.broker_id != self.coordinator_for(tid):
                return {**fail, "error_code": Err.NOT_COORDINATOR.wire}
            t = self.transactions.get(tid)
            if t is None:
                t = MockTransaction(tid=tid, pid=self._next_pid)
                self._next_pid += 1
                self.transactions[tid] = t
                self._pid_tid[t.pid] = tid
            elif t.state == "Ongoing":
                # previous instance died mid-transaction: abort it
                # before handing out the new epoch (real coordinator
                # behavior on InitProducerId with an ongoing txn)
                self._end_txn_locked(t, committed=False)
            t.epoch += 1
            t.state = "Empty"
            return {"throttle_time_ms": 0, "error_code": 0,
                    "producer_id": t.pid, "producer_epoch": t.epoch}

    def _txn_lookup_locked(self, conn, tid: str, pid: int, epoch: int,
                           *, check_coord: bool = True) -> Optional[Err]:
        """Validate a transactional request's identity; None = OK."""
        if check_coord and conn.broker_id != self.coordinator_for(tid):
            return Err.NOT_COORDINATOR
        t = self.transactions.get(tid)
        if t is None or t.pid != pid:
            return Err.INVALID_PRODUCER_ID_MAPPING
        if epoch < t.epoch:
            return Err.PRODUCER_FENCED     # zombie instance
        if epoch > t.epoch:
            return Err.INVALID_PRODUCER_EPOCH
        return None

    def _h_AddPartitionsToTxn(self, conn, corrid, hdr, body, inject):
        tid = body["transactional_id"]
        out = []
        with self._lock:
            base_err = inject or self._txn_lookup_locked(
                conn, tid, body["producer_id"], body["producer_epoch"])
            t = self.transactions.get(tid)
            for tr in body["topics"]:
                parts = []
                for p in tr["partitions"]:
                    err = base_err or Err.NO_ERROR
                    if err == Err.NO_ERROR:
                        if tr["topic"] not in self.topics or \
                                p >= len(self.topics[tr["topic"]]):
                            err = Err.UNKNOWN_TOPIC_OR_PART
                        else:
                            t.partitions.setdefault((tr["topic"], p), None)
                            t.state = "Ongoing"
                    parts.append({"partition": p, "error_code": err.wire})
                out.append({"topic": tr["topic"], "partitions": parts})
        return {"throttle_time_ms": 0, "results": out}

    def _h_AddOffsetsToTxn(self, conn, corrid, hdr, body, inject):
        with self._lock:
            err = inject or self._txn_lookup_locked(
                conn, body["transactional_id"], body["producer_id"],
                body["producer_epoch"])
            if err is None:
                t = self.transactions[body["transactional_id"]]
                t.groups.add(body["group_id"])
                t.state = "Ongoing"
        return {"throttle_time_ms": 0,
                "error_code": err.wire if err else 0}

    def _h_TxnOffsetCommit(self, conn, corrid, hdr, body, inject):
        # arrives at the GROUP coordinator (real protocol), so the
        # txn-coordinator pinning check is skipped; offsets stage in
        # the txn and only land in the group at EndTxn(commit)
        out = []
        with self._lock:
            err = inject or self._txn_lookup_locked(
                conn, body["transactional_id"], body["producer_id"],
                body["producer_epoch"], check_coord=False)
            t = self.transactions.get(body["transactional_id"])
            staged = (t.pending_offsets.setdefault(body["group_id"], {})
                      if err is None else None)
            for tr in body["topics"]:
                parts = []
                for p in tr["partitions"]:
                    if err is None:
                        staged[(tr["topic"], p["partition"])] = (
                            p["offset"], p["metadata"])
                    parts.append({"partition": p["partition"],
                                  "error_code": err.wire if err else 0})
                out.append({"topic": tr["topic"], "partitions": parts})
        return {"throttle_time_ms": 0, "topics": out}

    def _h_EndTxn(self, conn, corrid, hdr, body, inject):
        with self._lock:
            err = inject or self._txn_lookup_locked(
                conn, body["transactional_id"], body["producer_id"],
                body["producer_epoch"])
            if err is None:
                t = self.transactions[body["transactional_id"]]
                if t.state == ("CompleteCommit" if body["committed"]
                               else "CompleteAbort"):
                    # idempotent retry: the previous EndTxn landed but
                    # its response was lost (coordinator died mid-
                    # commit); the markers are already written, so the
                    # retry must succeed, not INVALID_TXN_STATE — or
                    # every coordinator-failover storm would go fatal
                    pass
                elif t.state != "Ongoing":
                    err = Err.INVALID_TXN_STATE
                else:
                    self._end_txn_locked(t, body["committed"])
        return {"throttle_time_ms": 0,
                "error_code": err.wire if err else 0}

    def _end_txn_locked(self, t: MockTransaction, committed: bool) -> None:
        """Write a COMMIT/ABORT control record into every partition the
        transaction touched, maintain the aborted-transaction index,
        release the LSO, and (on commit) land the staged group offsets
        (real coordinator: WriteTxnMarkers to the partition leaders)."""
        for (topic, pnum), first in t.partitions.items():
            parts = self.topics.get(topic)
            if parts is None or pnum >= len(parts):
                continue                    # topic deleted mid-txn
            part = parts[pnum]
            marker = self._control_batch(t.pid, t.epoch, committed)
            base = part.append(marker)
            part.open_txns.pop(t.pid, None)
            if not committed and first is not None:
                part.aborted.append({"producer_id": t.pid,
                                     "first_offset": first,
                                     "last_offset": base})
        if committed:
            for gid, offs in t.pending_offsets.items():
                self._group(gid).offsets.update(offs)
        t.partitions = {}
        t.pending_offsets = {}
        t.groups = set()
        t.state = "CompleteCommit" if committed else "CompleteAbort"

    @staticmethod
    def _control_batch(pid: int, epoch: int, committed: bool) -> bytes:
        """A v2 control RecordBatch exactly as a broker writes it: one
        record, key = [version i16, type i16], value = [version i16,
        coordinator_epoch i32], transactional+control attr bits set."""
        from ..protocol.msgset import MsgsetWriterV2, Record
        now_ms = int(time.time() * 1000)
        w = MsgsetWriterV2(producer_id=pid, producer_epoch=epoch,
                           base_sequence=-1, transactional=True,
                           control=True)
        key = struct.pack(">hh", 0, proto.CTRL_COMMIT if committed
                          else proto.CTRL_ABORT)
        rec = Record(key=key, value=struct.pack(">hi", 0, 0),
                     timestamp=now_ms)
        return w.write_batch([rec], now_ms)

    # --------------------------------------------------------------- admin --
    def _h_CreateTopics(self, conn, corrid, hdr, body, inject):
        out = []
        with self._lock:
            for t in body["topics"]:
                if inject:
                    err = inject
                elif t["topic"] in self.topics:
                    err = Err.TOPIC_ALREADY_EXISTS
                elif not _valid_topic_name(t["topic"]):
                    # broker-side name validation (real brokers reject
                    # bad names at creation, not just on metadata)
                    err = Err.TOPIC_EXCEPTION
                else:
                    self.create_topic(t["topic"], max(t["num_partitions"], 1))
                    err = Err.NO_ERROR
                out.append({"topic": t["topic"], "error_code": err.wire,
                            "error_message": None})
        return {"throttle_time_ms": 0, "topics": out}

    def _h_DeleteTopics(self, conn, corrid, hdr, body, inject):
        out = []
        with self._lock:
            for t in body["topics"]:
                if inject:
                    err = inject
                elif t in self.topics:
                    del self.topics[t]
                    err = Err.NO_ERROR
                else:
                    err = Err.UNKNOWN_TOPIC_OR_PART
                out.append({"topic": t, "error_code": err.wire})
        return {"throttle_time_ms": 0, "topics": out}

    def _h_CreatePartitions(self, conn, corrid, hdr, body, inject):
        out = []
        with self._lock:
            for t in body["topics"]:
                if inject:
                    err = inject
                elif t["topic"] not in self.topics:
                    err = Err.UNKNOWN_TOPIC_OR_PART
                elif t["count"] <= len(self.topics[t["topic"]]):
                    err = Err.INVALID_PARTITIONS
                else:
                    parts = self.topics[t["topic"]]
                    for i in range(len(parts), t["count"]):
                        parts.append(self._new_partition(t["topic"], i))
                    err = Err.NO_ERROR
                out.append({"topic": t["topic"], "error_code": err.wire,
                            "error_message": None})
        return {"throttle_time_ms": 0, "topics": out}

    _CONFIG_DEFAULTS = {"retention.ms": "604800000",
                        "cleanup.policy": "delete"}

    def _h_DescribeConfigs(self, conn, corrid, hdr, body, inject):
        out = []
        with self._lock:
            for r in body["resources"]:
                key = (r["resource_type"], r["resource_name"])
                merged = dict(self._CONFIG_DEFAULTS)
                merged.update(self._resource_configs.get(key, {}))
                entries = [{"name": n, "value": v, "read_only": False,
                            "source": 5, "sensitive": False,
                            "synonyms": []}
                           for n, v in sorted(merged.items())]
                out.append({"error_code": inject.wire if inject else 0,
                            "error_message": None,
                            "resource_type": r["resource_type"],
                            "resource_name": r["resource_name"],
                            "entries": entries})
        return {"throttle_time_ms": 0, "resources": out}

    def _h_AlterConfigs(self, conn, corrid, hdr, body, inject):
        # stateful like a real broker: altered entries are visible to a
        # following DescribeConfigs
        out = []
        with self._lock:
            for r in body["resources"]:
                key = (r["resource_type"], r["resource_name"])
                if not (inject and inject.wire):
                    store = self._resource_configs.setdefault(key, {})
                    for e in r.get("entries") or []:
                        store[e["name"]] = e["value"]
                out.append({"error_code": inject.wire if inject else 0,
                            "error_message": None,
                            "resource_type": r["resource_type"],
                            "resource_name": r["resource_name"]})
        return {"throttle_time_ms": 0, "resources": out}

    def _h_DescribeGroups(self, conn, corrid, hdr, body, inject):
        out = []
        with self._lock:
            for gid in body["groups"]:
                g = self.groups.get(gid)
                if g is None:
                    out.append({"error_code": 0, "group_id": gid,
                                "state": "Dead", "protocol_type": "",
                                "protocol": "", "members": []})
                    continue
                out.append({
                    "error_code": 0, "group_id": gid, "state": g.state,
                    "protocol_type": g.protocol_type, "protocol": g.protocol,
                    "members": [{"member_id": m.member_id,
                                 "client_id": m.client_id,
                                 "client_host": m.client_host,
                                 "metadata": m.metadata,
                                 "assignment": m.assignment}
                                for m in g.members.values()]})
        return {"groups": out}

    def _h_ListGroups(self, conn, corrid, hdr, body, inject):
        with self._lock:
            groups = [{"group_id": g.group_id,
                       "protocol_type": g.protocol_type}
                      for g in self.groups.values() if g.members]
        return {"error_code": inject.wire if inject else 0, "groups": groups}

    def _h_DeleteGroups(self, conn, corrid, hdr, body, inject):
        out = []
        with self._lock:
            for gid in body["groups"]:
                g = self.groups.get(gid)
                if g is None:
                    err = Err.GROUP_ID_NOT_FOUND
                elif g.members:
                    err = Err.NON_EMPTY_GROUP
                else:
                    del self.groups[gid]
                    err = Err.NO_ERROR
                out.append({"group_id": gid, "error_code": err.wire})
        return {"throttle_time_ms": 0, "results": out}

    def _h_SaslHandshake(self, conn, corrid, hdr, body, inject):
        mechs = ["PLAIN", "SCRAM-SHA-256", "SCRAM-SHA-512", "OAUTHBEARER"]
        err = 0
        if body["mechanism"] not in mechs:
            err = Err.UNSUPPORTED_SASL_MECHANISM.wire
        conn.sasl_mech = body["mechanism"]
        conn.scram = None
        return {"error_code": err, "mechanisms": mechs}

    @staticmethod
    def _sasl_fail(msg="authentication failed"):
        return {"error_code": Err.SASL_AUTHENTICATION_FAILED.wire,
                "error_message": msg, "auth_bytes": b""}

    def _h_SaslAuthenticate(self, conn, corrid, hdr, body, inject):
        data = body["auth_bytes"] or b""
        if inject:
            return self._sasl_fail()
        if conn.sasl_mech.startswith("SCRAM") or conn.scram is not None:
            return self._scram_auth(conn, data)
        if conn.sasl_mech == "OAUTHBEARER":
            # "n,a=...,\x01auth=Bearer <jws>\x01\x01" — accept any
            # well-formed unsecured JWS (the reference's builtin handler
            # produces exactly this shape)
            ok = data.startswith(b"n,") and b"\x01auth=Bearer " in data
            return ({"error_code": 0, "error_message": None,
                     "auth_bytes": b""} if ok else self._sasl_fail())
        # PLAIN: [authzid] \0 authcid \0 passwd
        parts = data.split(b"\x00")
        if len(parts) != 3 or not parts[1] or not parts[2]:
            return self._sasl_fail()
        if self.sasl_users is not None:
            user, pw = parts[1].decode(), parts[2].decode()
            if self.sasl_users.get(user) != pw:
                return self._sasl_fail()
        return {"error_code": 0, "error_message": None, "auth_bytes": b""}

    def _scram_auth(self, conn, data: bytes):
        """Server half of RFC 5802 (the peer of the client exchange in
        client/sasl.py ScramClient; reference server behavior is the real
        broker's — rdkafka_sasl_scram.c only implements the client)."""
        import base64
        import hashlib
        import hmac
        import os
        hashname = ("sha256" if conn.sasl_mech == "SCRAM-SHA-256"
                    else "sha512")
        if conn.scram is None:
            if self.sasl_users is None:
                return self._sasl_fail("SCRAM requires mock sasl_users")
            try:
                txt = data.decode()
                if not txt.startswith("n,,"):
                    return self._sasl_fail("bad GS2 header")
                bare = txt[3:]
                fields = dict(kv.split("=", 1) for kv in bare.split(","))
                user = fields["n"].replace("=2C", ",").replace("=3D", "=")
                cnonce = fields["r"]
            except (ValueError, KeyError, UnicodeDecodeError):
                return self._sasl_fail("malformed client-first")
            pw = self.sasl_users.get(user)
            if pw is None:
                return self._sasl_fail("unknown user")
            salt = os.urandom(16)
            iters = 4096
            snonce = base64.b64encode(os.urandom(18)).decode()
            server_first = (f"r={cnonce}{snonce},"
                            f"s={base64.b64encode(salt).decode()},i={iters}")
            salted = hashlib.pbkdf2_hmac(hashname, pw.encode(), salt, iters)
            conn.scram = (bare, server_first, salted)
            return {"error_code": 0, "error_message": None,
                    "auth_bytes": server_first.encode()}
        bare, server_first, salted = conn.scram
        conn.scram = None
        try:
            txt = data.decode()
            without_proof, _, proof_b64 = txt.rpartition(",p=")
            fields = dict(kv.split("=", 1) for kv in without_proof.split(","))
            proof = base64.b64decode(proof_b64)
        except (ValueError, UnicodeDecodeError):
            return self._sasl_fail("malformed client-final")
        expect_nonce = dict(kv.split("=", 1)
                            for kv in server_first.split(","))["r"]
        if fields.get("r") != expect_nonce:
            return self._sasl_fail("nonce mismatch")
        auth_msg = ",".join([bare, server_first, without_proof]).encode()
        client_key = hmac.new(salted, b"Client Key", hashname).digest()
        stored_key = hashlib.new(hashname, client_key).digest()
        sig = hmac.new(stored_key, auth_msg, hashname).digest()
        recovered = bytes(a ^ b for a, b in zip(proof, sig))
        if hashlib.new(hashname, recovered).digest() != stored_key:
            return self._sasl_fail("proof verification failed")
        server_key = hmac.new(salted, b"Server Key", hashname).digest()
        v = base64.b64encode(
            hmac.new(server_key, auth_msg, hashname).digest()).decode()
        return {"error_code": 0, "error_message": None,
                "auth_bytes": f"v={v}".encode()}
