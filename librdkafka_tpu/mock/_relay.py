"""Broker serving-plane relay: one OS process per mock broker.

Executed BY PATH (``python .../mock/_relay.py``) from the standalone
supervisor — deliberately not ``-m``: the relay must stay pure-stdlib
and never import the package (or JAX), so a broker process costs
milliseconds to spawn and dies instantly under SIGKILL.

The relay binds the broker's PUBLIC port and shuttles bytes to the
supervisor's internal MockCluster listener for that broker.  The split
mirrors a replicated deployment: the supervisor holds the storage/
controller plane (what an acks=all quorum would preserve), the relay
IS the broker process clients talk to — ``kill -9`` takes the port
down mid-write (half-written frames lost, connects refused),
``SIGSTOP``/``SIGCONT`` freeze it like a GC pause or VM migration,
and the client must survive with the delivery contract intact.

Handshake: one JSON line on stdout — ``{"broker", "port", "pid"}``.
Exits when stdin reaches EOF (supervisor died or closed the pipe), so
an orphaned relay can never linger eating the host.
"""
import argparse
import json
import os
import selectors
import socket
import sys

RECV_CHUNK = 65536
#: per-direction backpressure cap: stop reading a side whose peer is
#: this far behind (a slow client must not balloon the relay)
BUF_MAX = 1 << 20


class _Half:
    """One direction's state: bytes waiting to be written to ``sock``."""

    __slots__ = ("sock", "peer", "buf", "reading")

    def __init__(self, sock):
        self.sock = sock
        self.peer = None
        self.buf = bytearray()
        self.reading = True


def _events(h: _Half) -> int:
    ev = 0
    if h.reading:
        ev |= selectors.EVENT_READ
    if h.buf:
        ev |= selectors.EVENT_WRITE
    return ev


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--broker-id", type=int, required=True)
    ap.add_argument("--port", type=int, default=0,
                    help="public port to bind (0 = ephemeral; restarts "
                         "pass the original port back in)")
    ap.add_argument("--upstream", required=True, metavar="HOST:PORT",
                    help="the supervisor's internal listener for this "
                         "broker")
    args = ap.parse_args(argv)
    uhost, _, uport = args.upstream.rpartition(":")

    ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    ls.bind(("127.0.0.1", args.port))
    ls.listen(64)
    ls.setblocking(False)

    print(json.dumps({"broker": args.broker_id,
                      "port": ls.getsockname()[1],
                      "pid": os.getpid()}), flush=True)

    sel = selectors.DefaultSelector()
    sel.register(ls, selectors.EVENT_READ, "accept")
    # parent-death watch: stdin is a pipe from the supervisor; EOF
    # means it is gone (or told us to exit) — no polling anywhere
    sel.register(sys.stdin.fileno(), selectors.EVENT_READ, "stdin")

    halves: dict[socket.socket, _Half] = {}

    def close_pair(h: _Half):
        for side in (h, h.peer):
            if side is None or side.sock not in halves:
                continue
            try:
                sel.unregister(side.sock)
            except (KeyError, ValueError):
                pass
            try:
                side.sock.close()
            except OSError:
                pass
            del halves[side.sock]

    def update(h: _Half):
        try:
            sel.modify(h.sock, _events(h), "conn")
        except (KeyError, ValueError):
            pass

    while True:
        for key, mask in sel.select():
            if key.data == "stdin":
                if not os.read(sys.stdin.fileno(), 4096):
                    return 0
                continue
            if key.data == "accept":
                try:
                    cs, _ = ls.accept()
                except OSError:
                    continue
                us = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                try:
                    us.settimeout(5.0)
                    us.connect((uhost or "127.0.0.1", int(uport)))
                except OSError:
                    # storage plane unreachable (broker marked down but
                    # relay still alive — restart race): drop the client
                    cs.close()
                    us.close()
                    continue
                cs.setblocking(False)
                us.setblocking(False)
                ch, uh = _Half(cs), _Half(us)
                ch.peer, uh.peer = uh, ch
                halves[cs] = ch
                halves[us] = uh
                sel.register(cs, _events(ch), "conn")
                sel.register(us, _events(uh), "conn")
                continue

            h = halves.get(key.fileobj)
            if h is None:
                continue
            if mask & selectors.EVENT_READ:
                try:
                    data = h.sock.recv(RECV_CHUNK)
                except BlockingIOError:
                    data = None
                except OSError:
                    close_pair(h)
                    continue
                if data == b"":
                    close_pair(h)
                    continue
                if data:
                    dst = h.peer
                    dst.buf += data
                    try:
                        sent = dst.sock.send(dst.buf)
                        del dst.buf[:sent]
                    except BlockingIOError:
                        pass
                    except OSError:
                        close_pair(h)
                        continue
                    if len(dst.buf) > BUF_MAX:
                        h.reading = False
                    update(dst)
                    update(h)
            if mask & selectors.EVENT_WRITE and h.sock in halves:
                try:
                    if h.buf:
                        sent = h.sock.send(h.buf)
                        del h.buf[:sent]
                except BlockingIOError:
                    pass
                except OSError:
                    close_pair(h)
                    continue
                if len(h.buf) <= BUF_MAX and h.peer is not None \
                        and not h.peer.reading:
                    h.peer.reading = True
                    update(h.peer)
                update(h)


if __name__ == "__main__":
    sys.exit(main())
