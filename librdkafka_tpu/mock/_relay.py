"""Broker serving-plane relay: one OS process per mock broker.

Executed BY PATH (``python .../mock/_relay.py``) from the standalone
supervisor — deliberately not ``-m``: the relay must stay pure-stdlib
and never import the package (or JAX), so a broker process costs
milliseconds to spawn and dies instantly under SIGKILL.

The relay binds the broker's PUBLIC port and shuttles bytes to the
supervisor's internal MockCluster listener for that broker.  The split
mirrors a replicated deployment: the supervisor holds the storage/
controller plane (what an acks=all quorum would preserve), the relay
IS the broker process clients talk to — ``kill -9`` takes the port
down mid-write (half-written frames lost, connects refused),
``SIGSTOP``/``SIGCONT`` freeze it like a GC pause or VM migration,
and the client must survive with the delivery contract intact.

**Asymmetric brownouts** (ISSUE 11, the out-of-process analog of
sockem's one-direction rx_drop/tx_drop + latency): live-settable knobs
arrive as JSON command lines on stdin::

    {"set": {"rx_drop": true}}            broker->client data discarded
    {"set": {"tx_drop": true}}            client->broker data discarded
    {"set": {"rx_delay_ms": 200}}         broker->client latency
    {"set": {"tx_delay_ms": 50}}          client->broker latency
    {"set": {}}  /  all-zero knobs        heal

Each command is acked with one JSON line on stdout
(``{"ok": true, "knobs": {...}}``).  Directions are client-relative,
matching sockem: **tx** = client->broker, **rx** = broker->client —
so ``rx_drop`` is the classic half-open partition where the broker
hears requests but its responses vanish.

**Observability** (ISSUE 20) rides the same stdin channel::

    {"trace": 1|0}      enable/disable this relay's trace rings
    {"clock": 1}        ack carries mono_ns (clock offset exchange)
    {"trace_dump": 1}   ack carries pid + the whole ring dump inline

The tracer (obs/trace.py, itself pure stdlib) is loaded BY PATH on
first enable, so the relay never imports the package and its cold
startup stays milliseconds.  Instrumentation is per-connection, not
per-chunk: a ``conn_setup`` span around accept+upstream-connect and a
``conn`` span over each connection's lifetime.

Handshake: one JSON line on stdout — ``{"broker", "port", "pid"}``.
Exits when stdin reaches EOF (supervisor died or closed the pipe), so
an orphaned relay can never linger eating the host.
"""
import argparse
import json
import os
import selectors
import socket
import sys
import time

RECV_CHUNK = 65536
#: per-direction backpressure cap: stop reading a side whose peer is
#: this far behind (a slow client must not balloon the relay)
BUF_MAX = 1 << 20

#: live brownout knobs (stdin-settable; read per-chunk)
KNOBS = {"rx_drop": False, "tx_drop": False,
         "rx_delay_ms": 0.0, "tx_delay_ms": 0.0}

#: obs/trace.py module once {"trace": 1} loaded it by path (the relay
#: must never import the package — see the module docstring)
TRACE = None


def _load_trace():
    global TRACE
    if TRACE is None:
        import importlib.util
        path = os.path.abspath(os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            os.pardir, "obs", "trace.py"))
        spec = importlib.util.spec_from_file_location("_relay_trace", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        TRACE = mod
    return TRACE


class _Half:
    """One direction's state: bytes waiting to be written to ``sock``
    plus any delayed chunks still being 'held in flight'."""

    __slots__ = ("sock", "peer", "buf", "reading", "dir_read", "holdq",
                 "held", "t0")

    def __init__(self, sock, dir_read):
        self.sock = sock
        self.peer = None
        self.buf = bytearray()
        self.reading = True
        #: direction label of data READ from this sock ("tx" for the
        #: client-side half, "rx" for the upstream/broker-side half)
        self.dir_read = dir_read
        #: delayed chunks headed FOR this sock: [(release_t, bytes)]
        self.holdq = []
        self.held = 0               # total bytes in holdq
        self.t0 = 0                 # trace stamp at accept (conn span)


def _events(h: _Half) -> int:
    ev = 0
    if h.reading:
        ev |= selectors.EVENT_READ
    if h.buf:
        ev |= selectors.EVENT_WRITE
    return ev


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--broker-id", type=int, required=True)
    ap.add_argument("--port", type=int, default=0,
                    help="public port to bind (0 = ephemeral; restarts "
                         "pass the original port back in)")
    ap.add_argument("--upstream", required=True, metavar="HOST:PORT",
                    help="the supervisor's internal listener for this "
                         "broker")
    args = ap.parse_args(argv)
    uhost, _, uport = args.upstream.rpartition(":")

    ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    ls.bind(("127.0.0.1", args.port))
    ls.listen(64)
    ls.setblocking(False)

    print(json.dumps({"broker": args.broker_id,
                      "port": ls.getsockname()[1],
                      "pid": os.getpid()}), flush=True)

    sel = selectors.DefaultSelector()
    sel.register(ls, selectors.EVENT_READ, "accept")
    # parent-death watch + brownout command channel: stdin is a pipe
    # from the supervisor; EOF means it is gone
    sel.register(sys.stdin.fileno(), selectors.EVENT_READ, "stdin")
    stdin_buf = bytearray()

    halves: dict[socket.socket, _Half] = {}

    def close_pair(h: _Half):
        if TRACE is not None and TRACE.enabled:
            for side in (h, h.peer):
                if side is not None and side.t0 and side.sock in halves:
                    TRACE.complete("relay", "conn", side.t0,
                                   {"broker": args.broker_id})
                    side.t0 = 0
        for side in (h, h.peer):
            if side is None or side.sock not in halves:
                continue
            try:
                sel.unregister(side.sock)
            except (KeyError, ValueError):
                pass
            try:
                side.sock.close()
            except OSError:
                pass
            del halves[side.sock]

    def update(h: _Half):
        try:
            sel.modify(h.sock, _events(h), "conn")
        except (KeyError, ValueError):
            pass

    def deliver(dst: _Half, data) -> None:
        """Queue ``data`` for ``dst``'s socket and push what fits now;
        applies the backpressure contract on the reading side."""
        src = dst.peer
        dst.buf += data
        try:
            sent = dst.sock.send(dst.buf)
            del dst.buf[:sent]
        except BlockingIOError:
            pass
        except OSError:
            close_pair(dst)
            return
        if src is not None and len(dst.buf) + dst.held > BUF_MAX:
            src.reading = False
            update(src)
        update(dst)

    def handle_cmd(line: bytes) -> None:
        try:
            cmd = json.loads(line)
        except ValueError:
            print(json.dumps({"ok": False, "error": "bad json"}),
                  flush=True)
            return
        if "trace" in cmd:
            tr = _load_trace()
            if cmd["trace"]:
                tr.enable()
            else:
                tr.disable()
            print(json.dumps({"ok": True, "trace": bool(cmd["trace"])}),
                  flush=True)
            return
        if cmd.get("clock"):
            print(json.dumps({"ok": True,
                              "mono_ns": time.monotonic_ns()}),
                  flush=True)
            return
        if cmd.get("trace_dump"):
            evs = (TRACE.collect_events()
                   if TRACE is not None and TRACE.enabled else [])
            print(json.dumps({"ok": True, "pid": os.getpid(),
                              "mono_ns": time.monotonic_ns(),
                              "events": evs},
                             separators=(",", ":")), flush=True)
            return
        knobs = cmd.get("set") or {}
        for k, v in knobs.items():
            if k in ("rx_drop", "tx_drop"):
                KNOBS[k] = bool(v)
            elif k in ("rx_delay_ms", "tx_delay_ms"):
                KNOBS[k] = float(v)
        print(json.dumps({"ok": True, "knobs": KNOBS}), flush=True)

    while True:
        # release due held chunks first; the nearest future release
        # bounds the select timeout so latency injection stays accurate
        now = time.monotonic()
        timeout = None
        for h in list(halves.values()):
            while h.holdq and h.holdq[0][0] <= now:
                _t, data = h.holdq.pop(0)
                h.held -= len(data)
                deliver(h, data)
                if h.sock not in halves:
                    break
            if h.sock in halves and h.holdq:
                left = h.holdq[0][0] - now
                timeout = left if timeout is None else min(timeout, left)
        if timeout is not None:
            timeout = max(0.0, timeout)

        for key, mask in sel.select(timeout):
            if key.data == "stdin":
                chunk = os.read(sys.stdin.fileno(), 4096)
                if not chunk:
                    return 0
                stdin_buf += chunk
                while b"\n" in stdin_buf:
                    raw, _, rest = bytes(stdin_buf).partition(b"\n")
                    stdin_buf = bytearray(rest)
                    if raw.strip():
                        handle_cmd(raw)
                continue
            if key.data == "accept":
                t_acc = (TRACE.now() if TRACE is not None
                         and TRACE.enabled else 0)
                try:
                    cs, _ = ls.accept()
                except OSError:
                    continue
                us = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                try:
                    us.settimeout(5.0)
                    us.connect((uhost or "127.0.0.1", int(uport)))
                except OSError:
                    # storage plane unreachable (broker marked down but
                    # relay still alive — restart race): drop the client
                    cs.close()
                    us.close()
                    continue
                cs.setblocking(False)
                us.setblocking(False)
                ch, uh = _Half(cs, "tx"), _Half(us, "rx")
                ch.peer, uh.peer = uh, ch
                halves[cs] = ch
                halves[us] = uh
                sel.register(cs, _events(ch), "conn")
                sel.register(us, _events(uh), "conn")
                if t_acc:
                    # span over accept + upstream connect; the conn
                    # span itself closes with the pair
                    ch.t0 = t_acc
                    TRACE.complete("relay", "conn_setup", t_acc,
                                   {"broker": args.broker_id})
                continue

            h = halves.get(key.fileobj)
            if h is None:
                continue
            if mask & selectors.EVENT_READ:
                try:
                    data = h.sock.recv(RECV_CHUNK)
                except BlockingIOError:
                    data = None
                except OSError:
                    close_pair(h)
                    continue
                if data == b"":
                    close_pair(h)
                    continue
                if data:
                    # one-direction partition: silently discard this
                    # direction's traffic while its drop knob is set
                    # (the peer still sees an established connection —
                    # a half-open partition, not a close)
                    if KNOBS[h.dir_read + "_drop"]:
                        continue
                    delay = KNOBS[h.dir_read + "_delay_ms"]
                    dst = h.peer
                    if delay > 0:
                        dst.holdq.append(
                            (time.monotonic() + delay / 1000.0, data))
                        dst.held += len(data)
                        if len(dst.buf) + dst.held > BUF_MAX:
                            h.reading = False
                            update(h)
                    else:
                        deliver(dst, data)
            if mask & selectors.EVENT_WRITE and h.sock in halves:
                try:
                    if h.buf:
                        sent = h.sock.send(h.buf)
                        del h.buf[:sent]
                except BlockingIOError:
                    pass
                except OSError:
                    close_pair(h)
                    continue
                if (len(h.buf) + h.held <= BUF_MAX and h.peer is not None
                        and not h.peer.reading):
                    h.peer.reading = True
                    update(h.peer)
                update(h)


if __name__ == "__main__":
    sys.exit(main())
