"""Client-side handle for the supervised out-of-process mock cluster.

``ClusterHandle`` launches ``python -m librdkafka_tpu.mock.standalone
--supervise`` as a subprocess, parses its JSON handshake, and speaks
the supervisor's line-protocol control plane.  It presents the same
target-resolution surface the chaos schedule DSL resolves against on
an in-process ``MockCluster`` — ``alive_brokers()``, ``controller_id``,
``coordinator_for``, ``topics``/``partition``, ``kill_broker``/
``kill9``/``restart_broker``/``pause_broker``/``resume_broker``,
``set_partition_leader`` — so one ``Schedule`` drives either tier and
``replay_key`` stays seed-deterministic against real OS processes.

Every spawned pid (supervisor + brokers) is tracked in a module-level
registry; the conftest leak fixture asserts it empty after every test
and ``reap_leaked()`` SIGKILLs stragglers so one leaked rig cannot
poison the rest of the suite.
"""
from __future__ import annotations

import json
import os
import selectors
import signal
import socket
import subprocess
import sys
import time
from collections import namedtuple
from typing import Optional

from ..analysis.locks import new_lock

#: pid -> "what" for every live subprocess any ClusterHandle spawned
#: (supervisor and brokers); asserted empty by the conftest leak
#: fixture after each test
_ACTIVE_PIDS: dict[int, str] = {}
_REG_LOCK = new_lock("mock.external.registry")

PartView = namedtuple("PartView", ["leader"])


def active_subprocess_pids() -> dict[int, str]:
    """Snapshot of the live standalone-subprocess registry."""
    with _REG_LOCK:
        return dict(_ACTIVE_PIDS)


def reap_leaked() -> list[int]:
    """SIGKILL every registered subprocess and clear the registry —
    the leak fixture's cleanup arm, so a test that lost its handle
    fails loudly HERE instead of starving every later test."""
    with _REG_LOCK:
        pids = list(_ACTIVE_PIDS)
        _ACTIVE_PIDS.clear()
    for pid in pids:
        try:
            os.kill(pid, signal.SIGKILL)
        except (ProcessLookupError, OSError):
            pass
    return pids


def _register(pids: dict[int, str]) -> None:
    with _REG_LOCK:
        _ACTIVE_PIDS.update(pids)


def _deregister(pids) -> None:
    with _REG_LOCK:
        for pid in pids:
            _ACTIVE_PIDS.pop(pid, None)


# public registry surface for other subprocess-spawning rigs (the
# fleet driver registers every worker pid here, so the one conftest
# leak fixture polices brokers AND fleet clients)
def register_pids(pids: dict[int, str]) -> None:
    _register(pids)


def deregister_pids(pids) -> None:
    _deregister(pids)


def pid_alive(pid: int) -> bool:
    """True iff ``pid`` still exists (signal-0 probe)."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


class ClusterHandle:  # lint: ok shared-state
    """A supervised N-broker-process mock cluster, as one object.

    shared-state pragma: down/paused sets and proc_events are mutated
    only under ``mock.external.handle``; the control-plane socket is
    the cross-process boundary (no shared memory).

    >>> h = ClusterHandle(brokers=3, topics={"chaos": 4})
    >>> h.bootstrap_servers()
    '127.0.0.1:...,...'
    >>> h.kill9(2)            # real SIGKILL of broker 2's OS process
    >>> h.restart_broker(2)   # same public port, fresh pid
    >>> h.stop()
    """

    def __init__(self, brokers: int = 3, topics: Optional[dict] = None,
                 default_partitions: int = 4,
                 launch_timeout: float = 60.0):
        self.num_brokers = brokers
        self._lock = new_lock("mock.external.handle")
        self._down: set[int] = set()
        self._paused: set[int] = set()
        #: every confirmed process fault, for reports/tests:
        #: {"verb", "broker", "pid", "exit"/"new_pid", "verified_dead"}
        self.proc_events: list[dict] = []
        self._stopped = False

        cmd = [sys.executable, "-m", "librdkafka_tpu.mock.standalone",
               "--supervise", "--brokers", str(brokers),
               "--partitions", str(default_partitions)]
        for name, parts in (topics or {}).items():
            cmd += ["--topic", f"{name}:{parts}"]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = (pkg_parent + os.pathsep
                             + env.get("PYTHONPATH", "")).rstrip(os.pathsep)
        self._proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, env=env)
        try:
            self.handshake = self._read_handshake(launch_timeout)
            self.control_port = self.handshake["control"]
            self.broker_ports = {int(b): info["port"] for b, info
                                 in self.handshake["brokers"].items()}
            self.broker_pids = {int(b): info["pid"] for b, info
                                in self.handshake["brokers"].items()}
            self._ctl = socket.create_connection(
                ("127.0.0.1", self.control_port), timeout=20)
            self._ctl_buf = b""
        except Exception:
            self._proc.kill()
            self._proc.wait()
            raise
        _register({self._proc.pid: "standalone-supervisor",
                   **{pid: f"standalone-broker-{b}"
                      for b, pid in self.broker_pids.items()}})

    # ----------------------------------------------------------- wire --
    def _read_handshake(self, timeout: float) -> dict:
        fd = self._proc.stdout.fileno()
        sel = selectors.DefaultSelector()
        sel.register(fd, selectors.EVENT_READ)
        deadline = time.monotonic() + timeout
        buf = b""
        try:
            while b"\n" not in buf:
                left = deadline - time.monotonic()
                if left <= 0 or not sel.select(timeout=left):
                    raise TimeoutError(
                        f"supervisor handshake not received in {timeout}s")
                chunk = os.read(fd, 4096)
                if not chunk:
                    rc = self._proc.poll()
                    raise RuntimeError(
                        f"supervisor exited during handshake (rc={rc})")
                buf += chunk
        finally:
            sel.close()
        return json.loads(buf.split(b"\n", 1)[0])

    def _ctl_cmd(self, line: str) -> dict:
        """One control round-trip; raises on protocol/transport error,
        returns the decoded JSON reply (``{"error": ...}`` replies
        raise RuntimeError so schedules record them in the timeline)."""
        with self._lock:
            self._ctl.sendall(line.encode() + b"\n")
            while b"\n" not in self._ctl_buf:
                chunk = self._ctl.recv(65536)
                if not chunk:
                    raise ConnectionError("supervisor control socket EOF")
                self._ctl_buf += chunk
            raw, _, self._ctl_buf = self._ctl_buf.partition(b"\n")
        resp = json.loads(raw)
        if "error" in resp:
            raise RuntimeError(f"control {line.split()[0]!r}: "
                               f"{resp['error']}")
        return resp

    # ---------------------------------------- schedule target surface --
    def bootstrap_servers(self) -> str:
        return self.handshake["bootstrap"]

    def alive_brokers(self) -> list[int]:
        with self._lock:
            return [b for b in range(1, self.num_brokers + 1)
                    if b not in self._down]

    def paused_brokers(self) -> list[int]:
        with self._lock:
            return sorted(self._paused)

    @property
    def controller_id(self) -> int:
        return self.status()["controller"]

    def coordinator_for(self, key: str) -> int:
        return self._ctl_cmd(f"coordinator {key}")["broker"]

    @property
    def topics(self) -> dict[str, list[PartView]]:
        st = self.status()
        return {t: [PartView(leader=ld) for ld in leaders]
                for t, leaders in st["topics"].items()}

    def partition(self, topic: str, part: int) -> PartView:
        return self.topics[topic][part]

    def set_partition_leader(self, topic: str, part: int,
                             broker_id: int) -> None:
        self._ctl_cmd(f"leader {topic} {part} {broker_id}")

    def create_topic(self, name: str, partitions: int = 4) -> None:
        self._ctl_cmd(f"create_topic {name} {partitions}")

    def status(self) -> dict:
        return self._ctl_cmd("status")

    # ----------------------------------------------- process faults --
    def kill9(self, broker_id: int) -> dict:
        """SIGKILL broker ``broker_id``'s OS process.  Returns after
        the supervisor reaped it and migrated leadership; the event —
        with pid-liveness verification — lands in ``proc_events``."""
        resp = self._ctl_cmd(f"kill9 {broker_id}")
        pid = resp["pid"]
        with self._lock:
            self._down.add(broker_id)
            self._paused.discard(broker_id)
            self.proc_events.append({
                "verb": "kill9", "broker": broker_id, "pid": pid,
                "exit": resp.get("exit"),
                # reaped by the supervisor => the pid must be GONE
                "verified_dead": not pid_alive(pid)})
        _deregister([pid])
        return resp

    # the generic schedule verbs map onto the process faults, so a
    # Schedule written for MockCluster drives this handle unchanged
    kill_broker = kill9

    def restart_broker(self, broker_id: int) -> dict:
        resp = self._ctl_cmd(f"restart {broker_id}")
        with self._lock:
            self._down.discard(broker_id)
            self.broker_pids[broker_id] = resp["pid"]
            self.proc_events.append({
                "verb": "restart", "broker": broker_id,
                "pid": resp["pid"], "port": resp["port"]})
        _register({resp["pid"]: f"standalone-broker-{broker_id}"})
        return resp

    def pause_broker(self, broker_id: int) -> dict:
        resp = self._ctl_cmd(f"stop {broker_id}")
        with self._lock:
            self._paused.add(broker_id)
            self.proc_events.append({"verb": "pause", "broker": broker_id,
                                     "pid": resp.get("pid")})
        return resp

    def resume_broker(self, broker_id: int) -> dict:
        resp = self._ctl_cmd(f"cont {broker_id}")
        with self._lock:
            self._paused.discard(broker_id)
            self.proc_events.append({"verb": "resume", "broker": broker_id,
                                     "pid": resp.get("pid")})
        return resp

    # ------------------------------------ environment fault library --
    def set_storage_error(self, broker_id: Optional[int] = None,
                          on: bool = True) -> dict:
        """Disk-full/EIO window on the supervisor's storage plane
        (``env_eio``): Produce on the affected broker(s) returns
        KAFKA_STORAGE_ERROR until healed.  None = every broker."""
        resp = self._ctl_cmd(f"eio {broker_id or 0} {1 if on else 0}")
        with self._lock:
            self.proc_events.append({"verb": "eio", "broker": broker_id,
                                     "on": on})
        return resp

    def set_clock_skew(self, broker_id: int, skew_ms: float = 0.0) -> dict:
        """Clock-skew fault (``env_skew``): broker ``broker_id``'s
        wall clock reads ``skew_ms`` off true (0 heals)."""
        resp = self._ctl_cmd(f"skew {broker_id} {skew_ms}")
        with self._lock:
            self.proc_events.append({"verb": "skew", "broker": broker_id,
                                     "skew_ms": skew_ms})
        return resp

    def set_rlimit(self, broker_id: int, nbytes: int) -> dict:
        """Memory pressure (``env_rlimit``): soft RLIMIT_AS on the
        broker's relay OS process via prlimit (0 restores infinity)."""
        resp = self._ctl_cmd(f"rlimit {broker_id} {int(nbytes)}")
        with self._lock:
            self.proc_events.append({"verb": "rlimit",
                                     "broker": broker_id,
                                     "pid": resp.get("pid"),
                                     "soft": resp.get("soft")})
        return resp

    def brownout(self, broker_id: int, *, rx_drop: bool = False,
                 tx_drop: bool = False, rx_delay_ms: float = 0.0,
                 tx_delay_ms: float = 0.0) -> dict:
        """Asymmetric-partition brownout (``env_brownout``): live
        one-direction drop/latency knobs on the broker's relay — the
        out-of-process analog of sockem's rx_drop/tx_drop."""
        knobs = {"rx_drop": rx_drop, "tx_drop": tx_drop,
                 "rx_delay_ms": rx_delay_ms, "tx_delay_ms": tx_delay_ms}
        blob = json.dumps(knobs, separators=(",", ":"))
        resp = self._ctl_cmd(f"brownout {broker_id} {blob}")
        with self._lock:
            self.proc_events.append({"verb": "brownout",
                                     "broker": broker_id, **knobs})
        return resp

    def clear_brownout(self, broker_id: int) -> dict:
        return self.brownout(broker_id)

    # --------------------------------------------------- observability --
    def trace_enable(self) -> dict:
        """Rig-wide tracing on: supervisor rings + every relay's
        (ISSUE 20; relays respawned by ``restart`` rejoin)."""
        return self._ctl_cmd("trace 1")

    def trace_disable(self) -> dict:
        return self._ctl_cmd("trace 0")

    def collect_traces(self) -> list:
        """The rig's per-process dumps for ``obs/collect.merge``:
        supervisor + every alive relay, every clock mapped into THIS
        process's timebase (handle->supervisor offset from the
        ``clock`` verb round trip, supervisor->relay offsets measured
        supervisor-side and composed here)."""
        from ..obs import collect as _collect
        t_send = time.monotonic_ns()
        ck = self._ctl_cmd("clock")
        t_recv = time.monotonic_ns()
        sup_off, sup_err = _collect.align_offset(
            t_send, ck["mono_ns"], t_recv)
        resp = self._ctl_cmd("trace_dump")
        return [_collect.ProcessDump(
                    p["name"], p.get("pid") or 0, p.get("events", []),
                    sup_off + p.get("offset_ns", 0),
                    sup_err + p.get("err_ns", 0))
                for p in resp.get("procs", [])]

    # -------------------------------------------------------- teardown --
    def pids(self) -> dict[str, int]:
        with self._lock:
            return {"supervisor": self._proc.pid,
                    **{f"broker-{b}": pid
                       for b, pid in self.broker_pids.items()}}

    def stop(self) -> None:
        """Tear the whole rig down and deregister every pid
        (idempotent).  Escalates: control shutdown -> stdin EOF ->
        SIGKILL, then verifies each broker pid is actually gone."""
        if self._stopped:
            return
        self._stopped = True
        try:
            self._ctl_cmd("shutdown")
        except (OSError, RuntimeError, ConnectionError, json.JSONDecodeError):
            pass
        try:
            self._ctl.close()
        except OSError:
            pass
        try:
            self._proc.stdin.close()       # EOF: second exit trigger
        except OSError:
            pass
        try:
            self._proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            try:
                self._proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        try:
            self._proc.stdout.close()
        except OSError:
            pass
        # the supervisor kills its children on shutdown; SIGKILL any
        # survivor (e.g. supervisor itself was SIGKILLed mid-test)
        with self._lock:
            broker_pids = list(self.broker_pids.values())
        for pid in broker_pids:
            if pid_alive(pid):
                try:
                    os.kill(pid, signal.SIGKILL)
                except (ProcessLookupError, OSError):
                    pass
        _deregister([self._proc.pid] + broker_pids)

    def __enter__(self) -> "ClusterHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
