"""librdkafka_tpu.mock — in-process mock cluster (`cluster.py`), the
sockem network-shaping shim (`sockem.py`), and the out-of-process tier
(`standalone.py --supervise` supervisor + `_relay.py` broker processes
+ `external.py` ClusterHandle).  See CHAOS.md for the tier comparison.

Submodules import lazily on purpose: pulling ClusterHandle in here
eagerly would make every client import pay for the subprocess
machinery.
"""
