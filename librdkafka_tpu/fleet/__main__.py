"""CLI fleet runner: ``python -m librdkafka_tpu.fleet``.

    python -m librdkafka_tpu.fleet --list
    python -m librdkafka_tpu.fleet --scenario fleet_smoke --seed 51
    python -m librdkafka_tpu.fleet --fast        # tier-1 set
    python -m librdkafka_tpu.fleet --all         # including the flagship

Exit status 0 iff every requested run's merged-oracle verdict is
clean.  ``replay_key`` + ``--seed`` is the replay workflow, exactly
like the chaos CLI: same seed ⇒ same plan digest + fault timeline,
against freshly launched rigs.
"""
from __future__ import annotations

import argparse
import json
import sys

from ..chaos.oracle import OracleViolation
from .scenarios import SCENARIOS


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m librdkafka_tpu.fleet",
        description="multi-process client fleets against the "
                    "supervised out-of-process cluster")
    ap.add_argument("--scenario", action="append", default=[],
                    help="scenario name (repeatable); see --list")
    ap.add_argument("--seed", type=int, default=None,
                    help="override the scenario's default seed "
                         "(replay-from-seed)")
    ap.add_argument("--fast", action="store_true",
                    help="run the fast (tier-1) scenario set")
    ap.add_argument("--all", action="store_true",
                    help="run every scenario, flagship included")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios (name, tier, default seed, "
                         "invariants checked) and exit")
    args = ap.parse_args(argv)

    if args.list:
        print(f"{'scenario':24s} {'tier':5s} {'seed':>5s}  "
              f"invariants checked")
        for name, sc in SCENARIOS.items():
            print(f"{name:24s} {sc.tier:5s} {sc.seed:5d}  "
                  f"{sc.invariants}")
            print(f"{'':24s} {'':5s} {'':5s}  - {sc.desc}")
        return 0

    names = list(args.scenario)
    if args.all:
        names = list(SCENARIOS)
    elif args.fast:
        names = [n for n, sc in SCENARIOS.items() if sc.tier == "fast"]
    if not names:
        ap.error("pick --scenario NAME, --fast, or --all (see --list)")

    rc = 0
    for name in names:
        if name not in SCENARIOS:
            print(f"unknown scenario {name!r} (see --list)",
                  file=sys.stderr)
            return 2
        kwargs = {} if args.seed is None else {"seed": args.seed}
        print(f"== {name} ==", file=sys.stderr)
        try:
            report = SCENARIOS[name].fn(**kwargs)
        except OracleViolation as v:
            report = v.report
            rc = 1
        print(json.dumps(report, indent=1, default=str))
        ok = report.get("ok")
        fm = report.get("fleet_metrics") or {}
        print(f"== {name}: {'PASS' if ok else 'FAIL'} "
              f"(workers={report.get('workers')} "
              f"acked={report.get('acked')} "
              f"fleet_msgs_s={fm.get('fleet_msgs_s')})", file=sys.stderr)
        if not ok:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
