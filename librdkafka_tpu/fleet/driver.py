"""Fleet driver: spawn and supervise a population of real client OS
processes, merge their streamed ledgers into delivery oracles, and
aggregate fleet-level metrics.

One ``FleetDriver`` owns N worker subprocesses (``fleet/_worker.py``,
executed by path), speaking the stdin/stdout JSON-line protocol
documented there.  Per worker, a named reader thread
(``fleet-rd-<name>``) ingests the stream:

  * acked-produce rows merge into EVERY group's ``DeliveryOracle``
    (fan-out: each consumer group must independently deliver the whole
    acked set — loss is judged per group, not "someone somewhere saw
    it");
  * consumed rows and group assign/revoke/poll events route to the
    worker's OWN group's oracle, so convergence/coverage/stuck
    invariants hold per group over the merged membership;
  * per-worker stats (produced/acked/consumed counts, produce->ack
    latency percentiles from the worker's HdrHistogram) land in the
    driver's stats table for the fleet aggregate.

Worker pids are registered in ``mock.external``'s subprocess registry
(as ``fleet-worker-<name>``) the moment they spawn, so the conftest
leak fixture fails any test that loses a worker exactly like a lost
broker relay — and ``reap_leaked()`` covers both.

Observability (ISSUE 20, ``trace=True``): the driver enables its own
trace rings, tells every worker to do the same (flight dumps land in
a registered temp dir), runs the clock offset exchange per worker
(``clock_sync``), ingests streamed flight-dump paths and the final
inline ring dumps, and hands ``collect_traces()`` the per-process
dumps that obs/collect.py merges into ONE Perfetto timeline.
"""
from __future__ import annotations

import json
import os
import selectors
import subprocess
import sys
import threading
import time
from typing import Optional

from ..analysis.locks import new_lock
from ..analysis.races import shared_dict, shared_list
from ..chaos.oracle import DeliveryOracle
from ..mock import external
from ..obs import collect as _collect
from ..obs import metrics as _metrics
from ..obs import trace as _trace
from .traffic import TrafficPlan

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "_worker.py")


class _Worker:
    """One spawned client process + its stream bookkeeping."""

    __slots__ = ("spec", "proc", "pid", "reader", "done_evt", "clock")

    def __init__(self, spec: dict, proc: subprocess.Popen):
        self.spec = spec
        self.proc = proc
        self.pid = proc.pid
        self.reader: Optional[threading.Thread] = None
        self.done_evt = threading.Event()
        #: (offset_ns, err_ns) from clock_sync — worker clock into the
        #: driver's timebase; None until the exchange completes
        self.clock: Optional[tuple] = None

    @property
    def name(self) -> str:
        return self.spec["name"]

    @property
    def role(self) -> str:
        return self.spec["role"]


class FleetDriver:  # lint: ok shared-state
    """shared-state pragma is NOT used — the cross-thread tables are
    declared below; procs/pids are start()/stop()-thread-only and the
    per-worker stream state is owned by its reader thread."""

    #: worker name -> latest stats line (reader threads write, the
    #: aggregator reads; all under fleet.driver)
    stats: dict
    #: worker name -> final done summary
    done: dict
    #: worker/protocol errors observed on any stream
    errors: list
    #: clock token -> (worker mono_ns, driver recv mono_ns)
    clock_samples: dict
    #: worker name -> final inline ring-dump payload
    traces: dict
    #: streamed flight-recorder dump records ({worker, path})
    flight_paths: list

    def __init__(self, bootstrap: str, plan: TrafficPlan, *,
                 launch_timeout: float = 30.0,
                 dump_dir: Optional[str] = None,
                 trace: bool = False):
        self.bootstrap = bootstrap
        self.plan = plan
        self.launch_timeout = launch_timeout
        self._lock = new_lock("fleet.driver")
        self.stats = shared_dict("fleet.stats")
        self.done = shared_dict("fleet.done")
        self.errors = shared_list("fleet.errors")
        self.clock_samples = shared_dict("fleet.clock")
        self.traces = shared_dict("fleet.traces")
        self.flight_paths = shared_list("fleet.flight")
        self.trace = trace
        self.trace_dir: Optional[str] = None
        if trace:
            # driver-side rings + a registered flight-dump dir shared
            # with every worker (released in stop(); conftest fails
            # tests that leak it)
            self.trace_dir = _collect.make_dump_dir("tk_fleet_")
            _trace.enable(dump_dir=self.trace_dir)
        # one oracle per consumer group: every group must deliver the
        # whole acked set (record_acks fans out), its own members feed
        # only its own group ledger
        self.oracles = [DeliveryOracle(dump_dir=dump_dir)
                        for _ in range(max(1, plan.n_groups))]
        self.workers: list[_Worker] = []
        self._started = False
        self._stopped = False

    # ------------------------------------------------------- lifecycle --
    def start(self) -> "FleetDriver":
        assert not self._started, "fleet already started"
        self._started = True
        t0 = _trace.now() if _trace.enabled else 0
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = (pkg_parent + os.pathsep
                             + env.get("PYTHONPATH", "")).rstrip(os.pathsep)
        # spawn the whole population first (interpreter startups
        # overlap), then collect handshakes in order
        for spec in self.plan.specs:
            proc = subprocess.Popen(
                [sys.executable, _WORKER],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, env=env)
            w = _Worker(spec, proc)
            self.workers.append(w)
            external.register_pids(
                {proc.pid: f"fleet-worker-{spec['name']}"})
        deadline = time.monotonic() + self.launch_timeout
        try:
            for w in self.workers:
                hs = self._read_handshake(w, deadline)
                assert hs.get("ready") and hs.get("pid") == w.pid, \
                    f"worker {w.name} bad handshake: {hs}"
        except Exception:
            self.stop()
            raise
        for w in self.workers:
            spec = (dict(w.spec, trace=True, flight_dir=self.trace_dir)
                    if self.trace else w.spec)
            self._send(w, {"cmd": "start", "bootstrap": self.bootstrap,
                           "spec": spec})
            w.reader = threading.Thread(
                target=self._read_stream, args=(w,),
                name=f"fleet-rd-{w.name}", daemon=True)
            w.reader.start()
        if t0:
            _trace.complete("fleet", "fleet_start", t0,
                            {"workers": len(self.workers)})
        if _metrics.enabled:
            _metrics.gauge("fleet.workers").set(len(self.workers))
        return self

    def _read_handshake(self, w: _Worker, deadline: float) -> dict:
        fd = w.proc.stdout.fileno()
        sel = selectors.DefaultSelector()
        sel.register(fd, selectors.EVENT_READ)
        try:
            left = deadline - time.monotonic()
            if left <= 0 or not sel.select(timeout=left):
                raise TimeoutError(f"worker {w.name} handshake timeout")
        finally:
            sel.close()
        line = w.proc.stdout.readline()
        if not line:
            raise RuntimeError(
                f"worker {w.name} died at startup "
                f"(exit {w.proc.poll()})")
        return json.loads(line)

    def _send(self, w: _Worker, obj: dict) -> None:
        try:
            w.proc.stdin.write(
                json.dumps(obj, separators=(",", ":")).encode() + b"\n")
            w.proc.stdin.flush()
        except (OSError, ValueError):
            pass                        # already dead; reaped at stop()

    # --------------------------------------------------------- ingest --
    def _read_stream(self, w: _Worker) -> None:
        oracle = self._group_oracle(w)
        for line in iter(w.proc.stdout.readline, b""):
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            t = msg.get("type")
            if t == "acks":
                rows = [(r[0], r[1], r[2], r[3], r[4], None, r[5])
                        for r in msg["rows"]]
                if _metrics.enabled:
                    _metrics.counter("fleet.ack_rows").inc(len(rows))
                for o in self.oracles:
                    o.record_acks(rows)
            elif t == "consumed":
                oracle.record_consumed_rows(
                    [(r[0], r[1], r[2], r[3]) for r in msg["rows"]])
            elif t == "failed":
                for r in msg["rows"]:
                    oracle.record_failed(r[0], r[1], r[2], None, r[3])
            elif t == "group":
                # cooperative workers flag incremental deltas (KIP-429)
                # and revokes carry their partition set; eager events
                # keep the full-replace / full-revoke semantics
                if msg["event"] == "assign":
                    oracle.record_assign(
                        msg["member"],
                        [(p[0], p[1]) for p in msg["parts"]],
                        incremental=bool(msg.get("incremental")))
                elif msg["event"] == "revoke":
                    parts = msg.get("parts") or None
                    oracle.record_revoke(
                        msg["member"],
                        [(p[0], p[1]) for p in parts]
                        if msg.get("incremental") and parts else None)
            elif t == "poll":
                oracle.record_poll(msg["member"])
            elif t == "stats":
                with self._lock:
                    self.stats[msg["name"]] = msg
            elif t == "clock":
                # stamp the receive side of the offset exchange HERE,
                # in the reader, so queueing in clock_sync's poll loop
                # never widens the error bound
                with self._lock:
                    self.clock_samples[msg.get("token")] = (
                        msg["mono_ns"], time.monotonic_ns())
            elif t == "flight":
                if _trace.enabled:
                    _trace.instant("fleet", "flight_collected",
                                   {"worker": w.name})
                with self._lock:
                    self.flight_paths.append({"worker": w.name,
                                              "path": msg.get("path")})
            elif t == "trace":
                with self._lock:
                    self.traces[w.name] = msg
            elif t == "done":
                with self._lock:
                    self.done[msg["name"]] = msg["summary"]
                w.done_evt.set()
            elif t == "error":
                with self._lock:
                    self.errors.append(f"{msg.get('name')}: "
                                       f"{msg.get('error')}")
                w.done_evt.set()

    def _group_oracle(self, w: _Worker) -> DeliveryOracle:
        gi = w.spec.get("group_idx", 0)
        return self.oracles[gi if gi < len(self.oracles) else 0]

    # -------------------------------------------------- observability --
    def clock_sync(self, rounds: int = 3, timeout: float = 30.0) -> dict:
        """Per-worker clock offset exchange (the obs/collect.py model):
        ping each worker ``rounds`` times, keep the minimum-error
        round.  The first reply can lag seconds behind the worker's
        heavy package import, so the deadline covers the whole sync —
        run this during the traffic window, it costs the fleet
        nothing."""
        out: dict = {}
        deadline = time.monotonic() + timeout
        for w in self.workers:
            best = None
            for i in range(rounds):
                token = f"ck-{w.name}-{i}"
                t_send = time.monotonic_ns()
                self._send(w, {"cmd": "clock", "token": token})
                sample = None
                while time.monotonic() < deadline:
                    with self._lock:
                        sample = self.clock_samples.get(token)
                    if sample is not None or w.proc.poll() is not None:
                        break
                    time.sleep(0.005)
                if sample is None:
                    break
                off, err = _collect.align_offset(t_send, sample[0],
                                                 sample[1])
                if best is None or err < best[1]:
                    best = (off, err)
            w.clock = best
            if best is not None:
                out[w.name] = {"offset_ns": best[0], "err_ns": best[1]}
        return out

    def collect_traces(self, timeout: float = 30.0) -> list:
        """The per-process dumps for obs/collect.merge: the driver's
        own rings plus every worker's inline ring dump (workers ship
        theirs as the final protocol line before exiting — wait for
        stragglers, but never for a dead worker whose pipe drained)."""
        assert self.trace, "driver not constructed with trace=True"
        deadline = time.monotonic() + timeout
        names = {w.name for w in self.workers}
        while time.monotonic() < deadline:
            with self._lock:
                missing = names - set(self.traces)
            if not missing:
                break
            if all(w.proc.poll() is not None
                   and (w.reader is None or not w.reader.is_alive())
                   for w in self.workers if w.name in missing):
                break
            time.sleep(0.05)
        dumps = [_collect.ProcessDump("fleet-driver", os.getpid(),
                                      _trace.collect_events())]
        with self._lock:
            traces = dict(self.traces)
        for w in self.workers:
            payload = traces.get(w.name)
            if payload is None:
                continue
            off, err = w.clock if w.clock is not None else (0, 0)
            dumps.append(_collect.ProcessDump(
                f"worker-{w.name}", payload.get("pid", w.pid),
                payload.get("events", []), off, err))
        return dumps

    def flight_dumps(self, inline: bool = True) -> list:
        """Every flight-recorder dump the fleet produced: streamed
        paths first, then a sweep of the shared flight dir (a worker
        killed between writing the dump and streaming its path still
        left the file).  ``inline`` attaches the parsed payload — a
        chaos verdict must ship its evidence, not a path into a temp
        dir that stop() deletes."""
        with self._lock:
            records = [dict(r) for r in self.flight_paths]
        seen = {r["path"] for r in records}
        if self.trace_dir and os.path.isdir(self.trace_dir):
            for fn in sorted(os.listdir(self.trace_dir)):
                p = os.path.join(self.trace_dir, fn)
                if fn.startswith("tk_flight_") and p not in seen:
                    records.append({"worker": None, "path": p})
        for r in records:
            r["exists"] = bool(r["path"]) and os.path.isfile(r["path"])
            if inline and r["exists"]:
                try:
                    with open(r["path"]) as f:
                        payload = json.load(f)
                    r["events"] = sum(
                        1 for e in payload.get("traceEvents", [])
                        if e.get("ph") != "M")
                    r["payload"] = payload
                except (OSError, ValueError):
                    r["payload"] = None
        return records

    # ----------------------------------------------------------- stop --
    def stop_role(self, role: str, timeout: float = 60.0) -> None:
        """Graceful stop of one role tier (producers first, so the
        drain phase measures delivery, then consumers after the group
        verdict freezes — the Storm ordering, fleet-wide)."""
        targets = [w for w in self.workers if w.role == role]
        for w in targets:
            self._send(w, {"cmd": "stop"})
        deadline = time.monotonic() + timeout
        for w in targets:
            left = max(0.1, deadline - time.monotonic())
            try:
                w.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                w.proc.kill()
                try:
                    w.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass

    def stop(self) -> None:
        """Full teardown (idempotent): stop every worker, reap every
        pid, deregister from the leak registry, join readers."""
        if self._stopped:
            return
        self._stopped = True
        for w in self.workers:
            self._send(w, {"cmd": "stop"})
        deadline = time.monotonic() + 30.0
        for w in self.workers:
            left = max(0.1, deadline - time.monotonic())
            try:
                w.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                w.proc.kill()
                try:
                    w.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
        for w in self.workers:
            if w.reader is not None:
                w.reader.join(10)
            for f in (w.proc.stdin, w.proc.stdout):
                try:
                    f.close()
                except OSError:
                    pass
        external.deregister_pids([w.pid for w in self.workers])
        if self.trace:
            # release the driver's tracer reference and the shared
            # flight-dump dir exactly once (conftest fails leaks of
            # either); callers collect traces/dumps BEFORE stop()
            self.trace = False
            _trace.disable()
            if self.trace_dir is not None:
                _collect.release_dump_dir(self.trace_dir)

    def __enter__(self) -> "FleetDriver":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -------------------------------------------------------- verdict --
    def drain(self, timeout: float = 30.0) -> bool:
        """Wait until every group's oracle has consumed every acked
        record (or the deadline makes the gap a loss verdict)."""
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            if all(o.missing_count() == 0 for o in self.oracles):
                return True
            time.sleep(0.2)
        return all(o.missing_count() == 0 for o in self.oracles)

    def wait_converged(self, timeout: float = 25.0) -> Optional[float]:
        """Wait for every (group, topic) cover to converge; returns
        the convergence latency in seconds, or None (a violation)."""
        t0 = time.monotonic()
        end = t0 + timeout
        while time.monotonic() < end:
            if all(o.group_coverage(t, self.plan.partitions)["converged"]
                   for o in self.oracles for t in self.plan.topics):
                return round(time.monotonic() - t0, 2)
            time.sleep(0.2)
        return None

    def freeze_group_verdicts(self) -> list[dict]:
        """Snapshot each group's coverage BEFORE consumers stop — the
        teardown LeaveGroup cascade must not read as lost coverage."""
        now = time.monotonic()
        return [{"coverage": {t: o.group_coverage(t, self.plan.partitions)
                              for t in self.plan.topics},
                 "now": now}
                for o in self.oracles]

    def verify(self, *, converged_s: Optional[float],
               snapshots: Optional[list] = None,
               raise_on_violation: bool = True) -> list[dict]:
        """Judge every group's merged ledger: zero acked loss per
        group, coverage exact, nobody stuck.  Duplicates/order are
        relaxed — a multi-member group under kills is at-least-once
        (CHAOS.md) — while loss is always enforced."""
        reports = []
        for gi, o in enumerate(self.oracles):
            for topic in self.plan.topics:
                snap = snapshots[gi] if snapshots else None
                reports.append(o.verify(
                    check_duplicates=False, check_order=False,
                    check_group=True, group_topic=topic,
                    group_partitions=self.plan.partitions,
                    converged_s=converged_s,
                    coverage=snap["coverage"][topic] if snap else None,
                    now=snap["now"] if snap else None,
                    raise_on_violation=raise_on_violation))
        return reports

    # -------------------------------------------------------- metrics --
    def metrics(self) -> dict:
        """The fleet aggregate: total msgs/s over the acked window,
        per-client produce->ack p99 (max + median across clients), and
        raw per-worker summaries."""
        with self.oracles[0]._lock:
            acked_ts = sorted(self.oracles[0].acked_ts)
        with self._lock:
            stats = {k: dict(v) for k, v in self.stats.items()}
            done = {k: dict(v) for k, v in self.done.items()}
        for name, s in done.items():        # final beats periodic
            stats.setdefault(name, {}).update(s)
        p99s = {n: s["p99_ms"] for n, s in stats.items()
                if s.get("p99_ms") is not None}
        window = (acked_ts[-1] - acked_ts[0]) if len(acked_ts) > 1 else 0.0
        vals = sorted(p99s.values())
        return {
            "workers": len(self.workers),
            "acked_total": len(acked_ts),
            "fleet_msgs_s": (round(len(acked_ts) / window, 1)
                             if window > 0 else None),
            "client_p99_ms": p99s,
            "client_p99_ms_max": vals[-1] if vals else None,
            "client_p99_ms_median": (vals[len(vals) // 2]
                                     if vals else None),
            "produced_total": sum(s.get("produced", 0)
                                  for s in stats.values()),
            "consumed_total": sum(s.get("consumed", 0)
                                  for s in stats.values()),
        }

    def replay_key(self) -> str:
        return self.plan.replay_key()

    def set_worker_rlimit(self, name: str, nbytes: int) -> dict:
        """Memory-pressure fault on one WORKER process (the client
        side of the env_rlimit verb): soft RLIMIT_AS via prlimit —
        0 restores the soft limit to infinity."""
        import resource
        w = next(x for x in self.workers if x.name == name)
        soft = resource.RLIM_INFINITY if nbytes <= 0 else int(nbytes)
        old = resource.prlimit(w.pid, resource.RLIMIT_AS,
                               (soft, resource.RLIM_INFINITY))
        return {"worker": name, "pid": w.pid, "soft": soft, "old": old}
