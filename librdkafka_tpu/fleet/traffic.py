"""Generative traffic shapes: what a population of real clients does.

Production load is not a constant-rate loop — it ramps with the day,
bursts on events, concentrates on hot keys and hot partitions, and
fans one stream out to many consumer groups.  This module expresses
those shapes as **plain JSON-able dicts** (specs) so they ship to the
fleet's worker processes over the stdin line protocol unchanged, plus
the samplers that execute a spec inside one worker.

**Determinism contract** (the fleet analog of chaos/schedule.py's):
every random choice in a fleet run draws from ``random.Random`` seeded
along a fixed derivation chain — one plan seed assigns each worker its
own ``seed`` in spec order, and each worker's sampler consumes only
its own rng.  The plan's ``replay_key()`` is a digest of the fully
resolved spec list: two plans built from the same seed and parameters
are byte-identical, no matter when or where the workers actually run
(wall-clock pacing is execution, not identity — exactly like a chaos
schedule's timeline wall offsets).

Shape catalog (``rate_at(spec, t)`` gives msgs/s at elapsed t):

  flat(rate)                      constant rate
  diurnal(base, peak, period_s)   raised-cosine day cycle: base at
                                  t=0, peak at period/2
  bursts(quiet, burst, period_s,  square wave: ``burst`` for the first
         duty)                    ``duty`` fraction of each period,
                                  ``quiet`` for the rest
  stack(*shapes)                  sum of component shapes (diurnal +
                                  bursts = the flagship's day-with-
                                  storms curve)

Skew catalog:

  zipf(n_keys, s)                 Zipf(s) hot-key distribution over
                                  ``n_keys`` ranked keys (rank 1
                                  hottest); ZipfSampler draws keys
  hot_partitions(n, hot, weight)  partition picker: the ``hot``
                                  partition with probability
                                  ``weight``, uniform over the rest
                                  otherwise
"""
from __future__ import annotations

import hashlib
import json
import math
import random
from bisect import bisect_left
from typing import Optional


# ------------------------------------------------------------- shapes --
def flat(rate: float) -> dict:
    return {"kind": "flat", "rate": float(rate)}


def diurnal(base: float, peak: float, period_s: float,
            phase: float = 0.0) -> dict:
    """Raised-cosine 'day': rate(t) = base + (peak-base) *
    (1 - cos(2*pi*(t/period + phase))) / 2."""
    return {"kind": "diurnal", "base": float(base), "peak": float(peak),
            "period_s": float(period_s), "phase": float(phase)}


def bursts(quiet: float, burst: float, period_s: float,
           duty: float = 0.25) -> dict:
    """Burst/quiet square wave: ``burst`` msgs/s for the first
    ``duty`` fraction of every ``period_s`` window, ``quiet`` after."""
    return {"kind": "bursts", "quiet": float(quiet), "burst": float(burst),
            "period_s": float(period_s), "duty": float(duty)}


def stack(*shapes: dict) -> dict:
    return {"kind": "stack", "parts": list(shapes)}


def rate_at(shape: dict, t: float) -> float:
    """Instantaneous target rate (msgs/s) of ``shape`` at elapsed
    ``t`` seconds.  Pure: same (spec, t) always gives the same rate."""
    k = shape["kind"]
    if k == "flat":
        return shape["rate"]
    if k == "diurnal":
        frac = (1.0 - math.cos(
            2.0 * math.pi * (t / shape["period_s"] + shape["phase"]))) / 2.0
        return shape["base"] + (shape["peak"] - shape["base"]) * frac
    if k == "bursts":
        inside = (t % shape["period_s"]) < shape["duty"] * shape["period_s"]
        return shape["burst"] if inside else shape["quiet"]
    if k == "stack":
        return sum(rate_at(p, t) for p in shape["parts"])
    raise ValueError(f"unknown shape kind {k!r}")


# --------------------------------------------------------------- skew --
def zipf(n_keys: int, s: float = 1.2) -> dict:
    return {"kind": "zipf", "n_keys": int(n_keys), "s": float(s)}


def hot_partitions(n: int, hot: int, weight: float = 0.6) -> dict:
    """``weight`` of the traffic lands on partition ``hot``; the rest
    spreads uniformly over all ``n`` partitions."""
    return {"kind": "hot", "n": int(n), "hot": int(hot),
            "weight": float(weight)}


class ZipfSampler:
    """Draws key ranks 0..n-1 from Zipf(s) via an inverse-CDF table —
    rank 0 is the hottest key.  All randomness comes from the caller's
    rng, so a worker's key sequence replays from its spec seed."""

    def __init__(self, spec: dict, rng: random.Random):
        self._rng = rng
        n, s = spec["n_keys"], spec["s"]
        weights = [1.0 / (r + 1) ** s for r in range(n)]
        total = sum(weights)
        self._cdf = []
        acc = 0.0
        for w in weights:
            acc += w / total
            self._cdf.append(acc)
        self._cdf[-1] = 1.0

    def rank(self) -> int:
        return bisect_left(self._cdf, self._rng.random())


class PartitionPicker:
    """Executes a hot_partitions() spec (or uniform when spec is
    None) against the worker's rng."""

    def __init__(self, n_partitions: int, spec: Optional[dict],
                 rng: random.Random):
        self._n = n_partitions
        self._spec = spec
        self._rng = rng

    def pick(self) -> int:
        if self._spec is None:
            return self._rng.randrange(self._n)
        if self._rng.random() < self._spec["weight"]:
            return self._spec["hot"]
        return self._rng.randrange(self._spec["n"])


class Pacer:
    """Credit-based rate limiter: ``take(t)`` accrues ``rate_at(t)``
    credits per second and returns how many whole messages to send
    now (capped so a long stall cannot release an unbounded burst)."""

    BURST_CAP = 64.0

    def __init__(self, shape: dict):
        self._shape = shape
        self._last: Optional[float] = None
        self._credit = 0.0

    def take(self, t: float) -> int:
        if self._last is None:
            self._last = t
            return 0
        dt = max(0.0, t - self._last)
        self._last = t
        self._credit = min(self.BURST_CAP,
                           self._credit + dt * rate_at(self._shape, t))
        n = int(self._credit)
        self._credit -= n
        return n


# --------------------------------------------------------------- plan --
class TrafficPlan:
    """One fleet's fully resolved worker population.

    Derivation: a single ``random.Random(seed)`` is consumed in fixed
    spec order — per-producer phase jitter, hot-key/hot-partition
    placement, per-worker seeds — so the spec list (and therefore
    ``replay_key()``) is a pure function of the constructor arguments.

    Topology: ``producers`` producer workers spread round-robin over
    ``topics``; ``groups`` consumer groups of ``group_size`` members
    each, every group subscribing to ALL topics (fan-out: one produced
    record is consumed once per group).
    """

    def __init__(self, seed: int, *, producers: int = 2, groups: int = 1,
                 group_size: int = 2, topics: Optional[list] = None,
                 partitions: int = 4, shape: Optional[dict] = None,
                 keys: Optional[dict] = None,
                 hot_partition_weight: float = 0.0,
                 isolation: str = "read_uncommitted",
                 strategy: str = "range,roundrobin",
                 max_s: float = 120.0):
        self.seed = seed
        self.topics = list(topics) if topics else ["fleet"]
        self.partitions = partitions
        rng = random.Random(seed)
        shape = shape or flat(100.0)
        self.specs: list[dict] = []
        for i in range(producers):
            sh = json.loads(json.dumps(shape))   # per-worker copy
            if sh["kind"] in ("diurnal", "bursts"):
                sh = stack(sh)
            if sh["kind"] == "stack":
                # de-synchronize the fleet: each producer's cycles sit
                # at a seeded phase offset, like real user populations
                for part in sh["parts"]:
                    if part["kind"] == "diurnal":
                        part["phase"] = round(rng.random(), 6)
            skew = None
            if hot_partition_weight > 0:
                skew = hot_partitions(partitions, rng.randrange(partitions),
                                      hot_partition_weight)
            self.specs.append({
                "role": "producer", "name": f"p{i:02d}",
                "topic": self.topics[i % len(self.topics)],
                "partitions": partitions, "shape": sh,
                "keys": keys, "part_skew": skew,
                "seed": rng.randrange(1 << 31), "max_s": max_s})
        for g in range(groups):
            for m in range(group_size):
                self.specs.append({
                    "role": "consumer", "name": f"g{g}:c{m}",
                    "group": f"fleet-g{g}-{seed}", "group_idx": g,
                    "topics": self.topics, "isolation": isolation,
                    "strategy": strategy,
                    "seed": rng.randrange(1 << 31), "max_s": max_s})
        self.n_groups = groups

    @property
    def workers(self) -> int:
        return len(self.specs)

    def replay_key(self) -> str:
        """Digest of the fully resolved population — equal iff two
        plans would drive byte-identical worker behavior (modulo
        wall-clock pacing), the fleet half of a run's replay key."""
        blob = json.dumps(self.specs, sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]
