"""Fleet subsystem (ISSUE 11): multi-process client traffic simulator.

"Millions of users" traffic is many client processes misbehaving
together, not one fast loop — this package spawns tens-to-hundreds of
real client OS processes (producers and consumer-group members,
``fleet/_worker.py`` executed by path) against the supervised
out-of-process cluster (PR 9's rig), drives them with generative
traffic shapes (``traffic.py``: diurnal ramps, burst/quiet cycles,
Zipf hot keys, hot-partition skew, fan-out groups), merges their
streamed ledgers into per-group delivery oracles, and aggregates
fleet metrics (msgs/s, per-client p99, recovery envelopes).

See FLEET.md for the worker line protocol, the traffic-shape catalog,
the environment fault-verb table, and the metrics schema.
"""
from .driver import FleetDriver  # noqa: F401
from .scenarios import SCENARIOS, FleetRun  # noqa: F401
from .traffic import (TrafficPlan, bursts, diurnal, flat,  # noqa: F401
                      hot_partitions, rate_at, stack, zipf)
