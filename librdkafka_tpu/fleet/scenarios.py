"""Fleet scenario library: whole client populations — real OS
processes — under generative traffic shapes and scheduled faults
against the supervised out-of-process cluster.

A ``FleetRun`` is the fleet analog of ``chaos.scenarios.Storm``: it
owns the external rig (``ClusterHandle``), the traffic plan, the
driver, and an optional chaos schedule, and runs the same phase
discipline — traffic under fire, heal, producer flush, drain,
convergence wait, verdict freeze, per-group oracle verify — so one
report carries delivery AND robustness AND fleet metrics.

Replay contract: a fleet run's ``replay_key`` is the pair
``[plan_key, schedule_key]`` — the traffic plan digest (every worker
spec resolved from the plan seed) plus the chaos timeline's resolved
targets.  Same seed, two separately launched rigs (fresh supervisor,
fresh workers) ⇒ identical key; wall-clock pacing and message counts
are execution, not identity.

Run via ``python -m librdkafka_tpu.fleet`` (``--list``), the pytest
``fleet`` tier (``scripts/fleet.sh``), or ``bench.py --fleet``.
"""
from __future__ import annotations

import time
from typing import Callable, NamedTuple, Optional

from ..chaos.oracle import OracleViolation
from ..chaos.schedule import (ChaosScheduler, Schedule, env_brownout,
                              env_brownout_clear, env_eio, env_eio_clear,
                              proc_kill9, proc_restart)
from ..chaos.scenarios import storm_metrics
from ..mock.external import ClusterHandle
from ..obs import collect as _collect
from .driver import FleetDriver
from .traffic import TrafficPlan, bursts, diurnal, flat, stack, zipf


class FleetRun:
    """One fleet run: external rig + plan + driver + optional chaos.

    Phases (the Storm discipline, population-wide): start workers →
    storm → heal → stop producers (flush) → drain every group → wait
    convergence → freeze group verdicts → stop consumers → verify
    per group → aggregate metrics."""

    def __init__(self, *, seed: int, brokers: int = 3,
                 partitions: int = 4, topic: str = "fleet",
                 producers: int = 2, groups: int = 1, group_size: int = 2,
                 shape: Optional[dict] = None, keys: Optional[dict] = None,
                 hot_partition_weight: float = 0.0,
                 strategy: str = "range,roundrobin",
                 min_alive: int = 1, duration_s: float = 3.0,
                 drain_s: float = 30.0, converge_s: float = 25.0,
                 worker_max_s: float = 120.0,
                 trace_path: Optional[str] = None):
        self.seed = seed
        self.topic = topic
        self.duration_s = duration_s
        self.drain_s = drain_s
        self.converge_s = converge_s
        self.trace_path = trace_path
        self.handle = ClusterHandle(brokers=brokers,
                                    topics={topic: partitions})
        if trace_path:
            # rig-side rings on from the start: supervisor ctl spans
            # and relay connection spans belong in the merged timeline
            self.handle.trace_enable()
        self.plan = TrafficPlan(
            seed, producers=producers, groups=groups,
            group_size=group_size, topics=[topic], partitions=partitions,
            shape=shape, keys=keys,
            hot_partition_weight=hot_partition_weight,
            strategy=strategy,
            max_s=worker_max_s)
        self.driver = FleetDriver(self.handle.bootstrap_servers(),
                                  self.plan, trace=bool(trace_path))
        self.chaos = ChaosScheduler(self.handle, min_alive=min_alive)

    def run(self, schedule: Optional[Schedule] = None, *,
            tamper: Optional[Callable] = None,
            raise_on_violation: bool = True) -> dict:
        t0 = time.monotonic()
        violation: Optional[OracleViolation] = None
        try:
            self.driver.start()
            if schedule is not None and schedule.steps:
                self.chaos.start(schedule)
            if self.trace_path:
                # overlaps the traffic window: replies come from the
                # workers' own run loops, costing the fleet nothing
                self.driver.clock_sync()
            time.sleep(self.duration_s)
            if schedule is not None and schedule.steps:
                self.chaos.join(timeout=schedule.duration + 30)
            self.chaos.heal()
            # producers first: their stop flushes every in-flight
            # batch and streams the final ack ledger rows
            self.driver.stop_role("producer")
            self.driver.drain(self.drain_s)
            converged_s = self.driver.wait_converged(self.converge_s)
            snapshots = self.driver.freeze_group_verdicts()
            self.driver.stop_role("consumer")

            if tamper is not None:
                tamper(self.driver.oracles)
            reports = []
            try:
                reports = self.driver.verify(
                    converged_s=converged_s, snapshots=snapshots,
                    raise_on_violation=raise_on_violation)
            except OracleViolation as v:
                violation = v
                reports = [v.report]

            o0 = self.driver.oracles[0]
            with o0._lock:
                acked_ts = list(o0.acked_ts)
            metrics = self.driver.metrics()
            report = {
                "ok": (violation is None
                       and all(r["ok"] for r in reports)),
                "seed": self.seed,
                "workers": self.plan.workers,
                "acked": reports[0]["acked"] if reports else len(acked_ts),
                "consumed_by_group": [
                    len(o.consumed) for o in self.driver.oracles],
                "group_reports": [
                    {"ok": r["ok"], "group": r.get("group"),
                     "violations": {k: len(v) for k, v
                                    in r["violations"].items() if v}}
                    for r in reports],
                "converged_s": converged_s,
                "wall_s": round(time.monotonic() - t0, 2),
                "fleet_metrics": metrics,
                "timeline": self.chaos.timeline,
                "replay_key": [self.plan.replay_key(),
                               self.chaos.replay_key()],
                "schedule_errors": self.chaos.errors,
                "errors": list(self.driver.errors),
                "proc_events": list(self.handle.proc_events),
            }
            sm = storm_metrics(self.chaos.timeline, acked_ts)
            if sm is not None:
                report["storm_metrics"] = sm
            report["kills_fired"] = sum(
                1 for e in self.chaos.timeline
                if e["action"] == "proc_kill9"
                and (e.get("resolved") or {}).get("broker"))
            if self.trace_path:
                # every worker shipped its ring dump on exit; the rig
                # contributes supervisor + relay dumps over the control
                # socket — ONE Perfetto file, flow links stitched
                dumps = self.driver.collect_traces()
                dumps.extend(self.handle.collect_traces())
                events = _collect.merge(dumps)
                events, links = _collect.stitch_flows(events)
                _collect.write(self.trace_path, events)
                report["trace"] = {
                    "path": self.trace_path,
                    "processes": len(dumps),
                    "pids": sorted({d.pid for d in dumps}),
                    "flow_links": links,
                }
                # the chaos-evidence satellite: flight dumps ride the
                # verdict (inline — their temp dir dies with stop())
                report["flight_dumps"] = self.driver.flight_dumps()
                if violation is not None:
                    violation.report["flight_dumps"] = \
                        report["flight_dumps"]
                    violation.report["trace"] = report["trace"]
            if violation is not None:
                raise violation
            return report
        finally:
            self.driver.stop()
            self.chaos.stop()
            self.handle.stop()


# ------------------------------------------------------------ library --
def fleet_mini(seed: int = 47, *, raise_on_violation: bool = True,
               trace_path: Optional[str] = None) -> dict:
    """Smallest real fleet (bench --fleet --smoke): 1 producer + 1
    single-member group — two client OS processes — no faults, merged
    oracle clean.  Proves the spawn/stream/merge machinery in seconds."""
    run = FleetRun(seed=seed, brokers=1, partitions=2,
                   producers=1, groups=1, group_size=1,
                   shape=flat(150.0), duration_s=1.5,
                   drain_s=15.0, converge_s=15.0,
                   trace_path=trace_path)
    return run.run(None, raise_on_violation=raise_on_violation)


def fleet_smoke(seed: int = 51, *, raise_on_violation: bool = True,
                trace_path: Optional[str] = None) -> dict:
    """Tier-1 fleet smoke (<15 s): 4 worker processes (2 producers
    with burst + hot-partition + Zipf-key traffic, one 2-member
    group) sustaining one pid-verified SIGKILL/respawn; per-group
    merged-oracle verify (zero acked loss, coverage exact)."""
    run = FleetRun(seed=seed, brokers=2, partitions=4,
                   producers=2, groups=1, group_size=2,
                   shape=stack(flat(60.0), bursts(0.0, 90.0, 1.2, 0.33)),
                   keys=zipf(50, 1.1), hot_partition_weight=0.5,
                   min_alive=1, duration_s=2.5,
                   drain_s=25.0, converge_s=20.0,
                   trace_path=trace_path)
    sched = (Schedule(seed=seed)
             .at(0.9, proc_kill9("any"))
             .at(1.7, proc_restart()))
    report = run.run(sched, raise_on_violation=raise_on_violation)
    report["pids_killed"] = [e for e in report["proc_events"]
                            if e["verb"] == "kill9"]
    return report


def fleet_storm(seed: int = 61, *, producers: int = 16,
                groups: int = 2, group_size: int = 4,
                strategy: str = "cooperative-sticky",
                raise_on_violation: bool = True) -> dict:
    """FLAGSHIP (ISSUE 11): ≥24 real client OS processes — 16
    producers under a diurnal+burst traffic shape with Zipf hot keys
    and hot-partition skew, plus 2 consumer groups × 4 members
    (fan-out: every group must deliver the whole acked set) — against
    the 3-broker supervised cluster, sustaining 3 pid-verified
    SIGKILL/respawn cycles, one asymmetric rx-drop brownout, and one
    disk-full/EIO window.  Per-group merged-oracle verify: zero acked
    loss, exact final coverage, convergence, nobody stuck.  Since
    ISSUE 12 the consumer groups run the KIP-429 cooperative protocol
    (``strategy`` knob; pass ``"range"`` for the eager baseline)."""
    run = FleetRun(seed=seed, brokers=3, partitions=8,
                   producers=producers, groups=groups,
                   group_size=group_size,
                   shape=stack(diurnal(8.0, 30.0, 6.0),
                               bursts(0.0, 25.0, 2.0, 0.3)),
                   keys=zipf(200, 1.2), hot_partition_weight=0.6,
                   strategy=strategy,
                   min_alive=2, duration_s=9.5,
                   drain_s=45.0, converge_s=30.0,
                   worker_max_s=180.0)
    sched = (Schedule(seed=seed)
             .at(1.5, proc_kill9("any"))
             .at(2.6, proc_restart())
             .at(3.2, env_brownout("any", rx_drop=True))
             .at(4.4, env_brownout_clear())
             .at(4.8, proc_kill9("any"))
             .at(5.9, proc_restart())
             .at(6.3, env_eio("any"))
             .at(7.3, env_eio_clear())
             .at(7.6, proc_kill9("any"))
             .at(8.4, proc_restart()))
    report = run.run(sched, raise_on_violation=raise_on_violation)
    report["pids_killed"] = [e for e in report["proc_events"]
                            if e["verb"] == "kill9"]
    report["brownouts"] = [e for e in report["proc_events"]
                           if e["verb"] == "brownout"]
    report["eio_windows"] = [e for e in report["proc_events"]
                             if e["verb"] == "eio"]
    return report


class FleetScenario(NamedTuple):
    fn: Callable
    desc: str
    tier: str          # "fast" (tier-1) | "slow"
    seed: int
    invariants: str


SCENARIOS: dict[str, FleetScenario] = {
    "fleet_mini": FleetScenario(
        fleet_mini,
        "2-worker minimum fleet (1 producer + 1 consumer), no faults "
        "— the bench --fleet --smoke leg", "fast", 47, "loss,group"),
    "fleet_smoke": FleetScenario(
        fleet_smoke,
        "tier-1 smoke: 4 worker processes, burst+hot-partition "
        "traffic, one pid-verified SIGKILL, <15s", "fast", 51,
        "loss,group"),
    "fleet_storm": FleetScenario(
        fleet_storm,
        "FLAGSHIP: ≥24 worker processes, diurnal+burst traffic with "
        "hot-key/hot-partition skew, 3 SIGKILLs + brownout + EIO "
        "window, per-group verify", "slow", 61, "loss,group"),
}
