"""One fleet client: a real OS process running one producer or one
consumer-group member against the supervised cluster.

Executed BY PATH (``python .../fleet/_worker.py``) from the fleet
driver — deliberately not ``-m``, and nothing package-flavored is
imported at module scope: the handshake line goes out while the
process is still pure stdlib, so spawning a hundred workers costs
milliseconds each, and the heavy import (the client package) happens
exactly once, after the driver's ``start`` command arrives with the
worker's spec.

Line protocol (one JSON object per line):

  stdout  {"pid": N, "ready": true}                      handshake
          {"type": "acks",     "rows": [[t,p,off,key,val,ts], ...]}
          {"type": "failed",   "rows": [[t,p,val,err], ...]}
          {"type": "consumed", "rows": [[t,p,off,val,ts], ...]}
          {"type": "group", "event": "assign"|"revoke",
           "member": name, "parts": [[t,p], ...]}
          {"type": "poll",  "member": name}               liveness
          {"type": "stats", "name", "produced", "acked", "consumed",
           "p50_ms", "p99_ms", "max_ms"}                  periodic
          {"type": "done",  "name", "summary": {...}}     final
          {"type": "error", "name", "error": repr}
          {"type": "clock", "token", "mono_ns"}           clock reply
          {"type": "flight", "path"}              flight-recorder dump
          {"type": "trace", "name", "pid", "mono_ns",
           "events": [...]}                     ring dump at exit
  stdin   {"cmd": "start", "bootstrap": "...", "spec": {...}}
          {"cmd": "clock", "token": ...}
          {"cmd": "stop"}

Observability (ISSUE 20): when ``spec["trace"]`` is set the worker
enables its own obs/trace.py rings (flight dumps land in
``spec["flight_dir"]``), answers the driver's ``clock`` offset
exchange with ``time.monotonic_ns()``, streams flight-dump paths the
moment they appear (so a worker that dies mid-storm already shipped
its evidence), and ships its whole ring dump inline as the final
``trace`` line before exiting — the driver merges every process's
dump into one timeline (obs/collect.py).

``ts`` stamps are ``time.monotonic()`` — on Linux CLOCK_MONOTONIC is
machine-wide, so the driver can correlate them with the chaos
timeline's ``mono`` stamps for recovery envelopes.  The worker exits
on ``stop``, on stdin EOF (driver died — orphan protection, same
double-wall as mock/_relay.py), or at the spec's ``max_s`` deadline.

All worker randomness (keys, partitions, pacing jitter) draws from
``random.Random(spec["seed"])`` — the fleet replay contract.
"""
import json
import os
import random
import selectors
import sys
import time

FLUSH_EVERY_S = 0.25        # ledger/stats streaming cadence
POLL_EVERY_S = 0.4          # group-liveness heartbeat cadence
ROW_CAP = 400               # max ledger rows per stdout line

_TR = None                  # obs.trace module when spec["trace"] is set
_last_flight = None


def _emit(obj) -> None:
    sys.stdout.write(json.dumps(obj, separators=(",", ":")) + "\n")
    sys.stdout.flush()


def _poll_ctl(cmd) -> bool:
    """Dispatch one driver command; True means stop.  The clock reply
    is stamped HERE, as close to the read as possible, so the driver's
    half-round-trip error bound stays honest."""
    if not cmd:
        return False
    c = cmd.get("cmd")
    if c == "stop":
        return True
    if c == "clock":
        _emit({"type": "clock", "token": cmd.get("token"),
               "mono_ns": time.monotonic_ns()})
    return False


def _flight_watch() -> None:
    """Stream any new flight-recorder dump path immediately — the
    driver must hold the evidence BEFORE a chaos verdict (or a worker
    death) needs it."""
    global _last_flight
    if _TR is not None and _TR.last_flight_path != _last_flight:
        _last_flight = _TR.last_flight_path
        _emit({"type": "flight", "path": _last_flight})


class _Stdin:
    """Non-blocking stdin command reader (selector + line buffer)."""

    def __init__(self):
        self._sel = selectors.DefaultSelector()
        self._sel.register(sys.stdin.fileno(), selectors.EVENT_READ)
        self._buf = b""
        self.eof = False

    def next_cmd(self, timeout: float = 0.0):
        """One decoded command dict, or None.  ``eof`` latches when
        the driver's pipe closes."""
        if b"\n" not in self._buf and not self.eof:
            if self._sel.select(timeout=timeout):
                chunk = os.read(sys.stdin.fileno(), 65536)
                if not chunk:
                    self.eof = True
                self._buf += chunk
        if b"\n" in self._buf:
            raw, _, self._buf = self._buf.partition(b"\n")
            try:
                return json.loads(raw)
            except ValueError:
                return None
        return None


def _lat_summary(hist) -> dict:
    if hist.total == 0:
        return {"p50_ms": None, "p99_ms": None, "max_ms": None}
    return {"p50_ms": round(hist.value_at_percentile(50) / 1000.0, 2),
            "p99_ms": round(hist.value_at_percentile(99) / 1000.0, 2),
            "max_ms": round(hist.max_v / 1000.0, 2)}


def _run_producer(spec: dict, bootstrap: str, ctl: _Stdin) -> dict:
    from librdkafka_tpu import Producer
    from librdkafka_tpu.fleet.traffic import (Pacer, PartitionPicker,
                                              ZipfSampler)
    from librdkafka_tpu.utils.hdrhistogram import HdrHistogram

    name = spec["name"]
    topic = spec["topic"]
    rng = random.Random(spec["seed"])
    pacer = Pacer(spec["shape"])
    picker = PartitionPicker(spec["partitions"], spec.get("part_skew"), rng)
    keys = (ZipfSampler(spec["keys"], rng)
            if spec.get("keys") else None)
    hist = HdrHistogram(1, 60_000_000, 2)       # produce->ack latency, us

    p = Producer({
        "bootstrap.servers": bootstrap,
        "linger.ms": 2,
        "enable.idempotence": True,
        "message.send.max.retries": 1000,
        "retry.backoff.ms": 50,
        "message.timeout.ms": 120000,
        "reconnect.backoff.ms": 50,
        "reconnect.backoff.max.ms": 1000,       # chaos-rig tuning (PR 9)
    })
    acks: list = []
    failed: list = []
    produced = acked = 0

    def _dr(t_sent: float, value: str):
        def _cb(err, msg):
            nonlocal acked
            now = time.monotonic()
            if err is None:
                acked += 1
                hist.record(max(1, int((now - t_sent) * 1e6)))
                acks.append([msg.topic, msg.partition, msg.offset,
                             msg.key.decode("latin1") if msg.key else None,
                             value, round(now, 4)])
            else:
                failed.append([msg.topic, msg.partition, value, str(err)])
        return _cb

    t0 = time.monotonic()
    deadline = t0 + spec.get("max_s", 120.0)
    next_flush = t0 + FLUSH_EVERY_S
    stopping = False
    try:
        while True:
            now = time.monotonic()
            if now >= deadline:
                break
            cmd = ctl.next_cmd(0.0)
            if ctl.eof or _poll_ctl(cmd):
                stopping = True
            if stopping:
                break
            n = pacer.take(now - t0)
            for _ in range(n):
                value = "%s-%08d" % (name, produced)
                key = ("k%06d" % keys.rank()) if keys else None
                try:
                    p.produce(topic, value.encode(),
                              key=key.encode() if key else None,
                              partition=picker.pick(),
                              on_delivery=_dr(time.monotonic(), value))
                    produced += 1
                except Exception as e:   # _QUEUE_FULL etc: poll + retry
                    if "_QUEUE_FULL" not in repr(e):
                        raise
                    p.poll(0.05)
                    break
            p.poll(0)
            if now >= next_flush:
                next_flush = now + FLUSH_EVERY_S
                if acks:
                    _emit({"type": "acks", "rows": acks[:ROW_CAP]})
                    del acks[:ROW_CAP]
                if failed:
                    _emit({"type": "failed", "rows": failed[:ROW_CAP]})
                    del failed[:ROW_CAP]
                _emit({"type": "stats", "name": name, "produced": produced,
                       "acked": acked, **_lat_summary(hist)})
                _flight_watch()
            if n == 0:
                time.sleep(0.002)
    finally:
        left = p.flush(60.0)
        p.close()
        while acks:
            _emit({"type": "acks", "rows": acks[:ROW_CAP]})
            del acks[:ROW_CAP]
        if failed:
            _emit({"type": "failed", "rows": failed})
    return {"produced": produced, "acked": acked, "unflushed": left,
            **_lat_summary(hist)}


def _run_consumer(spec: dict, bootstrap: str, ctl: _Stdin) -> dict:
    from librdkafka_tpu import Consumer

    name = spec["name"]
    c = Consumer({
        "bootstrap.servers": bootstrap,
        "group.id": spec["group"],
        "client.id": name.replace(":", "-"),
        "auto.offset.reset": "earliest",
        "isolation.level": spec.get("isolation", "read_uncommitted"),
        # strategy knob (ISSUE 12): "cooperative-sticky" runs the
        # KIP-429 incremental protocol — fleet_storm exercises it
        "partition.assignment.strategy":
            spec.get("strategy", "range,roundrobin"),
        "heartbeat.interval.ms": 400,   # inside the mock's 3s rebalance
        "session.timeout.ms": 6000,     # window (PR 9 group tuning)
        "reconnect.backoff.ms": 50,
        "reconnect.backoff.max.ms": 1000,
    })

    def _on_assign(cons, parts):
        coop = cons.rebalance_protocol() == "COOPERATIVE"
        _emit({"type": "group", "event": "assign", "member": name,
               "incremental": coop,
               "parts": [[tp.topic, tp.partition] for tp in parts]})
        if coop:
            cons.incremental_assign(parts)
        else:
            cons.assign(parts)

    def _on_revoke(cons, parts):
        coop = cons.rebalance_protocol() == "COOPERATIVE"
        _emit({"type": "group", "event": "revoke", "member": name,
               "incremental": coop,
               "parts": [[tp.topic, tp.partition] for tp in parts]
               if coop else []})
        if coop:
            cons.incremental_unassign(parts)
        else:
            cons.unassign()

    rows: list = []
    consumed = 0
    t0 = time.monotonic()
    deadline = t0 + spec.get("max_s", 120.0)
    next_flush = t0 + FLUSH_EVERY_S
    next_poll_beat = t0
    try:
        c.subscribe(spec["topics"], on_assign=_on_assign,
                    on_revoke=_on_revoke)
        while True:
            now = time.monotonic()
            if now >= deadline:
                break
            cmd = ctl.next_cmd(0.0)
            if ctl.eof or _poll_ctl(cmd):
                break
            m = c.poll(0.1)
            if now >= next_poll_beat:
                next_poll_beat = now + POLL_EVERY_S
                _emit({"type": "poll", "member": name})
            if m is not None and m.error is None:
                consumed += 1
                rows.append([m.topic, m.partition, m.offset,
                             m.value.decode("latin1") if m.value else "",
                             round(time.monotonic(), 4)])
            if now >= next_flush:
                next_flush = now + FLUSH_EVERY_S
                while rows:
                    _emit({"type": "consumed", "rows": rows[:ROW_CAP]})
                    del rows[:ROW_CAP]
                _emit({"type": "stats", "name": name,
                       "consumed": consumed})
                _flight_watch()
    finally:
        c.close()
        while rows:
            _emit({"type": "consumed", "rows": rows[:ROW_CAP]})
            del rows[:ROW_CAP]
    return {"consumed": consumed}


def main() -> int:
    _emit({"pid": os.getpid(), "ready": True})
    ctl = _Stdin()
    # block (pure stdlib, cheap to sit here) until the driver starts us
    start = None
    deadline = time.monotonic() + 60.0
    while start is None:
        if time.monotonic() >= deadline or ctl.eof:
            return 1
        cmd = ctl.next_cmd(0.5)
        if cmd and cmd.get("cmd") == "start":
            start = cmd
        elif cmd and cmd.get("cmd") == "stop":
            return 0

    # the heavy import happens here, post-handshake: the package parent
    # goes on sys.path exactly like mock/external.py's PYTHONPATH wiring
    pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if pkg_parent not in sys.path:
        sys.path.insert(0, pkg_parent)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    spec = start["spec"]
    name = spec.get("name", "w?")
    global _TR
    if spec.get("trace"):
        # the worker holds its OWN tracer reference (not via client
        # conf) so the rings survive client close() and can be shipped
        # inline as the final protocol line
        from librdkafka_tpu.obs import trace as _obs_trace
        _TR = _obs_trace
        _TR.enable(dump_dir=spec.get("flight_dir"))
    try:
        if spec["role"] == "producer":
            summary = _run_producer(spec, start["bootstrap"], ctl)
        else:
            summary = _run_consumer(spec, start["bootstrap"], ctl)
        _emit({"type": "done", "name": name, "summary": summary})
        return 0
    except Exception as e:
        _emit({"type": "error", "name": name, "error": repr(e)})
        return 1
    finally:
        if _TR is not None:
            _flight_watch()
            events = _TR.collect_events()
            _TR.disable()
            _emit({"type": "trace", "name": name, "pid": os.getpid(),
                   "mono_ns": time.monotonic_ns(), "events": events})


if __name__ == "__main__":
    sys.exit(main())
