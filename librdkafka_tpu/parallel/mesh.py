"""Multi-chip scale-out of the codec sidecar over a jax.sharding.Mesh.

The reference's only "distributed backend" is per-broker TCP (SURVEY.md §5)
— network IO stays on host threads here too.  What DOES shard across chips
is the codec work: independent per-partition batches (the vmap axis of
SURVEY.md §3.2) are laid out along a 1-D ``batch`` mesh axis, each chip
compresses and checksums its shard locally (zero cross-chip traffic on the
hot path — the layout rides ICI only for the final stats reduction, a
psum of byte counters matching the reference's atomic stats counters,
rdatomic.h).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# version-compat shard_map: newer jax exposes it top-level (with the
# check_vma kwarg); older releases only ship
# jax.experimental.shard_map.shard_map (check_rep kwarg). Resolve once
# so sharded_codec_step works on both.
if hasattr(jax, "shard_map"):
    def _shard_map(f, *, mesh, in_specs, out_specs):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
else:
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map(f, *, mesh, in_specs, out_specs):
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)

from ..ops.crc32c_jax import _crc_kernel, _pick_kl, _shift_tables
from ..ops.lz4_jax import _lz4_block_one


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), ("batch",))


_STEP_CACHE: dict = {}


def sharded_codec_step(mesh: Mesh, N: int, with_crc: bool = True):
    """Build the jitted multi-chip codec step for (B, N) blocks.

    Returns fn(data (B,N) uint8 right-padded, lens (B,) int32,
    valid (B,) int32 row mask) →
      (lz4 bytes (B,C) uint8, lz4 lens (B,), crc32c (B,) uint32,
       total_out_bytes scalar — psum of valid rows across the mesh).
    B must be a multiple of the mesh size. ``with_crc=False`` builds a
    compress-only step (no CRC matmul, no psum) for callers that
    checksum elsewhere — e.g. the codec provider, whose batch CRC
    covers the assembled record batch, not raw blocks.
    """
    key = (tuple(d.id for d in mesh.devices.flat), N, with_crc)
    cached = _STEP_CACHE.get(key)
    if cached is not None:
        return cached
    K, L = _pick_kl(N)
    shift_tab = _shift_tables(L)

    def local(data, lens, valid):
        out, olen = jax.vmap(lambda d, n: _lz4_block_one(d, n, N))(data, lens)
        if not with_crc:
            return out, olen
        # the crc kernel needs LEFT-padded rows (leading zeros are a no-op
        # under a zero register); shift each right-padded row into place
        j = jnp.arange(N, dtype=jnp.int32)[None, :]
        src = j - (N - lens[:, None])
        crc_in = jnp.where(
            src >= 0,
            jnp.take_along_axis(data, jnp.clip(src, 0, N - 1), axis=1),
            jnp.uint8(0))
        crc = _crc_kernel(crc_in.reshape(-1, K, L), lens, shift_tab)
        total = jax.lax.psum(jnp.sum(olen * valid), "batch")
        return out, olen, crc, total

    out_specs = ((P("batch", None), P("batch"), P("batch"), P())
                 if with_crc else (P("batch", None), P("batch")))
    shard = _shard_map(
        local, mesh=mesh,
        in_specs=(P("batch", None), P("batch"), P("batch")),
        out_specs=out_specs)
    fn = jax.jit(shard)
    _STEP_CACHE[key] = fn
    return fn


def shard_compress(mesh: Mesh, blocks: list[bytes], with_crc: bool = True):
    """Compress blocks across the mesh (pads B up to a mesh multiple).
    Returns (blocks, crcs, total) with crcs=None/total=0 when
    with_crc=False."""
    from ..ops.packing import next_pow2, pad_right

    ndev = mesh.devices.size
    N = next_pow2(max((len(b) for b in blocks), default=64))
    data, lens = pad_right(blocks, N)
    B = len(blocks)
    Bp = ((B + ndev - 1) // ndev) * ndev
    valid = np.ones((B,), np.int32)
    if Bp != B:
        data = np.concatenate([data, np.zeros((Bp - B, N), np.uint8)])
        lens = np.concatenate([lens, np.zeros((Bp - B,), np.int32)])
        valid = np.concatenate([valid, np.zeros((Bp - B,), np.int32)])
    fn = sharded_codec_step(mesh, N, with_crc)
    row = NamedSharding(mesh, P("batch"))
    res = fn(
        jax.device_put(data, NamedSharding(mesh, P("batch", None))),
        jax.device_put(lens, row), jax.device_put(valid, row))
    if with_crc:
        out, olen, crc, total = res
    else:
        out, olen = res
        crc, total = None, 0
    out = np.asarray(out)
    olen = np.asarray(olen)
    return ([out[i, :olen[i]].tobytes() for i in range(B)],
            None if crc is None else np.asarray(crc)[:B], int(total))
