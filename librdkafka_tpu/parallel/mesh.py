"""Multi-chip scale-out of the codec sidecar over a jax.sharding.Mesh.

The reference's only "distributed backend" is per-broker TCP (SURVEY.md §5)
— network IO stays on host threads here too.  What DOES shard across chips
is the codec work: independent per-partition batches (the vmap axis of
SURVEY.md §3.2) are laid out along a 1-D ``batch`` mesh axis, each chip
compresses and checksums its shard locally (zero cross-chip traffic on the
hot path — the layout rides ICI only for the final stats reduction, a
psum of byte counters matching the reference's atomic stats counters,
rdatomic.h).

ISSUE 6 adds the ENGINE-FACING LANE API: the async offload engine
(ops/engine.py) shards its merged fan-in CRC launch groups across the
mesh through :func:`sharded_crc_step` — a shard_map of exactly the
single-device plane-split MXU body (crc32c_jax._mxu_rows_fn), so each
chip checksums its contiguous row shard locally and the gathered result
is bit-identical to the whole-to-one-device launch by construction.
Compiled steps live in a BOUNDED module-level LRU (``_STEP_CACHE``)
with a close-time release hook (:func:`release_step_cache`) so engines
and providers drop their compiled steps deterministically — the
conftest leak fixture asserts no cached step survives a test.
"""
from __future__ import annotations

import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# version-compat shard_map: newer jax exposes it top-level (with the
# check_vma kwarg); older releases only ship
# jax.experimental.shard_map.shard_map (check_rep kwarg). Resolve once
# so sharded_codec_step works on both.
if hasattr(jax, "shard_map"):
    def _shard_map(f, *, mesh, in_specs, out_specs):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
else:
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map(f, *, mesh, in_specs, out_specs):
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)

from ..ops.crc32c_jax import _crc_kernel, _pick_kl, _shift_tables
from ..ops.lz4_jax import _lz4_block_one


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), ("batch",))


# Bounded LRU of compiled sharded steps, keyed by (step kind, device
# ids, shape...).  Compiled shard_map executables pin device buffers
# (the broadcast Q-matrix constants alone are N*8*32 int8 per poly per
# chip), so the cache is BOUNDED — least-recently-used steps evict —
# and releasable: engine/provider close() calls release_step_cache()
# so no compiled step outlives its owner (conftest leak fixture).
_STEP_CACHE: OrderedDict = OrderedDict()
_STEP_CACHE_MAX = 16
_STEP_LOCK = threading.Lock()


def _step_cache_get(key):
    with _STEP_LOCK:
        v = _STEP_CACHE.get(key)
        if v is not None:
            _STEP_CACHE.move_to_end(key)
        return v


def _step_cache_put(key, val):
    with _STEP_LOCK:
        _STEP_CACHE[key] = val
        _STEP_CACHE.move_to_end(key)
        while len(_STEP_CACHE) > _STEP_CACHE_MAX:
            _STEP_CACHE.popitem(last=False)


def step_cache_count() -> int:
    """Live cached compiled steps (the conftest leak gauge)."""
    with _STEP_LOCK:
        return len(_STEP_CACHE)


def release_step_cache() -> None:
    """Close-time hook: drop every cached compiled step (engine close,
    provider close, test teardown).  Steps recompile on next use —
    correctness is unaffected, only the compile cost returns."""
    with _STEP_LOCK:
        _STEP_CACHE.clear()


def sharded_codec_step(mesh: Mesh, N: int, with_crc: bool = True):
    """Build the jitted multi-chip codec step for (B, N) blocks.

    Returns fn(data (B,N) uint8 right-padded, lens (B,) int32,
    valid (B,) int32 row mask) →
      (lz4 bytes (B,C) uint8, lz4 lens (B,), crc32c (B,) uint32,
       total_out_bytes scalar — psum of valid rows across the mesh).
    B must be a multiple of the mesh size. ``with_crc=False`` builds a
    compress-only step (no CRC matmul, no psum) for callers that
    checksum elsewhere — e.g. the codec provider, whose batch CRC
    covers the assembled record batch, not raw blocks.
    """
    key = ("codec", tuple(d.id for d in mesh.devices.flat), N, with_crc)
    cached = _step_cache_get(key)
    if cached is not None:
        return cached
    K, L = _pick_kl(N)
    shift_tab = _shift_tables(L)

    def local(data, lens, valid):
        out, olen = jax.vmap(lambda d, n: _lz4_block_one(d, n, N))(data, lens)
        if not with_crc:
            return out, olen
        # the crc kernel needs LEFT-padded rows (leading zeros are a no-op
        # under a zero register); shift each right-padded row into place
        j = jnp.arange(N, dtype=jnp.int32)[None, :]
        src = j - (N - lens[:, None])
        crc_in = jnp.where(
            src >= 0,
            jnp.take_along_axis(data, jnp.clip(src, 0, N - 1), axis=1),
            jnp.uint8(0))
        crc = _crc_kernel(crc_in.reshape(-1, K, L), lens, shift_tab)
        total = jax.lax.psum(jnp.sum(olen * valid), "batch")
        return out, olen, crc, total

    out_specs = ((P("batch", None), P("batch"), P("batch"), P())
                 if with_crc else (P("batch", None), P("batch")))
    shard = _shard_map(
        local, mesh=mesh,
        in_specs=(P("batch", None), P("batch"), P("batch")),
        out_specs=out_specs)
    fn = jax.jit(shard)
    _step_cache_put(key, fn)
    return fn


# ---------------------------------------------- engine-facing lane API ----
# The async offload engine's sharded CRC dispatch (ISSUE 6): a fused
# launch group whose block count spans a mesh multiple is laid out
# (B_shard * ndev, 64KB) and shard_mapped so every chip runs the
# plane-split kernel on its contiguous row shard.  The local body IS
# crc32c_jax's single-device body — results are bit-identical to the
# whole-to-one-lane route by construction; only WHERE each block's CRC
# runs changes.

def _crc_step_key(device_ids, Bs: int, N: int, kind: str) -> tuple:
    return ("crc", tuple(device_ids), int(Bs), int(N), kind)


def sharded_crc_ready(device_ids, Bs: int, N: int, kind: str) -> bool:
    """True once the sharded CRC step for (devices, per-shard rows Bs,
    block N, kind) is compiled — the engine's warmup gate for the
    split route (kind: 'crc32c' | 'crc32' | 'fused')."""
    return _step_cache_get(_crc_step_key(device_ids, Bs, N, kind)) \
        is not None


def sharded_crc_step(devices, Bs: int, N: int, kind: str):
    """(mesh, fn) for the sharded CRC launch: fn(data (Bs*ndev, N)
    uint8 left-padded, terms (Bs*ndev,) uint32[, sel (Bs*ndev,) uint32
    when kind='fused']) -> (Bs*ndev,) uint32.  Each device computes its
    Bs-row shard with the single-device MXU body; compiled steps are
    cached in the bounded module LRU."""
    ids = [d.id for d in devices]
    key = _crc_step_key(ids, Bs, N, kind)
    cached = _step_cache_get(key)
    if cached is not None:
        return cached
    from ..ops.crc32c_jax import _mxu_fused_rows_fn, _mxu_rows_fn
    fused = kind == "fused"
    local = _mxu_fused_rows_fn(N) if fused else _mxu_rows_fn(N, kind)
    mesh = Mesh(np.array(list(devices)), ("batch",))
    in_specs = ((P("batch", None), P("batch"), P("batch")) if fused
                else (P("batch", None), P("batch")))
    fn = jax.jit(_shard_map(local, mesh=mesh, in_specs=in_specs,
                            out_specs=P("batch")))
    val = (mesh, fn)
    _step_cache_put(key, val)
    return val


def warm_sharded_crc(devices, Bs: int, N: int, kind: str) -> None:
    """Compile the sharded CRC step off the hot path (the engine's
    warmup thread): AOT-lower against sharded ShapeDtypeStructs when
    the jax supports it, else execute zeros once.  Idempotent."""
    ids = [d.id for d in devices]
    if sharded_crc_ready(ids, Bs, N, kind):
        return
    mesh, fn = sharded_crc_step(devices, Bs, N, kind)
    ndev = mesh.devices.size
    B = Bs * ndev
    fused = kind == "fused"
    row = NamedSharding(mesh, P("batch"))
    try:
        d = jax.ShapeDtypeStruct((B, N), jnp.uint8,
                                 sharding=NamedSharding(
                                     mesh, P("batch", None)))
        t = jax.ShapeDtypeStruct((B,), jnp.uint32, sharding=row)
        args = (d, t, jax.ShapeDtypeStruct((B,), jnp.uint32,
                                           sharding=row)) \
            if fused else (d, t)
        exe = fn.lower(*args).compile()
        _step_cache_put(_crc_step_key(ids, Bs, N, kind), (mesh, exe))
    except Exception:
        # no AOT path: compile by executing zeros once (the jitted fn
        # keeps its own executable cache; the step stays cached)
        data = jax.device_put(np.zeros((B, N), np.uint8),
                              NamedSharding(mesh, P("batch", None)))
        terms = jax.device_put(np.zeros((B,), np.uint32), row)
        cargs = ((data, terms,
                  jax.device_put(np.zeros((B,), np.uint32), row))
                 if fused else (data, terms))
        np.asarray(fn(*cargs))


def shard_compress(mesh: Mesh, blocks: list[bytes], with_crc: bool = True):
    """Compress blocks across the mesh (pads B up to a mesh multiple).
    Returns (blocks, crcs, total) with crcs=None/total=0 when
    with_crc=False.  An empty block list short-circuits — shard_map
    cannot partition zero rows."""
    from ..ops.packing import next_pow2, pad_right

    if not blocks:
        return [], (np.zeros((0,), np.uint32) if with_crc else None), 0

    ndev = mesh.devices.size
    N = next_pow2(max((len(b) for b in blocks), default=64))
    data, lens = pad_right(blocks, N)
    B = len(blocks)
    Bp = ((B + ndev - 1) // ndev) * ndev
    valid = np.ones((B,), np.int32)
    if Bp != B:
        data = np.concatenate([data, np.zeros((Bp - B, N), np.uint8)])
        lens = np.concatenate([lens, np.zeros((Bp - B,), np.int32)])
        valid = np.concatenate([valid, np.zeros((Bp - B,), np.int32)])
    fn = sharded_codec_step(mesh, N, with_crc)
    row = NamedSharding(mesh, P("batch"))
    res = fn(
        jax.device_put(data, NamedSharding(mesh, P("batch", None))),
        jax.device_put(lens, row), jax.device_put(valid, row))
    if with_crc:
        out, olen, crc, total = res
    else:
        out, olen = res
        crc, total = None, 0
    out = np.asarray(out)
    olen = np.asarray(olen)
    return ([out[i, :olen[i]].tobytes() for i in range(B)],
            None if crc is None else np.asarray(crc)[:B], int(total))
