"""librdkafka_tpu.parallel"""
