"""CLI: ``python -m librdkafka_tpu.analysis [lint|stress|races|all]``.

``lint``   — AST project-invariant lint over the package (lint.py)
``stress`` — lockdep-enabled stress pass (stress.py)
``races``  — lockset data-race sweep + seeded schedule explorer
             (races.py / interleave.py via stress.py legs)
``all``    — everything (the scripts/check.sh gate); exit 1 on any
             finding
"""
import sys


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    cmd = argv[0] if argv else "all"
    if cmd not in ("lint", "stress", "races", "all"):
        print(__doc__)
        return 2
    rc = 0
    if cmd in ("lint", "all"):
        from .lint import main as lint_main
        rc |= lint_main(argv[1:] if cmd == "lint" else [])
    if cmd in ("stress", "all"):
        from .stress import main as stress_main
        rc |= stress_main()
    if cmd in ("races", "all"):
        from .stress import races_main
        rc |= races_main()
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
