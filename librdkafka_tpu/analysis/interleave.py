"""Seeded schedule explorer: deterministic preemption injection at the
concurrency yield points (the CHESS idea — Musuvathi et al., OSDI 2008
— scaled down to preemption-bounded fuzzing over this package's
instrumented seams).

The default thread scheduler explores a vanishingly thin slice of the
interleaving space: the GIL switches every ~5 ms, so a read-modify-
write that spans a few bytecodes virtually never gets preempted
mid-window, and a latent lost-update or ordering bug can survive every
straight test run.  The explorer widens the slice *deterministically*:

  * **Yield points** — the instrumented lock wrappers
    (``lockdep.DepLock/DepRLock`` acquire), ``OpQueue`` push/pop, and
    the lockset detector's :class:`~.races.Guarded` descriptor (a
    preemption between a recorded read and the following write is
    exactly the lost-update window).  Each point calls
    :func:`maybe_yield`, one module-attribute check when no fuzzer is
    installed (the trace-hook contract).
  * **SchedFuzzer(seed, preemption_bound)** — at each yield point the
    calling thread consults ITS OWN ``random.Random`` stream, seeded
    from ``(seed, thread name)`` (threads are named — the
    ``thread-name`` lint rule — and a thread's workload is
    deterministic, so its decision sequence is too: same seed ⇒ same
    per-thread preemption trace, independent of wall-clock
    interleaving).  A firing preemption sleeps the thread for a few
    hundred microseconds — long enough that every other runnable
    thread makes real progress through the window.  ``preemption_
    bound`` caps injected preemptions per thread (the CHESS insight:
    most schedule bugs need very few preemptions).
  * **replay_key()** — the chaos-style deterministic projection:
    ``(seed, bound, p)``.  A failing schedule re-runs exactly by
    installing a fuzzer with the same key (``SchedFuzzer.from_key``).

``analysis/stress.py`` reruns the engine-pipeline and txn legs under N
seeded schedules (``python -m librdkafka_tpu.analysis races``) so
latent races and orderings the default scheduler never produces
surface in CI, attributed by the lockset detector's reports.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Optional

#: fast-path guard: yield sites check this one module attribute before
#: calling maybe_yield (the hot-path cost when no fuzzer is installed)
active = False

_fuzzer: Optional["SchedFuzzer"] = None


class SchedFuzzer:
    """Deterministic preemption injector.

    ``seed``              one integer seeds every per-thread stream
    ``preemption_bound``  max injected preemptions PER THREAD
    ``p``                 per-yield-point preemption probability
    ``sleep_s``           (lo, hi) preemption sleep range, drawn from
                          the same per-thread stream
    """

    def __init__(self, seed: int, preemption_bound: int = 40,
                 p: float = 0.1,
                 sleep_s: tuple = (0.0002, 0.0015)):
        self.seed = int(seed)
        self.preemption_bound = int(preemption_bound)
        self.p = float(p)
        self.sleep_s = (float(sleep_s[0]), float(sleep_s[1]))
        self._tl = threading.local()
        self._trace_lock = threading.Lock()
        #: injected preemptions, in firing order:
        #: (thread name, yield point, per-thread yield seq)
        self.trace: list[tuple] = []

    @classmethod
    def from_key(cls, key: tuple) -> "SchedFuzzer":
        """Rebuild the fuzzer a :meth:`replay_key` describes."""
        tag, seed, bound, p_milli = key
        assert tag == "sched"
        return cls(seed, preemption_bound=bound, p=p_milli / 1000.0)

    def replay_key(self) -> tuple:
        """Deterministic projection (the CHAOS.md replay contract):
        identical across runs with one seed; rebuild via
        :meth:`from_key` to replay a failing schedule exactly."""
        return ("sched", self.seed, self.preemption_bound,
                round(self.p * 1000))

    # ------------------------------------------------------ per thread --
    def _slot(self):
        tl = self._tl
        if getattr(tl, "rng", None) is None:
            name = threading.current_thread().name
            tl.rng = random.Random(f"{self.seed}|{name}")
            tl.seq = 0
            tl.fired = 0
            tl.name = name
        return tl

    def maybe_yield(self, point: str) -> None:
        tl = self._slot()
        if tl.fired >= self.preemption_bound:
            return
        tl.seq += 1
        if tl.rng.random() >= self.p:
            return
        tl.fired += 1
        delay = tl.rng.uniform(*self.sleep_s)
        with self._trace_lock:
            self.trace.append((tl.name, point, tl.seq))
        time.sleep(delay)

    def trace_for(self, thread_name: str) -> list:
        """One thread's preemption decisions (deterministic given that
        thread's workload — the determinism-test projection; the global
        ``trace`` ordering depends on real interleaving)."""
        with self._trace_lock:
            return [t for t in self.trace if t[0] == thread_name]


def install(fuzzer: SchedFuzzer) -> None:
    """Install ``fuzzer`` as the process-wide scheduler (one at a
    time; yield points fire from the instant this returns)."""
    global _fuzzer, active
    _fuzzer = fuzzer
    active = True


def uninstall() -> None:
    global _fuzzer, active
    active = False
    _fuzzer = None


def maybe_yield(point: str) -> None:
    """Module-level yield point: call sites guard with
    ``if interleave.active:`` so an uninstalled fuzzer costs one
    attribute check."""
    f = _fuzzer
    if f is not None:
        f.maybe_yield(point)
