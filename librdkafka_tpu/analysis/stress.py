"""Lockdep/lockset-enabled stress passes: the runtime half of
scripts/check.sh.

Drives every concurrent layer under instrumented locks and asserts a
clean lock-order graph:

  * **engine pipeline** — a tpu-backend producer (ticketed compress +
    CRC through the offload engine's dispatch lanes) and a CRC-checking
    consumer against the in-process mock, so app thread, rdk:main,
    broker threads, the engine dispatch thread and the mock cluster
    thread all interleave;
  * **txn commit/abort** — the transactional FSM's RLock+condvar
    against the coordinator paths;
  * **fast chaos storm** — one broker kill/restart under idempotent
    produce/consume (chaos scheduler + oracle + connect-retry paths).

Any cycle or held-across-blocking finding fails the gate (exit 1) with
both acquisition stacks printed.  Run: ``python -m
librdkafka_tpu.analysis stress`` (or ``scripts/check.sh``).

The ``races`` pass (ISSUE 10) reruns the same legs under the Eraser-
style lockset detector (races.py): every declared shared field's
accesses refine their candidate locksets across app, rdk:main, broker,
codec-worker, engine dispatch/warmup, mock-cluster and chaos threads —
an empty-lockset write fails the gate with both access stacks.  It
then replays the engine-pipeline and txn legs under N seeded
schedules (interleave.SchedFuzzer): deterministic preemptions at the
lock/queue/descriptor yield points surface interleavings the default
scheduler never produces, each replayable via its ``replay_key``.
Run: ``python -m librdkafka_tpu.analysis races``.
"""
from __future__ import annotations

import time

from . import interleave, lockdep, races

#: seeds for the schedule-explorer reruns (one fuzzer per seed; any
#: failure names its replay_key so the exact schedule re-runs)
SCHEDULE_SEEDS = (11, 23)


def _engine_pipeline_leg() -> int:
    from .. import Consumer, Producer

    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "compression.backend": "tpu",
                  "tpu.transport.min.mb.s": 0,
                  "tpu.launch.min.batches": 2, "tpu.governor": False,
                  "tpu.warmup": False, "compression.codec": "lz4",
                  "linger.ms": 5})
    c = None
    try:
        bs = p._rk.mock_cluster.bootstrap_servers()
        for i in range(300):
            p.produce("lockdep-eng", value=b"v%d" % i * 20,
                      partition=i % 4)
        assert p.flush(120.0) == 0, "engine leg: flush left messages"
        c = Consumer({"bootstrap.servers": bs, "group.id": "lockdep-g",
                      "auto.offset.reset": "earliest",
                      "check.crcs": True})
        c.subscribe(["lockdep-eng"])
        got = 0
        deadline = time.monotonic() + 60
        while got < 300 and time.monotonic() < deadline:
            m = c.poll(0.2)
            if m is not None and m.error is None:
                got += 1
        assert got == 300, f"engine leg: consumed {got}/300"
        return got
    finally:
        p.close()
        if c is not None:
            c.close()


def _devcodec_leg() -> None:
    """ISSUE 17: the device compress route under instrumented locks —
    tpu.compress.device producer with two QoS-weighted topics, so the
    engine's lz4 staging rings, fused compress→CRC launches and the
    governor's QoS tallies (submitter-side note_topics vs dispatch-
    side note_qos vs the stats emitter's snapshots) interleave with
    the broker/app/mock threads; a CRC-checking consumer proves the
    device frames byte-valid end to end."""
    from .. import Consumer, Producer

    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "compression.backend": "tpu",
                  "tpu.transport.min.mb.s": 0,
                  "tpu.compress.device": True,
                  "tpu.launch.min.batches": 1, "tpu.governor": False,
                  "tpu.warmup": False, "compression.codec": "lz4",
                  "linger.ms": 5, "batch.num.messages": 16})
    c = None
    try:
        p._rk.set_topic_conf("lockdep-dc-lat", {"topic.qos.weight": 4.0})
        p._rk.set_topic_conf("lockdep-dc-bulk",
                             {"topic.qos.weight": 0.5})
        bs = p._rk.mock_cluster.bootstrap_servers()
        for i in range(120):
            topic = ("lockdep-dc-lat" if i % 3 else "lockdep-dc-bulk")
            p.produce(topic, value=b"dc%03d " % i * 12, key=b"k%d" % i)
        assert p.flush(120.0) == 0, "devcodec leg: flush left messages"
        eng = p._rk.codec_provider._engine
        snap = eng.compress_snapshot() if eng is not None else {}
        assert snap.get("launches", 0) >= 1, \
            f"devcodec leg: no device compress launch: {snap}"
        assert set(snap.get("qos", {})) >= {"lockdep-dc-lat",
                                            "lockdep-dc-bulk"}, snap
        c = Consumer({"bootstrap.servers": bs,
                      "group.id": "lockdep-dc-g",
                      "auto.offset.reset": "earliest",
                      "check.crcs": True})
        c.subscribe(["lockdep-dc-lat", "lockdep-dc-bulk"])
        got = 0
        deadline = time.monotonic() + 60
        while got < 120 and time.monotonic() < deadline:
            m = c.poll(0.2)
            if m is not None and m.error is None:
                got += 1
        assert got == 120, f"devcodec leg: consumed {got}/120"
    finally:
        p.close()
        if c is not None:
            c.close()


def _txn_leg() -> None:
    from .. import Producer

    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "transactional.id": "lockdep-tx",
                  "compression.codec": "lz4", "linger.ms": 1})
    try:
        p.init_transactions(30)
        p.begin_transaction()
        for i in range(20):
            p.produce("lockdep-txn", value=b"c%d" % i, partition=0)
        p.commit_transaction(30)
        p.begin_transaction()
        for i in range(20):
            p.produce("lockdep-txn", value=b"a%d" % i, partition=0)
        p.flush(30)
        p.abort_transaction(30)
    finally:
        p.close()


def _chaos_leg() -> None:
    from ..chaos.scenarios import fast_kill_restart

    res = fast_kill_restart(seed=7)
    assert res.get("ok", True), f"chaos leg violated delivery: {res}"


def _external_storm_leg() -> None:
    """ISSUE 9: the fast out-of-process storm — a real SIGKILL and a
    SIGSTOP brownout of broker OS processes while the instrumented
    client-side locks (broker reconnect, oracle, scheduler, handle
    control plane) feed the lock-order graph."""
    from ..chaos.scenarios import fast_external_kill9

    res = fast_external_kill9(seed=23)
    assert res.get("ok", True), f"external leg violated delivery: {res}"
    pids = [e for e in res.get("proc_events", []) if e["verb"] == "kill9"]
    assert pids and all(e["verified_dead"] for e in pids), \
        f"external leg: SIGKILL not pid-verified: {pids}"


def _fastlane_leg() -> None:
    """ISSUE 16: the widened arena fast lane under instrumented locks —
    app-thread produce() appends (murmur2 auto-partition + explicit
    timestamps + headers riding the C lane) race the broker thread's
    run take at linger.ms=0, while an interleaved shape-ineligible
    produce (per-message on_delivery) claims a hot toppar mid-stream so
    demote_arena's drain races concurrent appends (the broker-side
    "race" demotion path)."""
    from .. import Producer

    drs: list = []
    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "linger.ms": 0,
                  "dr_msg_cb": lambda e, m: drs.append(e)})
    try:
        p.set_topic_conf("lockdep-lane", {"partitioner": "murmur2"})
        # metadata warm-up: auto-partition needs the partition count
        t = p._rk.get_topic("lockdep-lane")
        deadline = time.monotonic() + 30
        while t.partition_cnt <= 0 and time.monotonic() < deadline:
            p.poll(0.05)
        assert t.partition_cnt > 0, "fastlane leg: no metadata"
        hdrs = [("k", b"v")]
        now_ms = int(time.time() * 1000)
        for i in range(400):
            p.produce("lockdep-lane", value=b"x%03d" % i,
                      key=b"k%02d" % (i % 37), timestamp=now_ms + i,
                      headers=hdrs)
            if i == 250:
                # shape-ineligible produce claims a toppar mid-run: if
                # the broker is mid-take this exercises the "race"
                # demotion, else the "ineligible" drain — both contend
                # with live appends
                p.produce("lockdep-lane", value=b"slow", partition=0,
                          on_delivery=lambda e, m: None)
            if i % 64 == 0:
                p.poll(0)
        assert p.flush(60.0) == 0, "fastlane leg: flush left messages"
        # the on_delivery produce routes to its own callback, not the
        # global dr_msg_cb: exactly the 400 lane messages land here
        assert len(drs) == 400 and all(e is None for e in drs), \
            f"fastlane leg: DRs {len(drs)}/400"
    finally:
        p.close()


def _session_leg() -> None:
    """ISSUE 14: incremental fetch sessions under instrumented locks —
    a 16-partition interest set negotiates a session, runs incremental
    epochs, survives a broker-side cache eviction (top-level
    FETCH_SESSION_ID_NOT_FOUND → reset + epoch-0 renegotiation) and
    rides the forgotten_topics path on unassign, interleaving the
    per-broker session state with the mock's shared session cache."""
    from .. import Consumer, Producer
    from ..client.consumer import TopicPartition
    from ..mock.cluster import MockCluster

    cluster = MockCluster(num_brokers=1, topics={"sess": 16})
    c = None
    try:
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "linger.ms": 2})
        for i in range(200):
            p.produce("sess", value=b"s%03d" % i, partition=i % 16)
        assert p.flush(60.0) == 0, "session leg: flush left messages"
        p.close()
        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "group.id": "lockdep-sess",
                      "auto.offset.reset": "earliest"})
        c.assign([TopicPartition("sess", i) for i in range(16)])
        got = 0
        deadline = time.monotonic() + 60
        while got < 100 and time.monotonic() < deadline:
            m = c.poll(0.2)
            if m is not None and m.error is None:
                got += 1
        assert got == 100, f"session leg: consumed {got}/100 pre-evict"
        assert cluster.evict_fetch_sessions() >= 1, \
            "session leg: no broker-side session to evict"
        while got < 200 and time.monotonic() < deadline:
            m = c.poll(0.2)
            if m is not None and m.error is None:
                got += 1
        assert got == 200, f"session leg: consumed {got}/200 post-evict"
        # the post-evict records may have been prefetched before the
        # eviction landed — keep polling until the next session fetch
        # hits FETCH_SESSION_ID_NOT_FOUND and resets
        reset_seen = False
        while not reset_seen and time.monotonic() < deadline:
            with c._rk._brokers_lock:
                brokers = list(c._rk.brokers.values())
            reset_seen = any(b._fetch_session.c_resets >= 1
                             for b in brokers)
            if not reset_seen:
                c.poll(0.1)
        assert reset_seen, \
            "session leg: eviction did not reset the client session"
        c.unassign()
        c.poll(0.2)
    finally:
        if c is not None:
            c.close()
        cluster.stop()


def _fleet_leg() -> None:
    """ISSUE 11: the tier-1 fleet smoke — 4 real client OS processes
    under burst traffic and a pid-verified SIGKILL while the driver's
    reader threads merge worker ledgers into the per-group oracles:
    the fleet.driver lock, oracle ledgers, handle control plane and
    scheduler all interleave under the instrumented locks."""
    from ..fleet.scenarios import fleet_smoke

    res = fleet_smoke(seed=51)
    assert res.get("ok", True), f"fleet leg violated delivery: {res}"
    pids = res.get("pids_killed", [])
    assert pids and all(e["verified_dead"] for e in pids), \
        f"fleet leg: SIGKILL not pid-verified: {pids}"


def run_stress() -> dict:
    """All four legs under one enabled window; returns the lockdep
    report (``lockdep.clean(report)`` is the pass predicate)."""
    lockdep.reset()
    lockdep.enable()
    try:
        _engine_pipeline_leg()
        _devcodec_leg()
        _txn_leg()
        _chaos_leg()
        _external_storm_leg()
        _fleet_leg()
        _session_leg()
        _fastlane_leg()
    finally:
        lockdep.disable()
    return lockdep.report()


def run_races(seeds=SCHEDULE_SEEDS) -> tuple:
    """The lockset pass: the same legs under the race detector (which
    holds a lockdep reference — locksets come from its held-stack),
    then the engine + txn legs re-run under one seeded schedule per
    ``seed``.  Returns ``(races_report, schedule_keys)``."""
    races.reset()
    lockdep.reset()
    races.enable()
    keys = []
    try:
        _engine_pipeline_leg()
        _devcodec_leg()
        _txn_leg()
        _chaos_leg()
        _fleet_leg()
        _session_leg()
        _fastlane_leg()
        for seed in seeds:
            fz = interleave.SchedFuzzer(seed)
            keys.append(fz.replay_key())
            interleave.install(fz)
            try:
                _engine_pipeline_leg()
                _txn_leg()
            finally:
                interleave.uninstall()
    finally:
        races.disable()
    return races.report(), keys


def races_main() -> int:
    t0 = time.perf_counter()
    rep, keys = run_races()
    print(races.format_report(rep))
    print(f"races: lockset sweep (engine pipeline + device codec + txn "
          f"+ fast chaos "
          f"storm + fleet smoke + fetch sessions + fast lane) + {len(keys)} seeded "
          f"schedules {[k for k in keys]} "
          f"in {time.perf_counter() - t0:.1f}s")
    return 0 if races.clean(rep) else 1


def main() -> int:
    t0 = time.perf_counter()
    rep = run_stress()
    print(lockdep.format_report(rep))
    print(f"stress: engine pipeline + device codec + txn commit/abort "
          f"+ fast chaos "
          f"storm + external SIGKILL storm + fleet smoke + fetch "
          f"sessions + fast lane in {time.perf_counter() - t0:.1f}s")
    return 0 if lockdep.clean(rep) else 1


if __name__ == "__main__":
    raise SystemExit(main())
