"""Static + runtime concurrency analysis (ANALYSIS.md).

The engine is a genuinely concurrent system — per-device dispatch
lanes, a warmup thread, broker threads, codec workers, the chaos
scheduler and sockem pumps coordinate through ~35 lock sites — and the
PR history established a set of invariants by convention (condvar
waits not sleep-polls, one-attr-check trace hooks, validated conf
Props, named threads).  This package turns those conventions into
checks:

  * :mod:`lockdep` — a runtime lock-ORDER checker in the spirit of the
    kernel's lockdep and the helgrind/TSAN CI the reference client
    runs (PAPER.md survey; librdkafka's ``rd_kafka_*lock`` discipline):
    instrumented Lock/RLock/Condition wrappers record per-thread
    acquisition stacks, build the global lock-order graph, and report
    AB/BA inversions, longer cycles, and locks held across blocking
    calls — each with the stack traces that created the edge.
  * :mod:`locks` — the central factory every concurrent layer creates
    its primitives through.  Disabled (the default), it returns PLAIN
    ``threading`` primitives: the production hot path pays exactly
    nothing (the decision happens once, at lock creation).
  * :mod:`races` — an Eraser-style lockset data-race detector over
    the DECLARED shared-state surface (``shared()`` class markers,
    ``register_slots()``, ``shared_dict/list/counter()``): each
    access refines a candidate lockset from lockdep's held-stack
    through the virgin→exclusive→shared→shared-modified machine, and
    an empty-lockset write is reported with both access stacks.
    Disabled, every declaration resolves to a plain attribute /
    container at creation time — the same zero-cost contract.
  * :mod:`interleave` — a seeded schedule explorer: deterministic
    preemption injection at the lock/queue/descriptor yield points
    (per-thread streams seeded from (seed, thread name)), so latent
    interleavings surface in CI and any failing schedule replays
    exactly via its ``replay_key``.
  * :mod:`lint` — an AST lint encoding the project invariants (rule
    catalog + rationale in ANALYSIS.md), including ``shared-state``:
    concurrent classes in the scoped layers must declare their
    cross-thread mutable attributes (or carry a justified pragma).

Gate: ``scripts/check.sh`` runs the lint over the whole package, a
lockdep-enabled stress pass (engine pipeline, chaos storms, txn
commit/abort), and the lockset races pass (same legs + seeded
schedule reruns) and exits nonzero on any finding.  ``pytest
--lockdep`` / ``pytest --races`` run the whole test suite under the
instrumented locks / the lockset detector.
"""
