"""Static + runtime concurrency analysis (ANALYSIS.md).

The engine is a genuinely concurrent system — per-device dispatch
lanes, a warmup thread, broker threads, codec workers, the chaos
scheduler and sockem pumps coordinate through ~35 lock sites — and the
PR history established a set of invariants by convention (condvar
waits not sleep-polls, one-attr-check trace hooks, validated conf
Props, named threads).  This package turns those conventions into
checks:

  * :mod:`lockdep` — a runtime lock-ORDER checker in the spirit of the
    kernel's lockdep and the helgrind/TSAN CI the reference client
    runs (PAPER.md survey; librdkafka's ``rd_kafka_*lock`` discipline):
    instrumented Lock/RLock/Condition wrappers record per-thread
    acquisition stacks, build the global lock-order graph, and report
    AB/BA inversions, longer cycles, and locks held across blocking
    calls — each with the stack traces that created the edge.
  * :mod:`locks` — the central factory every concurrent layer creates
    its primitives through.  Disabled (the default), it returns PLAIN
    ``threading`` primitives: the production hot path pays exactly
    nothing (the decision happens once, at lock creation).
  * :mod:`lint` — an AST lint encoding the project invariants (rule
    catalog + rationale in ANALYSIS.md).

Gate: ``scripts/check.sh`` runs the lint over the whole package plus a
lockdep-enabled stress pass (engine pipeline, a fast chaos storm, txn
commit/abort) and exits nonzero on any finding.  ``pytest --lockdep``
runs the whole test suite under instrumented locks.
"""
